"""Unified soak timeline: per-wall-interval rows + interference ledger.

The soak engine (``models.soak``) runs serving, maintenance and
monitoring through one slot plane; this module is where that
concurrency becomes OBSERVABLE.  A :class:`SoakTimeline` cuts the run
into fixed wall intervals and books, per interval:

* the serve plane — arrivals / admissions / completions / expiries per
  work class, a latency histogram of the interval's slot-served
  completions (p50/p99 derived from the bucket bounds, exactly the
  PR-7 artifact discipline: the embedded counts can always reproduce
  the quantiles, which is what ``check_trace`` re-derives), and the
  interval's SLO violations;
* the slot plane — dispatched slot-rounds split serve-vs-maintenance.
  The split's source of truth is the DEVICE work-class plane
  (``_soak_snapshot``'s per-class active counts) plus the harvest's
  per-class retirements; the total is the HOST's occupancy bookkeeping
  at burst entry.  ``serve + maintenance == total`` is therefore a
  real cross-check between two independent observers, not an identity
  of one counter with itself — ``check_trace.py`` holds it per row;
* the maintenance plane — sweep begins/finishes, slot-free store-sweep
  ops with their walls, and the monitor's coverage after each
  finished sweep;
* lifecycle boundary snapshots — cumulative
  ``admitted == completed + expired + in_flight`` per class, held at
  EVERY interval boundary (the ISSUE-11 conservation satellite), not
  just at drain.

:func:`interference_ledger` is the A/B half: given the timeline rows
of a maintenance-ON run and a maintenance-OFF run over the SAME
arrival schedule, it aligns intervals, recomputes both runs' p99 from
the embedded histograms, and attributes the serve-p99 delta to
maintenance-active intervals — the measured answer to "what does the
5.73 s standalone sweep cost when interleaved?".

:class:`SoakPlane` publishes the same catalogue through the PR-3
Prometheus registry.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils.metrics import Histogram, MetricsRegistry

# Work-class names, mirrored from models.soak (no jax import here —
# the checker loads this module in a process that never initializes a
# backend).
WCLASS_NAMES = ("read", "write", "repub", "monitor")
SERVE_NAMES = ("read", "write")
MAINT_NAMES = ("repub", "monitor")


class SoakTimeline:
    """Per-wall-interval accumulator for one soak run.

    ``interval_s`` fixes the row width (both A/B runs must use the
    same width or the ledger cannot align them); ``slots`` the serve
    slot count (occupancy denominators); ``bounds`` the latency
    histogram bucket bounds (default: the Prometheus latency shape);
    ``slo_target_s`` the per-request SLO the violation counts key on.

    All ``note_*`` timestamps are seconds on the soak clock (monotone
    within a run).  Scan completions count toward the interval's
    ``completed`` but NOT its latency histogram — scans execute
    through the trie engine at a different latency scale, and mixing
    them in would blur exactly the serve-tail signal the interference
    ledger exists to isolate (their latencies are summarized
    separately).
    """

    def __init__(self, interval_s: float, slots: int,
                 bounds: Optional[Sequence[float]] = None,
                 slo_target_s: float = 0.25):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got "
                             f"{interval_s}")
        self.interval_s = float(interval_s)
        self.slots = int(slots)
        self.bounds = [float(b) for b in
                       (bounds or Histogram.LATENCY_BUCKETS_S)]
        self.slo_target_s = float(slo_target_s)
        self.rows: List[dict] = []
        self._i = 0
        self._cur = self._new_row(0)
        self._closed = False

    # -- row plumbing --------------------------------------------------

    def _new_row(self, i: int) -> dict:
        z = {w: 0 for w in WCLASS_NAMES}
        return {
            "i": i,
            "t_start": round(i * self.interval_s, 6),
            "t_end": round((i + 1) * self.interval_s, 6),
            "arrivals": dict(z, scan=0, chunk=0),
            "admitted": dict(z),
            "completed": dict(z, scan=0, chunk=0),
            "expired": dict(z),
            "queue_samples": [],
            "bursts": 0,
            "rounds": 0,
            "total_slot_rounds": 0,
            "slot_rounds": dict(z),
            "latency_counts": [0] * (len(self.bounds) + 1),
            "latency_sum_s": 0.0,
            "slo_violations": 0,
            "scan_latency_sum_s": 0.0,
            "chunk_latency_sum_s": 0.0,
            "maint_ops": 0,
            "maint_ops_wall_s": 0.0,
            "other_ops": 0,
            "other_ops_wall_s": 0.0,
            "ops": [],
            "sweeps_finished": {"repub": 0, "monitor": 0},
            "coverage": None,
            "lifecycle": None,
        }

    def _roll(self, t: float) -> None:
        if self._closed:
            raise RuntimeError("timeline already closed")
        while t >= (self._i + 1) * self.interval_s:
            self._finalize_cur()
            self._i += 1
            self._cur = self._new_row(self._i)

    def _finalize_cur(self) -> None:
        row = self._cur
        qs = row.pop("queue_samples")
        row["queue_depth_mean"] = round(float(np.mean(qs)), 2) \
            if qs else 0.0
        row["queue_depth_max"] = int(np.max(qs)) if qs else 0
        n_lat = int(sum(row["latency_counts"]))
        row["latency_count"] = n_lat
        row["latency_sum_s"] = round(row["latency_sum_s"], 6)
        if n_lat:
            h = Histogram("soak_interval", "", buckets=self.bounds)
            h.observe_bulk(row["latency_counts"],
                           row["latency_sum_s"])
            row["latency_p50_s"] = round(h.quantile(0.50), 6)
            row["latency_p99_s"] = round(h.quantile(0.99), 6)
        else:
            row["latency_p50_s"] = None
            row["latency_p99_s"] = None
        denom = self.slots * row["rounds"]
        row["occupancy_serve"] = round(
            sum(row["slot_rounds"][w] for w in SERVE_NAMES)
            / denom, 4) if denom else 0.0
        row["occupancy_maint"] = round(
            sum(row["slot_rounds"][w] for w in MAINT_NAMES)
            / denom, 4) if denom else 0.0
        row["maint_ops_wall_s"] = round(row["maint_ops_wall_s"], 6)
        row["other_ops_wall_s"] = round(row["other_ops_wall_s"], 6)
        row["scan_latency_sum_s"] = round(row["scan_latency_sum_s"], 6)
        row["chunk_latency_sum_s"] = round(
            row["chunk_latency_sum_s"], 6)
        self.rows.append(row)

    # -- the note surface ---------------------------------------------

    def note_arrival(self, cls: str, t: float) -> None:
        self._roll(t)
        self._cur["arrivals"][cls] += 1

    def note_queue(self, depth: int, t: float) -> None:
        self._roll(t)
        self._cur["queue_samples"].append(depth)

    def note_admit(self, counts: Dict[str, int], t: float) -> None:
        self._roll(t)
        for cls, n in counts.items():
            self._cur["admitted"][cls] += n

    def note_complete(self, cls: str, latency_s: Optional[float],
                      t: float) -> None:
        self._roll(t)
        self._cur["completed"][cls] += 1
        if latency_s is None or cls in ("scan", "chunk"):
            # Station-served classes (scans, chunked reads/writes)
            # complete outside the slot plane at a different latency
            # scale — summarized separately, never mixed into the
            # serve histogram the interference ledger isolates.
            if latency_s is not None:
                self._cur[f"{cls}_latency_sum_s"] += latency_s
            return
        b = int(np.searchsorted(self.bounds, latency_s, side="left"))
        self._cur["latency_counts"][b] += 1
        self._cur["latency_sum_s"] += latency_s
        if latency_s > self.slo_target_s:
            self._cur["slo_violations"] += 1

    def note_expire(self, cls: str, t: float) -> None:
        self._roll(t)
        self._cur["expired"][cls] += 1

    def note_burst(self, rounds: int, entry_occ: Sequence[int],
                   retired: Sequence[int], dev_active: Sequence[int],
                   t: float) -> None:
        """Book one burst: ``entry_occ`` is the HOST's per-class slot
        occupancy at burst entry, ``retired``/``dev_active`` the
        harvest's per-class retirements and the DEVICE plane's
        per-class active counts after it.  The row's total uses the
        host side, the split uses the device side — the checker's
        cross-observer identity."""
        self._roll(t)
        row = self._cur
        row["bursts"] += 1
        row["rounds"] += rounds
        row["total_slot_rounds"] += rounds * int(sum(entry_occ))
        for x, w in enumerate(WCLASS_NAMES):
            row["slot_rounds"][w] += rounds * (
                int(retired[x]) + int(dev_active[x]))

    def note_lifecycle(self, by_class: Dict[str, Dict[str, int]],
                       t: float) -> None:
        """Cumulative per-class lifecycle counters; the value standing
        at each interval boundary is the row's conservation
        snapshot."""
        self._roll(t)
        self._cur["lifecycle"] = {
            cls: dict(v) for cls, v in by_class.items()}

    def note_op(self, name: str, t: float, wall_s: float,
                maint: bool = True) -> None:
        """Book an out-of-band op with its wall.  ``maint=False`` for
        work that runs in BOTH A/B arms (write flushes, scenario
        faults): only true maintenance ops may mark an interval
        maintenance-active, or the interference ledger would attribute
        churn/write costs to maintenance — the exact mis-attribution
        the A/B exists to rule out."""
        self._roll(t)
        kind = "maint_ops" if maint else "other_ops"
        self._cur[kind] += 1
        self._cur[f"{kind}_wall_s"] += wall_s
        self._cur["ops"].append(
            {"op": name, "t": round(t, 4),
             "wall_s": round(wall_s, 6), "maint": bool(maint)})

    def note_sweep(self, kind: str, record: dict, t: float) -> None:
        self._roll(t)
        self._cur["sweeps_finished"][kind] += 1
        if kind == "monitor" and "coverage" in record:
            self._cur["coverage"] = record["coverage"]

    def close(self, t: float) -> None:
        """Finalize through ``t`` (the run's elapsed wall)."""
        if self._closed:
            return
        self._roll(t + self.interval_s)  # flush the holding row
        # _roll appended up to and including the row containing t;
        # drop trailing all-empty rows past the run end.
        while self.rows and self.rows[-1]["t_start"] > t:
            self.rows.pop()
        self._closed = True

    # -- export --------------------------------------------------------

    def to_obj(self) -> dict:
        return {
            "interval_s": self.interval_s,
            "slots": self.slots,
            "slo_target_s": self.slo_target_s,
            "latency_bounds_s": self.bounds,
            "rows": self.rows,
        }


def _p99_of(rows: Sequence[dict], bounds: Sequence[float],
            q: float = 0.99) -> Optional[float]:
    counts = np.sum([r["latency_counts"] for r in rows], axis=0) \
        if rows else np.zeros(len(bounds) + 1)
    if counts.sum() == 0:
        return None
    h = Histogram("ledger_agg", "", buckets=list(bounds))
    h.observe_bulk([int(v) for v in counts], 0.0)
    return round(h.quantile(q), 6)


def interference_ledger(on: dict, off: dict) -> dict:
    """Attribute the serve-p99 delta to maintenance bursts.

    ``on``/``off`` are two :meth:`SoakTimeline.to_obj` exports over
    the SAME arrival schedule — one with maintenance interleaved, one
    without (the A/B contract: writes, scans and scenario faults run
    in both arms; only republish/monitor/listener maintenance is
    withheld).  Returns per-aligned-interval delta rows plus the
    attribution summary: the overall bucket-derived p99 of each arm,
    the p99 delta on maintenance-ACTIVE intervals vs quiet ones, and
    the maintenance work that ran (slot-rounds, op walls).

    Raises if the two arms disagree on interval width or latency
    bounds — misaligned ledgers attribute nothing.
    """
    if on["interval_s"] != off["interval_s"]:
        raise ValueError(
            f"interval mismatch: on={on['interval_s']} vs "
            f"off={off['interval_s']} — the A/B arms cannot align")
    if list(on["latency_bounds_s"]) != list(off["latency_bounds_s"]):
        raise ValueError("latency bounds differ between the A/B arms")
    bounds = on["latency_bounds_s"]
    rows_on, rows_off = on["rows"], off["rows"]
    n = min(len(rows_on), len(rows_off))
    deltas = []
    active_d, quiet_d = [], []
    for i in range(n):
        a, b = rows_on[i], rows_off[i]
        maint_rounds = sum(a["slot_rounds"][w] for w in MAINT_NAMES)
        maint_active = maint_rounds > 0 or a["maint_ops"] > 0
        p_on, p_off = a["latency_p99_s"], b["latency_p99_s"]
        d = round(p_on - p_off, 6) \
            if p_on is not None and p_off is not None else None
        deltas.append({
            "i": i,
            "maint_active": bool(maint_active),
            "maint_slot_rounds": int(maint_rounds),
            "maint_ops_wall_s": a["maint_ops_wall_s"],
            "p99_on_s": p_on,
            "p99_off_s": p_off,
            "p99_delta_s": d,
        })
        if d is not None:
            (active_d if maint_active else quiet_d).append(d)
    p99_on = _p99_of(rows_on, bounds)
    p99_off = _p99_of(rows_off, bounds)
    return {
        "interval_s": on["interval_s"],
        "intervals_compared": n,
        "p99_on_s": p99_on,
        "p99_off_s": p99_off,
        "p99_delta_s": round(p99_on - p99_off, 6)
        if p99_on is not None and p99_off is not None else None,
        "p50_on_s": _p99_of(rows_on, bounds, 0.50),
        "p50_off_s": _p99_of(rows_off, bounds, 0.50),
        "maint_active_intervals": len(active_d),
        "p99_delta_maint_mean_s": round(float(np.mean(active_d)), 6)
        if active_d else None,
        "p99_delta_maint_max_s": round(float(np.max(active_d)), 6)
        if active_d else None,
        "p99_delta_quiet_mean_s": round(float(np.mean(quiet_d)), 6)
        if quiet_d else None,
        "maint_slot_rounds_total": int(sum(
            d["maint_slot_rounds"] for d in deltas)),
        "maint_ops_wall_total_s": round(sum(
            d["maint_ops_wall_s"] for d in deltas), 6),
        "intervals": deltas,
    }


class SoakPlane:
    """The soak gauge catalogue on the PR-3 registry (``prefix``
    defaults to ``dht_soak``):

    * counters ``<p>_slot_rounds_total{wclass}``,
      ``<p>_requests_total{op,event}`` (event ∈ admitted / completed /
      expired), ``<p>_sweeps_total{kind}``, ``<p>_maint_ops_total``;
    * gauges ``<p>_interval_latency_seconds{q}`` (last interval's
      bucket-derived p50/p99), ``<p>_interval_slo_violation_ratio``,
      ``<p>_occupancy_ratio{side}``, ``<p>_monitor_coverage_ratio``,
      ``<p>_maint_ops_wall_seconds`` (cumulative).
    """

    def __init__(self, registry: MetricsRegistry,
                 prefix: str = "dht_soak"):
        self.registry = registry
        c, g = registry.counter, registry.gauge
        self._rounds = c(f"{prefix}_slot_rounds_total",
                         "Dispatched slot-rounds", ("wclass",))
        self._reqs = c(f"{prefix}_requests_total",
                       "Request lifecycle events", ("op", "event"))
        self._sweeps = c(f"{prefix}_sweeps_total",
                         "Maintenance sweeps finished", ("kind",))
        self._ops = c(f"{prefix}_maint_ops_total",
                      "Slot-free maintenance store sweeps")
        self._lat = g(f"{prefix}_interval_latency_seconds",
                      "Bucket-derived interval latency quantile",
                      ("q",))
        self._slo = g(f"{prefix}_interval_slo_violation_ratio",
                      "SLO violations over completions, last interval")
        self._occ = g(f"{prefix}_occupancy_ratio",
                      "Slot-round occupancy share of the interval",
                      ("side",))
        self._cov = g(f"{prefix}_monitor_coverage_ratio",
                      "Monitor coverage after the last finished sweep")
        self._opw = g(f"{prefix}_maint_ops_wall_seconds",
                      "Cumulative slot-free maintenance wall")
        self._ops_wall = 0.0

    def publish_interval(self, row: dict) -> None:
        for w in WCLASS_NAMES:
            self._rounds.inc(row["slot_rounds"][w], wclass=w)
        for op_name in row["admitted"]:
            self._reqs.inc(row["admitted"][op_name], op=op_name,
                           event="admitted")
        for op_name in row["completed"]:
            self._reqs.inc(row["completed"][op_name], op=op_name,
                           event="completed")
        for op_name in row["expired"]:
            self._reqs.inc(row["expired"][op_name], op=op_name,
                           event="expired")
        for kind, nswp in row["sweeps_finished"].items():
            if nswp:
                self._sweeps.inc(nswp, kind=kind)
        if row["maint_ops"]:
            self._ops.inc(row["maint_ops"])
        self._ops_wall += row["maint_ops_wall_s"]
        self._opw.set(round(self._ops_wall, 6))
        if row["latency_p50_s"] is not None:
            self._lat.set(row["latency_p50_s"], q="p50")
            self._lat.set(row["latency_p99_s"], q="p99")
        n_lat = row.get("latency_count", 0)
        if n_lat:
            self._slo.set(round(row["slo_violations"] / n_lat, 6))
        self._occ.set(row["occupancy_serve"], side="serve")
        self._occ.set(row["occupancy_maint"], side="maint")
        if row["coverage"] is not None:
            self._cov.set(row["coverage"])


# ---------------------------------------------------------------------
# resident serve loop (round 20)
# ---------------------------------------------------------------------

def resident_summary(report: dict) -> dict:
    """Derive the resident loop's headline aggregates from a
    :func:`~opendht_tpu.models.serve.serve_resident` report — the
    shared arithmetic between the bench's printed summary, the trace
    artifact and ``check_trace``'s resident block, so all three read
    the SAME numbers.

    ``overlap_frac`` is the double-buffer's yield: the share of the
    run wall spent BLOCKED in the drain ``device_get`` — near 0 means
    the readback fully overlapped device compute, near 1 means the
    loop degenerated to the burst engine's sync cadence.
    ``exchange_mb`` prices the routed exchange from the row counters
    (0 on the local engine) — the number that drops when mesh cache
    hits skip the ``all_to_all``.
    """
    r = report["resident"]
    elapsed = report["elapsed_s"]
    iters = r["iterations"]
    xchg = r["exchange"]
    rows = xchg["rows_init"] + xchg["rows_round"]
    return {
        "iterations": iters,
        "device_rounds": r["device_rounds"],
        "rounds_per_macro": (r["device_rounds"] / iters
                             if iters else 0.0),
        "host_orchestration_frac": r["host_orchestration_frac"],
        "host_orchestration_budget": r["host_orchestration_budget"],
        "overlap_frac": (r["blocked_get_s"] / elapsed
                         if elapsed > 0 else 0.0),
        "ring_utilization": (r["ring_depth_mean"] / r["ring_slots"]
                             if r["ring_slots"] else 0.0),
        "ring_shed": r["ring_shed"],
        "rung_select": r["rung_select"],
        "in_jit_rung_counts": list(r["in_jit_rung_counts"]),
        "exchange_rows": rows,
        "exchange_mb": rows * xchg["row_bytes"] / 1e6,
    }


class ResidentPlane:
    """Resident serve-loop gauges on the PR-3 registry (``prefix``
    defaults to ``dht_resident``): counters for macro iterations,
    device rounds, ring lifecycle events (enqueued / shed) and routed-
    exchange rows, plus gauges for the host-orchestration share, the
    drain-blocked (non-overlapped) share and the ring depth — the
    Prometheus face of :func:`resident_summary`."""

    def __init__(self, registry: MetricsRegistry,
                 prefix: str = "dht_resident"):
        self.registry = registry
        c, g = registry.counter, registry.gauge
        self._iters = c(f"{prefix}_macro_iterations_total",
                        "Resident macro steps dispatched")
        self._rounds = c(f"{prefix}_device_rounds_total",
                         "Lookup rounds run inside resident programs")
        self._ring = c(f"{prefix}_ring_events_total",
                       "Request-ring lifecycle events", ("event",))
        self._xchg = c(f"{prefix}_exchange_rows_total",
                       "Routed-exchange rows", ("leg",))
        self._orch = g(f"{prefix}_host_orchestration_ratio",
                       "Host share of the serve wall")
        self._blocked = g(f"{prefix}_drain_blocked_ratio",
                          "Non-overlapped drain share of the wall")
        self._depth = g(f"{prefix}_ring_depth",
                        "Device ring backlog", ("stat",))

    def publish_run(self, report: dict) -> None:
        r = report["resident"]
        self._iters.inc(r["iterations"])
        self._rounds.inc(r["device_rounds"])
        self._ring.inc(r["ring_enqueued"], event="enqueued")
        if r["ring_shed"]:
            self._ring.inc(r["ring_shed"], event="shed")
        xchg = r["exchange"]
        if xchg["rows_init"]:
            self._xchg.inc(xchg["rows_init"], leg="init")
        if xchg["rows_round"]:
            self._xchg.inc(xchg["rows_round"], leg="round")
        s = resident_summary(report)
        self._orch.set(round(s["host_orchestration_frac"], 6))
        self._blocked.set(round(s["overlap_frac"], 6))
        self._depth.set(r["ring_depth_mean"], stat="mean")
        self._depth.set(r["ring_depth_max"], stat="max")
