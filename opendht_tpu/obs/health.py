"""Swarm-health observability plane: coverage / freshness / lag gauges,
the analytic hop-distribution model, and the Poisson keyspace-density
profile.

Host half of the ISSUE-8 tentpole.  Three pieces:

* :class:`SwarmHealthPlane` — publishes the monitor's per-sweep record
  (``models.monitor.MonitorEngine``) through the PR-3 Prometheus
  registry: coverage ratio, tracked/actual population, freshness-age
  percentiles, churn-detection lag, false-alive/false-dead counts and
  per-coarse-prefix keyspace density gauges.
* :func:`analytic_hop_pmf` — the model-based fidelity instrument: a
  pure-numpy dynamic program over XOR prefix lengths predicting the
  engine's hop-count distribution from first principles (the
  probabilistic Kademlia analyses of arXiv:1309.5866 / 1402.1191 and
  the hop-count framework of arXiv:1307.7000, specialized to this
  engine's geometry).  ``tools/check_trace.py`` RECOMPUTES it when
  gating a monitor artifact, so the recorded band cannot be faked.
* :func:`poisson_density_profile` — distinct-node counts per crawl
  bucket against the Poisson(N/G) law that uniform random IDs obey
  (the 1402.1191 random-ID model): an anomaly in the observed
  count-of-counts profile means either the crawl under-samples a
  region or the ID space is not uniform.

Everything here is dependency-free host code (numpy only — no jax), so
the checker can import it in a process that never initializes a
backend.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import numpy as np

from ..utils.metrics import MetricsRegistry

# ---------------------------------------------------------------------------
# analytic hop-count distribution
# ---------------------------------------------------------------------------
#
# The engine's per-lookup ``hops`` counts solicitation ROUNDS until the
# sync quorum (8 closest all queried).  The model tracks the best-known
# common-prefix length p between shortlist head and target:
#
# * init: the origin shares c ~ Geometric(1/2) prefix bits with the
#   target; its bucket-c row returns K members that agree with the
#   target on >= c+1 bits (members differ from the origin exactly at
#   bit c, as does the target), each extending by an independent
#   Geometric(1/2) tail -> p0 = c + 1 + max(G_1..G_K).
# * per round: the alpha=4 solicited windows contribute the closer
#   bucket (K members each) of the leading responders; the trailing
#   window of the round's frontier sits one bucket shallower, so the
#   effective sample pool for the round's best extension is
#   (alpha-1)*K draws -> p' = p + 1 + max of (alpha-1)*K geometrics.
#   (The +1-per-round drift this yields, ~5.8 bits/round at K=8, is
#   what the measured 100k/1M/10M convergence depths imply.)
# * completion: the target's quorum-th closest node sits at prefix
#   p_q, where P(p_q >= j) = P(Poisson(N * 2^-j) >= quorum) — the
#   Poisson random-ID law.  The neighbourhood is REVEALED when the
#   frontier reaches p_q - reveal_margin (the revealing responder is
#   reached one query indirection early, and its two-bucket window
#   spans one extra depth), and syncing the quorum then costs
#   ceil(quorum/alpha) admission rounds plus the reveal round itself.
#
# Structural constants only — nothing is fitted to a measured
# histogram at run time.  Validated against measured histograms at
# 2^11..2^20 nodes: total variation <= 0.10 at every size
# (tests/test_monitor.py pins the small sizes), against the default
# gate band of HOP_TV_BAND.

HOP_TV_BAND = 0.20       # default artifact band (checker caps at 0.25)
HOP_MEDIAN_TOL = 1       # rounds of allowed median disagreement


def _poisson_tail_ge(lam: float, q: int) -> float:
    """P(Poisson(lam) >= q), stable for the small q used here."""
    if lam > 80.0:
        return 1.0
    term = math.exp(-lam)
    cdf = term
    for i in range(1, q):
        term *= lam / i
        cdf += term
    return max(0.0, 1.0 - cdf)


def analytic_hop_pmf(n_nodes: int, bucket_k: int = 8, alpha: int = 4,
                     quorum: int = 8, max_steps: int = 48) -> np.ndarray:
    """``[max_steps + 1]`` pmf over solicitation rounds predicted by
    the prefix-length dynamic program above — the analytic twin of
    ``models.swarm.hop_histogram`` (last bin = never converged)."""
    if n_nodes < 2:
        raise ValueError(f"analytic model needs n_nodes >= 2, got "
                         f"{n_nodes}")
    pmax = 96
    reveal_margin = 2
    sync_rounds = -(-quorum // alpha) + 1
    gain_samples = max(1, (alpha - 1) * bucket_k)

    def maxgeom_pmf(e: int) -> np.ndarray:
        cdf = (1.0 - 2.0 ** -(np.arange(pmax) + 1.0)) ** e
        return np.diff(np.concatenate([[0.0], cdf]))

    def shift1(p: np.ndarray) -> np.ndarray:   # p := p + 1
        out = np.roll(p, 1)
        out[0] = 0.0
        return out

    c_pmf = 2.0 ** -(np.arange(pmax) + 1.0)
    c_pmf /= c_pmf.sum()
    p0 = shift1(np.convolve(c_pmf, maxgeom_pmf(bucket_k))[:pmax])
    m_round = maxgeom_pmf(gain_samples)
    dists = [p0]
    for _ in range(max_steps):
        dists.append(shift1(np.convolve(dists[-1], m_round)[:pmax]))
    # P(p_r >= j): prefix growth is strictly monotone (+>=1 per
    # round), so first-passage pmfs are plain CDF differences.
    cdf_ge = [np.concatenate([np.cumsum(d[::-1])[::-1], [0.0]])
              for d in dists]

    tail = np.array([_poisson_tail_ge(n_nodes * 2.0 ** -j, quorum)
                     for j in range(pmax)])
    pq = tail.copy()
    pq[:-1] -= tail[1:]                       # P(p_q = j)

    h = np.zeros(max_steps + 1)
    for j in range(pmax):
        if pq[j] <= 0.0:
            continue
        thr = max(0, j - reveal_margin)
        for r in range(sync_rounds, max_steps):
            rr = r - sync_rounds
            prev = cdf_ge[rr - 1][thr] if rr >= 1 else 0.0
            cross = cdf_ge[rr][thr] - prev
            if cross > 0.0:
                h[r] += pq[j] * cross
    h[max_steps] += max(0.0, 1.0 - h.sum())
    return h


def _pmf_median(pmf: np.ndarray) -> int:
    c = np.cumsum(pmf)
    return int(np.searchsorted(c, 0.5 * c[-1], side="left"))


def hop_fidelity(measured_counts: Sequence[int], n_nodes: int,
                 bucket_k: int = 8, alpha: int = 4, quorum: int = 8,
                 band_tv: float = HOP_TV_BAND) -> Dict[str, object]:
    """Compare a measured hop histogram against the analytic model.

    Returns the comparison record the monitor artifact embeds and
    ``check_trace`` recomputes: total-variation distance, the two
    medians, the band, and the verdict (``tv <= band_tv`` AND medians
    within :data:`HOP_MEDIAN_TOL` rounds).
    """
    meas = np.asarray(measured_counts, float)
    total = meas.sum()
    if total <= 0:
        raise ValueError("measured hop histogram is empty")
    meas = meas / total
    model = analytic_hop_pmf(n_nodes, bucket_k=bucket_k, alpha=alpha,
                             quorum=quorum,
                             max_steps=len(meas) - 1)
    tv = 0.5 * float(np.abs(meas - model).sum())
    med_m, med_a = _pmf_median(meas), _pmf_median(model)
    return {
        "n_nodes": int(n_nodes),
        "bucket_k": int(bucket_k),
        "alpha": int(alpha),
        "quorum": int(quorum),
        "tv": round(tv, 6),
        "band_tv": float(band_tv),
        "median_measured": med_m,
        "median_model": med_a,
        "median_tolerance": HOP_MEDIAN_TOL,
        "ok": bool(tv <= band_tv
                   and abs(med_m - med_a) <= HOP_MEDIAN_TOL),
    }


# ---------------------------------------------------------------------------
# keyspace density vs the Poisson random-ID law
# ---------------------------------------------------------------------------

def poisson_density_profile(bucket_counts: Sequence[int],
                            max_count: int = 16) -> Dict[str, object]:
    """Distinct-node counts per crawl bucket vs Poisson(mean).

    ``bucket_counts``: the monitor fold's tracked-alive count per
    prefix bucket.  Uniform random IDs make these iid
    ~Binomial(N, 1/G) ≈ Poisson(N/G) (arXiv:1402.1191); the profile
    compares the observed count-of-counts pmf against that law
    (total variation + the two pmfs, counts clamped into a
    ``>= max_count`` tail bin).
    """
    counts = np.asarray(bucket_counts, np.int64)
    g = counts.shape[0]
    if g == 0:
        raise ValueError("no buckets")
    lam = float(counts.sum()) / g
    clamped = np.minimum(counts, max_count)
    observed = np.bincount(clamped, minlength=max_count + 1
                           ).astype(float) / g
    pois = np.zeros(max_count + 1)
    term = math.exp(-lam)
    for i in range(max_count):
        pois[i] = term
        term *= lam / (i + 1)
    pois[max_count] = max(0.0, 1.0 - pois[:max_count].sum())
    tv = 0.5 * float(np.abs(observed - pois).sum())
    return {
        "buckets": int(g),
        "tracked_nodes": int(counts.sum()),
        "mean_per_bucket": round(lam, 4),
        "max_count_bin": int(max_count),
        "observed_pmf": [round(float(v), 6) for v in observed],
        "poisson_pmf": [round(float(v), 6) for v in pois],
        "tv": round(tv, 6),
    }


def summarize_sweeps(records: Sequence[Dict[str, object]]
                     ) -> Dict[str, object]:
    """Steady-state summary of a monitor sweep-record list.

    The one reduction of per-sweep records every consumer needs — the
    monitor bench row, the soak artifact's monitor block, and
    ``tools/check_trace.py``'s soak checker (which RECOMPUTES it from
    the embedded records, so a summary diverging from its own sweeps
    cannot gate green).  Steady state = post-initial sweeps (sweep 0 is
    the full crawl); lag fields are ``None`` when no death was
    confirmed.  Records without the freshness plane (``coverage``
    absent) summarize to counts only.
    """
    recs = list(records)
    if not recs:
        raise ValueError("no sweep records to summarize")
    out: Dict[str, object] = {
        "sweeps": len(recs),
        "lookups_total": int(sum(r["lookups"] for r in recs)),
    }
    if "coverage" not in recs[0]:
        return out
    post = recs[1:] or recs
    lag_cnt = int(sum(r["lag_count"] for r in recs))
    out.update({
        "coverage_mean": round(
            float(np.mean([r["coverage"] for r in post])), 6),
        "coverage_min": round(min(r["coverage"] for r in post), 6),
        "coverage_final": recs[-1]["coverage"],
        "deaths_detected": lag_cnt,
        "detection_lag_mean": (round(
            sum(r["lag_sum"] for r in recs) / lag_cnt, 3)
            if lag_cnt else None),
        "detection_lag_max": (max(
            r["lag_max"] for r in recs if r["lag_count"])
            if lag_cnt else None),
        "false_dead_final": recs[-1]["false_dead"],
        "false_alive_final": recs[-1]["false_alive"],
        "freshness_p50_final": recs[-1]["age_p50"],
        "freshness_p99_final": recs[-1]["age_p99"],
    })
    return out


# ---------------------------------------------------------------------------
# the gauge surface
# ---------------------------------------------------------------------------

class SwarmHealthPlane:
    """Publishes the monitor's sweep records through the registry.

    Gauge catalogue (``prefix`` defaults to ``dht_swarm``):

    * ``<p>_coverage_ratio`` — tracked∩alive / alive;
    * ``<p>_tracked_alive`` / ``<p>_actual_alive`` — populations;
    * ``<p>_false_alive`` / ``<p>_false_dead`` — undetected
      departures / wrongly-presumed deaths;
    * ``<p>_freshness_age_sweeps{q="p50"|"p99"}`` — age percentiles;
    * ``<p>_detection_lag_sweeps{stat="mean"|"max"}`` — churn-
      detection lag of deaths confirmed this sweep;
    * ``<p>_sweep_index`` / ``<p>_buckets_probed`` — sweep geometry;
    * counters ``<p>_sweeps_total``, ``<p>_lookups_total``,
      ``<p>_nodes_seen_total``, ``<p>_deaths_detected_total``;
    * ``<p>_density_nodes{prefix}`` — tracked nodes per coarse
      keyspace region (top ``density_depth`` bits, 16 regions by
      default) and ``<p>_density_poisson_tv`` — the density profile's
      distance from the Poisson law.
    """

    def __init__(self, registry: MetricsRegistry,
                 prefix: str = "dht_swarm", density_depth: int = 4):
        self.registry = registry
        self.density_depth = density_depth
        g = registry.gauge
        self._coverage = g(f"{prefix}_coverage_ratio",
                           "Tracked-alive over actually-alive nodes")
        self._tracked = g(f"{prefix}_tracked_alive",
                          "Nodes the monitor presumes alive")
        self._actual = g(f"{prefix}_actual_alive",
                         "Ground-truth alive nodes")
        self._false_alive = g(f"{prefix}_false_alive",
                              "Departed nodes not yet detected")
        self._false_dead = g(f"{prefix}_false_dead",
                             "Alive nodes wrongly presumed dead")
        self._age = g(f"{prefix}_freshness_age_sweeps",
                      "Freshness age percentile over tracked nodes",
                      ("q",))
        self._lag = g(f"{prefix}_detection_lag_sweeps",
                      "Churn-detection lag of deaths confirmed this "
                      "sweep", ("stat",))
        self._sweep = g(f"{prefix}_sweep_index", "Last completed sweep")
        self._probed = g(f"{prefix}_buckets_probed",
                         "Buckets probed in the last sweep")
        c = registry.counter
        self._sweeps = c(f"{prefix}_sweeps_total", "Sweeps completed")
        self._lookups = c(f"{prefix}_lookups_total",
                          "Probe lookups dispatched")
        self._seen = c(f"{prefix}_nodes_seen_total",
                       "Node sightings folded")
        self._deaths = c(f"{prefix}_deaths_detected_total",
                         "Departures confirmed")
        self._density = g(f"{prefix}_density_nodes",
                          "Tracked nodes per coarse keyspace region",
                          ("prefix",))
        self._density_tv = g(f"{prefix}_density_poisson_tv",
                             "Total variation of the per-bucket "
                             "density profile vs the Poisson "
                             "random-ID law")

    def publish_sweep(self, record: Dict[str, object]) -> None:
        r = record
        self._sweep.set(r["sweep"])
        self._probed.set(r["buckets_probed"])
        self._sweeps.inc()
        self._lookups.inc(r["lookups"])
        if "coverage" not in r:        # freshness plane off
            return
        self._coverage.set(r["coverage"])
        self._tracked.set(r["tracked_alive"])
        self._actual.set(r["actual_alive"])
        self._false_alive.set(r["false_alive"])
        self._false_dead.set(r["false_dead"])
        self._age.set(r["age_p50"], q="p50")
        self._age.set(r["age_p99"], q="p99")
        self._seen.inc(r["nodes_seen"])
        self._deaths.inc(r["lag_count"])
        if r["lag_count"]:
            self._lag.set(r["lag_sum"] / r["lag_count"], stat="mean")
            self._lag.set(r["lag_max"], stat="max")

    def publish_density(self, bucket_counts: Sequence[int],
                        profile: Optional[Dict[str, object]] = None
                        ) -> Dict[str, object]:
        """Fold per-bucket tracked counts into the coarse density
        gauges; returns (and publishes the tv of) the Poisson
        profile."""
        counts = np.asarray(bucket_counts, np.int64)
        g = counts.shape[0]
        coarse = min(self.density_depth, max(0, g.bit_length() - 1))
        per = g >> coarse
        for i in range(1 << coarse):
            self._density.set(
                int(counts[i * per:(i + 1) * per].sum()),
                prefix=format(i, f"0{max(1, (coarse + 3) // 4)}x"))
        if profile is None:
            profile = poisson_density_profile(counts)
        self._density_tv.set(profile["tv"])
        return profile
