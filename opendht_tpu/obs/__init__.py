"""Observability plane: cost attribution on top of PR 3's flight
recorder.

``opendht_tpu.obs.ledger`` extends the counters-only recorder
(``LookupTrace``/``StoreTrace``) to *cost* attribution: per-compiled-
executable wall/FLOPs/bytes records, HBM watermarks, and the
round-sub-phase A/B pass that prices gather / window decode /
alpha-select / merge / scatter-writeback against the fused round.
``opendht_tpu.tools.roofline`` turns a ledger artifact into the
compute- vs memory- vs issue-bound verdict.
"""

from .health import (  # noqa: F401
    SwarmHealthPlane,
    analytic_hop_pmf,
    hop_fidelity,
    poisson_density_profile,
)
from .latency import (  # noqa: F401
    LatencyPlane,
    publish_hop_histogram,
)
from .ledger import (  # noqa: F401
    CostLedger,
    hbm_watermark,
    instrumented_entry_points,
    measure_round_phases,
    step_cache_size,
)
