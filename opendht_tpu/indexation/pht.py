"""Prefix Hash Tree: a distributed trie index over the DHT.

Re-design of the reference ``dht::indexation::Pht``
(ref: src/indexation/pht.cpp, include/opendht/indexation/pht.h:40-510):

* multi-field keys are padded per the key spec then bit-interleaved
  (z-curve) into one binary ``Prefix`` (``linearize``/``zcurve``
  pht.cpp:352-421);
* the trie node for a prefix lives at ``hash(content ‖ size)``
  (``Prefix::hash`` pht.h:103-107); node presence is marked by "canary"
  values with user_type ``index.pht.<name>.canary``
  (``updateCanary`` pht.cpp:291-310);
* lookup is an async binary search on prefix length, probing ``mid``
  and ``mid+1`` in parallel — leaf iff ``mid`` is a PHT node and
  ``mid+1`` is not (``lookupStep`` pht.cpp:131-268); inexact lookup
  keeps the entries with the longest common prefix;
* insert walks to the leaf; when the leaf is full
  (> MAX_NODE_ENTRY_COUNT = 16) it splits at the divergence point
  (``split`` pht.cpp:503-514, ``foundSplitLocation`` pht.h:468-475);
  a listen on the next prefix re-inserts when a deeper split is
  detected (``checkPhtUpdate`` pht.cpp:478-501);
* a client-side trie cache remembers known trie depth per prefix with
  5-minute node expiry (``Cache`` pht.cpp:42-126).

Uses only the public get/put/listen surface of the DHT (works over the
core, the runner, or the TPU-simulated swarm adapter).
"""

from __future__ import annotations

import random
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

import msgpack

from ..core.value import Value
from ..utils.infohash import InfoHash

INDEX_PREFIX = "index.pht."
MAX_NODE_ENTRY_COUNT = 16
CACHE_NODE_EXPIRE_TIME = 5 * 60.0
CACHE_MAX_ELEMENT = 1024

# An index entry points at (hash, value id) — ref pht.h:246.
IndexValue = Tuple[InfoHash, int]


class Prefix:
    """A bit-string prefix (MSB-first), with per-bit "known" flags
    (ref: pht.h:40-190)."""

    __slots__ = ("content", "flags", "size")

    def __init__(self, content: bytes = b"", size: Optional[int] = None,
                 flags: bytes = b""):
        self.content = bytes(content)
        self.flags = bytes(flags)
        self.size = len(self.content) * 8 if size is None else int(size)

    # -- bit helpers (MSB-first like the reference's isActiveBit) -------
    @staticmethod
    def _bit(b: bytes, pos: int) -> bool:
        return ((b[pos // 8] >> (7 - (pos % 8))) & 1) == 1

    @staticmethod
    def _with_bit_flipped(b: bytes, pos: int) -> bytes:
        ba = bytearray(b)
        ba[pos // 8] ^= 1 << (7 - (pos % 8))
        return bytes(ba)

    def is_content_bit_active(self, pos: int) -> bool:
        return self._bit(self.content, pos)

    def is_flag_active(self, pos: int) -> bool:
        return not self.flags or self._bit(self.flags, pos)

    # -- derivation -----------------------------------------------------
    def get_prefix(self, length: int) -> "Prefix":
        """Truncate to ``length`` bits (negative = relative to size)."""
        if abs(length) > len(self.content) * 8:
            raise IndexError("len larger than prefix size")
        if length < 0:
            length += self.size
        nbytes = length // 8
        rem = length % 8
        content = self.content[:nbytes]
        flags = self.flags[:nbytes] if self.flags else b""
        if rem:
            content += bytes([self.content[nbytes] & (0xFF << (8 - rem))])
            if self.flags:
                flags += bytes([self.flags[nbytes] & (0xFF << (8 - rem))])
        return Prefix(content, length, flags)

    def get_full_size(self) -> "Prefix":
        return Prefix(self.content, len(self.content) * 8, self.flags)

    def get_sibling(self) -> "Prefix":
        """Flip the last bit (ref: pht.h:94-101)."""
        if not self.size:
            return Prefix(self.content, self.size, self.flags)
        return Prefix(self._with_bit_flipped(self.content, self.size - 1),
                      self.size, self.flags)

    def hash(self) -> InfoHash:
        """Trie-node location: SHA-1(content ‖ size) (ref: pht.h:103-107;
        the reference truncates size to one byte — kept for shape)."""
        return InfoHash.get(self.content + bytes([self.size & 0xFF]))

    @staticmethod
    def common_bits(a: "Prefix", b: "Prefix") -> int:
        n = min(a.size, b.size)
        for i in range(n):
            if (a.is_content_bit_active(i) != b.is_content_bit_active(i)
                    or not a.is_flag_active(i) or not b.is_flag_active(i)):
                return i
        return n

    def __eq__(self, other):
        return (isinstance(other, Prefix) and self.size == other.size
                and self.content == other.content)

    def __repr__(self):
        bits = "".join("1" if self.is_content_bit_active(i) else "0"
                       for i in range(self.size))
        return f"Prefix({bits})"


class IndexEntry:
    """A stored index record: full linearized prefix + target
    (ref: pht.h:247-266)."""

    __slots__ = ("prefix", "value", "name")

    def __init__(self, prefix: Prefix, value: IndexValue, name: str = ""):
        self.prefix = prefix
        self.value = value
        self.name = name

    def pack_value(self) -> Value:
        blob = msgpack.packb({
            "p": self.prefix.content,
            "sz": self.prefix.size,
            "h": bytes(self.value[0]),
            "vid": self.value[1],
        })
        return Value(blob, 0, user_type=self.name)

    @classmethod
    def unpack_value(cls, v: Value) -> "IndexEntry":
        o = msgpack.unpackb(v.data, raw=False, strict_map_key=False)
        return cls(Prefix(bytes(o["p"]), int(o["sz"])),
                   (InfoHash(bytes(o["h"])), int(o["vid"])),
                   v.user_type)


class _CacheNode:
    __slots__ = ("children", "last_reply")

    def __init__(self):
        self.children: Dict[bool, "_CacheNode"] = {}
        self.last_reply = 0.0


class Cache:
    """Client-side trie depth cache (ref: pht.cpp:42-126)."""

    def __init__(self, now: Callable[[], float] = _time.monotonic):
        self.root = _CacheNode()
        self._now = now
        self._count = 0

    def insert(self, p: Prefix) -> None:
        now = self._now()
        node = self.root
        node.last_reply = now
        for i in range(p.size):
            bit = p.is_content_bit_active(i)
            nxt = node.children.get(bit)
            if nxt is None:
                nxt = _CacheNode()
                node.children[bit] = nxt
                self._count += 1
            nxt.last_reply = now
            node = nxt
        if self._count > CACHE_MAX_ELEMENT:
            self._expire(self.root, now)

    def lookup(self, p: Prefix) -> int:
        """Deepest cached trie depth along ``p`` (-1 if none)."""
        now = self._now()
        pos = -1
        node = self.root
        while node is not None and node.last_reply + \
                CACHE_NODE_EXPIRE_TIME >= now:
            pos += 1
            if pos >= len(p.content) * 8 or pos >= p.size:
                break
            node = node.children.get(p.is_content_bit_active(pos))
        return pos

    def _expire(self, node: _CacheNode, now: float) -> None:
        for bit, child in list(node.children.items()):
            if child.last_reply + CACHE_NODE_EXPIRE_TIME < now:
                del node.children[bit]
                self._count -= self._subtree_size(child)
            else:
                self._expire(child, now)

    @classmethod
    def _subtree_size(cls, node: _CacheNode) -> int:
        return 1 + sum(cls._subtree_size(c)
                       for c in node.children.values())


class Pht:
    """The index object (ref: pht.h:268-510)."""

    def __init__(self, name: str, key_spec: Dict[str, int], dht,
                 rng: Optional[random.Random] = None,
                 parent_insert: bool = True):
        self.name = INDEX_PREFIX + name
        self.canary = self.name + ".canary"
        self.key_spec = dict(key_spec)
        self.dht = dht
        self.rng = rng or random.Random()
        # The reference's _get_real_prefix heuristic (insert at the
        # parent while leaf+parent+sibling stay under the cap,
        # pht.cpp:423-476) is insertion-ORDER-dependent and parks
        # entries at interior nodes.  parent_insert=False pins inserts
        # to the true leaf — the deterministic rule the device index
        # (models/index.py) implements, and what the host↔device
        # conformance test runs both sides under.  Default True keeps
        # reference behavior.
        self.parent_insert = bool(parent_insert)
        now = getattr(dht, "scheduler", None)
        self.cache = Cache(now.time if now is not None else _time.monotonic)

    # ------------------------------------------------------------------ #
    # key linearization                                                  #
    # ------------------------------------------------------------------ #

    def valid_key(self, key: Dict[str, bytes]) -> bool:
        """ref: Pht::validKey pht.h:492-500."""
        return (set(key) == set(self.key_spec)
                and all(len(v) <= self.key_spec[k]
                        for k, v in key.items()))

    def linearize(self, key: Dict[str, bytes]) -> Prefix:
        """Pad each field to the max spec length + terminator, then
        z-curve interleave (ref: pht.cpp:400-421)."""
        if not self.valid_key(key):
            raise ValueError("Key does not match the PHT key spec.")
        max_len = max(self.key_spec.values()) + 1
        prefixes = []
        for field in sorted(self.key_spec):
            data = key[field]
            content = bytearray(data + bytes(max_len - len(data)))
            size = len(data) * 8
            # Terminator bit right after the content (disambiguates
            # "ab" from "ab\0") — the reference's addPadding end-marker.
            if len(data) < max_len:
                content[size // 8] |= 0x80 >> (size % 8)
            flags = bytes(b"\xFF" * max_len)
            prefixes.append(Prefix(bytes(content), len(content) * 8, flags))
        return self.zcurve(prefixes)

    @staticmethod
    def zcurve(prefixes: List[Prefix]) -> Prefix:
        """Bit-interleave the fields (ref: pht.cpp:352-398)."""
        if len(prefixes) == 1:
            return prefixes[0]
        nf = len(prefixes)
        nbits = len(prefixes[0].content) * 8
        content = bytearray((nbits * nf + 7) // 8)
        flags = bytearray(len(content))
        t = 0
        for i in range(nbits):
            for p in prefixes:
                if p.is_content_bit_active(i):
                    content[t // 8] |= 0x80 >> (t % 8)
                if p.is_flag_active(i):
                    flags[t // 8] |= 0x80 >> (t % 8)
                t += 1
        return Prefix(bytes(content), t, bytes(flags))

    # ------------------------------------------------------------------ #
    # lookup                                                             #
    # ------------------------------------------------------------------ #

    def _pht_filter(self, v: Value) -> bool:
        # Exact match (not startswith): trie-node hashes depend only on
        # the linearized key, so indexes named "foo" and "foobar" share
        # DHT keys and must be distinguished by user_type alone.
        return v.user_type in (self.name, self.canary)

    def lookup(self, key: Dict[str, bytes],
               cb: Callable[[List[IndexValue], Prefix], None],
               done_cb: Optional[Callable[[bool], None]] = None,
               exact: bool = True) -> None:
        """ref: Pht::lookup pht.cpp:270-289."""
        prefix = self.linearize(key)
        state = {"max_common": 0} if not exact else None
        self._lookup_step(
            prefix, [0], [prefix.size], [], cb, done_cb, state,
            self.cache.lookup(prefix), all_values=False)

    def _lookup_step(self, p: Prefix, lo: List[int], hi: List[int],
                     vals: List[IndexEntry], cb, done_cb,
                     inexact_state: Optional[dict], start: int,
                     all_values: bool) -> None:
        """Async binary search on prefix length
        (ref: Pht::lookupStep pht.cpp:131-268)."""
        # int() truncates toward zero like the reference's C int
        # division ((0 + -1)/2 == 0, not Python floor's -1)
        mid = start if start >= 0 else int((lo[0] + hi[0]) / 2)
        first = {"done": False, "is_pht": False}
        second = {"done": False, "is_pht": False}

        def on_done(ok: bool) -> None:
            is_leaf = first["is_pht"] and not second["is_pht"]
            if not ok:
                if done_cb:
                    done_cb(False)
            elif is_leaf or lo[0] > hi[0]:
                to_insert = p.get_prefix(mid)
                self.cache.insert(to_insert)
                if cb is not None:
                    if (not vals and inexact_state is not None
                            and mid > 0):
                        # Inexact miss: walk the sibling subtree.
                        p2 = p.get_prefix(mid).get_sibling().get_full_size()
                        lo[0] = mid
                        hi[0] = p2.size
                        self._lookup_step(p2, lo, hi, vals, cb, done_cb,
                                          inexact_state, -1, all_values)
                        return
                    cb([e.value for e in vals], to_insert)
                if done_cb:
                    done_cb(True)
            elif first["is_pht"]:
                lo[0] = mid + 1
                self._lookup_step(p, lo, hi, vals, cb, done_cb,
                                  inexact_state, -1, all_values)
            else:
                if done_cb:
                    done_cb(False)

        if lo[0] > hi[0]:
            on_done(True)
            return

        def on_get(values: List[Value], res: dict) -> bool:
            for value in values:
                if value.user_type == self.canary:
                    res["is_pht"] = True
                    continue
                try:
                    entry = IndexEntry.unpack_value(value)
                except Exception:
                    continue
                if any(e.value == entry.value for e in vals):
                    continue
                if inexact_state is not None:
                    cbits = Prefix.common_bits(p, entry.prefix)
                    if not vals:
                        vals.append(entry)
                        inexact_state["max_common"] = cbits
                    elif cbits == inexact_state["max_common"]:
                        vals.append(entry)
                    elif cbits > inexact_state["max_common"]:
                        vals.clear()
                        vals.append(entry)
                        inexact_state["max_common"] = cbits
                elif all_values or entry.prefix.content == p.content:
                    vals.append(entry)
            return True

        def first_done(ok: bool, nodes=None) -> None:
            if not ok:
                first["done"] = True
                if done_cb and second["done"]:
                    on_done(False)
                return
            if not first["is_pht"]:
                hi[0] = mid - 1
                self._lookup_step(p, lo, hi, vals, cb, done_cb,
                                  inexact_state, -1, all_values)
            else:
                first["done"] = True
                if second["done"] or mid >= p.size - 1:
                    on_done(True)

        def second_done(ok: bool, nodes=None) -> None:
            second["done"] = True
            if not ok:
                if done_cb and first["done"]:
                    on_done(False)
            elif first["done"]:
                on_done(True)

        self.dht.get(p.get_prefix(mid).hash(),
                     lambda vs: on_get(vs, first),
                     first_done, f=self._pht_filter)
        if mid < p.size - 1:
            self.dht.get(p.get_prefix(mid + 1).hash(),
                         lambda vs: on_get(vs, second),
                         second_done, f=self._pht_filter)
        else:
            second["done"] = True

    # ------------------------------------------------------------------ #
    # insert                                                             #
    # ------------------------------------------------------------------ #

    def insert(self, key: Dict[str, bytes], value: IndexValue,
               done_cb: Optional[Callable[[bool], None]] = None) -> None:
        """ref: Pht::insert pht.cpp:312-350."""
        kp = self.linearize(key)
        entry = IndexEntry(kp.get_full_size(), value, self.name)
        created = self._now()
        self._insert(kp, entry, [0], [kp.size], created, True, done_cb)

    def _now(self) -> float:
        sched = getattr(self.dht, "scheduler", None)
        return sched.time() if sched is not None else _time.monotonic()

    def _put(self, h: InfoHash, value: Value, done_cb=None) -> None:
        # Adapt our put's (ok, nodes) done signature to the simple one.
        self.dht.put(h, value,
                     (lambda ok, nodes: done_cb(ok)) if done_cb else None)

    def _insert(self, kp: Prefix, entry: IndexEntry, lo: List[int],
                hi: List[int], time_p: float, check_split: bool,
                done_cb) -> None:
        vals: List[IndexEntry] = []
        final_prefix: List[Optional[Prefix]] = [None]

        def on_leaf(values, p: Prefix) -> None:
            final_prefix[0] = p

        def on_lookup_done(ok: bool) -> None:
            if not ok:
                if done_cb:
                    done_cb(False)
                return

            def real_insert(p: Prefix, e: IndexEntry) -> None:
                self._update_canary(p)
                self._check_pht_update(p, e, time_p)
                self.cache.insert(p)
                self._put(p.hash(), e.pack_value(), done_cb)

            fp = final_prefix[0]
            if not check_split or (fp is not None and fp.size == kp.size):
                real_insert(fp if fp is not None else kp, entry)
            elif len(vals) < MAX_NODE_ENTRY_COUNT:
                if self.parent_insert:
                    self._get_real_prefix(fp, entry, real_insert)
                else:
                    real_insert(fp if fp is not None else kp, entry)
            else:
                self._split(fp, vals, entry, real_insert)

        self._lookup_step(kp, lo, hi, vals,
                          lambda values, p: on_leaf(values, p),
                          on_lookup_done, None, self.cache.lookup(kp),
                          all_values=True)

    def _update_canary(self, p: Prefix) -> None:
        """Mark trie-node presence, propagating up with p=1/2
        (ref: Pht::updateCanary pht.cpp:291-310)."""
        v = Value(b"", 0, user_type=self.canary)

        def done(ok, nodes=None):
            if p.size and self.rng.random() < 0.5:
                self._update_canary(p.get_prefix(-1))

        self.dht.put(p.hash(), v, done)
        if p.size:
            self.dht.put(p.get_sibling().hash(),
                         Value(b"", 0, user_type=self.canary), None)

    def _get_real_prefix(self, p: Optional[Prefix], entry: IndexEntry,
                         end_cb) -> None:
        """Count entries at leaf/parent/sibling; insert at the parent if
        the 3 together stay under the cap (ref: pht.cpp:423-476)."""
        if p is None or p.size == 0:
            end_cb(p if p is not None else Prefix(), entry)
            return
        total = [0]
        ended = [0]
        parent = p.get_prefix(-1)
        sibling = p.get_sibling()

        def count(values: List[Value]) -> bool:
            total[0] += sum(1 for v in values
                            if v.user_type != self.canary)
            return True

        def on_done(ok, nodes=None) -> None:
            ended[0] += 1
            if ended[0] == 3:
                if total[0] < MAX_NODE_ENTRY_COUNT:
                    end_cb(parent, entry)
                else:
                    end_cb(p, entry)

        for h in (parent.hash(), p.hash(), sibling.hash()):
            self.dht.get(h, count, on_done, f=self._pht_filter)

    def _check_pht_update(self, p: Prefix, entry: IndexEntry,
                          time_p: float) -> None:
        """Listen for a deeper split and re-insert when it happens
        (ref: Pht::checkPhtUpdate pht.cpp:478-501)."""
        full = entry.prefix
        if p.size + 1 > full.size:
            return
        next_prefix = full.get_prefix(p.size + 1)

        def on_values(values: List[Value]) -> bool:
            for v in values:
                if v.user_type == self.canary:
                    self._insert(full, entry, [0], [full.size], time_p,
                                 False, None)
                    return False
            return True

        self.dht.listen(next_prefix.hash(), on_values,
                        f=self._pht_filter)

    def _split(self, insert_p: Prefix, vals: List[IndexEntry],
               entry: IndexEntry, end_cb) -> None:
        """Split a full leaf at the divergence point
        (ref: Pht::split pht.cpp:503-514, foundSplitLocation
        pht.h:468-475)."""
        full = entry.prefix
        loc = self._found_split_location(full, vals)
        prefix_to_insert = full.get_prefix(loc)
        i = loc
        while i > insert_p.size - 1 and i > 0:
            self._update_canary(full.get_prefix(i))
            i -= 1
        end_cb(prefix_to_insert, entry)

    @staticmethod
    def _found_split_location(compared: Prefix,
                              vals: List[IndexEntry]) -> int:
        for i in range(len(compared.content) * 8 - 1):
            for e in vals:
                if (e.prefix.is_content_bit_active(i)
                        != compared.is_content_bit_active(i)):
                    return i + 1
        return len(compared.content) * 8 - 1
