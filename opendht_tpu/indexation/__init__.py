"""Secondary indexes over the DHT: prefix hash tree (PHT)."""

from .pht import Cache, IndexEntry, IndexValue, Pht, Prefix  # noqa: F401
