"""Device-native PHT secondary index over the swarm storage engine.

The host :class:`~opendht_tpu.indexation.pht.Pht` (ref
src/indexation/pht.cpp) runs one async callback chain per key: linearize
→ binary-search the trie depth with per-prefix ``get`` probes → insert /
split.  This module is its device twin (ROADMAP #5): the SAME trie —
canary values marking node presence, ≤ ``MAX_NODE_ENTRY_COUNT`` entries
per leaf, split at the divergence point — stored in a
:class:`~opendht_tpu.models.storage.SwarmStore` and driven as BATCHED
device programs:

* **key encoding** — multi-field keys are padded + terminator-marked +
  z-curve interleaved exactly like ``Pht.linearize`` (one vectorized
  bit-transpose kernel, :func:`_linearize_batch`), and a trie node at
  prefix depth ``m`` lives at ``SHA-1(content ‖ size-byte)`` — the
  *actual* ``Prefix.hash``, computed on device by the batched
  single-block SHA-1 (:mod:`opendht_tpu.ops.sha1`), so host and device
  derive bit-identical 160-bit store keys;
* **value taxonomy** — the engine's store holds ONE value per (node,
  key) slot, so the host's user-type taxonomy maps to a slot-key
  discriminator: discriminator 0 (the bare prefix hash) is the canary
  (user_type ``index.pht.<name>.canary``), discriminators 1..16 are the
  leaf's entry slots (user_type ``index.pht.<name>``), derived from the
  node key by an odd-constant limb mix (:func:`slot_keys`).  The
  16-entry leaf capacity is therefore STRUCTURAL — a trie node cannot
  hold a 17th entry, it must split, exactly the reference's
  ``MAX_NODE_ENTRY_COUNT`` rule;
* **batched leaf search** — the per-key async binary search on prefix
  length becomes a ``[B]``-wide lock-step walk: each refinement round
  issues ONE micro-batch of canary get-probes through the compacted
  burst engine (``lookup``'s ladder prices converged probe rows by the
  active set for free), converging every key in ≤ ``⌈log₂(maxdepth)⌉+1``
  rounds instead of B callback chains;
* **insert** — :meth:`DeviceIndex.insert_batch` walks all keys to
  their leaves, scatters entries into free slots (per-leaf arrival
  ranking keeps a batch sequentially-equivalent to the host's one-at-a-
  time inserts), and resolves full leaves with the host's split rule
  (canary chain from the old leaf to the divergence point, both
  siblings marked per level) plus a bounded re-insert pass — the eager
  twin of the host's listener-triggered deeper re-insert
  (``checkPhtUpdate``);
* **range scan** — :meth:`DeviceIndex.range_query` walks the contiguous
  leaf span covering ``[lo, hi]`` (z-curve order = prefix numeric
  order) and returns the EXACT entry set via batched slot gets — the
  read-heavy scan workload class of "Efficient Indexing of the
  BitTorrent DHT" (arXiv:1009.3681).

Two deliberate, documented deviations from the host object (both sides
of the conformance test use the same rules):

* the host's probabilistic canary up-propagation (``updateCanary``'s
  p=1/2 parent recursion) is dropped — it only re-marks interior nodes
  that the deterministic split chain already marked, so the reachable
  trie is identical;
* the host's ``_get_real_prefix`` parent-insert heuristic (insert at
  the parent while leaf+parent+sibling < 16) is order-dependent and
  parks entries at interior nodes where only some probe paths find
  them; the device engine always inserts at the true leaf.  The host
  ``Pht`` grew a ``parent_insert=False`` knob so the conformance test
  pins both implementations to the deterministic rule (the default
  host behavior is unchanged).

Everything host↔device interchangeable is proven in
``tests/test_index.py``: the same key set inserted via the host ``Pht``
(over :class:`StoreDht`, a host DHT facade speaking this encoding
against the same ``SwarmStore``) and via :class:`DeviceIndex` yields
identical leaf prefixes and per-leaf entry sets, and each side reads
the other's trie.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, FrozenSet, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..indexation.pht import INDEX_PREFIX, MAX_NODE_ENTRY_COUNT
from ..ops.sha1 import sha1_one_block, sha1_pad_le55
from .storage import (
    StoreConfig, SwarmStore, announce, get_values, pow2_width,
)
from .swarm import Swarm, SwarmConfig

# Canary value token ("CANA") — the device form of the
# ``index.pht.<name>.canary`` user type.  Entries carry per-entry
# tokens (:func:`entry_tokens`); the namespaces cannot collide because
# canaries live only at discriminator-0 keys and entries only at 1..16.
CANARY_TOKEN = 0x43414E41
# Odd ⇒ invertible mod 2³²: distinct discriminators give distinct keys.
SLOT_KEY_MULT = 0x9E3779B9
_TOKEN_MULT = 0x85EBCA6B
_U32 = jnp.uint32


class IndexSpec(NamedTuple):
    """Static index geometry (hashable — part of the jit cache key).

    ``fields``: sorted ``(name, max_bytes)`` pairs — the host
    ``key_spec`` dict in canonical order.  Derived quantities mirror
    ``Pht.linearize``: every field pads to ``max(max_bytes) + 1`` bytes
    (the +1 hosts the end-marker bit), and the z-curve interleaves all
    fields bit-by-bit, so a full key is always exactly
    ``prefix_bits`` long.
    """
    fields: Tuple[Tuple[str, int], ...]
    name: str = "index"

    @classmethod
    def from_key_spec(cls, name: str, key_spec: Dict[str, int]
                      ) -> "IndexSpec":
        spec = cls(tuple(sorted((k, int(v)) for k, v in
                               key_spec.items())), name)
        if spec.prefix_bytes > 32:
            raise ValueError(
                f"IndexSpec too wide: {spec.prefix_bytes} linearized "
                f"bytes > 32 (the device trie-hash packs prefix + size "
                f"byte into one SHA-1 block)")
        return spec

    @property
    def field_len(self) -> int:           # bytes per padded field
        return max(b for _, b in self.fields) + 1

    @property
    def n_fields(self) -> int:
        return len(self.fields)

    @property
    def prefix_bytes(self) -> int:
        return self.n_fields * self.field_len

    @property
    def prefix_bits(self) -> int:
        return self.prefix_bytes * 8

    @property
    def prefix_words(self) -> int:
        return -(-self.prefix_bytes // 4)

    @property
    def payload_words(self) -> int:
        """Entry payload layout: hash limbs [0:5], value id [5], prefix
        size bits [6], full prefix words [7:7+prefix_words] — the
        wire-complete :class:`~opendht_tpu.indexation.pht.IndexEntry`,
        so the host adapter can reconstruct the msgpack value from the
        store alone."""
        return 7 + self.prefix_words

    @property
    def value_type(self) -> str:
        return INDEX_PREFIX + self.name

    @property
    def canary_type(self) -> str:
        return self.value_type + ".canary"

    @property
    def probe_round_bound(self) -> int:
        """Binary-search round bound per leaf walk: the interval
        [0, prefix_bits) halves every round, and a depth-hint miss
        (reader over a deeper trie than its hint — see
        :meth:`DeviceIndex.leaf_search`) restarts the search once over
        the full interval, so the bound is two full halvings plus the
        empty-trie resolution round."""
        return 2 * (int(math.ceil(math.log2(self.prefix_bits + 1))) + 1)


# ---------------------------------------------------------------------------
# vectorized key encoding kernels
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("spec",))
def _linearize_batch(spec: IndexSpec, fbytes: jax.Array,
                     flens: jax.Array) -> jax.Array:
    """Vectorized ``Pht.linearize``: pad + end-marker + z-curve.

    ``fbytes [B, F, field_len] uint32`` holds each field's raw bytes
    (zero-padded; ``flens [B, F]`` gives each field's true byte
    length).  Returns the linearized prefix as ``[B, PW] uint32``
    MSB-first bit words.  The z-curve is literally a bit transpose:
    unpack per-field bits ``[B, F, fbits]``, transpose to
    ``[B, fbits, F]``, flatten — bit ``t`` of the output is bit
    ``t // F`` of field ``t % F``, exactly ``Pht.zcurve``.
    """
    fl = spec.field_len
    # End-marker bit right after the content (host linearize): byte
    # ``len`` gets its MSB set — valid keys always satisfy len < fl.
    idx = jnp.arange(fl, dtype=jnp.int32)
    marked = fbytes | jnp.where(
        idx[None, None, :] == flens[..., None], _U32(0x80), _U32(0))
    bidx = jnp.arange(fl * 8, dtype=jnp.int32)
    byte = jnp.take(marked, bidx // 8, axis=-1)         # [B,F,fl*8]
    fbits = (byte >> (7 - bidx % 8).astype(_U32)) & _U32(1)
    z = jnp.swapaxes(fbits, -1, -2).reshape(
        fbits.shape[0], -1)                             # [B, nbits]
    nbits = spec.prefix_bits
    pw = spec.prefix_words
    pad = pw * 32 - nbits
    if pad:
        z = jnp.concatenate(
            [z, jnp.zeros((z.shape[0], pad), _U32)], axis=1)
    weights = (_U32(1) << (31 - jnp.arange(32, dtype=jnp.int32)
                           ).astype(_U32))
    return jnp.sum(z.reshape(-1, pw, 32) * weights[None, None, :],
                   axis=-1, dtype=_U32)


def _word_masks(pw: int, nbits: jax.Array) -> jax.Array:
    """``[..., pw] uint32`` masks keeping the first ``nbits`` bits."""
    limbs = []
    for w in range(pw):
        rem = jnp.clip(nbits - 32 * w, 0, 32)
        shift = jnp.clip(32 - rem, 0, 31).astype(_U32)
        m = (_U32(0xFFFFFFFF) << shift) & _U32(0xFFFFFFFF)
        limbs.append(jnp.where(rem == 0, _U32(0), m))
    return jnp.stack(limbs, axis=-1)


@partial(jax.jit, static_argnames=("spec",))
def _trie_node_hash(spec: IndexSpec, bits: jax.Array,
                    depth: jax.Array) -> jax.Array:
    """Batched ``Prefix.hash``: SHA-1(masked content ‖ size byte).

    ``bits [..., PW] uint32``, ``depth [...] int32`` (prefix length in
    bits).  Returns ``[..., 5] uint32`` InfoHash limbs — byte-identical
    to ``Prefix.hash()`` of the same prefix, so host and device
    address the same trie nodes.
    """
    pw = spec.prefix_words
    d = depth.astype(jnp.int32)
    masked = bits & _word_masks(pw, d)
    nb = (d + 7) // 8                       # content bytes
    content = jnp.concatenate(
        [masked, jnp.zeros(masked.shape[:-1] + (1,), _U32)], axis=-1)
    size_byte = (d & 0xFF).astype(_U32)
    lane = jnp.clip(nb - 4 * (nb // 4), 0, 3)
    or_val = size_byte << (_U32(8) * (3 - lane).astype(_U32))
    widx = nb // 4
    sel = jnp.arange(pw + 1, dtype=jnp.int32)
    content = content | jnp.where(
        sel == widx[..., None], or_val[..., None], _U32(0))
    return sha1_one_block(sha1_pad_le55(content, nb + 1))


def slot_keys(tkeys: np.ndarray, d) -> np.ndarray:
    """Store key of discriminator ``d`` under trie-node key ``tkeys
    [..., 5]``: d = 0 is the canary (the node key itself), 1..16 the
    entry slots.  The odd multiplier makes distinct discriminators
    collide nowhere.  Host-side (numpy): slot keys are derived from
    device-computed node hashes in O(batch) scalar mixes — the heavy
    work (SHA-1, probes) stays on device."""
    tkeys = np.asarray(tkeys, np.uint32)
    mix = (np.asarray(d).astype(np.uint64) * SLOT_KEY_MULT
           % (1 << 32)).astype(np.uint32)
    shape = np.broadcast_shapes(tkeys.shape[:-1], mix.shape)
    out = np.broadcast_to(tkeys, shape + (5,)).copy()
    out[..., 4] ^= np.broadcast_to(mix, shape)
    return out


def entry_tokens(ehash0, vid) -> np.ndarray:
    """Per-entry uint32 value token: limb 0 of the entry's target hash
    mixed with the value id — the in-store identity the edit policy's
    same-value refresh test keys on."""
    return (np.asarray(ehash0, np.uint64)
            ^ (np.asarray(vid, np.uint64) * _TOKEN_MULT)
            ).astype(np.uint64).astype(np.uint32) & np.uint32(0xFFFFFFFF)


@partial(jax.jit, static_argnames=("spec",))
def _pack_entry_payloads(spec: IndexSpec, ehash: jax.Array,
                         vid: jax.Array, bits: jax.Array) -> jax.Array:
    """Entry payload ``[B, payload_words]`` — see
    :attr:`IndexSpec.payload_words` for the layout."""
    b = ehash.shape[0]
    return jnp.concatenate(
        [ehash.astype(_U32), vid.astype(_U32)[:, None],
         jnp.full((b, 1), spec.prefix_bits, _U32), bits.astype(_U32)],
        axis=1)


# ---------------------------------------------------------------------------
# host-side bit helpers (numpy, shared by the engine and the adapter)
# ---------------------------------------------------------------------------

def np_mask_bits(bits: np.ndarray, depth) -> np.ndarray:
    """Numpy twin of the per-word prefix mask."""
    bits = np.asarray(bits, np.uint32)
    depth = np.asarray(depth, np.int64)
    pw = bits.shape[-1]
    out = bits.copy()
    for w in range(pw):
        rem = np.clip(depth - 32 * w, 0, 32)
        mask = np.where(
            rem == 0, 0,
            (0xFFFFFFFF << (32 - np.minimum(rem, 32))) & 0xFFFFFFFF
        ).astype(np.uint32)
        out[..., w] &= mask
    return out


def np_get_bit(bits: np.ndarray, pos) -> np.ndarray:
    pos = np.asarray(pos, np.int64)
    w = pos // 32
    return (np.take_along_axis(
        np.asarray(bits, np.uint32), w[..., None], axis=-1)[..., 0]
        >> (31 - pos % 32).astype(np.uint32)) & 1


def np_flip_bit(bits: np.ndarray, pos) -> np.ndarray:
    """Rows with bit ``pos`` flipped (sibling derivation)."""
    bits = np.asarray(bits, np.uint32).copy()
    pos = np.asarray(pos, np.int64)
    w = pos // 32
    m = (np.uint32(1) << (31 - pos % 32).astype(np.uint32))
    np.put_along_axis(
        bits, w[..., None],
        np.take_along_axis(bits, w[..., None], axis=-1) ^ m[..., None],
        axis=-1)
    return bits


def np_bits_key(bits: np.ndarray, depth: int) -> bytes:
    """Canonical hashable id of a trie node: its masked prefix bytes."""
    masked = np_mask_bits(bits, depth)
    return bytes(masked.astype(">u4").tobytes())


def fields_to_arrays(spec: IndexSpec, keys: List[Dict[str, bytes]]
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Host key dicts → the ``(fbytes, flens)`` arrays
    :func:`_linearize_batch` consumes.  Validates like
    ``Pht.valid_key``."""
    fl = spec.field_len
    b = len(keys)
    fbytes = np.zeros((b, spec.n_fields, fl), np.uint32)
    flens = np.zeros((b, spec.n_fields), np.int32)
    names = [n for n, _ in spec.fields]
    caps = {n: c for n, c in spec.fields}
    for i, k in enumerate(keys):
        if set(k) != set(names):
            raise ValueError("key does not match the index key spec")
        for f, n in enumerate(names):
            data = k[n]
            if len(data) > caps[n]:
                raise ValueError(f"field {n!r} longer than spec")
            fbytes[i, f, :len(data)] = np.frombuffer(data, np.uint8)
            flens[i, f] = len(data)
    return fbytes, flens


def _pow2_width(m: int, floor: int = 16) -> int:
    """Pad batches to a power of two ≥ ``floor`` (the shared
    :func:`~opendht_tpu.models.storage.pow2_width` rule): bounds the
    jit specializations of the probe/put programs to ~log₂ of the
    largest batch (and keeps every width mesh-divisible for the
    sharded twin)."""
    return pow2_width(m, floor)


# ---------------------------------------------------------------------------
# the device engine
# ---------------------------------------------------------------------------

class DeviceIndex:
    """Batched PHT engine over a device :class:`SwarmStore`.

    One instance owns a live store reference (``self.store`` is
    replaced by each mutating op — the announce path returns a new
    pytree) plus host-side trie bookkeeping (max known depth, walk
    statistics).  All heavy work — linearize, SHA-1 node keys, canary
    probes, entry gets/puts — runs as batched device programs through
    the SAME ``lookup``/``announce``/``get_values`` entry points every
    other workload uses, so the compacted burst engine, donation and
    the flight recorder apply unchanged.
    """

    def __init__(self, swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                 scfg: StoreConfig, spec: IndexSpec, seed: int = 0):
        if scfg.payload_words != spec.payload_words:
            raise ValueError(
                f"index store needs payload_words == "
                f"{spec.payload_words} (entry wire format), got "
                f"{scfg.payload_words}")
        if scfg.slots < MAX_NODE_ENTRY_COUNT + 1:
            # One trie node's canary + 16 entry slots share the same
            # 128-bit key prefix, so ALL of them land on the same
            # quorum of closest nodes — a node whose ring is smaller
            # than a full trie node evicts the canary mid-insert and
            # silently corrupts the index.
            raise ValueError(
                f"index store needs slots ≥ {MAX_NODE_ENTRY_COUNT + 1} "
                f"(one full trie node — canary + "
                f"{MAX_NODE_ENTRY_COUNT} entries — lands on one "
                f"node's ring), got {scfg.slots}")
        self.swarm, self.cfg = swarm, cfg
        self.store, self.scfg = store, scfg
        self.spec = spec
        self._rng = jax.random.PRNGKey(seed)
        self._op = 0
        self._max_depth = 0          # deepest canary ever written
        self.stats = {
            "probe_batches": 0, "probe_keys": 0, "walk_rounds_max": 0,
            "splits": 0, "split_levels": 0, "entries_inserted": 0,
            "dup_refreshed": 0, "overfull_drops": 0, "canary_puts": 0,
            "entry_puts": 0, "insert_passes": 0,
        }

    # -- engine ops (the sharded twin overrides these two) -------------

    def _next_key(self) -> jax.Array:
        self._op += 1
        return jax.random.fold_in(self._rng, self._op)

    def _get_raw(self, keys: jax.Array):
        res = get_values(self.swarm, self.cfg, self.store, self.scfg,
                         keys, self._next_key())
        return res.hit, res.val, res.payload

    def _put_raw(self, keys, vals, seqs, payloads) -> None:
        self.store, _rep = announce(
            self.swarm, self.cfg, self.store, self.scfg, keys, vals,
            seqs, 0, self._next_key(), payloads=payloads)

    # -- padded batch wrappers ----------------------------------------

    def _get(self, keys_np: np.ndarray):
        """Batched store get of ``[M, 5]`` keys → host ``(hit, val,
        payload)``.  Pads to a power-of-two width (repeating row 0 —
        duplicate gets are idempotent) so probe programs compile once
        per width rung."""
        m = keys_np.shape[0]
        w = _pow2_width(m)
        if w > m:
            keys_np = np.concatenate(
                [keys_np, np.broadcast_to(keys_np[:1], (w - m, 5))])
        hit, val, pl = self._get_raw(jnp.asarray(keys_np))
        hit, val, pl = jax.device_get((hit, val, pl))
        self.stats["probe_batches"] += 1
        self.stats["probe_keys"] += int(m)
        return hit[:m], val[:m], pl[:m]

    def _put(self, keys_np, vals_np, payloads_np) -> None:
        """Batched announce (seq 1 — index values are immutable;
        re-puts are same-value refreshes under the edit policy).  Pads
        by repeating row 0: the insert path's in-batch dedup keeps
        one copy."""
        m = keys_np.shape[0]
        if m == 0:
            return
        w = _pow2_width(m)
        if w > m:
            keys_np = np.concatenate(
                [keys_np, np.broadcast_to(keys_np[:1], (w - m, 5))])
            vals_np = np.concatenate(
                [vals_np, np.broadcast_to(vals_np[:1], (w - m,))])
            payloads_np = np.concatenate(
                [payloads_np,
                 np.broadcast_to(payloads_np[:1],
                                 (w - m, payloads_np.shape[1]))])
        self._put_raw(jnp.asarray(keys_np), jnp.asarray(vals_np),
                      jnp.ones((w,), _U32), jnp.asarray(payloads_np))

    # -- key encoding --------------------------------------------------

    def linearize(self, keys: List[Dict[str, bytes]]) -> np.ndarray:
        """Host key dicts → ``[B, PW]`` linearized prefix words."""
        fbytes, flens = fields_to_arrays(self.spec, keys)
        return np.asarray(_linearize_batch(
            self.spec, jnp.asarray(fbytes), jnp.asarray(flens)))

    def _node_hash(self, bits_np: np.ndarray,
                   depth_np: np.ndarray) -> np.ndarray:
        return np.asarray(_trie_node_hash(
            self.spec, jnp.asarray(np.asarray(bits_np, np.uint32)),
            jnp.asarray(np.asarray(depth_np, np.int32))))

    # -- batched binary search on prefix length ------------------------

    def leaf_search(self, bits_np: np.ndarray) -> np.ndarray:
        """Walk ``[B]`` keys to their leaf depths — the batched twin of
        ``Pht._lookup_step``'s binary search.  Each refinement round is
        ONE canary get micro-batch (2 probes per active key) through
        the burst engine; the host ladder compacts converged keys out
        of later rounds.  Returns leaf depths ``[B] int``.

        The search interval starts at ``[0, _max_depth]`` — the device
        twin of the host's client-side Cache HINT.  The canary
        invariant (marked iff depth ≤ leaf depth on the path) makes
        every probe sound, so the hint can only fail one way: a probe
        proves the leaf sits BELOW the hinted ceiling (``go_dn`` past
        ``hi``), and the row restarts once over the full interval —
        a reader over a store someone else built (the conformance
        test's whole point) self-corrects instead of mis-resolving.
        """
        s = self.spec.prefix_bits
        b = bits_np.shape[0]
        lo = np.zeros(b, np.int64)
        hi = np.full(b, min(s - 1, self._max_depth), np.int64)
        done = np.zeros(b, bool)
        leaf = np.zeros(b, np.int64)
        rounds = 0
        while not done.all() and rounds <= self.spec.probe_round_bound:
            act = np.nonzero(~done)[0]
            amid = (lo[act] + hi[act]) // 2
            amid2 = np.minimum(amid + 1, s - 1)
            keys1 = self._node_hash(bits_np[act], amid)
            keys2 = self._node_hash(bits_np[act], amid2)
            hit, val, _ = self._get(
                np.concatenate([keys1, keys2]).astype(np.uint32))
            is_pht = hit & (val == CANARY_TOKEN)
            first = is_pht[:act.size]
            second = is_pht[act.size:] & (amid < s - 1)
            go_up = ~first
            go_dn = first & second
            at_leaf = first & ~second
            # canary(mid) ∧ ¬canary(mid+1) ⇒ mid IS the leaf.
            leaf[act[at_leaf]] = amid[at_leaf]
            done[act[at_leaf]] = True
            # ¬canary(mid) ⇒ leaf < mid; an empty interval here means
            # no canary at depth 0 at all — the empty-trie root leaf.
            hi[act[go_up]] = amid[go_up] - 1
            fin_up = go_up & (amid - 1 < lo[act])
            leaf[act[fin_up]] = 0
            done[act[fin_up]] = True
            # canary(mid+1) ⇒ leaf > mid; an empty interval here means
            # the hint ceiling was too low — restart over [mid+1, s-1].
            lo[act[go_dn]] = amid[go_dn] + 1
            retry = go_dn & (amid + 1 > hi[act])
            hi[act[retry]] = s - 1
            rounds += 1
        self.stats["walk_rounds_max"] = max(
            self.stats["walk_rounds_max"], rounds)
        if not done.all():
            raise RuntimeError(
                "leaf walk exceeded the binary-search round bound — "
                "the canary structure is inconsistent")
        return leaf

    def read_node_entries(self, bits_np: np.ndarray,
                          depth_np: np.ndarray):
        """Entry sets of ``[A]`` trie nodes: one get micro-batch over
        all 16 slot keys per node.  Returns ``(tkeys [A,5], valid
        [A,16], ehash [A,16,5], evid [A,16], ebits [A,16,PW])``."""
        a = bits_np.shape[0]
        pw = self.spec.prefix_words
        tkeys = self._node_hash(bits_np, depth_np)
        d = np.arange(1, MAX_NODE_ENTRY_COUNT + 1, dtype=np.uint32)
        skeys = slot_keys(tkeys[:, None, :], d[None, :])   # [A,16,5]
        hit, _val, pl = self._get(skeys.reshape(-1, 5))
        valid = hit.reshape(a, MAX_NODE_ENTRY_COUNT)
        pl = pl.reshape(a, MAX_NODE_ENTRY_COUNT, -1)
        ehash = pl[:, :, 0:5].astype(np.uint32)
        evid = pl[:, :, 5].astype(np.uint32)
        ebits = pl[:, :, 7:7 + pw].astype(np.uint32)
        return tkeys, valid, ehash, evid, ebits

    # -- insert ---------------------------------------------------------

    def insert_batch(self, keys: List[Dict[str, bytes]],
                     ehash: np.ndarray, evid: np.ndarray) -> dict:
        """Insert ``B`` (key → (hash, vid)) index entries.

        Batch processing is SEQUENTIALLY EQUIVALENT to the host's
        one-at-a-time inserts: per pass, rows arriving at one leaf are
        ranked by worklist order (free slots go to the earliest rows,
        like sequential arrivals), only the earliest row at a full
        leaf performs that leaf's split (later rows requeue and see
        the post-split trie), and a split requeues the old leaf's
        entries BEFORE the splitting row — the host's listener-order
        migration.  Passes repeat until the worklist drains (bounded
        by the trie depth).
        """
        bits = self.linearize(keys).astype(np.uint32)
        ehash = np.asarray(ehash, np.uint32).reshape(-1, 5)
        evid = np.asarray(evid, np.uint32).reshape(-1)
        pw = self.spec.prefix_words
        s = self.spec.prefix_bits

        # Growable work store (migrated entries append).
        all_bits = list(bits)
        all_ehash = list(ehash)
        all_evid = list(evid)
        work = list(range(len(all_bits)))
        passes = 0
        max_passes = s + 4
        while work and passes < max_passes:
            passes += 1
            act = np.asarray(work, np.int64)
            abits = np.stack([all_bits[i] for i in work])
            aehash = np.stack([all_ehash[i] for i in work])
            aevid = np.asarray([all_evid[i] for i in work], np.uint32)
            depth = self.leaf_search(abits)
            tkeys, valid, n_eh, n_ev, n_eb = self.read_node_entries(
                abits, depth)

            gks: List[bytes] = []
            groups: Dict[bytes, List[int]] = {}
            for j in range(len(work)):
                gk = np_bits_key(abits[j], int(depth[j])) \
                    + int(depth[j]).to_bytes(2, "big")
                gks.append(gk)
                groups.setdefault(gk, []).append(j)

            next_work: List[int] = []
            canary_jobs: List[Tuple[np.ndarray, int]] = []
            put_keys: List[np.ndarray] = []
            put_vals: List[int] = []
            put_ehash: List[np.ndarray] = []
            put_evid: List[int] = []
            put_bits: List[np.ndarray] = []
            split_leaves: set = set()
            # (ehash, vid) pairs an EARLIER row of this same pass is
            # already putting at each leaf — the store-side dup check
            # below cannot see them yet.
            pass_pairs: Dict[bytes, set] = {}

            for j in range(len(work)):
                gk = gks[j]
                rows = groups[gk]
                rank = rows.index(j)
                d_j = int(depth[j])
                # Duplicate (same hash+vid already at the leaf, or put
                # there by an earlier row of this pass) → the host's
                # same-value refresh; the set is unchanged.
                pair = (aehash[j].tobytes(), int(aevid[j]))
                dup = (valid[j] & (n_ev[j] == aevid[j])
                       & (n_eh[j] == aehash[j][None, :]).all(axis=1))
                if dup.any() or pair in pass_pairs.get(gk, ()):
                    self.stats["dup_refreshed"] += 1
                    continue
                free = np.nonzero(~valid[j])[0]
                occ = MAX_NODE_ENTRY_COUNT - free.size
                if rank < free.size:
                    slot_d = int(free[rank]) + 1
                    pass_pairs.setdefault(gk, set()).add(pair)
                    put_keys.append(slot_keys(tkeys[j], slot_d))
                    put_vals.append(int(entry_tokens(
                        aehash[j][0], aevid[j])))
                    put_ehash.append(aehash[j])
                    put_evid.append(int(aevid[j]))
                    put_bits.append(abits[j])
                    # Canary refresh at the node (+ sibling beyond the
                    # root) — the deterministic part of updateCanary.
                    canary_jobs.append((abits[j], d_j))
                    if d_j > 0:
                        canary_jobs.append(
                            (np_flip_bit(abits[j], d_j - 1), d_j))
                    self.stats["entries_inserted"] += 1
                    continue
                if occ < MAX_NODE_ENTRY_COUNT:
                    # Free slots exhausted by earlier batch rows this
                    # pass — requeue; the next pass sees the true
                    # occupancy (sequential arrival semantics).
                    next_work.append(work[j])
                    continue
                # Full leaf: the earliest row splits, the rest requeue.
                if gk in split_leaves:
                    next_work.append(work[j])
                    continue
                split_leaves.add(gk)
                # Divergence point over the leaf's entries vs this key
                # (Pht._found_split_location).
                loc = s - 1
                for i in range(s - 1):
                    eb = np_get_bit(n_eb[j], np.full(
                        MAX_NODE_ENTRY_COUNT, i))
                    kb = int(np_get_bit(abits[j][None, :],
                                        np.asarray([i]))[0])
                    if (eb[valid[j]] != kb).any():
                        loc = i + 1
                        break
                if loc <= d_j:
                    # No divergence below the leaf (> 16 identical
                    # keys): structurally unsplittable — count and
                    # drop rather than corrupt a slot.
                    self.stats["overfull_drops"] += 1
                    continue
                # Canary chain old-leaf → divergence point, siblings
                # included per level (Pht._split + updateCanary).
                for i in range(max(d_j, 1), loc + 1):
                    canary_jobs.append((abits[j], i))
                    canary_jobs.append((np_flip_bit(abits[j], i - 1), i))
                self.stats["splits"] += 1
                self.stats["split_levels"] += loc - d_j
                self._max_depth = max(self._max_depth, loc)
                # Requeue: the old leaf's entries first (listener-order
                # migration), then the splitting row.
                for sl in np.nonzero(valid[j])[0]:
                    all_bits.append(n_eb[j][sl].astype(np.uint32))
                    all_ehash.append(n_eh[j][sl].astype(np.uint32))
                    all_evid.append(np.uint32(n_ev[j][sl]))
                    next_work.append(len(all_bits) - 1)
                next_work.append(work[j])

            # One canary batch + one entry batch per pass (canaries
            # first — the host writes the chain before the value put).
            if canary_jobs:
                cb = np.stack([b for b, _ in canary_jobs])
                cd = np.asarray([d for _, d in canary_jobs], np.int32)
                ckeys = self._node_hash(cb, cd)
                self._put(ckeys.astype(np.uint32),
                          np.full(len(canary_jobs), CANARY_TOKEN,
                                  np.uint32),
                          np.zeros((len(canary_jobs),
                                    self.spec.payload_words),
                                   np.uint32))
                self.stats["canary_puts"] += len(canary_jobs)
            if put_keys:
                pk = np.stack(put_keys).astype(np.uint32)
                pv = np.asarray(put_vals, np.uint32)
                pl = np.asarray(_pack_entry_payloads(
                    self.spec,
                    jnp.asarray(np.stack(put_ehash).astype(np.uint32)),
                    jnp.asarray(np.asarray(put_evid, np.uint32)),
                    jnp.asarray(np.stack(put_bits).astype(np.uint32))))
                self._put(pk, pv, pl)
                self.stats["entry_puts"] += len(put_keys)
            work = next_work
        self.stats["insert_passes"] += passes
        if work:
            self.stats["overfull_drops"] += len(work)
        return dict(self.stats)

    # -- reads ----------------------------------------------------------

    def lookup_batch(self, keys: List[Dict[str, bytes]]):
        """Exact lookup of ``B`` keys: walk to leaves, probe slots,
        keep entries whose FULL linearized prefix equals the queried
        key (``Pht.lookup`` exact semantics).  Returns
        ``(leaf_depths [B], entries: list of [(ehash_bytes, vid)])``."""
        bits = self.linearize(keys).astype(np.uint32)
        depth = self.leaf_search(bits)
        _tk, valid, eh, ev, eb = self.read_node_entries(bits, depth)
        out = []
        for j in range(bits.shape[0]):
            match = valid[j] & (eb[j] == bits[j][None, :]).all(axis=1)
            out.append([
                (eh[j][sl].astype(">u4").tobytes(), int(ev[j][sl]))
                for sl in np.nonzero(match)[0]])
        return depth, out

    def range_query(self, lo_bits: np.ndarray, hi_bits: np.ndarray,
                    max_leaves: int = 65536):
        """Exact range scan: for each of ``R`` inclusive ranges over
        linearized key space, enumerate the contiguous leaf span
        (z-curve order = prefix numeric order) and return the entries
        whose full key falls inside.  Returns ``(entries: list of R
        lists of (ehash_bytes, vid), leaves_touched [R])``."""
        lo_bits = np.asarray(lo_bits, np.uint32).reshape(
            -1, self.spec.prefix_words)
        hi_bits = np.asarray(hi_bits, np.uint32).reshape(
            -1, self.spec.prefix_words)
        r = lo_bits.shape[0]
        cur = lo_bits.copy()
        active = np.ones(r, bool)
        results: List[list] = [[] for _ in range(r)]
        seen: List[set] = [set() for _ in range(r)]
        leaves = np.zeros(r, np.int64)
        steps = 0
        while active.any():
            steps += 1
            if steps > max_leaves:
                raise RuntimeError("range walk exceeded max_leaves")
            act = np.nonzero(active)[0]
            depth = self.leaf_search(cur[act])
            _tk, valid, eh, ev, eb = self.read_node_entries(
                cur[act], depth)
            leaves[act] += 1
            for k, q in enumerate(act):
                lo_t = tuple(lo_bits[q].tolist())
                hi_t = tuple(hi_bits[q].tolist())
                for sl in np.nonzero(valid[k])[0]:
                    full = tuple(eb[k][sl].tolist())
                    if lo_t <= full <= hi_t:
                        ent = (eh[k][sl].astype(">u4").tobytes(),
                               int(ev[k][sl]))
                        if ent not in seen[q]:
                            seen[q].add(ent)
                            results[q].append(ent)
                # Advance past this leaf's key-space: its upper bound
                # is the masked prefix with every sub-prefix bit set.
                d = int(depth[k])
                upper = np_mask_bits(cur[q], d) | (
                    ~np_mask_bits(np.full_like(cur[q], 0xFFFFFFFF), d)
                    & np.uint32(0xFFFFFFFF))
                # Trailing pad bits past prefix_bits stay zero in keys;
                # clamp the successor into key space via the full mask.
                upper = np_mask_bits(upper, self.spec.prefix_bits)
                nxt, carry = _np_increment(upper, self.spec.prefix_bits)
                if carry or tuple(nxt.tolist()) > tuple(
                        hi_bits[q].tolist()):
                    active[q] = False
                else:
                    cur[q] = nxt
        return results, leaves

    # -- trie enumeration (conformance / artifact view) -----------------

    def trie_snapshot(self):
        """BFS the canary structure from the root and return
        ``(leaves, interior)`` where ``leaves`` maps ``(depth,
        prefix_bytes)`` → frozenset of ``(ehash_bytes, vid)`` and
        ``interior`` is the set of non-leaf marked nodes — the logical
        trie as READ FROM THE STORE, which is what host↔device
        conformance compares."""
        zero = np.zeros(self.spec.prefix_words, np.uint32)
        hit, val, _ = self._get(self._node_hash(
            zero[None, :], np.asarray([0], np.int32)).astype(np.uint32))
        leaves: Dict[Tuple[int, bytes], FrozenSet] = {}
        interior = set()
        if not (hit[0] and val[0] == CANARY_TOKEN):
            return leaves, interior
        frontier = [(0, zero)]
        while frontier:
            fb = np.stack([b for _, b in frontier])
            fd = np.asarray([d for d, _ in frontier], np.int64)
            # Probe both children of every frontier node at once.
            kids_b, kids_d, owner = [], [], []
            for i, (d, b) in enumerate(frontier):
                if d < self.spec.prefix_bits:
                    for bitv in (0, 1):
                        cb = np_mask_bits(b, d)
                        if bitv:
                            cb = np_flip_bit(cb[None, :],
                                             np.asarray([d]))[0]
                        kids_b.append(cb)
                        kids_d.append(d + 1)
                        owner.append(i)
            marked = np.zeros(len(kids_b), bool)
            if kids_b:
                kk = self._node_hash(np.stack(kids_b),
                                     np.asarray(kids_d, np.int32))
                hit, val, _ = self._get(kk.astype(np.uint32))
                marked = hit & (val == CANARY_TOKEN)
            has_kid = np.zeros(len(frontier), bool)
            nxt = []
            for j in np.nonzero(marked)[0]:
                has_kid[owner[j]] = True
                nxt.append((kids_d[j], kids_b[j]))
            leaf_rows = [i for i in range(len(frontier))
                         if not has_kid[i]]
            if leaf_rows:
                lb = fb[leaf_rows]
                ld = fd[leaf_rows]
                _tk, valid, eh, ev, _eb = self.read_node_entries(lb, ld)
                for k, i in enumerate(leaf_rows):
                    ents = frozenset(
                        (eh[k][sl].astype(">u4").tobytes(),
                         int(ev[k][sl]))
                        for sl in np.nonzero(valid[k])[0])
                    leaves[(int(fd[i]),
                            np_bits_key(fb[i], int(fd[i])))] = ents
            for i in range(len(frontier)):
                if has_kid[i]:
                    interior.add((int(fd[i]),
                                  np_bits_key(fb[i], int(fd[i]))))
            frontier = nxt
        return leaves, interior


def _np_increment(words: np.ndarray, nbits: int):
    """Big-integer successor of an ``nbits``-wide MSB-aligned word
    vector (+1 at bit position nbits-1).  Returns ``(succ, carry)``."""
    pw = words.shape[-1]
    out = words.astype(np.uint64).copy()
    pos = nbits - 1
    w = pos // 32
    inc = np.uint64(1) << np.uint64(31 - pos % 32)
    while w >= 0:
        out[w] += inc
        if out[w] <= 0xFFFFFFFF:
            return out.astype(np.uint32), False
        out[w] &= 0xFFFFFFFF
        inc = np.uint64(1)
        w -= 1
    return out.astype(np.uint32), True


# ---------------------------------------------------------------------------
# host DHT facade over the device store (Pht ↔ SwarmStore bridge)
# ---------------------------------------------------------------------------

class StoreDht:
    """The host DHT surface (get/put/listen) the UNMODIFIED host
    :class:`~opendht_tpu.indexation.pht.Pht` runs against, backed by
    the device :class:`SwarmStore` and speaking the exact slot-key
    encoding of :class:`DeviceIndex` — so a host-built and a
    device-built index are views of the same stored trie.

    Synchronous by construction: every callback fires before the call
    returns, and listens deliver current values at registration plus
    on every subsequent matching put (the adapter twin of the host
    cluster's listen push) — which makes the host's listener-triggered
    post-split re-inserts run eagerly, matching the device engine's
    bounded re-insert pass.
    """

    def __init__(self, swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                 scfg: StoreConfig, spec: IndexSpec, seed: int = 1):
        self._ix = DeviceIndex(swarm, cfg, store, scfg, spec, seed=seed)
        self.spec = spec
        self._listeners: Dict[bytes, list] = {}

    @classmethod
    def over(cls, ix: "DeviceIndex") -> "StoreDht":
        """An adapter view over an EXISTING engine (shares the live
        engine and hence its store reference): host Pht reads see
        device writes and vice versa — the cross-read direction of the
        conformance contract."""
        self = cls.__new__(cls)
        self._ix = ix
        self.spec = ix.spec
        self._listeners = {}
        return self

    @property
    def store(self) -> SwarmStore:
        return self._ix.store

    @staticmethod
    def _limbs(h) -> np.ndarray:
        return np.frombuffer(bytes(h), dtype=">u4").astype(np.uint32)

    def _node_values(self, h) -> list:
        """All index values stored under trie-node hash ``h``: the
        canary (slot 0) plus every entry slot, reconstructed as host
        :class:`Value` objects."""
        from ..core.value import Value
        from ..indexation.pht import IndexEntry, Prefix
        from ..utils.infohash import InfoHash

        base = self._limbs(h)
        keys = slot_keys(
            np.broadcast_to(base, (MAX_NODE_ENTRY_COUNT + 1, 5)).copy(),
            np.arange(MAX_NODE_ENTRY_COUNT + 1, dtype=np.uint32))
        hit, val, pl = self._ix._get(keys.astype(np.uint32))
        vals = []
        if hit[0] and val[0] == CANARY_TOKEN:
            vals.append(Value(b"", 0, user_type=self.spec.canary_type))
        pw = self.spec.prefix_words
        for sl in range(1, MAX_NODE_ENTRY_COUNT + 1):
            if not hit[sl]:
                continue
            ehash = pl[sl][0:5].astype(">u4").tobytes()
            vid = int(pl[sl][5])
            size = int(pl[sl][6])
            content = pl[sl][7:7 + pw].astype(">u4").tobytes()
            entry = IndexEntry(
                Prefix(content[:self.spec.prefix_bytes], size),
                (InfoHash(ehash), vid), self.spec.value_type)
            vals.append(entry.pack_value())
        return vals

    # -- the Pht-facing surface -----------------------------------------

    def get(self, h, get_cb, done_cb=None, f=None) -> None:
        vals = self._node_values(h)
        if f is not None:
            vals = [v for v in vals if f(v)]
        if vals and get_cb is not None:
            get_cb(vals)
        if done_cb:
            done_cb(True, None)

    def put(self, h, value, done_cb=None) -> None:
        from ..indexation.pht import IndexEntry

        base = self._limbs(h)
        hb = bytes(h)
        if value.user_type == self.spec.canary_type:
            self._ix._put(
                base[None, :],
                np.asarray([CANARY_TOKEN], np.uint32),
                np.zeros((1, self.spec.payload_words), np.uint32))
        else:
            entry = IndexEntry.unpack_value(value)
            ehash = self._limbs(entry.value[0])
            vid = np.uint32(entry.value[1])
            # Slot choice mirrors the device engine: an existing same
            # (hash, vid) slot refreshes; otherwise the first free.
            keys = slot_keys(
                np.broadcast_to(base, (MAX_NODE_ENTRY_COUNT, 5)).copy(),
                np.arange(1, MAX_NODE_ENTRY_COUNT + 1, dtype=np.uint32))
            hit, _val, pl = self._ix._get(keys.astype(np.uint32))
            slot = None
            for sl in range(MAX_NODE_ENTRY_COUNT):
                if hit[sl] and int(pl[sl][5]) == int(vid) \
                        and (pl[sl][0:5] == ehash).all():
                    slot = sl
                    break
            if slot is None:
                free = np.nonzero(~hit)[0]
                if free.size == 0:
                    if done_cb:
                        done_cb(False, None)
                    return
                slot = int(free[0])
            content = entry.prefix.content
            content = content + bytes(self.spec.prefix_words * 4
                                      - len(content))
            bits = np.frombuffer(content, dtype=">u4").astype(np.uint32)
            payload = np.concatenate([
                ehash, np.asarray([vid, entry.prefix.size], np.uint32),
                bits]).astype(np.uint32)[None, :]
            self._ix._put(keys[slot][None, :].astype(np.uint32),
                          entry_tokens(ehash[0], vid)[None],
                          payload)
        if done_cb:
            done_cb(True, None)
        self._fire_listeners(hb)

    def listen(self, h, cb, f=None) -> int:
        hb = bytes(h)
        self._listeners.setdefault(hb, []).append((cb, f))
        # The reference's listen pushes current values at registration.
        self._deliver(hb, cb, f)
        return len(self._listeners[hb])

    # -- listener plumbing ----------------------------------------------

    def _deliver(self, hb: bytes, cb, f) -> None:
        from ..utils.infohash import InfoHash
        vals = self._node_values(InfoHash(hb))
        if f is not None:
            vals = [v for v in vals if f(v)]
        if vals:
            cb(vals)

    def _fire_listeners(self, hb: bytes) -> None:
        for cb, f in list(self._listeners.get(hb, ())):
            self._deliver(hb, cb, f)


# ---------------------------------------------------------------------------
# pure-python oracle (sequential reference replay)
# ---------------------------------------------------------------------------

class PhtOracle:
    """Sequential in-memory replay of the trie rules (leaf walk, ≤16
    capacity, divergence-point split, eager migration) — the host-Pht
    oracle the bench holds range-scan recall against, and the third
    view of the conformance test.  State is exact bit-level prefixes;
    no DHT, no store."""

    def __init__(self, spec: IndexSpec):
        self.spec = spec
        self.canaries: set = set()
        self.nodes: Dict[Tuple[int, bytes], list] = {}

    def _leaf_of(self, bits: np.ndarray) -> int:
        if (0, np_bits_key(bits, 0)) not in self._marked:
            return 0
        d = 0
        while d < self.spec.prefix_bits and \
                (d + 1, np_bits_key(bits, d + 1)) in self._marked:
            d += 1
        return d

    @property
    def _marked(self):
        return self.canaries

    def insert(self, bits: np.ndarray, ehash_b: bytes, vid: int,
               _split_ok: bool = True) -> None:
        bits = np.asarray(bits, np.uint32)
        s = self.spec.prefix_bits
        self.canaries.add((0, np_bits_key(bits, 0)))
        d = self._leaf_of(bits)
        node = (d, np_bits_key(bits, d))
        ents = self.nodes.setdefault(node, [])
        ent = (ehash_b, vid, tuple(bits.tolist()))
        if any(e[0] == ehash_b and e[1] == vid for e in ents):
            return
        if len(ents) < MAX_NODE_ENTRY_COUNT or not _split_ok:
            ents.append(ent)
            return
        loc = s - 1
        for i in range(s - 1):
            kb = int(np_get_bit(bits[None, :], np.asarray([i]))[0])
            if any(int(np_get_bit(
                    np.asarray(e[2], np.uint32)[None, :],
                    np.asarray([i]))[0]) != kb for e in ents):
                loc = i + 1
                break
        if loc <= d:
            return                        # unsplittable (> 16 dups)
        for i in range(max(d, 1), loc + 1):
            self.canaries.add((i, np_bits_key(bits, i)))
            sib = np_flip_bit(bits[None, :], np.asarray([i - 1]))[0]
            self.canaries.add((i, np_bits_key(sib, i)))
        for e in list(ents):              # listener-order migration
            self.insert(np.asarray(e[2], np.uint32), e[0], e[1],
                        _split_ok=False)
        self.insert(bits, ehash_b, vid)

    def leaves(self) -> Dict[Tuple[int, bytes], FrozenSet]:
        out = {}
        for (d, kb) in self.canaries:
            bits = np.frombuffer(kb, dtype=">u4").astype(np.uint32)
            kid0 = (d + 1, np_bits_key(bits, d + 1))
            kid1 = (d + 1, np_bits_key(
                np_flip_bit(bits[None, :], np.asarray([d]))[0], d + 1))
            if d < self.spec.prefix_bits and (
                    kid0 in self.canaries or kid1 in self.canaries):
                continue
            ents = self.nodes.get((d, kb), [])
            out[(d, kb)] = frozenset((e[0], e[1]) for e in ents)
        return out

    def entries_in_range(self, lo_bits, hi_bits) -> set:
        lo = tuple(np.asarray(lo_bits, np.uint32).tolist())
        hi = tuple(np.asarray(hi_bits, np.uint32).tolist())
        out = set()
        leaf_set = self.leaves()
        for node, ents in self.nodes.items():
            if node not in leaf_set:
                continue
            for e in ents:
                if lo <= e[2] <= hi:
                    out.add((e[0], e[1]))
        return out
