"""Variable-size values on the device store: multi-slot chunking.

The reference stores variable-size values up to 64 KB
(/root/reference/include/opendht/value.h:73) and ships big ones as
MTU-sized parts (``sendValueParts``,
/root/reference/src/network_engine.cpp:830-882).  The device store's
slots are fixed-width (``StoreConfig.payload_words`` = W u32 words per
slot); this module stores a value of any byte length ≤ ``parts·4·W``
across ``ceil(words/W)`` slots of the SAME replica:

* **part keys** — part ``j`` stores under ``key XOR (j in limb 4)``.
  Routing uses the high bits (limb 0), so every part has the SAME
  closest-node set: one lookup per value, parts co-resident on each
  replica, like the reference's one-Storage-entry-per-value;
* **length** — part 0's ``size`` field records the value's true BYTE
  length (the per-value size the budget already accounts); parts ≥ 1
  carry nominal size 1.  A reader recovers the part count exactly from
  part 0, so there is no ambiguity at width-multiple lengths;
* **consistency** — parts are only accepted by the per-slot edit
  policy (monotone seq), and a read requires every needed part to
  carry the winning part-0 ``(val, seq)``: a torn multi-part update
  (some parts dropped under capacity) reads as MISSING, never as
  garbled bytes — fail-safe, healed by the next republish sweep like
  any dropped announce.

This removes the "one fixed payload width per store" fidelity
asterisk: per-value lengths are real, bytes are real, reassembly is
exact.

Integrity plane (``scfg.verify``) — hash-list content addressing.
The flat store's plane recomputes ``SHA-1(payload) == key`` per slot,
which cannot hold for part keys (``pk_j = key XOR j`` is derived from
the base key, not from part j's bytes).  Chunked values instead use
the reference's hash-list shape: the base key is the digest of the
PER-PART digests plus the true length,

    ``key = SHA-1( SHA-1(part_0) ‖ … ‖ SHA-1(part_{parts-1}) ‖ len )``

over the CANONICAL payload form (:func:`mask_chunk_payloads`: inactive
parts and words past the value end zeroed).  Writers mint keys with
:func:`chunked_content_ids` (host twin
:func:`chunked_content_ids_host`); part inserts and probes run with
the per-slot digest check OFF (``scfg._replace(verify=False)`` — the
exact unverified programs), and the defense moves to the READ MERGE:
:func:`_chunked_root_ok` recomputes the root in-jit from the
reassembled parts, so one forged or corrupted part flips the root and
the value reads as MISSING — same fail-safe as a torn write, never a
garbled byte.  The threat model is thus availability-loss only: an
attacker who can announce a higher-seq part can suppress a value (as
any torn write does) but can never make a reader ACCEPT bytes that do
not hash to the key, and the length under the root stops a forged
part 0 from lying about the value size.
"""

from __future__ import annotations

import hashlib
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.sha1 import sha1_words
from .storage import (
    AnnounceReport,
    StoreConfig,
    SwarmStore,
    _announce_insert,
    _get_probe,
    _listen_insert,
    ack_listeners,
    cancel_listen,
    dev_u32,
)
from .swarm import Swarm, SwarmConfig, lookup


class ChunkedGetResult(NamedTuple):
    hit: jax.Array      # [P] bool — value completely reassembled
    val: jax.Array      # [P] uint32 — value token
    seq: jax.Array      # [P] uint32
    length: jax.Array   # [P] uint32 — true byte length
    payload: jax.Array  # [P, parts*W] uint32 — reassembled words
    hops: jax.Array     # [P]
    done: jax.Array     # [P]


class ChunkedCollectResult(NamedTuple):
    """One collected listener delivery — the value-LIST push of the
    reference's ``tellListener`` reassembled from per-part slots."""
    ready: jax.Array    # [P] bool — a complete value was delivered
    val: jax.Array      # [P] uint32
    seq: jax.Array      # [P] uint32
    length: jax.Array   # [P] uint32 — true byte length
    payload: jax.Array  # [P, parts*W] uint32


def mask_chunk_payloads(payloads: jax.Array, lengths: jax.Array
                        ) -> Tuple[jax.Array, jax.Array]:
    """Canonical chunk form: clamp ``lengths [P]`` to what
    ``payloads [P, parts, W]`` can represent and zero every word at or
    past each value's end (inactive parts zero entirely).  The root id
    is defined over THIS form, so storage padding past the value end
    can never affect a digest."""
    p, parts, w = payloads.shape
    lengths = jnp.minimum(jnp.asarray(lengths).astype(jnp.uint32),
                          jnp.uint32(parts * w * 4))
    words = -(-lengths.astype(jnp.int32) // 4)               # [P]
    idx = jnp.arange(parts * w, dtype=jnp.int32).reshape(parts, w)
    masked = jnp.where(idx[None] < words[:, None, None],
                       payloads.astype(jnp.uint32), 0)
    return masked, lengths


def _root_ids(payloads: jax.Array, lengths: jax.Array) -> jax.Array:
    """Hash-list root of chunked values (traced body shared by the
    writer-side mint and the reader-side check): per-part SHA-1 digests
    over the canonical form, then SHA-1 over the digest list plus the
    true byte length."""
    p, parts, w = payloads.shape
    masked, lengths = mask_chunk_payloads(payloads, lengths)
    digests = sha1_words(masked)                             # [P,parts,5]
    msg = jnp.concatenate(
        [digests.reshape(p, parts * 5), lengths[:, None]], axis=1)
    return sha1_words(msg)


@jax.jit
def chunked_content_ids(payloads: jax.Array,
                        lengths: jax.Array) -> jax.Array:
    """Content-addressed base keys for chunked values:
    ``key = SHA-1(SHA-1(part_0) ‖ … ‖ SHA-1(part_{parts-1}) ‖ len)``
    over ``payloads [P, parts, W]`` / ``lengths [P]`` — the chunked
    twin of :func:`opendht_tpu.models.integrity.content_ids` (hash-list
    shape, because a reader must be able to re-derive the key from the
    reassembled parts).  Returns ``[P, 5]`` uint32 digest limbs."""
    return _root_ids(payloads, lengths)


@jax.jit
def _chunked_root_ok(keys: jax.Array, payloads: jax.Array,
                     lengths: jax.Array) -> jax.Array:
    """Reader-side integrity check, in-jit at the get merge: does the
    reassembled value hash back to its claimed base key?  One forged or
    corrupted part flips its digest, the digest flips the root, and the
    row reads as missing — never as garbled bytes."""
    return jnp.all(_root_ids(payloads, lengths) == keys, axis=-1)


def chunked_content_ids_host(payloads, lengths) -> np.ndarray:
    """Bit-identical hashlib twin of :func:`chunked_content_ids` for
    ``[P, parts, W]`` uint32 payloads (parity pinned in tests — host
    and device views of one chunked id must be interchangeable, like
    :func:`~opendht_tpu.models.integrity.content_ids_host`)."""
    pl = np.ascontiguousarray(np.asarray(payloads, np.uint32))
    if pl.ndim == 2:
        pl = pl[None]
    p, parts, w = pl.shape
    lengths = np.minimum(
        np.asarray(lengths, np.uint32).reshape(p),
        np.uint32(parts * w * 4))
    words = -(-lengths.astype(np.int64) // 4)
    idx = np.arange(parts * w).reshape(parts, w)
    masked = np.where(idx[None] < words[:, None, None], pl,
                      0).astype(">u4")
    out = np.zeros((p, 5), np.uint32)
    for i in range(p):
        msg = b"".join(hashlib.sha1(masked[i, j].tobytes()).digest()
                       for j in range(parts))
        msg += np.array([lengths[i]], dtype=">u4").tobytes()
        d = hashlib.sha1(msg).digest()
        out[i] = np.frombuffer(d, dtype=">u4").astype(np.uint32)
    return out


def part_key(keys: jax.Array, j: int) -> jax.Array:
    """Derived storage key of part ``j``: base key with the part index
    XORed into limb 4 (the low 32 id bits) — identical routing prefix,
    distinct storage identity."""
    if j == 0:
        return keys
    tag = jnp.zeros((keys.shape[0], 5), jnp.uint32).at[:, 4].set(j)
    return keys ^ tag


def announce_chunked(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                     scfg: StoreConfig, keys: jax.Array,
                     vals: jax.Array, seqs: jax.Array, now,
                     rng: jax.Array, payloads: jax.Array,
                     lengths: jax.Array
                     ) -> Tuple[SwarmStore, AnnounceReport]:
    """Batched put of variable-size values.

    ``payloads [P, parts, W]`` (W = ``scfg.payload_words``),
    ``lengths [P]`` true byte lengths (≤ parts·4·W).  One lookup per
    value; each active part becomes a storage insert at its part key
    on the same quorum replicas.  The report's ``replicas`` counts
    replicas that accepted part 0 (the part whose size carries the
    value length).

    Zero-length values round-trip: the reference permits empty value
    data (value.h:73 caps only the maximum), so part 0 is stored for
    EVERY valid announce row — a length-0 value occupies one slot with
    recorded size 0 and reads back as a hit with empty payload, not as
    a silent un-announce (ADVICE round 5).
    """
    p, parts, w = payloads.shape
    assert w == scfg.payload_words, (w, scfg.payload_words)
    res = lookup(swarm, cfg, keys, rng)
    # Canonical form: clamp lengths to what ``payloads`` can actually
    # represent (an oversize recorded length would store unreadable-
    # forever parts — the reader rejects need_words > parts·w) and zero
    # padding past the value end, so the stored bytes ARE the form the
    # hash-list root is defined over.
    payloads, lengths = mask_chunk_payloads(payloads, lengths)
    words = -(-lengths.astype(jnp.int32) // 4)               # [P]
    # Part keys are key-derived, not content-derived, so the per-slot
    # digest check can never pass on them: parts always insert through
    # the UNVERIFIED programs and integrity moves to the read merge
    # (see module docstring) — same compiled insert either way.
    part_scfg = scfg._replace(verify=False)
    rep0, trace = None, None
    for j in range(parts):
        # Part 0 is active unconditionally (it carries the value's
        # existence and true length — including length 0).
        active = (words > j * w) | (j == 0)
        found_j = jnp.where(active[:, None], res.found, -1)
        sizes_j = (lengths.astype(jnp.uint32) if j == 0
                   else jnp.ones_like(lengths, jnp.uint32))
        store, rep, tr = _announce_insert(
            swarm.alive, cfg, store, part_scfg, found_j,
            part_key(keys, j), vals, seqs, jnp.uint32(now), sizes_j,
            None, payloads[:, j])
        trace = tr if trace is None else trace + tr
        if j == 0:
            rep0 = rep
    return store, AnnounceReport(replicas=rep0, hops=res.hops,
                                 done=res.done, trace=trace)


def get_chunked(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                scfg: StoreConfig, keys: jax.Array, rng: jax.Array,
                parts: int) -> ChunkedGetResult:
    """Batched get of variable-size values: one lookup, per-part quorum
    probes, exact reassembly.

    A value is ``hit`` iff part 0 is found and every part the recorded
    length requires is found with part-0's ``(val, seq)`` — a torn or
    partially-expired value reads as missing, never as garbled bytes.
    With ``scfg.verify`` the reassembled value must also hash back to
    its base key (:func:`_chunked_root_ok`): a forged or corrupted
    part downgrades the row to missing, same fail-safe shape.
    """
    p = keys.shape[0]
    w = scfg.payload_words
    part_scfg = scfg._replace(verify=False)   # see announce_chunked
    res = lookup(swarm, cfg, keys, rng)
    h0, val, seq, pl0, sz = _get_probe(swarm.alive, cfg, store,
                                       part_scfg, res.found, keys)
    need_words = -(-sz.astype(jnp.int32) // 4)               # [P]
    n_parts = jnp.clip(-(-need_words // max(w, 1)), 1, parts)
    # A value longer than the caller's ``parts`` budget must read as
    # missing, not silently truncate (the module contract: torn or
    # unrepresentable reads are MISSING, never garbled).
    ok = h0 & (need_words <= parts * w)
    pls = [pl0]
    for j in range(1, parts):
        hj, vj, sj, plj, _ = _get_probe(swarm.alive, cfg, store,
                                        part_scfg, res.found,
                                        part_key(keys, j))
        needed = n_parts > j
        ok = ok & (~needed | (hj & (vj == val) & (sj == seq)))
        pls.append(jnp.where(needed[:, None], plj, 0))
    payload = jnp.concatenate(pls, axis=1)                   # [P,parts*W]
    # Canonicalize (zero words past the true length — a part slot's
    # tail words beyond the value end are storage padding, not value
    # bytes), check the root over the canonical form, THEN zero rows
    # that failed either the reassembly guard or the root.
    idx = jnp.arange(parts * w, dtype=jnp.int32)[None, :]
    payload = jnp.where(idx < need_words[:, None], payload, 0)
    if scfg.verify:
        ok = ok & _chunked_root_ok(keys, payload.reshape(p, parts, w),
                                   sz.astype(jnp.uint32))
    payload = jnp.where(ok[:, None], payload, 0)
    return ChunkedGetResult(
        hit=ok, val=jnp.where(ok, val, 0), seq=jnp.where(ok, seq, 0),
        length=jnp.where(ok, sz, 0), payload=payload,
        hops=res.hops, done=res.done)


# ---------------------------------------------------------------------------
# chunked listeners — value-LIST delivery (ref tellListener semantics)
# ---------------------------------------------------------------------------

def chunked_reg_ids(reg_ids: jax.Array, parts: int) -> jax.Array:
    """Dense per-part registration-id block of a chunked listener:
    logical id ``r`` owns delivery slots ``r·parts … r·parts+parts-1``
    (part ``j`` delivers into slot ``r·parts + j``).  Callers keep
    ``r·parts + parts ≤ scfg.max_listeners``; invalid ids stay
    negative and are dropped by the table insert.  Returns the
    flattened ``[P·parts]`` int32 id vector (ack/cancel sweeps take it
    directly)."""
    rid = jnp.asarray(reg_ids, jnp.int32)
    block = rid[:, None] * parts + jnp.arange(parts, dtype=jnp.int32)
    return jnp.where(rid[:, None] >= 0, block, -1).reshape(-1)


def listen_chunked(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                   scfg: StoreConfig, keys: jax.Array,
                   reg_ids: jax.Array, rng: jax.Array, parts: int,
                   now=0) -> Tuple[SwarmStore, jax.Array]:
    """Register chunked listeners: ONE lookup per base key, then ONE
    listener-table insert covering every part key, so every part's
    future announces deliver into the logical listener's per-part
    slots (:func:`chunked_reg_ids`).

    All parts ride a SINGLE insert batch on purpose: a node accepts at
    most ``listen_slots`` rows per batch in sorted-key order, and one
    key's part keys sort adjacent, so a node either holds a chunked
    registration WHOLE or not at all — per-part calls would instead
    wrap the ring slot-by-slot and tear every co-located registration
    (keys sharing a neighborhood share their entire quorum).  A node
    needs ``listen_slots ≥ parts`` to hold one chunked registration;
    keys co-located beyond ``listen_slots // parts`` fall back to the
    quorum nodes they do not share.  Returns ``(store, done [P])``."""
    res = lookup(swarm, cfg, keys, rng)
    rid = jnp.asarray(reg_ids, jnp.int32)
    found_b = jnp.tile(res.found, (parts, 1))
    keys_b = jnp.concatenate([part_key(keys, j) for j in range(parts)])
    rid_b = jnp.concatenate([jnp.where(rid >= 0, rid * parts + j, -1)
                             for j in range(parts)])
    store = _listen_insert(swarm.alive, cfg, store, scfg, found_b,
                           keys_b, rid_b, dev_u32(now))
    return store, res.done


@partial(jax.jit, static_argnames=("scfg", "parts"))
def collect_chunked(store: SwarmStore, scfg: StoreConfig,
                    reg_ids: jax.Array, parts: int,
                    keys: Optional[jax.Array] = None
                    ) -> ChunkedCollectResult:
    """Reassemble delivered chunked values from listener slots — the
    reference pushes the changed VALUE LIST to a listener
    (``tellListener``, src/network_engine.cpp:161-173); here the list
    is the per-part delivery slots, merged under the same guard as
    :func:`get_chunked`: ready iff part 0 delivered and every needed
    part was delivered with part-0's ``(val, seq)``.  A torn delivery
    (some parts' announces lost) is NOT ready — never garbled.  With
    ``scfg.verify`` and the base ``keys [P,5]`` given, the reassembled
    value must also hash back to its key (:func:`_chunked_root_ok`).
    Pair with :func:`ack_chunked` to consume and re-arm."""
    w = scfg.payload_words
    ml = scfg.max_listeners
    rid = jnp.asarray(reg_ids, jnp.int32)
    p = rid.shape[0]
    slot0 = rid * parts
    valid = (rid >= 0) & (slot0 + parts <= ml)
    s0 = jnp.clip(slot0, 0, ml - 1)
    nseq0 = store.nseqs[s0]                  # delivered seq + 1, 0=none
    val0 = store.nvals[s0]
    sz = store.nsizes[s0]
    need_words = -(-sz.astype(jnp.int32) // 4)
    n_parts = jnp.clip(-(-need_words // max(w, 1)), 1, parts)
    ready = valid & (nseq0 > 0) & (need_words <= parts * w)
    pls = [store.npayload[s0]]
    for j in range(1, parts):
        sj = jnp.clip(slot0 + j, 0, ml - 1)
        needed = n_parts > j
        same = (store.nseqs[sj] == nseq0) & (store.nvals[sj] == val0)
        ready = ready & (~needed | same)
        pls.append(jnp.where(needed[:, None], store.npayload[sj], 0))
    payload = jnp.concatenate(pls, axis=1)
    idx = jnp.arange(parts * w, dtype=jnp.int32)[None, :]
    payload = jnp.where(idx < need_words[:, None], payload, 0)
    if scfg.verify and keys is not None:
        ready = ready & _chunked_root_ok(
            keys, payload.reshape(p, parts, w), sz)
    payload = jnp.where(ready[:, None], payload, 0)
    # nseqs stores delivered_seq+1 saturated at 0xFFFFFFFE+1.
    return ChunkedCollectResult(
        ready=ready, val=jnp.where(ready, val0, 0),
        seq=jnp.where(ready, nseq0 - 1, 0),
        length=jnp.where(ready, sz, 0), payload=payload)


def ack_chunked(store: SwarmStore, reg_ids: jax.Array,
                parts: int) -> SwarmStore:
    """Consume the delivery slots of whole chunked listeners (all
    parts at once) so the next accepted announce re-delivers."""
    return ack_listeners(store, chunked_reg_ids(reg_ids, parts))


def cancel_chunked(store: SwarmStore, scfg: StoreConfig,
                   reg_ids: jax.Array, parts: int) -> SwarmStore:
    """Cancel whole chunked listeners mesh-wide: every part's table
    rows die and the per-part delivery slots clear."""
    return cancel_listen(store, scfg, chunked_reg_ids(reg_ids, parts))
