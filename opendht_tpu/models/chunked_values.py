"""Variable-size values on the device store: multi-slot chunking.

The reference stores variable-size values up to 64 KB
(/root/reference/include/opendht/value.h:73) and ships big ones as
MTU-sized parts (``sendValueParts``,
/root/reference/src/network_engine.cpp:830-882).  The device store's
slots are fixed-width (``StoreConfig.payload_words`` = W u32 words per
slot); this module stores a value of any byte length ≤ ``parts·4·W``
across ``ceil(words/W)`` slots of the SAME replica:

* **part keys** — part ``j`` stores under ``key XOR (j in limb 4)``.
  Routing uses the high bits (limb 0), so every part has the SAME
  closest-node set: one lookup per value, parts co-resident on each
  replica, like the reference's one-Storage-entry-per-value;
* **length** — part 0's ``size`` field records the value's true BYTE
  length (the per-value size the budget already accounts); parts ≥ 1
  carry nominal size 1.  A reader recovers the part count exactly from
  part 0, so there is no ambiguity at width-multiple lengths;
* **consistency** — parts are only accepted by the per-slot edit
  policy (monotone seq), and a read requires every needed part to
  carry the winning part-0 ``(val, seq)``: a torn multi-part update
  (some parts dropped under capacity) reads as MISSING, never as
  garbled bytes — fail-safe, healed by the next republish sweep like
  any dropped announce.

This removes the "one fixed payload width per store" fidelity
asterisk: per-value lengths are real, bytes are real, reassembly is
exact.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .storage import (
    AnnounceReport,
    StoreConfig,
    SwarmStore,
    _announce_insert,
    _get_probe,
)
from .swarm import Swarm, SwarmConfig, lookup


class ChunkedGetResult(NamedTuple):
    hit: jax.Array      # [P] bool — value completely reassembled
    val: jax.Array      # [P] uint32 — value token
    seq: jax.Array      # [P] uint32
    length: jax.Array   # [P] uint32 — true byte length
    payload: jax.Array  # [P, parts*W] uint32 — reassembled words
    hops: jax.Array     # [P]
    done: jax.Array     # [P]


def part_key(keys: jax.Array, j: int) -> jax.Array:
    """Derived storage key of part ``j``: base key with the part index
    XORed into limb 4 (the low 32 id bits) — identical routing prefix,
    distinct storage identity."""
    if j == 0:
        return keys
    tag = jnp.zeros((keys.shape[0], 5), jnp.uint32).at[:, 4].set(j)
    return keys ^ tag


def announce_chunked(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                     scfg: StoreConfig, keys: jax.Array,
                     vals: jax.Array, seqs: jax.Array, now,
                     rng: jax.Array, payloads: jax.Array,
                     lengths: jax.Array
                     ) -> Tuple[SwarmStore, AnnounceReport]:
    """Batched put of variable-size values.

    ``payloads [P, parts, W]`` (W = ``scfg.payload_words``),
    ``lengths [P]`` true byte lengths (≤ parts·4·W).  One lookup per
    value; each active part becomes a storage insert at its part key
    on the same quorum replicas.  The report's ``replicas`` counts
    replicas that accepted part 0 (the part whose size carries the
    value length).

    Zero-length values round-trip: the reference permits empty value
    data (value.h:73 caps only the maximum), so part 0 is stored for
    EVERY valid announce row — a length-0 value occupies one slot with
    recorded size 0 and reads back as a hit with empty payload, not as
    a silent un-announce (ADVICE round 5).
    """
    p, parts, w = payloads.shape
    assert w == scfg.payload_words, (w, scfg.payload_words)
    res = lookup(swarm, cfg, keys, rng)
    # Clamp to what ``payloads`` can actually represent: an oversize
    # recorded length would store unreadable-forever parts (the reader
    # rejects need_words > parts·w), silently wasting replica budget.
    lengths = jnp.minimum(lengths, jnp.uint32(parts * w * 4))
    words = -(-lengths.astype(jnp.int32) // 4)               # [P]
    rep0, trace = None, None
    for j in range(parts):
        # Part 0 is active unconditionally (it carries the value's
        # existence and true length — including length 0).
        active = (words > j * w) | (j == 0)
        found_j = jnp.where(active[:, None], res.found, -1)
        sizes_j = (lengths.astype(jnp.uint32) if j == 0
                   else jnp.ones_like(lengths, jnp.uint32))
        store, rep, tr = _announce_insert(
            swarm.alive, cfg, store, scfg, found_j, part_key(keys, j),
            vals, seqs, jnp.uint32(now), sizes_j, None, payloads[:, j])
        trace = tr if trace is None else trace + tr
        if j == 0:
            rep0 = rep
    return store, AnnounceReport(replicas=rep0, hops=res.hops,
                                 done=res.done, trace=trace)


def get_chunked(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                scfg: StoreConfig, keys: jax.Array, rng: jax.Array,
                parts: int) -> ChunkedGetResult:
    """Batched get of variable-size values: one lookup, per-part quorum
    probes, exact reassembly.

    A value is ``hit`` iff part 0 is found and every part the recorded
    length requires is found with part-0's ``(val, seq)`` — a torn or
    partially-expired value reads as missing, never as garbled bytes.
    """
    w = scfg.payload_words
    res = lookup(swarm, cfg, keys, rng)
    h0, val, seq, pl0, sz = _get_probe(swarm.alive, cfg, store, scfg,
                                       res.found, keys)
    need_words = -(-sz.astype(jnp.int32) // 4)               # [P]
    n_parts = jnp.clip(-(-need_words // max(w, 1)), 1, parts)
    # A value longer than the caller's ``parts`` budget must read as
    # missing, not silently truncate (the module contract: torn or
    # unrepresentable reads are MISSING, never garbled).
    ok = h0 & (need_words <= parts * w)
    pls = [pl0]
    for j in range(1, parts):
        hj, vj, sj, plj, _ = _get_probe(swarm.alive, cfg, store, scfg,
                                        res.found, part_key(keys, j))
        needed = n_parts > j
        ok = ok & (~needed | (hj & (vj == val) & (sj == seq)))
        pls.append(jnp.where(needed[:, None], plj, 0))
    payload = jnp.concatenate(pls, axis=1)                   # [P,parts*W]
    # Zero everything past the true length (a part slot's tail words
    # beyond the value end are storage padding, not value bytes).
    idx = jnp.arange(parts * w, dtype=jnp.int32)[None, :]
    payload = jnp.where((idx < need_words[:, None]) & ok[:, None],
                        payload, 0)
    return ChunkedGetResult(
        hit=ok, val=jnp.where(ok, val, 0), seq=jnp.where(ok, seq, 0),
        length=jnp.where(ok, sz, 0), payload=payload,
        hops=res.hops, done=res.done)
