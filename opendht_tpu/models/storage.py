"""Device-resident value storage for the TPU swarm engine.

Round 1's engine simulated *routing only* (``onFindNode``).  This module
adds the half that makes it a DHT: every simulated node carries a small
value store and a listener table as packed tensors, and the reference's
storage RPCs become batched scatters/gathers:

* ``announce``  — vectorized ``Dht::onAnnounce``
  (/root/reference/src/dht.cpp:3333-3399): a batch of puts runs the
  lock-step lookup to find each key's ``quorum`` closest nodes, then
  inserts (key, value, seq) into those nodes' stores with the
  edit-policy seq check (monotonically increasing sequence numbers for
  an existing key, /root/reference/src/securedht.cpp:103-118) and a
  bounded per-node budget (the 64 MB / value-count caps of
  ``Dht::storageStore``, /root/reference/src/dht.cpp:2227-2258, scaled
  to ``slots`` values per node).
* ``get_values`` — vectorized ``Dht::onGetValues``
  (/root/reference/src/dht.cpp:3202-3225): a batch of gets runs the
  lookup, then probes the stores of the closest queried nodes and
  returns the freshest matching value (highest seq, the reference's
  refresh-wins semantics).
* ``listen_at`` / listener notification — vectorized
  ``Dht::storageAddListener`` + ``storageChanged``
  (/root/reference/src/dht.cpp:2186-2225,2299-2322): listener
  registrations live in a per-node table; every accepted announce
  matches against the target node's listeners and flips their
  "notified" bits (the ``tellListener`` push).  Registrations carry an
  expiry (``StoreConfig.listen_ttl``) refreshed by
  :func:`refresh_listeners` — the reference re-registers listeners
  every 30 s and expires silent ones — and are cancelable mesh-wide
  (:func:`cancel_listen`, the reference's ``Dht::cancelListen``,
  include/opendht/dht.h:341-351).  Delivery slots are CONSUMABLE: a
  reader ack (:func:`ack_listeners`) resets ``notified``/``nseqs`` so
  the next accepted announce re-delivers — a listener observes the
  second and third change, not just the first.

  Deliberate simplification vs the reference: ``tellListener`` ships
  the node's whole changed-VALUE LIST; these delivery slots hold only
  the freshest single value per listener (highest seq wins).  A
  listener over a key with several live values sees the newest one per
  push — sufficient for the pub/sub scenarios the engine models, and
  what keeps the per-listener state O(1) at 10M nodes.  Consequence of
  consumable slots: after an ack, a re-announce at the SAME seq (or a
  genuinely stale replica's republish) re-fires delivery — the
  reference behaves the same way (every storageChanged pushes; clients
  dedup by value id).
* ``expire`` — per-value TTL sweep (``Storage::expire``,
  /root/reference/src/dht.cpp:2361-2381).
* ``republish_from`` — per-node value maintenance: chosen nodes
  re-announce everything they store, the sim equivalent of
  ``Dht::dataPersistence``/``maintainStorage``
  (/root/reference/src/dht.cpp:2887-2947) that keeps values alive
  under churn.

Storage deviates from the reference in one documented way: when a
node's store is full, the ring cursor overwrites the oldest slot
instead of rejecting the new value — under steady TTL expiry the two
behaviours converge, and the ring keeps every shape static.

All state is a pytree of ``[N, slots]``-shaped arrays, so it shards
over the node axis exactly like the routing tables.
"""

from __future__ import annotations

import time
from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.sha1 import sha1_words
from ..ops.xor_metric import N_LIMBS
from ..utils.hostdevice import dev_u32
from .swarm import LookupResult, Swarm, SwarmConfig, lookup

INT32_MAX = 0x7FFFFFFF


def _pl_gather(flat1: jax.Array, row: jax.Array, w: int) -> jax.Array:
    """Gather payload rows ``[..., w]`` from the FLAT 1-D payload view.

    Multi-GB payload operands with a small (non-128) minor dim crash
    the TPU compiler on 2-D/3-D gathers (measured at 10M nodes, W=8 —
    the same non-lane-aligned-minor failure mode as the table layout
    work, BASELINE.md round 4); W per-column 1-D gathers are pad-free
    and compile clean.  ``row`` is a slot-row index (node·S + slot);
    element index ``row·w + j`` must stay below 2³¹ — (N+1)·S·W <
    2^31, ample for every real config (10M × 16 slots × 8 words =
    1.3e9).
    """
    idx = row[..., None] * w + jnp.arange(
        w, dtype=jnp.int32).reshape((1,) * row.ndim + (w,))
    return flat1[idx]


def _pl_scatter(flat1: jax.Array, row: jax.Array, vals: jax.Array,
                w: int) -> jax.Array:
    """Scatter payload rows ``vals [..., w]`` into the flat 1-D view
    as ONE element scatter with an ``[..., w]`` index array (a
    per-column loop of w chained scatters held w full-array versions
    live — measured 25 GB at W=64; see :func:`_pl_gather` for why the
    operand must be flat).  Out-of-bounds rows (masked requests)
    drop."""
    idx = row[..., None] * w + jnp.arange(
        w, dtype=jnp.int32).reshape((1,) * row.ndim + (w,))
    return flat1.at[idx].set(vals, mode="drop")


def _payload_digest(pl: jax.Array) -> jax.Array:
    """Order-sensitive 32-bit digest of payload rows ``[..., W]``.

    One word per value on the probe wire stands in for W words of
    bytes: ``sum_j pl[..., j] · C^(j+1) (mod 2³²)`` with odd constant
    C (invertible mod 2³²), so a word swap or single-word change moves
    the digest — cheap (one fused multiply-sum), not cryptographic.
    Used by the announce probe to match the edit policy's "data
    exactly the same" test without shipping the payload
    (:func:`opendht_tpu.parallel.sharded_storage._probe_refresh`).
    """
    w = pl.shape[-1]
    if w == 0:
        return jnp.zeros(pl.shape[:-1], jnp.uint32)
    c, x, pows = 0x9E3779B1, 1, []
    for _ in range(w):
        x = (x * c) & 0xFFFFFFFF
        pows.append(x)
    return jnp.sum(pl * jnp.asarray(pows, jnp.uint32), axis=-1,
                   dtype=jnp.uint32)


def _key_match(flat_keys: jax.Array, node: jax.Array, n_slots: int,
               key: jax.Array) -> jax.Array:
    """``[..., n_slots]`` bool: does ``node``'s slot j hold ``key``?

    Per-column 1-D gathers over the FLAT ``[N·S·5]`` key store —
    materializing a ``[N,S,5]`` key array for 3-D gathers acquires a
    transposed tiled-layout copy (measured 25.6 GB at 10M nodes,
    slots=5), while 1-D element gathers are pad-free.  ``node``
    broadcasts against ``key[..., l]``.
    """
    cols = []
    for j in range(n_slots):
        m = None
        for l in range(N_LIMBS):
            g = flat_keys[(node * n_slots + j) * N_LIMBS + l] \
                == key[..., l]
            m = g if m is None else (m & g)
        cols.append(m)
    return jnp.stack(cols, axis=-1)


def _key_rows(flat_keys: jax.Array, row: jax.Array) -> jax.Array:
    """Gather whole 5-limb keys ``[..., 5]`` by slot-row index from the
    flat key store (one element gather; dtype-generic _pl_gather)."""
    return _pl_gather(flat_keys, row, N_LIMBS)


def _key_write(flat_keys: jax.Array, row: jax.Array,
               key: jax.Array) -> jax.Array:
    """Scatter 5-limb keys by slot-row index, one element scatter
    (OOB rows drop; dtype-generic _pl_scatter)."""
    return _pl_scatter(flat_keys, row, key, N_LIMBS)


def _mask_dead_idx(alive: jax.Array, cfg: SwarmConfig,
                   req_node: jax.Array) -> jax.Array:
    """-1 out requests aimed at dead or invalid nodes (dead replicas
    never ack — the reference's expired announce targets)."""
    return jnp.where(
        (req_node >= 0)
        & alive[jnp.clip(req_node, 0, cfg.n_nodes - 1)],
        req_node, -1)


class StoreConfig(NamedTuple):
    """Static storage geometry (jit cache key).

    ``slots`` scales the reference's per-node budget (≤1024 values/hash,
    64 MB total, callbacks.h:72 / dht.h:333-339) down to simulation
    size; ``ttl`` is the default per-value lifetime in abstract
    sim-time units (0 disables expiry), standing in for the
    per-ValueType expiration
    (/root/reference/include/opendht/value.h:75-106) — announces may
    override it per value.  ``budget`` is the per-node total stored
    *size* cap in abstract units (0 = unlimited), the scaled analog of
    the reference's 64 MB ``max_store_size``; values also carry sizes,
    so full-node rejection is by bytes, not just slot count.

    ``payload_words`` > 0 attaches a fixed-width REAL payload to every
    stored value (``[N, S, W] uint32`` — 4·W bytes each): announces
    carry the actual bytes, replicas store them, gets return the
    freshest replica's bytes.  This is the device analogue of the
    reference's value data (64 KB cap, value.h:73) at a fixed chunk
    width; 0 (default) keeps the token-only store, flagged as
    ``sim_fidelity: "token-values"`` in bench artifacts.

    ``listen_ttl`` is the listener-registration lifetime in sim-time
    units (0 = registrations never expire): the reference registers
    listeners WITH expiration and re-registers every ~30 s
    (/root/reference/src/dht.cpp:2299-2322); :func:`refresh_listeners`
    is that re-register sweep, :func:`expire_listeners` the reclaim.

    ``verify`` arms the DEVICE INTEGRITY PLANE
    (:mod:`opendht_tpu.models.integrity`): values are content-addressed
    (``key = SHA-1(payload bytes)``), the insert programs recompute
    every arriving payload's digest and reject rows whose claimed key
    contradicts it (``StoreTrace.integrity_rejects``), and the get
    probes discard forged candidate replicas inside the jit before
    they can enter a result set — the storage twin of the chaos
    engine's merge-time distance-claim verification.  Requires
    ``payload_words > 0`` (a token-only store has no bytes to
    address).  False (default) keeps every program byte-identical to
    the unverified engine — the plane is a pure overlay.
    """
    slots: int = 16
    listen_slots: int = 4
    ttl: int = 0
    max_listeners: int = 1 << 16
    budget: int = 0
    payload_words: int = 0
    listen_ttl: int = 0
    verify: bool = False


class SwarmStore(NamedTuple):
    """Per-node value store + listener table (a pytree of arrays)."""
    # Stored key hashes, FLAT [N·S·5] uint32 (slot-row r = node·S +
    # slot owns limbs [r·5, r·5+5)) — same flat-layout rule as
    # ``payload`` below.
    keys: jax.Array      # [N*S*5] uint32 — stored key hashes
    vals: jax.Array      # [N,S] uint32   — value tokens
    seqs: jax.Array      # [N,S] uint32   — sequence numbers
    created: jax.Array   # [N,S] uint32   — sim-time of storage
    used: jax.Array      # [N,S] bool
    cursor: jax.Array    # [N] uint32     — ring write position
    lkeys: jax.Array     # [N*LS*5] uint32 — listened-for keys (flat)
    lids: jax.Array      # [N*LS] int32 — listener registration id, -1 (flat)
    lexps: jax.Array     # [N*LS] uint32 — listener expiry time (0 = never)
    lcursor: jax.Array   # [N] uint32
    notified: jax.Array  # [max_listeners] bool — listener got a push
    sizes: jax.Array     # [N,S] uint32   — stored value sizes
    ttls: jax.Array      # [N,S] uint32   — per-value ttl (0 = cfg.ttl)
    # Value bytes, FLAT [N·S·W] uint32 (slot-row r = node·S + slot
    # owns elements [r·W, (r+1)·W); W = 0: tokens only).  Flat because
    # a [N,S,W] form acquires a tiled device layout whose small minor
    # dims pad 8×128 — measured 25.6× expansion (40.96 GB for the
    # 1.6 GB 10M-node payload store); 1-D tiles linearly, pad-free.
    payload: jax.Array   # [N*S*W] uint32 — value bytes
    # Listener DELIVERY slots: what ``tellListener`` pushed — the
    # changed value itself, not just a "something changed" bit
    # (/root/reference/src/dht.cpp:2186-2225,
    # src/network_engine.cpp:161-173).  Freshest-seq announce wins.
    # ``nseqs`` holds delivered_seq + 1 so 0 means "nothing delivered"
    # even for a seq-0 value (keeps the cross-shard winner merge
    # unambiguous on first delivery).
    nseqs: jax.Array     # [max_listeners] uint32 — delivered seq + 1
    nvals: jax.Array     # [max_listeners] uint32 — delivered value token
    npayload: jax.Array  # [max_listeners,W] uint32 — delivered bytes
    # Delivered value SIZE: chunked listeners reassemble value LISTS
    # from per-part delivery slots, and part 0's recorded size is the
    # only way a collector recovers the true byte length.
    nsizes: jax.Array    # [max_listeners] uint32 — delivered value size


class StoreTrace(NamedTuple):
    """Flight-recorder counters for ONE storage sweep (scalar int32
    leaves, accumulated on-device inside the insert program — read
    them with one ``device_get``, never per-field fetches).

    The storage twin of :class:`~opendht_tpu.models.swarm.LookupTrace`:
    where the reference's ``storageStore`` returns a per-call bool and
    logs, the batched engine folds the whole sweep's outcome taxonomy
    into five reductions.  Under the sharded engine the leaves are
    psum-reduced before leaving the shard_map body, so the host always
    sees mesh-global numbers.

    * ``requests``       — storage RPCs that reached a live store;
    * ``accepts_update`` — edit-policy overwrites/refreshes accepted;
    * ``accepts_new``    — new-key ring inserts accepted;
    * ``rejects``        — surviving requests refused (stale seq,
      equal-seq conflict, byte budget, ring overflow/conflict);
    * ``notified``       — listener delivery matches fired
      (``storageChanged`` → ``tellListener`` pushes);
    * ``integrity_rejects`` — surviving requests whose payload digest
      contradicted their claimed content-addressed key, dropped by the
      verified insert (``StoreConfig.verify``; always 0 with the
      plane off).  Conservation is EXACT on dedup-free batches:
      ``requests == accepts_update + accepts_new + rejects +
      integrity_rejects`` — the auth gate's accounting identity.
    """
    requests: jax.Array
    accepts_update: jax.Array
    accepts_new: jax.Array
    rejects: jax.Array
    notified: jax.Array
    integrity_rejects: jax.Array

    @staticmethod
    def zeros() -> "StoreTrace":
        z = jnp.int32(0)
        return StoreTrace(z, z, z, z, z, z)

    def __add__(self, other: "StoreTrace") -> "StoreTrace":
        return StoreTrace(*[a + b for a, b in zip(self, other)])

    def to_dict(self) -> dict:
        host = jax.device_get(self)
        return {k: int(v) for k, v in zip(self._fields, host)}


class StoreStats(NamedTuple):
    """Point-in-time device-side storage gauges (one reduction pass —
    the device analogue of the host ``get_storage_log`` summary line /
    ``total_store_size``/``total_values`` counters)."""
    values: jax.Array          # live stored values
    stored_bytes: jax.Array    # sum of live value sizes (abstract units)
    listeners: jax.Array       # live listener-table registrations
    pending_notifies: jax.Array  # delivery slots awaiting an ack

    def to_dict(self) -> dict:
        host = jax.device_get(self)
        return {k: int(v) for k, v in zip(self._fields, host)}


@jax.jit
def store_stats(store: SwarmStore) -> StoreStats:
    """Compute :class:`StoreStats` gauges for a (local or sharded)
    store.  Elementwise reductions — under a ``NamedSharding`` XLA
    reduces shard-local and combines, so the single-chip op IS the
    sharded one."""
    return StoreStats(
        values=jnp.sum(store.used.astype(jnp.int32)),
        stored_bytes=jnp.sum(
            jnp.where(store.used, store.sizes, 0), dtype=jnp.uint32),
        listeners=jnp.sum((store.lids >= 0).astype(jnp.int32)),
        pending_notifies=jnp.sum(store.notified.astype(jnp.int32)))


class AnnounceReport(NamedTuple):
    replicas: jax.Array  # [P] int32 — copies stored per put
    hops: jax.Array      # [P] — lookup rounds
    done: jax.Array      # [P] bool — lookup converged
    # Sweep telemetry (None on paths that don't collect it).
    trace: "StoreTrace | None" = None


class GetResult(NamedTuple):
    hit: jax.Array   # [P] bool — value retrieved
    val: jax.Array   # [P] uint32 — freshest value token (0 if miss)
    seq: jax.Array   # [P] uint32
    hops: jax.Array  # [P]
    done: jax.Array  # [P]
    payload: jax.Array = None  # [P,W] uint32 — bytes (None/W=0: tokens)


def validate_store_geometry(n_nodes: int, scfg: StoreConfig) -> None:
    """Reject store geometries whose FLAT element indices overflow
    int32 — a bad config must fail loudly at construction, not wrap
    indices and silently drop writes.

    Every payload/key op computes ``row·width + col`` in int32 with
    rows up to ``(n_nodes+1)·slots`` (masked requests scatter to the
    out-of-bounds node ``n_nodes`` and rely on ``mode="drop"`` — a
    WRAPPED negative index is in-bounds again and corrupts live data).
    Before this check, ``bench.py --mode repub --nodes 10000000`` with
    default slots=4 / payload_words=64 (2.56e9 elements > 2³¹) wrapped
    exactly that way (ADVICE round 5, medium).
    """
    if scfg.verify and not scfg.payload_words:
        raise ValueError(
            "StoreConfig.verify needs payload_words > 0: content-"
            "addressed ids are digests of the value BYTES, and a "
            "token-only store has no bytes to verify")
    lim = 2 ** 31
    rows = (n_nodes + 1) * scfg.slots
    lrows = (n_nodes + 1) * scfg.listen_slots
    checks = (
        ("keys", rows * N_LIMBS),
        ("payload", rows * scfg.payload_words),
        ("listener keys", lrows * N_LIMBS),
        ("listener ids", lrows),
        ("listener table", scfg.max_listeners),
    )
    for name, n_elems in checks:
        if n_elems >= lim:
            raise ValueError(
                f"StoreConfig overflows int32 flat indexing: the {name} "
                f"store needs {n_elems:,} elements "
                f"(≥ 2^31 = {lim:,}) at n_nodes={n_nodes:,}, "
                f"slots={scfg.slots}, listen_slots={scfg.listen_slots}, "
                f"payload_words={scfg.payload_words}, "
                f"max_listeners={scfg.max_listeners} — gathers/scatters "
                f"would wrap and silently corrupt stored values; shrink "
                f"slots or payload_words (sharding does not help: the "
                f"flat index space is global, not per-shard)")


@partial(jax.jit, static_argnames=("n_nodes", "scfg"))
def empty_store(n_nodes: int, scfg: StoreConfig) -> SwarmStore:
    validate_store_geometry(n_nodes, scfg)
    n, s, ls = n_nodes, scfg.slots, scfg.listen_slots
    return SwarmStore(
        keys=jnp.zeros((n * s * N_LIMBS,), jnp.uint32),
        vals=jnp.zeros((n, s), jnp.uint32),
        seqs=jnp.zeros((n, s), jnp.uint32),
        created=jnp.zeros((n, s), jnp.uint32),
        used=jnp.zeros((n, s), bool),
        cursor=jnp.zeros((n,), jnp.uint32),
        lkeys=jnp.zeros((n * ls * N_LIMBS,), jnp.uint32),
        lids=jnp.full((n * ls,), -1, jnp.int32),
        lexps=jnp.zeros((n * ls,), jnp.uint32),
        lcursor=jnp.zeros((n,), jnp.uint32),
        notified=jnp.zeros((scfg.max_listeners,), bool),
        sizes=jnp.zeros((n, s), jnp.uint32),
        ttls=jnp.zeros((n, s), jnp.uint32),
        payload=jnp.zeros((n * s * scfg.payload_words,), jnp.uint32),
        nseqs=jnp.zeros((scfg.max_listeners,), jnp.uint32),
        nvals=jnp.zeros((scfg.max_listeners,), jnp.uint32),
        npayload=jnp.zeros((scfg.max_listeners, scfg.payload_words),
                           jnp.uint32),
        nsizes=jnp.zeros((scfg.max_listeners,), jnp.uint32),
    )


# ---------------------------------------------------------------------------
# core vectorized insert (the onAnnounce storage path)
# ---------------------------------------------------------------------------

def _segment_excl_sum(weights: jax.Array,
                      first: jax.Array) -> jax.Array:
    """Per-row exclusive prefix sum within each segment.

    ``first[i]`` = index of the first row of row i's segment (from a
    ``searchsorted(sorted_node, sorted_node)`` the caller computes
    once and shares).
    """
    c = jnp.cumsum(weights) - weights
    return c - c[first]


def _segment_rank(sorted_node: jax.Array, flag: jax.Array,
                  first: jax.Array | None = None) -> jax.Array:
    """Rank of each flagged row within its node segment.

    ``sorted_node`` ascending; ``flag`` marks rows that consume a slot.
    Returns, per row, the number of flagged rows strictly before it in
    the same segment.
    """
    if first is None:
        first = jnp.searchsorted(sorted_node, sorted_node, side="left")
    return _segment_excl_sum(flag.astype(jnp.int32), first)


@partial(jax.jit, static_argnames=("scfg",), donate_argnums=(0,))
def _store_insert(store: SwarmStore, scfg: StoreConfig,
                  req_node: jax.Array, req_key: jax.Array,
                  req_val: jax.Array, req_seq: jax.Array,
                  req_put: jax.Array, now: jax.Array,
                  req_size: jax.Array | None = None,
                  req_ttl: jax.Array | None = None,
                  put_payloads: jax.Array | None = None
                  ) -> Tuple[SwarmStore, jax.Array, StoreTrace]:
    """Insert a flat batch of (node, key, val, seq) storage requests.

    ``req_node [M]`` (-1 = skip), ``req_key [M,5]``, ``req_val [M]``,
    ``req_seq [M]``, ``req_put [M]`` (originating put row);
    ``req_size``/``req_ttl`` optional ``[M]`` (default 1 / cfg
    default).  ``put_payloads [Pmax, W]``: optional real value bytes,
    indexed by ``req_put`` (per-PUT, not per-request, so the request
    sort never carries W-wide columns).  Returns the new store,
    accepted-replica counts scattered by ``req_put`` into a length-M
    vector (callers slice the first P rows), and the sweep's
    :class:`StoreTrace` counters.

    Semantics per request, mirroring ``Dht::storageStore`` +
    ``secureType`` edit policy
    (/root/reference/src/securedht.cpp:94-116):
    * key already stored on the node → overwrite iff ``seq >`` stored
      seq, or ``seq ==`` stored seq with the SAME value (a re-announce
      refresh, possibly by a third party); an equal-seq edit with
      different data is rejected — "sequence number must be
      increasing";
    * new key → ring-slot insert (oldest evicted when full), at most
      ``slots`` new keys per node per batch (excess dropped), and —
      when ``scfg.budget`` is set — only while the node's stored bytes
      plus the batch's earlier-ranked new bytes stay within budget
      (conservative: bytes freed by ring eviction are not credited
      until the next batch), the scaled ``max_store_size`` rejection
      of ``Dht::storageStore`` (/root/reference/src/dht.cpp:2227-2258).
    """
    s = scfg.slots
    m = req_node.shape[0]
    valid = req_node >= 0
    if req_size is None:
        req_size = jnp.ones((m,), jnp.uint32)
    if req_ttl is None:
        req_ttl = jnp.zeros((m,), jnp.uint32)

    # --- sort requests by (node, key, seq) so per-node work is contiguous
    node_sk = jnp.where(valid, req_node, INT32_MAX)
    sort_ops = (node_sk,) + tuple(req_key[:, i] for i in range(N_LIMBS)) \
        + (req_seq, req_val, req_put, req_node, req_size, req_ttl)
    out = jax.lax.sort(sort_ops, dimension=0, num_keys=N_LIMBS + 2,
                       is_stable=True)
    s_node_sk = out[0]
    s_key = jnp.stack(out[1:1 + N_LIMBS], axis=-1)
    s_seq, s_val, s_put, s_node = out[1 + N_LIMBS:5 + N_LIMBS]
    s_size, s_ttl = out[5 + N_LIMBS], out[6 + N_LIMBS]
    s_valid = s_node >= 0

    # --- in-batch dedup: same (node, key) → keep the last (max seq) row
    nxt_same = jnp.zeros((m,), bool).at[:-1].set(
        (s_node_sk[:-1] == s_node_sk[1:])
        & jnp.all(s_key[:-1] == s_key[1:], axis=-1))
    live = s_valid & ~nxt_same

    # --- match against existing slots on the target node
    n_nodes = store.used.shape[0]
    n_safe = jnp.clip(s_node, 0, n_nodes - 1)
    slot_used = store.used[n_safe]                        # [M,S]
    km = slot_used & _key_match(store.keys, n_safe, s, s_key)  # [M,S]
    has_match = jnp.any(km, axis=-1)
    mslot = jnp.argmax(km, axis=-1).astype(jnp.int32)     # first match

    first = jnp.searchsorted(s_node_sk, s_node_sk, side="left")

    # --- edit policy (monotone seq; equal seq only re-announces the
    # --- same value — token AND bytes, ref securedht.cpp:105-115
    # --- "if the data is exactly the same") and new-key candidacy
    w = scfg.payload_words
    if w:
        s_pl = (jnp.zeros((m, w), jnp.uint32) if put_payloads is None
                else put_payloads[
                    jnp.clip(s_put, 0, put_payloads.shape[0] - 1)])
        # Payload ops run on the flat store, one column at a time
        # (_pl_gather/_pl_scatter): any multi-element-minor form
        # crashed the compiler at 10M nodes.  Trash indices land out
        # of bounds and drop.
        flat_pl = store.payload
    cur_seq = store.seqs[n_safe, mslot]
    cur_val = store.vals[n_safe, mslot]
    same = s_val == cur_val
    if w:
        same = same & jnp.all(
            s_pl == _pl_gather(flat_pl, n_safe * s + mslot, w), axis=-1)
    # Device integrity plane (scfg.verify, static): the claimed
    # content-addressed key must equal the recomputed payload digest,
    # or the row is dropped HERE — a forged id or corrupted bytes
    # never reaches the edit policy, never takes a ring slot, and is
    # booked as an integrity reject.  Verify-off compiles the exact
    # pre-plane program (live_ok IS live; the trace column folds to 0).
    if scfg.verify:
        integ_ok = jnp.all(sha1_words(s_pl) == s_key, axis=-1)
        live_ok = live & integ_ok
    else:
        live_ok = live
    upd = live_ok & has_match & (
        (s_seq > cur_seq) | ((s_seq == cur_seq) & same))
    new = live_ok & ~has_match
    if scfg.budget:
        # Byte budget (the reference's max_store_size rejection,
        # storageStore src/dht.cpp:2227-2258): stored bytes on the
        # node plus this batch's earlier-ranked *growth* — new-key
        # bytes and growing-refresh deltas share ONE per-segment
        # prefix sum, so their combined accepts can never sum past the
        # cap.  Conservative on purpose: growth of rows later rejected
        # still counts against successors (they retry next round), and
        # shrinking refreshes are not credited in-batch.  A refinement
        # that re-admits shadowed rows can overshoot the cap via
        # mutually-blind re-accepts, and the cap is a hard invariant.
        budget = jnp.int32(min(scfg.budget, INT32_MAX - 1))
        # Clamp request sizes to budget+1 BEFORE the signed arithmetic:
        # an oversize request then still surely fails its admit check,
        # while a raw uint32 size ≥ 2^31 can no longer wrap negative
        # and bypass the cap (and per-row growth ≤ budget+1 keeps the
        # int32 segment prefix sum exact for any segment whose
        # candidate growth stays below 2^31).
        s_sz = jnp.minimum(
            s_size, jnp.uint32(budget) + 1).astype(jnp.int32)
        node_bytes = jnp.sum(
            jnp.where(store.used, store.sizes, 0), axis=1)  # [N]
        base = node_bytes[n_safe].astype(jnp.int32)
        # Stored sizes are ≤ budget by this same check's invariant.
        old_size = jnp.where(has_match, store.sizes[n_safe, mslot],
                             0).astype(jnp.int32)
        delta = s_sz - old_size
        growth = jnp.where(upd & (delta > 0), delta, 0) \
            + jnp.where(new, s_sz, 0)
        cum = _segment_excl_sum(growth, first)
        upd = upd & (base + cum + jnp.maximum(delta, 0) <= budget)
        new = new & (base + cum + s_sz <= budget)
    # Masked rows scatter to the OUT-OF-BOUNDS index n_nodes and are
    # DROPPED (mode="drop") — no padded-copy trick: _pad1's
    # concatenate forced a full copy of every store leaf, and at the
    # 10M-node payload config those copies (on top of the runtime's
    # un-aliased jit inputs/outputs) blew the program past HBM
    # (measured 19.1 GB of 15.75 GB).
    un, us = jnp.where(upd, s_node, n_nodes), mslot
    vals = store.vals.at[un, us].set(s_val, mode="drop")
    seqs = store.seqs.at[un, us].set(s_seq, mode="drop")
    created = store.created.at[un, us].set(now, mode="drop")
    sizes = store.sizes.at[un, us].set(s_size, mode="drop")
    ttls = store.ttls.at[un, us].set(s_ttl, mode="drop")
    # Payload written unconditionally when enabled (zeros for a
    # payload-less announce): a slot's bytes must never outlive the
    # value that owned them — a ring-wrapped new key would otherwise
    # return the previous occupant's bytes on get.
    if w:
        flat_pl = _pl_scatter(flat_pl, un * s + us, s_pl, w)

    # --- new-key path: ring-slot allocation, ≤ slots per node per batch
    rank = _segment_rank(s_node_sk, new, first)
    slot = ((store.cursor[n_safe] + rank.astype(jnp.uint32))
            % jnp.uint32(s)).astype(jnp.int32)
    # A ring slot may coincide with a slot an *update in this same
    # batch* just refreshed; overwriting it would silently destroy an
    # accepted value.  Drop the new key instead — the reference's
    # reject-when-full (``storageStore`` returning false,
    # /root/reference/src/dht.cpp:2227-2258).
    upd_map = jnp.zeros_like(store.used).at[un, us].set(
        upd, mode="drop")
    conflict = upd_map[n_safe, slot]
    accept_new = new & (rank < s) & ~conflict
    nn = jnp.where(accept_new, s_node, n_nodes)
    keys = _key_write(store.keys, nn * s + slot, s_key)
    vals = vals.at[nn, slot].set(s_val, mode="drop")
    seqs = seqs.at[nn, slot].set(s_seq, mode="drop")
    created = created.at[nn, slot].set(now, mode="drop")
    sizes = sizes.at[nn, slot].set(s_size, mode="drop")
    ttls = ttls.at[nn, slot].set(s_ttl, mode="drop")
    if w:
        flat_pl = _pl_scatter(flat_pl, nn * s + slot, s_pl, w)
        payload = flat_pl
    else:
        payload = store.payload
    used = store.used.at[nn, slot].set(True, mode="drop")
    n_new = jnp.zeros_like(store.cursor).at[jnp.where(accept_new, s_node, 0)
                                            ].add(accept_new.astype(jnp.uint32))
    cursor = store.cursor + n_new

    # --- listener notification (storageChanged → tellListener)
    accepted = upd | accept_new
    ls_n = store.lids.shape[0] // n_nodes                 # listen slots
    lid = jnp.stack([store.lids[n_safe * ls_n + j]
                     for j in range(ls_n)], axis=-1)      # [M,LS]
    # Expired registrations stop matching lazily (0 = no expiry) —
    # the reference drops listeners whose expiration passed without a
    # re-register (src/dht.cpp:2299-2322); expire_listeners reclaims
    # the rows, but correctness never depends on the sweep running.
    lexp = jnp.stack([store.lexps[n_safe * ls_n + j]
                      for j in range(ls_n)], axis=-1)     # [M,LS]
    lmatch = (lid >= 0) \
        & ((lexp == 0) | (jnp.uint32(now) <= lexp)) \
        & _key_match(store.lkeys, n_safe, ls_n, s_key) \
        & accepted[:, None]
    lid_safe = jnp.clip(lid, 0, store.notified.shape[0] - 1)
    notified = store.notified.at[
        jnp.where(lmatch, lid_safe, 0).reshape(-1)
    ].max(lmatch.reshape(-1))

    # --- listener VALUE delivery: the push carries the changed value
    # itself (ref tellListener sends the value list,
    # src/network_engine.cpp:161-173), freshest seq winning.  No-blend
    # winner pick without a sort: (1) scatter-max each listener's seq
    # (vs the already-delivered one), (2) scatter-max the REQUEST ROW
    # among rows achieving that seq, (3) one gather copies exactly that
    # row's (val, seq, payload) — duplicate-seq ties resolve to one
    # deterministic row, so val and bytes can never mix across rows.
    lidf = jnp.where(lmatch, lid_safe, 0).reshape(-1)     # [M*LS]
    matchf = lmatch.reshape(-1)
    rowf = jnp.repeat(jnp.arange(m, dtype=jnp.int32), lmatch.shape[1])
    # seq+1, saturating: seq 0xFFFFFFFF must not wrap to the "nothing
    # delivered" sentinel 0 (it would overwrite nvals while nseqs says
    # no delivery).  The last two seq values share one slot encoding —
    # harmless, monotonicity preserved.
    seq1f = jnp.minimum(jnp.repeat(s_seq, lmatch.shape[1]),
                        jnp.uint32(0xFFFFFFFE)) + 1
    nseqs = store.nseqs.at[lidf].max(jnp.where(matchf, seq1f, 0))
    win1 = matchf & (seq1f == nseqs[lidf])
    rmax = jnp.full_like(store.nseqs, -1, jnp.int32).at[lidf].max(
        jnp.where(win1, rowf, -1))
    deliver = rmax >= 0                                   # [max_listeners]
    r_safe = jnp.clip(rmax, 0, m - 1)
    nvals = jnp.where(deliver, s_val[r_safe], store.nvals)
    nseqs = jnp.where(
        deliver,
        jnp.minimum(s_seq[r_safe], jnp.uint32(0xFFFFFFFE)) + 1,
        store.nseqs)
    if w:
        npayload = jnp.where(deliver[:, None], s_pl[r_safe],
                             store.npayload)
    else:
        npayload = store.npayload
    nsizes = jnp.where(deliver, s_size[r_safe], store.nsizes)

    new_store = store._replace(keys=keys, vals=vals, seqs=seqs,
                               created=created, used=used, cursor=cursor,
                               notified=notified, sizes=sizes, ttls=ttls,
                               payload=payload, nseqs=nseqs, nvals=nvals,
                               npayload=npayload, nsizes=nsizes)
    # Per-put replica counts.
    put_safe = jnp.clip(s_put, 0, None)
    replicas = jnp.zeros((m,), jnp.int32).at[put_safe].add(
        accepted.astype(jnp.int32))
    i32 = jnp.int32
    trace = StoreTrace(
        requests=jnp.sum(valid.astype(i32)),
        accepts_update=jnp.sum(upd.astype(i32)),
        accepts_new=jnp.sum(accept_new.astype(i32)),
        # Surviving (post-dedup) requests refused by the edit policy,
        # byte budget, or ring allocation — what the reference's
        # storageStore-returns-false / "seq must be increasing" paths
        # count one call at a time.
        rejects=jnp.sum((live_ok & ~upd & ~accept_new).astype(i32)),
        notified=jnp.sum(lmatch.astype(i32)),
        integrity_rejects=(jnp.sum((live & ~integ_ok).astype(i32))
                           if scfg.verify else jnp.int32(0)))
    return new_store, replicas, trace


# ---------------------------------------------------------------------------
# public batched DHT ops
# ---------------------------------------------------------------------------

def _announce_targets(swarm: Swarm, cfg: SwarmConfig, keys: jax.Array,
                      rng: jax.Array) -> LookupResult:
    """Lookup phase of a put: find each key's quorum closest nodes
    (``searchSendAnnounceValue`` announces to the synced search head,
    /root/reference/src/dht.cpp:1237-1339)."""
    return lookup(swarm, cfg, keys, rng)


@partial(jax.jit, static_argnames=("cfg", "scfg"), donate_argnums=(2,))
def _announce_insert(alive: jax.Array, cfg: SwarmConfig,
                     store: SwarmStore,
                     scfg: StoreConfig, res_found: jax.Array,
                     keys: jax.Array, vals: jax.Array, seqs: jax.Array,
                     now: jax.Array, sizes: jax.Array | None = None,
                     ttls: jax.Array | None = None,
                     payloads: jax.Array | None = None
                     ) -> Tuple[SwarmStore, jax.Array, StoreTrace]:
    # Takes the bare ``alive`` mask, NOT the whole swarm: the runtime
    # keeps every jit input resident (no unused-arg pruning through the
    # AOT tunnel), and a rides-along 10 GB routing table was the
    # measured difference between compiling and a 19.1 GB HBM blowup
    # at the 10M-node payload config.
    p, q = res_found.shape
    req_node = _mask_dead_idx(alive, cfg, res_found.reshape(-1))
    req_key = jnp.repeat(keys, q, axis=0)
    req_val = jnp.repeat(vals, q, axis=0)
    req_seq = jnp.repeat(seqs, q, axis=0)
    req_put = jnp.repeat(jnp.arange(p, dtype=jnp.int32), q, axis=0)
    req_size = None if sizes is None else jnp.repeat(sizes, q, axis=0)
    req_ttl = None if ttls is None else jnp.repeat(ttls, q, axis=0)
    store, rep_m, trace = _store_insert(store, scfg, req_node, req_key,
                                        req_val, req_seq, req_put, now,
                                        req_size, req_ttl, payloads)
    return store, rep_m[:p], trace


def drop_exchanges(found: jax.Array, drop_frac: float,
                   drop_key: jax.Array | None) -> jax.Array:
    """Fault injection for the storage path, symmetric to the lookup
    path's ``churn()``: lose a uniform ``drop_frac`` of the per-replica
    announce/probe exchanges (each dropped entry is one storage RPC
    that never arrives — the netem packet-loss analogue).  Dropped
    replicas cost replication for the round and are healed by the next
    maintenance sweep, exactly like reference announces lost under
    load."""
    if not drop_frac or drop_key is None:
        return found
    keep = jax.random.uniform(drop_key, found.shape) >= drop_frac
    return jnp.where(keep, found, -1)


def announce(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
             scfg: StoreConfig, keys: jax.Array, vals: jax.Array,
             seqs: jax.Array, now, rng: jax.Array,
             sizes: jax.Array | None = None,
             ttls: jax.Array | None = None,
             payloads: jax.Array | None = None,
             drop_frac: float = 0.0,
             drop_key: jax.Array | None = None
             ) -> Tuple[SwarmStore, AnnounceReport]:
    """Batched put: lookup each key, store at its quorum closest alive
    nodes.  ``keys [P,5]``, ``vals [P]``, ``seqs [P]``; optional
    per-value ``sizes`` (budget accounting), ``ttls`` (per-type
    expiration), both ``[P]``, and real value bytes ``payloads
    [P, scfg.payload_words]``.  ``drop_frac``/``drop_key`` inject
    storage-RPC loss (see :func:`drop_exchanges`)."""
    res = _announce_targets(swarm, cfg, keys, rng)
    found = drop_exchanges(res.found, drop_frac, drop_key)
    store, replicas, trace = _announce_insert(
        swarm.alive, cfg, store, scfg, found, keys, vals, seqs,
        dev_u32(now), sizes, ttls, payloads)
    return store, AnnounceReport(replicas=replicas, hops=res.hops,
                                 done=res.done, trace=trace)


@partial(jax.jit, static_argnames=("cfg", "scfg"))
def _get_probe(alive: jax.Array, cfg: SwarmConfig, store: SwarmStore,
               scfg: StoreConfig,
               found: jax.Array, keys: jax.Array
               ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array,
                          jax.Array]:
    """Probe the stores of each get's closest queried nodes
    (``onGetValues`` replies, collected by ``onGetValuesDone``,
    /root/reference/src/dht.cpp:3227-3297).  Freshest seq wins.
    Returns ``(hit, val, seq, payload, size)`` — size is the winning
    replica's stored size (0 on miss), which chunked values use to
    recover a value's true byte length from its part-0 slot."""
    n_safe = jnp.clip(found, 0, cfg.n_nodes - 1)
    ok = (found >= 0) & alive[n_safe]
    sslots = scfg.slots
    hit = store.used[n_safe] & ok[..., None] \
        & _key_match(store.keys, n_safe, sslots,
                     keys[:, None, :])                     # [P,Q,S]
    if scfg.verify:
        # Verified merge (the integrity plane's read half): every
        # candidate replica's payload is re-digested and compared to
        # the content-addressed key BEFORE the freshest-seq merge — a
        # forged or corrupted replica is discarded inside the jit and
        # can neither win the merge nor shadow an honest older copy.
        rows3 = n_safe[..., None] * sslots \
            + jnp.arange(sslots, dtype=jnp.int32)
        cand_pl = _pl_gather(store.payload, rows3, scfg.payload_words)
        hit = hit & jnp.all(sha1_words(cand_pl)
                            == keys[:, None, None, :], axis=-1)
    sseq = jnp.where(hit, store.seqs[n_safe], 0)
    best_seq = jnp.max(sseq, axis=(1, 2))
    is_best = hit & (sseq == best_seq[:, None, None])
    val = jnp.max(jnp.where(is_best, store.vals[n_safe], 0), axis=(1, 2))
    any_hit = jnp.any(hit, axis=(1, 2))
    p = found.shape[0]
    is_win = (is_best & (store.vals[n_safe] == val[:, None, None])
              ).reshape(p, -1)                         # [P, Q*S]
    # ONE winning replica's payload/size, fetched by flat slot-row
    # index with per-column 1-D gathers (never an elementwise max
    # across replicas, and never a small-minor gather on a multi-GB
    # payload operand — see _pl_gather).
    widx = jnp.argmax(is_win, axis=1).astype(jnp.int32)  # [P]
    qi, si = widx // sslots, widx % sslots
    node_w = jnp.take_along_axis(n_safe, qi[:, None], axis=1)[:, 0]
    roww = node_w * sslots + si
    w = scfg.payload_words
    if w:
        pl = jnp.where(any_hit[:, None],
                       _pl_gather(store.payload, roww, w), 0)
    else:
        pl = jnp.zeros((p, 0), jnp.uint32)
    sz = jnp.where(any_hit, store.sizes.reshape(-1)[roww], 0)
    return any_hit, val, best_seq, pl, sz


def _pick_payload(win: jax.Array, pls: jax.Array,
                  any_hit: jax.Array) -> jax.Array:
    """ONE winning replica's payload, picked by index — never an
    elementwise max across replicas: divergent same-(seq,val) replica
    payloads (possible via partial-quorum announces) would otherwise
    blend into bytes no replica ever held.  ``win [M,K]`` winner mask,
    ``pls [M,K,W]`` candidate payloads, ``any_hit [M]``; zeros on miss.
    """
    widx = jnp.argmax(win, axis=1)
    pl = jnp.take_along_axis(pls, widx[:, None, None], axis=1)[:, 0]
    return jnp.where(any_hit[:, None], pl, 0)


def get_values(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
               scfg: StoreConfig, keys: jax.Array, rng: jax.Array,
               chunk: int = 32768) -> GetResult:
    """Batched get: lookup each key, return the freshest stored value
    among the closest queried nodes.  ``keys [P,5]``."""
    res = lookup(swarm, cfg, keys, rng)
    p = keys.shape[0]
    hits, vals, seqs, pls = [], [], [], []
    for lo in range(0, p, chunk):
        hi = min(lo + chunk, p)
        h, v, s, pl, _ = _get_probe(swarm.alive, cfg, store, scfg,
                                    res.found[lo:hi], keys[lo:hi])
        hits.append(h), vals.append(v), seqs.append(s), pls.append(pl)
    return GetResult(
        hit=jnp.concatenate(hits), val=jnp.concatenate(vals),
        seq=jnp.concatenate(seqs), hops=res.hops, done=res.done,
        payload=jnp.concatenate(pls))


@partial(jax.jit, static_argnames=("cfg", "scfg"))
def _listen_insert(alive: jax.Array, cfg: SwarmConfig,
                   store: SwarmStore,
                   scfg: StoreConfig, found: jax.Array, keys: jax.Array,
                   reg_ids: jax.Array, now: jax.Array) -> SwarmStore:
    ls = scfg.listen_slots
    p, q = found.shape
    req_node = _mask_dead_idx(alive, cfg, found.reshape(-1))
    req_key = jnp.repeat(keys, q, axis=0)
    req_id = jnp.repeat(reg_ids, q, axis=0)
    # Out-of-range registration ids are dropped outright — clipping
    # would flip some other listener's notified bit at announce time.
    req_node = jnp.where(
        (req_id >= 0) & (req_id < scfg.max_listeners), req_node, -1)
    valid = req_node >= 0

    node_sk = jnp.where(valid, req_node, INT32_MAX)
    out = jax.lax.sort(
        (node_sk,) + tuple(req_key[:, i] for i in range(N_LIMBS))
        + (req_id, req_node),
        dimension=0, num_keys=1, is_stable=True)
    s_node_sk = out[0]
    s_key = jnp.stack(out[1:1 + N_LIMBS], axis=-1)
    s_id, s_node = out[1 + N_LIMBS], out[2 + N_LIMBS]
    live = s_node >= 0

    rank = _segment_rank(s_node_sk, live)
    accept = live & (rank < ls)
    n_safe = jnp.clip(s_node, 0, cfg.n_nodes - 1)
    slot = ((store.lcursor[n_safe] + rank.astype(jnp.uint32))
            % jnp.uint32(ls)).astype(jnp.int32)
    nn = jnp.where(accept, s_node, cfg.n_nodes)
    lkeys = _key_write(store.lkeys, nn * ls + slot, s_key)
    lids = store.lids.at[nn * ls + slot].set(s_id, mode="drop")
    exp = (jnp.uint32(now) + jnp.uint32(scfg.listen_ttl)
           if scfg.listen_ttl else jnp.uint32(0))
    lexps = store.lexps.at[nn * ls + slot].set(
        jnp.broadcast_to(exp, s_id.shape), mode="drop")
    n_new = jnp.zeros_like(store.lcursor).at[
        jnp.where(accept, s_node, 0)].add(accept.astype(jnp.uint32))
    return store._replace(lkeys=lkeys, lids=lids, lexps=lexps,
                          lcursor=store.lcursor + n_new)


def listen_at(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
              scfg: StoreConfig, keys: jax.Array, reg_ids: jax.Array,
              rng: jax.Array, now=0) -> Tuple[SwarmStore, LookupResult]:
    """Batched listen: register listener ``reg_ids [P]`` for ``keys
    [P,5]`` at each key's quorum closest nodes (``Dht::listenTo`` →
    ``storageAddListener``).  Subsequent announces of a key flip the
    ``notified`` bit of its listeners and fill their delivery slots.
    With ``scfg.listen_ttl`` set, registrations expire at ``now +
    listen_ttl`` unless re-registered (:func:`refresh_listeners`)."""
    res = lookup(swarm, cfg, keys, rng)
    store = _listen_insert(swarm.alive, cfg, store, scfg, res.found,
                           keys, reg_ids, dev_u32(now))
    return store, res


@partial(jax.jit, static_argnames=("scfg",))
def refresh_listeners(store: SwarmStore, scfg: StoreConfig,
                      active: jax.Array, now) -> SwarmStore:
    """Re-register sweep: push the expiry of every table row whose
    listener id is still ``active`` ([max_listeners] bool) out to
    ``now + listen_ttl`` — the device twin of the reference's ~30 s
    listener re-register (``Dht::listenTo`` keepalives,
    /root/reference/src/dht.cpp:2299-2322).  Rows whose owner is not
    in ``active`` keep their old deadline and lapse.  Elementwise over
    the listener table, so the sharded store runs it shard-local.
    No-op when ``listen_ttl`` is 0 (registrations never expire)."""
    if not scfg.listen_ttl:
        return store
    lid_safe = jnp.clip(store.lids, 0, scfg.max_listeners - 1)
    hit = (store.lids >= 0) & active[lid_safe]
    exp = jnp.uint32(now) + jnp.uint32(scfg.listen_ttl)
    return store._replace(lexps=jnp.where(hit, exp, store.lexps))


@partial(jax.jit, static_argnames=("scfg",))
def expire_listeners(store: SwarmStore, scfg: StoreConfig,
                     now) -> SwarmStore:
    """Reclaim listener-table rows whose expiry passed (lapsed
    registrations already stop matching lazily inside the announce
    path; this sweep frees their ring slots for new listeners)."""
    dead = (store.lids >= 0) & (store.lexps > 0) \
        & (store.lexps < jnp.uint32(now))
    return store._replace(lids=jnp.where(dead, -1, store.lids))


@partial(jax.jit, static_argnames=("scfg",))
def cancel_listen(store: SwarmStore, scfg: StoreConfig,
                  reg_ids: jax.Array) -> SwarmStore:
    """Cancel listeners mesh-wide (``Dht::cancelListen``,
    /root/reference/include/opendht/dht.h:341-351): every table row
    registered to a canceled id dies on every node, and the canceled
    ids' delivery slots clear.  ``reg_ids [P]`` int32; out-of-range
    ids are ignored.  Elementwise over the listener table — the
    sharded store runs it shard-local with zero communication."""
    ml = scfg.max_listeners
    safe = jnp.where((reg_ids >= 0) & (reg_ids < ml), reg_ids, ml)
    cancel = jnp.zeros((ml,), bool).at[safe].set(True, mode="drop")
    lid_safe = jnp.clip(store.lids, 0, ml - 1)
    dead = (store.lids >= 0) & cancel[lid_safe]
    return store._replace(
        lids=jnp.where(dead, -1, store.lids),
        notified=store.notified & ~cancel,
        nseqs=jnp.where(cancel, 0, store.nseqs),
        nvals=jnp.where(cancel, 0, store.nvals),
        npayload=jnp.where(cancel[:, None], 0, store.npayload),
        nsizes=jnp.where(cancel, 0, store.nsizes))


@jax.jit
def ack_listeners(store: SwarmStore, reg_ids: jax.Array) -> SwarmStore:
    """Reader ack: consume the delivery slots of ``reg_ids [P]`` —
    reset ``notified`` and the ``nseqs`` watermark (and the delivered
    value/bytes) so the NEXT accepted announce of a listened-for key
    re-delivers.  This is what makes the pub/sub path observe the
    second and third change instead of firing once: without an ack the
    slots keep freshest-wins semantics (the value updates in place),
    with acks each change is a distinct consumable event.  After an
    ack even a same-seq re-announce (or a stale replica's republish)
    re-fires — matching the reference, where every ``storageChanged``
    pushes and clients dedup by value id."""
    ml = store.notified.shape[0]
    safe = jnp.where((reg_ids >= 0) & (reg_ids < ml), reg_ids, ml)
    ack = jnp.zeros((ml,), bool).at[safe].set(True, mode="drop")
    return store._replace(
        notified=store.notified & ~ack,
        nseqs=jnp.where(ack, 0, store.nseqs),
        nvals=jnp.where(ack, 0, store.nvals),
        npayload=jnp.where(ack[:, None], 0, store.npayload),
        nsizes=jnp.where(ack, 0, store.nsizes))


@partial(jax.jit, static_argnames=("scfg",))
def expire(store: SwarmStore, scfg: StoreConfig, now) -> SwarmStore:
    """TTL sweep (``Storage::expire``, src/dht.cpp:2361-2381).

    Per-value TTLs (set at announce — the per-ValueType expiration)
    take precedence; values with ttl 0 fall back to ``scfg.ttl``; when
    both are 0 the value is permanent.
    """
    age = jnp.uint32(now) - store.created
    eff = jnp.where(store.ttls > 0, store.ttls, jnp.uint32(scfg.ttl))
    return store._replace(used=store.used & ((eff == 0) | (age <= eff)))


@partial(jax.jit, static_argnames=("cfg", "scfg"))
def _repub_extract(alive: jax.Array, store: SwarmStore,
                   node_idx: jax.Array, cfg: SwarmConfig,
                   scfg: StoreConfig):
    """Store-row extract phase of a republish sweep, as ONE compiled
    program: eager clip/compare/gather with Python-int bounds uploads
    a scalar per op (forbidden by graftlint's strict transfer-guard
    replay); jitted, the constants fold into the executable."""
    s = scfg.slots
    n_safe = jnp.clip(node_idx, 0, cfg.n_nodes - 1)
    ok = (node_idx >= 0)[:, None] & alive[n_safe][:, None] \
        & store.used[n_safe]                               # [M,S]
    vals = store.vals[n_safe].reshape(-1)
    seqs = store.seqs[n_safe].reshape(-1)
    sizes = store.sizes[n_safe].reshape(-1)
    ttls = store.ttls[n_safe].reshape(-1)
    m_rows = node_idx.shape[0] * s
    rows = (n_safe[:, None] * s
            + jnp.arange(s, dtype=jnp.int32)[None, :]).reshape(-1)
    keys = _key_rows(store.keys, rows)                   # [M·S, 5]
    w = scfg.payload_words
    if w:
        payloads = _pl_gather(store.payload, rows, w)
    else:
        payloads = jnp.zeros((m_rows, 0), jnp.uint32)
    return keys, vals, seqs, sizes, ttls, payloads, ok.reshape(-1)


@jax.jit
def _mask_unowned(okf: jax.Array, found: jax.Array) -> jax.Array:
    """Blank the lookup heads of rows whose slot is empty/dead (the
    ``-1`` sentinel folds as a program constant, not a per-sweep
    upload)."""
    return jnp.where(okf[:, None], found, -1)


def pow2_width(m: int, floor: int) -> int:
    """Smallest power of two ≥ ``max(m, floor)`` — compacted batch
    widths round up to a pow2 rung so the number of jit
    specializations of the downstream lookup/insert programs stays at
    ~log2 of the largest batch (the republish sweep compaction and the
    index engine's probe/put padding share this rule)."""
    return max(floor, 1 << max(0, (m - 1)).bit_length())


# Smallest compacted maintenance width: lets a near-empty store sweep
# at trivial width without minting single-digit-width programs.
_REPUB_COMPACT_FLOOR = 256


@partial(jax.jit, static_argnames=("cfg", "scfg"))
def _repub_live(alive: jax.Array, store: SwarmStore,
                node_idx: jax.Array, cfg: SwarmConfig,
                scfg: StoreConfig):
    """Live-first ordering of a maintenance batch — the cheap pre-pass
    the sweep compaction keys on (PR-6 ledger finding: the sweep
    priced the full ``N·slots`` lookup batch for ~32× fewer live
    values).  Returns ``(order [M·S] int32, n_live)``: a STABLE
    permutation of the flat (node, slot) rows with live rows (alive
    republisher & used slot) first."""
    n_safe = jnp.clip(node_idx, 0, cfg.n_nodes - 1)
    ok = ((node_idx >= 0)[:, None] & alive[n_safe][:, None]
          & store.used[n_safe]).reshape(-1)
    order = jnp.argsort(~ok, stable=True).astype(jnp.int32)
    return order, jnp.sum(ok.astype(jnp.int32))


@partial(jax.jit, static_argnames=("cfg", "scfg"))
def _repub_extract_rows(alive: jax.Array, store: SwarmStore,
                        node_idx: jax.Array, rows: jax.Array,
                        cfg: SwarmConfig, scfg: StoreConfig):
    """Store-row extract for a COMPACTED maintenance subset: ``rows
    [W]`` indexes the flat ``[M·slots]`` batch of ``node_idx``.  Same
    outputs as :func:`_repub_extract`, at width W."""
    s = scfg.slots
    node = node_idx[jnp.clip(rows // s, 0, node_idx.shape[0] - 1)]
    n_safe = jnp.clip(node, 0, cfg.n_nodes - 1)
    slot = rows % s
    ok = (node >= 0) & alive[n_safe] & store.used[n_safe, slot]
    srow = n_safe * s + slot
    keys = _key_rows(store.keys, srow)
    vals = store.vals[n_safe, slot]
    seqs = store.seqs[n_safe, slot]
    sizes = store.sizes[n_safe, slot]
    ttls = store.ttls[n_safe, slot]
    w = scfg.payload_words
    if w:
        payloads = _pl_gather(store.payload, srow, w)
    else:
        payloads = jnp.zeros((rows.shape[0], 0), jnp.uint32)
    return keys, vals, seqs, sizes, ttls, payloads, ok


@partial(jax.jit, static_argnames=("m",))
def _repub_writeback(rows: jax.Array, replicas: jax.Array,
                     hops: jax.Array, done: jax.Array, m: int):
    """Scatter a compacted sweep's per-row report back to the full
    ``[M·slots]`` batch shape (callers see the same report layout
    compacted or not).  Unselected rows are dead/empty: 0 replicas,
    0 hops, trivially done."""
    rep = jnp.zeros((m,), replicas.dtype).at[rows].set(replicas)
    hp = jnp.zeros((m,), hops.dtype).at[rows].set(hops)
    dn = jnp.ones((m,), bool).at[rows].set(done)
    return rep, hp, dn


def republish_from(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                   scfg: StoreConfig, node_idx: jax.Array, now,
                   rng: jax.Array, drop_frac: float = 0.0,
                   drop_key: jax.Array | None = None,
                   stats: dict | None = None,
                   compact: bool = True
                   ) -> Tuple[SwarmStore, AnnounceReport]:
    """Chosen nodes re-announce every value they hold — the storage
    maintenance that restores replication after churn
    (``Dht::dataPersistence``, /root/reference/src/dht.cpp:2887-2947).

    ``node_idx [M]``: republishing nodes (use alive survivors).  Their
    ``M*slots`` stored values become one announce batch (unused slots
    are masked out by announcing to no one via key of an impossible
    put row — we simply reuse ``announce`` with masked lookups).
    ``drop_frac``/``drop_key`` inject maintenance-RPC loss
    (:func:`drop_exchanges`) — the chaos harness's knob for proving
    survival degrades gracefully, not catastrophically.

    ``stats`` with ``time_phases`` set splits the sweep's wall into
    ``extract_s`` (store-row gathers → the announce batch),
    ``lookup_s`` (the per-value lookup phase), ``insert_s`` (the
    store-insert scatter program) and ``sweep_total_s``, with a
    ``block_until_ready`` barrier between phases — the cost ledger's
    repub-profile attribution (same contract as ``lookup``'s
    ``stats["time_phases"]``: the barriers de-pipeline the device
    queue, so attribution passes are SEPARATE from timed sweeps).

    ``compact`` (default on — the PR-6 ledger finding's fix): gather
    the LIVE maintenance rows into a dense power-of-two prefix BEFORE
    the lookup phase, so a sweep prices ~n_live lookups instead of
    the full ``M·slots`` batch (the r06 profile paid the full batch
    for 32× fewer live values).  The per-row report is scattered back
    to the full batch shape, so callers see identical layout either
    way; the extract phase window absorbs the compaction (one
    live-count readback per sweep).  ``compact=False`` keeps the
    full-width sweep for A/B.
    """
    timing = bool(stats) and stats.get("time_phases")
    t0 = time.perf_counter() if timing else 0.0
    m = node_idx.shape[0] * scfg.slots
    rows = None
    if compact:
        order, nlive_d = _repub_live(swarm.alive, store, node_idx,
                                     cfg, scfg)
        n_live = int(jax.device_get(nlive_d))
        wdt = min(m, pow2_width(n_live, _REPUB_COMPACT_FLOOR))
        if wdt < m:
            rows = order[:wdt]
    if rows is not None:
        keys, vals, seqs, sizes, ttls, payloads, okf = \
            _repub_extract_rows(swarm.alive, store, node_idx, rows,
                                cfg, scfg)
    else:
        keys, vals, seqs, sizes, ttls, payloads, okf = _repub_extract(
            swarm.alive, store, node_idx, cfg, scfg)
    if timing:
        jax.block_until_ready((keys, vals, seqs, payloads, okf))
        t1 = time.perf_counter()
        stats["extract_s"] = t1 - t0
    res = lookup(swarm, cfg, keys, rng)
    if timing:
        jax.block_until_ready(res)
        t2 = time.perf_counter()
        stats["lookup_s"] = t2 - t1
    found = _mask_unowned(okf, res.found)
    found = drop_exchanges(found, drop_frac, drop_key)
    store, replicas, trace = _announce_insert(swarm.alive, cfg, store,
                                              scfg, found, keys, vals,
                                              seqs, dev_u32(now),
                                              sizes, ttls, payloads)
    hops, done = res.hops, res.done
    if rows is not None:
        replicas, hops, done = _repub_writeback(rows, replicas, hops,
                                                done, m)
    if timing:
        jax.block_until_ready((store, replicas))
        t3 = time.perf_counter()
        stats["insert_s"] = t3 - t2
        stats["sweep_total_s"] = t3 - t0
    if stats is not None:
        stats["lookup_rows"] = int(keys.shape[0])
        stats["batch_rows"] = m
    return store, AnnounceReport(replicas=replicas, hops=hops,
                                 done=done, trace=trace)
