"""Swarm-scale models: the TPU-resident Kademlia simulation engine."""

from .swarm import (  # noqa: F401
    LookupFaults,
    LookupResult,
    LookupState,
    Swarm,
    SwarmConfig,
    build_swarm,
    chaos_lookup,
    churn,
    corrupt_swarm,
    heal_swarm,
    honest_recall,
    lookup,
    lookup_init,
    lookup_recall,
    lookup_step,
    true_closest,
)
