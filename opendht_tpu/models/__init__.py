"""Swarm-scale models: the TPU-resident Kademlia simulation engine."""

from .swarm import (  # noqa: F401
    LookupResult,
    LookupState,
    Swarm,
    SwarmConfig,
    build_swarm,
    churn,
    heal_swarm,
    lookup,
    lookup_init,
    lookup_recall,
    lookup_step,
    true_closest,
)
