"""Always-on node soak: one engine serving, maintaining and monitoring.

Every capability before this module is a separate closed bench mode —
serve (PR 7), republish maintenance (PR 10's compacted sweep), the
monitor's incremental crawl (PR 8).  A real node runs them ALL AT ONCE:
the reference's time-ordered scheduler loop
(include/opendht/scheduler.h:38-123) interleaves listen refreshes,
storage republish and maintenance jobs between answering queries, and
the number that matters is what that interleaving does to the serve
tail.  This module is the device twin of that loop, built so the
interference is MEASURED, not guessed: the PR-10 compacted republish
sweep is 5.73 s standalone at the r06 profile config — interleaved
into free serve slots it should cost milliseconds per burst, and the
unified timeline (``obs.timeline``) is where that claim is checked.

Architecture — the :class:`SoakEngine` wraps the PR-7 slot-recycled
:class:`~opendht_tpu.models.serve.ServeEngine` and adds:

* **a per-slot work-class plane** — a resident ``[C] int32`` array
  tagging every slot's occupant class (read / write / republish /
  monitor), maintained by the same mechanism as the lifecycle plane:
  donated scatters at admission (:func:`_scatter_wclass`,
  :func:`_admit_maintenance`), one fused per-burst readback
  (:func:`_soak_snapshot`) returning per-class ACTIVE slot counts next
  to the serve harvest.  The plane is what lets the timeline split
  slot-rounds serve-vs-maintenance per interval, and lets the checker
  hold the device's view against the host's slot bookkeeping (a
  mismatch fails the artifact).
* **maintenance micro-batching** — a republish sweep no longer calls
  the closed-loop ``lookup`` on its whole compacted batch: the sweep's
  live rows are extracted once (the PR-10 ``_repub_live`` /
  ``_repub_extract_rows`` compaction, verbatim), then admitted into
  FREE serve slots a micro-batch at a time, strictly AFTER queued
  serve requests.  Completed rows INSERT at their harvest, a
  micro-batch at a time (:func:`_repub_insert_completed` — the
  one-shot sweep-close insert was the measured residual stall on the
  serve tail), with replica stats accumulating on device; the sweep
  close is pure bookkeeping.  Monitor sweeps ride the same admission
  path with a device-side sighting buffer instead
  (:func:`_fold_completed`, the interleaved sweep fold):
  ``MonitorEngine.begin_sweep`` picks the stale buckets, probes run
  through serve slots, and ``finish_sweep`` folds the buffer with its
  conservation identities intact.  Listener-refresh
  and TTL expiry are slot-free single-program store sweeps, run on
  their own cadence and booked (with walls) as maintenance ops.
* **a scenario engine** — churn, routing-table heal and a contiguous
  keyspace outage injected DURING serving by wall-clock events
  (:class:`ScenarioEvent`), with ground-truth kills recorded through
  the monitor's kill ledger so detection lag stays measurable against
  the PR-8 scheduler bound.

The loop is clock-injectable end to end (``clock``/``sleep``), and its
maintenance-off path is BIT-identical to
:func:`~opendht_tpu.models.serve.serve_open_loop` on the same schedule
— same admissions, same marks arithmetic, same latency samples —
asserted in ``tests/test_soak.py``: the soak wrapper is provably a
pure superset of the serve engine.
"""

from __future__ import annotations

import time
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.xor_metric import N_LIMBS
from ..utils.hostdevice import dev_i32, dev_u32
from . import storage as _storage
from .monitor import MonitorEngine, kill_node_range
from .serve import (
    ServeEngine,
    ServeOverloadError,
    _ring_enqueue,
    _scatter_rows_into,
    poisson_zipf_events,
    warm_serve_engine,
)
from .swarm import (
    Swarm,
    SwarmConfig,
    _finalize,
    _local_respond,
    _sample_origins,
    churn,
    heal_swarm,
    init_impl,
)

# Work classes of the per-slot plane.  READ/WRITE are the serve side
# (open-loop client requests); REPUB/MONITOR are the maintenance side
# (republish rows and crawl probes admitted into free slots).  Index
# range scans ride the arrival stream too but execute through the trie
# engine, not through slots — they have their own lifecycle counters.
WC_READ = 0
WC_WRITE = 1
WC_REPUB = 2
WC_MONITOR = 3
N_WORK_CLASSES = 4
WORK_CLASS_NAMES = ("read", "write", "repub", "monitor")
SERVE_CLASSES = (WC_READ, WC_WRITE)
MAINT_CLASSES = (WC_REPUB, WC_MONITOR)


@partial(jax.jit, donate_argnums=(0,))
def _scatter_wclass(wc: jax.Array, slots: jax.Array,
                    cls: jax.Array) -> jax.Array:
    """Tag admitted slots with their work class — the plane twin of the
    admission scatter (slot sentinel ``C`` dropped, like every
    admission program).  ``cls`` is ``[A]`` (per-slot classes: one
    serve micro-batch can mix reads and writes) or scalar; the plane
    buffer is DONATED — single-owner like the serve carry."""
    return wc.at[slots].set(jnp.asarray(cls, jnp.int32), mode="drop")


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2, 3, 4))
def _admit_serve_cached(swarm: Swarm, cfg: SwarmConfig, st, wc, cache,
                        keys: jax.Array, slots: jax.Array,
                        cls: jax.Array, probe_ok: jax.Array,
                        origins: jax.Array, rnd: jax.Array):
    """Serve admission with the hot-key result cache probe FUSED in —
    the soak twin of ``serve._admit_cached`` plus the work-class tag
    (ROADMAP #1's soak follow-up: ``cache_slots`` was provisioning-only
    before this program existed).

    ``probe_ok [A]`` masks WHICH rows may consume a cache hit: only
    READ-class rows — a WRITE must always take a slot and run its
    lookup, because its completion fold reads the live state at its
    slot and its announce heads must reflect the current swarm, and a
    maintenance row is never admitted through this program at all.
    Hit rows redirect to the drop sentinel in BOTH scatters (state and
    work-class plane), so a hit occupies no slot and leaves no stale
    tag; misses scatter exactly like the plain path.  State, plane and
    cache are all DONATED (single-owner carries); the cache passes
    through unchanged — fills stay a harvest-side concern.
    Returns ``(st, wc, cache, hit [A], hit_found [A,q],
    hit_hops [A])``.
    """
    from .serve import _probe_impl
    c = st.done.shape[0]
    hit_raw, h_found, h_hops = _probe_impl(cache, keys)
    hit = hit_raw & probe_ok
    new = init_impl(swarm.ids, _local_respond(swarm, cfg), cfg, keys,
                    origins)
    eff = jnp.where(hit, jnp.int32(c), slots)
    st = _scatter_rows_into(st, new, eff, rnd)
    wc = wc.at[eff].set(jnp.asarray(cls, jnp.int32), mode="drop")
    return st, wc, cache, hit, h_found, h_hops


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2, 3))
def _admit_maintenance(swarm: Swarm, cfg: SwarmConfig, st, wc,
                       pool_keys: jax.Array, pool_idx: jax.Array,
                       slots: jax.Array, origins: jax.Array,
                       rnd: jax.Array, cls: jax.Array):
    """Admit one maintenance micro-batch into free serve slots.

    The maintenance twin of ``serve._admit``, fused with the
    work-class tag: ``pool_keys [W,5]`` is the sweep's resident key
    pool (republish rows' value keys, or the monitor sweep's bucket
    targets), ``pool_idx [A]`` the rows this micro-batch admits (pad
    ``-1`` — clipped for the gather, dropped by the slot sentinel),
    ``slots [A]`` the target slots (pad sentinel ``C``), ``cls`` the
    work class.  Keys never round-trip through the host: the gather
    happens HERE, against the pool that was extracted on device at
    sweep begin.  State and plane are both DONATED.
    """
    pkeys = pool_keys[jnp.clip(pool_idx, 0, pool_keys.shape[0] - 1)]
    new = init_impl(swarm.ids, _local_respond(swarm, cfg), cfg, pkeys,
                    origins)
    st = _scatter_rows_into(st, new, slots, rnd)
    wc = wc.at[slots].set(jnp.asarray(cls, jnp.int32), mode="drop")
    return st, wc


@partial(jax.jit, donate_argnums=(0,))
def _ring_enqueue_maintenance(rings, pool_keys: jax.Array,
                              pool_idx: jax.Array, n_new: jax.Array,
                              cls: jax.Array):
    """Enqueue one maintenance micro-batch into a RESIDENT engine's
    request ring (the round-20 twin of :func:`_admit_maintenance`):
    keys gather on device from the sweep's resident pool — exactly the
    ``_admit_maintenance`` gather, so maintenance keys still never
    round-trip through the host — and the request index is encoded as
    ``-2 - pool_idx`` so the harvest side can map a completion ring
    row back to its sweep position (client requests use indices
    ``>= 0``; ``-1`` stays the never-written sentinel).  The rings are
    DONATED; shed/backpressure semantics are the serve ring's
    (maintenance rows past the free space are counted and dropped —
    the sweep re-offers them next micro-batch)."""
    pkeys = pool_keys[jnp.clip(pool_idx, 0, pool_keys.shape[0] - 1)]
    reqs = jnp.int32(-2) - jnp.asarray(pool_idx, jnp.int32)
    cls_a = jnp.broadcast_to(jnp.asarray(cls, jnp.int32),
                             pool_idx.shape)
    return _ring_enqueue(rings, pkeys, reqs, cls_a, n_new)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def _fold_completed(buf: jax.Array, ids: jax.Array, st,
                    cfg: SwarmConfig, sl: jax.Array,
                    pos: jax.Array) -> jax.Array:
    """The interleaved sweep fold: scatter completed slots' finalized
    result heads into a sweep's device-side accumulation buffer.

    ``buf [W, quorum]`` (``-1`` init — an unfolded row reads as a
    probe that found nobody), ``sl [A]`` the harvested slots (pad
    ``0``, clipped — the matching ``pos`` sentinel drops the row),
    ``pos [A]`` each slot's row position within the sweep (pad
    sentinel ``W``).  The heads are recomputed from the LIVE state
    (the same ``_finalize`` the snapshot runs), so the sweep's data
    plane never round-trips through the host — a republish sweep's
    announce targets and a monitor sweep's sighting sets accumulate
    across bursts entirely on device.  The buffer is DONATED; the
    state is read-only (it stays resident in the loop).
    """
    fin = _finalize(ids, st, cfg)
    heads = fin[jnp.clip(sl, 0, st.done.shape[0] - 1)]
    return buf.at[pos].set(heads, mode="drop")


@partial(jax.jit, static_argnames=("cfg", "scfg"),
         donate_argnums=(4, 15))
def _repub_insert_completed(ids: jax.Array, alive: jax.Array,
                            cfg: SwarmConfig, scfg, store, st,
                            sl: jax.Array, pos: jax.Array,
                            pool_keys: jax.Array, vals: jax.Array,
                            seqs: jax.Array, sizes: jax.Array,
                            ttls: jax.Array, payloads: jax.Array,
                            okf: jax.Array, acc: jax.Array,
                            now: jax.Array):
    """The republish half of the interleaved sweep fold: INSERT a
    harvested micro-batch of completed republish rows straight into
    the store, instead of accumulating them for one stop-the-world
    insert at sweep close.

    The one-shot close insert was the measured residual interference
    (a ~sweep-wide ``_announce_insert`` lands as one multi-hundred-ms
    stall on the serve tail); this program is its micro-batch twin:
    ``sl [A]`` harvested slots / ``pos [A]`` sweep row positions (pad
    sentinel ``W`` → masked), the announce heads recomputed from the
    live state (``_finalize``, as in :func:`_fold_completed`), the
    row's key/value/seq/ttl gathered from the sweep's device pools,
    and dead-slot rows masked exactly like ``_mask_unowned``.  The
    store and the ``[3]`` replica accumulator (count, sum, min over
    live rows) are DONATED; the replica stats surface at sweep close
    with zero extra syncs.
    """
    w = pool_keys.shape[0]
    fin = _finalize(ids, st, cfg)
    heads = fin[jnp.clip(sl, 0, st.done.shape[0] - 1)]     # [A,q]
    p_safe = jnp.clip(pos, 0, w - 1)
    ok = (pos >= 0) & (pos < w) & okf[p_safe]
    found = jnp.where(ok[:, None], heads, -1)
    keys = pool_keys[p_safe]
    store, rep, _trace = _storage._announce_insert(
        alive, cfg, store, scfg, found, keys, vals[p_safe],
        seqs[p_safe], now, sizes[p_safe], ttls[p_safe],
        payloads[p_safe])
    acc = jnp.stack([
        acc[0] + jnp.sum(ok.astype(jnp.int32)),
        acc[1] + jnp.sum(jnp.where(ok, rep, 0)),
        jnp.minimum(acc[2], jnp.min(jnp.where(ok, rep, 2 ** 30))),
    ])
    return store, acc


@partial(jax.jit, static_argnames=("cfg",))
def _soak_snapshot(swarm: Swarm, cfg: SwarmConfig, st, wc: jax.Array):
    """The soak harvest readback: the serve snapshot plus the
    work-class plane's per-class ACTIVE slot counts (not-done slots
    only — a free slot's stale tag is masked by ``done``).  The counts
    are the device's own occupancy-split testimony: the timeline books
    them against the host's slot bookkeeping and the checker fails any
    interval where the two disagree."""
    active = ~st.done
    cls_idx = jnp.where(active, wc, N_WORK_CLASSES)
    counts = jnp.zeros((N_WORK_CLASSES,), jnp.int32).at[cls_idx].add(
        1, mode="drop")
    return (st.done, st.hops, st.admitted_round, st.completed_round,
            _finalize(swarm.ids, st, cfg), counts)


class SoakConfig(NamedTuple):
    """Host-side soak policy (wall-clock cadences in seconds).

    * ``interval_s`` — timeline interval width (the unit of every
      per-interval row, conservation check and interference
      attribution);
    * ``repub_period_s`` — gap between the END of one republish sweep
      and the begin of the next (``Dht::dataPersistence`` runs on a
      timer; here the timer re-arms once the previous sweep drained);
    * ``monitor_gap_s`` — same, for monitor sweeps (0 = continuous
      crawling: a sweep begins as soon as the previous finishes);
    * ``listen_period_s`` — cadence of the slot-free store sweeps
      (listener refresh + TTL expiry), booked as maintenance ops;
    * ``maint_cap`` — maintenance rows admitted per loop iteration at
      most (into free slots only, after serve admission);
    * ``maint_slot_frac`` — hard ceiling on the fraction of slots
      maintenance may OCCUPY at once (the serve engine's admission
      reserve: serve requests admit first every iteration, and
      maintenance can never crowd the slot plane past this share —
      without it a continuous crawl saturates the slots and queueing
      delay books as serve tail latency);
    * ``write_flush`` — completed write requests batched per
      ``_announce_insert`` flush (also that program's compiled width);
    * ``scan_batch`` / ``scan_max_wait_s`` — scan-station batching:
      flush when this many scans are pending or the oldest has waited
      this long;
    * ``chunk_max_wait_s`` — chunked-station batching deadline: the
      station flushes when its compiled batch width fills (the
      ``ChunkedStation.batch`` knob) or the oldest pending chunked
      request has waited this long.
    """
    interval_s: float = 0.5
    repub_period_s: float = 1.0
    monitor_gap_s: float = 0.0
    listen_period_s: float = 1.0
    maint_cap: int = 256
    maint_slot_frac: float = 0.25
    write_flush: int = 256
    scan_batch: int = 16
    scan_max_wait_s: float = 0.25
    chunk_max_wait_s: float = 0.25


class _Sweep:
    """One in-flight maintenance sweep (host state machine).

    Rows live in ``keys_dev [total, 5]`` (device); ``cursor`` is the
    admission frontier (``cursor == admitted`` always — rows admit in
    pool order); ``buf [total, quorum]`` accumulates completed rows'
    result heads via :func:`_fold_completed` (monitor sweeps; repub
    sweeps insert incrementally via :func:`_repub_insert_completed`
    and carry no buffer).  The sweep closes when every row was
    admitted and retired (completed or expired)."""

    __slots__ = ("cls", "keys_dev", "total", "cursor", "buf",
                 "completed", "expired", "admitted", "began_t",
                 "meta", "hops", "done_rows")

    def __init__(self, cls: int, keys_dev, buf, began_t: float,
                 meta=None):
        self.cls = cls
        self.keys_dev = keys_dev
        self.total = int(keys_dev.shape[0])
        self.cursor = 0
        self.buf = buf
        self.completed = 0
        self.expired = 0
        self.admitted = 0
        self.began_t = began_t
        self.meta = meta or {}
        self.hops: list[int] = []
        self.done_rows: list[int] = []   # completed row positions

    @property
    def retired(self) -> int:
        return self.completed + self.expired

    @property
    def drained(self) -> bool:
        return self.cursor >= self.total \
            and self.retired >= self.admitted


class SoakEngine:
    """The always-on node: one resident serve state, one work-class
    plane, a value store under maintenance, and a monitor plane — all
    advanced by one host loop (:func:`soak_open_loop`).

    ``store``/``scfg`` arm the republish + listener maintenance (and
    the write-request flush path); ``monitor`` (a
    :class:`~opendht_tpu.models.monitor.MonitorEngine` built on the
    SAME swarm) arms the interleaved crawl; ``index`` (a
    ``models.index.DeviceIndex``) plus ``scan_key_fn`` (rank → index
    key dict) arm the scan station.  Any of them may be ``None`` —
    with all three off the engine degrades to exactly the PR-7 serve
    engine (the pure-superset equivalence ``tests/test_soak.py``
    pins).

    The engine OWNS its swarm: churn/heal/outage donate or replace
    swarm buffers, and the serve/monitor halves are re-pointed at the
    new pytree after every scenario event (:meth:`_sync_swarm`).
    """

    def __init__(self, swarm: Swarm, cfg: SwarmConfig, slots: int,
                 scfg=None, store=None,
                 monitor: Optional[MonitorEngine] = None,
                 index=None, scan_key_fn=None,
                 admit_cap: int | None = None,
                 soak_cfg: SoakConfig | None = None,
                 maint_key: jax.Array | None = None,
                 cache_slots: int = 0,
                 chunk_station=None):
        self.swarm, self.cfg = swarm, cfg
        # ``cache_slots`` arms the serve engine's hot-key result cache
        # AND the soak loop's probe-fused admission
        # (:func:`_admit_serve_cached`): READ-class requests that hit
        # complete at their admission wall without a slot or a
        # work-class tag, harvested read completions fill the cache,
        # and the write-flush store insert bumps the epoch (the
        # announce-side invalidation).  0 (default) keeps the engine
        # byte-identical to the pre-cache one (the pure-overlay /
        # serve-bit-identity contract in tests/test_soak.py).
        self.serve = ServeEngine(swarm, cfg, slots,
                                 admit_cap=admit_cap,
                                 cache_slots=cache_slots)
        self.scfg, self.store = scfg, store
        self.mon = monitor
        self.index = index
        self.scan_key_fn = scan_key_fn
        # A ``models.serve.ChunkedStation`` arms the chunked request
        # class ("chunk"/"chunkw" ops): multi-part values served
        # through the chunked engine against this engine's store.
        self.chunk = chunk_station
        self.soak_cfg = soak_cfg or SoakConfig()
        self.maint_key = (maint_key if maint_key is not None
                          else jax.random.PRNGKey(0x50AC))
        self.wc = jnp.zeros((slots,), jnp.int32)
        self._madm_i = 0
        self._warmed_admit: set[int] = set()
        self._warmed_fold: set[int] = set()
        self._warmed_insert: set[int] = set()
        self._warmed_mon_finish: set[int] = set()
        self.repub_records: list[dict] = []
        self.maint_ops: list[dict] = []
        self.store_now = 1        # uint32 store clock (announce epochs)
        self._listen_active = None

    # ------------------------------------------------------------------
    # plumbing
    # ------------------------------------------------------------------

    def _sync_swarm(self, swarm: Swarm) -> None:
        self.swarm = swarm
        self.serve.swarm = swarm
        if self.mon is not None:
            self.mon.swarm = swarm

    def snapshot(self, st):
        return jax.device_get(
            _soak_snapshot(self.swarm, self.cfg, st, self.wc))

    def admit_serve(self, st, keys, slots, cls_np, key, rnd):
        """Serve-side admission.  Cache off: the UNMODIFIED serve
        admit (so the maintenance-off path stays bit-identical to the
        serve engine) plus one work-class scatter on the plane; hit
        info comes back ``None``.  Cache on: the probe-fused
        :func:`_admit_serve_cached` — READ rows that hit never occupy
        their slot or tag the plane, and ``(hit, hit_found, hit_hops)``
        come back as host arrays (the one small per-admission sync the
        cache-on loop pays, exactly like ``serve.admit_probed``)."""
        if self.serve.cache is None:
            st = self.serve.admit(st, keys, slots, key, rnd)
            self.wc = _scatter_wclass(self.wc, slots,
                                      jnp.asarray(cls_np, jnp.int32))
            return st, None, None, None
        origins = _sample_origins(key, self.swarm.alive, keys.shape[0])
        probe_ok = jnp.asarray(np.asarray(cls_np) == WC_READ)
        st, self.wc, self.serve.cache, hit, h_found, h_hops = \
            _admit_serve_cached(
                self.swarm, self.cfg, st, self.wc, self.serve.cache,
                keys, slots, jnp.asarray(cls_np, jnp.int32), probe_ok,
                origins, dev_i32(rnd))
        h, f, hp = jax.device_get((hit, h_found, h_hops))
        return st, h, f, hp

    def admit_maintenance(self, st, sweep: _Sweep, pool_idx_np,
                          slots_np, rnd):
        origins = _sample_origins(
            jax.random.fold_in(self.maint_key, self._madm_i),
            self.swarm.alive, self.serve.admit_cap)
        self._madm_i += 1
        st, self.wc = _admit_maintenance(
            self.swarm, self.cfg, st, self.wc, sweep.keys_dev,
            jnp.asarray(pool_idx_np), jnp.asarray(slots_np), origins,
            dev_i32(rnd), dev_i32(sweep.cls))
        return st

    def enqueue_maintenance(self, rings, sweep: _Sweep, pool_idx_np,
                            n: int):
        """Resident-loop maintenance admission: offer ``n`` sweep rows
        (``pool_idx_np``, padded to the admission width with ``-1``)
        to a resident engine's request ring.  The resident program
        itself pops them into free slots strictly behind earlier-
        queued serve traffic (ring FIFO order), so the burst loop's
        "maintenance only into leftover capacity" policy becomes a
        queue-position property instead of host bookkeeping.  Returns
        the donated-through rings; decode completions via
        ``pool_idx = -2 - comp_req`` for rows with ``comp_req <= -2``
        and class ``sweep.cls``."""
        return _ring_enqueue_maintenance(
            rings, sweep.keys_dev, jnp.asarray(pool_idx_np),
            dev_i32(n), dev_i32(sweep.cls))

    def fold_completed(self, sweep: _Sweep, st, sl_np, pos_np):
        sweep.buf = _fold_completed(
            sweep.buf, self.swarm.ids, st, self.cfg,
            jnp.asarray(sl_np), jnp.asarray(pos_np))

    def insert_completed(self, sweep: _Sweep, st, sl_np, pos_np):
        """Micro-batch republish insert at harvest (the repub half of
        the interleaved fold — store and replica accumulator donated
        through)."""
        meta = sweep.meta
        self.store, meta["acc"] = _repub_insert_completed(
            self.swarm.ids, self.swarm.alive, self.cfg, self.scfg,
            self.store, st, jnp.asarray(sl_np), jnp.asarray(pos_np),
            sweep.keys_dev, meta["vals"], meta["seqs"], meta["sizes"],
            meta["ttls"], meta["payloads"], meta["okf"], meta["acc"],
            meta["now_u"])

    def warm_sweep_width(self, st, width: int) -> None:
        """Compile the admission/fold programs for a sweep width at
        sweep BEGIN (off the burst marks): a fresh jit inside a burst
        clock would book as serve tail latency and be attributed to
        the wrong cause.  Throwaway operands; the resident state is
        never touched.  Sweep widths are power-of-two rungs (the
        republish compaction and the bucket scheduler both round up),
        so the specialization count stays logarithmic."""
        c, a_cap = self.serve.slots, self.serve.admit_cap
        if width not in self._warmed_admit:
            tmp = self.serve.empty()
            twc = jnp.zeros((c,), jnp.int32)
            pool = jnp.zeros((width, N_LIMBS), jnp.uint32)
            _admit_maintenance(
                self.swarm, self.cfg, tmp, twc, pool,
                jnp.full((a_cap,), -1, jnp.int32),
                jnp.full((a_cap,), c, jnp.int32),
                _sample_origins(self.maint_key, self.swarm.alive,
                                a_cap),
                dev_i32(0), dev_i32(WC_REPUB))
            self._warmed_admit.add(width)
        if width not in self._warmed_fold:
            _fold_completed(
                jnp.full((width, self.cfg.quorum), -1, jnp.int32),
                self.swarm.ids, st, self.cfg,
                jnp.zeros((a_cap,), jnp.int32),
                jnp.full((a_cap,), width, jnp.int32))
            self._warmed_fold.add(width)

    def warm(self, st) -> None:
        """Compile the fixed-width soak programs off the clock (the
        per-sweep-width programs warm at sweep begin)."""
        c, a_cap = self.serve.slots, self.serve.admit_cap
        self.wc = _scatter_wclass(
            self.wc, jnp.full((a_cap,), c, jnp.int32),
            jnp.zeros((a_cap,), jnp.int32))
        if self.serve.cache is not None:
            # Probe-fused soak admission: all-sentinel slots write
            # nothing, probe_ok all-False hits nothing — the program
            # compiles, the cache passes through untouched (the
            # cache-cold warm contract warm_serve_engine's fill warm
            # also keeps).
            tmp = self.serve.empty()
            twc = jnp.zeros((c,), jnp.int32)
            tmp, twc, self.serve.cache, _h, _f, _hp = \
                _admit_serve_cached(
                    self.swarm, self.cfg, tmp, twc, self.serve.cache,
                    jnp.zeros((a_cap, N_LIMBS), jnp.uint32),
                    jnp.full((a_cap,), c, jnp.int32),
                    jnp.zeros((a_cap,), jnp.int32),
                    jnp.zeros((a_cap,), bool),
                    _sample_origins(self.maint_key, self.swarm.alive,
                                    a_cap),
                    dev_i32(0))
            jax.device_get((_h, _f, _hp))
        self.snapshot(st)

    def warm_repub_insert(self, st, width: int) -> None:
        """Compile the micro-batch republish insert at a sweep pool
        width with a fully-masked batch (every ``pos`` is the pad
        sentinel → announce to nobody: store content untouched, only
        the donated buffers turn over)."""
        if width in self._warmed_insert:
            return
        cfg, scfg = self.cfg, self.scfg
        a_cap = self.serve.admit_cap
        z32 = jnp.zeros((width,), jnp.uint32)
        self.store, _acc = _repub_insert_completed(
            self.swarm.ids, self.swarm.alive, cfg, scfg, self.store,
            st, jnp.zeros((a_cap,), jnp.int32),
            jnp.full((a_cap,), width, jnp.int32),
            jnp.zeros((width, N_LIMBS), jnp.uint32), z32, z32, z32,
            z32, jnp.zeros((width, scfg.payload_words), jnp.uint32),
            jnp.zeros((width,), bool),
            jnp.asarray([0, 0, 2 ** 30], jnp.int32),
            dev_u32(self.store_now))
        self._warmed_insert.add(width)

    def warm_monitor_finish(self, width: int) -> None:
        """Compile the sweep-close fold at a sweep width against a
        THROWAWAY freshness state (the donated operand), so the first
        on-clock ``finish_sweep`` of that width runs pre-compiled."""
        if width in self._warmed_mon_finish or self.mon is None:
            return
        from .monitor import empty_freshness, fold_sweep
        n = self.cfg.n_nodes
        dummy = empty_freshness(n)
        fold_sweep(dummy,
                   jnp.full((width, self.cfg.quorum), -1, jnp.int32),
                   jnp.zeros((self.mon.n_buckets,), bool),
                   self.swarm.ids[:, 0], dev_i32(0), self.swarm.alive,
                   self.mon.kill_sweep, self.mon.mcfg)
        self._warmed_mon_finish.add(width)

    # ------------------------------------------------------------------
    # republish sweeps (maintenance work class)
    # ------------------------------------------------------------------

    def begin_repub_sweep(self, st, t: float) -> Optional[_Sweep]:
        """Open a republish sweep: the PR-10 compacted extract, kept on
        device as the sweep's admission pool.  Returns ``None`` when
        the store holds no live rows (nothing to maintain)."""
        cfg, scfg = self.cfg, self.scfg
        node_idx = jnp.arange(cfg.n_nodes, dtype=jnp.int32)
        m = cfg.n_nodes * scfg.slots
        order, nlive_d = _storage._repub_live(
            self.swarm.alive, self.store, node_idx, cfg, scfg)
        n_live = int(jax.device_get(nlive_d))
        if n_live == 0:
            return None
        wdt = min(m, _storage.pow2_width(
            n_live, _storage._REPUB_COMPACT_FLOOR))
        if wdt < m:
            keys, vals, seqs, sizes, ttls, payloads, okf = \
                _storage._repub_extract_rows(
                    self.swarm.alive, self.store, node_idx,
                    order[:wdt], cfg, scfg)
        else:
            keys, vals, seqs, sizes, ttls, payloads, okf = \
                _storage._repub_extract(
                    self.swarm.alive, self.store, node_idx, cfg, scfg)
        w = int(keys.shape[0])
        self.warm_sweep_width(st, w)
        self.warm_repub_insert(st, w)
        now_u = dev_u32(self.store_now)
        self.store_now += 1
        return _Sweep(WC_REPUB, keys, None, t,
                      meta={"vals": vals, "seqs": seqs, "sizes": sizes,
                            "ttls": ttls, "payloads": payloads,
                            "okf": okf, "n_live": n_live,
                            "batch_rows": m, "now_u": now_u,
                            "acc": jnp.asarray([0, 0, 2 ** 30],
                                               jnp.int32)})

    def finish_repub_sweep(self, sw: _Sweep, t: float) -> dict:
        """Close a republish sweep: every completed row already
        inserted at its harvest (``_repub_insert_completed``), so the
        close is pure bookkeeping — one readback of the replica
        accumulator."""
        meta = sw.meta
        n_rep, rep_sum, rep_min = (
            int(v) for v in jax.device_get(meta["acc"]))
        rec = {
            "began_t": round(sw.began_t, 4),
            "finished_t": round(t, 4),
            "rows": sw.total,
            "live_rows": meta["n_live"],
            "batch_rows": meta["batch_rows"],
            "admitted": sw.admitted,
            "completed": sw.completed,
            "expired": sw.expired,
            "in_flight": sw.admitted - sw.completed - sw.expired,
            "replicas_mean": round(
                float(rep_sum) / max(1, int(n_rep)), 3),
            "replicas_min": int(rep_min) if int(n_rep) else None,
        }
        self.repub_records.append(rec)
        return rec

    # ------------------------------------------------------------------
    # monitor sweeps (monitor work class)
    # ------------------------------------------------------------------

    def begin_monitor_sweep(self, st, t: float) -> _Sweep:
        buckets, targets = self.mon.begin_sweep()
        w = int(targets.shape[0])
        self.warm_sweep_width(st, w)
        self.warm_monitor_finish(w)
        return _Sweep(WC_MONITOR, targets,
                      jnp.full((w, self.cfg.quorum), -1, jnp.int32),
                      t, meta={"buckets": np.asarray(buckets)})

    def finish_monitor_sweep(self, sw: _Sweep, t: float) -> dict:
        """Close a monitor sweep: fold the accumulated sighting buffer.
        Only COMPLETED probes' buckets count as probed — an expired
        probe must not strike the nodes it never reached."""
        buckets = sw.meta["buckets"]
        probed = buckets[np.asarray(sorted(sw.done_rows), np.int64)] \
            if sw.done_rows else np.zeros((0,), np.int64)
        rec = self.mon.finish_sweep(
            sw.buf, probed,
            done_frac=sw.completed / max(1, sw.total),
            hops=np.asarray(sw.hops, np.int64) if sw.hops else None)
        rec["began_t"] = round(sw.began_t, 4)
        rec["finished_t"] = round(t, 4)
        rec["probes"] = sw.total
        rec["admitted_probes"] = sw.admitted
        rec["expired_probes"] = sw.expired
        rec["in_flight_probes"] = \
            sw.admitted - sw.completed - sw.expired
        return rec

    # ------------------------------------------------------------------
    # slot-free maintenance ops (listener refresh / TTL expiry)
    # ------------------------------------------------------------------

    def run_store_sweeps(self, t: float, clock,
                         book: bool = True) -> dict:
        """The reference's periodic jobs with no lookup phase: listener
        TTL refresh/expiry and value TTL expiry — single store-wide
        programs, booked with their walls as maintenance ops
        (``book=False`` = the pre-clock compile warm)."""
        t0 = clock()
        if self._listen_active is None:
            # The soak node keeps every registration alive (the ~30 s
            # keepalive of Dht::listenTo); a churn model for listener
            # OWNERS would thread a real mask here.
            self._listen_active = jnp.ones(
                (self.scfg.max_listeners,), bool)
        self.store = _storage.refresh_listeners(
            self.store, self.scfg, self._listen_active,
            self.store_now)
        self.store = _storage.expire_listeners(self.store, self.scfg,
                                               self.store_now)
        self.store = _storage.expire(self.store, self.scfg,
                                     self.store_now)
        jax.block_until_ready(self.store.used)
        rec = {"op": "listen-refresh+expire", "t": round(t, 4),
               "wall_s": round(clock() - t0, 6)}
        if book:
            self.maint_ops.append(rec)
        return rec


def mixed_events(rate: float, duration: float, key_pool: int,
                 zipf_s: float, seed: int = 0, hot_frac: float = 0.01,
                 write_frac: float = 0.0, scan_frac: float = 0.0,
                 scan_span: int = 64, chunk_frac: float = 0.0,
                 chunk_write_frac: float = 0.25):
    """The soak arrival schedule: :func:`poisson_zipf_events` plus an
    op class per request (read / write / scan / chunk) and rank
    windows for the scans.

    Returns ``(arrival_ts [R], keys [R,5], klass [R] hot/cold,
    ops [R] read/write/scan/chunk/chunkw, scan_lo [R], scan_hi [R])``.
    Scan windows ride the same Zipf popularity as the keys (hot ranks
    get scanned more — the arXiv:1009.3681 read-heavy shape); rows
    whose op is not ``scan`` carry unused windows.  ``chunk_frac`` of
    requests are CHUNKED (multi-part value) ops, of which
    ``chunk_write_frac`` are writes (same-bytes seq-bump refreshes —
    ``"chunkw"``) and the rest reassembling reads (``"chunk"``); the
    chunked station maps the Zipf draw in ``scan_lo`` onto its value
    pool.
    """
    if not 0.0 <= write_frac <= 1.0 or not 0.0 <= scan_frac <= 1.0 \
            or not 0.0 <= chunk_frac <= 1.0 \
            or write_frac + scan_frac + chunk_frac > 1.0:
        raise ValueError(
            f"scenario-mix fractions must be in [0, 1] with "
            f"write + scan + chunk <= 1, got write={write_frac} "
            f"scan={scan_frac} chunk={chunk_frac}")
    if not 0.0 <= chunk_write_frac <= 1.0:
        raise ValueError(f"chunk_write_frac must be in [0, 1], got "
                         f"{chunk_write_frac}")
    ts, keys, klass, draw = poisson_zipf_events(
        rate, duration, key_pool, zipf_s, seed=seed,
        hot_frac=hot_frac, return_draw=True)
    r = len(ts)
    rng = np.random.default_rng(seed ^ 0x50AC)
    u = rng.random(r)
    cw = scan_frac + chunk_frac * chunk_write_frac
    cr = scan_frac + chunk_frac
    ops = np.where(
        u < scan_frac, "scan",
        np.where(u < cw, "chunkw",
                 np.where(u < cr, "chunk",
                          np.where(u < cr + write_frac, "write",
                                   "read"))))
    scan_lo = np.minimum(draw, key_pool - 1).astype(np.int64)
    scan_hi = np.minimum(scan_lo + scan_span - 1, key_pool - 1)
    return ts, keys, klass, ops, scan_lo, scan_hi


class ScenarioEvent(NamedTuple):
    """One scheduled fault: at wall second ``t`` (on the soak clock),
    ``kind`` in ``{"churn", "outage"}`` kills ``frac`` of the
    population — churn uniformly, outage as ONE contiguous sorted-id
    range at the keyspace midpoint (the PR-8 localized outage, here
    injected DURING serving).  Every event is followed by a routing
    heal (the chaos-harness convention), and ground truth lands in the
    monitor's kill ledger so detection lag stays measurable."""
    t: float
    kind: str
    frac: float


def _apply_event(soak: SoakEngine, ev: ScenarioEvent,
                 ev_i: int) -> None:
    cfg = soak.cfg
    k_ev = jax.random.fold_in(soak.maint_key, 7000 + ev_i)
    if ev.kind == "churn":
        if soak.mon is not None:
            soak.mon.kill(ev.frac, k_ev)
            soak._sync_swarm(soak.mon.swarm)
        else:
            soak._sync_swarm(churn(soak.swarm, k_ev, ev.frac, cfg))
    elif ev.kind == "outage":
        n0 = cfg.n_nodes // 2
        hi_n = n0 + int(cfg.n_nodes * ev.frac)
        if soak.mon is not None:
            soak.mon.kill_range(n0, hi_n)
            soak._sync_swarm(soak.mon.swarm)
        else:
            soak._sync_swarm(kill_node_range(
                soak.swarm, jnp.int32(n0), jnp.int32(hi_n), cfg))
    else:
        raise ValueError(f"unknown scenario event kind {ev.kind!r}")
    k_heal = jax.random.fold_in(soak.maint_key, 8000 + ev_i)
    if soak.mon is not None:
        soak.mon.heal(k_heal)
        soak._sync_swarm(soak.mon.swarm)
    else:
        soak._sync_swarm(heal_swarm(soak.swarm, cfg, k_heal))


def soak_open_loop(soak: SoakEngine, arrival_ts, keys, key,
                   klass=None, ops=None, scan_lo=None, scan_hi=None,
                   burst: int = 2, duration: float | None = None,
                   overload_queue_factor: int = 8,
                   drain_round_cap: int | None = None,
                   maintenance: bool = True,
                   scenario: tuple = (),
                   timeline=None,
                   latency_plane=None,
                   clock=None, sleep=None) -> dict:
    """Drive the soak engine against an open-loop arrival schedule.

    The serve half of this loop is :func:`serve_open_loop`'s body —
    same admission policy, same burst/harvest cadence, same marks
    arithmetic, same expiry and overload contracts — so with
    ``maintenance=False``, no monitor, no scans and an empty scenario
    it produces BIT-identical results (``tests/test_soak.py``).  On
    top of it:

    * queued serve requests admit FIRST; remaining free slots take
      maintenance micro-batches (monitor probes before republish rows
      — detection lag is the bounded quantity), capped at
      ``soak_cfg.maint_cap`` per iteration;
    * ``scenario`` events (churn / outage + heal) fire by wall time at
      the iteration top;
    * ``timeline`` (an ``obs.timeline.SoakTimeline``) books every
      burst, admission, completion and maintenance op into
      per-wall-interval rows; ``latency_plane`` observes serve (and
      scan) completions with an ``op`` label;
    * ``maintenance=False`` is the interference A/B's off-arm: writes,
      scans and the scenario still run (they are serve work and
      environment), only republish/monitor/listener maintenance is
      withheld;
    * after the schedule drains, in-flight sweeps drain too (no new
      sweeps begin), then close with partial folds — unadmitted rows
      were never dispatched, so every conservation identity holds.

    Returns the serve report (superset of ``serve_open_loop``'s keys)
    plus per-class lifecycle counters, sweep records and scan-station
    stats.
    """
    clock = clock or time.perf_counter
    sleep = sleep or time.sleep
    engine = soak.serve
    scfg_soak = soak.soak_cfg
    cfg, c = engine.cfg, engine.slots
    a_cap = engine.admit_cap
    keys = np.asarray(keys)
    r_total = len(arrival_ts)
    if klass is None:
        klass = np.full(r_total, "all")
    if ops is None:
        ops = np.full(r_total, "read")
    ops = np.asarray(ops)
    if "write" in ops and soak.store is None:
        raise ValueError("write requests need a store (scfg/store on "
                         "the SoakEngine)")
    if "scan" in ops and (soak.index is None
                          or soak.scan_key_fn is None):
        raise ValueError("scan requests need an index + scan_key_fn "
                         "on the SoakEngine")
    has_chunk = ("chunk" in ops) or ("chunkw" in ops)
    if has_chunk and (soak.chunk is None or soak.store is None):
        raise ValueError("chunked requests need a ChunkedStation + "
                         "store (chunk_station/scfg/store on the "
                         "SoakEngine)")
    drain_cap = drain_round_cap or 4 * cfg.max_steps
    if duration is None:
        duration = float(arrival_ts[-1]) if r_total else 0.0
    hard_wall = duration * 5.0 + 30.0
    events = sorted(scenario, key=lambda e: e.t)
    ev_i = 0
    do_maint = maintenance and soak.store is not None
    do_mon = maintenance and soak.mon is not None
    if (do_maint or do_mon) \
            and int(scfg_soak.maint_slot_frac * c) < 1:
        raise ValueError(
            f"maint_slot_frac {scfg_soak.maint_slot_frac} of {c} "
            f"slots reserves no whole slot — maintenance could never "
            f"admit a row; raise the fraction or the slot count")
    do_scan = soak.index is not None and "scan" in ops
    do_chunk = soak.chunk is not None and has_chunk
    has_writes = "write" in ops
    n_scan_sched = int(np.sum(ops == "scan")) if do_scan else 0
    n_chunk_sched = int(np.sum((ops == "chunk")
                               | (ops == "chunkw"))) if do_chunk else 0

    # --- warm pass: the serve programs (identical set — bit-identity
    # depends on it), then the soak-only fixed-width programs.
    warm_serve_engine(engine)
    st = engine.empty()
    soak.warm(st)
    # Flush width must hold at least one fold chunk (chunks are
    # admit-cap wide), or a single burst's completions could overflow
    # the buffer between flush checks.
    wf = max(scfg_soak.write_flush, a_cap)
    if has_writes:
        # Write-station warm: the fold at flush width and the insert
        # program (a found=-1 insert writes nothing — same store
        # content, fresh donated buffer).
        _fold_completed(
            jnp.full((wf, cfg.quorum), -1, jnp.int32),
            soak.swarm.ids, st, cfg, jnp.zeros((a_cap,), jnp.int32),
            jnp.full((a_cap,), wf, jnp.int32))
        soak.store, _r, _t = _storage._announce_insert(
            soak.swarm.alive, cfg, soak.store, soak.scfg,
            jnp.full((wf, cfg.quorum), -1, jnp.int32),
            jnp.zeros((wf, N_LIMBS), jnp.uint32),
            jnp.zeros((wf,), jnp.uint32), jnp.zeros((wf,), jnp.uint32),
            dev_u32(soak.store_now))
    if do_scan:
        pw = soak.index.spec.prefix_words
        soak.index.range_query(np.zeros((1, pw), np.uint32),
                               np.zeros((1, pw), np.uint32))
    if do_chunk:
        # Chunked-station warm, pre-clock: the pool announce seeds the
        # values chunked requests serve, then one empty padded read
        # and one empty padded refresh compile the station's two
        # programs (fixed batch width) before the clock starts.
        soak.store = soak.chunk.announce_pool(
            soak.swarm, soak.store,
            jax.random.fold_in(soak.maint_key, 0xC400),
            soak.store_now)
        soak.store_now += 1
        soak.chunk.read(soak.swarm, soak.store, [],
                        jax.random.fold_in(soak.maint_key, 0xC401))
        soak.store = soak.chunk.refresh(
            soak.swarm, soak.store, [],
            jax.random.fold_in(soak.maint_key, 0xC402),
            soak.store_now)
        soak.store_now += 1
        soak.chunk.reads = soak.chunk.writes = 0
        soak.chunk.garbled = soak.chunk.missing = 0
    # Maintenance/scenario warm, all PRE-clock: the serve loop's
    # contract — compile must never masquerade as queueing delay —
    # applies doubly here, because an on-clock compile would book as
    # MAINTENANCE interference and poison exactly the attribution this
    # engine exists to measure.  Sweeps are pre-armed (their begin
    # compiles the width-specialized admit/fold/close programs), the
    # monitor's steady-state widths are warmed ahead, and a zero-kill
    # churn + empty outage compiles the scenario path (both A/B arms
    # run the identical warm, so the arms stay schedule-identical).
    repub_sweep: Optional[_Sweep] = None
    mon_sweep: Optional[_Sweep] = None
    if do_maint:
        soak.run_store_sweeps(0.0, clock, book=False)
        repub_sweep = soak.begin_repub_sweep(st, 0.0)
        if repub_sweep is not None:
            # Writes grow the live-row pool, so a LATER sweep can land
            # one power-of-two rung up — warm that rung's programs now
            # (sweep widths only move in pow2 steps).
            m_full = cfg.n_nodes * soak.scfg.slots
            nxt = min(m_full, 2 * repub_sweep.total)
            soak.warm_sweep_width(st, nxt)
            soak.warm_repub_insert(st, nxt)
    if do_mon:
        mon_sweep = soak.begin_monitor_sweep(st, 0.0)
        g, per = soak.mon.n_buckets, soak.mon.mcfg.period
        budget_w = 1 << max(0, (-(-g // per) - 1)).bit_length()
        for wdt in {min(g, budget_w), min(g, 2 * budget_w)}:
            soak.warm_sweep_width(st, wdt)
            soak.warm_monitor_finish(wdt)
    if events:
        _apply_event(soak, ScenarioEvent(-1.0, "churn", 0.0), -1)
        _apply_event(soak, ScenarioEvent(-1.0, "outage", 0.0), -2)

    free = list(range(c - 1, -1, -1))     # pop() → lowest slot first
    # slot -> (work class, ref); ref = request index for serve slots,
    # (sweep, row position) for maintenance slots.
    occupied: dict[int, tuple] = {}
    queue: list[int] = []
    scan_queue: list[int] = []
    next_ev = 0
    rnd = 0
    adm_i = 0
    marks_r = [0]
    marks_w = [0.0]
    rec_req, rec_lat, rec_hops, rec_rounds, rec_found = [], [], [], [], []
    admit_wall = {}
    queue_depths = []
    occ_samples = []
    admitted = completed = expired = 0
    adm_c = [0] * N_WORK_CLASSES
    com_c = [0] * N_WORK_CLASSES
    exp_c = [0] * N_WORK_CLASSES
    use_cache = soak.serve.cache is not None
    cache_hits = cache_misses = 0
    drain_rounds = 0
    overload = overload_queue_factor * c
    wclass_mismatches = 0
    maint_occupied = 0
    next_repub_t = 0.0
    next_mon_t = 0.0
    next_listen_t = scfg_soak.listen_period_s if do_maint else None
    repub_done_records: list[dict] = []
    mon_sweep_records: list[dict] = []
    # Write-flush station.
    wbuf = jnp.full((wf, cfg.quorum), -1, jnp.int32) \
        if has_writes else None
    wpend: list[int] = []     # request indices folded into wbuf rows
    write_seq: dict = {}
    write_flushes = 0
    write_flush_wall = 0.0
    # Scan station.
    scan_done, scan_lat, scan_entries = 0, [], 0
    scan_flushes = 0
    scan_flush_wall = 0.0
    # Chunked station.
    chunk_queue: list[int] = []
    chunk_done, chunk_lat = 0, []
    chunk_reads_done = chunk_writes_done = 0
    chunk_flushes = 0
    chunk_flush_wall = 0.0

    def flush_writes(now_w):
        nonlocal wbuf, wpend, write_flushes, write_flush_wall
        if not wpend:
            return
        t0f = clock()
        wk = np.zeros((wf, N_LIMBS), np.uint32)
        wv = np.zeros((wf,), np.uint32)
        ws = np.zeros((wf,), np.uint32)
        for j, ri in enumerate(wpend):
            kb = keys[ri].tobytes()
            wk[j] = keys[ri]
            wv[j] = (ri + 1) & 0x7FFFFFFF
            ws[j] = 2 + write_seq.get(kb, 0)
            write_seq[kb] = write_seq.get(kb, 0) + 1
        soak.store, _reps, _tr = _storage._announce_insert(
            soak.swarm.alive, cfg, soak.store, soak.scfg, wbuf,
            jnp.asarray(wk), jnp.asarray(wv), jnp.asarray(ws),
            dev_u32(soak.store_now))
        soak.store_now += 1
        # The store-insert path bumps the result-cache epoch: a cached
        # found-set is a closest-node claim the announce may have
        # changed (the cache's TTL/invalidation contract; a no-op
        # without a cache).
        soak.serve.invalidate_cache()
        wbuf = jnp.full((wf, cfg.quorum), -1, jnp.int32)
        wpend = []
        write_flushes += 1
        wall = clock() - t0f
        write_flush_wall += wall
        if timeline is not None:
            timeline.note_op("write-flush", now_w, wall, maint=False)

    def flush_scans():
        nonlocal scan_queue, scan_done, scan_entries, scan_flushes, \
            scan_flush_wall
        if not scan_queue:
            return
        take, scan_queue = scan_queue, []
        t0f = clock()
        lo = soak.index.linearize(
            [soak.scan_key_fn(int(scan_lo[ri])) for ri in take])
        hi = soak.index.linearize(
            [soak.scan_key_fn(int(scan_hi[ri])) for ri in take])
        res, _leaves = soak.index.range_query(lo, hi)
        t1f = clock()
        for j, ri in enumerate(take):
            lat = max(0.0, (t1f - t0) - float(arrival_ts[ri]))
            scan_lat.append(lat)
            scan_entries += len(res[j])
            if latency_plane is not None:
                latency_plane.observe(lat, op="scan")
            if timeline is not None:
                timeline.note_complete("scan", lat, t1f - t0)
        scan_done += len(take)
        scan_flushes += 1
        scan_flush_wall += t1f - t0f

    def flush_chunks():
        nonlocal chunk_queue, chunk_done, chunk_flushes, \
            chunk_flush_wall, chunk_reads_done, chunk_writes_done
        cb = soak.chunk.batch
        while chunk_queue:
            take, chunk_queue = chunk_queue[:cb], chunk_queue[cb:]
            t0f = clock()
            kf = jax.random.fold_in(soak.maint_key,
                                    0xC500 + chunk_flushes)
            w_rows = [ri for ri in take if ops[ri] == "chunkw"]
            r_rows = [ri for ri in take if ops[ri] != "chunkw"]
            if w_rows:
                # A chunked write is a same-bytes seq-bump refresh (a
                # store insert): bump the result-cache epoch, exactly
                # like the write flush.
                soak.store = soak.chunk.refresh(
                    soak.swarm, soak.store,
                    [int(scan_lo[ri]) for ri in w_rows],
                    jax.random.fold_in(kf, 1), soak.store_now)
                soak.store_now += 1
                soak.serve.invalidate_cache()
            if r_rows:
                soak.chunk.read(
                    soak.swarm, soak.store,
                    [int(scan_lo[ri]) for ri in r_rows],
                    jax.random.fold_in(kf, 2))
            t1f = clock()
            for ri in take:
                lat = max(0.0, (t1f - t0) - float(arrival_ts[ri]))
                chunk_lat.append(lat)
                if latency_plane is not None:
                    latency_plane.observe(lat, op="chunk")
                if timeline is not None:
                    timeline.note_complete("chunk", lat, t1f - t0)
            chunk_done += len(take)
            chunk_reads_done += len(r_rows)
            chunk_writes_done += len(w_rows)
            chunk_flushes += 1
            chunk_flush_wall += t1f - t0f

    t0 = clock()
    while True:
        now = clock() - t0
        # --- scenario events (strictly by wall time)
        while ev_i < len(events) and events[ev_i].t <= now:
            ev = events[ev_i]
            ev_i += 1
            t_ev = clock()
            _apply_event(soak, ev, ev_i)
            if timeline is not None:
                timeline.note_op(f"scenario-{ev.kind}", now,
                                 clock() - t_ev, maint=False)

        while next_ev < r_total and arrival_ts[next_ev] <= now:
            if ops[next_ev] == "scan" and do_scan:
                scan_queue.append(next_ev)
                if timeline is not None:
                    timeline.note_arrival("scan", now)
            elif ops[next_ev] in ("chunk", "chunkw") and do_chunk:
                chunk_queue.append(next_ev)
                if timeline is not None:
                    timeline.note_arrival("chunk", now)
            else:
                queue.append(next_ev)
                if timeline is not None:
                    timeline.note_arrival(
                        "write" if ops[next_ev] == "write" else "read",
                        now)
            next_ev += 1
        if len(queue) > overload:
            raise ServeOverloadError(
                f"serve overload: admission queue reached {len(queue)} "
                f"requests (> {overload_queue_factor} x {c} slots) at "
                f"t={now:.2f}s — the arrival rate exceeds what this "
                f"slot capacity sustains on this machine; lower "
                f"--arrival-rate or raise --serve-slots")
        in_drain = next_ev >= r_total and not queue
        if now > hard_wall and not (in_drain and (do_maint or do_mon)):
            # The hard wall bounds the SCHEDULE phase.  Once arrivals
            # are served and only maintenance is draining, the drain
            # round cap governs termination instead — a 1M-node sweep
            # legitimately drains longer than a small serve horizon,
            # and that is backlog, not overload.  (Maintenance-off
            # keeps the serve loop's unconditional wall: bit-identity.)
            raise ServeOverloadError(
                f"serve overload: run exceeded the {hard_wall:.0f}s "
                f"hard wall ({r_total - next_ev + len(queue)} requests "
                f"not yet admitted, {len(occupied)} in flight) — the "
                f"arrival rate exceeds serve capacity on this machine")
        queue_depths.append(len(queue))
        if timeline is not None:
            timeline.note_queue(len(queue), now)

        # --- serve admission (strictly first; the serve loop verbatim)
        m = min(len(queue), len(free), a_cap)
        if m:
            take = queue[:m]
            del queue[:m]
            slots_np = np.full(a_cap, c, np.int32)
            keys_np = np.zeros((a_cap, N_LIMBS), np.uint32)
            cls_np = np.zeros(a_cap, np.int32)
            for j, ri in enumerate(take):
                slot = free.pop()
                slots_np[j] = slot
                wcls = WC_WRITE if ops[ri] == "write" else WC_READ
                cls_np[j] = wcls
                occupied[slot] = (wcls, ri)
                admit_wall[ri] = now
                adm_c[wcls] += 1
            keys_np[:m] = keys[np.asarray(take)]
            st, hit, h_found, _h_hops = soak.admit_serve(
                st, jnp.asarray(keys_np), jnp.asarray(slots_np),
                cls_np, jax.random.fold_in(key, adm_i), rnd)
            adm_i += 1
            admitted += m
            if timeline is not None:
                timeline.note_admit(
                    {"read": int(np.sum(cls_np[:m] == WC_READ)),
                     "write": int(np.sum(cls_np[:m] == WC_WRITE))},
                    now)
            if hit is not None:
                # Cache-probed admission: READ rows that hit complete
                # AT the admission wall — zero service rounds, zero
                # slots, no work-class tag (the fused program dropped
                # both scatters), latency = pure queueing delay.
                for j, ri in enumerate(take):
                    if cls_np[j] != WC_READ:
                        continue
                    if not hit[j]:
                        cache_misses += 1
                        continue
                    slot = int(slots_np[j])
                    occupied.pop(slot)
                    free.append(slot)
                    lat = max(0.0, now - float(arrival_ts[ri]))
                    rec_req.append(ri)
                    rec_lat.append(lat)
                    rec_hops.append(0)
                    rec_rounds.append(0)
                    rec_found.append(int(h_found[j, 0]) >= 0)
                    completed += 1
                    com_c[WC_READ] += 1
                    cache_hits += 1
                    if latency_plane is not None:
                        latency_plane.observe(
                            lat, op=WORK_CLASS_NAMES[WC_READ])
                    if timeline is not None:
                        timeline.note_complete(
                            WORK_CLASS_NAMES[WC_READ], lat, now)

        sched_done = next_ev >= r_total and not queue

        # --- maintenance cadence: arm new sweeps (never once the
        # schedule drained — in-flight sweeps still finish below)
        if do_mon and mon_sweep is None and not sched_done \
                and now >= next_mon_t:
            mon_sweep = soak.begin_monitor_sweep(st, now)
        if do_maint and repub_sweep is None and not sched_done \
                and now >= next_repub_t:
            repub_sweep = soak.begin_repub_sweep(st, now)
            if repub_sweep is None:       # empty store — re-arm later
                next_repub_t = now + scfg_soak.repub_period_s
        if next_listen_t is not None and now >= next_listen_t:
            rec = soak.run_store_sweeps(now, clock)
            next_listen_t = now + scfg_soak.listen_period_s
            if timeline is not None:
                timeline.note_op(rec["op"], now, rec["wall_s"])

        # --- maintenance admission into LEFTOVER free slots (monitor
        # probes first: detection lag is the bounded quantity), with
        # the occupancy ceiling: maintenance never holds more than
        # maint_slot_frac of the slot plane at once
        maint_budget = min(
            len(free), scfg_soak.maint_cap,
            max(0, int(scfg_soak.maint_slot_frac * c)
                - maint_occupied))
        for sw in (mon_sweep, repub_sweep):
            # Up to maint_cap rows per iteration, admitted in admit-cap
            # chunks (the compiled admission width): one chunk per
            # iteration would starve a wide slot plane — at 1M nodes a
            # sweep feeds thousands of recycled slots per harvest.
            while sw is not None and maint_budget > 0 \
                    and sw.cursor < sw.total:
                take_n = min(maint_budget, sw.total - sw.cursor,
                             a_cap)
                slots_np = np.full(a_cap, c, np.int32)
                idx_np = np.full(a_cap, -1, np.int32)
                for j in range(take_n):
                    slot = free.pop()
                    slots_np[j] = slot
                    occupied[slot] = (sw.cls, (sw, sw.cursor))
                    idx_np[j] = sw.cursor
                    sw.cursor += 1
                st = soak.admit_maintenance(st, sw, idx_np, slots_np,
                                            rnd)
                sw.admitted += take_n
                adm_c[sw.cls] += take_n
                maint_budget -= take_n
                maint_occupied += take_n
                if timeline is not None:
                    timeline.note_admit(
                        {WORK_CLASS_NAMES[sw.cls]: take_n}, now)

        # --- scan station (batched, between bursts)
        if do_scan and scan_queue and (
                len(scan_queue) >= scfg_soak.scan_batch or sched_done
                or now - float(arrival_ts[scan_queue[0]])
                >= scfg_soak.scan_max_wait_s):
            flush_scans()

        # --- chunked station (batched, between bursts)
        if do_chunk and chunk_queue and (
                len(chunk_queue) >= soak.chunk.batch or sched_done
                or now - float(arrival_ts[chunk_queue[0]])
                >= scfg_soak.chunk_max_wait_s):
            flush_chunks()

        draining = sched_done and not scan_queue and not chunk_queue
        if draining and not occupied:
            break
        if not occupied and not queue:
            if next_ev < r_total:
                gap = arrival_ts[next_ev] - (clock() - t0)
                if gap > 0:
                    sleep(min(gap, 0.05))
                continue
            break

        # --- burst + harvest (the one sync per iteration)
        entry_occ = [0] * N_WORK_CLASSES
        for (wcls, _ref) in occupied.values():
            entry_occ[wcls] += 1
        for _ in range(burst):
            st = engine.step(st, rnd)
            rnd += 1
        done, hops, adm_r, com_r, found, dev_active = soak.snapshot(st)
        w = clock() - t0
        marks_r.append(rnd)
        marks_w.append(w)
        occ_samples.append(len(occupied) / c)

        # Slots retired this burst, per class (includes done-but-never-
        # stamped rows booked expired): the conservation identity is
        # entry_occ == retired_this_burst + device_active_after.
        retired_b = [0] * N_WORK_CLASSES
        fold_groups: dict = {}
        fill_k, fill_f, fill_h = [], [], []
        for slot in [s for s, _ in occupied.items() if done[s]]:
            wcls, ref = occupied.pop(slot)
            free.append(slot)
            retired_b[wcls] += 1
            if wcls in MAINT_CLASSES:
                maint_occupied -= 1
            cr = int(com_r[slot])
            if wcls in SERVE_CLASSES:
                ri = ref
                if cr < 0:
                    # Done with no completion stamp = forced retirement
                    # — booked expired, never a latency sample.
                    expired += 1
                    exp_c[wcls] += 1
                    if timeline is not None:
                        timeline.note_expire(WORK_CLASS_NAMES[wcls], w)
                    continue
                cw = float(np.interp(cr + 1, marks_r[-2:],
                                     marks_w[-2:]))
                cw = max(cw, admit_wall[ri])
                lat = cw - float(arrival_ts[ri])
                rec_req.append(ri)
                rec_lat.append(lat)
                rec_hops.append(int(hops[slot]))
                rec_rounds.append(cr - int(adm_r[slot]) + 1)
                rec_found.append(int(found[slot, 0]) >= 0)
                completed += 1
                com_c[wcls] += 1
                if use_cache and wcls == WC_READ:
                    fill_k.append(keys[ri])
                    fill_f.append(found[slot])
                    fill_h.append(int(hops[slot]))
                if wcls == WC_WRITE:
                    fold_groups.setdefault("write", []).append(
                        (slot, ri))
                if latency_plane is not None:
                    latency_plane.observe(
                        lat, op=WORK_CLASS_NAMES[wcls])
                if timeline is not None:
                    timeline.note_complete(WORK_CLASS_NAMES[wcls],
                                           lat, w)
            else:
                sw, pos = ref
                if cr < 0:
                    # Forced retirement without a completion stamp —
                    # the probe/row never resolved: book it expired so
                    # it is neither folded nor inserted, and (monitor)
                    # its bucket is never marked probed — an expired
                    # probe must not strike the nodes it never
                    # reached.
                    sw.expired += 1
                    exp_c[wcls] += 1
                    if timeline is not None:
                        timeline.note_expire(WORK_CLASS_NAMES[wcls],
                                             w)
                    continue
                sw.completed += 1
                sw.done_rows.append(pos)
                sw.hops.append(int(hops[slot]))
                com_c[wcls] += 1
                fold_groups.setdefault(sw, []).append((slot, pos))
                if timeline is not None:
                    timeline.note_complete(WORK_CLASS_NAMES[wcls],
                                           None, w)

        if use_cache and fill_k:
            # Fill the harvest's read completions so their followers
            # hit (one donated fixed-width dispatch, no sync — the
            # serve loop's fill contract verbatim).
            soak.serve.fill_cache(np.asarray(fill_k),
                                  np.asarray(fill_f),
                                  np.asarray(fill_h), rnd)

        # Device-vs-host occupancy cross-check: after popping done
        # slots, the host's per-class occupancy must equal the plane's
        # active counts — the work-class plane's integrity gate.
        post_occ = [0] * N_WORK_CLASSES
        for (wcls, _ref) in occupied.values():
            post_occ[wcls] += 1
        if any(post_occ[x] != int(dev_active[x])
               for x in range(N_WORK_CLASSES)):
            wclass_mismatches += 1

        # --- interleaved sweep folds (device-side, before the slots
        # recycle into new admissions; chunked at the admit width —
        # one burst can retire far more than a_cap slots)
        for gkey, pairs in fold_groups.items():
            for lo in range(0, len(pairs), a_cap):
                chunk = pairs[lo:lo + a_cap]
                sl_np = np.zeros(a_cap, np.int32)
                if gkey == "write":
                    if len(wpend) + len(chunk) > wf:
                        # Flush BEFORE the buffer would overflow: a
                        # fold position past wf is a silent drop.
                        flush_writes(w)
                    pos_np = np.full(a_cap, wf, np.int32)
                    for j, (slot, ri) in enumerate(chunk):
                        sl_np[j] = slot
                        pos_np[j] = len(wpend)
                        wpend.append(ri)
                    wbuf = _fold_completed(
                        wbuf, soak.swarm.ids, st, cfg,
                        jnp.asarray(sl_np), jnp.asarray(pos_np))
                else:
                    sw = gkey
                    pos_np = np.full(a_cap, sw.total, np.int32)
                    for j, (slot, pos) in enumerate(chunk):
                        sl_np[j] = slot
                        pos_np[j] = pos
                    if sw.cls == WC_REPUB:
                        soak.insert_completed(sw, st, sl_np, pos_np)
                    else:
                        soak.fold_completed(sw, st, sl_np, pos_np)

        # --- expiry: rows past their round budget retire (identical
        # policy; per-class bookkeeping)
        stale = [s for s in occupied
                 if not done[s] and rnd - int(adm_r[s]) >= cfg.max_steps]
        if stale:
            batch = stale[:a_cap]
            sl = np.full(a_cap, c, np.int32)
            sl[:len(batch)] = batch
            st = engine.expire(st, jnp.asarray(sl))
            for slot in batch:
                wcls, ref = occupied.pop(slot)
                free.append(slot)
                exp_c[wcls] += 1
                if wcls in SERVE_CLASSES:
                    expired += 1
                else:
                    ref[0].expired += 1
                    maint_occupied -= 1
                if timeline is not None:
                    timeline.note_expire(WORK_CLASS_NAMES[wcls], w)

        # --- timeline burst + lifecycle-boundary bookkeeping
        if timeline is not None:
            life_occ = [0] * N_WORK_CLASSES
            for (wcls, _ref) in occupied.values():
                life_occ[wcls] += 1
            timeline.note_burst(
                burst, list(entry_occ), list(retired_b),
                [int(dev_active[x]) for x in range(N_WORK_CLASSES)],
                w)
            timeline.note_lifecycle(
                {WORK_CLASS_NAMES[x]: {
                    "admitted": adm_c[x], "completed": com_c[x],
                    "expired": exp_c[x], "in_flight": life_occ[x]}
                 for x in range(N_WORK_CLASSES)}, w)

        # --- sweep completion: close drained sweeps, re-arm cadence
        if mon_sweep is not None and mon_sweep.drained:
            mon_sweep_records.append(
                soak.finish_monitor_sweep(mon_sweep, w))
            if timeline is not None:
                timeline.note_sweep("monitor", mon_sweep_records[-1],
                                    w)
            mon_sweep = None
            next_mon_t = w + scfg_soak.monitor_gap_s
        if repub_sweep is not None and repub_sweep.drained:
            repub_done_records.append(
                soak.finish_repub_sweep(repub_sweep, w))
            if timeline is not None:
                timeline.note_sweep("repub", repub_done_records[-1], w)
            repub_sweep = None
            next_repub_t = w + scfg_soak.repub_period_s

        if draining:
            drain_rounds += burst
            if drain_rounds > drain_cap:
                break

    elapsed = clock() - t0
    # Final flush + partial sweep closes (drain-cap leftovers fold
    # with what completed; unadmitted rows were never dispatched, so
    # every conservation identity holds).
    if wpend:
        flush_writes(elapsed)
    if mon_sweep is not None and mon_sweep.admitted:
        mon_sweep_records.append(
            soak.finish_monitor_sweep(mon_sweep, elapsed))
    if repub_sweep is not None and repub_sweep.admitted:
        repub_done_records.append(
            soak.finish_repub_sweep(repub_sweep, elapsed))
    if timeline is not None:
        timeline.close(elapsed)

    in_flight_c = [0] * N_WORK_CLASSES
    for (wcls, _ref) in occupied.values():
        in_flight_c[wcls] += 1
    serve_in_flight = sum(in_flight_c[x] for x in SERVE_CLASSES)
    scan_arrived = scan_done + len(scan_queue)
    chunk_arrived = chunk_done + len(chunk_queue)
    return {
        "slots": c,
        "admit_cap": a_cap,
        "burst": burst,
        "admitted": admitted,
        "completed": completed,
        "expired": expired,
        "in_flight": serve_in_flight,
        # Slot-served never-admitted: queued + not-yet-arrived, minus
        # the schedule's scan/chunked ops their stations own.  With
        # no stations this is the serve loop's formula verbatim.
        "never_admitted": len(queue) + (r_total - next_ev)
        - (n_scan_sched - scan_arrived)
        - (n_chunk_sched - chunk_arrived),
        "rounds": rnd,
        "elapsed_s": elapsed,
        "sustained_rps": completed / elapsed if elapsed > 0 else 0.0,
        "request": np.asarray(rec_req, np.int64),
        "latency_s": np.asarray(rec_lat, np.float64),
        "hops": np.asarray(rec_hops, np.int64),
        "service_rounds": np.asarray(rec_rounds, np.int64),
        "found_nonempty": np.asarray(rec_found, bool),
        "klass": np.asarray(klass)[np.asarray(rec_req, np.int64)]
        if completed else np.asarray([], dtype="<U4"),
        "op": np.asarray(ops)[np.asarray(rec_req, np.int64)]
        if completed else np.asarray([], dtype="<U5"),
        "queue_depth_mean": float(np.mean(queue_depths))
        if queue_depths else 0.0,
        "queue_depth_max": int(np.max(queue_depths))
        if queue_depths else 0,
        "slot_occupancy_frac": float(np.mean(occ_samples))
        if occ_samples else 0.0,
        "burst_marks": list(zip(marks_r, marks_w)),
        # --- soak superset ---
        "maintenance": bool(do_maint or do_mon),
        "cache_slots": soak.serve.cache_slots,
        "cache_hits": cache_hits,
        "cache_misses": cache_misses,
        "lifecycle_by_class": {
            WORK_CLASS_NAMES[x]: {
                "admitted": adm_c[x], "completed": com_c[x],
                "expired": exp_c[x], "in_flight": in_flight_c[x]}
            for x in range(N_WORK_CLASSES)},
        "wclass_mismatches": wclass_mismatches,
        "repub_sweeps": repub_done_records,
        "monitor_sweeps": mon_sweep_records,
        "maint_ops": soak.maint_ops,
        "write_flushes": write_flushes,
        "write_flush_wall_s": round(write_flush_wall, 6),
        "scan": {
            "arrived": scan_arrived,
            "completed": scan_done,
            "pending": len(scan_queue),
            "flushes": scan_flushes,
            "flush_wall_s": round(scan_flush_wall, 6),
            "entries_returned": scan_entries,
            "latency_mean_s": round(float(np.mean(scan_lat)), 6)
            if scan_lat else None,
            "latency_max_s": round(float(np.max(scan_lat)), 6)
            if scan_lat else None,
        },
        "chunked": {
            "arrived": chunk_arrived,
            "completed": chunk_done,
            "pending": len(chunk_queue),
            "reads": chunk_reads_done,
            "writes": chunk_writes_done,
            "garbled": soak.chunk.garbled if do_chunk else 0,
            "missing": soak.chunk.missing if do_chunk else 0,
            "flushes": chunk_flushes,
            "flush_wall_s": round(chunk_flush_wall, 6),
            "latency_mean_s": round(float(np.mean(chunk_lat)), 6)
            if chunk_lat else None,
            "latency_max_s": round(float(np.max(chunk_lat)), 6)
            if chunk_lat else None,
        },
    }
