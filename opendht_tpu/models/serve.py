"""Open-loop serving engine: slot-recycled continuous lookups.

Everything before this module is closed-loop batch — ``L`` lookups in,
one wall number out.  A production DHT front-end instead serves a
CONTINUOUS arrival stream (the reference rate-limits exactly such a
stream at 1,600 req/s global inbound,
include/opendht/network_engine.h:462), and the number it lives on is
not throughput but the per-request arrival→completion latency
distribution under that stream (the distribution-fidelity methodology
of arXiv:1307.7000, applied to latency instead of hop counts).

The engine keeps a fixed ``[C]``-slot :class:`LookupState` resident on
device.  A FREE slot is ``done=True`` with an empty shortlist — inert
inside the shared round step (done rows solicit nobody), so occupancy
is a pure cost knob, not a semantics one.  Each host-loop iteration:

* **admit** — queued requests (arrived per their open-loop timestamps)
  are scattered into free slots as one fixed-width micro-batch
  (``admit_cap``, padded with dropped sentinel slots): the seed
  exchange is :func:`~opendht_tpu.models.swarm.init_impl`, exactly the
  batch engine's, and ``admitted_round`` is stamped with the current
  round index;
* **burst** — a few rounds of the UNMODIFIED donated step
  (``_lookup_step_d`` / the routed ``_sharded_lookup_step``) advance
  every occupied slot in lock-step; finished rows freeze and their
  ``completed_round`` is stamped by ``_merge_round``'s lifecycle plane;
* **harvest** — the one per-burst readback (the same sync cadence the
  batch burst loop already pays) returns done/hops/lifecycle/found;
  newly-done slots are recorded and recycled for the next admission —
  finished rows' slots admit NEW requests mid-flight instead of
  compacting away (the serve twin of PR 4's active-set ladder).

Latency is reconstructed, not per-row-probed: the device holds round
indices, the host holds per-burst wall clocks, and
``arrival→completion = round-end wall(completed_round) − arrival_ts``
with round-end walls linearly interpolated inside each burst (floored
at the admission wall, so queueing delay is included and latency can
never go negative on a sub-burst completion).

Round 16 made the engine production-shaped across three layers, all
strict overlays (cache off + no admission policy = the byte-identical
r07 engine):

* **device hot-key result cache** (:class:`ResultCache`) — consulted
  inside the admission jit; a Zipf-hot key that completed before
  answers in ZERO rounds without occupying a slot;
* **per-class token-bucket admission** (:class:`AdmissionControl`) —
  the host twin of the reference's ``rate_limiter.h``; policy
  ``shed``/``queue``/``degrade``, and overload sheds gracefully
  instead of raising;
* **first-class sharded serve** — :class:`ShardedServeEngine` driven
  open-loop by the bench (``--mode serve --sharded``), its closed-
  loop replay bit-identical to ``sharded_lookup`` on the mesh, the
  cache replicated across devices.
"""

from __future__ import annotations

import math
import time
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.xor_metric import N_LIMBS, merge_ladder_widths
from ..utils.hostdevice import dev_i32
from . import swarm as _swarm
from .swarm import (
    UINT32_MAX,
    LookupResult,
    LookupState,
    Swarm,
    SwarmConfig,
    _finalize,
    _local_respond,
    _sample_origins,
    burst_schedule,
    init_impl,
    step_impl,
)


class ServeOverloadError(RuntimeError):
    """The open-loop arrival stream exceeds what the slot capacity can
    drain: the admission queue grew past the overload bound.  Raised
    with a clear message instead of letting the queue (and the run)
    grow without bound — the serve bench surfaces it as a CLI error.
    With an :class:`AdmissionControl` of policy ``shed`` the engine
    sheds the excess instead and never raises this."""


# ---------------------------------------------------------------------------
# device hot-key result cache
# ---------------------------------------------------------------------------
#
# The Zipf workload sends 1 % of keys the overwhelming majority of
# traffic (poisson_zipf_events' hot class), yet every request pays the
# full multi-round lookup — the per-round-cost lever of arXiv:1408.3079
# applied at the REQUEST level instead of the round level.  This cache
# is the device twin of the host's ``core/node_cache.py`` (one
# canonical answer per id, consulted before any network work): a
# fixed-capacity direct-mapped result cache resident on device,
# consulted INSIDE the admission jit — a hit completes in ~0 rounds
# without ever occupying a lookup slot, a miss falls through to the
# normal seed exchange.  Fills happen at harvest from completed rows;
# invalidation is a device epoch the probe checks (store-insert paths
# bump it via ``_cache_invalidate`` — the soak engine's write flush
# does, tests drive it directly), so one announce retires every cached
# answer at once, like the reference dropping its cached nodes on a
# connectivity change (``clear_bad_nodes``).

class ResultCache(NamedTuple):
    """Device-resident hot-key result cache (a pytree of arrays).

    Direct-mapped over ``K = keys.shape[0]`` slots: a key's slot is a
    murmur-style mix of its five limbs mod K (``_cache_slot_of``), so
    probe and fill are ONE gather / one scatter each — no sort, no
    scan.  A colliding fill evicts (hot keys re-fill within one
    harvest, cold keys were never worth keeping).  An entry is live
    iff its ``fill_epoch`` equals the scalar ``epoch``:
    ``_cache_invalidate`` bumps the epoch and every entry goes stale
    in O(1) — the announce-time invalidation contract.  ``fill_round``
    records the engine round the entry was harvested at (result age
    in rounds, reported in the serve artifact's cache block).
    """
    keys: jax.Array        # [K,5] uint32 cached key limbs
    found: jax.Array       # [K,quorum] int32 result heads (-1 pad)
    hops: jax.Array        # [K] int32 hops the FILL paid (a hit pays 0)
    fill_round: jax.Array  # [K] int32 engine round at fill
    fill_epoch: jax.Array  # [K] uint32 epoch at fill (0 = never)
    epoch: jax.Array       # []  uint32 current epoch (starts at 1)


@partial(jax.jit, static_argnames=("cfg", "k_slots"))
def empty_result_cache(cfg: SwarmConfig, k_slots: int) -> ResultCache:
    """All-stale ``[k_slots]`` cache: fill epochs 0, current epoch 1 —
    nothing can hit until the first fill."""
    return ResultCache(
        keys=jnp.zeros((k_slots, N_LIMBS), jnp.uint32),
        found=jnp.full((k_slots, cfg.quorum), -1, jnp.int32),
        hops=jnp.zeros((k_slots,), jnp.int32),
        fill_round=jnp.zeros((k_slots,), jnp.int32),
        fill_epoch=jnp.zeros((k_slots,), jnp.uint32),
        epoch=jnp.uint32(1))


def _cache_slot_of(keys: jax.Array, k_slots: int) -> jax.Array:
    """``[A,5] -> [A]`` direct-map slot: murmur-style limb mix mod
    ``k_slots`` (static, folds into the program)."""
    h = keys[:, 0]
    for j in range(1, N_LIMBS):
        h = (h * jnp.uint32(0x9E3779B1)) ^ keys[:, j]
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> jnp.uint32(15))
    return (h % jnp.uint32(k_slots)).astype(jnp.int32)


def _cache_slot_np(keys: np.ndarray, k_slots: int) -> np.ndarray:
    """Numpy twin of :func:`_cache_slot_of` (bit-identical,
    parity-tested): the host dedupes a fill batch by SLOT before the
    device scatter, because ``_cache_fill`` writes its five fields
    with five independent scatters and XLA leaves the duplicate-index
    winner implementation-defined PER SCATTER — two colliding rows
    could otherwise land key A paired with key B's found-set."""
    with np.errstate(over="ignore"):
        k = keys.astype(np.uint32)
        h = k[:, 0]
        for j in range(1, N_LIMBS):
            h = (h * np.uint32(0x9E3779B1)) ^ k[:, j]
        h = h ^ (h >> np.uint32(16))
        h = h * np.uint32(0x7FEB352D)
        h = h ^ (h >> np.uint32(15))
        return (h % np.uint32(k_slots)).astype(np.int64)


def _probe_impl(cache: ResultCache, keys: jax.Array):
    """Shared probe body (inlined into the admission jits and the
    standalone ``_cache_probe``): one slot gather, a 5-limb compare,
    and the epoch liveness check.  Returns ``(hit [A] bool,
    found [A,q] i32, hops [A] i32)``."""
    sl = _cache_slot_of(keys, cache.keys.shape[0])
    hit = (jnp.all(cache.keys[sl] == keys, axis=1)
           & (cache.fill_epoch[sl] == cache.epoch))
    return hit, cache.found[sl], cache.hops[sl]


@jax.jit
def _cache_probe(cache: ResultCache, keys: jax.Array):
    """Standalone probe (no admission): the ``degrade`` admission
    policy answers rate-limited hot keys from cache only — this is
    that read.  Pure; the cache is untouched."""
    return _probe_impl(cache, keys)


@partial(jax.jit, donate_argnums=(0,))
def _cache_fill(cache: ResultCache, keys: jax.Array, found: jax.Array,
                hops: jax.Array, mask: jax.Array,
                rnd: jax.Array) -> ResultCache:
    """Fill harvested results (DONATED cache — single-owner like the
    serve carry).  ``keys [M,5]`` / ``found [M,q]`` / ``hops [M]`` are
    the harvest's completed rows, ``mask [M]`` selects real rows
    (padding False; masked rows scatter to the drop sentinel).
    The caller must pass SLOT-UNIQUE real rows (``fill_cache`` dedupes
    host-side via :func:`_cache_slot_np`): the five per-field scatters
    resolve duplicate indices independently, so colliding rows inside
    one call could mix fields from different winners."""
    k_slots = cache.keys.shape[0]
    sl = jnp.where(mask, _cache_slot_of(keys, k_slots),
                   jnp.int32(k_slots))
    ep = jnp.broadcast_to(cache.epoch, sl.shape)
    r32 = jnp.broadcast_to(jnp.asarray(rnd, jnp.int32), sl.shape)
    return cache._replace(
        keys=cache.keys.at[sl].set(keys, mode="drop"),
        found=cache.found.at[sl].set(found, mode="drop"),
        hops=cache.hops.at[sl].set(hops, mode="drop"),
        fill_round=cache.fill_round.at[sl].set(r32, mode="drop"),
        fill_epoch=cache.fill_epoch.at[sl].set(ep, mode="drop"))


@partial(jax.jit, donate_argnums=(0,))
def _cache_invalidate(cache: ResultCache) -> ResultCache:
    """Bump the epoch: every entry goes stale in O(1).  The
    store-insert paths call this on announce (a cached found-set is a
    closest-node claim the new value may have changed); epoch
    wraparound at 2^32 bumps is out of scope for any real run."""
    return cache._replace(epoch=cache.epoch + jnp.uint32(1))


@partial(jax.jit, static_argnames=("cfg", "slots"))
def empty_serve_state(cfg: SwarmConfig, slots: int) -> LookupState:
    """All-free ``[slots]`` serve state: every row done with an empty
    shortlist (inert in the round step) and lifecycle ``-1``/``-1``
    (never admitted)."""
    s = cfg.search_width
    return LookupState(
        targets=jnp.zeros((slots, N_LIMBS), jnp.uint32),
        idx=jnp.full((slots, s), -1, jnp.int32),
        dist=jnp.full((slots, s), UINT32_MAX, jnp.uint32),
        queried=jnp.zeros((slots, s), bool),
        done=jnp.ones((slots,), bool),
        hops=jnp.zeros((slots,), jnp.int32),
        admitted_round=jnp.full((slots,), -1, jnp.int32),
        completed_round=jnp.full((slots,), -1, jnp.int32))


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def _admit(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
           keys: jax.Array, slots: jax.Array, origins: jax.Array,
           rnd: jax.Array) -> LookupState:
    """Scatter a padded admission micro-batch into free slots.

    ``keys [A,5]``; ``slots [A]`` target slot per request with the
    PAD SENTINEL ``C`` (= the slot count — ``mode="drop"`` makes padded
    rows vanish); ``origins [A]`` issuing nodes.  The seed exchange is
    the batch engine's ``init_impl`` verbatim, so a closed-loop replay
    through this path is bit-identical to ``lookup`` (tests).  The
    state is DONATED: the serve carry is single-owner, like the burst
    loops'.
    """
    new = init_impl(swarm.ids, _local_respond(swarm, cfg), cfg, keys,
                    origins)
    return _scatter_rows_into(st, new, slots, rnd)


def _scatter_rows_into(st: LookupState, new: LookupState,
                       slots: jax.Array, rnd) -> LookupState:
    """ONE copy of the admission scatter (slot sentinel = slot count,
    dropped), shared by the local and sharded admit programs — a new
    ``LookupState`` field lands in both or in neither."""
    sl = slots
    return LookupState(
        targets=st.targets.at[sl].set(new.targets, mode="drop"),
        idx=st.idx.at[sl].set(new.idx, mode="drop"),
        dist=st.dist.at[sl].set(new.dist, mode="drop"),
        queried=st.queried.at[sl].set(new.queried, mode="drop"),
        done=st.done.at[sl].set(False, mode="drop"),
        hops=st.hops.at[sl].set(0, mode="drop"),
        admitted_round=st.admitted_round.at[sl].set(
            jnp.asarray(rnd, jnp.int32), mode="drop"),
        completed_round=st.completed_round.at[sl].set(-1, mode="drop"))


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2, 3))
def _admit_cached(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
                  cache: ResultCache, keys: jax.Array,
                  slots: jax.Array, origins: jax.Array,
                  rnd: jax.Array):
    """:func:`_admit` with the result cache consulted INSIDE the
    admission program: rows whose key hits a live cache entry are
    redirected to the drop sentinel — they never occupy a slot, never
    solicit anybody, and complete in zero rounds; misses scatter
    exactly like :func:`_admit` (same ``init_impl`` seed exchange, so
    the cache-off engine is a strict subset program).  Both the serve
    state AND the cache are donated (single-owner carries); the cache
    passes through unchanged — fills are a harvest-side concern
    (:func:`_cache_fill`), admission only reads.  Returns
    ``(state, cache, hit [A], hit_found [A,q], hit_hops [A])``; the
    host reads the hit row right after dispatch (its only per-
    admission sync, paid only when the cache is on)."""
    c = st.done.shape[0]
    hit, h_found, h_hops = _probe_impl(cache, keys)
    new = init_impl(swarm.ids, _local_respond(swarm, cfg), cfg, keys,
                    origins)
    eff = jnp.where(hit, jnp.int32(c), slots)
    st = _scatter_rows_into(st, new, eff, rnd)
    return st, cache, hit, h_found, h_hops


@partial(jax.jit, static_argnames=("cfg",))
def _snapshot(swarm: Swarm, cfg: SwarmConfig, st: LookupState):
    """The per-burst harvest readback: done mask, hops, lifecycle rows
    and the finalized result heads — one ``device_get`` of small
    arrays, the serve loop's only host sync."""
    return (st.done, st.hops, st.admitted_round, st.completed_round,
            _finalize(swarm.ids, st, cfg))


@partial(jax.jit, donate_argnums=(0,))
def _expire_slots(st: LookupState, slots: jax.Array) -> LookupState:
    """Retire rows that exceeded their round budget: mark them done so
    the step stops soliciting and the slot can recycle.
    ``completed_round`` stays -1 — an expired request never completed,
    and the host books it as ``expired``, not as a latency sample.
    The serve twin of the batch engine's ``max_steps`` cap (which
    reports stragglers as ``done=False`` instead of spinning forever);
    without it a non-converging lookup would hold its slot for the
    whole run and a sustainable arrival rate could still starve into a
    misleading overload error."""
    return st._replace(done=st.done.at[slots].set(True, mode="drop"))


class ServeEngine:
    """Single-chip serve engine: admit / step / snapshot over one
    resident ``[slots]`` state.  ``admit_cap`` fixes the admission
    micro-batch width (one compiled admit program).

    ``cache_slots > 0`` attaches the device hot-key result cache
    (:class:`ResultCache`): admissions go through
    :meth:`admit_probed` (probe fused into the admission jit, hits
    complete instantly without a slot), harvested completions fill
    via :meth:`fill_cache`, and announces invalidate via
    :meth:`invalidate_cache`.  ``cache_slots = 0`` (default) keeps
    every program byte-identical to the pre-cache engine — the cache
    is a pure overlay (proven in tests/test_serve.py)."""

    def __init__(self, swarm: Swarm, cfg: SwarmConfig, slots: int,
                 admit_cap: int | None = None, cache_slots: int = 0):
        self.swarm, self.cfg, self.slots = swarm, cfg, slots
        self.admit_cap = min(slots, admit_cap or min(slots, 512))
        if cache_slots < 0:
            raise ValueError(f"cache_slots must be >= 0, got "
                             f"{cache_slots}")
        self.cache_slots = cache_slots
        self.cache = (empty_result_cache(cfg, cache_slots)
                      if cache_slots else None)
        # Test hook: False keeps the cache permanently cold (every
        # probe misses) — the pure-overlay equivalence proof runs the
        # cache-ON programs against the cache-off engine with fills
        # disabled, so the two must be bit-identical end to end.
        self.cache_fill_enabled = True

    def empty(self) -> LookupState:
        return empty_serve_state(self.cfg, self.slots)

    def admit(self, st, keys, slots, key, rnd):
        # Origin draw with the caller's key DIRECTLY (no folding): the
        # closed-loop replay relies on this matching the batch engine's
        # ``_sample_origins(key, alive, l)`` bit-for-bit.
        origins = _sample_origins(key, self.swarm.alive,
                                  keys.shape[0])
        # dev_i32: explicit cached round-coordinate upload — the
        # serve loop admits every iteration, and an implicit
        # jnp.int32(rnd) transfer per admit is exactly the hot-path
        # leak graftlint's strict transfer-guard replay forbids.
        return _admit(self.swarm, self.cfg, st, keys, slots, origins,
                      dev_i32(rnd))

    def step(self, st, rnd):
        # Resolved through the module attribute so the cost ledger's
        # in-place instrumentation (obs/ledger.py ENTRY_POINTS) sees
        # serve rounds like burst-loop rounds.
        return _swarm._lookup_step_d(self.swarm, self.cfg, st,
                                     dev_i32(rnd))

    def admit_probed(self, st, keys, slots, key, rnd):
        """Cache-consulted admission: like :meth:`admit` but rows
        whose key hits the cache never occupy their slot.  Returns
        ``(state, hit, hit_found, hit_hops)`` with the hit row already
        on the host (one small readback per admission — the cache-on
        loop's extra sync; the cache-off loop pays none)."""
        origins = _sample_origins(key, self.swarm.alive, keys.shape[0])
        st, self.cache, hit, found, hops = _admit_cached(
            self.swarm, self.cfg, st, self.cache, keys, slots, origins,
            dev_i32(rnd))
        h, f, hp = jax.device_get((hit, found, hops))
        return st, h, f, hp

    def probe_cache(self, keys):
        """Host-visible cache read (the ``degrade`` policy's path):
        ``(hit, found, hops)`` numpy rows for ``keys [A,5]``."""
        return jax.device_get(_cache_probe(self.cache, keys))

    def fill_cache(self, keys_np, found_np, hops_np, rnd) -> int:
        """Fill harvested completions into the cache, padded to ONE
        compiled width (``admit_cap``).  Rows colliding on a cache
        slot are deduped HOST-side first (last writer wins — see
        :func:`_cache_slot_np` for why the device scatter must see
        unique slots), and batches wider than the cap truncate — a
        fill is best-effort (dropped rows' keys stay cache-cold and
        re-fill at their next completion).  Returns the rows actually
        filled."""
        if self.cache is None or not self.cache_fill_enabled:
            return 0
        keys_np = np.asarray(keys_np, np.uint32).reshape(-1, N_LIMBS)
        found_np = np.asarray(found_np)
        if len(keys_np):
            # Never cache a NEGATIVE result: an empty found head is a
            # transient (a lookup racing churn), and pinning it would
            # answer every follower "not found" in zero rounds for a
            # whole epoch where the cache-off engine would retry and
            # likely succeed.
            ok = found_np[:, 0] >= 0
            keys_np = keys_np[ok]
            found_np = found_np[ok]
            hops_np = np.asarray(hops_np)[ok]
        if len(keys_np):
            sl = _cache_slot_np(keys_np, self.cache_slots)
            # Keep the LAST occurrence per slot (the freshest result).
            _, last = np.unique(sl[::-1], return_index=True)
            pick = np.sort(len(sl) - 1 - last)
            keys_np = keys_np[pick]
            found_np = found_np[pick]
            hops_np = np.asarray(hops_np)[pick]
        a = self.admit_cap
        m = min(len(keys_np), a)
        keys = np.zeros((a, N_LIMBS), np.uint32)
        found = np.full((a, self.cfg.quorum), -1, np.int32)
        hops = np.zeros((a,), np.int32)
        mask = np.zeros((a,), bool)
        keys[:m] = keys_np[:m]
        found[:m] = found_np[:m]
        hops[:m] = hops_np[:m]
        mask[:m] = True
        self.cache = _cache_fill(
            self.cache, jnp.asarray(keys), jnp.asarray(found),
            jnp.asarray(hops), jnp.asarray(mask), dev_i32(rnd))
        return m

    def invalidate_cache(self) -> None:
        """Announce-side TTL: the store-insert paths bump the device
        epoch the probe checks (one O(1) scalar bump retires every
        entry).  The soak engine's write flush calls this; a no-op
        without a cache."""
        if self.cache is not None:
            self.cache = _cache_invalidate(self.cache)

    def expire(self, st, slots):
        return _expire_slots(st, slots)

    def snapshot(self, st):
        return jax.device_get(_snapshot(self.swarm, self.cfg, st))


class ShardedServeEngine(ServeEngine):
    """Mesh serve engine: the routed ``_sharded_lookup_step`` advances
    the resident state; admission seeds through the routed init (shard-
    local origin sampling) and scatters into the global slot axis.
    ``slots`` and ``admit_cap`` must divide the mesh."""

    def __init__(self, swarm: Swarm, cfg: SwarmConfig, slots: int,
                 mesh, capacity_factor: float = 2.0,
                 admit_cap: int | None = None, cache_slots: int = 0):
        super().__init__(swarm, cfg, slots, admit_cap,
                         cache_slots=cache_slots)
        from ..parallel.mesh import AXIS
        self.mesh, self.capacity_factor = mesh, capacity_factor
        d = mesh.shape[AXIS]
        if slots % d or self.admit_cap % d:
            raise ValueError(f"serve slots {slots} and admit_cap "
                             f"{self.admit_cap} must divide the "
                             f"{d}-device mesh")
        # Routed-exchange row counter (observability): init rows that
        # actually rode the all_to_all — cache hits are excluded by
        # the masked init, which is the provable mesh-hit skip.
        self.xchg_init_rows = 0

    def admit(self, st, keys, slots, key, rnd):
        # Routed seed exchange (shard-local origin folding inside the
        # init body), then one GSPMD scatter into the resident state.
        from ..parallel.sharded import _sharded_lookup_init
        new = _sharded_lookup_init(self.swarm, self.cfg, keys, key,
                                   self.mesh, self.capacity_factor)
        return _scatter_admission(st, new, slots, dev_i32(rnd))

    def admit_probed(self, st, keys, slots, key, rnd):
        # Cache-AWARE sharded admission (round 20): the replicated
        # cache is probed BEFORE the routed init and hit rows are
        # handed to the init as its skip mask, so a mesh hit never
        # rides the ``all_to_all`` (previously hit rows ran the full
        # routed seed exchange and were only dropped at the scatter).
        # Same sync count as before: ONE small readback per
        # admission, now of the standalone probe.  Non-hit rows'
        # init is bit-identical (the masked body's full-width origin
        # draw), so the admitted state is unchanged.
        from ..parallel.sharded import _sharded_lookup_init_masked
        hit, found, hops = _cache_probe(self.cache, keys)
        new = _sharded_lookup_init_masked(
            self.swarm, self.cfg, keys, key, hit, self.mesh,
            self.capacity_factor)
        st = _scatter_admission_masked(st, new, slots, hit,
                                       dev_i32(rnd))
        h, f, hp = jax.device_get((hit, found, hops))
        self.xchg_init_rows += int(keys.shape[0] - h.sum())
        return st, h, f, hp

    def step(self, st, rnd):
        from ..parallel.sharded import _sharded_lookup_step
        return _sharded_lookup_step(self.swarm, self.cfg, st, self.mesh,
                                    self.capacity_factor,
                                    rnd=dev_i32(rnd))


@partial(jax.jit, donate_argnums=(0,))
def _scatter_admission(st: LookupState, new: LookupState,
                       slots: jax.Array, rnd: jax.Array) -> LookupState:
    return _scatter_rows_into(st, new, slots, rnd)


@partial(jax.jit, donate_argnums=(0,))
def _scatter_admission_masked(st: LookupState, new: LookupState,
                              slots: jax.Array, skip: jax.Array,
                              rnd: jax.Array) -> LookupState:
    """Sharded cached-admission scatter, round-20 form: the probe now
    runs STANDALONE before the routed init (so hit rows can be
    masked out of the ``all_to_all`` — see
    ``ShardedServeEngine.admit_probed``), and this scatter only has
    to drop the skipped rows to the sentinel.  Replaces the retired
    ``_scatter_admission_cached``, whose probe-in-scatter form forced
    every hit row through the routed seed exchange first.  The cache
    stays REPLICATED across the mesh exactly as before (fills come
    from replicated host-side inputs, so the copies never diverge)."""
    c = st.done.shape[0]
    eff = jnp.where(skip, jnp.int32(c), slots)
    return _scatter_rows_into(st, new, eff, rnd)


def poisson_zipf_events(rate: float, duration: float, key_pool: int,
                        zipf_s: float, seed: int = 0,
                        hot_frac: float = 0.01,
                        return_draw: bool = False):
    """Open-loop request schedule: Poisson(``rate``) arrival timestamps
    over ``[0, duration)`` with Zipf(``zipf_s``)-popular keys drawn
    from a ``key_pool``-key universe (``zipf_s = 0`` → uniform).

    Returns ``(arrival_ts [R] float64, keys [R,5] uint32 jnp,
    klass [R] array of "hot"/"cold")`` — a key is "hot" when its
    popularity rank falls in the top ``hot_frac`` of the pool, the
    request-class axis of the latency histograms.  With
    ``return_draw`` the per-request popularity RANKS ride along as a
    fourth element (the soak schedule derives its scan windows from
    them, ``models.soak.mixed_events``) — the first three are
    bit-identical either way.
    """
    if rate <= 0 or duration <= 0:
        raise ValueError("rate and duration must be > 0")
    rng = np.random.default_rng(seed)
    # Inter-arrival exponentials until the horizon (Poisson process).
    n_est = int(rate * duration * 1.5) + 64
    while True:
        gaps = rng.exponential(1.0 / rate, size=n_est)
        ts = np.cumsum(gaps)
        if ts[-1] >= duration:
            break
        n_est *= 2
    ts = ts[ts < duration]
    r = len(ts)
    pool = np.asarray(jax.random.bits(jax.random.PRNGKey(seed ^ 0x5EED),
                                      (key_pool, N_LIMBS), jnp.uint32))
    if zipf_s > 0:
        rnk = np.arange(1, key_pool + 1, dtype=np.float64)
        prob = rnk ** -zipf_s
        prob /= prob.sum()
        draw = rng.choice(key_pool, size=r, p=prob)
    else:
        draw = rng.integers(0, key_pool, size=r)
    hot_cut = max(1, int(key_pool * hot_frac))
    klass = np.where(draw < hot_cut, "hot", "cold")
    # Keys stay HOST-side numpy: the serve loop gathers each admission
    # micro-batch on the host and ships ONE padded array to the device
    # — a jnp key matrix here would put a device gather + blocking
    # readback + re-upload inside every admission of the measured loop.
    if return_draw:
        return ts, pool[draw], klass, draw
    return ts, pool[draw], klass


class AdmissionControl:
    """Per-class token-bucket admission policy — the host half of the
    reference's inbound rate limiting (``rate_limiter.h`` + the
    1,600 req/s global cap, network_engine.h:462), applied where this
    engine admits: the slot plane's admission step.

    One :class:`~opendht_tpu.utils.rate_limiter.TokenBucket` per
    request class (the serve workload's ``hot``/``cold`` — the
    per-client axis this harness models), each accruing ``rate``
    tokens/s up to ``burst``.  ``per_key_rate`` adds a SECOND bucket
    layer keyed by the request KEY (the true per-client fairness axis
    the per-class buckets approximate — ROADMAP #1's named follow-up,
    the reference's per-IP limiter next to its global one): a key's
    bucket is checked FIRST, so one hot key's flood dies at its own
    bucket without draining the shared class bucket — the hot key can
    no longer starve cold keys of class tokens.  The check-then-spend
    is ATOMIC across the pair (``TokenBucket.peek`` before any
    ``limit``): a refusal by either bucket charges NEITHER, so a
    repeatedly-refused request cannot drain the other bucket by
    retrying.  The key map is BOUNDED: at most ``max_keys`` buckets
    live at once, evicted LRU (an evicted key restarts with a full
    burst — a brief over-admit for a key cold enough to be evicted,
    never unbounded memory; the reference's IP limiter map has the
    same decay shape).  Per-key buckets are REJECTED with the
    ``queue`` policy: queue is head-of-line by contract, and a
    key-dry head would block every request behind it — precisely the
    starvation the key buckets exist to eliminate (use ``shed`` or
    ``degrade``, where a refused request is consumed, not parked).

    A request whose bucket (key or class) is dry is handled per
    ``policy``:

    * ``shed``    — dropped and booked as ``shed`` in the lifecycle
      accounting (the reference's behavior: over-quota packets are
      dropped, the node stays up).  Queue overflow past the overload
      bound ALSO sheds under this policy instead of raising
      :class:`ServeOverloadError` — graceful degradation replaces
      exit 2.
    * ``queue``   — waits in the admission queue for tokens (head-of-
      line; the overload guard still applies, so a persistently
      over-rate stream eventually raises — that IS this policy's
      contract).
    * ``degrade`` — answered from the result cache only: a hit
      completes (booked as admitted + completed + cache hit), a miss
      is shed.  Over-quota traffic costs one cache probe, never a
      lookup slot.
    """

    POLICIES = ("shed", "queue", "degrade")

    def __init__(self, rate: float, burst: float | None = None,
                 policy: str = "shed",
                 per_key_rate: float | None = None,
                 per_key_burst: float | None = None,
                 max_keys: int = 4096):
        from ..utils.rate_limiter import TokenBucket
        if policy not in self.POLICIES:
            raise ValueError(f"admission policy must be one of "
                             f"{self.POLICIES}, got {policy!r}")
        if rate <= 0:
            raise ValueError(f"admission rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None \
            else max(1.0, self.rate)
        if self.burst < 1.0:
            # Validate HERE, not at the first lazy TokenBucket deep
            # inside the serve loop (after minutes of swarm build).
            raise ValueError(f"admission burst must be >= 1, got "
                             f"{self.burst}")
        self.policy = policy
        if per_key_rate is not None and per_key_rate <= 0:
            raise ValueError(f"per-key admission rate must be > 0, "
                             f"got {per_key_rate}")
        if per_key_rate is not None and policy == "queue":
            raise ValueError(
                "per-key buckets are incompatible with the 'queue' "
                "policy: queue is head-of-line, so a key-dry head "
                "request would block every request behind it — the "
                "exact starvation per-key fairness exists to remove; "
                "use policy 'shed' or 'degrade'")
        self.per_key_rate = (float(per_key_rate)
                             if per_key_rate is not None else None)
        self.per_key_burst = (float(per_key_burst)
                              if per_key_burst is not None
                              else (max(1.0, self.per_key_rate)
                                    if self.per_key_rate else None))
        if self.per_key_burst is not None and self.per_key_burst < 1.0:
            raise ValueError(f"per-key admission burst must be >= 1, "
                             f"got {self.per_key_burst}")
        if max_keys < 1:
            raise ValueError(f"max_keys must be >= 1, got {max_keys}")
        self.max_keys = int(max_keys)
        self.key_evictions = 0
        self._tb = TokenBucket                 # class, for lazy buckets
        self._buckets: dict = {}
        from collections import OrderedDict
        self._key_buckets: "OrderedDict" = OrderedDict()

    def allow(self, klass, now: float, key=None) -> bool:
        # Key bucket first (when armed): an over-rate key is refused
        # by ITS bucket before it can touch the shared class tokens —
        # the fairness property tests/test_serve.py pins (hot key at
        # 100x its quota, cold keys still fully admitted).  Both
        # buckets are PEEKED before either is charged: a composite
        # refusal must not spend the bucket that said yes, or a
        # retried request drains it without ever being admitted.
        kb = None
        if self.per_key_rate is not None and key is not None:
            kb = self._key_buckets.get(key)
            if kb is None:
                if len(self._key_buckets) >= self.max_keys:
                    self._key_buckets.popitem(last=False)   # LRU out
                    self.key_evictions += 1
                kb = self._key_buckets[key] = self._tb(
                    self.per_key_rate, self.per_key_burst)
            else:
                self._key_buckets.move_to_end(key)
            if not kb.peek(now):
                return False
        b = self._buckets.get(klass)
        if b is None:
            b = self._buckets[klass] = self._tb(self.rate, self.burst)
        if not b.peek(now):
            return False
        if kb is not None:
            kb.limit(now)
        return b.limit(now)


def measure_round_wall(swarm: Swarm, cfg: SwarmConfig,
                       slots: int = 1024, rounds: int = 6) -> float:
    """Measured per-round wall of a FULLY-OCCUPIED ``[slots]`` serve
    state (warmed first — compile never books as round wall): the
    input the slot autotuner sizes from.  One probe engine, ``rounds``
    back-to-back steps, one barrier."""
    eng = ServeEngine(swarm, cfg, slots=slots, admit_cap=slots)
    warm_serve_engine(eng)
    st = eng.empty()
    keys = jax.random.bits(jax.random.PRNGKey(17), (slots, N_LIMBS),
                           jnp.uint32)
    st = eng.admit(st, keys, jnp.arange(slots, dtype=jnp.int32),
                   jax.random.PRNGKey(18), 0)
    jax.block_until_ready(st)
    t0 = time.perf_counter()
    for r in range(rounds):
        st = eng.step(st, r)
    jax.block_until_ready(st)
    return (time.perf_counter() - t0) / rounds


def autotune_serve_slots(cfg: SwarmConfig, arrival_rate: float,
                         round_wall_s: float,
                         target_occupancy: float = 0.5,
                         floor: int = 128,
                         ceil: int = 65536) -> int:
    """Size the slot plane from arrival rate × measured round wall —
    the PR-7 0.15-occupancy finding (1,024 slots for a load that
    needed ~150) turned into arithmetic.

    Little's law: concurrent in-flight work ``D = rate × service
    time``, with service time ≈ the calibrated convergence depth
    (``burst_schedule`` rounds, +1 for the admission round) × the
    measured round wall.  Slots = the next power of two covering
    ``D / target_occupancy`` (the headroom keeping queueing off the
    admission path when arrivals burst), clamped to
    ``[floor, ceil]``.  Pure arithmetic — the measurement half is
    :func:`measure_round_wall` — so it unit-tests without a clock."""
    if arrival_rate <= 0 or round_wall_s <= 0:
        raise ValueError("arrival_rate and round_wall_s must be > 0")
    if not 0.0 < target_occupancy <= 1.0:
        raise ValueError(f"target_occupancy must be in (0, 1], got "
                         f"{target_occupancy}")
    service_s = (burst_schedule(cfg) + 1) * round_wall_s
    demand = arrival_rate * service_s / target_occupancy
    slots = 1 << max(0, math.ceil(math.log2(max(1.0, demand))))
    return int(min(ceil, max(floor, slots)))


def warm_serve_engine(engine: ServeEngine) -> None:
    """Compile admit/step/snapshot/expire OFF the serve clock (compile
    time must never masquerade as queueing delay).  Shared by
    :func:`serve_open_loop` and the soak loop
    (``models.soak.soak_open_loop``), which must warm the identical
    program set so a maintenance-off soak is bit-identical to the
    plain serve loop from the first admission on."""
    c, a_cap = engine.slots, engine.admit_cap
    st = engine.empty()
    warm_keys = jnp.zeros((a_cap, N_LIMBS), jnp.uint32)
    warm_slots = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.full((a_cap - 1,), c, jnp.int32)]) if a_cap > 1 \
        else jnp.zeros((1,), jnp.int32)
    st = engine.admit(st, warm_keys, warm_slots,
                      jax.random.PRNGKey(0), 0)
    st = engine.step(st, 0)
    engine.snapshot(st)
    # Expire compiles too: its first real use is mid-run by definition
    # (a request aging past max_steps), where a fresh jit would land
    # inside a burst wall mark and read as tail latency.
    engine.expire(st, jnp.full((a_cap,), c, jnp.int32))
    if engine.cache is not None:
        # Cache-on programs warm too (probe-fused admit, the fill at
        # its one padded width, the standalone degrade probe).  The
        # warm fill is an all-masked batch: compiles the program,
        # writes nothing — the cache stays cold, which the
        # pure-overlay equivalence proof depends on.  Cache-off
        # engines skip this block entirely, so the warmed program set
        # (and the soak loop's bit-identity contract) is unchanged.
        st2 = engine.empty()
        st2, _h, _f, _hp = engine.admit_probed(
            st2, warm_keys, warm_slots, jax.random.PRNGKey(0), 0)
        fills_on = engine.cache_fill_enabled
        engine.cache_fill_enabled = True
        engine.fill_cache(np.zeros((0, N_LIMBS), np.uint32),
                          np.zeros((0, engine.cfg.quorum), np.int32),
                          np.zeros((0,), np.int32), 0)
        engine.cache_fill_enabled = fills_on
        engine.probe_cache(warm_keys)


def serve_open_loop(engine: ServeEngine, arrival_ts, keys, key,
                    klass=None, burst: int = 2,
                    duration: float | None = None,
                    overload_queue_factor: int = 8,
                    drain_round_cap: int | None = None,
                    clock=None, sleep=None,
                    admission: AdmissionControl | None = None,
                    sig_stage=None, signed=None,
                    signed_value_of=None) -> dict:
    """Drive the serve engine against an open-loop arrival schedule.

    ``arrival_ts``/``keys``(/``klass``) come from
    :func:`poisson_zipf_events` (or any sorted schedule).  The wall
    clock starts AFTER a warm pass compiled every program (compile must
    not masquerade as queueing delay); requests then arrive strictly by
    their timestamps — if the engine falls behind, the queue grows, and
    past ``overload_queue_factor × slots`` the run aborts with
    :class:`ServeOverloadError` (the open-loop contract: arrivals never
    wait for the server).  A request that hasn't converged within
    ``cfg.max_steps`` rounds of its admission is EXPIRED (slot
    retired and recycled, booked as ``expired``, never as a latency
    sample) — the serve twin of the batch engine's round cap, so a
    non-converging lookup can't squat on a slot until the queue reads
    as overload.  After the schedule is exhausted the loop drains
    in-flight work, capped at ``drain_round_cap`` rounds (leftovers
    are reported as ``in_flight`` — the checker's ``admitted ==
    completed + in_flight + expired`` conservation still holds).

    ``clock``/``sleep`` inject the time source (defaults:
    ``time.perf_counter`` / ``time.sleep``).  A deterministic virtual
    clock makes the whole loop — admission decisions, burst marks, the
    reconstructed latency samples — a pure function of the schedule,
    which is how ``tests/test_soak.py`` proves the soak loop's
    maintenance-off path BIT-identical to this one.  The cache and
    admission-control paths below are strictly additive: with the
    cache off and no admission policy, the loop makes the exact same
    program dispatches AND the exact same ``clock()``/``sleep()`` call
    sequence as before this round — the overlay proofs in
    tests/test_serve.py lean on both.

    With ``engine.cache`` attached, admissions go through the
    probe-fused admit: rows that hit complete instantly (zero service
    rounds, zero slots, latency = queueing delay, floored at the
    admission wall like every completion), misses fall through
    unchanged, and harvested completions fill the cache for their
    followers.  ``admission`` applies the per-class token buckets at
    the admission step (policy ``shed`` / ``queue`` / ``degrade`` —
    see :class:`AdmissionControl`); ``shed`` also converts the
    overload guard from exit-2 to graceful shedding, so an overload
    scenario ends with ``shed`` requests accounted instead of a dead
    bench.

    ``sig_stage`` (a :class:`~opendht_tpu.models.integrity.
    SignatureStage`) + ``signed`` (a ``[R]`` bool mask) admit a SIGNED
    request class through the pipelined host verify: each harvest's
    completed signed requests are submitted as ONE batch right after
    the harvest, so the worker thread's RSA verifies overlap the next
    device burst instead of serializing per value.
    ``signed_value_of(ri)`` maps a request index to the host value
    object the stage verifies (defaults to the index itself — the
    counting-only path the optional-dep null contract uses).  Both
    default off with zero behavioral change.

    Returns the serve report dict (see the module docstring for the
    latency reconstruction); per-request arrays are ordered by
    completion observation.
    """
    clock = clock or time.perf_counter
    sleep = sleep or time.sleep
    cfg, c = engine.cfg, engine.slots
    a_cap = engine.admit_cap
    use_cache = getattr(engine, "cache", None) is not None
    if admission is not None and admission.policy == "degrade" \
            and not use_cache:
        raise ValueError("admission policy 'degrade' answers from the "
                         "result cache — build the engine with "
                         "cache_slots > 0")
    keys = np.asarray(keys)        # host-side: see poisson_zipf_events
    r_total = len(arrival_ts)
    if klass is None:
        klass = np.full(r_total, "all")
    drain_cap = drain_round_cap or 4 * cfg.max_steps
    if duration is None:
        duration = float(arrival_ts[-1]) if r_total else 0.0
    # Absolute backstop: a run that can't even drain by 5x the schedule
    # horizon is overloaded whatever the queue gauge says.
    hard_wall = duration * 5.0 + 30.0

    # --- warm pass: compile admit/step/snapshot/expire off the clock.
    warm_serve_engine(engine)
    st = engine.empty()

    free = list(range(c - 1, -1, -1))     # pop() → lowest slot first
    occupied: dict[int, int] = {}         # slot -> request index
    queue: list[int] = []
    next_ev = 0
    rnd = 0
    adm_i = 0
    marks_r = [0]
    marks_w = [0.0]
    # Per completed request (completion-observation order):
    rec_req, rec_lat, rec_hops, rec_rounds, rec_found = [], [], [], [], []
    admit_wall = {}
    queue_depths = []
    occ_samples = []
    admitted = completed = expired = 0
    shed = cache_hits = cache_misses = degraded_hits = 0
    drain_rounds = 0
    overload = overload_queue_factor * c
    sig_submitted = 0
    sig_pending: list[int] = []     # completed signed ris this iter

    t0 = clock()
    while True:
        now = clock() - t0
        while next_ev < r_total and arrival_ts[next_ev] <= now:
            queue.append(next_ev)
            next_ev += 1
        if len(queue) > overload:
            if admission is not None \
                    and admission.policy in ("shed", "degrade"):
                # Graceful degradation: shed the NEWEST arrivals past
                # the bound (FIFO fairness for the older queue) and
                # keep serving — the reference drops over-quota
                # packets, it does not exit.  (``degrade`` sheds here
                # too: queue overflow is beyond what cache probes can
                # absorb; only ``queue`` keeps the hard error.)
                over = len(queue) - overload
                del queue[-over:]
                shed += over
            else:
                raise ServeOverloadError(
                    f"serve overload: admission queue reached "
                    f"{len(queue)} requests (> {overload_queue_factor}"
                    f" x {c} slots) at t={now:.2f}s — the arrival "
                    f"rate exceeds what this slot capacity sustains "
                    f"on this machine; lower --arrival-rate, raise "
                    f"--serve-slots, or shed with --admission shed")
        if now > hard_wall:
            if admission is not None \
                    and admission.policy in ("shed", "degrade"):
                # The shedding policies never exit 2: a run that blew
                # the hard wall sheds its ENTIRE backlog (queued and
                # not-yet-arrived — they would only queue behind it)
                # and falls through to drain the in-flight work, so
                # the report ends with honest sheds instead of a dead
                # bench.  Booked before the admission step: nothing
                # from the backlog is admitted after the wall.
                shed += len(queue) + (r_total - next_ev)
                queue.clear()
                next_ev = r_total
            else:
                raise ServeOverloadError(
                    f"serve overload: run exceeded the "
                    f"{hard_wall:.0f}s hard wall "
                    f"({r_total - next_ev + len(queue)} requests not "
                    f"yet admitted, {len(occupied)} in flight) — the "
                    f"arrival rate exceeds serve capacity on this "
                    f"machine")
        queue_depths.append(len(queue))

        # --- admission control: per-class token buckets gate which
        # queued requests may take a slot this iteration.
        cap = min(len(free), a_cap)
        degr: list[int] = []
        if admission is None:
            m = min(len(queue), cap)
            take = queue[:m]
            del queue[:m]
        else:
            # Every examined request is consumed (taken / shed /
            # degraded) except under the queue policy, which stops at
            # the first dry head — so the decisions cover a strict
            # PREFIX and one slice-delete keeps this O(examined),
            # like the admission-None path (queue.pop(0) per request
            # would be O(queue) each on the firehose leg's pinned
            # 2k-deep queue).
            take = []
            qi = 0
            per_key = admission.per_key_rate is not None
            while qi < len(queue) and len(take) < cap \
                    and len(degr) < a_cap:
                ri = queue[qi]
                if admission.allow(str(klass[ri]), now,
                                   key=(keys[ri].tobytes()
                                        if per_key else None)):
                    take.append(ri)
                elif admission.policy == "shed":
                    shed += 1
                elif admission.policy == "degrade":
                    degr.append(ri)
                else:           # queue: head-of-line waits for tokens
                    break
                qi += 1
            del queue[:qi]
            m = len(take)

        # --- admit one micro-batch into recycled slots
        if m:
            slots_np = np.full(a_cap, c, np.int32)
            keys_np = np.zeros((a_cap, N_LIMBS), np.uint32)
            for j, ri in enumerate(take):
                slot = free.pop()
                slots_np[j] = slot
                occupied[slot] = ri
                admit_wall[ri] = now
            keys_np[:m] = keys[np.asarray(take)]
            if use_cache:
                # Probe-fused admission: the hit row comes back with
                # the dispatch (the cache-on loop's one extra small
                # sync).  Hit rows never occupied their slot — the
                # scatter dropped them — so they free immediately and
                # complete AT the admission wall: latency is pure
                # queueing delay, service is zero rounds / zero hops.
                st, hit, h_found, h_hops = engine.admit_probed(
                    st, jnp.asarray(keys_np), jnp.asarray(slots_np),
                    jax.random.fold_in(key, adm_i), rnd)
                for j, ri in enumerate(take):
                    if not hit[j]:
                        cache_misses += 1
                        continue
                    slot = int(slots_np[j])
                    occupied.pop(slot)
                    free.append(slot)
                    rec_req.append(ri)
                    rec_lat.append(max(0.0,
                                       now - float(arrival_ts[ri])))
                    rec_hops.append(0)
                    rec_rounds.append(0)
                    rec_found.append(int(h_found[j, 0]) >= 0)
                    completed += 1
                    cache_hits += 1
                    if sig_stage is not None and signed is not None \
                            and signed[ri]:
                        sig_pending.append(ri)
            else:
                st = engine.admit(st, jnp.asarray(keys_np),
                                  jnp.asarray(slots_np),
                                  jax.random.fold_in(key, adm_i), rnd)
            adm_i += 1
            admitted += m

        # --- degrade policy: over-quota requests get one cache probe
        # — a hit answers (admitted + completed, zero rounds), a miss
        # sheds.  Costs no slot, no lookup round.
        if degr:
            dk = np.zeros((a_cap, N_LIMBS), np.uint32)
            dk[:len(degr)] = keys[np.asarray(degr)]
            d_hit, d_found, _d_hops = engine.probe_cache(
                jnp.asarray(dk))
            for j, ri in enumerate(degr):
                if d_hit[j]:
                    rec_req.append(ri)
                    rec_lat.append(max(0.0,
                                       now - float(arrival_ts[ri])))
                    rec_hops.append(0)
                    rec_rounds.append(0)
                    rec_found.append(int(d_found[j, 0]) >= 0)
                    admitted += 1
                    completed += 1
                    cache_hits += 1
                    degraded_hits += 1
                    if sig_stage is not None and signed is not None \
                            and signed[ri]:
                        sig_pending.append(ri)
                else:
                    shed += 1

        draining = next_ev >= r_total and not queue
        if draining and not occupied:
            break
        if not occupied and not queue:
            # Idle gap between arrivals: sleep to the next event rather
            # than spinning dispatches on an empty state.
            if next_ev < r_total:
                gap = arrival_ts[next_ev] - (clock() - t0)
                if gap > 0:
                    sleep(min(gap, 0.05))
                continue
            break

        # --- burst + harvest (the one sync per iteration)
        for _ in range(burst):
            st = engine.step(st, rnd)
            rnd += 1
        done, hops, adm_r, com_r, found = engine.snapshot(st)
        w = clock() - t0
        marks_r.append(rnd)
        marks_w.append(w)
        occ_samples.append(len(occupied) / c)

        fill_k, fill_f, fill_h = [], [], []
        for slot in [s for s, _ in occupied.items() if done[s]]:
            ri = occupied.pop(slot)
            free.append(slot)
            cr = int(com_r[slot])
            if cr < 0:
                # Done with no completion stamp can only mean a forced
                # retirement — book it as expired, never as a latency
                # sample (conservation: admitted = completed +
                # in-flight + expired).
                expired += 1
                continue
            # Round-end wall: interpolated inside the burst, floored at
            # the admission wall so queueing delay is counted and a
            # sub-burst completion can never interpolate before its own
            # arrival.  Only the last two marks matter: every done row
            # is harvested in the burst it completed (the snapshot
            # follows the burst and pops all done slots), so walking
            # the whole mark history per completion would be O(n²)
            # host work inside the clocked loop for nothing.
            cw = float(np.interp(cr + 1, marks_r[-2:], marks_w[-2:]))
            cw = max(cw, admit_wall[ri])
            rec_req.append(ri)
            rec_lat.append(cw - float(arrival_ts[ri]))
            rec_hops.append(int(hops[slot]))
            rec_rounds.append(cr - int(adm_r[slot]) + 1)
            rec_found.append(int(found[slot, 0]) >= 0)
            completed += 1
            if sig_stage is not None and signed is not None \
                    and signed[ri]:
                sig_pending.append(ri)
            if use_cache:
                fill_k.append(keys[ri])
                fill_f.append(found[slot])
                fill_h.append(int(hops[slot]))
        if use_cache and fill_k:
            # Fill the harvest's completions (the miss path's results)
            # so their followers hit: one donated fixed-width fill
            # dispatch, no sync.
            engine.fill_cache(np.asarray(fill_k), np.asarray(fill_f),
                              np.asarray(fill_h), rnd)
        if sig_stage is not None and sig_pending:
            # ONE batch per harvest: the stage's worker verifies while
            # the NEXT iteration's burst runs on device — the
            # pipelined signature contract.
            sig_stage.submit([signed_value_of(ri) if signed_value_of
                              else ri for ri in sig_pending])
            sig_submitted += len(sig_pending)
            sig_pending = []

        # --- expiry: rows past their round budget (the batch engine's
        # max_steps cap) retire instead of squatting on their slot.
        # One fixed-width (padded) expire program; a pathological
        # backlog wider than admit_cap drains over later iterations.
        stale = [s for s in occupied
                 if not done[s] and rnd - int(adm_r[s]) >= cfg.max_steps]
        if stale:
            batch = stale[:a_cap]
            sl = np.full(a_cap, c, np.int32)
            sl[:len(batch)] = batch
            st = engine.expire(st, jnp.asarray(sl))
            for slot in batch:
                ri = occupied.pop(slot)
                free.append(slot)
                expired += 1
        if draining:
            drain_rounds += burst
            if drain_rounds > drain_cap:
                break

    elapsed = clock() - t0
    if sig_stage is not None and sig_pending:
        # Completions from an iteration that exited before its burst
        # (idle-gap break / drain end) still reach the stage.
        sig_stage.submit([signed_value_of(ri) if signed_value_of
                          else ri for ri in sig_pending])
        sig_submitted += len(sig_pending)
        sig_pending = []
    return {
        "slots": c,
        "admit_cap": a_cap,
        "burst": burst,
        "admitted": admitted,
        "completed": completed,
        "expired": expired,
        "in_flight": len(occupied),
        "never_admitted": len(queue) + (r_total - next_ev),
        "shed": shed,
        "cache_hits": cache_hits,
        "cache_misses": cache_misses,
        "degraded_hits": degraded_hits,
        "cache_slots": getattr(engine, "cache_slots", 0),
        "admission_policy": admission.policy if admission else None,
        "sig_submitted": sig_submitted,
        "rounds": rnd,
        "elapsed_s": elapsed,
        "sustained_rps": completed / elapsed if elapsed > 0 else 0.0,
        "request": np.asarray(rec_req, np.int64),
        "latency_s": np.asarray(rec_lat, np.float64),
        "hops": np.asarray(rec_hops, np.int64),
        "service_rounds": np.asarray(rec_rounds, np.int64),
        "found_nonempty": np.asarray(rec_found, bool),
        "klass": np.asarray(klass)[np.asarray(rec_req, np.int64)]
        if completed else np.asarray([], dtype="<U4"),
        "queue_depth_mean": float(np.mean(queue_depths))
        if queue_depths else 0.0,
        "queue_depth_max": int(np.max(queue_depths))
        if queue_depths else 0,
        "slot_occupancy_frac": float(np.mean(occ_samples))
        if occ_samples else 0.0,
        "burst_marks": list(zip(marks_r, marks_w)),
    }


def closed_loop_replay(swarm: Swarm, cfg: SwarmConfig,
                       targets: jax.Array, key: jax.Array,
                       engine: ServeEngine | None = None
                       ) -> tuple[LookupResult, LookupState]:
    """Feed a fixed batch through the serve engine's admit/step path
    (slots = L, everything admitted at round 0) and run to completion.

    This is the serve twin of ``lookup(swarm, cfg, targets, key)`` and
    must produce bit-identical found/hops/done for the same key: the
    admission seed exchange is ``init_impl`` with the batch engine's
    origin draw, the rounds are the same shared step, and finished
    slots simply freeze (nothing recycles in a closed-loop replay) —
    asserted in tests/test_serve.py, mirroring test_compaction.py's
    seed-identity pattern.  Returns ``(LookupResult, final state)`` so
    callers can inspect the lifecycle rows.

    ``engine`` overrides the default local engine — passing a
    :class:`ShardedServeEngine` (slots = admit_cap = L) replays
    through the ROUTED admit/step and must be bit-identical to
    ``sharded_lookup(..., compact=False)`` for the same key: the
    routed init folds the key per shard exactly like the burst
    formulation's init body, and the routed step is the same donated
    program — the slot-recycling admission equivalence, proven on the
    mesh.  The replay always uses the PLAIN admit (never the cache
    probe): replay semantics are the batch engine's.
    """
    l = targets.shape[0]
    eng = engine if engine is not None \
        else ServeEngine(swarm, cfg, slots=l, admit_cap=l)
    if eng.slots != l or eng.admit_cap < l:
        raise ValueError(f"closed-loop replay needs slots == L == "
                         f"admit_cap; engine has slots={eng.slots}, "
                         f"admit_cap={eng.admit_cap} for L={l}")
    st = eng.empty()
    st = eng.admit(st, targets, jnp.arange(l, dtype=jnp.int32), key, 0)
    rnd = 0
    burst = burst_schedule(cfg)
    while rnd < cfg.max_steps:
        n = min(burst, cfg.max_steps - rnd)
        for _ in range(n):
            st = eng.step(st, rnd)
            rnd += 1
        # Per-BURST done poll (explicit device_get: bool() on a device
        # array is an implicit D2H transfer, forbidden under the
        # strict transfer-guard replay).
        # graftlint: disable=sync-in-loop (per-burst done-check readback, amortized over >=2 device rounds — the BURST replay's contract; resident_closed_loop_replay runs the same workload with zero in-loop polls)
        if bool(jax.device_get(jnp.all(st.done))):
            break
        burst = 2
    res = LookupResult(found=_finalize(swarm.ids, st, cfg),
                       hops=st.hops, done=st.done)
    return res, st


# ---------------------------------------------------------------------------
# device-resident serve loop (ISSUE 20)
# ---------------------------------------------------------------------------
#
# The burst engines above still pay one host round-trip PER BURST (the
# ``engine.snapshot`` harvest readback), and PR 14's negative result
# measured exactly that cost: 1-round bursts ran 13 % slower because
# host dispatch serializes against device execution.  The resident
# loop is the reference's single-threaded event loop
# (include/opendht/scheduler.h:38-123) rebuilt as ONE device program:
# admit → rounds → harvest fused into a single jit whose admission
# rides a device ring buffer the host fills ahead of time and whose
# completions come back as one bulk output the host drains one macro
# step LATER (double-buffered: macro k+1 is dispatched before macro
# k's output is read, so the only host sync in the steady state — the
# ``device_get`` of the PREVIOUS step's output — overlaps the current
# step's device compute instead of serializing against it).
#
# Ring contract (all device-side, scanned by ``_ring_enqueue`` /
# ``_ring_pop`` inside the resident program):
#
# * ``rq_*[R]`` is a circular request queue; ``head``/``tail`` are
#   MONOTONIC i32 counters (positions are taken mod R), so
#   ``tail - head`` is the backlog and fullness needs no wrap flag.
# * The host may enqueue at most ``R - backlog`` rows per step;
#   overflow rows are counted in ``shed`` and dropped (the open-loop
#   driver throttles hand-off so this stays 0 — excess waits in the
#   HOST queue under the overload guard, never silently on device).
# * Admission pops ``min(backlog, free slots, admit_cap)`` rows into
#   the LOWEST free slots (a stable argsort over the free mask — the
#   deterministic order the closed-loop replay identity leans on),
#   seeds them with the batch engine's ``init_impl`` exchange, and
#   stamps ``slot_req`` so completions can be attributed without any
#   host-side slot bookkeeping.
# * Completions drain through the bulk ``ResidentOut`` rows exactly
#   once: the step frees a completed slot (``admitted_round = -1``)
#   in the same program that reported it.

class ServeRings(NamedTuple):
    """Device-resident admission ring + slot attribution (a pytree).

    ``rq_keys [R,5]`` / ``rq_req [R]`` / ``rq_cls [R]`` — the circular
    request queue (key limbs, host request index, work class;
    ``rq_req = -1`` means never-written).  ``head``/``tail``/``shed``
    — monotonic pop/accept/overflow counters.  ``slot_req [C]`` /
    ``slot_cls [C]`` — which request currently owns each lookup slot
    (-1 free), the device twin of the burst loop's host ``occupied``
    dict."""
    rq_keys: jax.Array
    rq_req: jax.Array
    rq_cls: jax.Array
    head: jax.Array
    tail: jax.Array
    shed: jax.Array
    slot_req: jax.Array
    slot_cls: jax.Array


class ResidentOut(NamedTuple):
    """Bulk per-macro-step output of the resident program — the ONE
    readback the host drains (one macro step late).

    Scalars: ``adm``/``hits`` rows admitted / answered from cache this
    step, ``queued`` ring backlog after admission, ``head``/``tail``/
    ``shed`` the ring's monotonic counters, ``rounds_run`` actual
    while-loop trips (early exit when everything drains).
    ``hit*`` rows are admission-width ``[A]``: cache hits answered at
    pop time without ever occupying a slot.  ``comp*`` rows are
    slot-width ``[C]``: slots that finished (or expired,
    ``comp_com = -1``) during this step — drained exactly once, the
    program frees them after reporting.  ``rung_counts`` are the
    in-jit width-ladder selections (``[1]`` when the ladder is off);
    ``xchg_*_rows`` count routed-exchange rows on the sharded engine
    (0 locally) — the counter that proves mesh cache hits skip the
    ``all_to_all``."""
    adm: jax.Array
    hits: jax.Array
    queued: jax.Array
    head: jax.Array
    tail: jax.Array
    shed: jax.Array
    rounds_run: jax.Array
    hit: jax.Array
    hit_req: jax.Array
    hit_found: jax.Array
    hit_hops: jax.Array
    comp: jax.Array
    comp_req: jax.Array
    comp_cls: jax.Array
    comp_hops: jax.Array
    comp_adm: jax.Array
    comp_com: jax.Array
    comp_found: jax.Array
    rung_counts: jax.Array
    xchg_init_rows: jax.Array
    xchg_round_rows: jax.Array


@partial(jax.jit, static_argnames=("slots", "ring_slots"))
def empty_serve_rings(slots: int, ring_slots: int) -> ServeRings:
    """All-empty rings: zero backlog, every slot unattributed."""
    return ServeRings(
        rq_keys=jnp.zeros((ring_slots, N_LIMBS), jnp.uint32),
        rq_req=jnp.full((ring_slots,), -1, jnp.int32),
        rq_cls=jnp.full((ring_slots,), -1, jnp.int32),
        head=jnp.int32(0),
        tail=jnp.int32(0),
        shed=jnp.int32(0),
        slot_req=jnp.full((slots,), -1, jnp.int32),
        slot_cls=jnp.full((slots,), -1, jnp.int32))


def _ring_enqueue(rings: ServeRings, keys: jax.Array, reqs: jax.Array,
                  cls: jax.Array, n_new: jax.Array) -> ServeRings:
    """Accept ``n_new`` (≤ admission width) rows into the ring.

    Rows past the ring's free space are SHED (counted, dropped) —
    full-ring backpressure is explicit, never a silent overwrite of
    queued work.  Traced inside the resident jits."""
    a = keys.shape[0]
    r = rings.rq_keys.shape[0]
    n_new = jnp.clip(jnp.asarray(n_new, jnp.int32), 0, a)
    space = jnp.int32(r) - (rings.tail - rings.head)
    n_in = jnp.minimum(n_new, space)
    j = jnp.arange(a, dtype=jnp.int32)
    qpos = jnp.where(j < n_in, (rings.tail + j) % jnp.int32(r),
                     jnp.int32(r))
    return rings._replace(
        rq_keys=rings.rq_keys.at[qpos].set(keys, mode="drop"),
        rq_req=rings.rq_req.at[qpos].set(reqs, mode="drop"),
        rq_cls=rings.rq_cls.at[qpos].set(cls, mode="drop"),
        tail=rings.tail + n_in,
        shed=rings.shed + (n_new - n_in))


def _ring_pop(st: LookupState, rings: ServeRings, a: int):
    """Pop up to ``a`` queued rows and pair them with free slots.

    A slot is FREE iff ``done & admitted_round < 0`` (the engines'
    invariant).  Free slots are taken LOWEST-INDEX-FIRST via a stable
    argsort over the free mask — on an all-free state slot j serves
    popped row j, which is what makes the closed-loop replay
    bit-identical to the batch engine's row order.  Returns
    ``(rings, pkeys [a,5], preq [a], pcls [a], cand [a], valid [a])``
    with the ring head already advanced; rows ``j >= p`` are padding
    (``valid`` False, ``preq = -1``)."""
    c = st.done.shape[0]
    r = rings.rq_keys.shape[0]
    free = st.done & (st.admitted_round < 0)
    n_free = jnp.sum(free.astype(jnp.int32))
    backlog = rings.tail - rings.head
    p = jnp.minimum(jnp.minimum(backlog, n_free), jnp.int32(a))
    j = jnp.arange(a, dtype=jnp.int32)
    valid = j < p
    rpos = (rings.head + j) % jnp.int32(r)
    pkeys = rings.rq_keys[rpos]
    preq = jnp.where(valid, rings.rq_req[rpos], -1)
    pcls = jnp.where(valid, rings.rq_cls[rpos], -1)
    order = jnp.argsort(~free, stable=True).astype(jnp.int32)
    cand = order[jnp.clip(j, 0, c - 1)]
    return (rings._replace(head=rings.head + p), pkeys, preq, pcls,
            cand, valid)


def _cache_fill_sorted(cache: ResultCache, keys: jax.Array,
                       found: jax.Array, hops: jax.Array,
                       mask: jax.Array, rnd: jax.Array) -> ResultCache:
    """In-jit cache fill with DEVICE-side slot dedup (the resident
    twin of ``fill_cache``'s host dedup): one stable sort groups rows
    by cache slot and the LAST row of each group wins, so the five
    per-field scatters see unique indices — the
    :func:`_cache_fill`-documented mixed-winner hazard cannot occur.
    Masked rows sort to the drop sentinel."""
    k_slots = cache.keys.shape[0]
    m = keys.shape[0]
    cs = jnp.where(mask, _cache_slot_of(keys, k_slots),
                   jnp.int32(k_slots))
    rows = jnp.arange(m, dtype=jnp.int32)
    cs_s, row_s = jax.lax.sort((cs, rows), dimension=0, num_keys=1,
                               is_stable=True)
    last = jnp.concatenate([cs_s[1:] != cs_s[:-1],
                            jnp.ones((1,), bool)])
    eff = jnp.where(last & (cs_s < k_slots), cs_s, jnp.int32(k_slots))
    ep = jnp.broadcast_to(cache.epoch, eff.shape)
    r32 = jnp.broadcast_to(jnp.asarray(rnd, jnp.int32), eff.shape)
    return cache._replace(
        keys=cache.keys.at[eff].set(keys[row_s], mode="drop"),
        found=cache.found.at[eff].set(found[row_s], mode="drop"),
        hops=cache.hops.at[eff].set(hops[row_s], mode="drop"),
        fill_round=cache.fill_round.at[eff].set(r32, mode="drop"),
        fill_epoch=cache.fill_epoch.at[eff].set(ep, mode="drop"))


def _resident_rounds(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
                     rnd0: jax.Array, rounds: int,
                     rung_block: int | None):
    """The fused round loop: ``rounds`` lock-step rounds as ONE
    ``lax.while_loop`` with on-device early exit (everything-done
    states stop paying round dispatches — no host poll involved).

    ``rung_block`` folds PR 14's width ladder in as IN-JIT rung
    selection: the ladder's ``wneed`` watermark (the exact
    ``_pending_and_wneed`` formula) is recomputed on device each
    round and a ``lax.switch`` picks the narrowest
    ``rank_merge_round_d0_w`` rung covering it — bit-identical to the
    full-width merge by the rung guard, so this is purely a pricing
    decision.  PR 14 measured the switch 2.5× SLOWER than host-side
    rung selection on XLA:CPU when each round was its own dispatch;
    inside the resident loop the host-dispatch rationale is gone, so
    the verdict is re-measured here (BASELINE.md).  Returns
    ``(state, rounds_run, rung_counts)``."""
    if _swarm.resolve_merge_impl(cfg) == "pallas-round":
        if rung_block is not None:
            raise ValueError(
                "rung_block width selection needs the XLA rank merge; "
                "merge_impl='pallas-round' fuses its own fixed-width "
                "merge — drop one of the two")

        def one_round(st, rnd):
            return _swarm._fused_round_step(swarm, cfg, st, rnd=rnd), 0
        n_rungs = 1
    elif rung_block is None:
        def one_round(st, rnd):
            return step_impl(swarm.ids, swarm.alive,
                             _local_respond(swarm, cfg), cfg, st,
                             rnd=rnd), 0
        n_rungs = 1
    else:
        full_w = cfg.alpha * 2 * cfg.bucket_k
        rungs = merge_ladder_widths(full_w, rung_block)
        thresholds = jnp.asarray(rungs, jnp.int32)
        n_rungs = len(rungs)

        def _branch(w):
            mw = None if w >= full_w else w

            def run(st, rnd):
                return step_impl(swarm.ids, swarm.alive,
                                 _local_respond(swarm, cfg), cfg, st,
                                 rnd=rnd, merge_w=mw)
            return run
        branches = [_branch(w) for w in rungs]

        def one_round(st, rnd):
            # In-jit wneed: the widest pending row's solicitation
            # width (mirrors _pending_and_wneed without the readback).
            unq = jnp.sum((st.idx >= 0) & ~st.queried, axis=1)
            blocks = jnp.where(st.done, 0,
                               jnp.minimum(cfg.alpha, unq))
            wneed = jnp.max(blocks) * (2 * cfg.bucket_k)
            bi = jnp.clip(
                jnp.searchsorted(thresholds, wneed, side="left"),
                0, n_rungs - 1).astype(jnp.int32)
            return jax.lax.switch(bi, branches, st, rnd), bi

    def cond(carry):
        st, it, _counts = carry
        return (it < jnp.int32(rounds)) & jnp.any(~st.done)

    def body(carry):
        st, it, counts = carry
        st, bi = one_round(st, rnd0 + it)
        return st, it + 1, counts.at[bi].add(1)

    st, it, counts = jax.lax.while_loop(
        cond, body,
        (st, jnp.int32(0), jnp.zeros((n_rungs,), jnp.int32)))
    return st, it, counts


def _resident_tail(ids: jax.Array, cfg: SwarmConfig, st: LookupState,
                   rings: ServeRings, cache: ResultCache | None,
                   rnd_end: jax.Array, expire: bool):
    """Shared harvest tail of the resident programs (local and
    sharded): in-jit expiry, completion detection, finalize, in-jit
    cache fill, and slot freeing — the completed rows drain exactly
    once because the SAME program that reports them frees them.
    Returns ``(st, rings, cache, comp, fin)``."""
    if expire:
        stale = (~st.done) & (st.admitted_round >= 0) \
            & (rnd_end - st.admitted_round >= cfg.max_steps)
        st = st._replace(done=st.done | stale)
    comp = st.done & (st.admitted_round >= 0)
    fin = _finalize(ids, st, cfg)
    if cache is not None:
        # Fill only true completions with non-empty heads — never
        # expired rows, never negatives (the fill_cache contract).
        fmask = comp & (st.completed_round >= 0) & (fin[:, 0] >= 0)
        cache = _cache_fill_sorted(cache, st.targets, fin, st.hops,
                                   fmask, rnd_end)
    return st, rings, cache, comp, fin


def _resident_core(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
                   rings: ServeRings, cache: ResultCache | None,
                   keys: jax.Array, reqs: jax.Array, cls: jax.Array,
                   key: jax.Array, n_new: jax.Array, rnd0: jax.Array,
                   rounds: int, expire: bool, rung_block: int | None):
    """The local resident macro step: enqueue → pop/probe/admit →
    fused rounds → harvest/fill/free, ONE program end to end."""
    c = st.done.shape[0]
    a = keys.shape[0]
    rings = _ring_enqueue(rings, keys, reqs, cls, n_new)
    rings, pkeys, preq, pcls, cand, valid = _ring_pop(st, rings, a)
    if cache is not None:
        hit_raw, h_found, h_hops = _probe_impl(cache, pkeys)
        hit = hit_raw & valid
    else:
        hit = jnp.zeros((a,), bool)
        h_found = jnp.full((a, cfg.quorum), -1, jnp.int32)
        h_hops = jnp.zeros((a,), jnp.int32)
    take = valid & ~hit
    # Full-width origin draw with the caller's key DIRECTLY — the
    # replay identity needs this to match the batch engine's
    # ``_sample_origins(key, alive, l)`` bit-for-bit; non-admitted
    # rows' init results are dropped by the sentinel scatter exactly
    # like ``_admit_cached``'s hit rows.
    origins = _sample_origins(key, swarm.alive, a)
    eff = jnp.where(take, cand, jnp.int32(c))
    new = init_impl(swarm.ids, _local_respond(swarm, cfg), cfg, pkeys,
                    origins)
    st = _scatter_rows_into(st, new, eff, rnd0)
    rings = rings._replace(
        slot_req=rings.slot_req.at[eff].set(preq, mode="drop"),
        slot_cls=rings.slot_cls.at[eff].set(pcls, mode="drop"))
    st, rounds_run, rung_counts = _resident_rounds(
        swarm, cfg, st, rnd0, rounds, rung_block)
    rnd_end = rnd0 + jnp.int32(rounds)
    st, rings, cache, comp, fin = _resident_tail(
        swarm.ids, cfg, st, rings, cache, rnd_end, expire)
    out = ResidentOut(
        adm=jnp.sum(take.astype(jnp.int32)),
        hits=jnp.sum(hit.astype(jnp.int32)),
        queued=rings.tail - rings.head,
        head=rings.head, tail=rings.tail, shed=rings.shed,
        rounds_run=rounds_run,
        hit=hit,
        hit_req=jnp.where(hit, preq, -1),
        hit_found=h_found, hit_hops=h_hops,
        comp=comp,
        comp_req=jnp.where(comp, rings.slot_req, -1),
        comp_cls=jnp.where(comp, rings.slot_cls, -1),
        comp_hops=st.hops,
        comp_adm=st.admitted_round,
        comp_com=st.completed_round,
        comp_found=fin,
        rung_counts=rung_counts,
        xchg_init_rows=jnp.int32(0),
        xchg_round_rows=jnp.int32(0))
    # Free the reported slots: done stays True, lifecycle clears —
    # the FREE invariant — and attribution clears with it.
    st = st._replace(
        admitted_round=jnp.where(comp, -1, st.admitted_round))
    rings = rings._replace(
        slot_req=jnp.where(comp, -1, rings.slot_req),
        slot_cls=jnp.where(comp, -1, rings.slot_cls))
    return st, rings, cache, out


@partial(jax.jit,
         static_argnames=("cfg", "rounds", "expire", "rung_block"),
         donate_argnums=(2, 3))
def _resident_step(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
                   rings: ServeRings, keys: jax.Array,
                   reqs: jax.Array, cls: jax.Array, key: jax.Array,
                   n_new: jax.Array, rnd0: jax.Array, *, rounds: int,
                   expire: bool = True,
                   rung_block: int | None = None):
    """Cache-off resident macro step (state + rings donated — the
    resident carries are single-owner like the burst loops')."""
    st, rings, _cache, out = _resident_core(
        swarm, cfg, st, rings, None, keys, reqs, cls, key, n_new,
        rnd0, rounds, expire, rung_block)
    return st, rings, out


@partial(jax.jit,
         static_argnames=("cfg", "rounds", "expire", "rung_block"),
         donate_argnums=(2, 3, 4))
def _resident_step_cached(swarm: Swarm, cfg: SwarmConfig,
                          st: LookupState, rings: ServeRings,
                          cache: ResultCache, keys: jax.Array,
                          reqs: jax.Array, cls: jax.Array,
                          key: jax.Array, n_new: jax.Array,
                          rnd0: jax.Array, *, rounds: int,
                          expire: bool = True,
                          rung_block: int | None = None):
    """Resident macro step with the ResultCache riding INSIDE the
    program: pop-time probe (a hit never occupies a slot and its
    answer never leaves the device until the bulk drain) and
    harvest-time fill with device-side slot dedup
    (:func:`_cache_fill_sorted`) — no per-admission host sync at all,
    unlike the burst engines' ``admit_probed``."""
    st, rings, cache, out = _resident_core(
        swarm, cfg, st, rings, cache, keys, reqs, cls, key, n_new,
        rnd0, rounds, expire, rung_block)
    return st, rings, cache, out


class ResidentServeEngine(ServeEngine):
    """Serve engine whose whole iteration is ONE device program
    (:func:`_resident_step`/``_cached``): the host only fills the
    admission ring and drains the bulk output, one macro step late.

    ``ring_slots`` (default ``4 × admit_cap``) sizes the device
    admission ring; it must be ≥ ``2 × admit_cap`` so the open-loop
    driver's hand-off throttle (which holds back up to one in-flight
    enqueue batch of uncertainty) can always prove space and device
    sheds stay 0.  ``rounds_per_iter`` is the macro step's round
    budget (the resident analogue of the burst width — the loop early-
    exits on device when everything drains, so overshoot is cheap).
    ``rung_block`` turns on in-jit width-ladder rung selection (see
    :func:`_resident_rounds`); incompatible with
    ``merge_impl='pallas-round'``."""

    def __init__(self, swarm: Swarm, cfg: SwarmConfig, slots: int,
                 admit_cap: int | None = None, cache_slots: int = 0,
                 ring_slots: int | None = None,
                 rounds_per_iter: int = 2,
                 rung_block: int | None = None):
        super().__init__(swarm, cfg, slots, admit_cap,
                         cache_slots=cache_slots)
        self.ring_slots = ring_slots or 4 * self.admit_cap
        if self.ring_slots < 2 * self.admit_cap:
            raise ValueError(
                f"ring_slots {self.ring_slots} must be >= 2 x "
                f"admit_cap {self.admit_cap}: the host throttle "
                f"reserves one in-flight enqueue batch of headroom")
        if rounds_per_iter < 1:
            raise ValueError("rounds_per_iter must be >= 1")
        self.rounds_per_iter = rounds_per_iter
        if rung_block is not None \
                and _swarm.resolve_merge_impl(cfg) == "pallas-round":
            raise ValueError(
                "rung_block width selection needs the XLA rank "
                "merge; merge_impl='pallas-round' fuses its own "
                "fixed-width merge — drop one of the two")
        self.rung_block = rung_block

    def empty_rings(self) -> ServeRings:
        return empty_serve_rings(self.slots, self.ring_slots)

    def macro_step(self, st, rings, keys, reqs, cls, key, n_new, rnd0,
                   rounds: int | None = None, expire: bool = True,
                   use_cache: bool | None = None):
        """One resident macro step.  ``keys/reqs/cls`` are the padded
        ``[admit_cap]``-wide enqueue batch (``n_new`` real rows);
        returns ``(st, rings, out)`` with NOTHING synced — the caller
        drains ``out`` whenever it likes (the double buffer)."""
        rounds = self.rounds_per_iter if rounds is None else rounds
        if use_cache is None:
            use_cache = self.cache is not None
        if use_cache:
            if self.cache is None:
                raise ValueError("use_cache=True needs cache_slots>0")
            st, rings, self.cache, out = _resident_step_cached(
                self.swarm, self.cfg, st, rings, self.cache, keys,
                reqs, cls, key, dev_i32(n_new), dev_i32(rnd0),
                rounds=rounds, expire=expire,
                rung_block=self.rung_block)
        else:
            st, rings, out = _resident_step(
                self.swarm, self.cfg, st, rings, keys, reqs, cls, key,
                dev_i32(n_new), dev_i32(rnd0), rounds=rounds,
                expire=expire, rung_block=self.rung_block)
        return st, rings, out

    def warm_resident(self, rounds: int | None = None) -> None:
        """Compile the macro program off the clock on throwaway
        carries (same shapes, zero work — nothing queued)."""
        a = self.admit_cap
        st = self.empty()
        rings = self.empty_rings()
        keys = jnp.zeros((a, N_LIMBS), jnp.uint32)
        reqs = jnp.full((a,), -1, jnp.int32)
        cls = jnp.full((a,), -1, jnp.int32)
        st, rings, out = self.macro_step(
            st, rings, keys, reqs, cls, jax.random.PRNGKey(0), 0, 0,
            rounds=rounds)
        jax.block_until_ready(out)


class ShardedResidentServeEngine(ResidentServeEngine):
    """Mesh resident engine: the macro step is
    :func:`opendht_tpu.parallel.sharded._sharded_resident_step` —
    rings and cache replicated, state sharded, the round loop a
    psum-synchronised ``while_loop`` under ``shard_map``, and the
    cache probed BEFORE the routed init so mesh hits never ride the
    ``all_to_all`` (``out.xchg_init_rows`` proves it).  No
    ``rung_block`` (the routed step prices its own exchange)."""

    def __init__(self, swarm: Swarm, cfg: SwarmConfig, slots: int,
                 mesh, capacity_factor: float = 2.0,
                 admit_cap: int | None = None, cache_slots: int = 0,
                 ring_slots: int | None = None,
                 rounds_per_iter: int = 2):
        super().__init__(swarm, cfg, slots, admit_cap,
                         cache_slots=cache_slots,
                         ring_slots=ring_slots,
                         rounds_per_iter=rounds_per_iter)
        from ..parallel.mesh import AXIS
        self.mesh, self.capacity_factor = mesh, capacity_factor
        d = mesh.shape[AXIS]
        if slots % d or self.admit_cap % d:
            raise ValueError(f"serve slots {slots} and admit_cap "
                             f"{self.admit_cap} must divide the "
                             f"{d}-device mesh")

    @property
    def exchange_row_bytes(self) -> int:
        """Bytes one admission/solicitation row pays on the routed
        exchange after the slim return leg: an 8-byte query row plus
        a ``2K``-candidate response row (u16 pair windows and i32
        index rows both land at ``8·K`` bytes)."""
        return 8 + 8 * self.cfg.bucket_k

    def macro_step(self, st, rings, keys, reqs, cls, key, n_new, rnd0,
                   rounds: int | None = None, expire: bool = True,
                   use_cache: bool | None = None):
        from ..parallel.sharded import _sharded_resident_step
        rounds = self.rounds_per_iter if rounds is None else rounds
        if use_cache is None:
            use_cache = self.cache is not None
        if use_cache and self.cache is None:
            raise ValueError("use_cache=True needs cache_slots>0")
        cache = self.cache if use_cache else None
        st, rings, cache, out = _sharded_resident_step(
            self.swarm, self.cfg, st, rings, cache, keys, reqs, cls,
            key, dev_i32(n_new), dev_i32(rnd0), self.mesh,
            self.capacity_factor, rounds=rounds, expire=expire)
        if use_cache:
            self.cache = cache
        return st, rings, out


def resident_closed_loop_replay(swarm: Swarm, cfg: SwarmConfig,
                                targets: jax.Array, key: jax.Array,
                                engine: ResidentServeEngine | None
                                = None):
    """Closed-loop replay through the RESIDENT program: enqueue the
    whole batch, run one macro step with the full round budget, and
    read the bulk output — must be bit-identical (found/hops/done) to
    :func:`closed_loop_replay` and hence to the batch engines, for
    the same key (asserted in tests/test_serve.py).

    The identity chain: an all-free state pops row j into slot j (the
    stable argsort), the origin draw is the caller's key direct and
    full-width (the batch draw), the seed exchange is ``init_impl``,
    and each while-loop trip is the SAME shared round at the same
    round index.  ``expire=False`` because the batch engines report
    stragglers as ``done=False`` instead of retiring them; the replay
    always runs cache-off (replay semantics are the batch engine's).
    Returns ``(LookupResult, final state, ResidentOut)``."""
    l = targets.shape[0]
    eng = engine if engine is not None \
        else ResidentServeEngine(swarm, cfg, slots=l, admit_cap=l,
                                 ring_slots=2 * l)
    if eng.slots != l or eng.admit_cap < l:
        raise ValueError(f"resident replay needs slots == L == "
                         f"admit_cap; engine has slots={eng.slots}, "
                         f"admit_cap={eng.admit_cap} for L={l}")
    st = eng.empty()
    rings = eng.empty_rings()
    st, rings, out = eng.macro_step(
        st, rings, jnp.asarray(targets),
        jnp.arange(l, dtype=jnp.int32), jnp.zeros((l,), jnp.int32),
        key, l, 0, rounds=cfg.max_steps, expire=False,
        use_cache=False)
    res = LookupResult(found=out.comp_found, hops=out.comp_hops,
                       done=out.comp)
    return res, st, out


def serve_resident(engine: ResidentServeEngine, arrival_ts, keys, key,
                   klass=None, duration: float | None = None,
                   overload_queue_factor: int = 8,
                   drain_round_cap: int | None = None,
                   clock=None, sleep=None,
                   admission: AdmissionControl | None = None,
                   host_orchestration_budget: float = 0.05) -> dict:
    """Open-loop driver for the resident engine — the double-buffered
    twin of :func:`serve_open_loop`.

    Each host iteration (1) pulls due arrivals into the host queue,
    (2) hands at most one padded enqueue batch to the device ring —
    throttled to ``ring − backlog − admit_cap`` rows so the device
    ring NEVER sheds (excess waits in the host queue under the same
    overload guard as the burst loop), (3) dispatches macro step
    ``k+1``, and only then (4) drains macro step ``k``'s bulk output
    — the one ``device_get`` in the steady state, which therefore
    overlaps step ``k+1``'s device compute instead of serializing
    against it.  Latency is reconstructed exactly like the burst
    loop's (round-end walls interpolated between macro marks, floored
    at the request's hand-off wall so queueing delay is counted);
    marks are stamped when the macro DISPATCH returns — compute end
    on the synchronous backend, the same instant the burst loop's
    per-burst sync stamps — not at the double-buffered drain a macro
    later.

    ``admission`` supports policies ``shed`` and ``queue`` (applied
    host-side at hand-off); ``degrade`` needs the per-batch host
    probe the resident loop exists to avoid — build the burst engine
    for that.  The report is the burst loop's dict plus a
    ``"resident"`` block (ring counters, host-orchestration share,
    in-jit rung counts, routed-exchange rows when sharded).
    ``host_orchestration_frac`` is the wall share that is NEITHER the
    macro dispatch (device compute runs inline in that call on a
    synchronous backend), nor the drain's blocked ``device_get``, nor
    idle sleep — i.e. genuine host bookkeeping;
    ``host_orchestration_budget`` is recorded alongside for the
    checker's <5 % gate."""
    clock = clock or time.perf_counter
    sleep = sleep or time.sleep
    cfg, c = engine.cfg, engine.slots
    a_cap = engine.admit_cap
    rounds = engine.rounds_per_iter
    use_cache = engine.cache is not None
    if admission is not None and admission.policy == "degrade":
        raise ValueError(
            "admission policy 'degrade' needs the burst engine's "
            "host-side cache probe; the resident loop supports "
            "'shed' and 'queue'")
    keys = np.asarray(keys)
    arrival_np = np.asarray(arrival_ts, np.float64)
    r_total = len(arrival_np)
    if klass is None:
        klass = np.full(r_total, "all")
    drain_cap = drain_round_cap or 4 * cfg.max_steps
    if duration is None:
        duration = float(arrival_ts[-1]) if r_total else 0.0
    hard_wall = duration * 5.0 + 30.0

    engine.warm_resident()
    st = engine.empty()
    rings = engine.empty_rings()

    queue: list[int] = []
    enq_wall = np.zeros(r_total, np.float64)
    next_ev = 0
    rnd = 0
    it_i = 0
    marks_r = [0]
    marks_w = [0.0]
    # Interp window: a completion's round is ≥ rnd_end − max_steps
    # (expiry retires older rows), so this many trailing marks always
    # bracket every cr+1 — the tail window keeps the per-drain interp
    # O(window), not O(run).
    tw = cfg.max_steps // max(1, rounds) + 4
    rec_req, rec_lat, rec_hops, rec_rounds, rec_found = \
        [], [], [], [], []
    queue_depths: list[int] = []
    occ_samples: list[float] = []
    completed = expired = 0
    shed = cache_hits = 0
    handed = 0            # rows handed to the device ring
    ring_backlog = 0      # proven upper bound on the device backlog
    in_flight = 0
    drain_rounds = 0
    dev_shed = 0
    dev_rounds = 0
    macro_n = 0
    ring_depths: list[int] = []
    rung_counts = None
    xchg_init = xchg_round = 0
    blocked_s = 0.0
    sleep_s = 0.0
    dispatch_s = 0.0
    overload = overload_queue_factor * c
    pend = None           # (out handle, rnd0, rnd_end) of macro k

    def _drain(o, r0):
        nonlocal completed, expired, cache_hits, in_flight, \
            ring_backlog, dev_shed, dev_rounds, macro_n, \
            rung_counts, xchg_init, xchg_round
        macro_n += 1
        dev_rounds += int(o.rounds_run)
        dev_shed = int(o.shed)
        ring_depths.append(int(o.queued))
        rc = np.asarray(o.rung_counts, np.int64)
        rung_counts = rc if rung_counts is None else rung_counts + rc
        xchg_init += int(o.xchg_init_rows)
        xchg_round += int(o.xchg_round_rows)
        mr = marks_r[-tw:]
        mw = marks_w[-tw:]
        # Cache hits: answered at pop time (the start of the macro),
        # zero rounds, zero hops — latency is pure queueing delay.
        # All record keeping is VECTORIZED (array chunks, concatenated
        # once at report time): per-row Python here would put the host
        # back on the serve wall the resident program just left.
        hit = np.asarray(o.hit)
        n_hit = int(hit.sum())
        if n_hit:
            w0 = float(np.interp(r0, mr, mw))
            hreq = np.asarray(o.hit_req)[hit].astype(np.int64)
            cw = np.maximum(w0, enq_wall[hreq])
            rec_req.append(hreq)
            rec_lat.append(np.maximum(0.0, cw - arrival_np[hreq]))
            rec_hops.append(np.zeros(n_hit, np.int64))
            rec_rounds.append(np.zeros(n_hit, np.int64))
            rec_found.append(np.asarray(o.hit_found)[hit][:, 0] >= 0)
        cache_hits += n_hit
        completed += n_hit
        comp = np.asarray(o.comp)
        if comp.any():
            sl = np.nonzero(comp)[0]
            req = np.asarray(o.comp_req)[sl].astype(np.int64)
            cr = np.asarray(o.comp_com)[sl]
            # Done with no completion stamp = in-jit expiry — booked
            # expired, never a latency sample.
            live = cr >= 0
            expired += int((~live).sum())
            if live.any():
                req, cr = req[live], cr[live]
                adm = np.asarray(o.comp_adm)[sl][live]
                w = np.maximum(np.interp(cr + 1, mr, mw),
                               enq_wall[req])
                rec_req.append(req)
                rec_lat.append(np.maximum(0.0, w - arrival_np[req]))
                rec_hops.append(np.asarray(o.comp_hops)[sl][live]
                                .astype(np.int64))
                rec_rounds.append((cr - adm + 1).astype(np.int64))
                rec_found.append(
                    np.asarray(o.comp_found)[sl][live][:, 0] >= 0)
                completed += int(live.sum())
        in_flight = int(o.head) - completed - expired
        ring_backlog = int(o.queued)
        occ_samples.append(in_flight / c)

    t0 = clock()
    while True:
        now = clock() - t0
        new_ev = int(np.searchsorted(arrival_np, now, side="right"))
        if new_ev > next_ev:
            queue.extend(range(next_ev, new_ev))
            next_ev = new_ev
        if len(queue) > overload:
            if admission is not None \
                    and admission.policy in ("shed", "degrade"):
                over = len(queue) - overload
                del queue[-over:]
                shed += over
            else:
                raise ServeOverloadError(
                    f"serve overload: admission queue reached "
                    f"{len(queue)} requests (> "
                    f"{overload_queue_factor} x {c} slots) at "
                    f"t={now:.2f}s — the arrival rate exceeds what "
                    f"this slot capacity sustains on this machine; "
                    f"lower --arrival-rate, raise --serve-slots, or "
                    f"shed with --admission shed")
        if now > hard_wall:
            if admission is not None \
                    and admission.policy in ("shed", "degrade"):
                shed += len(queue) + (r_total - next_ev)
                queue.clear()
                next_ev = r_total
            else:
                raise ServeOverloadError(
                    f"serve overload: run exceeded the "
                    f"{hard_wall:.0f}s hard wall "
                    f"({r_total - next_ev + len(queue)} requests "
                    f"not yet admitted, {in_flight} in flight) — "
                    f"the arrival rate exceeds serve capacity on "
                    f"this machine")
        queue_depths.append(len(queue))

        # --- hand-off throttle: the proven backlog bound is the last
        # drained snapshot plus every batch handed since (at most one,
        # the double buffer's in-flight macro) — keep one admit_cap of
        # headroom below the ring so the DEVICE never sheds.
        safe = engine.ring_slots - ring_backlog - a_cap
        if pend is not None:
            safe -= a_cap     # macro k+1's enqueue not yet snapshot
        m = min(len(queue), a_cap, max(0, safe))
        if admission is not None and m:
            take = []
            qi = 0
            while qi < len(queue) and len(take) < m:
                ri = queue[qi]
                if admission.allow(str(klass[ri]), now):
                    take.append(ri)
                elif admission.policy == "shed":
                    shed += 1
                else:          # queue: head-of-line waits for tokens
                    break
                qi += 1
            del queue[:qi]
            m = len(take)
        else:
            take = queue[:m]
            del queue[:m]

        # ``in_flight``/``ring_backlog`` are knowledge as of the LAST
        # DRAINED macro — rows handed to the still-pending macro are
        # not in them yet, so "work may exist" is m>0 OR known device
        # work OR an undrained macro that may have admitted some.
        busy = m > 0 or in_flight > 0 or ring_backlog > 0
        new_pend = None
        if busy:
            keys_np = np.zeros((a_cap, N_LIMBS), np.uint32)
            reqs_np = np.full((a_cap,), -1, np.int32)
            if m:
                take_np = np.asarray(take, np.int64)
                keys_np[:m] = keys[take_np]
                reqs_np[:m] = take_np
                enq_wall[take_np] = now
            # The dispatch wall is DEVICE time, not orchestration: on
            # a synchronous backend (CPU) the macro program runs
            # inline in this call; on an async one the call returns
            # fast and the device wait lands in the drain's blocked
            # window instead — either way the two timers partition the
            # non-host share of the wall.
            td = clock()
            st, rings, out = engine.macro_step(
                st, rings, jnp.asarray(keys_np),
                jnp.asarray(reqs_np),
                jnp.zeros((a_cap,), jnp.int32),
                jax.random.fold_in(key, it_i), m, rnd)
            dispatch_s += clock() - td
            # Mark the macro's round boundary NOW (dispatch return =
            # compute end on the synchronous backend): completion
            # walls interpolate against these marks, and stamping
            # them at drain time instead would tax every latency
            # sample with the double buffer's one-macro reporting
            # lag — a wall the device never actually paid.
            marks_r.append(rnd + rounds)
            marks_w.append(clock() - t0)
            handed += m
            new_pend = (out, rnd, rnd + rounds)
            rnd += rounds
            it_i += 1

        if pend is not None:
            o, r0, _r1 = pend
            tb = clock()
            # The steady state's ONE host sync: the PREVIOUS macro's
            # bulk output, drained while the current macro runs.
            # graftlint: disable=sync-in-loop (double-buffered drain: reads macro k's output while macro k+1 computes — the resident loop's one amortized readback)
            o = jax.device_get(o)
            blocked_s += clock() - tb
            _drain(o, r0)

        pend = new_pend
        draining = next_ev >= r_total and not queue
        if draining and pend is None and in_flight == 0 \
                and ring_backlog == 0:
            break
        if not busy and pend is None:
            if next_ev < r_total:
                gap = arrival_ts[next_ev] - (clock() - t0)
                if gap > 0:
                    sg = min(gap, 0.05)
                    sleep(sg)
                    sleep_s += sg
                continue
        if draining and busy and m == 0:
            drain_rounds += rounds
            if drain_rounds > drain_cap:
                break

    if pend is not None:
        # Drain-cap exit with a macro still in flight (post-loop, so
        # the steady state pays no extra sync for this cold path).
        o, r0, _r1 = pend
        _drain(jax.device_get(o), r0)
        pend = None

    elapsed = clock() - t0
    admitted = completed + expired + in_flight
    shed += dev_shed
    orch = max(0.0, elapsed - dispatch_s - blocked_s - sleep_s)
    req_arr = (np.concatenate(rec_req) if rec_req
               else np.asarray([], np.int64))
    report = {
        "slots": c,
        "admit_cap": a_cap,
        "burst": rounds,
        "admitted": admitted,
        "completed": completed,
        "expired": expired,
        "in_flight": in_flight,
        "never_admitted": len(queue) + (r_total - next_ev)
        + ring_backlog,
        "shed": shed,
        "cache_hits": cache_hits,
        "cache_misses": (admitted - cache_hits) if use_cache else 0,
        "degraded_hits": 0,
        "cache_slots": engine.cache_slots,
        "admission_policy": admission.policy if admission else None,
        "sig_submitted": 0,
        "rounds": rnd,
        "elapsed_s": elapsed,
        "sustained_rps": completed / elapsed if elapsed > 0 else 0.0,
        "request": req_arr,
        "latency_s": np.concatenate(rec_lat) if rec_lat
        else np.asarray([], np.float64),
        "hops": np.concatenate(rec_hops) if rec_hops
        else np.asarray([], np.int64),
        "service_rounds": np.concatenate(rec_rounds) if rec_rounds
        else np.asarray([], np.int64),
        "found_nonempty": np.concatenate(rec_found) if rec_found
        else np.asarray([], bool),
        "klass": np.asarray(klass)[req_arr]
        if len(req_arr) else np.asarray([], dtype="<U4"),
        "queue_depth_mean": float(np.mean(queue_depths))
        if queue_depths else 0.0,
        "queue_depth_max": int(np.max(queue_depths))
        if queue_depths else 0,
        "slot_occupancy_frac": float(np.mean(occ_samples))
        if occ_samples else 0.0,
        "burst_marks": list(zip(marks_r, marks_w)),
        "resident": {
            "ring_slots": engine.ring_slots,
            "rounds_per_iter": rounds,
            "iterations": macro_n,
            "device_rounds": dev_rounds,
            "ring_enqueued": handed,
            "ring_shed": dev_shed,
            "ring_backlog_final": ring_backlog,
            "ring_depth_mean": float(np.mean(ring_depths))
            if ring_depths else 0.0,
            "ring_depth_max": int(np.max(ring_depths))
            if ring_depths else 0,
            "host_orchestration_s": orch,
            "host_orchestration_frac": orch / elapsed
            if elapsed > 0 else 0.0,
            "host_orchestration_budget": host_orchestration_budget,
            "device_dispatch_s": dispatch_s,
            "blocked_get_s": blocked_s,
            "sleep_s": sleep_s,
            "rung_select": engine.rung_block,
            "in_jit_rung_counts":
                [int(x) for x in rung_counts]
                if rung_counts is not None else [],
            "exchange": {
                "rows_init": xchg_init,
                "rows_round": xchg_round,
                "row_bytes": getattr(engine, "exchange_row_bytes", 0),
            },
        },
    }
    return report


# ---------------------------------------------------------------------------
# chunked-value request station (ISSUE 16)
# ---------------------------------------------------------------------------

class ChunkedStation:
    """Host-side station serving CHUNKED-value requests as a first-
    class class of the serve/soak plane.

    Holds a pool of content-addressed multi-part values (random bytes;
    row 0 is the zero-length edge — exactly one, because every zero-
    length value shares ONE content key).  Chunked READS reassemble
    through :func:`~opendht_tpu.models.chunked_values.get_chunked` and
    are byte-checked against the host oracle: a hit is either exact or
    books as ``garbled`` — the contract-violation counter the soak
    checker pins at 0 (missing is the only legal degradation).
    Chunked WRITES are same-bytes seq-bump refreshes: the key IS the
    content, so the only in-place write is a republish-style refresh
    (mutating the bytes would mint a different key, i.e. a new value).

    Batches pad to a fixed ``batch`` width so the station drives
    exactly one compiled program per phase (both warmed pre-clock by
    the soak loop); padding rows re-read/re-announce pool row 0 at its
    CURRENT seq with its own bytes, so the store content cannot
    change and results on padding rows are discarded.
    """

    def __init__(self, cfg: SwarmConfig, scfg, parts: int,
                 pool: int = 32, batch: int = 16, seed: int = 0):
        from .chunked_values import (
            chunked_content_ids, mask_chunk_payloads,
        )
        if parts < 1:
            raise ValueError(f"parts must be >= 1, got {parts}")
        if pool < 1 or batch < 1:
            raise ValueError(f"pool/batch must be >= 1, got "
                             f"pool={pool} batch={batch}")
        self.cfg, self.scfg = cfg, scfg
        self.parts, self.pool, self.batch = parts, pool, batch
        w = scfg.payload_words
        rng = np.random.default_rng(seed ^ 0xC4)
        pls = rng.integers(0, 2 ** 32, (pool, parts, w),
                           dtype=np.uint64).astype(np.uint32)
        lens = rng.integers(1, parts * w * 4 + 1, (pool,),
                            dtype=np.int64).astype(np.uint32)
        lens[0] = 0
        self.payloads = jnp.asarray(pls)
        self.lengths = jnp.asarray(lens)
        self.keys = chunked_content_ids(self.payloads, self.lengths)
        masked, _ = mask_chunk_payloads(self.payloads, self.lengths)
        self.oracle = np.asarray(masked).reshape(pool, parts * w)
        self.oracle_len = np.asarray(lens)
        self.vals = jnp.arange(1, pool + 1, dtype=jnp.uint32)
        self.seqs = np.full((pool,), 2, np.uint64)  # host seq ledger
        self.reads = self.writes = 0
        self.garbled = self.missing = 0

    def announce_pool(self, swarm, store, key, now):
        """Initial full-pool announce (the values chunked requests
        will read); returns the donated store."""
        from .chunked_values import announce_chunked
        store, _rep = announce_chunked(
            swarm, self.cfg, store, self.scfg, self.keys, self.vals,
            jnp.asarray(self.seqs.astype(np.uint32)), now, key,
            self.payloads, self.lengths)
        return store

    def _pad(self, ranks):
        ranks = np.asarray(ranks, np.int64) % self.pool
        n = len(ranks)
        if n > self.batch:
            raise ValueError(f"batch of {n} exceeds the compiled "
                             f"station width {self.batch}")
        out = np.zeros((self.batch,), np.int64)
        out[:n] = ranks
        return jnp.asarray(out), n

    def read(self, swarm, store, ranks, key):
        """Serve one padded batch of chunked reads; books hits /
        garbled / missing over the REAL rows and returns
        ``(hits, garbled)``."""
        from .chunked_values import get_chunked
        idx, n = self._pad(ranks)
        res = get_chunked(swarm, self.cfg, store, self.scfg,
                          self.keys[idx], key, self.parts)
        rows = np.asarray(idx)[:n]
        hit = np.asarray(res.hit)[:n]
        ok = hit \
            & (np.asarray(res.length)[:n] == self.oracle_len[rows]) \
            & np.all(np.asarray(res.payload)[:n]
                     == self.oracle[rows], axis=1)
        garbled = int((hit & ~ok).sum())
        self.reads += n
        self.garbled += garbled
        self.missing += int(n - hit.sum())
        return int(hit.sum()), garbled

    def refresh(self, swarm, store, ranks, key, now):
        """Serve one padded batch of chunked writes (same-bytes
        seq-bump refreshes); returns the donated store."""
        from .chunked_values import announce_chunked
        idx, n = self._pad(ranks)
        rows = np.asarray(idx)
        self.seqs[rows[:n]] += 1
        store, _rep = announce_chunked(
            swarm, self.cfg, store, self.scfg, self.keys[idx],
            self.vals[idx],
            jnp.asarray(self.seqs[rows].astype(np.uint32)), now, key,
            self.payloads[idx], self.lengths[idx])
        self.writes += n
        return store
