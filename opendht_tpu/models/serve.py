"""Open-loop serving engine: slot-recycled continuous lookups.

Everything before this module is closed-loop batch — ``L`` lookups in,
one wall number out.  A production DHT front-end instead serves a
CONTINUOUS arrival stream (the reference rate-limits exactly such a
stream at 1,600 req/s global inbound,
include/opendht/network_engine.h:462), and the number it lives on is
not throughput but the per-request arrival→completion latency
distribution under that stream (the distribution-fidelity methodology
of arXiv:1307.7000, applied to latency instead of hop counts).

The engine keeps a fixed ``[C]``-slot :class:`LookupState` resident on
device.  A FREE slot is ``done=True`` with an empty shortlist — inert
inside the shared round step (done rows solicit nobody), so occupancy
is a pure cost knob, not a semantics one.  Each host-loop iteration:

* **admit** — queued requests (arrived per their open-loop timestamps)
  are scattered into free slots as one fixed-width micro-batch
  (``admit_cap``, padded with dropped sentinel slots): the seed
  exchange is :func:`~opendht_tpu.models.swarm.init_impl`, exactly the
  batch engine's, and ``admitted_round`` is stamped with the current
  round index;
* **burst** — a few rounds of the UNMODIFIED donated step
  (``_lookup_step_d`` / the routed ``_sharded_lookup_step``) advance
  every occupied slot in lock-step; finished rows freeze and their
  ``completed_round`` is stamped by ``_merge_round``'s lifecycle plane;
* **harvest** — the one per-burst readback (the same sync cadence the
  batch burst loop already pays) returns done/hops/lifecycle/found;
  newly-done slots are recorded and recycled for the next admission —
  finished rows' slots admit NEW requests mid-flight instead of
  compacting away (the serve twin of PR 4's active-set ladder).

Latency is reconstructed, not per-row-probed: the device holds round
indices, the host holds per-burst wall clocks, and
``arrival→completion = round-end wall(completed_round) − arrival_ts``
with round-end walls linearly interpolated inside each burst (floored
at the admission wall, so queueing delay is included and latency can
never go negative on a sub-burst completion).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.xor_metric import N_LIMBS
from ..utils.hostdevice import dev_i32
from . import swarm as _swarm
from .swarm import (
    UINT32_MAX,
    LookupResult,
    LookupState,
    Swarm,
    SwarmConfig,
    _finalize,
    _local_respond,
    _sample_origins,
    burst_schedule,
    init_impl,
)


class ServeOverloadError(RuntimeError):
    """The open-loop arrival stream exceeds what the slot capacity can
    drain: the admission queue grew past the overload bound.  Raised
    with a clear message instead of letting the queue (and the run)
    grow without bound — the serve bench surfaces it as a CLI error."""


@partial(jax.jit, static_argnames=("cfg", "slots"))
def empty_serve_state(cfg: SwarmConfig, slots: int) -> LookupState:
    """All-free ``[slots]`` serve state: every row done with an empty
    shortlist (inert in the round step) and lifecycle ``-1``/``-1``
    (never admitted)."""
    s = cfg.search_width
    return LookupState(
        targets=jnp.zeros((slots, N_LIMBS), jnp.uint32),
        idx=jnp.full((slots, s), -1, jnp.int32),
        dist=jnp.full((slots, s), UINT32_MAX, jnp.uint32),
        queried=jnp.zeros((slots, s), bool),
        done=jnp.ones((slots,), bool),
        hops=jnp.zeros((slots,), jnp.int32),
        admitted_round=jnp.full((slots,), -1, jnp.int32),
        completed_round=jnp.full((slots,), -1, jnp.int32))


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def _admit(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
           keys: jax.Array, slots: jax.Array, origins: jax.Array,
           rnd: jax.Array) -> LookupState:
    """Scatter a padded admission micro-batch into free slots.

    ``keys [A,5]``; ``slots [A]`` target slot per request with the
    PAD SENTINEL ``C`` (= the slot count — ``mode="drop"`` makes padded
    rows vanish); ``origins [A]`` issuing nodes.  The seed exchange is
    the batch engine's ``init_impl`` verbatim, so a closed-loop replay
    through this path is bit-identical to ``lookup`` (tests).  The
    state is DONATED: the serve carry is single-owner, like the burst
    loops'.
    """
    new = init_impl(swarm.ids, _local_respond(swarm, cfg), cfg, keys,
                    origins)
    return _scatter_rows_into(st, new, slots, rnd)


def _scatter_rows_into(st: LookupState, new: LookupState,
                       slots: jax.Array, rnd) -> LookupState:
    """ONE copy of the admission scatter (slot sentinel = slot count,
    dropped), shared by the local and sharded admit programs — a new
    ``LookupState`` field lands in both or in neither."""
    sl = slots
    return LookupState(
        targets=st.targets.at[sl].set(new.targets, mode="drop"),
        idx=st.idx.at[sl].set(new.idx, mode="drop"),
        dist=st.dist.at[sl].set(new.dist, mode="drop"),
        queried=st.queried.at[sl].set(new.queried, mode="drop"),
        done=st.done.at[sl].set(False, mode="drop"),
        hops=st.hops.at[sl].set(0, mode="drop"),
        admitted_round=st.admitted_round.at[sl].set(
            jnp.asarray(rnd, jnp.int32), mode="drop"),
        completed_round=st.completed_round.at[sl].set(-1, mode="drop"))


@partial(jax.jit, static_argnames=("cfg",))
def _snapshot(swarm: Swarm, cfg: SwarmConfig, st: LookupState):
    """The per-burst harvest readback: done mask, hops, lifecycle rows
    and the finalized result heads — one ``device_get`` of small
    arrays, the serve loop's only host sync."""
    return (st.done, st.hops, st.admitted_round, st.completed_round,
            _finalize(swarm.ids, st, cfg))


@partial(jax.jit, donate_argnums=(0,))
def _expire_slots(st: LookupState, slots: jax.Array) -> LookupState:
    """Retire rows that exceeded their round budget: mark them done so
    the step stops soliciting and the slot can recycle.
    ``completed_round`` stays -1 — an expired request never completed,
    and the host books it as ``expired``, not as a latency sample.
    The serve twin of the batch engine's ``max_steps`` cap (which
    reports stragglers as ``done=False`` instead of spinning forever);
    without it a non-converging lookup would hold its slot for the
    whole run and a sustainable arrival rate could still starve into a
    misleading overload error."""
    return st._replace(done=st.done.at[slots].set(True, mode="drop"))


class ServeEngine:
    """Single-chip serve engine: admit / step / snapshot over one
    resident ``[slots]`` state.  ``admit_cap`` fixes the admission
    micro-batch width (one compiled admit program)."""

    def __init__(self, swarm: Swarm, cfg: SwarmConfig, slots: int,
                 admit_cap: int | None = None):
        self.swarm, self.cfg, self.slots = swarm, cfg, slots
        self.admit_cap = min(slots, admit_cap or min(slots, 512))

    def empty(self) -> LookupState:
        return empty_serve_state(self.cfg, self.slots)

    def admit(self, st, keys, slots, key, rnd):
        # Origin draw with the caller's key DIRECTLY (no folding): the
        # closed-loop replay relies on this matching the batch engine's
        # ``_sample_origins(key, alive, l)`` bit-for-bit.
        origins = _sample_origins(key, self.swarm.alive,
                                  keys.shape[0])
        # dev_i32: explicit cached round-coordinate upload — the
        # serve loop admits every iteration, and an implicit
        # jnp.int32(rnd) transfer per admit is exactly the hot-path
        # leak graftlint's strict transfer-guard replay forbids.
        return _admit(self.swarm, self.cfg, st, keys, slots, origins,
                      dev_i32(rnd))

    def step(self, st, rnd):
        # Resolved through the module attribute so the cost ledger's
        # in-place instrumentation (obs/ledger.py ENTRY_POINTS) sees
        # serve rounds like burst-loop rounds.
        return _swarm._lookup_step_d(self.swarm, self.cfg, st,
                                     dev_i32(rnd))

    def expire(self, st, slots):
        return _expire_slots(st, slots)

    def snapshot(self, st):
        return jax.device_get(_snapshot(self.swarm, self.cfg, st))


class ShardedServeEngine(ServeEngine):
    """Mesh serve engine: the routed ``_sharded_lookup_step`` advances
    the resident state; admission seeds through the routed init (shard-
    local origin sampling) and scatters into the global slot axis.
    ``slots`` and ``admit_cap`` must divide the mesh."""

    def __init__(self, swarm: Swarm, cfg: SwarmConfig, slots: int,
                 mesh, capacity_factor: float = 2.0,
                 admit_cap: int | None = None):
        super().__init__(swarm, cfg, slots, admit_cap)
        from ..parallel.mesh import AXIS
        self.mesh, self.capacity_factor = mesh, capacity_factor
        d = mesh.shape[AXIS]
        if slots % d or self.admit_cap % d:
            raise ValueError(f"serve slots {slots} and admit_cap "
                             f"{self.admit_cap} must divide the "
                             f"{d}-device mesh")

    def admit(self, st, keys, slots, key, rnd):
        # Routed seed exchange (shard-local origin folding inside the
        # init body), then one GSPMD scatter into the resident state.
        from ..parallel.sharded import _sharded_lookup_init
        new = _sharded_lookup_init(self.swarm, self.cfg, keys, key,
                                   self.mesh, self.capacity_factor)
        return _scatter_admission(st, new, slots, dev_i32(rnd))

    def step(self, st, rnd):
        from ..parallel.sharded import _sharded_lookup_step
        return _sharded_lookup_step(self.swarm, self.cfg, st, self.mesh,
                                    self.capacity_factor,
                                    rnd=dev_i32(rnd))


@partial(jax.jit, donate_argnums=(0,))
def _scatter_admission(st: LookupState, new: LookupState,
                       slots: jax.Array, rnd: jax.Array) -> LookupState:
    return _scatter_rows_into(st, new, slots, rnd)


def poisson_zipf_events(rate: float, duration: float, key_pool: int,
                        zipf_s: float, seed: int = 0,
                        hot_frac: float = 0.01,
                        return_draw: bool = False):
    """Open-loop request schedule: Poisson(``rate``) arrival timestamps
    over ``[0, duration)`` with Zipf(``zipf_s``)-popular keys drawn
    from a ``key_pool``-key universe (``zipf_s = 0`` → uniform).

    Returns ``(arrival_ts [R] float64, keys [R,5] uint32 jnp,
    klass [R] array of "hot"/"cold")`` — a key is "hot" when its
    popularity rank falls in the top ``hot_frac`` of the pool, the
    request-class axis of the latency histograms.  With
    ``return_draw`` the per-request popularity RANKS ride along as a
    fourth element (the soak schedule derives its scan windows from
    them, ``models.soak.mixed_events``) — the first three are
    bit-identical either way.
    """
    if rate <= 0 or duration <= 0:
        raise ValueError("rate and duration must be > 0")
    rng = np.random.default_rng(seed)
    # Inter-arrival exponentials until the horizon (Poisson process).
    n_est = int(rate * duration * 1.5) + 64
    while True:
        gaps = rng.exponential(1.0 / rate, size=n_est)
        ts = np.cumsum(gaps)
        if ts[-1] >= duration:
            break
        n_est *= 2
    ts = ts[ts < duration]
    r = len(ts)
    pool = np.asarray(jax.random.bits(jax.random.PRNGKey(seed ^ 0x5EED),
                                      (key_pool, N_LIMBS), jnp.uint32))
    if zipf_s > 0:
        rnk = np.arange(1, key_pool + 1, dtype=np.float64)
        prob = rnk ** -zipf_s
        prob /= prob.sum()
        draw = rng.choice(key_pool, size=r, p=prob)
    else:
        draw = rng.integers(0, key_pool, size=r)
    hot_cut = max(1, int(key_pool * hot_frac))
    klass = np.where(draw < hot_cut, "hot", "cold")
    # Keys stay HOST-side numpy: the serve loop gathers each admission
    # micro-batch on the host and ships ONE padded array to the device
    # — a jnp key matrix here would put a device gather + blocking
    # readback + re-upload inside every admission of the measured loop.
    if return_draw:
        return ts, pool[draw], klass, draw
    return ts, pool[draw], klass


def warm_serve_engine(engine: ServeEngine) -> None:
    """Compile admit/step/snapshot/expire OFF the serve clock (compile
    time must never masquerade as queueing delay).  Shared by
    :func:`serve_open_loop` and the soak loop
    (``models.soak.soak_open_loop``), which must warm the identical
    program set so a maintenance-off soak is bit-identical to the
    plain serve loop from the first admission on."""
    c, a_cap = engine.slots, engine.admit_cap
    st = engine.empty()
    warm_keys = jnp.zeros((a_cap, N_LIMBS), jnp.uint32)
    warm_slots = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.full((a_cap - 1,), c, jnp.int32)]) if a_cap > 1 \
        else jnp.zeros((1,), jnp.int32)
    st = engine.admit(st, warm_keys, warm_slots,
                      jax.random.PRNGKey(0), 0)
    st = engine.step(st, 0)
    engine.snapshot(st)
    # Expire compiles too: its first real use is mid-run by definition
    # (a request aging past max_steps), where a fresh jit would land
    # inside a burst wall mark and read as tail latency.
    engine.expire(st, jnp.full((a_cap,), c, jnp.int32))


def serve_open_loop(engine: ServeEngine, arrival_ts, keys, key,
                    klass=None, burst: int = 2,
                    duration: float | None = None,
                    overload_queue_factor: int = 8,
                    drain_round_cap: int | None = None,
                    clock=None, sleep=None) -> dict:
    """Drive the serve engine against an open-loop arrival schedule.

    ``arrival_ts``/``keys``(/``klass``) come from
    :func:`poisson_zipf_events` (or any sorted schedule).  The wall
    clock starts AFTER a warm pass compiled every program (compile must
    not masquerade as queueing delay); requests then arrive strictly by
    their timestamps — if the engine falls behind, the queue grows, and
    past ``overload_queue_factor × slots`` the run aborts with
    :class:`ServeOverloadError` (the open-loop contract: arrivals never
    wait for the server).  A request that hasn't converged within
    ``cfg.max_steps`` rounds of its admission is EXPIRED (slot
    retired and recycled, booked as ``expired``, never as a latency
    sample) — the serve twin of the batch engine's round cap, so a
    non-converging lookup can't squat on a slot until the queue reads
    as overload.  After the schedule is exhausted the loop drains
    in-flight work, capped at ``drain_round_cap`` rounds (leftovers
    are reported as ``in_flight`` — the checker's ``admitted ==
    completed + in_flight + expired`` conservation still holds).

    ``clock``/``sleep`` inject the time source (defaults:
    ``time.perf_counter`` / ``time.sleep``).  A deterministic virtual
    clock makes the whole loop — admission decisions, burst marks, the
    reconstructed latency samples — a pure function of the schedule,
    which is how ``tests/test_soak.py`` proves the soak loop's
    maintenance-off path BIT-identical to this one.

    Returns the serve report dict (see the module docstring for the
    latency reconstruction); per-request arrays are ordered by
    completion observation.
    """
    clock = clock or time.perf_counter
    sleep = sleep or time.sleep
    cfg, c = engine.cfg, engine.slots
    a_cap = engine.admit_cap
    keys = np.asarray(keys)        # host-side: see poisson_zipf_events
    r_total = len(arrival_ts)
    if klass is None:
        klass = np.full(r_total, "all")
    drain_cap = drain_round_cap or 4 * cfg.max_steps
    if duration is None:
        duration = float(arrival_ts[-1]) if r_total else 0.0
    # Absolute backstop: a run that can't even drain by 5x the schedule
    # horizon is overloaded whatever the queue gauge says.
    hard_wall = duration * 5.0 + 30.0

    # --- warm pass: compile admit/step/snapshot/expire off the clock.
    warm_serve_engine(engine)
    st = engine.empty()

    free = list(range(c - 1, -1, -1))     # pop() → lowest slot first
    occupied: dict[int, int] = {}         # slot -> request index
    queue: list[int] = []
    next_ev = 0
    rnd = 0
    adm_i = 0
    marks_r = [0]
    marks_w = [0.0]
    # Per completed request (completion-observation order):
    rec_req, rec_lat, rec_hops, rec_rounds, rec_found = [], [], [], [], []
    admit_wall = {}
    queue_depths = []
    occ_samples = []
    admitted = completed = expired = 0
    drain_rounds = 0
    overload = overload_queue_factor * c

    t0 = clock()
    while True:
        now = clock() - t0
        while next_ev < r_total and arrival_ts[next_ev] <= now:
            queue.append(next_ev)
            next_ev += 1
        if len(queue) > overload:
            raise ServeOverloadError(
                f"serve overload: admission queue reached {len(queue)} "
                f"requests (> {overload_queue_factor} x {c} slots) at "
                f"t={now:.2f}s — the arrival rate exceeds what this "
                f"slot capacity sustains on this machine; lower "
                f"--arrival-rate or raise --serve-slots")
        if now > hard_wall:
            raise ServeOverloadError(
                f"serve overload: run exceeded the {hard_wall:.0f}s "
                f"hard wall ({r_total - next_ev + len(queue)} requests "
                f"not yet admitted, {len(occupied)} in flight) — the "
                f"arrival rate exceeds serve capacity on this machine")
        queue_depths.append(len(queue))

        # --- admit one micro-batch into recycled slots
        m = min(len(queue), len(free), a_cap)
        if m:
            take = queue[:m]
            del queue[:m]
            slots_np = np.full(a_cap, c, np.int32)
            keys_np = np.zeros((a_cap, N_LIMBS), np.uint32)
            for j, ri in enumerate(take):
                slot = free.pop()
                slots_np[j] = slot
                occupied[slot] = ri
                admit_wall[ri] = now
            keys_np[:m] = keys[np.asarray(take)]
            st = engine.admit(st, jnp.asarray(keys_np),
                              jnp.asarray(slots_np),
                              jax.random.fold_in(key, adm_i), rnd)
            adm_i += 1
            admitted += m

        draining = next_ev >= r_total and not queue
        if draining and not occupied:
            break
        if not occupied and not queue:
            # Idle gap between arrivals: sleep to the next event rather
            # than spinning dispatches on an empty state.
            if next_ev < r_total:
                gap = arrival_ts[next_ev] - (clock() - t0)
                if gap > 0:
                    sleep(min(gap, 0.05))
                continue
            break

        # --- burst + harvest (the one sync per iteration)
        for _ in range(burst):
            st = engine.step(st, rnd)
            rnd += 1
        done, hops, adm_r, com_r, found = engine.snapshot(st)
        w = clock() - t0
        marks_r.append(rnd)
        marks_w.append(w)
        occ_samples.append(len(occupied) / c)

        for slot in [s for s, _ in occupied.items() if done[s]]:
            ri = occupied.pop(slot)
            free.append(slot)
            cr = int(com_r[slot])
            if cr < 0:
                # Done with no completion stamp can only mean a forced
                # retirement — book it as expired, never as a latency
                # sample (conservation: admitted = completed +
                # in-flight + expired).
                expired += 1
                continue
            # Round-end wall: interpolated inside the burst, floored at
            # the admission wall so queueing delay is counted and a
            # sub-burst completion can never interpolate before its own
            # arrival.  Only the last two marks matter: every done row
            # is harvested in the burst it completed (the snapshot
            # follows the burst and pops all done slots), so walking
            # the whole mark history per completion would be O(n²)
            # host work inside the clocked loop for nothing.
            cw = float(np.interp(cr + 1, marks_r[-2:], marks_w[-2:]))
            cw = max(cw, admit_wall[ri])
            rec_req.append(ri)
            rec_lat.append(cw - float(arrival_ts[ri]))
            rec_hops.append(int(hops[slot]))
            rec_rounds.append(cr - int(adm_r[slot]) + 1)
            rec_found.append(int(found[slot, 0]) >= 0)
            completed += 1

        # --- expiry: rows past their round budget (the batch engine's
        # max_steps cap) retire instead of squatting on their slot.
        # One fixed-width (padded) expire program; a pathological
        # backlog wider than admit_cap drains over later iterations.
        stale = [s for s in occupied
                 if not done[s] and rnd - int(adm_r[s]) >= cfg.max_steps]
        if stale:
            batch = stale[:a_cap]
            sl = np.full(a_cap, c, np.int32)
            sl[:len(batch)] = batch
            st = engine.expire(st, jnp.asarray(sl))
            for slot in batch:
                ri = occupied.pop(slot)
                free.append(slot)
                expired += 1
        if draining:
            drain_rounds += burst
            if drain_rounds > drain_cap:
                break

    elapsed = clock() - t0
    return {
        "slots": c,
        "admit_cap": a_cap,
        "burst": burst,
        "admitted": admitted,
        "completed": completed,
        "expired": expired,
        "in_flight": len(occupied),
        "never_admitted": len(queue) + (r_total - next_ev),
        "rounds": rnd,
        "elapsed_s": elapsed,
        "sustained_rps": completed / elapsed if elapsed > 0 else 0.0,
        "request": np.asarray(rec_req, np.int64),
        "latency_s": np.asarray(rec_lat, np.float64),
        "hops": np.asarray(rec_hops, np.int64),
        "service_rounds": np.asarray(rec_rounds, np.int64),
        "found_nonempty": np.asarray(rec_found, bool),
        "klass": np.asarray(klass)[np.asarray(rec_req, np.int64)]
        if completed else np.asarray([], dtype="<U4"),
        "queue_depth_mean": float(np.mean(queue_depths))
        if queue_depths else 0.0,
        "queue_depth_max": int(np.max(queue_depths))
        if queue_depths else 0,
        "slot_occupancy_frac": float(np.mean(occ_samples))
        if occ_samples else 0.0,
        "burst_marks": list(zip(marks_r, marks_w)),
    }


def closed_loop_replay(swarm: Swarm, cfg: SwarmConfig,
                       targets: jax.Array, key: jax.Array
                       ) -> tuple[LookupResult, LookupState]:
    """Feed a fixed batch through the serve engine's admit/step path
    (slots = L, everything admitted at round 0) and run to completion.

    This is the serve twin of ``lookup(swarm, cfg, targets, key)`` and
    must produce bit-identical found/hops/done for the same key: the
    admission seed exchange is ``init_impl`` with the batch engine's
    origin draw, the rounds are the same shared step, and finished
    slots simply freeze (nothing recycles in a closed-loop replay) —
    asserted in tests/test_serve.py, mirroring test_compaction.py's
    seed-identity pattern.  Returns ``(LookupResult, final state)`` so
    callers can inspect the lifecycle rows.
    """
    l = targets.shape[0]
    eng = ServeEngine(swarm, cfg, slots=l, admit_cap=l)
    st = eng.empty()
    st = eng.admit(st, targets, jnp.arange(l, dtype=jnp.int32), key, 0)
    rnd = 0
    burst = burst_schedule(cfg)
    while rnd < cfg.max_steps:
        n = min(burst, cfg.max_steps - rnd)
        for _ in range(n):
            st = eng.step(st, rnd)
            rnd += 1
        # Per-BURST done poll (explicit device_get: bool() on a device
        # array is an implicit D2H transfer, forbidden under the
        # strict transfer-guard replay).
        # graftlint: disable=sync-in-loop (per-burst done-check readback, amortized over >=2 device rounds — same contract as the burst loops')
        if bool(jax.device_get(jnp.all(st.done))):
            break
        burst = 2
    res = LookupResult(found=_finalize(swarm.ids, st, cfg),
                       hops=st.hops, done=st.done)
    return res, st
