"""Device integrity plane: content-addressed values + pipelined
host signature verify.

The crypto overlay (ref ``src/securedht.cpp``) was the last reference
capability with no device story: signature checking is host-only and
optional-dep gated, so a forged or corrupted payload on the device
engines was indistinguishable from an honest one.  This module closes
the gap with the same defense shape PR 2 used for distance claims —
**trusted claims verified inside the jit at the point they could do
damage**:

* **content-addressed ids** — a value's id is ``SHA-1(payload bytes)``
  (:func:`content_ids`, the device digest; :func:`content_ids_host`
  the bit-identical hashlib twin).  With ``StoreConfig.verify`` set,
  the store-insert programs recompute the digest of every arriving
  payload and REJECT rows whose claimed id contradicts it (booked in
  ``StoreTrace.integrity_rejects`` with exact accept+reject
  conservation), and the get probe discards forged candidate replicas
  inside the jit before they can enter a result set — the storage twin
  of PR 2's merge-time distance-claim verification.  What this
  defends: payload substitution, bit corruption, forged-id injection.
  What it cannot defend: values that are legitimately mutable under
  one id (seq-updatable values) — those need host signatures.
* **pipelined signature stage** — RSA verify stays host-side (the
  reference's ``Value::checkSignature``), but becomes a BATCH stage
  (:class:`SignatureStage`): a harvested value batch is submitted to a
  worker thread whose OpenSSL verifies release the GIL, so the host
  crypto overlaps the next device lookup burst instead of serializing
  per value.  The ``cryptography`` dep stays OPTIONAL: without it the
  stage stays constructible and the signed legs report ``null``
  instead of crashing (``tests/test_integrity.py`` pins that path).
"""

from __future__ import annotations

import hashlib
import queue
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.sha1 import sha1_words

try:                                      # optional dep (PR 1 contract)
    from ..crypto.securedht import check_value_signature  # noqa: F401
    HAVE_CRYPTO = True
except ImportError:
    HAVE_CRYPTO = False


@jax.jit
def content_ids(payloads: jax.Array) -> jax.Array:
    """Batched content-addressed value ids: ``id = SHA-1(payload)``.

    ``payloads [..., W] uint32`` — the fixed-width value bytes exactly
    as the store holds them (word j = bytes 4j..4j+3 big-endian).
    Returns ``[..., 5] uint32`` digest limbs — the storage KEY a
    content-addressed announce uses, and the claim the verified insert
    re-derives.  The jitted entry wraps :func:`~opendht_tpu.ops.sha1.
    sha1_words`; the insert/get programs inline the same traced body.
    """
    return sha1_words(payloads)


def content_ids_host(payloads) -> np.ndarray:
    """Bit-identical hashlib twin of :func:`content_ids` for ``[P, W]``
    uint32 payload rows (parity pinned in tests — the host and device
    views of one id must be interchangeable, like the PHT keys)."""
    pl = np.ascontiguousarray(np.asarray(payloads, np.uint32))
    if pl.ndim == 1:
        pl = pl[None]
    out = np.zeros((pl.shape[0], 5), np.uint32)
    be = pl.astype(">u4")
    for i in range(pl.shape[0]):
        d = hashlib.sha1(be[i].tobytes()).digest()
        out[i] = np.frombuffer(d, dtype=">u4").astype(np.uint32)
    return out


def forge_payloads(payloads, key: jax.Array, flip_frac: float = 1.0):
    """Adversarial payload mutation for the auth scenario: flip ONE bit
    in a ``flip_frac`` fraction of rows (a corrupted or maliciously
    substituted value whose claimed id no longer matches).  Returns the
    mutated ``[P, W]`` array and the boolean mask of mutated rows."""
    pl = jnp.asarray(payloads, jnp.uint32)
    p, w = pl.shape
    k1, k2, k3 = jax.random.split(key, 3)
    hit = jax.random.uniform(k1, (p,)) < flip_frac
    col = jax.random.randint(k2, (p,), 0, max(w, 1))
    bit = jax.random.randint(k3, (p,), 0, 32).astype(jnp.uint32)
    mask = jnp.zeros((p, w), jnp.uint32).at[
        jnp.arange(p), jnp.clip(col, 0, w - 1)].set(
        jnp.uint32(1) << bit)
    return jnp.where(hit[:, None], pl ^ mask, pl), hit


# ---------------------------------------------------------------------------
# pipelined host signature stage
# ---------------------------------------------------------------------------

class SignatureStage:
    """Pipelined batch signature verify — the host half of the
    integrity plane.

    The reference verifies one value per callback
    (``getCallbackFilter``, src/securedht.cpp:237-279); under an
    open-loop device engine that per-value cadence would serialize the
    host crypto against the device rounds.  This stage instead takes
    whole harvested batches: :meth:`submit` enqueues a batch and
    returns immediately, a single worker thread runs the RSA verifies
    (OpenSSL releases the GIL, so the verify wall genuinely overlaps
    the next device lookup burst the caller dispatches), and
    :meth:`drain` joins and returns the stats.

    Without the optional ``cryptography`` dep the stage is still
    constructible with ``available == False``: submissions are counted
    and ``verified``/``failed``/``verifies_per_sec`` report ``None`` —
    the signed legs degrade to null instead of crashing (the crawl
    mode's optional-dep contract, now tested).
    """

    def __init__(self):
        self.available = HAVE_CRYPTO
        self.submitted = 0
        self.batches = 0
        self._verified = 0
        self._failed = 0
        self._verify_wall = 0.0
        self._q: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._drained = False
        self._worker: Optional[threading.Thread] = None
        if self.available:
            self._worker = threading.Thread(target=self._run,
                                            daemon=True)
            self._worker.start()

    def _run(self) -> None:
        from ..crypto.securedht import verify_values_batch
        while True:
            batch = self._q.get()
            if batch is None:
                return
            t0 = time.perf_counter()
            ok = sum(verify_values_batch(batch))
            dt = time.perf_counter() - t0
            with self._lock:
                self._verified += ok
                self._failed += len(batch) - ok
                self._verify_wall += dt

    def submit(self, values: List) -> None:
        """Enqueue one harvested value batch (returns immediately —
        the device work the caller dispatches next overlaps the
        worker's verify wall).  A drained stage refuses: counting a
        batch the dead worker will never verify would break the
        ``verified + failed == submitted`` conservation the checker
        gates.  The drained check, the counters AND the enqueue all
        happen under the stage lock: an unlocked check-then-count
        raced :meth:`drain` (a batch counted after the drain flag
        flipped — or enqueued after the worker's stop sentinel — would
        never be verified), which is exactly the check-then-act shape
        graftlint's lock plane flags."""
        with self._lock:
            if self._drained:
                raise RuntimeError(
                    "SignatureStage.submit after drain: the worker "
                    "has exited — build a fresh stage per measured "
                    "leg")
            self.submitted += len(values)
            self.batches += 1
            if self.available and values:
                self._q.put(list(values))

    def drain(self) -> dict:
        """Join the worker and return the stage stats.  ``null`` crypto
        figures without the optional dep — the artifact field contract
        the checker and the crawl mode share.  The drain flag flips
        and the stop sentinel enqueues under the same lock
        :meth:`submit` counts under, so no batch can slip between the
        flag and the sentinel; the JOIN happens outside it (the worker
        takes the lock to book its stats — joining under it would
        deadlock)."""
        with self._lock:
            first = not self._drained
            self._drained = True
            worker = self._worker
            if first and worker is not None:
                self._q.put(None)       # stop sentinel, exactly once
        if worker is not None:
            # EVERY drainer joins (joining a finished thread is a
            # no-op): a second concurrent drain() must not return
            # stats before the in-flight batch is booked, or
            # verified+failed == submitted breaks for that caller.
            worker.join()
        with self._lock:
            self._worker = None
            if not self.available:
                return {"available": False, "submitted": self.submitted,
                        "batches": self.batches, "verified": None,
                        "failed": None, "verify_wall_s": None,
                        "verifies_per_sec": None}
            vps = ((self._verified + self._failed) / self._verify_wall
                   if self._verify_wall > 0 else None)
            return {"available": True, "submitted": self.submitted,
                    "batches": self.batches,
                    "verified": self._verified,
                    "failed": self._failed,
                    "verify_wall_s": round(self._verify_wall, 6),
                    "verifies_per_sec": (round(vps, 1)
                                         if vps is not None else None)}


def make_signed_values(n: int, key_length: int = 2048):
    """Build ``n`` host values signed by a fresh identity, for the
    signed-putget/listen legs.  Returns ``(values, identity)`` or
    ``(None, None)`` without the optional dep."""
    if not HAVE_CRYPTO:
        return None, None
    from ..core.value import Value
    from ..crypto.identity import generate_identity
    from ..crypto.securedht import sign_value
    ident = generate_identity("auth-bench", key_length=key_length)
    vals = []
    for i in range(n):
        v = Value(bytes([i & 0xFF]) * 64, value_id=i + 1)
        sign_value(ident.key, v)
        vals.append(v)
    return vals, ident
