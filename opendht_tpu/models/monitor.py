"""Resident swarm-health monitor: continuous incremental crawl under
churn.

``bench.py --mode crawl`` proved one-shot enumeration (99.27 % of 1M
nodes in 0.49 s); this module turns that into the *monitoring* workload
of "Efficient Indexing of the BitTorrent DHT" (arXiv:1009.3681): a
resident engine that keeps per-node freshness state, re-crawls only the
keyspace regions whose freshness has decayed, and detects departures
under live churn — reporting per-sweep coverage, freshness percentiles
and churn-detection lag.

Architecture (device half of ISSUE 8's tentpole):

* **freshness plane** — a ``[N]`` :class:`FreshnessState`
  (``last_seen`` / ``discovered`` / ``missed`` / ``dead_since`` sweep
  indices) updated by ONE donated jit per sweep (:func:`fold_sweep`)
  from the sweep's lookup results.  The fold is a PURE OBSERVER of the
  lookup engine: it consumes ``LookupResult.found`` and never feeds
  anything back into a round, so sweep results are bit-identical with
  the plane on or off (asserted in ``tests/test_monitor.py`` for the
  plain and 8-device sharded engines).
* **incremental sweeps** — the keyspace is cut into ``G = 2^depth``
  dyadic prefix buckets (the one-shot crawl's 2× oversampled grid:
  ~4 nodes per bucket, one 8-closest lookup per bucket).  Each sweep
  probes only *stale* buckets: every bucket is force-probed at least
  once per ``period`` sweeps (phase-jittered due dates so the work
  spreads evenly instead of lumping into periodic full crawls), plus
  any bucket whose freshness deficit (fraction of tracked nodes older
  than ``fresh_ttl`` sweeps) passed ``stale_threshold``, plus any
  bucket holding a node awaiting death confirmation (``missed ≥ 1``) —
  so a suspected departure is re-probed on the NEXT sweep, not at the
  next periodic refresh.  Probes run through the existing compacted
  burst engine (:func:`~opendht_tpu.models.swarm.lookup`), the routed
  sharded formulation on a mesh, or the defended chaos engine when the
  swarm carries Byzantine responders.
* **departure detection** — a tracked node in a probed bucket that the
  probe did not return takes a missed-probe strike; at ``miss_limit``
  consecutive strikes it is presumed dead (``dead_since`` stamped).  A
  later sighting resurrects it (strikes reset).  The scheduler bounds
  detection lag by construction: a node killed at sweep ``k`` is first
  probed by sweep ``k + period`` (hard due date) and confirmed within
  ``miss_limit - 1`` further sweeps (the pending-confirmation
  trigger), so ``lag ≤ period + miss_limit - 1`` — the
  ``detection_lag_bound_sweeps`` the artifact states and
  ``tools/check_trace.py`` gates.  Ground-truth kill sweeps
  (:func:`record_kills`) feed the *measurement only* — the detector
  itself sees nothing but probe results.

Host half: ``opendht_tpu.obs.health`` (gauge catalogue, the analytic
hop-distribution model, Poisson keyspace-density profile) and
``bench.py --mode monitor``.
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.hostdevice import dev_i32
from .swarm import (
    LookupFaults,
    LookupResult,
    Swarm,
    SwarmConfig,
    chaos_lookup,
    churn,
    heal_swarm,
    hop_histogram,
    lookup,
)


class MonitorConfig(NamedTuple):
    """Static monitor geometry and policy (Python ints — jit cache key).

    * ``depth`` — prefix depth of the crawl grid: ``G = 2^depth``
      buckets, one lookup per probed bucket (the one-shot crawl's 2×
      oversampling: ``depth = ceil(log2(N/4))`` → ~4 nodes/bucket,
      8-closest per lookup).
    * ``period`` — hard refresh bound: every bucket is probed at least
      once per ``period`` sweeps, staggered by a per-bucket phase so
      steady-state work is ~``G/period`` lookups per sweep.
    * ``fresh_ttl`` — node age (sweeps since last sighting) beyond
      which it counts toward its bucket's staleness deficit.
    * ``stale_threshold`` — deficit fraction above which a bucket is
      re-probed ahead of its due date (the freshness-percentile decay
      trigger of the tentpole).
    * ``miss_limit`` — consecutive missed probes before a tracked node
      is presumed dead.  2 by default: a single probe can miss an
      alive node (the one-shot crawl's ~0.7 % miss rate), so one miss
      is suspicion, not proof.
    * ``age_cap`` — freshness-histogram bin cap (ages clamp into the
      last bin).
    """
    depth: int
    period: int = 4
    fresh_ttl: int = 2
    stale_threshold: float = 0.25
    miss_limit: int = 2
    age_cap: int = 64

    @classmethod
    def for_nodes(cls, n_nodes: int, **kw) -> "MonitorConfig":
        d = max(1, int(math.ceil(math.log2(max(16, n_nodes // 4)))))
        return cls(depth=d, **kw)

    @property
    def detection_lag_bound(self) -> int:
        """Scheduler-guaranteed worst-case churn-detection lag in
        sweeps: first post-kill probe within ``period`` sweeps (hard
        due date), confirmation within ``miss_limit - 1`` more (the
        pending trigger probes suspects on consecutive sweeps)."""
        return self.period + self.miss_limit - 1


class FreshnessState(NamedTuple):
    """Per-node liveness book-keeping (all ``[N] int32``).

    ``last_seen``/``discovered`` are sweep indices (-1 = never seen);
    ``missed`` counts CONSECUTIVE missed probes (reset on sighting);
    ``dead_since`` is the sweep the monitor presumed the node dead
    (-1 = presumed alive or never seen).  The state is built ONLY from
    probe observations — ground truth enters :func:`fold_sweep` for
    the reported statistics, never for the state update.
    """
    last_seen: jax.Array
    discovered: jax.Array
    missed: jax.Array
    dead_since: jax.Array


def empty_freshness(n: int) -> FreshnessState:
    # Distinct buffers per field: the state is DONATED into
    # ``fold_sweep``, and donating one aliased zeros/-1 buffer through
    # several pytree leaves is a runtime error (same rule as
    # ``empty_lookup_trace``'s non-donation note).
    m1 = lambda: jnp.full((n,), -1, jnp.int32)
    return FreshnessState(last_seen=m1(), discovered=m1(),
                          missed=jnp.zeros((n,), jnp.int32),
                          dead_since=m1())


def bucket_targets(buckets, depth: int) -> jax.Array:
    """``[S,5] uint32`` lookup targets for a set of prefix buckets —
    the same grid points as the one-shot crawl (``bench.py --mode
    crawl``): limb 0 = bucket prefix, lower limbs mid-range."""
    b = jnp.asarray(buckets, jnp.uint32)
    s = b.shape[0]
    return jnp.stack(
        [b << jnp.uint32(32 - depth)]
        + [jnp.full((s,), jnp.uint32(0x80000000)) for _ in range(4)],
        axis=1)


@jax.jit
def record_kills(kill_sweep: jax.Array, prev_alive: jax.Array,
                 new_alive: jax.Array, sweep: jax.Array) -> jax.Array:
    """Ground-truth kill ledger: stamp the sweep index on every node
    that just died.  Feeds detection-lag MEASUREMENT only — the
    monitor's own state never reads it."""
    return jnp.where(prev_alive & ~new_alive,
                     jnp.asarray(sweep, jnp.int32), kill_sweep)


@partial(jax.jit, static_argnames=("mcfg",), donate_argnums=(0,))
def fold_sweep(fr: FreshnessState, found: jax.Array, probed: jax.Array,
               ids0: jax.Array, sweep: jax.Array, alive: jax.Array,
               kill_sweep: jax.Array, mcfg: MonitorConfig):
    """Fold one sweep's lookup results into the freshness plane.

    ``found``: the sweep's ``[S, quorum]`` discovered node indices
    (-1 pad); ``probed``: ``[G] bool`` buckets probed this sweep;
    ``ids0``: ``swarm.ids[:, 0]`` (node → bucket); ``alive`` /
    ``kill_sweep``: ground truth, consumed by the STATS ONLY — the
    state update reads nothing but ``found`` and ``probed``.

    One donated jit per sweep; returns ``(state, stats, age_hist,
    (cnt_tracked, cnt_stale, cnt_pending))`` — the per-bucket count
    vectors drive the host scheduler AND double as the per-prefix
    keyspace-density estimate (``obs.health.poisson_density_profile``).
    All device arithmetic; the caller materializes everything in one
    ``device_get``.

    Exact conservation identities (the ``check_trace`` monitor gate):
    ``tracked_alive' = tracked_alive + newly_discovered + resurrected
    - newly_dead``, ``probed_tracked = probed_seen + probed_missed``,
    and ``age_hist[0] == nodes_seen`` (a node is fresh iff this sweep
    saw it).
    """
    n = ids0.shape[0]
    g = 1 << mcfg.depth
    sweep = jnp.asarray(sweep, jnp.int32)
    flat = found.reshape(-1)
    seen = jnp.zeros((n,), bool).at[
        jnp.where(flat >= 0, flat, n)].set(True, mode="drop")
    bucket = (ids0 >> jnp.uint32(32 - mcfg.depth)).astype(jnp.int32)
    probed_node = probed[bucket]

    tracked0 = fr.discovered >= 0
    palive0 = tracked0 & (fr.dead_since < 0)     # presumed alive
    miss_hit = probed_node & palive0 & ~seen
    newly_dead_m = miss_hit & (fr.missed + 1 >= mcfg.miss_limit)
    resurrected_m = seen & tracked0 & (fr.dead_since >= 0)

    last_seen = jnp.where(seen, sweep, fr.last_seen)
    discovered = jnp.where(seen & ~tracked0, sweep, fr.discovered)
    missed = jnp.where(seen, 0,
                       jnp.where(miss_hit, fr.missed + 1, fr.missed))
    dead_since = jnp.where(seen, -1,
                           jnp.where(newly_dead_m, sweep,
                                     fr.dead_since))
    new = FreshnessState(last_seen=last_seen, discovered=discovered,
                         missed=missed, dead_since=dead_since)

    # --- statistics (ground truth allowed from here on) -------------
    cnt = lambda m: jnp.sum(m.astype(jnp.int32))
    tracked1 = discovered >= 0
    palive1 = tracked1 & (dead_since < 0)
    age = jnp.clip(sweep - last_seen, 0, mcfg.age_cap)
    age_hist = jnp.zeros((mcfg.age_cap + 1,), jnp.int32).at[
        jnp.where(palive1, age, mcfg.age_cap + 1)].add(1, mode="drop")

    lag = sweep - kill_sweep
    detect = newly_dead_m & (kill_sweep >= 0)
    stats = {
        "nodes_seen": cnt(seen),
        "newly_discovered": cnt(seen & ~tracked0),
        "resurrected": cnt(resurrected_m),
        "newly_dead": cnt(newly_dead_m),
        "tracked_alive": cnt(palive1),
        "tracked_alive_before": cnt(palive0),
        "covered": cnt(palive1 & alive),
        "actual_alive": cnt(alive),
        # Undetected departures (presumed alive, actually dead) and
        # false deaths (presumed dead, actually alive — probe misses
        # that reached miss_limit; resurrection repairs them).
        "false_alive": cnt(palive1 & ~alive),
        "false_dead": cnt(tracked1 & (dead_since >= 0) & alive),
        "probed_tracked": cnt(probed_node & palive0),
        "probed_seen": cnt(probed_node & palive0 & seen),
        "probed_missed": cnt(miss_hit),
        "lag_sum": jnp.sum(jnp.where(detect, lag, 0)),
        "lag_count": cnt(detect),
        "lag_max": jnp.max(jnp.where(detect, lag, -1)),
        "false_detect": cnt(newly_dead_m & (kill_sweep < 0)),
    }
    oob = jnp.where(palive1, bucket, g)
    cnt_tracked = jnp.zeros((g,), jnp.int32).at[oob].add(1, mode="drop")
    cnt_stale = jnp.zeros((g,), jnp.int32).at[
        jnp.where(palive1 & (age > mcfg.fresh_ttl), bucket, g)
    ].add(1, mode="drop")
    cnt_pending = jnp.zeros((g,), jnp.int32).at[
        jnp.where(palive1 & (missed >= 1), bucket, g)
    ].add(1, mode="drop")
    return new, stats, age_hist, (cnt_tracked, cnt_stale, cnt_pending)


@partial(jax.jit, static_argnames=("cfg",))
def kill_node_range(swarm: Swarm, lo: jax.Array, hi: jax.Array,
                    cfg: SwarmConfig) -> Swarm:
    """Kill the contiguous sorted-id range ``[lo, hi)`` — a localized
    keyspace outage (the ``node_range`` fault shape of the storage
    chaos harness, applied to the alive mask): a whole dyadic region
    goes dark at once, which is exactly what the deficit trigger must
    catch faster than the periodic refresh."""
    idx = jnp.arange(cfg.n_nodes, dtype=jnp.int32)
    dead = (idx >= lo) & (idx < hi)
    return swarm._replace(alive=swarm.alive & ~dead)


def _percentile_from_hist(hist: np.ndarray, q: float) -> int:
    """Smallest bin whose cumulative count reaches the q-quantile."""
    total = int(hist.sum())
    if total == 0:
        return 0
    c = np.cumsum(hist)
    return int(np.searchsorted(c, q * total, side="left"))


class MonitorEngine:
    """Host driver of the resident monitoring loop.

    Owns the (mutable) swarm, the device freshness plane, and the
    host-side probe scheduler.  One sweep = select stale buckets →
    batched lookups through the shared engine → one donated fold →
    one ``device_get`` of the sweep statistics.

    ``mesh`` routes sweeps through the table-sharded engine
    (``parallel.sharded.sharded_lookup``); ``faults`` (with a swarm
    carrying ``byzantine``) routes them through the defended chaos
    engine — a convicted liar stops being seen and is eventually
    presumed dead, the monitor's view of an attacker leaving the
    honest overlay.  ``track_freshness=False`` disables the plane
    entirely (sweeps still run; used by the pure-observer equivalence
    tests).

    NOTE ``heal`` donates the swarm's table buffer (``heal_swarm``):
    the engine owns its swarm; callers must not hold the old pytree.
    """

    def __init__(self, swarm: Swarm, cfg: SwarmConfig,
                 mcfg: MonitorConfig | None = None, mesh=None,
                 faults: LookupFaults | None = None,
                 track_freshness: bool = True,
                 capacity_factor: float = 2.0):
        self.swarm, self.cfg = swarm, cfg
        self.mcfg = mcfg or MonitorConfig.for_nodes(cfg.n_nodes)
        self.mesh, self.faults = mesh, faults
        self.capacity_factor = capacity_factor
        n, g = cfg.n_nodes, 1 << self.mcfg.depth
        self.n_buckets = g
        self.fresh = empty_freshness(n) if track_freshness else None
        self.kill_sweep = jnp.full((n,), -1, jnp.int32)
        self.sweep_idx = 0
        # Host scheduler state: last probe sweep per bucket (phase-
        # jittered after the first full sweep so due dates spread over
        # the period) and the fold's latest per-bucket counts.
        self.last_probed = np.full((g,), np.iinfo(np.int32).min // 2,
                                   np.int64)
        self.phase = np.random.default_rng(0xD47).integers(
            0, self.mcfg.period, size=g)
        self.bucket_counts = None
        self.hop_hist = np.zeros(cfg.max_steps + 1, np.int64)
        self.hop_hist_initial = None
        self.initial_alive = None
        self.records: list[dict] = []

    # -- churn injection (ground truth recorded for lag measurement) --

    def kill(self, frac: float, key: jax.Array) -> None:
        prev = self.swarm.alive
        self.swarm = churn(self.swarm, key, frac, self.cfg)
        self.kill_sweep = record_kills(self.kill_sweep, prev,
                                       self.swarm.alive,
                                       jnp.int32(self.sweep_idx))

    def kill_range(self, lo: int, hi: int) -> None:
        prev = self.swarm.alive
        self.swarm = kill_node_range(self.swarm, jnp.int32(lo),
                                     jnp.int32(hi), self.cfg)
        self.kill_sweep = record_kills(self.kill_sweep, prev,
                                       self.swarm.alive,
                                       jnp.int32(self.sweep_idx))

    def heal(self, key: jax.Array) -> None:
        """Routing-table maintenance between sweeps (donates tables)."""
        self.swarm = heal_swarm(self.swarm, self.cfg, key)

    # -- probe scheduling --------------------------------------------

    def select_buckets(self) -> np.ndarray:
        """Stale-bucket set for the next sweep (host, numpy).

        Union of the three staleness triggers (due date / deficit /
        pending confirmation), topped up with the longest-unprobed
        buckets to a steady ``ceil(G/period)`` budget, then rounded up
        to a power-of-two width (more stale buckets, never duplicates)
        so the lookup engine sees a bounded set of batch shapes — and
        every width divides the 8-way mesh.
        """
        m, g, s = self.mcfg, self.n_buckets, self.sweep_idx
        age_p = s - self.last_probed
        due = age_p >= m.period
        if self.bucket_counts is not None:
            tracked, stale, pending = self.bucket_counts
            deficit = stale / np.maximum(tracked, 1)
            due = due | (pending > 0) | (
                (tracked > 0) & (deficit > m.stale_threshold))
        sel = np.flatnonzero(due)
        budget = -(-g // m.period)
        width = max(len(sel), budget, 1)
        if self.mesh is not None:
            width = max(width, self.mesh.size)
        width = min(g, 1 << (width - 1).bit_length())
        if len(sel) < width:
            rest = np.flatnonzero(~due)
            top = rest[np.argsort(-age_p[rest], kind="stable")]
            sel = np.concatenate([sel, top[:width - len(sel)]])
        return np.sort(sel).astype(np.int64)

    # -- the sweep ----------------------------------------------------

    def _run_lookup(self, targets: jax.Array,
                    key: jax.Array) -> LookupResult:
        if self.mesh is not None:
            from ..parallel.sharded import sharded_lookup
            return sharded_lookup(self.swarm, self.cfg, targets, key,
                                  self.mesh,
                                  capacity_factor=self.capacity_factor)
        if self.faults is not None:
            res, _ = chaos_lookup(self.swarm, self.cfg, targets, key,
                                  self.faults)
            return res
        return lookup(self.swarm, self.cfg, targets, key)

    def begin_sweep(self, buckets=None
                    ) -> tuple[np.ndarray, jax.Array]:
        """Open sweep ``self.sweep_idx``: pick the stale-bucket set and
        build its lookup targets WITHOUT running the probes.

        The split half of :meth:`sweep` the soak engine rides
        (``models.soak``): it admits the returned targets as
        micro-batches into free serve slots over several bursts, then
        closes the sweep with :meth:`finish_sweep` once every probe
        retired.  ``sweep_idx`` is NOT bumped here — kills recorded
        while the sweep is in flight stamp the in-progress index, which
        is what keeps the ``period + miss_limit - 1`` lag bound valid
        for interleaved sweeps too.
        """
        if buckets is None:
            buckets = self.select_buckets()
        buckets = np.asarray(buckets)
        return buckets, bucket_targets(buckets, self.mcfg.depth)

    def finish_sweep(self, found: jax.Array, buckets,
                     done_frac: float = 1.0,
                     hops=None) -> dict:
        """Fold one sweep's probe results and close the sweep.

        ``found``: the sweep's ``[S, quorum]`` discovered node indices
        (-1 pad — an expired/unfinished probe row folds as all-missed,
        exactly like a probe that found nobody); ``buckets``: the
        ``begin_sweep`` set, in the same row order; ``hops``: optional
        per-probe convergence rounds folded into the engine's hop
        histogram (the fidelity instrument; omit for probes that never
        converged).  Returns the sweep record and bumps ``sweep_idx``.
        """
        s = self.sweep_idx
        buckets = np.asarray(buckets)
        record = {"sweep": s, "buckets_probed": int(len(buckets)),
                  "lookups": int(len(buckets)),
                  "done_frac": float(done_frac)}
        if self.fresh is not None:
            probed = np.zeros((self.n_buckets,), bool)
            probed[buckets] = True
            self.fresh, stats, age_hist, bcounts = fold_sweep(
                self.fresh, jnp.asarray(found), jnp.asarray(probed),
                self.swarm.ids[:, 0], dev_i32(s), self.swarm.alive,
                self.kill_sweep, self.mcfg)
            stats, age_hist, bcounts = jax.device_get(
                (stats, age_hist, bcounts))
            self.bucket_counts = tuple(np.asarray(b) for b in bcounts)
            record.update({k: int(v) for k, v in stats.items()})
            aa = max(1, record["actual_alive"])
            record["coverage"] = round(record["covered"] / aa, 6)
            record["age_p50"] = _percentile_from_hist(age_hist, 0.50)
            record["age_p99"] = _percentile_from_hist(age_hist, 0.99)
            record["nodes_fresh"] = int(age_hist[0])
        if hops is not None:
            hist = np.asarray(
                hop_histogram(jnp.asarray(hops), self.cfg.max_steps),
                np.int64)
            self.hop_hist += hist
            if self.hop_hist_initial is None:
                self.hop_hist_initial = hist
                self.initial_alive = int(np.asarray(
                    jnp.sum(self.swarm.alive.astype(jnp.int32))))
        if s == 0:
            # Phase-jitter the due dates off the initial full crawl so
            # steady-state sweeps probe ~G/period buckets instead of
            # re-crawling everything each `period`-th sweep.  (The
            # backdate is scheduling fiction only — freshness ages
            # come from the fold, not from ``last_probed``.)
            self.last_probed[buckets] = -self.phase[buckets]
        else:
            self.last_probed[buckets] = s
        self.sweep_idx = s + 1
        self.records.append(record)
        return record

    def sweep(self, key: jax.Array, buckets=None
              ) -> tuple[dict, LookupResult]:
        """Run one monitoring sweep; returns ``(record, result)``.

        ``buckets`` overrides the scheduler (the equivalence tests
        drive tracked and untracked engines over one explicit
        schedule).  The record carries the fold's statistics plus the
        derived coverage / freshness-percentile / lag fields; with the
        plane off it carries only the sweep geometry.  Implemented as
        ``begin_sweep`` → one closed-loop probe batch →
        ``finish_sweep`` — the soak engine runs the same two halves
        with the probe batch spread over serve bursts instead.
        """
        buckets, targets = self.begin_sweep(buckets)
        res = self._run_lookup(targets, key)
        record = self.finish_sweep(
            res.found, buckets,
            done_frac=float(np.asarray(res.done).mean()),
            hops=res.hops)
        return record, res
