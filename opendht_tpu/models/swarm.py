"""SimSwarm — the TPU-resident Kademlia swarm engine.

The flagship "model" of this framework: an entire DHT swarm of N
simulated nodes held on-device as packed tensors, with all iterative
lookups advanced in lock-step.  This replaces the reference's one-node-
at-a-time event loop (``Dht::searchStep`` src/dht.cpp:1343-1464 driving
``NetworkEngine`` RPCs over UDP) with batched tensor exchanges:

* **node matrix** — ``ids [N,5] uint32`` sorted lexicographically (=
  160-bit numeric order), so every dyadic prefix range (a Kademlia
  bucket's key-space) is a contiguous slice, found by binary search;
* **routing tables** — ``tables [N,B,K] int32``: for node ``i`` bucket
  ``b`` holds K members sharing *exactly* ``b`` prefix bits with ``i``
  (the reference's ``Bucket`` of ≤8 nodes, routing_table.h:26,
  ``TARGET_NODES``), sampled uniformly from the bucket's sorted range —
  the steady-state of the reference's bucket maintenance
  (src/dht.cpp:2826-2885) without simulating each ping;
* **lookups** — a ``[L]``-batch of iterative searches in lock-step;
  each step solicits the α=4 best unqueried shortlist nodes
  (``MAX_REQUESTED_SEARCH_NODES`` dht.h:327), gathers their bucket
  ``c = commonBits(node, target)`` rows (the nodes they would return
  from ``onFindNode``, src/dht.cpp:3189-3200), and merges via the exact
  160-bit sort (``Search::insertNode`` src/dht.cpp:961-1047); a lookup
  is done when its 8 closest known nodes are all queried
  (``Search::isSynced`` src/dht.cpp:1466-1479, quorum =
  ``TARGET_NODES``);
* **churn** — an ``alive [N]`` mask; dead solicited nodes return
  nothing (the α-slot waste models the reference's 3×1 s timeout,
  request.h:113) — the netem-equivalent fault injection.

Everything is static-shape, ``jit``-compiled, and sharding-friendly:
the lookup batch axis shards cleanly over a mesh (see
``opendht_tpu.parallel``).
"""

from __future__ import annotations

import math
import time
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.xor_metric import (
    N_LIMBS,
    closest_nodes_batched,
    lex_searchsorted,
    merge_ladder_widths,
    merge_shortlists_d0,
    pick_merge_width,
    prefix_len32,
    rank_merge_round_d0,
    rank_merge_round_d0_w,
)
from ..utils.hostdevice import dev_i32

UINT32_MAX = 0xFFFFFFFF


def _pad128(x: int) -> int:
    """Round up to the TPU lane multiple (128 elements)."""
    return -(-x // 128) * 128


def device_hbm_bytes() -> int:
    """Accelerator memory limit of the default device, in bytes.

    Queried from ``memory_stats()`` so the augmented-table cutoff and
    store sizing track the actual chip instead of hardcoding one HBM
    size (a 16 GB literal OOMs a v5e-1 with less usable HBM and
    needlessly disables the fast path on bigger parts).  Backends
    without stats (CPU, some drivers) fall back to the measured v5e-1
    figure this repo's thresholds were calibrated on.
    """
    global _HBM_BYTES
    if _HBM_BYTES is None:
        # Never INITIALIZE a backend from here: config construction
        # must stay pure (initializing would freeze the platform and
        # break the dryrun's switch-to-virtual-CPU-first invariant,
        # __graft_entry__._force_virtual_cpu_devices — the round-1
        # failure mode).  Query only an already-live backend; return
        # the fallback uncached otherwise so a later, initialized call
        # can still pick up the real figure.
        try:
            from jax._src import xla_bridge as _xb
            live = bool(getattr(_xb, "_backends", None))
        except Exception:
            live = False   # fail CLOSED: never initialize from here
        if not live:
            return 16_000_000_000
        limit = 0
        try:
            stats = jax.local_devices()[0].memory_stats()
            if stats:
                limit = int(stats.get("bytes_limit", 0))
        except Exception:
            limit = 0
        _HBM_BYTES = limit if limit > 0 else 16_000_000_000
    return _HBM_BYTES


_HBM_BYTES: int | None = None


# Measured headroom for ids + ~500k-lookup per-step transients
# (~4.5 GB at the 10M-node north-star config, BASELINE.md round 4 —
# 16 GB chip → the calibrated 11.5 GB table cutoff).  Shared by the
# aug-table cutoff, the bench auto-slots sizing and the sharded-lookup
# while/burst dispatcher so the three HBM models cannot desynchronize.
LOOKUP_HEADROOM_BYTES = 4_500_000_000


def _aug_table_budget() -> int:
    """HBM available to the augmented table (see LOOKUP_HEADROOM_BYTES)."""
    return device_hbm_bytes() - LOOKUP_HEADROOM_BYTES


def table_bytes(cfg: "SwarmConfig") -> int:
    """Exact device bytes of a swarm's routing table (padded rows)."""
    if cfg.aug_tables:
        return cfg.n_nodes * _pad128(cfg.n_buckets * 3 * cfg.bucket_k) * 2
    return cfg.n_nodes * cfg.n_buckets * cfg.bucket_k * 4


class SwarmConfig(NamedTuple):
    """Static swarm geometry (Python ints — part of the jit cache key).

    Defaults mirror the reference's scale constants: K=8 per bucket
    (routing_table.h:26), 14-node search sets (dht.h:314), α=4
    (dht.h:327), sync quorum 8.
    """
    n_nodes: int
    n_buckets: int
    bucket_k: int = 8
    search_width: int = 14
    alpha: int = 4
    quorum: int = 8
    max_steps: int = 48
    # Augment routing tables with a 16-bit *window surrogate* of each
    # member's first id limb (``[N,B,3K] uint16``: index lo half, index
    # hi half, window).  TPU random gathers cost ~10 ns per *fetch*
    # regardless of row width (measured v5e), so shipping each member's
    # distance surrogate inside the already-fetched bucket row removes
    # the dominant per-step gather (64 scalar fetches/lookup → 0).  The
    # window stores bits [b, b+16) of the member's limb 0 for bucket b
    # — the bits above it are shared with the owning node and
    # reconstructed from the solicitation's own distance (_window_d0) —
    # so the surrogate always carries ≥16 significant bits past the
    # leading one at 6 B/entry instead of round 3's exact-limb
    # 8 B/entry, which is what lets the fast path fit 10M nodes on a
    # 16 GB chip (10.1 GB vs 13.4 GB).
    aug_tables: bool = True
    # Round-merge micro-architecture (static, part of the jit key):
    #   "auto"     — Pallas fused round kernel on TPU, XLA rank-merge
    #                everywhere else (Pallas NEVER runs in interpret
    #                mode on a hot path);
    #   "xla"      — sort-free rank-based merge
    #                (ops.xor_metric.rank_merge_round_d0): dedups
    #                responses by membership/earlier-slot planes and
    #                computes every survivor's output slot by rank
    #                arithmetic over the already-sorted frontier — no
    #                sort over any candidate width;
    #   "xla-sort" — the two-pass full-width sorted merge
    #                (merge_shortlists_d0 over the concatenated
    #                candidates) — the pre-round-9 reference path the
    #                equivalence suite pins the others against;
    #   "pallas"   — the fused dedup+merge+quorum Pallas kernel
    #                (ops.pallas_kernels.merge_round_pallas); interpret
    #                mode off-TPU, so only tests should force it there.
    #   "pallas-round" — the WHOLE-ROUND fused Pallas kernel
    #                (ops.pallas_kernels.fused_round_pallas): the
    #                frontier stays VMEM-resident across table gather
    #                (in-kernel row DMAs) + window decode +
    #                queried/evict update + merge + quorum check.
    #                Local plain engine with augmented tables only;
    #                traced/chaos/routed engines degrade to the
    #                merge-only kernel.  Opt-in (never auto-resolved)
    #                until a TPU measurement exists; interpret mode
    #                off-TPU is for tests only, like "pallas".
    merge_impl: str = "auto"

    @classmethod
    def for_nodes(cls, n_nodes: int, **kw) -> "SwarmConfig":
        # Enough buckets that the deepest one holds ~2·K nodes.  Capped
        # at 26: bucket indices derive from first-limb prefix lengths
        # (exact to depth 32), and build_swarm's prefix histograms use
        # up to 2^depth bins — 26 covers ~2^29 nodes, far past what a
        # chip holds.
        b = min(26, max(4, int(math.ceil(math.log2(max(16, n_nodes)))) - 3))
        k = kw.get("bucket_k", 8)
        # Augmented while the table fits the device's HBM with lookup
        # headroom.  Sized with the PADDED row width — rows pad to a
        # 128-lane multiple, up to ~27% over the raw B*3K estimate —
        # so a table near the cutoff can't silently exceed budget.
        # The 10M-node north star (10.2 GB padded at B=21) stays on
        # for a 16 GB chip.
        kw.setdefault("aug_tables", n_nodes * _pad128(b * 3 * k) * 2
                      <= _aug_table_budget())
        return cls(n_nodes=n_nodes, n_buckets=b, **kw)


# Geometry invariant, enforced at CONFIG BUILD time (wrapping the
# generated NamedTuple __new__; typing.NamedTuple forbids defining one
# in the class body): ``_finalize`` exact-sorts only the top
# ``quorum + 2`` surrogate ranks, so the two-slot margin that bounds
# its order error (BASELINE.md sim_fidelity) only exists when the
# shortlist is at least that wide.  A config violating it would
# silently report fewer than ``quorum`` results from a shrunken head
# instead of failing loudly here.  (``_replace`` bypasses __new__ via
# ``_make``; the entry points all construct configs directly.)
_swarmconfig_new = SwarmConfig.__new__


MERGE_IMPLS = ("auto", "xla", "xla-sort", "pallas", "pallas-round")


def _swarmconfig_checked_new(cls, *args, **kw):
    cfg = _swarmconfig_new(cls, *args, **kw)
    if cfg.quorum + 2 > cfg.search_width:
        raise ValueError(
            f"SwarmConfig requires quorum + 2 <= search_width (the "
            f"_finalize exact re-sort covers the top quorum+2 surrogate "
            f"ranks — see BASELINE.md sim_fidelity); got quorum="
            f"{cfg.quorum}, search_width={cfg.search_width}")
    if cfg.merge_impl not in MERGE_IMPLS:
        raise ValueError(
            f"SwarmConfig.merge_impl must be one of {MERGE_IMPLS}; "
            f"got {cfg.merge_impl!r}")
    return cfg


SwarmConfig.__new__ = _swarmconfig_checked_new


def resolve_merge_impl(cfg: SwarmConfig) -> str:
    """Concrete round-merge implementation for this run.

    ``auto`` picks the fused Pallas kernel only where it compiles to
    real TPU code; every other backend gets the XLA rank-merge — the
    CPU gate must never pay Pallas interpret mode on the hot path.
    Resolved at trace time (the backend choice is process-stable).
    """
    if cfg.merge_impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return cfg.merge_impl


class Swarm(NamedTuple):
    """Device-resident swarm state (a pytree of arrays).

    ``tables`` layout depends on ``SwarmConfig.aug_tables``.  It is
    stored 2-D with buckets flattened row-contiguously — bucket ``b``
    of node ``i`` is ``tables[i, b*W:(b+1)*W]`` — and, for augmented
    tables, the row is PADDED UP TO A LANE MULTIPLE (128 u16).  Both
    choices are dictated by measured TPU gather behavior (v5e, this
    runtime): the ONLY fast dynamic fetch over a ~10 GB operand is the
    classic embedding-style whole-row gather ``tables[idx]`` on a
    lane-aligned 2-D array (~10 ns/row amortized).  Every alternative
    loses by orders of magnitude: 3-D ``[N,B,W]`` slice gathers make
    XLA materialize a transposed operand copy whose minor dim pads to
    128 lanes (54 GB at 10M nodes — compile OOM), and any
    variable-start or multi-element-slice gather (2-D spans, 1-D
    windows) runs ~2.5 µs per index — the slow per-element path.  The
    respond path therefore fetches each solicited node's ENTIRE row
    and extracts the two-bucket window on-chip with a static-slice
    select chain (:func:`_respond`).

    * augmented (default): ``[N, pad128(B*3K)] uint16`` — per bucket
      row, the K member indices' low halves, their high halves, then
      the K members' 16-bit limb-0 windows (bits [b, b+16) for bucket
      b, MSB-aligned; empty slot = index 0xFFFFFFFF → -1).  One row
      fetch brings every bucket's candidate list *and* distance
      surrogates — see SwarmConfig.aug_tables and :func:`_window_d0`.
    * plain: ``[N, B*K] int32`` member indices only (-1 = empty);
      fetched via span gathers — functional fallback, slow at scale.
    """
    ids: jax.Array     # [N,5] uint32, lexicographically sorted
    tables: jax.Array  # [N, pad128(B*3K)] u16 (augmented) or
    #                    [N, B*K] i32 (plain) — see class docstring
    alive: jax.Array   # [N] bool
    # Byzantine responder mask (None = honest swarm, the default —
    # existing pytrees/programs are unchanged).  Members stay alive and
    # answer solicitations, but with POISONED closest-node windows (see
    # :func:`chaos_step_impl`): the adversarial model of S/Kademlia
    # (Baumgart & Mies 2007, PAPERS.md), where lookup failure comes
    # from nodes that answer *wrongly*, not from node loss.  Only the
    # chaos lookup path reads it; `lookup()` ignores it entirely.
    byzantine: jax.Array | None = None   # [N] bool


class LookupState(NamedTuple):
    """Lock-step batched lookup state (all ``[L, ...]``).

    The shortlist carries only the first 32 bits of the XOR distance
    (``dist = limb0(id ^ target)``): that surrogate decides the
    per-round merge order (exact up to ~2^-33 d0 collisions per merge
    — see :func:`opendht_tpu.ops.xor_metric.merge_shortlists_d0`),
    while the final result is re-sorted by the exact 160-bit distance
    once per lookup (:func:`_finalize`).  Keeping the hot-loop state
    free of ``[..., 5]``-minor arrays is what lets every per-round op
    tile fully onto TPU lanes.
    """
    targets: jax.Array  # [L,5]
    idx: jax.Array      # [L,S] shortlist node indices, sorted by dist
    dist: jax.Array     # [L,S] uint32 first-limb xor distance (~0=empty)
    queried: jax.Array  # [L,S] bool
    done: jax.Array     # [L] bool
    hops: jax.Array     # [L] int32 — solicitation rounds until sync
    # Per-request lifecycle plane (OFF by default: ``None`` keeps every
    # existing program and pytree structure untouched).  When attached
    # (:func:`init_lifecycle`), each row records the round it was
    # admitted at and the round its ``done`` bit first went True — a
    # PURE OBSERVER: neither field feeds any round decision, so
    # results/strikes/traces are bit-identical with tracking on or off
    # (tests/test_serve.py).  The fields ride the compaction repack
    # like every other row vector and cost zero extra host syncs;
    # combined with the burst loop's per-burst wall clocks they
    # reconstruct arrival→completion wall latency per request without a
    # per-row device_get — the device half of the serve telemetry
    # plane (models/serve.py, ROADMAP #2).
    admitted_round: jax.Array | None = None   # [L] int32 (-1 = free)
    completed_round: jax.Array | None = None  # [L] int32 (-1 = inflight)


class LookupResult(NamedTuple):
    found: jax.Array  # [L,quorum] closest queried node indices (-1 pad)
    hops: jax.Array   # [L]
    done: jax.Array   # [L]


def init_lifecycle(st: LookupState,
                   rnd: int | jax.Array = 0) -> LookupState:
    """Attach the per-request lifecycle plane to a fresh state: every
    row admitted at round ``rnd``, completion pending.  Steps must then
    receive their round index (``rnd=``) so ``_merge_round`` can stamp
    ``completed_round`` — the loops do this automatically when the
    fields are present.  The round scalar rides an explicit cached
    upload and the fill runs jitted (constants fold into the program),
    so the strict transfer-guard replay sees no implicit transfer."""
    return _init_lifecycle_j(st, dev_i32(rnd))


@jax.jit
def _init_lifecycle_j(st: LookupState, rnd32: jax.Array) -> LookupState:
    l = st.done.shape[0]
    return st._replace(
        admitted_round=jnp.broadcast_to(rnd32, (l,)),
        completed_round=jnp.where(st.done, rnd32, jnp.int32(-1)))


class LookupTrace(NamedTuple):
    """Flight recorder: per-round device-side lookup telemetry.

    Every counter is a ``[max_steps] int32`` row indexed by
    solicitation round, accumulated INSIDE the jitted round loop with
    ``at[rnd]`` scatters — no host syncs ride the burst loop; the whole
    pytree is materialized once when the caller reads it
    (:func:`trace_to_dict`).  This is the device twin of the host
    engine's per-message-type counters (net/network_engine.py
    metrics), capturing what the papers say is the diagnostic signal
    for lookup health: per-round convergence and churn distributions
    (arXiv 1307.7000 §IV, 1408.3079 §3).

    Fields that are per-shard partial sums under the table-sharded
    engine reduce with ``psum``; fields computed from already-replicated
    state (``strikes``/``convictions`` after the chaos strike psums,
    ``rounds``) reduce with ``pmax`` — see
    :func:`opendht_tpu.parallel.sharded._trace_allreduce`.

    * ``requests``  — solicitations issued (α-slots holding a node);
    * ``replies``   — candidate entries that reached the merge (post
      drop/poison filtering);
    * ``drops``     — solicitations that returned nothing: dead
      targets, capacity-shed sends, in-transit losses;
    * ``poison``    — contradicted distance claims detected (chaos
      defend path; 0 elsewhere);
    * ``strikes``   — strike-counter increments (chaos defend path);
    * ``convictions`` — blacklisted nodes at round end (gauge);
    * ``churn``     — shortlist slots whose occupant changed;
    * ``done``      — lookups done at round end (gauge, monotone);
    * ``active_rows`` — lookups still pending at round ENTRY (gauge,
      monotone non-increasing; the complement of the previous round's
      ``done``).  The area between this curve and the batch width is
      the row-rounds a full-width dispatcher wastes on finished
      lookups — the number the compaction ladder exists to reclaim
      (``trace_to_dict`` derives it as ``wasted_row_rounds``);
    * ``rounds``    — scalar: rounds actually executed.
    """
    requests: jax.Array     # [R] int32
    replies: jax.Array      # [R] int32
    drops: jax.Array        # [R] int32
    poison: jax.Array       # [R] int32
    strikes: jax.Array      # [R] int32
    convictions: jax.Array  # [R] int32 (gauge)
    churn: jax.Array        # [R] int32
    done: jax.Array         # [R] int32 (gauge)
    active_rows: jax.Array  # [R] int32 (gauge)
    rounds: jax.Array       # []  int32


@partial(jax.jit, static_argnames=("cfg",))
def empty_lookup_trace(cfg: SwarmConfig) -> LookupTrace:
    z = jnp.zeros((cfg.max_steps,), jnp.int32)
    return LookupTrace(requests=z, replies=z, drops=z, poison=z,
                       strikes=z, convictions=z, churn=z, done=z,
                       active_rows=z, rounds=jnp.int32(0))


def merge_traces(traces) -> LookupTrace:
    """Combine traces of DISJOINT lookup batches (bench chunks).

    Counters sum element-wise (each chunk's lookups — and, for chaos
    runs, its per-batch strike state — are independent) and ``rounds``
    takes the max.  The GAUGES (``done``, ``convictions``) are
    forward-filled past each chunk's own exit round first: a chunk
    that converged in 7 rounds still holds all its lookups done while
    a 9-round sibling finishes, so without the fill the merged done
    gauge would DIP at round 7 and undercount the final row —
    summing raw gauge rows across different round counts is the bug,
    not the contract.  ``active_rows`` gets the same treatment with
    its post-exit value, which is ZERO — a converged chunk has nothing
    pending while its siblings finish — so the merged gauge stays
    monotone non-increasing and ``active[r] == L - done[r-1]`` keeps
    holding across chunks (the ``check_trace`` invariants).
    """
    def fill_forward(t: LookupTrace) -> LookupTrace:
        r = jnp.maximum(t.rounds, 1)
        idx = jnp.arange(t.done.shape[0])
        ff = lambda row: jnp.where(idx < r, row, row[r - 1])
        return t._replace(done=ff(t.done),
                          convictions=ff(t.convictions),
                          active_rows=jnp.where(idx < r, t.active_rows,
                                                0))

    out = fill_forward(traces[0])
    for t in traces[1:]:
        t = fill_forward(t)
        out = LookupTrace(
            *[jnp.maximum(a, b) if name == "rounds" else a + b
              for name, a, b in zip(LookupTrace._fields, out, t)])
    return out


def trace_to_dict(trace: LookupTrace,
                  n_lookups: int | None = None) -> dict:
    """One host materialization of the whole trace (a single
    ``device_get``, never per-element fetches) → a JSON-able dict with
    counters truncated to the executed rounds."""
    host = jax.device_get(trace)
    r = max(1, int(host.rounds))
    out = {
        "rounds": int(host.rounds),
        "max_steps": int(host.requests.shape[0]),
        "counters": {
            name: [int(v) for v in getattr(host, name)[:r]]
            for name in LookupTrace._fields if name != "rounds"
        },
    }
    if n_lookups:
        out["n_lookups"] = int(n_lookups)
        out["done_frac"] = [round(int(d) / n_lookups, 6)
                            for d in host.done[:r]]
        # Row-rounds a full-width dispatcher spends on already-finished
        # lookups: the area between the batch width and the active
        # curve — the quantity the compaction shape ladder reclaims
        # (README "Performance").
        out["wasted_row_rounds"] = int(sum(
            max(0, n_lookups - int(a)) for a in host.active_rows[:r]))
    return out


# ---------------------------------------------------------------------------
# bit helpers on packed ids (work with traced bit positions)
# ---------------------------------------------------------------------------

def _prefix_mask(nbits: jax.Array) -> jax.Array:
    """``[5]`` uint32 mask keeping the first ``nbits`` bits of an id."""
    limbs = []
    for j in range(N_LIMBS):
        rem = jnp.clip(nbits - 32 * j, 0, 32)
        shift = jnp.clip(32 - rem, 0, 31).astype(jnp.uint32)
        m = (jnp.uint32(UINT32_MAX) << shift) & jnp.uint32(UINT32_MAX)
        limbs.append(jnp.where(rem == 0, jnp.uint32(0), m))
    return jnp.stack(limbs, axis=-1)


def _bit_mask(bit: jax.Array) -> jax.Array:
    """``[5]`` uint32 with only ``bit`` (0 = MSB of limb 0) set."""
    limbs = []
    for j in range(N_LIMBS):
        off = bit - 32 * j
        in_limb = (off >= 0) & (off < 32)
        pos = jnp.clip(31 - off, 0, 31).astype(jnp.uint32)
        limbs.append(jnp.where(in_limb, jnp.uint32(1) << pos, jnp.uint32(0)))
    return jnp.stack(limbs, axis=-1)


def bucket_range(sorted_ids: jax.Array, node_ids: jax.Array,
                 b: jax.Array, inclusive=False):
    """Sorted-range ``[lo, hi)`` of a node's bucket-``b`` key-space.

    Exclusive (normal) bucket: ids sharing *exactly* ``b`` prefix bits
    — "first b bits equal, bit b flipped", a dyadic interval, hence
    contiguous in the sorted matrix.  Inclusive (deepest) bucket: ids
    sharing *at least* ``b`` bits — the reference's unsplit own-bucket
    tail that holds a node's nearest neighbours
    (``RoutingTable::split``/``depth``, src/routing_table.cpp:139-163).
    """
    pm1 = _prefix_mask(b + 1)
    pmb = _prefix_mask(b)
    bm = _bit_mask(b)
    # Keep the node's first b+1 bits, then flip bit b: the bucket's
    # key-space prefix.
    lo_ex = (node_ids & pm1) ^ bm
    hi_ex = lo_ex | (~pm1 & jnp.uint32(UINT32_MAX))
    lo_in = node_ids & pmb
    hi_in = lo_in | (~pmb & jnp.uint32(UINT32_MAX))
    inc = jnp.asarray(inclusive)
    lo_key = jnp.where(inc, lo_in, lo_ex)
    hi_key = jnp.where(inc, hi_in, hi_ex)
    lo = lex_searchsorted(sorted_ids, lo_key, side="left")
    hi = lex_searchsorted(sorted_ids, hi_key, side="right")
    return lo, hi


# ---------------------------------------------------------------------------
# swarm construction
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("cfg",))
def _build_ids(key: jax.Array, cfg: SwarmConfig) -> jax.Array:
    raw = jax.random.bits(key, (cfg.n_nodes, N_LIMBS), jnp.uint32)
    limbs = tuple(raw[:, i] for i in range(N_LIMBS))
    sorted_limbs = jax.lax.sort(limbs, num_keys=N_LIMBS)
    return jnp.stack(sorted_limbs, axis=-1)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def _build_bucket(tables: jax.Array, ids0: jax.Array, b: jax.Array,
                  key: jax.Array, cfg: SwarmConfig,
                  alive: jax.Array | None = None) -> jax.Array:
    """Fill bucket ``b`` (traced scalar) of every node's table.

    Bucket ranges via prefix histograms, not binary search: in the
    sorted id matrix every bucket's key-space is a dyadic interval
    determined by the first d ≤ 32 bits (d = bucket depth + 1), so its
    [lo, hi) is a pair of adjacent prefix-histogram cumsums — O(N) per
    bucket with one small gather, where per-node binary search was
    O(N log N) random gathers (and its unrolled HLO crashed the
    compiler at 10M nodes).  ``b`` is traced (histogram padded to
    ``2^B`` bins) so all buckets share ONE compiled program, and
    ``tables`` is DONATED so the 10 GB buffer is updated in place —
    an unrolled whole-build jit kept a second table-sized buffer live
    and OOMed a 16 GB chip at 10M nodes.

    With ``alive`` (a ``[N] bool`` mask), members are sampled among
    ALIVE nodes only: the histogram weighs alive nodes, samples become
    alive-ranks, and one ``searchsorted`` over the alive cumsum maps
    ranks back to node indices (ids are sorted, so alive-rank order is
    id order within every dyadic range) — :func:`heal_swarm`'s bucket
    maintenance.
    """
    n, b_total, k = cfg.n_nodes, cfg.n_buckets, cfg.bucket_k
    assert b_total <= 26, "prefix histogram capped at 2^26 bins"
    inclusive = b == b_total - 1
    d = jnp.where(inclusive, b, b + 1)   # prefix depth of the interval
    # d ≥ 1 always (b_total ≥ 4), so the shift stays < 32.
    pref = (ids0 >> (jnp.uint32(32) - d.astype(jnp.uint32))
            ).astype(jnp.int32)
    weight = (jnp.ones((n,), jnp.int32) if alive is None
              else alive.astype(jnp.int32))
    counts = jnp.zeros((1 << b_total,), jnp.int32).at[pref].add(weight)
    bounds = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)])
    p = jnp.where(inclusive, pref, pref ^ 1)   # own vs sibling interval
    lo, hi = bounds[p], bounds[p + 1]
    size = (hi - lo).astype(jnp.float32)
    # Stratified samples across the range: bucket membership is
    # uniform-random in the reference's steady state too.
    u = jax.random.uniform(key, (n, k))
    strat = (jnp.arange(k, dtype=jnp.float32)[None, :] + u) / k
    # floor(strat·size) ∈ [0, size-1] ⊆ [0, n-1] mathematically
    # (strat < 1, size ≤ n) — but that bound rides data the interval
    # prover cannot see through the uniform's bit pipeline.  The clamp
    # makes it STATIC, so the f32→i32 cast is interval-proven
    # (graftlint plane 4); on the reachable domain the clamp is an
    # identity, bit-identical tables either way.
    samp = lo[:, None] + jnp.clip(
        jnp.floor(strat * size[:, None]), 0.0,
        jnp.float32(n - 1)).astype(jnp.int32)
    samp = jnp.clip(samp, lo[:, None], hi[:, None] - 1)
    if alive is not None:
        # samp is an alive-RANK; the (r+1)-th alive node's index is
        # the first position whose alive-cumsum exceeds r.
        acum = jnp.cumsum(weight)
        samp = jnp.clip(
            jnp.searchsorted(acum, samp, side="right"), 0, n - 1
        ).astype(jnp.int32)
    samp = jnp.where((hi > lo)[:, None], samp, -1)       # [N,K]
    if cfg.aug_tables:
        # Fused u16 row [idx-lo K | idx-hi K | window K].  The window
        # is bits [b, b+16) of the member's limb 0, MSB-aligned (see
        # _window_d0); empty slots (-1) become 0xFFFF halves and
        # reconstruct to -1.
        m0 = ids0[jnp.clip(samp, 0, n - 1)]
        s16 = ((m0 << b.astype(jnp.uint32)) >> jnp.uint32(16)
               ).astype(jnp.uint16)
        su = samp.astype(jnp.uint32)
        samp = jnp.concatenate(
            [(su & jnp.uint32(0xFFFF)).astype(jnp.uint16),
             (su >> jnp.uint32(16)).astype(jnp.uint16),
             s16], axis=-1)                              # [N,3K]
    width = samp.shape[-1]
    return jax.lax.dynamic_update_slice(
        tables, samp, (jnp.int32(0), b * width))


def build_swarm(key: jax.Array, cfg: SwarmConfig) -> Swarm:
    """Generate a random swarm with steady-state routing tables.

    O(N·B) total: per bucket, one padded prefix histogram + K
    stratified-uniform samples per node.  Not one monolithic jit —
    the per-bucket program donates the table buffer so peak HBM stays
    at tables + O(N·K) transients (see ``_build_bucket``).
    """
    n, b_total, k = cfg.n_nodes, cfg.n_buckets, cfg.bucket_k
    k_ids, k_samp = jax.random.split(key)
    ids = _build_ids(k_ids, cfg)
    ids0 = ids[:, 0]
    if cfg.aug_tables:
        # Row padded to a 128-lane multiple: lane-aligned rows are what
        # keeps the whole-row gather on the fast path (Swarm docstring).
        row_w = _pad128(b_total * 3 * k)
        tables = jnp.full((n, row_w), 0xFFFF, jnp.uint16)
    else:
        tables = jnp.full((n, b_total * k), -1, jnp.int32)
    for b in range(b_total):
        tables = _build_bucket(tables, ids0, jnp.int32(b),
                               jax.random.fold_in(k_samp, b), cfg=cfg)
    return Swarm(ids=ids, tables=tables, alive=jnp.ones((n,), bool))


@partial(jax.jit, static_argnames=("cfg",))
def churn(swarm: Swarm, key: jax.Array, kill_frac: float,
          cfg: SwarmConfig) -> Swarm:
    """Kill a uniform fraction of nodes (netem-equivalent fault mask).

    Dead nodes stop answering; routing-table entries pointing at them
    become wasted α-slots, exactly like the reference's expired nodes
    awaiting eviction (src/node.cpp:34-40).
    """
    keep = jax.random.uniform(key, (cfg.n_nodes,)) >= kill_frac
    return swarm._replace(alive=swarm.alive & keep)


@partial(jax.jit, static_argnames=("cfg",))
def corrupt_swarm(swarm: Swarm, key: jax.Array, byzantine_frac: float,
                  cfg: SwarmConfig) -> Swarm:
    """Mark a uniform fraction of nodes Byzantine — the adversarial
    twin of :func:`churn`.

    Byzantine members stay alive (a dead attacker is just churn) and
    keep answering, but their ``_respond`` windows are poisoned by the
    chaos step (:func:`chaos_step_impl`): random node ids advertised at
    near-zero distance, or eclipse-style self-promotion.  The plain
    :func:`lookup` path ignores the mask entirely — adversarial
    behavior is opt-in per run, like the storage path's
    ``drop_exchanges``.
    """
    byz = jax.random.uniform(key, (cfg.n_nodes,)) < byzantine_frac
    return swarm._replace(byzantine=byz)


def heal_swarm(swarm: Swarm, cfg: SwarmConfig, key: jax.Array) -> Swarm:
    """Routing-table maintenance after churn: re-sample every bucket
    among the ALIVE nodes.

    The reference evicts expired members and refills buckets from
    discovered traffic (``expireBuckets``/neighbourhood maintenance,
    src/dht.cpp:2826-2885, 2991-3027); this is that process's steady
    state, at the same modeling altitude as :func:`build_swarm` (which
    samples the full-swarm steady state without simulating each ping).
    Under heavy cumulative death the raw engine degrades exactly like
    a reference node that never ran maintenance — buckets full of
    corpses starve the lookup frontier (measured: recall of the true
    alive-8-closest falls to ~0.5 at 24 % alive on 2048 nodes) — so
    chaos scenarios pair ``churn`` with a heal, like the host cluster
    pairs kills with virtual-time maintenance windows.

    Same per-bucket donated-buffer build as :func:`build_swarm`: the
    input swarm's table buffer is CONSUMED (donated); use the returned
    swarm.  O(N·B) plus one ``searchsorted`` per sampled member.
    """
    tables = swarm.tables
    ids0 = swarm.ids[:, 0]
    for b in range(cfg.n_buckets):
        tables = _build_bucket(tables, ids0, jnp.int32(b),
                               jax.random.fold_in(key, b), cfg=cfg,
                               alive=swarm.alive)
    return swarm._replace(tables=tables)


# ---------------------------------------------------------------------------
# lookups
# ---------------------------------------------------------------------------

def _respond(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
             nid: jax.Array, nid_d0: jax.Array):
    """What each solicited node returns for each target.

    ``targets``: ``[L,5]``; ``nid``: ``[L,A]`` node indices (-1 =
    none); ``nid_d0``: ``[L,A]`` the solicited nodes' first-limb XOR
    distance to the target — already in the caller's shortlist state,
    so the bucket index ``c = clz(d0)`` (= ``commonBits(self,
    target)``, exact for n_buckets ≤ 32) costs no gather at all.

    Returns ``(resp [L,A*2K], resp_d0 [L,A*2K], answered [L,A])``:
    candidate indices and their first-limb distances — the solicited
    node's bucket ``c`` (every member strictly closer to the target
    than the node itself) plus bucket ``c+1``, the node's best
    approximation of "the 8 closest I know" (``Dht::onFindNode``
    src/dht.cpp:3189-3200).  With augmented tables the distances are
    reconstructed from the 16-bit member windows riding inside the
    bucket-row fetches (:func:`_window_d0`); otherwise they come from
    a per-candidate id gather — the slow path, kept for swarms too big
    to afford the aug table.  Dead or empty slots return -1 /
    all-ones.  ``answered`` is the delivery mask: the local engine
    always delivers to live targets; the sharded transport may drop
    over-capacity queries (they retry next round).
    """
    n, b_total, k = cfg.n_nodes, cfg.n_buckets, cfg.bucket_k
    l = targets.shape[0]
    safe = jnp.clip(nid, 0, n - 1)
    c = prefix_len32(nid_d0)                                    # [L,A]
    ok = (nid >= 0) & swarm.alive[safe]
    if swarm.tables.dtype == jnp.uint16:                    # augmented
        # One whole-row fetch per solicited node (the only fast gather
        # over a 10 GB table — see the Swarm docstring), then the
        # bucket-pair window [c0·3K, c0·3K+6K) is extracted on-chip by
        # a B-way static-slice select chain (XLA fuses it into a
        # single pass over the fetched rows).  At the deepest bucket
        # this returns rows B-2 and B-1 where the per-row form
        # returned B-1 twice; a candidate superset, same semantics.
        rows = swarm.tables[safe.reshape(-1)]        # [Q, row_w] u16
        c0f = jnp.clip(c, 0, b_total - 2).reshape(-1)        # [Q]
        w3 = 3 * k
        win = _select_pair_window(rows, c0f, w3, b_total)
        idx, d0 = _unpack_pair_window(
            win, c0f, c0f + 1, jnp.repeat(targets[:, 0], nid.shape[1]),
            nid_d0.reshape(-1),
            ok.reshape(-1), k)                       # [Q,2K] each
        resp = idx.reshape(l, -1)
        d0 = d0.reshape(l, -1)
    else:
        c0 = jnp.clip(c, 0, b_total - 1)
        c1 = jnp.clip(c + 1, 0, b_total - 1)
        rows0 = _gather_span(swarm.tables, safe, c0 * k, k)  # [L,A,K]
        rows1 = _gather_span(swarm.tables, safe, c1 * k, k)
        resp = jnp.concatenate([rows0, rows1], axis=-1)     # [L,A,2K]
        resp = jnp.where(ok[..., None], resp, -1).reshape(l, -1)
        d0 = _resp_dist(swarm.ids, cfg, targets, resp)
    return resp, d0, ok


def _select_pair_window(rows: jax.Array, c0: jax.Array, w3: int,
                        b_total: int) -> jax.Array:
    """Extract the adjacent bucket-pair window ``rows[q,
    c0[q]·w3 : c0[q]·w3 + 2·w3]`` with static-slice selects (XLA fuses
    them into one pass over the fetched rows).  ``c0`` must be
    pre-clipped to ``[0, b_total-2]``.

    Two-level select when the padded row allows it: a coarse select
    among ``ceil(B/g)`` group windows of width ``(g+1)·w3`` (g=4),
    then a fine g-way select inside the group — ~30 % fewer
    where-elements than the linear B-way chain, which profiled at
    ~19 ms/step at the 10M-node config (the second-largest step cost
    after the row gather itself).
    """
    g = 4
    n_pos = b_total - 1                   # c0 ∈ [0, b_total-2]
    hi_max = (n_pos - 1) // g
    gw = (g + 1) * w3
    if hi_max >= 1 and hi_max * g * w3 + gw <= rows.shape[1]:
        hi = c0 // g
        lo = c0 - hi * g
        grp = rows[:, 0:gw]
        for h in range(1, hi_max + 1):
            s = h * g * w3
            grp = jnp.where((hi == h)[:, None], rows[:, s:s + gw], grp)
        win = grp[:, 0:2 * w3]
        for b in range(1, g):
            win = jnp.where((lo == b)[:, None],
                            grp[:, b * w3:b * w3 + 2 * w3], win)
        return win
    win = rows[:, 0:2 * w3]
    for b in range(1, b_total - 1):
        win = jnp.where((c0 == b)[:, None],
                        rows[:, b * w3:b * w3 + 2 * w3], win)
    return win


def _unpack_pair_window(win: jax.Array, w0: jax.Array, w1: jax.Array,
                        target0: jax.Array, nid_d0: jax.Array,
                        okf: jax.Array, k: int):
    """Decode a fetched bucket-pair window into candidates.

    ``win [Q, 6K] uint16``: two bucket rows ``[lo K | hi K | s16 K]``
    back to back; ``w0``/``w1`` ``[Q]``: the two rows' bucket depths
    (= window starts); ``target0``/``nid_d0``/``okf`` ``[Q]``.
    Returns ``(idx [Q,2K] int32, d0 [Q,2K] uint32)`` with invalid
    slots -1 / all-ones.

    All math runs on 1-D ``[Q]`` COLUMNS of the window, stacked on
    axis 0 (``[2K, Q]`` — minor dim Q, pad-free) and transposed once
    at the very end: any ``[.., 2, K]``- or ``[Q, small]``-shaped
    intermediate acquires a TPU tiled layout whose minor dims pad to
    (8·)128 lanes — measured 16-128× memory expansion per temp at
    Q≥1M, which is what OOMed the 10M-node lookup step twice.  1-D
    arrays tile flat and pad nothing; the single ``[Q, 2K]`` transpose
    at the end is the one padded buffer this function pays for.
    """
    idx_cols, d0_cols = [], []
    for r, w in ((0, w0), (1, w1)):
        base = r * 3 * k
        for m in range(k):
            lo = win[:, base + m].astype(jnp.uint32)
            hi = win[:, base + k + m].astype(jnp.uint32)
            s16 = win[:, base + 2 * k + m].astype(jnp.uint32)
            idx_j = jax.lax.bitcast_convert_type(
                lo | (hi << jnp.uint32(16)), jnp.int32)
            valid = okf & (idx_j >= 0)
            d0_j = _window_d0(s16, w, target0, nid_d0)
            idx_cols.append(jnp.where(valid, idx_j, -1))
            d0_cols.append(jnp.where(valid, d0_j,
                                     jnp.uint32(UINT32_MAX)))
    return (jnp.stack(idx_cols, axis=0).T,
            jnp.stack(d0_cols, axis=0).T)


def _window_d0(s16: jax.Array, w: jax.Array, target0: jax.Array,
               nid_d0: jax.Array) -> jax.Array:
    """Approximate first-limb XOR distance from a 16-bit member window.

    A bucket-``b`` table row stores, per member, bits ``[b, b+16)`` of
    the member's first id limb (MSB-aligned ``s16``).  Every bit above
    the window is *shared with the owning node* — bucket members agree
    with their node on all bits before the bucket depth — so those
    distance bits equal the corresponding bits of ``nid_d0``, the
    solicited node's own distance to the target, which the caller
    already holds and whose leading ``clz+1`` bits are always exact
    (``w ≤ clz(nid_d0)+1`` by construction of the two-row gather).
    Bits below the window are unknown and read as zero.

    The result is exact through bit ``w+16``: ≥16 significant bits
    past the leading one, a 2⁻¹⁶ worst-case relative order error —
    see the tie analysis in
    :func:`opendht_tpu.ops.xor_metric.merge_shortlists_d0`.  A valid
    reconstruction can never equal the 0xFFFFFFFF empty sentinel: the
    sub-window bits are zero unless ``w+16 ≥ 32``, which needs
    ``w ≥ 16``, while an all-ones prefix forces ``clz(nid_d0)=0`` and
    hence ``w ≤ 1``.

    Args broadcast together; ``w`` is the window start (= bucket
    index), int32.
    """
    wu = jnp.clip(w, 0, 31).astype(jnp.uint32)
    t16 = (target0 << wu) >> jnp.uint32(16)
    d16 = s16 ^ t16
    lsh = jnp.clip(16 - w, 0, 16).astype(jnp.uint32)
    rsh = jnp.clip(w - 16, 0, 16).astype(jnp.uint32)
    placed = jnp.where(w <= 16, d16 << lsh, d16 >> rsh)
    hm = jnp.where(
        w > 0,
        jnp.uint32(UINT32_MAX)
        << jnp.clip(32 - w, 0, 31).astype(jnp.uint32),
        jnp.uint32(0))
    return (nid_d0 & hm) | placed


def _gather_span(tables: jax.Array, node: jax.Array, start: jax.Array,
                 width: int) -> jax.Array:
    """Gather ``tables[node, start:start+width]`` → ``[..., width]``.

    One gather op fetching a contiguous ``width``-element span of the
    2-D row-contiguous table per (node, start) pair — the adjacent-
    buckets fetch is a single span, half the fetches of two per-row
    gathers, and layout-aligned with the table's minor dimension (no
    transposed operand copy — see the ``Swarm`` docstring).
    """
    idx = jnp.stack([node, start], axis=-1)           # [..., 2]
    return jax.lax.gather(
        tables, idx,
        jax.lax.GatherDimensionNumbers(
            offset_dims=(node.ndim,),
            collapsed_slice_dims=(0,),
            start_index_map=(0, 1)),
        slice_sizes=(1, width),
        mode=jax.lax.GatherScatterMode.CLIP)


def _select_alpha(st: LookupState, cfg: SwarmConfig):
    """α best unqueried shortlist nodes per lookup, with their d0 and
    shortlist slot.

    The shortlist is already distance-sorted, so the α best unqueried
    are the first α unqueried slots.  One vectorized one-hot
    extraction: the unqueried-rank cumsum compared against
    ``arange(alpha)`` gives a single ``[L,S,A]`` selection tensor
    (at most one slot per (row, rank) pair), contracted by three
    max-reductions — replacing the former per-rank Python loop whose
    HLO grew linearly with α.  Returns ``(sel [L,A] int32, sel_d0
    [L,A], sel_pos [L,A] int32)``: the d0 rides along so responders
    can derive their bucket index without touching the id matrix, and
    the slot position lets the round tail scatter the queried/evict
    updates straight back instead of re-matching ``sel`` against the
    whole shortlist (the old ``[L,S,A]`` hit tensor).
    """
    unq = (st.idx >= 0) & ~st.queried
    order = jnp.cumsum(unq.astype(jnp.int32), axis=1)
    oh = unq[:, :, None] & (
        order[:, :, None] == jnp.arange(1, cfg.alpha + 1,
                                        dtype=jnp.int32)[None, None, :])
    sel = jnp.max(jnp.where(oh, st.idx[:, :, None], -1), axis=1)
    sel_d0 = jnp.max(jnp.where(oh, st.dist[:, :, None],
                               jnp.uint32(0)), axis=1)
    slots = jnp.arange(st.idx.shape[1], dtype=jnp.int32)[None, :, None]
    sel_pos = jnp.max(jnp.where(oh, slots, -1), axis=1)
    return sel, sel_d0, sel_pos


def _sync_done(st_idx: jax.Array, st_queried: jax.Array,
               cfg: SwarmConfig) -> jax.Array:
    """True where the ``quorum`` closest known nodes are all queried."""
    head_idx = st_idx[:, :cfg.quorum]
    head_q = st_queried[:, :cfg.quorum]
    valid = head_idx >= 0
    return jnp.all(head_q | ~valid, axis=1) & jnp.any(valid, axis=1)


def init_impl(ids: jax.Array, respond, cfg: SwarmConfig,
              targets: jax.Array, origins: jax.Array) -> LookupState:
    """Shared lock-step init: seed each lookup from its origin node's
    own routing table — the reference's search creation consulting
    local buckets (``Dht::search`` src/dht.cpp:1672-1735).

    ``respond(targets, nid, nid_d0)`` abstracts where routing tables
    live: local gathers (single chip) or the all_to_all routed
    exchange (:mod:`opendht_tpu.parallel.sharded`).
    """
    l = targets.shape[0]
    s = cfg.search_width
    o_d0 = ids[:, 0][origins] ^ targets[:, 0]         # [L]
    resp, resp_d0, _ = respond(targets, origins[:, None], o_d0[:, None])
    pad = max(0, s - resp.shape[1])
    if pad:
        resp = jnp.concatenate(
            [resp, jnp.full((l, pad), -1, jnp.int32)], axis=1)
        resp_d0 = jnp.concatenate(
            [resp_d0, jnp.full((l, pad), UINT32_MAX, jnp.uint32)], axis=1)
    f_idx, f_dist, f_q = merge_shortlists_d0(
        resp_d0, resp, jnp.zeros_like(resp, bool), keep=s)
    return LookupState(
        targets=targets, idx=f_idx, dist=f_dist, queried=f_q,
        done=jnp.zeros((l,), bool), hops=jnp.zeros((l,), jnp.int32))


def step_impl(ids: jax.Array, alive: jax.Array, respond,
              cfg: SwarmConfig, st: LookupState,
              trace: LookupTrace | None = None,
              rnd: jax.Array | None = None, done_base: int = 0,
              merge_w: int | None = None):
    """Shared lock-step solicitation round (vectorized ``searchStep``,
    src/dht.cpp:1343-1464): select α unqueried, solicit via
    ``respond``, merge responses, re-sort, check sync quorum.

    With a ``trace`` (and its round index ``rnd``), returns
    ``(state, trace)`` with the round's counters folded in — the
    flight-recorder path; ``trace=None`` (default) keeps the bare
    hot-path signature.  ``done_base`` is the count of finished rows
    the compaction ladder excluded from this dispatch (they sit
    outside ``st`` but are still done) — added to the done GAUGE so a
    truncated dispatch reports the same batch-wide convergence curve
    as a full-width one.  ``merge_w`` (static) is the response-width
    ladder rung the rank merge is priced at — guarded in-jit, so any
    value is bit-identical to ``None`` (full width); see
    :func:`opendht_tpu.ops.xor_metric.rank_merge_round_d0_w`."""
    # Finished lookups stop soliciting: besides wasting gathers, their
    # traffic would consume bounded all_to_all capacity and could
    # starve still-active queries on a hot shard.
    sel, sel_d0, sel_pos = _select_alpha(st, cfg)               # [L,A]
    sel = jnp.where(st.done[:, None], -1, sel)
    sel_alive = (sel >= 0) & alive[jnp.clip(sel, 0, cfg.n_nodes - 1)]
    resp, resp_d0, answered = respond(st.targets, sel, sel_d0)  # [L,A*2K]
    return _merge_round(st, cfg, sel, sel_pos, sel_alive, answered,
                        resp, resp_d0, trace=trace, rnd=rnd,
                        done_base=done_base, merge_w=merge_w)


def _merge_round(st: LookupState, cfg: SwarmConfig, sel: jax.Array,
                 sel_pos: jax.Array, sel_alive: jax.Array,
                 answered: jax.Array, resp: jax.Array,
                 resp_d0: jax.Array,
                 trace: LookupTrace | None = None,
                 rnd: jax.Array | None = None, done_base: int = 0,
                 merge_w: int | None = None):
    """Round tail shared by the plain and chaos engines: fold the α
    solicitations' outcomes into the shortlist, merge, re-sort, check
    the sync quorum.  ONE copy of the merge/eviction/done semantics,
    so the two engines cannot silently diverge.

    Answered solicitations become "queried"; nodes in ``sel`` marked
    not ``sel_alive`` (dead — or, on the chaos path, convicted /
    contradicted) are evicted from the shortlist entirely — the
    reference expires a node after 3 unanswered attempts and replaces
    it with the next candidate (request.h:113, src/dht.cpp:1059-1074).
    Alive-but-unanswered (transport drop) stays unqueried and is
    re-solicited next round.

    ``sel_pos`` is each solicitation's shortlist slot (from
    ``_select_alpha``): the queried/evict updates scatter straight to
    those slots — the shortlist is duplicate-free and unchanged since
    selection, so the old ``[L,S,α]`` equality hit tensor resolved to
    exactly these positions.  The merge itself dispatches on
    ``SwarmConfig.merge_impl`` (see :func:`resolve_merge_impl`): the
    sort-free rank merge, the fused Pallas round kernel, or the
    two-pass sorted reference — all bit-identical on this input domain
    (``tests/test_merge_equivalence.py``).
    """
    l, s_w = st.idx.shape
    rows = jnp.arange(l, dtype=jnp.int32)[:, None]
    valid_sel = sel >= 0
    q_hit = valid_sel & sel_alive & answered
    e_hit = valid_sel & ~sel_alive
    queried = st.queried.at[
        rows, jnp.where(q_hit, sel_pos, s_w)].set(True, mode="drop")
    evict = jnp.zeros_like(st.queried).at[
        rows, jnp.where(e_hit, sel_pos, s_w)].set(True, mode="drop")
    idx = jnp.where(evict, -1, st.idx)
    # Evicted frontier slots must not keep their old (now invalid)
    # distance keys.
    fr_dist = jnp.where(evict, jnp.uint32(UINT32_MAX), st.dist)
    impl = resolve_merge_impl(cfg)
    done_merge = None
    if impl in ("pallas", "pallas-round"):
        # "pallas-round" reaching THIS dispatch means the engine cannot
        # fuse the whole round (traced/chaos/routed paths, plain
        # tables) — it degrades to the merge-only kernel; the local
        # plain engine intercepts it earlier (lookup_step).
        from ..ops.pallas_kernels import merge_round_pallas
        f_idx, f_dist, f_q, done_merge = merge_round_pallas(
            idx, fr_dist, queried, resp, resp_d0,
            quorum=cfg.quorum, keep=cfg.search_width)
    elif impl == "xla":
        f_idx, f_dist, f_q = rank_merge_round_d0_w(
            idx, fr_dist, queried, resp, resp_d0,
            keep=cfg.search_width, merge_w=merge_w)
    else:                                               # "xla-sort"
        cand_idx = jnp.concatenate([idx, resp], axis=1)
        cand_dist = jnp.concatenate([fr_dist, resp_d0], axis=1)
        cand_q = jnp.concatenate(
            [queried, jnp.zeros_like(resp, bool)], axis=1)
        f_idx, f_dist, f_q = merge_shortlists_d0(
            cand_dist, cand_idx, cand_q, keep=cfg.search_width)

    active = ~st.done & jnp.any(sel >= 0, axis=1)
    if done_merge is None:
        done_merge = _sync_done(f_idx, f_q, cfg) | ~jnp.any(
            (f_idx >= 0) & ~f_q, axis=1)
    done = st.done | done_merge
    # No done-freeze copies: a done lookup solicits nobody (sel = -1),
    # so its merge inputs are its own shortlist plus invalid slots, and
    # the two-pass stable merge is idempotent on an already-merged
    # state (equal-d0 ties order by node index from pass 1, independent
    # of input order) — f_* already equal st.* bit-for-bit for done
    # rows.  The wheres cost three [L,S] copies per round.
    completed = st.completed_round
    if completed is not None:
        # Lifecycle stamp (pure observer — nothing downstream reads
        # it): the round a row's done bit first went True.  Free serve
        # slots (admitted_round = -1) are already done, so they can
        # never restamp.
        if rnd is None:
            raise ValueError(
                "lifecycle tracking needs the round index: pass rnd= "
                "to the step (the loops do when the fields are present)")
        completed = jnp.where(done & ~st.done,
                              jnp.asarray(rnd, jnp.int32), completed)
    new_st = LookupState(
        targets=st.targets, idx=f_idx, dist=f_dist, queried=f_q,
        done=done, hops=st.hops + active.astype(jnp.int32),
        admitted_round=st.admitted_round, completed_round=completed)
    if trace is None:
        return new_st
    i32 = jnp.int32
    trace = trace._replace(
        requests=trace.requests.at[rnd].add(
            jnp.sum((sel >= 0).astype(i32)), mode="drop"),
        replies=trace.replies.at[rnd].add(
            jnp.sum((resp >= 0).astype(i32)), mode="drop"),
        drops=trace.drops.at[rnd].add(
            jnp.sum(((sel >= 0) & (~sel_alive | ~answered)).astype(i32)),
            mode="drop"),
        churn=trace.churn.at[rnd].add(
            jnp.sum((f_idx != st.idx).astype(i32)), mode="drop"),
        done=trace.done.at[rnd].set(
            jnp.sum(done.astype(i32)) + i32(done_base), mode="drop"),
        # Pending at round ENTRY (pre-merge done mask).  Rows hidden by
        # the compaction ladder are all done, so the prefix's pending
        # count IS the batch-wide one — no done_base needed here.
        active_rows=trace.active_rows.at[rnd].add(
            jnp.sum((~st.done).astype(i32)), mode="drop"),
        rounds=jnp.maximum(trace.rounds, i32(rnd) + 1))
    return new_st, trace


def _resp_dist(ids: jax.Array, cfg: SwarmConfig, targets: jax.Array,
               cand_idx: jax.Array) -> jax.Array:
    """First-limb XOR distance for candidate indices (~0 where -1)."""
    cand_ids0 = ids[:, 0][jnp.clip(cand_idx, 0, cfg.n_nodes - 1)]
    d0 = jnp.bitwise_xor(cand_ids0, targets[:, 0][:, None])
    return jnp.where(cand_idx < 0, jnp.uint32(UINT32_MAX), d0)


def _local_respond(swarm: Swarm, cfg: SwarmConfig):
    return lambda tg, nid, nid_d0: _respond(swarm, cfg, tg, nid, nid_d0)


@partial(jax.jit, static_argnames=("l",))
def _sample_origins(key: jax.Array, alive: jax.Array,
                    l: int) -> jax.Array:
    """Uniform random *alive* origin per lookup — exact masked sampling.

    Inverse-CDF over the alive mask: one [N] cumsum + L binary
    searches, O(N + L·log N) time, O(N+L) memory.  (A categorical over
    the alive mask materializes an [L,N] gumbel plane when not fused —
    372 GB at L=100k, N=1M.  The former two-draw rejection fell back
    to ONE fixed node with probability kill_frac² per lookup: at the
    mult_time bench's 66 % cumulative death, ~44 % of maintenance
    lookups originated from a single node, skewing hop counts and
    localized-damage survival.)
    """
    n = alive.shape[0]
    cum = jnp.cumsum(alive.astype(jnp.int32))                  # [N]
    total = cum[-1]
    u = jax.random.randint(key, (l,), 0, jnp.maximum(total, 1),
                           jnp.int32)
    # First index whose cumulative alive-count exceeds u = the
    # (u+1)-th alive node; clip only guards the all-dead degenerate.
    # All-alive fast path (every non-churn benchmark): cum is the
    # identity+1, so the inverse-CDF is u itself — lax.cond skips the
    # L·log N binary-search gathers at runtime (measured ~80 ms per
    # 500k draws over 10M nodes, 3 % of the whole north-star run).
    return jax.lax.cond(
        total == n,
        lambda: u,
        lambda: jnp.clip(jnp.searchsorted(cum, u, side="right"),
                         0, n - 1).astype(jnp.int32))


@partial(jax.jit, static_argnames=("cfg",))
def lookup_init(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
                origins: jax.Array) -> LookupState:
    return init_impl(swarm.ids, _local_respond(swarm, cfg), cfg,
                     targets, origins)


@partial(jax.jit, static_argnames=("cfg", "merge_w"))
def lookup_step(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
                rnd: jax.Array | None = None,
                merge_w: int | None = None) -> LookupState:
    """One plain round.  ``rnd`` (the loop's round index) is only
    needed — and only passed by the loops — when the state carries the
    lifecycle plane; without it the program is byte-identical to the
    pre-lifecycle step.  ``merge_w`` (static, loops only) is the
    response-width rung the rank merge is priced at — ``None`` keeps
    the exact pre-ladder program; any value is bit-identical (in-jit
    guarded)."""
    if resolve_merge_impl(cfg) == "pallas-round":
        return _fused_round_step(swarm, cfg, st, rnd=rnd)
    return step_impl(swarm.ids, swarm.alive, _local_respond(swarm, cfg),
                     cfg, st, rnd=rnd, merge_w=merge_w)


@partial(jax.jit, static_argnames=("cfg", "merge_w"),
         donate_argnums=(2,))
def _lookup_step_d(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
                   rnd: jax.Array | None = None,
                   merge_w: int | None = None) -> LookupState:
    """:func:`lookup_step` with the state DONATED — the burst-loop
    carry is single-owner, so XLA reuses its buffers in place instead
    of holding input+output copies across every round (and across the
    compaction repack).  Internal to the burst loops: external callers
    keep the non-donating :func:`lookup_step`, whose inputs stay
    valid."""
    if resolve_merge_impl(cfg) == "pallas-round":
        return _fused_round_step(swarm, cfg, st, rnd=rnd)
    return step_impl(swarm.ids, swarm.alive, _local_respond(swarm, cfg),
                     cfg, st, rnd=rnd, merge_w=merge_w)


def _fused_round_step(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
                      rnd: jax.Array | None = None) -> LookupState:
    """One plain round through the WHOLE-ROUND fused Pallas kernel
    (``merge_impl="pallas-round"``): the α-select scalars are prepared
    by a thin XLA prelude, then table-row gather (in-kernel DMAs),
    window decode, the queried/evict update, the rank merge and the
    quorum check all run with the frontier resident in VMEM —
    :func:`opendht_tpu.ops.pallas_kernels.fused_round_pallas`.

    Semantics are EXACTLY :func:`step_impl` over the local augmented
    respond — asserted bit-identical (results, hops, done) in
    ``tests/test_merge_equivalence.py`` under interpret mode.  Only
    the local plain engine takes this path; augmented tables are
    required (the kernel's row DMAs and window decode are the aug
    layout's).
    """
    if swarm.tables.dtype != jnp.uint16:
        raise ValueError(
            "merge_impl='pallas-round' requires augmented tables "
            "(SwarmConfig.aug_tables=True): the fused round kernel "
            "gathers and decodes the u16 bucket-row layout in-kernel")
    from ..ops.pallas_kernels import fused_round_pallas
    n, b_total = cfg.n_nodes, cfg.n_buckets
    sel, sel_d0, sel_pos = _select_alpha(st, cfg)               # [L,A]
    sel = jnp.where(st.done[:, None], -1, sel)
    safe = jnp.clip(sel, 0, n - 1)
    valid_sel = sel >= 0
    sel_alive = valid_sel & swarm.alive[safe]
    # Local respond delivers to every live target (answered ≡ alive).
    q_hit = valid_sel & sel_alive
    e_hit = valid_sel & ~sel_alive
    w0 = jnp.clip(prefix_len32(sel_d0), 0, b_total - 2)
    f_idx, f_dist, f_q, done_merge = fused_round_pallas(
        swarm.tables, st.targets[:, 0], st.idx, st.dist, st.queried,
        safe, sel_d0, sel_pos, w0, q_hit, e_hit,
        bucket_k=cfg.bucket_k, quorum=cfg.quorum,
        keep=cfg.search_width)
    active = ~st.done & jnp.any(sel >= 0, axis=1)
    done = st.done | done_merge
    completed = st.completed_round
    if completed is not None:
        if rnd is None:
            raise ValueError(
                "lifecycle tracking needs the round index: pass rnd= "
                "to the step (the loops do when the fields are present)")
        completed = jnp.where(done & ~st.done,
                              jnp.asarray(rnd, jnp.int32), completed)
    return LookupState(
        targets=st.targets, idx=f_idx, dist=f_dist, queried=f_q,
        done=done, hops=st.hops + active.astype(jnp.int32),
        admitted_round=st.admitted_round, completed_round=completed)


def lookup(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
           key: jax.Array, compact: bool = True,
           stats: dict | None = None,
           track_lifecycle: bool = False) -> LookupResult:
    """Run a batch of iterative lookups to completion.

    ``targets``: ``[L,5]``.  Origins are random alive nodes (each
    lookup is issued "from" a random participant, like the scenario
    tests' random-node gets, python/tools/dht/tests.py:865-950).

    The round loop runs on the HOST: a device-side ``lax.while_loop``
    threads every captured array through the loop state, and XLA
    materializes a full copy of the multi-GB routing table for that —
    at 10M nodes a second 10 GB buffer that OOMs the chip.  Rounds are
    dispatched in BURSTS with a done-check only between bursts: each
    scalar readback through the device tunnel costs ~100 ms, so a
    per-round check would serialize the loop on round-trips, while
    burst dispatches pipeline back-to-back on the device.  Finished
    lookups are frozen inside the step, so overshooting the
    convergence round by a few bursts is wall-clock waste only, never
    a semantics change.

    ``compact`` (default) turns on the straggler-harvesting ladder:
    after the first burst, pending rows are stably repacked to the
    front and tail rounds dispatch on shrinking power-of-two prefixes,
    with finished rows scattered back at finalize — bit-identical
    results (see the compaction block comment), tail rounds priced by
    the ACTIVE set instead of the batch width.  (The round-5 one-shot
    quarter-width variant measured slower at 10M because it paid an
    extra pending readback and a fixed width; the ladder reuses the
    existing done-check and tracks the true tail.)  ``stats`` receives
    the dispatch-attribution fields (see
    :func:`run_compacted_burst_loop`).

    ``track_lifecycle`` attaches the per-request lifecycle plane
    (``admitted_round``/``completed_round`` — see :class:`LookupState`)
    to the loop carry: a pure observer (bit-identical results, asserted
    in tests/test_serve.py); the per-row round indices land in
    ``stats["admitted_round"]``/``stats["completed_round"]`` (original
    batch order) when a ``stats`` dict is passed.
    """
    l = targets.shape[0]
    # Phase attribution (bench satellite): with ``stats["time_phases"]``
    # set, wall time is split init / loop / finalize with a
    # ``block_until_ready`` barrier between phases.  The barriers
    # de-pipeline the device queue, so attribution runs are SEPARATE
    # from rate measurements (bench.py runs one extra untimed pass).
    timing = bool(stats) and stats.get("time_phases")
    t0 = time.perf_counter() if timing else 0.0
    # Origins are drawn from *alive* nodes: the issuing node exists.
    origins = _sample_origins(key, swarm.alive, l)
    st = lookup_init(swarm, cfg, targets, origins)
    if track_lifecycle:
        st = init_lifecycle(st)
    # EXPLICIT cached upload (utils/hostdevice) for the per-round
    # coordinate: spelled jnp.int32(r) it is an implicit host→device
    # transfer every round, which graftlint's strict transfer-guard
    # replay forbids on steady-state loops.
    rnd_of = dev_i32 if track_lifecycle else (lambda r: None)
    if timing:
        jax.block_until_ready(st)
        t1 = time.perf_counter()
        stats["init_s"] = t1 - t0
    if not compact:
        st = run_burst_loop(
            lambda s, r: lookup_step(swarm, cfg, s, rnd_of(r)), st, cfg)
        if track_lifecycle and stats is not None:
            stats["admitted_round"] = st.admitted_round
            stats["completed_round"] = st.completed_round
        return LookupResult(found=_finalize(swarm.ids, st, cfg),
                            hops=st.hops, done=st.done)
    st, _, order = run_compacted_burst_loop(
        lambda s, ex, r, hidden, mw: (_lookup_step_d(
            swarm, cfg, s, rnd_of(r), merge_w=mw), ex),
        st, cfg, stats=stats,
        width_ladder=resolve_merge_impl(cfg) == "xla")
    if track_lifecycle and stats is not None:
        stats["admitted_round"] = _scatter_rows(st.admitted_round, order)
        stats["completed_round"] = _scatter_rows(st.completed_round,
                                                 order)
    if timing:
        jax.block_until_ready(st)
        t2 = time.perf_counter()
        stats["loop_s"] = t2 - t1
    found, hops, done = _finalize_scattered(swarm.ids, st, order, cfg)
    if timing:
        jax.block_until_ready((found, hops, done))
        t3 = time.perf_counter()
        stats["finalize_s"] = t3 - t2
        stats["phase_total_s"] = t3 - t0
    return LookupResult(found=found, hops=hops, done=done)


def burst_schedule(cfg: SwarmConfig) -> int:
    """First-burst round count: the MEASURED convergence depth
    (pending-by-round on v5e-1, 500k uniform lookups: 100k nodes → 7
    rounds, 1M → 8, 10M → 9 = ceil(log2 N / 2.65) at all three
    calibration points).  The previous 2.56 divisor overshot the 10M
    north star by one round — ceil(23.25/2.56) = 10 — dispatching a
    ~97 ms full-batch step with nothing pending on every call; 2.65
    lands 7/8/9 exactly (valid divisor window from the three points:
    (2.583, 2.767]).  Every extra dispatched round costs a full-batch
    step whether or not anything is pending, while an undershoot costs
    one ~100 ms scalar readback plus a 2-round top-up — so aim exactly
    and let the done-check loop absorb seed variance.  The one
    calibration constant shared by the local and sharded burst loops.
    """
    return min(cfg.max_steps,
               max(6, math.ceil(math.log2(max(2, cfg.n_nodes)) / 2.65)))


def run_burst_loop(step_fn, state, cfg: SwarmConfig,
                   done_of=lambda s: s.done):
    """Host-driven round loop: dispatch ``burst_schedule`` rounds
    back-to-back (they pipeline on the device), then check global
    done-ness with one scalar readback, topping up 2 rounds at a time.
    Finished lookups are frozen inside the step, so overshoot is
    wall-clock waste only, never a semantics change.

    ``step_fn(state, round)`` advances an opaque carry one round (the
    round index doubles as the chaos engine's stateless fault-stream
    coordinate); ``done_of`` extracts the ``[L]`` done mask from the
    carry.  One loop serves the plain engines (carry = LookupState)
    and the chaos engine (carry = (LookupState, strikes)) — burst
    policy tuning lands in exactly one place."""
    burst = burst_schedule(cfg)
    rounds = 0
    while rounds < cfg.max_steps:
        n = min(burst, cfg.max_steps - rounds)
        for _ in range(n):
            state = step_fn(state, rounds)
            rounds += 1
        # Per-BURST done poll (explicit device_get: bool() on a device
        # array is an implicit D2H transfer, forbidden under the
        # strict transfer-guard replay).
        # graftlint: disable=sync-in-loop (per-burst done-check readback, amortized over >=2 device rounds — the burst loop's contract; the round-20 resident serve loop is the zero-per-burst-poll alternative, its early exit living in lax.while_loop cond instead)
        if bool(jax.device_get(jnp.all(done_of(state)))):
            break
        burst = 2
    return state


# ---------------------------------------------------------------------------
# straggler harvesting: done-partitioned compaction of the burst loop
# ---------------------------------------------------------------------------
#
# Hop counts concentrate around log2 N / log2 k but carry a long tail
# (arXiv 1307.7000): the done gauge crosses ~90 % several rounds before
# the loop exits, yet every full-width round pays [L]-wide gathers,
# merges and sorts for rows that finished long ago.  After each burst
# the pending rows are stably repacked to the front and subsequent
# rounds dispatch on a power-of-two-truncated PREFIX (shape ladder
# L, …, 2^k, … — at most log2 L step specializations, each compiled
# once since pending only shrinks).  Stability is what makes the
# compacted engines bit-identical to the uncompacted ones: every round
# op is row-local (responds gather per row, the fault hashes key on
# (node, target, round), strikes scatter into [N]) EXCEPT the sharded
# transport's capacity bucketing, which ranks real queries by arrival
# order — done rows emit no queries and a stable repack preserves the
# pending rows' relative order, so the ranks (and hence capacity
# drops) are unchanged.  Finished rows wait outside the prefix and are
# scattered back to their original positions at finalize.  Every jit
# below DONATES its state operands so the repack never holds two
# copies of the [L,S] carry (the round-5 attempt's HBM regression).

def _ladder_width(pending: int, l: int, floor: int = 128) -> int:
    """Dispatch width covering ``pending`` rows: the smallest power of
    two ≥ pending (and ≥ ``floor`` — sub-lane widths waste more in
    relaunch overhead than they save), capped at the batch width."""
    if pending >= l:
        return l
    p = max(1, pending, min(floor, l))
    return min(l, 1 << (p - 1).bit_length())


def _stable_done_perm(done: jax.Array) -> jax.Array:
    """Stable pending-first permutation of row indices.

    ``lax.sort`` with ``is_stable`` rather than ``jnp.argsort`` —
    stability is a CORRECTNESS requirement here (see the block comment
    above), not a tiebreak nicety."""
    l = done.shape[0]
    _, perm = jax.lax.sort(
        (done.astype(jnp.int32), jnp.arange(l, dtype=jnp.int32)),
        dimension=0, num_keys=1, is_stable=True)
    return perm


def _permute_state(st: LookupState, perm: jax.Array) -> LookupState:
    # The lifecycle fields are None when tracking is off — skip, don't
    # crash (same guard in every generic per-field helper below).
    return LookupState(*[x if x is None else jnp.take(x, perm, axis=0)
                         for x in st])


@partial(jax.jit, static_argnames=("w",), donate_argnums=(0, 1))
def _compact_slice(st: LookupState, order: jax.Array, w: int):
    """First compaction: repack pending-first, return the repacked
    full state, the row provenance, and the ``[:w]`` dispatch view."""
    perm = _stable_done_perm(st.done)
    full = _permute_state(st, perm)
    return full, order[perm], LookupState(
        *[x if x is None else x[:w] for x in full])


@partial(jax.jit, static_argnames=("w",), donate_argnums=(0, 1))
def _compact_resize(full: LookupState, order: jax.Array,
                    sub: LookupState, w: int):
    """Subsequent compactions: fold the advanced prefix back into the
    full state, repack, re-slice at the (smaller) ladder width.  The
    [w_old] ``sub`` is not donated — its buffers can alias neither the
    [L] full state nor the narrower new slice."""
    wo = sub.done.shape[0]
    full = LookupState(*[f if f is None else f.at[:wo].set(s)
                         for f, s in zip(full, sub)])
    perm = _stable_done_perm(full.done)
    full = _permute_state(full, perm)
    return full, order[perm], LookupState(
        *[x if x is None else x[:w] for x in full])


@partial(jax.jit, donate_argnums=(0,))
def _writeback_prefix(full: LookupState, sub: LookupState) -> LookupState:
    wo = sub.done.shape[0]
    return LookupState(*[f if f is None else f.at[:wo].set(s)
                         for f, s in zip(full, sub)])


@partial(jax.jit, static_argnames=("lim",))
def _ge_limit(x: jax.Array, lim: int) -> jax.Array:
    """``x >= lim`` with the Python-int limit folded as a program
    constant instead of an eager per-call scalar upload."""
    return x >= lim


@partial(jax.jit, static_argnames=("n",))
def _zeros_i32(n: int) -> jax.Array:
    """``[n]`` int32 zeros as a compiled program constant — eager
    ``jnp.zeros`` is a fresh host→device upload per call, which the
    strict transfer-guard replay forbids on engine paths."""
    return jnp.zeros((n,), jnp.int32)


@partial(jax.jit, static_argnames=("cfg",))
def _pending_and_wneed(st: LookupState, cfg: SwarmConfig):
    """Fused per-burst readback pair: the pending count that steers the
    ROW ladder, and the live-slot WATERMARK that steers the merge-width
    ladder — the widest pending row's next-round solicitation count
    times the 2K response block, i.e. an upper bound on next round's
    live response columns (dead solicitations still occupy a block but
    return only invalid slots, which the merge prices as empty).  Two
    scalars, ONE device_get — the readback the burst loop already
    pays."""
    pending = jnp.sum(~st.done)
    unq = jnp.sum(((st.idx >= 0) & ~st.queried).astype(jnp.int32),
                  axis=1)
    blocks = jnp.where(st.done, 0, jnp.minimum(cfg.alpha, unq))
    return pending, jnp.max(blocks) * (2 * cfg.bucket_k)


@jax.jit
def _scatter_rows(x: jax.Array, order: jax.Array) -> jax.Array:
    """Return rows to their pre-compaction batch positions (``order[i]``
    is row ``i``'s original index).  Jitted so the zero template is a
    program constant, not a fresh host upload per call (strict
    transfer-guard hygiene)."""
    return jnp.zeros_like(x).at[order].set(x)


@partial(jax.jit, static_argnames=("cfg",))
def _finalize_scattered(ids: jax.Array, st: LookupState,
                        order: jax.Array, cfg: SwarmConfig):
    found = _finalize(ids, st, cfg)
    return (_scatter_rows(found, order), _scatter_rows(st.hops, order),
            _scatter_rows(st.done, order))


def run_compacted_burst_loop(step_fn, st: LookupState, cfg: SwarmConfig,
                             extras=(), stats: dict | None = None,
                             width_ladder: bool = False):
    """:func:`run_burst_loop` with active-set compaction.

    ``step_fn(st, extras, rnd, hidden, merge_w)`` advances one round
    and returns ``(st, extras)``; ``hidden`` (a Python int, ≤ log2 L
    distinct values) is the count of finished rows excluded from the
    dispatched prefix — traced steps add it to the done gauge;
    ``merge_w`` is the response-width rung the merge should be priced
    at (``None`` = full width; steps that don't ladder just drop it).
    ``extras`` is an opaque tuple riding the carry at full shape
    (chaos strike vectors, traces); only the ``LookupState`` is
    compacted.  The done-check readback the burst loop already pays
    doubles as the pending count that drives the shape ladder — and,
    with ``width_ladder`` on, as the live-slot WATERMARK that drives
    the merge-width ladder (one fused readback, still zero extra host
    syncs).  The watermark is not monotone, so a stale rung is
    corrected in-jit by the merge's overflow guard
    (:func:`opendht_tpu.ops.xor_metric.rank_merge_round_d0_w`) —
    bit-identical either way.  Returns ``(full_state, extras, order)``
    — ``order[i]`` is row ``i``'s original batch position, for the
    finalize scatter-back.

    ``stats`` (optional dict) receives ``rounds_dispatched``,
    ``dispatched_row_rounds``, ``mean_active_frac`` and the distinct
    ``widths`` used — the bench's attribution fields — plus
    ``merge_widths`` when the width ladder engages.
    """
    l = st.done.shape[0]
    order = jnp.arange(l, dtype=jnp.int32)
    full, sub, w = st, st, l
    resp_w = cfg.alpha * 2 * cfg.bucket_k
    ladder = (merge_ladder_widths(resp_w, 2 * cfg.bucket_k)
              if width_ladder else [resp_w])
    merge_w = None
    merge_widths = []
    # First burst SHORTENED vs the uncompacted loop's calibrated
    # convergence depth: the done gauge crosses ~90 % two rounds
    # before the burst exit (measured 100k/1M/10M pending-by-round),
    # so stopping the full-width burst at the knee and letting the
    # ladder price the last rounds by the active set is where most of
    # the wasted row-rounds are — the cost is ONE extra done-check
    # readback vs aiming the whole depth.
    burst = max(2, burst_schedule(cfg) - 2)
    # Per-burst wall clocks for the bench's per-round attribution:
    # rounds inside a burst pipeline with no sync, so the honest
    # per-round figure is burst wall (including its done-check
    # readback, the barrier the loop pays anyway) divided by the
    # burst's round count.
    timing = stats is not None and stats.get("time_phases")
    rounds = 0
    row_rounds = 0
    widths = []
    while rounds < cfg.max_steps:
        n = min(burst, cfg.max_steps - rounds)
        tb = time.perf_counter() if timing else 0.0
        for _ in range(n):
            sub, extras = step_fn(sub, extras, rounds, l - w, merge_w)
            rounds += 1
            row_rounds += w
        if w not in widths:
            widths.append(w)
        if merge_w not in merge_widths:
            merge_widths.append(merge_w)
        # graftlint: disable=sync-in-loop (per-burst pending readback steers the ladder width — amortized over >=2 device rounds; the resident loop's rung_block moves this selection in-jit and pays no readback at all)
        pending, wneed = (int(x) for x in jax.device_get(
            _pending_and_wneed(sub, cfg)))
        if timing:
            stats.setdefault("burst_walls", []).append(
                (time.perf_counter() - tb, n))
        if pending == 0:
            break
        # Tail bursts stay 2 rounds: a 1-round tail was measured 13%
        # SLOWER on the gate leg — the per-round readback serializes
        # host dispatch against device execution, costing more than
        # the overshoot round it saves.
        burst = 2
        if len(ladder) > 1:
            # Merge-width rung for the NEXT burst from the live-slot
            # watermark: the widest pending row can solicit at most
            # ``wneed/2K`` nodes next round, so its response block's
            # live columns are bounded by ``wneed`` — the in-jit guard
            # covers the non-monotone case where a later round in the
            # burst regrows past the rung.
            merge_w = pick_merge_width(wneed, resp_w,
                                       2 * cfg.bucket_k)
        w_new = _ladder_width(pending, l)
        if w_new < w:
            if w == l:
                full, order, sub = _compact_slice(sub, order, w_new)
            else:
                full, order, sub = _compact_resize(full, order, sub,
                                                   w_new)
            w = w_new
    full = _writeback_prefix(full, sub) if w < l else sub
    if stats is not None:
        stats["rounds_dispatched"] = rounds
        stats["dispatched_row_rounds"] = row_rounds
        stats["mean_active_frac"] = (
            round(row_rounds / (rounds * l), 4) if rounds else 0.0)
        stats["widths"] = widths
        if width_ladder:
            stats["merge_widths"] = [resp_w if mw is None else mw
                                     for mw in merge_widths]
    return full, extras, order


@partial(jax.jit, static_argnames=("cfg",))
def traced_lookup_step(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
                       trace: LookupTrace, rnd: jax.Array):
    return step_impl(swarm.ids, swarm.alive, _local_respond(swarm, cfg),
                     cfg, st, trace=trace, rnd=rnd)


@partial(jax.jit, static_argnames=("cfg", "done_base", "merge_w"),
         donate_argnums=(2,))
def _traced_lookup_step_d(swarm: Swarm, cfg: SwarmConfig,
                          st: LookupState, trace: LookupTrace,
                          rnd: jax.Array, done_base: int = 0,
                          merge_w: int | None = None):
    """Donated-carry :func:`traced_lookup_step` for the compacted burst
    loop; ``done_base`` folds the ladder-hidden finished rows into the
    done gauge (one static value per ladder width).  The trace is NOT
    donated: it is [max_steps]-tiny, and ``empty_lookup_trace`` aliases
    one zeros buffer across its fields (double-donation).  ``merge_w``
    is the merge width rung (guarded, bit-identical — the traced gate
    leg must ride the same ladder as the plain engine or the recorded
    rate would not)."""
    return step_impl(swarm.ids, swarm.alive, _local_respond(swarm, cfg),
                     cfg, st, trace=trace, rnd=rnd, done_base=done_base,
                     merge_w=merge_w)


def traced_lookup(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
                  key: jax.Array, compact: bool = True,
                  stats: dict | None = None,
                  track_lifecycle: bool = False
                  ) -> tuple[LookupResult, LookupTrace]:
    """:func:`lookup` with the flight recorder on: identical semantics
    and seeds (same origins, same solicitation schedule — the trace
    scatters are pure observers), returning ``(result, LookupTrace)``.

    The trace rides the burst-loop carry, so capture adds ZERO extra
    host syncs — the only readbacks are the burst loop's existing
    done-checks; the trace itself stays on device until the caller
    materializes it (:func:`trace_to_dict`, one ``device_get``).
    Compaction (default, like :func:`lookup`) leaves the trace
    untouched too: hidden rows fold into the done gauge via
    ``done_base``, and a compacted traced run records the same counters
    as an uncompacted one (asserted in ``tests/test_compaction.py``).
    """
    l = targets.shape[0]
    timing = bool(stats) and stats.get("time_phases")
    t0 = time.perf_counter() if timing else 0.0
    origins = _sample_origins(key, swarm.alive, l)
    st = lookup_init(swarm, cfg, targets, origins)
    if track_lifecycle:
        st = init_lifecycle(st)
    trace = empty_lookup_trace(cfg)
    if timing:
        jax.block_until_ready(st)
        t1 = time.perf_counter()
        stats["init_s"] = t1 - t0
    if not compact:
        st, trace = run_burst_loop(
            lambda c, r: traced_lookup_step(swarm, cfg, c[0], c[1],
                                            dev_i32(r)),
            (st, trace), cfg, done_of=lambda c: c[0].done)
        if track_lifecycle and stats is not None:
            stats["admitted_round"] = st.admitted_round
            stats["completed_round"] = st.completed_round
        return (LookupResult(found=_finalize(swarm.ids, st, cfg),
                             hops=st.hops, done=st.done), trace)

    def step(s, ex, r, hidden, mw):
        s, tr = _traced_lookup_step_d(swarm, cfg, s, ex[0],
                                      dev_i32(r), hidden, merge_w=mw)
        return s, (tr,)

    st, (trace,), order = run_compacted_burst_loop(
        step, st, cfg, extras=(trace,), stats=stats,
        width_ladder=resolve_merge_impl(cfg) == "xla")
    if track_lifecycle and stats is not None:
        stats["admitted_round"] = _scatter_rows(st.admitted_round, order)
        stats["completed_round"] = _scatter_rows(st.completed_round,
                                                 order)
    if timing:
        jax.block_until_ready(st)
        t2 = time.perf_counter()
        stats["loop_s"] = t2 - t1
    found, hops, done = _finalize_scattered(swarm.ids, st, order, cfg)
    if timing:
        jax.block_until_ready((found, hops, done))
        t3 = time.perf_counter()
        stats["finalize_s"] = t3 - t2
        stats["phase_total_s"] = t3 - t0
    return (LookupResult(found=found, hops=hops, done=done), trace)


@partial(jax.jit, static_argnames=("cfg", "n_steps"))
def lookup_steps(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
                 n_steps: int) -> LookupState:
    """Run a fixed number of lock-step rounds (no early exit)."""
    return jax.lax.fori_loop(
        0, n_steps, lambda _, s: lookup_step(swarm, cfg, s), st)


@partial(jax.jit, static_argnames=("cfg",))
def _finalize(ids: jax.Array, st: LookupState,
              cfg: SwarmConfig) -> jax.Array:
    """Exact-order result extraction, once per lookup.

    The hot loop orders the shortlist by the 32-bit surrogate; here the
    shortlist HEAD is re-sorted by the full 160-bit distance (one small
    gather + one [L,F] sort), so the reported top-``quorum`` is exactly
    XOR-ordered regardless of surrogate ties.  Only ``F = quorum + 2``
    head entries join the exact sort: a true top-``quorum`` member can
    sit below surrogate rank F only after ≥2 surrogate-order inversions
    against it, and a d0 inversion between distinct candidates needs a
    ≥16-significant-bit tie (≤2⁻¹⁷ per pair — see
    ``merge_shortlists_d0``); the two-slot margin covers the ~per-mille
    single-inversion cases while cutting the dominant per-row id gather
    from S=14 to 10 rows per lookup (measured ~90 ms per 1M lookups at
    10M nodes).
    """
    n = ids.shape[0]
    f = min(cfg.search_width, cfg.quorum + 2)
    idx, queried = st.idx[:, :f], st.queried[:, :f]
    cand = ids[jnp.clip(idx, 0, n - 1)]                     # [L,F,5]
    d = jnp.bitwise_xor(cand, st.targets[:, None, :])
    d = jnp.where((idx < 0)[..., None], jnp.uint32(UINT32_MAX), d)
    keys = tuple(d[..., i] for i in range(N_LIMBS))
    out = jax.lax.sort(keys + (idx, queried), dimension=1,
                       num_keys=N_LIMBS)
    f_idx, f_q = out[N_LIMBS], out[N_LIMBS + 1]
    return jnp.where(f_q[:, :cfg.quorum], f_idx[:, :cfg.quorum], -1)


@partial(jax.jit, static_argnames=("cfg", "k"))
def true_closest(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
                 k: int = 8) -> jax.Array:
    """Exact alive k-closest (ground truth for recall measurement)."""
    return closest_nodes_batched(swarm.ids, targets, k,
                                 valid=swarm.alive)


def lookup_recall(swarm: Swarm, cfg: SwarmConfig, result: LookupResult,
                  targets: jax.Array, k: int = 8,
                  valid: jax.Array | None = None) -> jax.Array:
    """Fraction of the true k closest alive nodes found, per lookup.

    ``valid`` overrides the ground-truth membership mask (default
    ``swarm.alive``): adversarial scenarios pass ``alive & ~byzantine``
    so recall measures finding the true HONEST closest — convicted
    liars are excluded by design, like host-blacklisted peers.
    """
    sw = swarm if valid is None else swarm._replace(alive=valid)
    truth = true_closest(sw, cfg, targets, k)                   # [L,k]
    found = result.found                                        # [L,q]
    match = (truth[:, :, None] == found[:, None, :]) & (
        truth[:, :, None] >= 0)
    return jnp.any(match, axis=2).mean(axis=1)


@partial(jax.jit, static_argnames=("max_steps",))
def hop_histogram(hops: jax.Array, max_steps: int) -> jax.Array:
    """``[max_steps + 1] int32`` histogram of per-lookup solicitation
    rounds: bin ``r`` counts lookups that converged in exactly ``r``
    rounds, the last bin absorbing ``>= max_steps`` (non-converged).
    One scatter-add — the device-side form of the hop-count
    distributions that arXiv 1307.7000/1408.3079 use as the lookup-
    health diagnostic; sums to the lookup count by construction."""
    h = jnp.clip(hops, 0, max_steps).astype(jnp.int32)
    return jnp.zeros((max_steps + 1,), jnp.int32).at[h].add(1)


def honest_recall(swarm: Swarm, cfg: SwarmConfig, result: LookupResult,
                  targets: jax.Array, k: int = 8) -> jax.Array:
    """:func:`lookup_recall` against the honest alive ground truth
    (``alive & ~byzantine``) — the survival metric of the adversarial
    bench and tests."""
    valid = (swarm.alive if swarm.byzantine is None
             else swarm.alive & ~swarm.byzantine)
    return lookup_recall(swarm, cfg, result, targets, k, valid=valid)


# ---------------------------------------------------------------------------
# adversarial lookups: Byzantine faults + device strike/blacklist state
# ---------------------------------------------------------------------------

class LookupFaults(NamedTuple):
    """Static fault + defense model for the adversarial lookup path
    (Python scalars — part of the jit cache key, like ``SwarmConfig``).

    PR 1 gave the *storage* path its chaos knobs (``drop_exchanges``,
    mid-republish kills); this is the lookup twin plus the layer
    neither path had: nodes that answer *wrongly* rather than not at
    all — the S/Kademlia adversarial-responder model (Baumgart & Mies
    2007; see PAPERS.md), which is what lookup correctness must
    actually be proved against.

    * ``drop_frac`` — fraction of solicitation replies lost in transit
      (counter-hash Bernoulli per (node, target, round)); the origin
      keeps the entry unqueried and re-solicits next round, the
      lock-step analogue of the reference's 1 s retransmit
      (request.h:113) and the symmetric twin of the storage path's
      ``drop_exchanges``.
    * ``eclipse`` — poison shape of Byzantine responders
      (``Swarm.byzantine``): False = random node ids advertised at
      near-zero claimed distance (shortlist flooding); True =
      COLLUDER PROMOTION — every poisoned slot names a fellow
      Byzantine node claimed near zero, so a captured frontier keeps
      soliciting (and being fed by) the attacker set.
    * ``seed`` — the stateless fault stream (runs are reproducible per
      seed; no key threads through the lock-step loop).
    * ``strike_limit`` — strikes before device blacklist, the twin of
      the reference's 3-attempt expiry (request.h:113) feeding
      ``blacklist_node`` (net/network_engine.py).
    * ``defend`` — False disables verification/conviction entirely and
      measures the UNDEFENDED damage (the bench's reference rows).
    """
    drop_frac: float = 0.0
    eclipse: bool = False
    seed: int = 0
    strike_limit: int = 3
    defend: bool = True


def _fault_hash(x: jax.Array, y: jax.Array, rnd: jax.Array,
                seed: int) -> jax.Array:
    """Stateless per-exchange uint32 hash (murmur-style finalizer) —
    the chaos path's counter-based RNG.  Deterministic per
    (x, y, round, seed), so fault schedules replay exactly without
    threading PRNG keys through the lock-step loop state."""
    h = (x.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
         ^ y.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)
         ^ (rnd.astype(jnp.uint32) + jnp.uint32(seed & 0xFFFFFFFF))
         * jnp.uint32(0xC2B2AE35))
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> jnp.uint32(15))
    h = h * jnp.uint32(0x846CA68B)
    return h ^ (h >> jnp.uint32(16))


def byz_colluder_pool(byzantine: jax.Array):
    """Precompute the eclipse poison's colluder index pool: a stable
    argsort compacts the mask's True indices to the front, and the
    count clamps to ≥1 (unused when no responder is Byzantine).  The
    pool is constant for a whole chaos run — callers compute it ONCE
    and pass it to every round, keeping the [N] sort off the per-round
    path."""
    pool = jnp.argsort(~byzantine).astype(jnp.int32)
    n_byz = jnp.maximum(jnp.sum(byzantine.astype(jnp.int32)),
                        1).astype(jnp.uint32)
    return pool, n_byz


def chaos_step_impl(ids: jax.Array, alive: jax.Array,
                    byzantine: jax.Array | None, respond,
                    cfg: SwarmConfig, faults: LookupFaults,
                    st: LookupState, strikes: jax.Array,
                    rnd: jax.Array, allreduce=None, byz_aux=None,
                    trace: LookupTrace | None = None,
                    done_base: int = 0):
    """One adversarial lock-step round: :func:`step_impl` plus the
    Byzantine fault model and the strike/blacklist defense.

    Fault injection per round:
    * Byzantine responders (``byzantine`` mask) answer with POISONED
      windows — every candidate slot replaced per ``faults.eclipse``
      with a random node id claimed at a near-zero distance, or a
      fellow-attacker id claimed near zero (colluders capturing the
      shortlist head and, once solicited, feeding back more
      colluders);
    * a ``faults.drop_frac`` Bernoulli of replies is lost in transit
      (entry stays unqueried, re-solicited next round).

    Why the attack has power here at all: the reference ships full
    node IDs and the receiver computes distances itself, so a liar is
    limited to advertising useless-or-fake ids that later time out.
    The aug-table engine ships 16-bit distance *claims* for speed
    (:func:`_window_d0`), so a Byzantine responder can also lie about
    placement — strictly nastier.

    Defense (``faults.defend``), the device twin of the host engine's
    request-lifecycle robustness (net/network_engine.py
    ``_request_step``/``blacklist_node``):
    * every incoming candidate's CLAIMED distance is verified against
      the exact first limb before it may merge (one ``[L, α·2K]``
      limb-0 gather per round — the price of not trusting windows;
      honest reconstructions are exact through at least the top 16
      bits, so a top-16 mismatch is PROOF of a poisoned reply).
      Contradicted candidates never enter the shortlist and the
      responder whose reply carried them takes a strike per poisoned
      exchange;
    * replies the fault model lost take a strike on the silent node
      (the origin counts it like the reference's unanswered attempt —
      capacity drops of the sharded transport do NOT strike: the
      origin itself shed those sends and retries them knowingly);
    * a clean answer RESETS the responder's strikes (the reference's
      ``node.received()`` clearing expiry) — under pure loss a node
      needs ``strike_limit`` consecutive silent rounds to be
      convicted, matching the 3-attempt expiry semantics;
    * nodes at ``strikes >= strike_limit`` are blacklisted: evicted
      from every shortlist at once, never solicited again, and their
      ids rejected from incoming candidate windows — conviction is
      mesh-wide, like ``blacklist_node`` cancelling every pending
      request of a convicted node.

    One round's strike events merge order-free (a clean answer
    forgives that round's silence and resets the counter; poisoned-
    reply proof always adds), so the sharded path gets identical
    semantics from one stacked ``[3, N]`` all-reduce (``allreduce``).
    Returns ``(new_state, new_strikes)``.
    """
    n = cfg.n_nodes
    t0 = st.targets[:, 0]
    defend = faults.defend
    if defend:
        blk = strikes >= faults.strike_limit
        # Convicted nodes leave every shortlist at once (mesh-wide
        # blacklist eviction).
        conv = (st.idx >= 0) & blk[jnp.clip(st.idx, 0, n - 1)]
        st = st._replace(
            idx=jnp.where(conv, -1, st.idx),
            dist=jnp.where(conv, jnp.uint32(UINT32_MAX), st.dist),
            queried=st.queried & ~conv)

    sel, sel_d0, sel_pos = _select_alpha(st, cfg)
    sel = jnp.where(st.done[:, None], -1, sel)
    safe = jnp.clip(sel, 0, n - 1)
    valid = sel >= 0

    sel_alive = valid & alive[safe]
    solicit = jnp.where(sel_alive, sel, -1)
    resp, resp_d0, answered = respond(st.targets, solicit, sel_d0)

    a = sel.shape[1]
    k2 = resp.shape[1] // a
    if byzantine is not None:
        byz_sel = sel_alive & byzantine[safe] & answered
        byz_rep = jnp.repeat(byz_sel, k2, axis=1)         # [L, A*2K]
        slot = jnp.arange(a * k2, dtype=jnp.uint32)[None, :]
        h = _fault_hash(
            jnp.repeat(safe, k2, axis=1).astype(jnp.uint32)
            + slot * jnp.uint32(7919),
            t0[:, None], rnd, faults.seed ^ 0x517CC1B7)
        if faults.eclipse:
            # Colluder promotion: poisoned slots name OTHER Byzantine
            # nodes, so captured frontiers keep feeding the attacker
            # set.  The pool is run-constant — precomputed by the
            # caller (byz_colluder_pool) so the [N] sort stays off the
            # per-round path.
            byz_pool, n_byz = (byz_aux if byz_aux is not None
                               else byz_colluder_pool(byzantine))
            p_idx = byz_pool[(h % n_byz).astype(jnp.int32)]
        else:
            p_idx = (h % jnp.uint32(n)).astype(jnp.int32)
        # Claimed distance: near zero (top 17 bits clear) — the lie
        # that heads every shortlist it touches.
        p_d0 = _fault_hash(h, t0[:, None], rnd,
                           faults.seed ^ 0x27220A95) >> jnp.uint32(17)
        resp = jnp.where(byz_rep, p_idx, resp)
        resp_d0 = jnp.where(byz_rep, p_d0, resp_d0)

    if faults.drop_frac:
        # Only exchanges that were actually DELIVERED can lose their
        # reply in transit: ``answered`` is still the respond
        # contract's delivery mask here, so capacity-shed sends (the
        # sharded transport's bounded all_to_all) are excluded — the
        # origin shed those itself and must not strike for them.
        thresh = jnp.uint32(min(1.0, faults.drop_frac) * 4294967295.0)
        dropm = sel_alive & answered & (_fault_hash(
            safe.astype(jnp.uint32), t0[:, None], rnd,
            faults.seed) <= thresh)
        answered = answered & ~dropm
        drop_rep = jnp.repeat(dropm, k2, axis=1)
        resp = jnp.where(drop_rep, -1, resp)
        resp_d0 = jnp.where(drop_rep, jnp.uint32(UINT32_MAX), resp_d0)
    else:
        dropm = jnp.zeros_like(valid)

    if defend:
        # Verify every candidate's claim against the exact first limb
        # and reject convicted ids — poisoned entries never merge.
        c_safe = jnp.clip(resp, 0, n - 1)
        exact_d0 = ids[:, 0][c_safe] ^ t0[:, None]        # [L, A*2K]
        contradicted = (resp >= 0) & (
            (resp_d0 >> jnp.uint32(16)) != (exact_d0 >> jnp.uint32(16)))
        bad_cand = contradicted | ((resp >= 0) & blk[c_safe])
        resp = jnp.where(bad_cand, -1, resp)
        resp_d0 = jnp.where(bad_cand, jnp.uint32(UINT32_MAX), resp_d0)
        # A reply carrying any contradicted claim is a poisoned
        # exchange, attributable to its responder.
        malformed = jnp.any(contradicted.reshape(-1, a, k2), axis=2)
        poison_ct = jnp.sum(contradicted.astype(jnp.int32))
    else:
        malformed = jnp.zeros_like(valid)
        poison_ct = jnp.int32(0)

    # Shared round tail: dead solicitations evict via ~sel_alive;
    # poisoned/blacklisted response slots were invalidated above, and
    # convicted RESPONDERS leave shortlists at the next round's
    # blacklist eviction (plus the final _censor_convicted pass).
    merged = _merge_round(st, cfg, sel, sel_pos, sel_alive, answered,
                          resp, resp_d0, trace=trace, rnd=rnd,
                          done_base=done_base)
    if trace is None:
        new_st = merged
    else:
        new_st, trace = merged
        trace = trace._replace(
            poison=trace.poison.at[rnd].add(poison_ct, mode="drop"))

    # --- strike accounting (see the docstring's defense contract).
    # Undefended runs skip it entirely: strikes would drive nothing,
    # and the per-round [N] scatters (+ mesh all-reduces) are pure
    # waste there.
    if not defend:
        return ((new_st, strikes) if trace is None
                else (new_st, strikes, trace))
    succ = sel_alive & answered & ~malformed
    oob = jnp.int32(n)
    succ_ct = jnp.zeros((n,), jnp.int32).at[
        jnp.where(succ, sel, oob)].add(1, mode="drop")
    drop_ct = jnp.zeros((n,), jnp.int32).at[
        jnp.where(dropm, sel, oob)].add(1, mode="drop")
    lie_ct = jnp.zeros((n,), jnp.int32).at[
        jnp.where(malformed, sel, oob)].add(1, mode="drop")
    if allreduce is not None:
        cts = allreduce(jnp.stack([succ_ct, drop_ct, lie_ct]))
        succ_ct, drop_ct, lie_ct = cts[0], cts[1], cts[2]
    # Silence is circumstantial: a round with ANY clean answer proves
    # liveness and forgives that round's drops along with the old
    # count, and an all-silent round counts as ONE strike no matter
    # how many lookups went unanswered (a node dark for one round must
    # not be convicted outright by concurrent solicitations — strikes
    # grow only across CONSECUTIVE all-silent rounds, the 3-attempt
    # expiry semantics).  Poisoned replies are PROOF and always count
    # per exchange.  Conviction is permanent for the lifetime of the
    # batch — shorter than the host twin's 10-minute sentence; fresh
    # batches start clean.
    new_strikes = jnp.where(succ_ct > 0, 0,
                            strikes + jnp.minimum(drop_ct, 1)) + lie_ct
    if trace is None:
        return new_st, new_strikes
    # Strike/conviction telemetry is computed AFTER the (possibly
    # psum-reduced) strike merge, so the numbers are replicated across
    # shards — the sharded reducer takes pmax of these rows, not psum.
    trace = trace._replace(
        strikes=trace.strikes.at[rnd].add(
            jnp.sum(jnp.maximum(new_strikes - strikes, 0)),
            mode="drop"),
        convictions=trace.convictions.at[rnd].set(
            jnp.sum((new_strikes >= faults.strike_limit
                     ).astype(jnp.int32)), mode="drop"))
    return new_st, new_strikes, trace


@partial(jax.jit, static_argnames=("cfg",))
def chaos_lookup_init(swarm: Swarm, cfg: SwarmConfig,
                      targets: jax.Array,
                      origins: jax.Array) -> LookupState:
    # The seed exchange consults the origin's OWN routing table (the
    # reference's search creation, src/dht.cpp:1672-1735): trusted, so
    # no fault injection — matching the storage path's uncapped init.
    return init_impl(swarm.ids, _local_respond(swarm, cfg), cfg,
                     targets, origins)


@partial(jax.jit, static_argnames=("cfg", "faults"))
def chaos_lookup_step(swarm: Swarm, cfg: SwarmConfig,
                      faults: LookupFaults, st: LookupState,
                      strikes: jax.Array, rnd: jax.Array,
                      byz_aux=None, trace: LookupTrace | None = None):
    return chaos_step_impl(swarm.ids, swarm.alive, swarm.byzantine,
                           _local_respond(swarm, cfg), cfg, faults,
                           st, strikes, rnd, byz_aux=byz_aux,
                           trace=trace)


@partial(jax.jit, static_argnames=("cfg", "faults", "done_base"),
         donate_argnums=(3,))
def _chaos_step_d(swarm: Swarm, cfg: SwarmConfig, faults: LookupFaults,
                  st: LookupState, strikes: jax.Array, rnd: jax.Array,
                  byz_aux=None, trace: LookupTrace | None = None,
                  done_base: int = 0):
    """Donated-carry :func:`chaos_lookup_step` for the compacted burst
    loop.  Only the [L,S] state is donated: ``byz_aux`` is
    run-constant, the trace is [max_steps]-tiny, and the [N] strike
    vector must SURVIVE its step — the loop keeps the previous round's
    strikes alive for the deferred blacklist-eviction pass."""
    return chaos_step_impl(swarm.ids, swarm.alive, swarm.byzantine,
                           _local_respond(swarm, cfg), cfg, faults,
                           st, strikes, rnd, byz_aux=byz_aux,
                           trace=trace, done_base=done_base)


def chaos_lookup(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
                 key: jax.Array,
                 faults: LookupFaults = LookupFaults(),
                 collect_trace: bool = False, compact: bool = True,
                 stats: dict | None = None,
                 track_lifecycle: bool = False):
    """Run a batch of lookups to completion UNDER the adversarial
    fault model (Byzantine responders + exchange loss) with the
    strike/blacklist defense — the lookup-path twin of the storage
    chaos harness.

    Same host-driven burst loop as :func:`lookup` (the round counter
    doubles as the stateless fault stream's round coordinate); origins
    are drawn from honest alive nodes (the issuing node itself is not
    the attacker).  Returns ``(LookupResult, strikes [N] int32)`` —
    ``strikes >= faults.strike_limit`` is the conviction mask, which
    benches report as true/false-conviction rates against
    ``swarm.byzantine``.  ``collect_trace=True`` turns the flight
    recorder on and returns ``(result, strikes, LookupTrace)`` —
    capture rides the loop carry, adding no host syncs.

    Compaction (default, like :func:`lookup`) is bit-identical here
    too: every fault-model decision keys on (node id, target, round) —
    never on a row's batch position — and strike state scatters into
    the [N] axis, so a stable repack changes nothing the adversary or
    the defense can observe (asserted incl. a churn+byzantine case in
    ``tests/test_compaction.py``).
    """
    l = targets.shape[0]
    honest_alive = (swarm.alive if swarm.byzantine is None
                    else swarm.alive & ~swarm.byzantine)
    origins = _sample_origins(key, honest_alive, l)
    st = chaos_lookup_init(swarm, cfg, targets, origins)
    if track_lifecycle:
        # The chaos steps always carry their round index (the fault
        # stream's coordinate), so lifecycle needs no extra plumbing.
        st = init_lifecycle(st)
    strikes = _zeros_i32(cfg.n_nodes)
    byz_aux = (byz_colluder_pool(swarm.byzantine)
               if faults.eclipse and swarm.byzantine is not None
               else None)
    trace0 = empty_lookup_trace(cfg) if collect_trace else None
    if compact:
        # The strike vector as of the LAST round's start: its blacklist
        # is what the full-width engine last scrubbed every shortlist
        # with (last-round convictions reach results only through
        # _censor_convicted, there as here).
        prev = {"strikes": strikes}

        def step(s, ex, r, hidden, mw):
            # The chaos engine keeps full-width merges (mw unused): it
            # is a fault harness, not the perf-gate path, and its
            # defense planes dominate the round anyway.
            prev["strikes"] = ex[0]
            out = _chaos_step_d(swarm, cfg, faults, s, ex[0],
                                dev_i32(r), byz_aux,
                                trace=(ex[1] if collect_trace else None),
                                done_base=hidden)
            return out[0], tuple(out[1:])

        extras = (strikes, trace0) if collect_trace else (strikes,)
        st, extras, order = run_compacted_burst_loop(
            step, st, cfg, extras=extras, stats=stats)
        if track_lifecycle and stats is not None:
            stats["admitted_round"] = _scatter_rows(st.admitted_round,
                                                    order)
            stats["completed_round"] = _scatter_rows(st.completed_round,
                                                     order)
        strikes = extras[0]
        if collect_trace:
            trace = extras[1]
        if faults.defend:
            # Frozen done rows missed the per-round blacklist scrubs —
            # apply them in one deferred pass (see _evict_blacklisted).
            # The limit compare runs jitted: an eager `>= python-int`
            # uploads the scalar every call (strict-transfer hygiene).
            st = _evict_blacklisted(
                st, _ge_limit(prev["strikes"], faults.strike_limit),
                cfg)
        found, hops, done = _finalize_scattered(swarm.ids, st, order,
                                                cfg)
        found = _censor_convicted(found, strikes, cfg, faults)
        res = LookupResult(found=found, hops=hops, done=done)
        return (res, strikes, trace) if collect_trace else (res, strikes)
    if collect_trace:
        st, strikes, trace = run_burst_loop(
            lambda c, r: chaos_lookup_step(swarm, cfg, faults, c[0],
                                           c[1], dev_i32(r), byz_aux,
                                           trace=c[2]),
            (st, strikes, trace0), cfg,
            done_of=lambda c: c[0].done)
    else:
        st, strikes = run_burst_loop(
            lambda c, r: chaos_lookup_step(swarm, cfg, faults, c[0],
                                           c[1], dev_i32(r), byz_aux),
            (st, strikes), cfg, done_of=lambda c: c[0].done)
    if track_lifecycle and stats is not None:
        stats["admitted_round"] = st.admitted_round
        stats["completed_round"] = st.completed_round
    found = _finalize(swarm.ids, st, cfg)
    found = _censor_convicted(found, strikes, cfg, faults)
    res = LookupResult(found=found, hops=st.hops, done=st.done)
    return (res, strikes, trace) if collect_trace else (res, strikes)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def _evict_blacklisted(st: LookupState, blk: jax.Array,
                       cfg: SwarmConfig) -> LookupState:
    """One deferred blacklist-eviction + re-sort pass over every row.

    The full-width chaos engine scrubs convicted nodes from EVERY
    shortlist at each round start — including rows that are already
    done — and the follow-up merge promotes the next-best survivors
    into the vacated head slots.  The compaction ladder freezes
    finished rows outside the dispatch prefix, so they miss those
    per-round scrubs; this single pass applied before ``_finalize``
    reproduces them exactly: convictions are permanent (the union of
    per-round blacklists is the final pre-last-round blacklist) and
    the merge is order-deterministic on the surviving set, so evicting
    once with the union and re-sorting once lands bit-identical state.
    For rows that were dispatched through the last round it is a
    no-op: their shortlists were scrubbed with this same blacklist at
    the last round's start, and incoming candidates are blk-rejected.
    """
    n = cfg.n_nodes
    conv = (st.idx >= 0) & blk[jnp.clip(st.idx, 0, n - 1)]
    idx = jnp.where(conv, -1, st.idx)
    dist = jnp.where(conv, jnp.uint32(UINT32_MAX), st.dist)
    f_idx, f_dist, f_q = merge_shortlists_d0(
        dist, idx, st.queried & ~conv, keep=cfg.search_width)
    return st._replace(idx=f_idx, dist=f_dist, queried=f_q)


@partial(jax.jit, static_argnames=("cfg", "faults"))
def _censor_convicted(found: jax.Array, strikes: jax.Array,
                      cfg: SwarmConfig,
                      faults: LookupFaults) -> jax.Array:
    """Drop convicted nodes from reported results.  Blacklist eviction
    runs at the START of each round, so a conviction landing in the
    LAST executed round would otherwise survive in a done lookup's
    head — the one gap in mesh-wide eviction.  Jitted so the limit /
    sentinel scalars fold as program constants (strict-transfer
    hygiene)."""
    if not faults.defend:
        return found
    blk = strikes >= faults.strike_limit
    hole = (found >= 0) & blk[jnp.clip(found, 0, cfg.n_nodes - 1)]
    return jnp.where(hole, -1, found)
