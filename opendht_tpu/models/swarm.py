"""SimSwarm — the TPU-resident Kademlia swarm engine.

The flagship "model" of this framework: an entire DHT swarm of N
simulated nodes held on-device as packed tensors, with all iterative
lookups advanced in lock-step.  This replaces the reference's one-node-
at-a-time event loop (``Dht::searchStep`` src/dht.cpp:1343-1464 driving
``NetworkEngine`` RPCs over UDP) with batched tensor exchanges:

* **node matrix** — ``ids [N,5] uint32`` sorted lexicographically (=
  160-bit numeric order), so every dyadic prefix range (a Kademlia
  bucket's key-space) is a contiguous slice, found by binary search;
* **routing tables** — ``tables [N,B,K] int32``: for node ``i`` bucket
  ``b`` holds K members sharing *exactly* ``b`` prefix bits with ``i``
  (the reference's ``Bucket`` of ≤8 nodes, routing_table.h:26,
  ``TARGET_NODES``), sampled uniformly from the bucket's sorted range —
  the steady-state of the reference's bucket maintenance
  (src/dht.cpp:2826-2885) without simulating each ping;
* **lookups** — a ``[L]``-batch of iterative searches in lock-step;
  each step solicits the α=4 best unqueried shortlist nodes
  (``MAX_REQUESTED_SEARCH_NODES`` dht.h:327), gathers their bucket
  ``c = commonBits(node, target)`` rows (the nodes they would return
  from ``onFindNode``, src/dht.cpp:3189-3200), and merges via the exact
  160-bit sort (``Search::insertNode`` src/dht.cpp:961-1047); a lookup
  is done when its 8 closest known nodes are all queried
  (``Search::isSynced`` src/dht.cpp:1466-1479, quorum =
  ``TARGET_NODES``);
* **churn** — an ``alive [N]`` mask; dead solicited nodes return
  nothing (the α-slot waste models the reference's 3×1 s timeout,
  request.h:113) — the netem-equivalent fault injection.

Everything is static-shape, ``jit``-compiled, and sharding-friendly:
the lookup batch axis shards cleanly over a mesh (see
``opendht_tpu.parallel``).
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.xor_metric import (
    N_LIMBS,
    closest_nodes_batched,
    lex_searchsorted,
    merge_shortlists_d0,
    prefix_len32,
)

UINT32_MAX = 0xFFFFFFFF


class SwarmConfig(NamedTuple):
    """Static swarm geometry (Python ints — part of the jit cache key).

    Defaults mirror the reference's scale constants: K=8 per bucket
    (routing_table.h:26), 14-node search sets (dht.h:314), α=4
    (dht.h:327), sync quorum 8.
    """
    n_nodes: int
    n_buckets: int
    bucket_k: int = 8
    search_width: int = 14
    alpha: int = 4
    quorum: int = 8
    max_steps: int = 48
    # Augment routing tables with their members' first id limbs
    # ([N,B,K] uint32 alongside the index table).  TPU random gathers
    # cost ~10 ns per *fetch* regardless of row width (measured v5e),
    # so shipping each member's distance surrogate inside the already-
    # fetched bucket row removes the dominant per-step gather (64
    # scalar fetches/lookup → 0).  Costs one extra tables-sized array —
    # for_nodes turns it off above 2M nodes where HBM gets tight.
    aug_tables: bool = True

    @classmethod
    def for_nodes(cls, n_nodes: int, **kw) -> "SwarmConfig":
        # Enough buckets that the deepest one holds ~2·K nodes.  Capped
        # at 26: bucket indices derive from first-limb prefix lengths
        # (exact to depth 32), and build_swarm's prefix histograms use
        # up to 2^depth bins — 26 covers ~2^29 nodes, far past what a
        # chip holds.
        b = min(26, max(4, int(math.ceil(math.log2(max(16, n_nodes)))) - 3))
        kw.setdefault("aug_tables", n_nodes <= 2_000_000)
        return cls(n_nodes=n_nodes, n_buckets=b, **kw)


class Swarm(NamedTuple):
    """Device-resident swarm state (a pytree of arrays).

    ``tables`` layout depends on ``SwarmConfig.aug_tables``:

    * augmented (default): ``[N,B,2K] int32`` — per bucket row, the K
      member indices followed by the K members' first id limbs
      (uint32, bitcast to int32).  One fetch brings a candidate list
      *and* its distance surrogates — see SwarmConfig.aug_tables.
    * plain: ``[N,B,K] int32`` member indices only (-1 = empty).
    """
    ids: jax.Array     # [N,5] uint32, lexicographically sorted
    tables: jax.Array  # [N,B,K or 2K] int32 — see class docstring
    alive: jax.Array   # [N] bool


class LookupState(NamedTuple):
    """Lock-step batched lookup state (all ``[L, ...]``).

    The shortlist carries only the first 32 bits of the XOR distance
    (``dist = limb0(id ^ target)``): that surrogate decides the
    per-round merge order (exact up to ~2^-33 d0 collisions per merge
    — see :func:`opendht_tpu.ops.xor_metric.merge_shortlists_d0`),
    while the final result is re-sorted by the exact 160-bit distance
    once per lookup (:func:`_finalize`).  Keeping the hot-loop state
    free of ``[..., 5]``-minor arrays is what lets every per-round op
    tile fully onto TPU lanes.
    """
    targets: jax.Array  # [L,5]
    idx: jax.Array      # [L,S] shortlist node indices, sorted by dist
    dist: jax.Array     # [L,S] uint32 first-limb xor distance (~0=empty)
    queried: jax.Array  # [L,S] bool
    done: jax.Array     # [L] bool
    hops: jax.Array     # [L] int32 — solicitation rounds until sync


class LookupResult(NamedTuple):
    found: jax.Array  # [L,quorum] closest queried node indices (-1 pad)
    hops: jax.Array   # [L]
    done: jax.Array   # [L]


# ---------------------------------------------------------------------------
# bit helpers on packed ids (work with traced bit positions)
# ---------------------------------------------------------------------------

def _prefix_mask(nbits: jax.Array) -> jax.Array:
    """``[5]`` uint32 mask keeping the first ``nbits`` bits of an id."""
    limbs = []
    for j in range(N_LIMBS):
        rem = jnp.clip(nbits - 32 * j, 0, 32)
        shift = jnp.clip(32 - rem, 0, 31).astype(jnp.uint32)
        m = (jnp.uint32(UINT32_MAX) << shift) & jnp.uint32(UINT32_MAX)
        limbs.append(jnp.where(rem == 0, jnp.uint32(0), m))
    return jnp.stack(limbs, axis=-1)


def _bit_mask(bit: jax.Array) -> jax.Array:
    """``[5]`` uint32 with only ``bit`` (0 = MSB of limb 0) set."""
    limbs = []
    for j in range(N_LIMBS):
        off = bit - 32 * j
        in_limb = (off >= 0) & (off < 32)
        pos = jnp.clip(31 - off, 0, 31).astype(jnp.uint32)
        limbs.append(jnp.where(in_limb, jnp.uint32(1) << pos, jnp.uint32(0)))
    return jnp.stack(limbs, axis=-1)


def bucket_range(sorted_ids: jax.Array, node_ids: jax.Array,
                 b: jax.Array, inclusive=False):
    """Sorted-range ``[lo, hi)`` of a node's bucket-``b`` key-space.

    Exclusive (normal) bucket: ids sharing *exactly* ``b`` prefix bits
    — "first b bits equal, bit b flipped", a dyadic interval, hence
    contiguous in the sorted matrix.  Inclusive (deepest) bucket: ids
    sharing *at least* ``b`` bits — the reference's unsplit own-bucket
    tail that holds a node's nearest neighbours
    (``RoutingTable::split``/``depth``, src/routing_table.cpp:139-163).
    """
    pm1 = _prefix_mask(b + 1)
    pmb = _prefix_mask(b)
    bm = _bit_mask(b)
    # Keep the node's first b+1 bits, then flip bit b: the bucket's
    # key-space prefix.
    lo_ex = (node_ids & pm1) ^ bm
    hi_ex = lo_ex | (~pm1 & jnp.uint32(UINT32_MAX))
    lo_in = node_ids & pmb
    hi_in = lo_in | (~pmb & jnp.uint32(UINT32_MAX))
    inc = jnp.asarray(inclusive)
    lo_key = jnp.where(inc, lo_in, lo_ex)
    hi_key = jnp.where(inc, hi_in, hi_ex)
    lo = lex_searchsorted(sorted_ids, lo_key, side="left")
    hi = lex_searchsorted(sorted_ids, hi_key, side="right")
    return lo, hi


# ---------------------------------------------------------------------------
# swarm construction
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("cfg",))
def build_swarm(key: jax.Array, cfg: SwarmConfig) -> Swarm:
    """Generate a random swarm with steady-state routing tables.

    O(N·B·log N): per (node, bucket), one binary search for the bucket's
    sorted range, then K stratified-uniform samples from it.
    """
    n, b_total, k = cfg.n_nodes, cfg.n_buckets, cfg.bucket_k
    k_ids, k_samp = jax.random.split(key)
    raw = jax.random.bits(k_ids, (n, N_LIMBS), jnp.uint32)
    limbs = tuple(raw[:, i] for i in range(N_LIMBS))
    sorted_limbs = jax.lax.sort(limbs, num_keys=N_LIMBS)
    ids = jnp.stack(sorted_limbs, axis=-1)

    # Bucket ranges via prefix histograms, not binary search: in the
    # sorted id matrix every bucket's key-space is a dyadic interval
    # determined by the first d ≤ 32 bits (d = bucket depth + 1), so
    # its [lo, hi) is a pair of adjacent prefix-histogram cumsums —
    # O(N) per bucket with one small gather, where per-node binary
    # search was O(N log N) random gathers (and its unrolled HLO
    # crashed the compiler at 10M nodes).
    assert b_total <= 26, "prefix histogram capped at 2^26 bins"
    ids0 = ids[:, 0]
    width = 2 * k if cfg.aug_tables else k
    tables = jnp.full((n, b_total, width), -1, jnp.int32)
    for b in range(b_total):
        inclusive = b == b_total - 1
        d = b if inclusive else b + 1   # prefix depth of the interval
        pref = (ids0 >> jnp.uint32(32 - d)).astype(jnp.int32) \
            if d else jnp.zeros((n,), jnp.int32)
        counts = jnp.zeros((1 << d,), jnp.int32).at[pref].add(1)
        bounds = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)])
        p = pref if inclusive else pref ^ 1   # own vs sibling interval
        lo, hi = bounds[p], bounds[p + 1]
        size = (hi - lo).astype(jnp.float32)
        # Stratified samples across the range: bucket membership is
        # uniform-random in the reference's steady state too.
        u = jax.random.uniform(jax.random.fold_in(k_samp, b), (n, k))
        strat = (jnp.arange(k, dtype=jnp.float32)[None, :] + u) / k
        samp = lo[:, None] + jnp.floor(
            strat * size[:, None]).astype(jnp.int32)
        samp = jnp.clip(samp, lo[:, None], hi[:, None] - 1)
        samp = jnp.where((hi > lo)[:, None], samp, -1)   # [N,K]
        if cfg.aug_tables:
            # Fused row [idx K | member-limb K], filled per bucket so
            # the peak stays at tables + one [N,2K] slice (a whole-
            # table concat would transiently triple the footprint).
            m0 = jax.lax.bitcast_convert_type(
                ids0[jnp.clip(samp, 0, n - 1)], jnp.int32)
            samp = jnp.concatenate([samp, m0], axis=-1)  # [N,2K]
        tables = tables.at[:, b, :].set(samp)
    return Swarm(ids=ids, tables=tables, alive=jnp.ones((n,), bool))


@partial(jax.jit, static_argnames=("cfg",))
def churn(swarm: Swarm, key: jax.Array, kill_frac: float,
          cfg: SwarmConfig) -> Swarm:
    """Kill a uniform fraction of nodes (netem-equivalent fault mask).

    Dead nodes stop answering; routing-table entries pointing at them
    become wasted α-slots, exactly like the reference's expired nodes
    awaiting eviction (src/node.cpp:34-40).
    """
    keep = jax.random.uniform(key, (cfg.n_nodes,)) >= kill_frac
    return swarm._replace(alive=swarm.alive & keep)


# ---------------------------------------------------------------------------
# lookups
# ---------------------------------------------------------------------------

def _respond(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
             nid: jax.Array, nid_d0: jax.Array):
    """What each solicited node returns for each target.

    ``targets``: ``[L,5]``; ``nid``: ``[L,A]`` node indices (-1 =
    none); ``nid_d0``: ``[L,A]`` the solicited nodes' first-limb XOR
    distance to the target — already in the caller's shortlist state,
    so the bucket index ``c = clz(d0)`` (= ``commonBits(self,
    target)``, exact for n_buckets ≤ 32) costs no gather at all.

    Returns ``(resp [L,A*2K], resp_d0 [L,A*2K], answered [L,A])``:
    candidate indices and their first-limb distances — the solicited
    node's bucket ``c`` (every member strictly closer to the target
    than the node itself) plus bucket ``c+1``, the node's best
    approximation of "the 8 closest I know" (``Dht::onFindNode``
    src/dht.cpp:3189-3200).  With augmented tables the distances ride
    inside the bucket-row fetches (members' first limbs XOR the
    target); otherwise they come from a per-candidate id gather — the
    slow path, kept for swarms too big to afford the aug table.  Dead
    or empty slots return -1 / all-ones.  ``answered`` is the delivery
    mask: the local engine always delivers to live targets; the
    sharded transport may drop over-capacity queries (they retry next
    round).
    """
    n, b_total, k = cfg.n_nodes, cfg.n_buckets, cfg.bucket_k
    l = targets.shape[0]
    safe = jnp.clip(nid, 0, n - 1)
    c = prefix_len32(nid_d0)                                    # [L,A]
    ok = (nid >= 0) & swarm.alive[safe]
    if swarm.tables.shape[-1] == 2 * k:                     # augmented
        # One fetch per solicited node: buckets c and c+1 are adjacent
        # rows, so gather a [2, 2K] slice starting at min(c, B-2) —
        # random-gather cost is per fetch, not per byte.  (At the
        # deepest bucket this returns rows B-2 and B-1 where the
        # per-row form returned B-1 twice; a candidate superset, same
        # semantics.)  Plain tables stay on per-row gathers: on
        # multi-GB tables XLA has been seen satisfying this gather's
        # layout with a full padded transposed copy of the operand.
        c0 = jnp.clip(c, 0, b_total - 2)
        rows = _gather_rows2(swarm.tables, safe, c0)     # [L,A,2,2K]
        rows0, rows1 = rows[..., 0, :], rows[..., 1, :]
        resp = jnp.concatenate([rows0[..., :k], rows1[..., :k]],
                               axis=-1)
        resp = jnp.where(ok[..., None], resp, -1).reshape(l, -1)
        m0 = jax.lax.bitcast_convert_type(
            jnp.concatenate([rows0[..., k:], rows1[..., k:]], axis=-1),
            jnp.uint32)
        d0 = m0.reshape(l, -1) ^ targets[:, 0][:, None]
        d0 = jnp.where(resp < 0, jnp.uint32(UINT32_MAX), d0)
    else:
        c0 = jnp.clip(c, 0, b_total - 1)
        c1 = jnp.clip(c + 1, 0, b_total - 1)
        rows0 = swarm.tables[safe, c0]                      # [L,A,K]
        rows1 = swarm.tables[safe, c1]
        resp = jnp.concatenate([rows0, rows1], axis=-1)     # [L,A,2K]
        resp = jnp.where(ok[..., None], resp, -1).reshape(l, -1)
        d0 = _resp_dist(swarm.ids, cfg, targets, resp)
    return resp, d0, ok


def _gather_rows2(tables: jax.Array, node: jax.Array,
                  bucket: jax.Array) -> jax.Array:
    """Gather ``tables[node, bucket:bucket+2, :]`` → ``[..., 2, W]``.

    A single gather op with slice size 2 on the bucket axis — half the
    fetches of two per-row gathers.  ``bucket`` must be ≤ B-2.
    """
    b_total, w = tables.shape[1], tables.shape[2]
    idx = jnp.stack([node, bucket], axis=-1)          # [..., 2]
    return jax.lax.gather(
        tables, idx,
        jax.lax.GatherDimensionNumbers(
            offset_dims=(node.ndim, node.ndim + 1),
            collapsed_slice_dims=(0,),
            start_index_map=(0, 1)),
        slice_sizes=(1, 2, w),
        mode=jax.lax.GatherScatterMode.CLIP)


def _select_alpha(st: LookupState, cfg: SwarmConfig):
    """α best unqueried shortlist nodes per lookup, with their d0.

    The shortlist is already distance-sorted, so the α best unqueried
    are the first α unqueried slots; each is extracted with one masked
    reduction (at most one slot per row has rank j), which beats a
    sort for α ≪ S.  Returns ``(sel [L,A] int32, sel_d0 [L,A])`` —
    the d0 rides along so responders can derive their bucket index
    without touching the id matrix.
    """
    unq = (st.idx >= 0) & ~st.queried
    order = jnp.cumsum(unq.astype(jnp.int32), axis=1)
    sel, sel_d0 = [], []
    for j in range(cfg.alpha):
        m = unq & (order == j + 1)
        sel.append(jnp.max(jnp.where(m, st.idx, -1), axis=1))
        sel_d0.append(jnp.max(jnp.where(m, st.dist, 0), axis=1))
    return jnp.stack(sel, axis=1), jnp.stack(sel_d0, axis=1)


def _sync_done(st_idx: jax.Array, st_queried: jax.Array,
               cfg: SwarmConfig) -> jax.Array:
    """True where the ``quorum`` closest known nodes are all queried."""
    head_idx = st_idx[:, :cfg.quorum]
    head_q = st_queried[:, :cfg.quorum]
    valid = head_idx >= 0
    return jnp.all(head_q | ~valid, axis=1) & jnp.any(valid, axis=1)


def init_impl(ids: jax.Array, respond, cfg: SwarmConfig,
              targets: jax.Array, origins: jax.Array) -> LookupState:
    """Shared lock-step init: seed each lookup from its origin node's
    own routing table — the reference's search creation consulting
    local buckets (``Dht::search`` src/dht.cpp:1672-1735).

    ``respond(targets, nid, nid_d0)`` abstracts where routing tables
    live: local gathers (single chip) or the all_to_all routed
    exchange (:mod:`opendht_tpu.parallel.sharded`).
    """
    l = targets.shape[0]
    s = cfg.search_width
    o_d0 = ids[:, 0][origins] ^ targets[:, 0]         # [L]
    resp, resp_d0, _ = respond(targets, origins[:, None], o_d0[:, None])
    pad = max(0, s - resp.shape[1])
    if pad:
        resp = jnp.concatenate(
            [resp, jnp.full((l, pad), -1, jnp.int32)], axis=1)
        resp_d0 = jnp.concatenate(
            [resp_d0, jnp.full((l, pad), UINT32_MAX, jnp.uint32)], axis=1)
    f_idx, f_dist, f_q = merge_shortlists_d0(
        resp_d0, resp, jnp.zeros_like(resp, bool), keep=s)
    return LookupState(
        targets=targets, idx=f_idx, dist=f_dist, queried=f_q,
        done=jnp.zeros((l,), bool), hops=jnp.zeros((l,), jnp.int32))


def step_impl(ids: jax.Array, alive: jax.Array, respond,
              cfg: SwarmConfig, st: LookupState) -> LookupState:
    """Shared lock-step solicitation round (vectorized ``searchStep``,
    src/dht.cpp:1343-1464): select α unqueried, solicit via
    ``respond``, merge responses, re-sort, check sync quorum."""
    # Finished lookups stop soliciting: besides wasting gathers, their
    # traffic would consume bounded all_to_all capacity and could
    # starve still-active queries on a hot shard.
    sel, sel_d0 = _select_alpha(st, cfg)                        # [L,A]
    sel = jnp.where(st.done[:, None], -1, sel)
    sel_alive = (sel >= 0) & alive[jnp.clip(sel, 0, cfg.n_nodes - 1)]
    resp, resp_d0, answered = respond(st.targets, sel, sel_d0)  # [L,A*2K]
    hit = st.idx[:, :, None] == sel[:, None, :]                 # [L,S,A]
    hit = hit & (sel[:, None, :] >= 0)
    # Answered solicitations become "queried"; dead nodes are evicted
    # from the shortlist entirely — the reference expires a node after
    # 3 unanswered attempts and replaces it with the next candidate
    # (request.h:113, src/dht.cpp:1059-1074).  Alive-but-unanswered
    # (transport drop) stays unqueried and is re-solicited next round.
    queried = st.queried | jnp.any(
        hit & (sel_alive & answered)[:, None, :], axis=2)
    evict = jnp.any(hit & (~sel_alive & (sel >= 0))[:, None, :], axis=2)
    idx = jnp.where(evict, -1, st.idx)
    cand_idx = jnp.concatenate([idx, resp], axis=1)
    # Evicted frontier slots must not keep their old (now invalid)
    # distance keys.
    fr_dist = jnp.where(evict, jnp.uint32(UINT32_MAX), st.dist)
    cand_dist = jnp.concatenate([fr_dist, resp_d0], axis=1)
    cand_q = jnp.concatenate(
        [queried, jnp.zeros_like(resp, bool)], axis=1)
    f_idx, f_dist, f_q = merge_shortlists_d0(
        cand_dist, cand_idx, cand_q, keep=cfg.search_width)

    active = ~st.done & jnp.any(sel >= 0, axis=1)
    done = st.done | _sync_done(f_idx, f_q, cfg) | ~jnp.any(
        (f_idx >= 0) & ~f_q, axis=1)
    return LookupState(
        targets=st.targets,
        idx=jnp.where(st.done[:, None], st.idx, f_idx),
        dist=jnp.where(st.done[:, None], st.dist, f_dist),
        queried=jnp.where(st.done[:, None], st.queried, f_q),
        done=done,
        hops=st.hops + active.astype(jnp.int32))


def _resp_dist(ids: jax.Array, cfg: SwarmConfig, targets: jax.Array,
               cand_idx: jax.Array) -> jax.Array:
    """First-limb XOR distance for candidate indices (~0 where -1)."""
    cand_ids0 = ids[:, 0][jnp.clip(cand_idx, 0, cfg.n_nodes - 1)]
    d0 = jnp.bitwise_xor(cand_ids0, targets[:, 0][:, None])
    return jnp.where(cand_idx < 0, jnp.uint32(UINT32_MAX), d0)


def _local_respond(swarm: Swarm, cfg: SwarmConfig):
    return lambda tg, nid, nid_d0: _respond(swarm, cfg, tg, nid, nid_d0)


@partial(jax.jit, static_argnames=("l",))
def _sample_origins(key: jax.Array, alive: jax.Array,
                    l: int) -> jax.Array:
    """Uniform random *alive* origin per lookup.

    Two-draw rejection with a first-alive fallback — O(L) memory.
    (A categorical over the alive mask materializes an [L, N] gumbel
    plane when not fused: 372 GB at L=100k, N=1M.)
    """
    n = alive.shape[0]
    c1 = jax.random.randint(key, (l,), 0, n, jnp.int32)
    c2 = jax.random.randint(jax.random.fold_in(key, 1), (l,), 0, n,
                            jnp.int32)
    first_alive = jnp.argmax(alive).astype(jnp.int32)
    return jnp.where(alive[c1], c1,
                     jnp.where(alive[c2], c2, first_alive))


@partial(jax.jit, static_argnames=("cfg",))
def lookup_init(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
                origins: jax.Array) -> LookupState:
    return init_impl(swarm.ids, _local_respond(swarm, cfg), cfg,
                     targets, origins)


@partial(jax.jit, static_argnames=("cfg",))
def lookup_step(swarm: Swarm, cfg: SwarmConfig,
                st: LookupState) -> LookupState:
    return step_impl(swarm.ids, swarm.alive, _local_respond(swarm, cfg),
                     cfg, st)


@partial(jax.jit, static_argnames=("cfg",))
def lookup(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
           key: jax.Array) -> LookupResult:
    """Run a batch of iterative lookups to completion.

    ``targets``: ``[L,5]``.  Origins are random alive nodes (each
    lookup is issued "from" a random participant, like the scenario
    tests' random-node gets, python/tools/dht/tests.py:865-950).
    """
    l = targets.shape[0]
    # Origins are drawn from *alive* nodes: the issuing node exists.
    origins = _sample_origins(key, swarm.alive, l)
    st = lookup_init(swarm, cfg, targets, origins)

    def cond(st):
        return ~jnp.all(st.done) & (jnp.max(st.hops) < cfg.max_steps)

    st = jax.lax.while_loop(cond, lambda s: lookup_step(swarm, cfg, s), st)
    return LookupResult(found=_finalize(swarm.ids, st, cfg),
                        hops=st.hops, done=st.done)


@partial(jax.jit, static_argnames=("cfg", "n_steps"))
def lookup_steps(swarm: Swarm, cfg: SwarmConfig, st: LookupState,
                 n_steps: int) -> LookupState:
    """Run a fixed number of lock-step rounds (no early exit)."""
    return jax.lax.fori_loop(
        0, n_steps, lambda _, s: lookup_step(swarm, cfg, s), st)


@partial(jax.jit, static_argnames=("cfg",))
def _finalize(ids: jax.Array, st: LookupState,
              cfg: SwarmConfig) -> jax.Array:
    """Exact-order result extraction, once per lookup.

    The hot loop orders the shortlist by the 32-bit surrogate; here the
    S=14 survivors are re-sorted by the full 160-bit distance (one
    small gather + one [L,S] sort), so the reported top-``quorum`` is
    exactly XOR-ordered regardless of surrogate ties.
    """
    n = ids.shape[0]
    cand = ids[jnp.clip(st.idx, 0, n - 1)]                  # [L,S,5]
    d = jnp.bitwise_xor(cand, st.targets[:, None, :])
    d = jnp.where((st.idx < 0)[..., None], jnp.uint32(UINT32_MAX), d)
    keys = tuple(d[..., i] for i in range(N_LIMBS))
    out = jax.lax.sort(keys + (st.idx, st.queried), dimension=1,
                       num_keys=N_LIMBS)
    f_idx, f_q = out[N_LIMBS], out[N_LIMBS + 1]
    return jnp.where(f_q[:, :cfg.quorum], f_idx[:, :cfg.quorum], -1)


@partial(jax.jit, static_argnames=("cfg", "k"))
def true_closest(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
                 k: int = 8) -> jax.Array:
    """Exact alive k-closest (ground truth for recall measurement)."""
    return closest_nodes_batched(swarm.ids, targets, k,
                                 valid=swarm.alive)


def lookup_recall(swarm: Swarm, cfg: SwarmConfig, result: LookupResult,
                  targets: jax.Array, k: int = 8) -> jax.Array:
    """Fraction of the true k closest alive nodes found, per lookup."""
    truth = true_closest(swarm, cfg, targets, k)                # [L,k]
    found = result.found                                        # [L,q]
    match = (truth[:, :, None] == found[:, None, :]) & (
        truth[:, :, None] >= 0)
    return jnp.any(match, axis=2).mean(axis=1)
