"""Outbound RPC request lifecycle.

Re-design of the reference ``net::Request`` (ref: include/opendht/request.h:
60-137): a request is PENDING until a reply (COMPLETED), an error/cancel
(CANCELLED), or 3 unanswered attempts 1 s apart (EXPIRED) — retransmits are
scheduler jobs, never blocking (ref: requestStep
src/network_engine.cpp:232-262).
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

from ..core.constants import MAX_ATTEMPT_COUNT


class RequestState(enum.Enum):
    PENDING = 0
    CANCELLED = 1
    EXPIRED = 2
    COMPLETED = 3


class Request:
    __slots__ = ("tid", "node", "msg", "on_done", "on_expired", "attempt_count",
                 "start", "last_try", "reply_time", "state", "_job",
                 "__weakref__")

    def __init__(self, tid: int, node, msg: bytes,
                 on_done: Optional[Callable] = None,
                 on_expired: Optional[Callable] = None):
        self.tid = tid
        self.node = node
        self.msg = msg
        self.on_done = on_done      # (request, answer) -> None
        self.on_expired = on_expired  # (request, over) -> None
        self.attempt_count = 0
        self.start = 0.0
        self.last_try = 0.0
        self.reply_time = 0.0
        self.state = RequestState.PENDING
        self._job = None            # retransmit scheduler job

    def pending(self) -> bool:
        return self.state == RequestState.PENDING

    def completed(self) -> bool:
        return self.state == RequestState.COMPLETED

    def expired(self) -> bool:
        return self.state == RequestState.EXPIRED

    def cancel(self) -> None:
        if self.pending():
            self.state = RequestState.CANCELLED
            self._cancel_job()

    def set_done(self, now: float) -> None:
        self.reply_time = now
        self.state = RequestState.COMPLETED
        self._cancel_job()

    def set_expired(self) -> None:
        if self.pending():
            self.state = RequestState.EXPIRED
            self._cancel_job()
            if self.node is not None:
                self.node.request_expired(self)
            if self.on_expired:
                self.on_expired(self, True)

    def over_attempts(self) -> bool:
        return self.attempt_count >= MAX_ATTEMPT_COUNT

    def _cancel_job(self) -> None:
        if self._job is not None:
            self._job.cancel()
            self._job = None
