"""The wire-protocol engine: RPC send/receive, retries, rate limits,
fragmentation, listen sockets.

Re-design of the reference ``net::NetworkEngine``
(ref: src/network_engine.cpp, include/opendht/network_engine.h).  The engine
owns parsing/sending and request lifecycles; the DHT core owns semantics and
is attached via a handler object exposing the nine callbacks the reference
injects with std::bind (ref: src/dht.cpp:2746-2755):

    on_error(request, code)
    on_new_node(node, confirm)          confirm: 0 seen / 1 queried-us / 2 replied
    on_reported_addr(node_id, addr)
    on_ping(node)                       -> RequestAnswer
    on_find(node, target, want)         -> RequestAnswer
    on_get_values(node, info_hash, want, query) -> RequestAnswer
    on_listen(node, info_hash, token, socket_id, query) -> RequestAnswer
    on_announce(node, info_hash, values, created, token) -> RequestAnswer
    on_refresh(node, info_hash, value_id, token) -> RequestAnswer

Handlers raise :class:`DhtProtocolException` to produce wire errors.

Inbound path (ref: processMessage :365-450): martian filter, blacklist,
per-IP + global rate limit, self-message drop, network-id check, then
dispatch.  Outbound requests retransmit every MAX_RESPONSE_TIME (1 s) up to
3 attempts via scheduler jobs (ref: requestStep :232-262).

Large-value transfers (>8 KB aggregate or >50 values) are fragmented: a
header message carries ``psize`` (total payload bytes), then MTU-sized
``ValueData`` part packets follow, reassembled with 3 s inter-part / 10 s
total timeouts (ref: packValueHeader/sendValueParts :831-882,
maintainRxBuffer :1433-1482).
"""

from __future__ import annotations

import ipaddress
import os
import random
from typing import Callable, Dict, List, Optional, Tuple

import msgpack

from ..core.constants import (BLACKLIST_EXPIRE_TIME, MAX_BLACKLIST_SIZE,
                              MAX_PACKET_VALUE_SIZE, MAX_REQUESTS_PER_SEC,
                              MAX_REQUESTS_PER_SEC_PER_IP, MAX_RESPONSE_TIME,
                              MAX_MESSAGE_VALUE_COUNT, MTU, RX_MAX_PACKET_TIME,
                              RX_TIMEOUT)
from ..core.node import Node
from ..core.node_cache import NodeCache
from ..core.scheduler import Scheduler
from ..core.value import Query, Value
from ..utils.infohash import InfoHash
from ..utils.logger import NONE, Logger
from ..utils.metrics import MetricsRegistry
from ..utils.rate_limiter import (RateLimiter, TokenBucket,
                                  make_rate_limiter)
from ..utils.sockaddr import AF_INET, AF_INET6, SockAddr
from .request import Request, RequestState
from .transport import DatagramTransport
from .wire import (MessageBuilder, MessageType, ParsedMessage, make_tid,
                   pack_nodes, parse_message, E_NON_AUTHORITATIVE_INFORMATION,
                   E_UNAUTHORIZED, METHODS, PING, FIND_NODE, GET_VALUES,
                   ANNOUNCE_VALUE, REFRESH, LISTEN, WANT4, WANT6)

SEND_NODES = 8  # nodes per reply (ref: src/network_engine.cpp:58)

# Canonical message-type taxonomy for the per-type counters (ref:
# network_engine.h:509-516 keeps one enum-indexed array per direction).
# Request keys are the METHODS names — identical for inbound (wire "q"
# strings) and outbound, so stats_in/stats_out finally share ONE key
# set; replies/errors count under "reply"/"error" in BOTH directions
# (the previous code only counted the inbound side and keyed inbound
# requests on the RAW wire string, handing an attacker unbounded
# counter-key cardinality); fragmentation part packets count as
# "value_parts"; anything unrecognized folds into "other".
CANONICAL_TYPES = tuple(name for name, _ in METHODS.values()) + (
    "reply", "error", "value_parts", "other")


class DhtProtocolException(Exception):
    INVALID_TID_SIZE = 421
    UNKNOWN_TID = 422
    WRONG_NODE_INFO_BUF_LEN = 423
    UNAUTHORIZED = E_UNAUTHORIZED
    NOT_FOUND = 404

    def __init__(self, code: int, message: str = ""):
        super().__init__(message)
        self.code = code
        self.message = message


class RequestAnswer:
    """Reply payload produced by DHT-core handlers
    (ref: NetworkEngine::RequestAnswer network_engine.h:220-240)."""

    __slots__ = ("ntoken", "vid", "values", "fields", "nodes4", "nodes6",
                 "expired")

    def __init__(self):
        self.ntoken = b""
        self.vid = 0
        self.values: List[Value] = []
        self.fields: List["FieldValueIndex"] = []  # partial values
        self.nodes4: List[Node] = []
        self.nodes6: List[Node] = []
        self.expired = False  # listen push marked values as expired


class Socket:
    """A persistent tid a remote may reuse to push listen updates
    (ref: openSocket :190-205)."""

    __slots__ = ("id", "on_receive")

    def __init__(self, sid: bytes, cb: Callable):
        self.id = sid
        self.on_receive = cb


class PartialMessage:
    __slots__ = ("msg", "from_addr", "start", "last_part", "buf", "total",
                 "received")

    def __init__(self, msg: ParsedMessage, from_addr: SockAddr, now: float):
        self.msg = msg
        self.from_addr = from_addr
        self.start = now
        self.last_part = now
        self.total = msg.value_parts_total
        self.buf = bytearray(self.total)
        self.received = [False] * ((self.total + MTU - 1) // MTU) if self.total else []

    def append(self, offset: int, data: bytes, now: float) -> None:
        if offset + len(data) > self.total:
            return
        self.buf[offset:offset + len(data)] = data
        idx = offset // MTU
        if idx < len(self.received):
            self.received[idx] = True
        self.last_part = now

    def complete(self) -> bool:
        return bool(self.received) and all(self.received)


class NetworkEngine:
    def __init__(self, myid: InfoHash, network: int,
                 transport4: Optional[DatagramTransport],
                 transport6: Optional[DatagramTransport],
                 scheduler: Scheduler, handler, cache: NodeCache,
                 logger: Logger = NONE, rng: Optional[random.Random] = None,
                 metrics: Optional[MetricsRegistry] = None):
        self.myid = myid
        self.network = network
        self.scheduler = scheduler
        self.handler = handler
        self.cache = cache
        self.log = logger
        self.rng = rng or random.Random()
        self.builder = MessageBuilder(myid, network)

        self.t4 = transport4
        self.t6 = transport6
        if self.t4:
            self.t4.set_receive_callback(self._on_packet)
        if self.t6:
            self.t6.set_receive_callback(self._on_packet)

        self.requests: Dict[bytes, Request] = {}
        self.opened_sockets: Dict[bytes, Socket] = {}
        self._tid_seq = self.rng.randrange(1 << 16)
        self._sock_seq = self.rng.randrange(1 << 16)

        self.rate_limiter = make_rate_limiter(MAX_REQUESTS_PER_SEC)
        # Keyed by host string (IPv4) or 8-byte packed /64 prefix
        # (IPv6).  Token buckets, not sliding windows: the map grows
        # one entry per distinct sender, so per-sender state must be
        # O(1) floats, not a deque of up to 200 timestamps — same
        # steady-state admit rate (utils/rate_limiter.py).
        self.ip_limiters: Dict[object, RateLimiter | TokenBucket] = {}
        self.blacklist: Dict[SockAddr, float] = {}

        self.partial_messages: Dict[bytes, PartialMessage] = {}
        self._rx_job = None

        # Per-message-type counters in/out (ref: network_engine.h:
        # 509-516), now registry-backed so the Prometheus/JSON surface
        # and the legacy stats_in/stats_out dict views read ONE source
        # of truth.  Keys are CANONICAL_TYPES only.
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._msg_ctr = self.metrics.counter(
            "dht_net_messages_total",
            "DHT wire messages by direction and canonical type",
            ("dir", "type"))
        self._drop_ctr = self.metrics.counter(
            "dht_net_dropped_total",
            "Inbound packets dropped before dispatch",
            ("reason",))

    # ------------------------------------------------------------------ #
    # sending                                                            #
    # ------------------------------------------------------------------ #

    def _next_tid(self, method: int) -> bytes:
        self._tid_seq = (self._tid_seq + 1) & 0xFFFF
        if self._tid_seq == 0:
            self._tid_seq = 1
        return make_tid(METHODS[method][1], self._tid_seq)

    def _transport_for(self, addr: SockAddr) -> Optional[DatagramTransport]:
        return self.t4 if addr.family == AF_INET else self.t6

    def _send(self, data: bytes, dest: SockAddr) -> None:
        t = self._transport_for(dest)
        if t is not None:
            t.send(data, dest)

    def _count(self, direction: str, key: str) -> None:
        """Count one wire message under the canonical taxonomy (raw
        wire strings fold into "other" — counter keys must stay a
        CLOSED set, never attacker-chosen)."""
        if key not in CANONICAL_TYPES:
            key = "other"
        self._msg_ctr.inc(dir=direction, type=key)

    @property
    def stats_in(self) -> Dict[str, int]:
        """Legacy dict view of the inbound counters (canonical keys)."""
        return self._stats_dict("in")

    @property
    def stats_out(self) -> Dict[str, int]:
        """Legacy dict view of the outbound counters (canonical keys)."""
        return self._stats_dict("out")

    def _stats_dict(self, direction: str) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for labels, value in self._msg_ctr.series():
            d = dict(labels)
            if d.get("dir") == direction:
                out[d["type"]] = int(value)
        return out

    def _send_request(self, method: int, node: Node, msg_for_tid, on_done,
                      on_expired) -> Request:
        tid = self._next_tid(method)
        msg = msg_for_tid(tid)
        req = Request(tid, node, msg, on_done, on_expired)
        self.requests[tid] = req
        node.requested(req)
        self._count("out", METHODS[method][0])
        self._request_step(req)
        return req

    def _request_step(self, req: Request) -> None:
        """Transmit + schedule retransmit (ref: requestStep :232-262)."""
        if not req.pending():
            return
        now = self.scheduler.time()
        if req.over_attempts():
            # 3 unanswered attempts: request and node expire
            # (ref: requestStep :243-247)
            req.state = RequestState.EXPIRED
            self.requests.pop(req.tid, None)
            if req.node is not None:
                req.node.request_expired(req)
                req.node.set_expired()
            if req.on_expired:
                req.on_expired(req, True)
            return
        if req.attempt_count == 0:
            req.start = now
        req.attempt_count += 1
        req.last_try = now
        self._send(req.msg, req.node.addr)
        req._job = self.scheduler.add(now + MAX_RESPONSE_TIME,
                                      lambda: self._request_step(req))

    # -- public RPC senders (ref: network_engine.h:131-218) ---------------
    def send_ping(self, node: Node, on_done=None, on_expired=None) -> Request:
        return self._send_request(
            PING, node, lambda tid: self.builder.ping(tid), on_done, on_expired)

    def send_find_node(self, node: Node, target: InfoHash, want: int = 0,
                       on_done=None, on_expired=None) -> Request:
        return self._send_request(
            FIND_NODE, node,
            lambda tid: self.builder.find_node(tid, target, want),
            on_done, on_expired)

    def send_get_values(self, node: Node, info_hash: InfoHash,
                        query: Optional[Query], want: int = 0,
                        on_done=None, on_expired=None) -> Request:
        return self._send_request(
            GET_VALUES, node,
            lambda tid: self.builder.get_values(tid, info_hash, query, want),
            on_done, on_expired)

    def send_listen(self, node: Node, info_hash: InfoHash, token: bytes,
                    query: Optional[Query] = None,
                    socket: Optional[Socket] = None,
                    on_done=None, on_expired=None, socket_cb=None
                    ) -> Tuple[Request, Socket]:
        if socket is None:
            socket = self.open_socket(socket_cb)
        req = self._send_request(
            LISTEN, node,
            lambda tid: self.builder.listen(tid, info_hash, token, socket.id,
                                            query),
            on_done, on_expired)
        return req, socket

    def send_announce_value(self, node: Node, info_hash: InfoHash, value: Value,
                            created: Optional[float], token: bytes,
                            on_done=None, on_expired=None) -> Request:
        # Absolute creation time, only sent when in the past (the
        # reference packs to_time_t(created) iff created < now,
        # src/network_engine.cpp:1103-1106; receiver clamps to now).
        created_abs = None
        if created is not None and created < self.scheduler.time():
            created_abs = created
        packed = value.packed()
        if len(packed) < MAX_PACKET_VALUE_SIZE:
            return self._send_request(
                ANNOUNCE_VALUE, node,
                lambda tid: self.builder.announce_value(
                    tid, info_hash, value, created_abs, token),
                on_done, on_expired)
        # fragmented announce: header + parts
        blob = msgpack.packb([value.pack()])

        def build_header(tid: bytes) -> bytes:
            args = {"id": bytes(self.myid), "h": bytes(info_hash),
                    "token": token, "psize": len(blob), "_q": "put"}
            if created_abs is not None:
                args["c"] = int(created_abs)
            env = {"a": args, "q": args.pop("_q"), "t": tid, "y": "q",
                   "v": "RNG1"}
            if self.network:
                env["n"] = self.network
            return msgpack.packb(env)

        req = self._send_request(ANNOUNCE_VALUE, node, build_header,
                                 on_done, on_expired)
        self._send_value_parts(req.tid, blob, node.addr)
        return req

    def send_refresh_value(self, node: Node, info_hash: InfoHash, vid: int,
                           token: bytes, on_done=None, on_expired=None
                           ) -> Request:
        return self._send_request(
            REFRESH, node,
            lambda tid: self.builder.refresh_value(tid, info_hash, vid, token),
            on_done, on_expired)

    def _send_value_parts(self, tid: bytes, blob: bytes, dest: SockAddr) -> None:
        """ref: sendValueParts :855-882"""
        for off in range(0, len(blob), MTU):
            self._send(self.builder.value_part(tid, off, blob[off:off + MTU]),
                       dest)

    # -- sockets / listen push (ref: :161-205) -----------------------------
    def open_socket(self, cb) -> Socket:
        self._sock_seq = (self._sock_seq + 1) & 0xFFFF
        sid = make_tid(b"so", self._sock_seq)
        s = Socket(sid, cb)
        self.opened_sockets[sid] = s
        return s

    def close_socket(self, socket: Optional[Socket]) -> None:
        if socket is not None:
            self.opened_sockets.pop(socket.id, None)

    def tell_listener(self, node: Node, socket_id: bytes, info_hash: InfoHash,
                      values: List[Value], ntoken: bytes = b"",
                      expired: bool = False) -> None:
        """Push value updates to a remote listener via its socket id
        (ref: tellListener :161-173; expired flag per sendUpdateValues)."""
        packed = [v.pack() for v in values]
        total = sum(len(msgpack.packb(p)) for p in packed)
        r: Dict[str, object] = {"id": bytes(self.myid)}
        if ntoken:
            r["token"] = ntoken
        if expired:
            r["exp"] = True
        self._count("out", "reply")  # listen pushes ride reply envelopes
        if total < MAX_PACKET_VALUE_SIZE and len(values) <= MAX_MESSAGE_VALUE_COUNT:
            r["values"] = packed
            env = {"r": r, "t": socket_id, "y": "r", "v": "RNG1"}
            if self.network:
                env["n"] = self.network
            self._send(msgpack.packb(env), node.addr)
        else:
            blob = msgpack.packb(packed)
            r["psize"] = len(blob)
            env = {"r": r, "t": socket_id, "y": "r", "v": "RNG1"}
            if self.network:
                env["n"] = self.network
            self._send(msgpack.packb(env), node.addr)
            self._send_value_parts(socket_id, blob, node.addr)

    # -- node blacklisting (ref: :344-356) ---------------------------------
    def blacklist_node(self, node: Optional[Node]) -> None:
        if node is None:
            return
        node.set_expired()
        for tid, req in list(self.requests.items()):
            if req.node is node:
                req.cancel()
                del self.requests[tid]
        self._purge_blacklist(self.scheduler.time())
        self.blacklist[node.addr] = (self.scheduler.time()
                                     + BLACKLIST_EXPIRE_TIME)

    def _purge_blacklist(self, now: float) -> None:
        """Blacklist hygiene: drop entries whose sentence is served
        (`is_node_blacklisted` only reaps the addresses it is asked
        about — addresses never heard from again would otherwise
        accumulate forever), then enforce the size cap by evicting the
        soonest-to-expire entries (they were convicted earliest; an
        attacker cycling source addresses must not grow the map
        without bound — SURVEY §4's bounded misbehaving-peer set)."""
        for addr, until in list(self.blacklist.items()):
            if until < now:
                del self.blacklist[addr]
        excess = len(self.blacklist) - (MAX_BLACKLIST_SIZE - 1)
        if excess > 0:
            for addr, _ in sorted(self.blacklist.items(),
                                  key=lambda kv: kv[1])[:excess]:
                del self.blacklist[addr]

    def is_node_blacklisted(self, addr: SockAddr) -> bool:
        until = self.blacklist.get(addr)
        if until is None:
            return False
        if until < self.scheduler.time():
            del self.blacklist[addr]
            return False
        return True

    # ------------------------------------------------------------------ #
    # receiving                                                          #
    # ------------------------------------------------------------------ #

    def _on_packet(self, data: bytes, from_addr: SockAddr) -> None:
        self.process_message(data, from_addr)

    def _is_martian(self, addr: SockAddr) -> bool:
        """ref: :308-339 — drop unusable source addresses."""
        return addr.port == 0

    def process_message(self, data: bytes, from_addr: SockAddr) -> None:
        if self._is_martian(from_addr):
            self._drop_ctr.inc(reason="martian")
            return
        if self.is_node_blacklisted(from_addr):
            self._drop_ctr.inc(reason="blacklist")
            return
        if not data:
            return
        try:
            msg = parse_message(data)
        except Exception:
            self.log.w("can't parse message from %s", from_addr)
            self._drop_ctr.inc(reason="parse")
            return
        now = self.scheduler.time()

        if msg.network != self.network:
            self._drop_ctr.inc(reason="network_mismatch")
            return  # ref: :387-390

        if msg.type == MessageType.ValueData:
            self._count("in", "value_parts")
            pm = self.partial_messages.get(msg.tid)
            if pm is not None and pm.from_addr == from_addr:
                pm.append(msg.part_offset, msg.part_data, now)
                if pm.complete():
                    del self.partial_messages[msg.tid]
                    self._deliver_assembled(pm)
            return

        if msg.id == self.myid:
            self._drop_ctr.inc(reason="self_message")
            return  # self-message drop (ref: :421)

        is_request = msg.type not in (MessageType.Error, MessageType.Reply)
        if is_request:
            # rate limits apply to requests only (ref: :287-305)
            if not self._rate_limit_ok(from_addr, now):
                self._drop_ctr.inc(reason="rate_limit")
                return
            # One canonical key per wire method — the raw "q" string is
            # never a counter key (unknown methods fold into "other").
            self._count("in", msg.type or "other")
        else:
            self._count("in", "reply" if msg.type == MessageType.Reply
                        else "error")

        if msg.value_parts_total and not msg.values:
            # header of a fragmented message: stash and await parts
            self.partial_messages[msg.tid] = PartialMessage(msg, from_addr, now)
            self._schedule_rx_maintenance()
            return

        self._process(msg, from_addr)

    def _rate_limit_ok(self, addr: SockAddr, now: float) -> bool:
        key = addr.host
        if addr.family == AF_INET6 and ":" in key:
            # Group IPv6 by /64 (ref: network_engine.h:572-599).  The
            # textual form may be compressed ("2001:db9::5"), so take
            # the first 8 of the 16 packed bytes, not string hextets.
            try:
                key = ipaddress.ip_address(key.split("%")[0]).packed[:8]
            except ValueError:
                key = ":".join(key.split(":")[:4])
        lim = self.ip_limiters.get(key)
        if lim is None:
            lim = self.ip_limiters[key] = make_rate_limiter(
                MAX_REQUESTS_PER_SEC_PER_IP, kind="token-bucket")
        return lim.limit(now) and self.rate_limiter.limit(now)

    def _deliver_assembled(self, pm: PartialMessage) -> None:
        try:
            packed_values = msgpack.unpackb(bytes(pm.buf), raw=False,
                                           strict_map_key=False)
            for vo in packed_values:
                try:
                    pm.msg.values.append(Value.unpack(vo))
                except Exception:
                    continue
        except Exception:
            return
        pm.msg.value_parts_total = 0
        self._process(pm.msg, pm.from_addr)

    def _schedule_rx_maintenance(self) -> None:
        if self._rx_job is None or not self._rx_job.active:
            self._rx_job = self.scheduler.add(
                self.scheduler.time() + RX_TIMEOUT, self._maintain_rx_buffer)

    def _maintain_rx_buffer(self) -> None:
        """ref: maintainRxBuffer :1433-1444"""
        self._rx_job = None
        now = self.scheduler.time()
        for tid, pm in list(self.partial_messages.items()):
            if (pm.start + RX_MAX_PACKET_TIME < now
                    or pm.last_part + RX_TIMEOUT < now):
                del self.partial_messages[tid]
        if self.partial_messages:
            self._schedule_rx_maintenance()

    # -- dispatch (ref: process :453-594) ----------------------------------
    def _process(self, msg: ParsedMessage, from_addr: SockAddr) -> None:
        now = self.scheduler.time()

        if msg.type in (MessageType.Error, MessageType.Reply):
            req = self.requests.get(msg.tid)
            if req is not None and req.node.addr.host != from_addr.host:
                # reply from unexpected origin: ignore
                return
            if req is None:
                sock = self.opened_sockets.get(msg.tid)
                if sock is not None and msg.type == MessageType.Reply:
                    # listen push on a socket
                    node = self.cache.get_node(msg.id, from_addr) if msg.id else None
                    if node:
                        node.received(now, None)
                        self.handler.on_new_node(node, 2)
                    sock.on_receive(node, msg)
                return
            if not req.pending():
                self.requests.pop(msg.tid, None)
                return

            node = req.node
            if node.id != msg.id and msg.id:
                if not node.id:
                    # Reply to a message sent before we knew the node id
                    # (bootstrap ping): swap in the canonical cached Node
                    # so one id maps to one object everywhere
                    # (ref: src/network_engine.cpp:470-473).
                    node = self.cache.get_node(msg.id, from_addr)
                    req.node = node
                else:
                    # Reply from an unexpected node id
                    # (ref: src/network_engine.cpp:474-479).
                    node.received(now, req)
                    self.handler.on_new_node(node, 2)
                    self.log.w("[node %s] reply from unexpected node",
                               node.id)
                    return

            if msg.type == MessageType.Error:
                self.requests.pop(msg.tid, None)
                node.received(now, req)
                self.handler.on_new_node(node, 2)
                req.state = RequestState.COMPLETED
                req._cancel_job()
                self.handler.on_error(req, msg.error_code)
                return

            # Reply
            self.requests.pop(msg.tid, None)
            node.received(now, req)
            node.auth_success()
            self.handler.on_new_node(node, 2)
            if msg.addr is not None:
                self.handler.on_reported_addr(msg.id, msg.addr)
            req.set_done(now)
            self._process_discovered_nodes(msg)
            if req.on_done:
                req.on_done(req, self._answer_from(msg))
            return

        # request from remote
        if not msg.id:
            self.log.w("request with no id from %s", from_addr)
            return
        node = self.cache.get_node(msg.id, from_addr)
        node.update(from_addr)
        node.received(now, None)
        self.handler.on_new_node(node, 1)

        try:
            if msg.type == MessageType.Ping:
                self.handler.on_ping(node)
                self._send(self.builder.pong(msg.tid, from_addr), from_addr)
            elif msg.type == MessageType.FindNode:
                ans = self.handler.on_find(node, msg.target, msg.want)
                self._send_nodes_values(msg.tid, from_addr, ans)
            elif msg.type == MessageType.GetValues:
                ans = self.handler.on_get_values(node, msg.info_hash, msg.want,
                                                 msg.query)
                self._send_nodes_values(msg.tid, from_addr, ans, msg.query)
            elif msg.type == MessageType.AnnounceValue:
                created = None
                if msg.created is not None:
                    # Absolute time, clamped to now (importValues-style
                    # clamp, ref src/dht.cpp:3069-3073).
                    created = min(now, msg.created)
                ans = self.handler.on_announce(node, msg.info_hash, msg.values,
                                               created, msg.token)
                self._send(self.builder.value_announced(msg.tid, from_addr,
                                                        ans.vid), from_addr)
            elif msg.type == MessageType.Refresh:
                ans = self.handler.on_refresh(node, msg.info_hash, msg.value_id,
                                              msg.token)
                self._send(self.builder.value_announced(msg.tid, from_addr,
                                                        msg.value_id), from_addr)
            elif msg.type == MessageType.Listen:
                self.handler.on_listen(node, msg.info_hash, msg.token,
                                       msg.socket_id, msg.query)
                self._send(self.builder.listen_confirm(msg.tid, from_addr),
                           from_addr)
            else:
                self.log.w("unknown query type %r", msg.type)
                return
            # Every handled request above answered with one reply.
            self._count("out", "reply")
        except DhtProtocolException as e:
            self._count("out", "error")
            self._send(self.builder.error(msg.tid, e.code, e.message,
                                          include_id=True), from_addr)

    def _process_discovered_nodes(self, msg: ParsedMessage) -> None:
        """Insert nodes learned from reply node lists (confirm=0)."""
        for nid, addr in msg.nodes4 + msg.nodes6:
            if nid == self.myid:
                continue
            n = self.cache.get_node(nid, addr)
            self.handler.on_new_node(n, 0)

    def _answer_from(self, msg: ParsedMessage) -> RequestAnswer:
        from ..core.value import Field, FieldValueIndex
        ans = RequestAnswer()
        ans.ntoken = msg.token
        ans.vid = msg.value_id
        ans.values = msg.values
        ans.fields = [FieldValueIndex.from_fields(
            [Field(f) for f in msg.fields], row) for row in msg.field_values]
        ans.nodes4 = [self.cache.get_node(nid, a) for nid, a in msg.nodes4
                      if nid != self.myid]
        ans.nodes6 = [self.cache.get_node(nid, a) for nid, a in msg.nodes6
                      if nid != self.myid]
        return ans

    def _send_nodes_values(self, tid: bytes, dest: SockAddr,
                           ans: RequestAnswer,
                           query: Optional[Query] = None) -> None:
        """ref: sendNodesValues :885-940 (fields projection + fragmentation)"""
        n4 = pack_nodes(ans.nodes4[:SEND_NODES], AF_INET)
        n6 = pack_nodes(ans.nodes6[:SEND_NODES], AF_INET6)
        fields = None
        values = None
        psize = 0
        if ans.fields and query is not None:
            flat = []
            for v in ans.values:
                flat.extend(v.pack_fields([f for f in query.select.fields]))
            fields = {"f": [int(f) for f in query.select.fields], "v": flat}
        elif ans.values:
            packed = [v.pack() for v in ans.values]
            total = sum(len(msgpack.packb(p)) for p in packed)
            if total < MAX_PACKET_VALUE_SIZE and \
                    len(packed) <= MAX_MESSAGE_VALUE_COUNT:
                values = packed
            else:
                blob = msgpack.packb(packed)
                psize = len(blob)
                self._send(self.builder.nodes_values(
                    tid, dest, n4, n6, None, None, ans.ntoken, psize), dest)
                self._send_value_parts(tid, blob, dest)
                return
        self._send(self.builder.nodes_values(tid, dest, n4, n6, values,
                                             fields, ans.ntoken), dest)

    # ------------------------------------------------------------------ #
    # maintenance                                                        #
    # ------------------------------------------------------------------ #

    def cancel_request(self, req: Optional[Request]) -> None:
        if req is not None:
            req.cancel()
            self.requests.pop(req.tid, None)

    def get_stats(self) -> Tuple[Dict[str, int], Dict[str, int]]:
        return dict(self.stats_in), dict(self.stats_out)
