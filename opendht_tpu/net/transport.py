"""Datagram transports: the swap seam between real UDP and simulation.

The reference hard-wires UDP sockets into DhtRunner (ref:
src/dhtrunner.cpp:364-454) and passes raw fds to the engine; its callback
seam (SURVEY §1) is what makes a simulated transport possible.  Here the
seam is explicit: everything above speaks :class:`DatagramTransport`.

* :class:`VirtualNetwork` / :class:`VirtualSocket` — deterministic
  in-memory network.  Delivery is a scheduler job after a configurable
  delay; packet loss and partitions are injected by policy — the in-process
  equivalent of the reference's netns + netem harness
  (ref: python/tools/dht/virtual_network_builder.py:61-116).
* :class:`UdpTransport` — real sockets for live interop (used by
  DhtRunner's receive thread).
"""

from __future__ import annotations

import random
import socket
import threading
from typing import Callable, Dict, Optional, Tuple

from ..core.scheduler import Scheduler
from ..utils.sockaddr import AF_INET, AF_INET6, SockAddr

ReceiveCb = Callable[[bytes, SockAddr], None]


class DatagramTransport:
    def send(self, data: bytes, dest: SockAddr) -> None:  # pragma: no cover
        raise NotImplementedError

    def set_receive_callback(self, cb: ReceiveCb) -> None:
        self._cb = cb

    def local_addr(self) -> SockAddr:  # pragma: no cover
        raise NotImplementedError

    def close(self) -> None:
        pass


class VirtualNetwork:
    """An in-memory packet-switched network driven by one scheduler.

    Models the netem knobs (delay/jitter/loss) and partitions; every
    delivery is deterministic given the rng seed.
    """

    def __init__(self, scheduler: Scheduler, delay: float = 0.005,
                 jitter: float = 0.0, loss: float = 0.0,
                 seed: int = 42):
        self.scheduler = scheduler
        self.delay = delay
        self.jitter = jitter
        self.loss = loss
        self.rng = random.Random(seed)
        self._endpoints: Dict[Tuple[str, int], "VirtualSocket"] = {}
        self._partitions: set = set()   # hosts currently unreachable
        self.packets_sent = 0
        self.packets_dropped = 0
        self.bytes_sent = 0

    def socket(self, host: str, port: int) -> "VirtualSocket":
        s = VirtualSocket(self, SockAddr(host, port))
        self._endpoints[(host, port)] = s
        return s

    def unregister(self, addr: SockAddr) -> None:
        self._endpoints.pop((addr.host, addr.port), None)

    def partition(self, host: str, isolated: bool = True) -> None:
        """Isolate/restore a host (the node-kill / net-split knob)."""
        if isolated:
            self._partitions.add(host)
        else:
            self._partitions.discard(host)

    def deliver(self, data: bytes, src: SockAddr, dest: SockAddr) -> None:
        self.packets_sent += 1
        self.bytes_sent += len(data)
        if (src.host in self._partitions or dest.host in self._partitions
                or (self.loss and self.rng.random() < self.loss)):
            self.packets_dropped += 1
            return
        delay = self.delay
        if self.jitter:
            delay += self.rng.uniform(0, self.jitter)

        def _arrive(data=data, src=src, dest_key=(dest.host, dest.port)):
            ep = self._endpoints.get(dest_key)
            if ep is not None and ep._cb is not None:
                ep._cb(data, src)

        self.scheduler.add(self.scheduler.time() + delay, _arrive)


class VirtualSocket(DatagramTransport):
    def __init__(self, net: VirtualNetwork, addr: SockAddr):
        self.net = net
        self.addr = addr
        self._cb: Optional[ReceiveCb] = None

    def send(self, data: bytes, dest: SockAddr) -> None:
        self.net.deliver(data, self.addr, dest)

    def local_addr(self) -> SockAddr:
        return self.addr

    def close(self) -> None:
        self.net.unregister(self.addr)


class UdpTransport(DatagramTransport):
    """Real UDP socket with a background receive thread.

    The receive thread pushes packets into a callback; binding, 250 ms
    select tick and the rcv queue mirror the reference's receive loop
    (ref: src/dhtrunner.cpp:404-454).
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 0, af: int = AF_INET):
        fam = socket.AF_INET if af == AF_INET else socket.AF_INET6
        self.sock = socket.socket(fam, socket.SOCK_DGRAM)
        if af == AF_INET6:
            self.sock.setsockopt(socket.IPPROTO_IPV6, socket.IPV6_V6ONLY, 1)
        self.sock.bind((host, port))
        self.sock.settimeout(0.25)
        self.af = af
        self._cb: Optional[ReceiveCb] = None
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._recv_loop, daemon=True)
        self._thread.start()

    def _recv_loop(self) -> None:
        while self._running:
            try:
                data, src = self.sock.recvfrom(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            if self._cb is not None:
                self._cb(data, SockAddr(src[0], src[1]))

    def send(self, data: bytes, dest: SockAddr) -> None:
        try:
            self.sock.sendto(data, dest.to_tuple())
        except OSError:
            pass

    def local_addr(self) -> SockAddr:
        host, port = self.sock.getsockname()[:2]
        return SockAddr(host, port)

    def close(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None
        self.sock.close()
