"""The msgpack wire protocol: message building and parsing.

Re-design of the reference wire layer (ref: src/network_engine.cpp:604-1430).
Message envelope is a msgpack map with single-letter keys:

* ``t`` — 4-byte transaction id: 2-char method prefix + u16 seqno
  (prefixes pn/fn/gt/pt/rf/lt — ref src/network_engine.cpp:47-52)
* ``y`` — kind: "q" query / "r" reply / "e" error / "v" value part
* ``q`` + ``a`` — method name + argument map (queries)
* ``r`` — result map (replies; always carries ``id`` and echoed ``sa``)
* ``e`` — [code, message] (errors)
* ``v`` — agent tag ("RNG1"), ``n`` — optional network id

Argument keys: ``id`` sender, ``target``/``h`` lookup keys, ``token`` write
token, ``values``, ``vid`` value id, ``sid`` listen socket id, ``w`` want,
``c`` created offset, ``q`` query, ``n4``/``n6`` packed node lists
(26 B IPv4 / 38 B IPv6 per node — ref src/network_engine.cpp:943-992),
``sa`` echoed observed source address, ``p`` {o: offset, d: chunk} value
parts for fragmented transfers (ref :855-882).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import msgpack

from ..core.constants import AGENT
from ..core.value import Query, Value
from ..utils.infohash import HASH_LEN, InfoHash
from ..utils.sockaddr import AF_INET, AF_INET6, SockAddr

# method id <-> (name, tid prefix)
PING, FIND_NODE, GET_VALUES, ANNOUNCE_VALUE, REFRESH, LISTEN = range(6)
METHODS = {
    PING: ("ping", b"pn"),
    FIND_NODE: ("find", b"fn"),
    GET_VALUES: ("get", b"gt"),
    ANNOUNCE_VALUE: ("put", b"pt"),
    REFRESH: ("refresh", b"rf"),
    LISTEN: ("listen", b"lt"),
}
NAME_TO_METHOD = {name: m for m, (name, _) in METHODS.items()}

WANT4, WANT6 = 1, 2

# error codes (ref: include/opendht/net.h)
E_NON_AUTHORITATIVE_INFORMATION = 203
E_UNAUTHORIZED = 401
E_NOT_FOUND = 404


def make_tid(prefix: bytes, seq: int) -> bytes:
    return prefix + (seq & 0xFFFF).to_bytes(2, "little")


class MessageType:
    Error = "e"
    Reply = "r"
    Ping = "ping"
    FindNode = "find"
    GetValues = "get"
    AnnounceValue = "put"
    Refresh = "refresh"
    Listen = "listen"
    ValueData = "v"


def pack_nodes(nodes, af: int) -> bytes:
    """Compact node info: id ‖ ip ‖ port (ref: bufferNodes :943-992)."""
    out = bytearray()
    for n in nodes:
        out += bytes(n.id)
        out += n.addr.pack_ip()
    return bytes(out)


def unpack_nodes(blob: bytes, af: int) -> List[Tuple[InfoHash, SockAddr]]:
    """ref: deserializeNodes src/network_engine.cpp:788-828"""
    step = HASH_LEN + (6 if af == AF_INET else 18)
    out = []
    if len(blob) % step:
        return out
    for i in range(0, len(blob), step):
        nid = InfoHash(blob[i:i + HASH_LEN])
        addr = SockAddr.unpack_ip(blob[i + HASH_LEN:i + step])
        out.append((nid, addr))
    return out


class ParsedMessage:
    """Decoded inbound message (ref: ParsedMessage src/network_engine.cpp:
    1252-1430)."""

    __slots__ = ("type", "tid", "id", "network", "info_hash", "target",
                 "token", "value_id", "values", "fields", "field_values",
                 "nodes4", "nodes6", "addr", "created", "socket_id", "want",
                 "query", "error_code", "is_reply", "part_index",
                 "part_offset", "part_data", "value_parts_total")

    def __init__(self):
        self.type = None
        self.tid = b""
        self.id = None            # sender InfoHash
        self.network = 0
        self.info_hash = None
        self.target = None
        self.token = b""
        self.value_id = 0
        self.values: List[Value] = []
        self.fields: List[int] = []
        self.field_values: List[list] = []
        self.nodes4: List[Tuple[InfoHash, SockAddr]] = []
        self.nodes6: List[Tuple[InfoHash, SockAddr]] = []
        self.addr: Optional[SockAddr] = None   # our address as seen by peer
        self.created: Optional[float] = None   # age offset (seconds)
        self.socket_id = b""
        self.want = 0
        self.query: Optional[Query] = None
        self.error_code = 0
        self.is_reply = False
        self.part_index = 0
        self.part_offset = 0
        self.part_data = b""
        self.value_parts_total = 0


def parse_message(data: bytes) -> ParsedMessage:
    o = msgpack.unpackb(data, raw=False, strict_map_key=False)
    if not isinstance(o, dict):
        raise ValueError("not a msgpack map")
    m = ParsedMessage()
    m.tid = bytes(o.get("t", b""))
    m.network = o.get("n", 0)
    y = o.get("y", "q")

    if y == "e":
        m.type = MessageType.Error
        e = o.get("e", [0, ""])
        m.error_code = int(e[0]) if e else 0
        r = o.get("r", {})
        if "id" in r:
            m.id = InfoHash(bytes(r["id"]))
        return m

    if y == "v":
        # fragmented value part: p = {value_index: {o, d}} (ref :872-875)
        m.type = MessageType.ValueData
        p = o.get("p", {})
        if p and not ("o" in p or "d" in p):
            m.part_index, inner = next(iter(p.items()))
            m.part_index = int(m.part_index)
        else:  # tolerate the un-indexed flat form
            inner = p
        m.part_offset = int(inner.get("o", 0))
        m.part_data = bytes(inner.get("d", b""))
        return m

    body = o.get("r") if y == "r" else o.get("a", {})
    body = body or {}
    m.is_reply = (y == "r")
    if "id" in body:
        m.id = InfoHash(bytes(body["id"]))
    if "sa" in body:
        try:
            m.addr = SockAddr.unpack_ip(bytes(body["sa"]))
        except ValueError:
            pass
    if "h" in body:
        m.info_hash = InfoHash(bytes(body["h"]))
    if "target" in body:
        m.target = InfoHash(bytes(body["target"]))
    if "token" in body:
        m.token = bytes(body["token"])
    if "vid" in body:
        m.value_id = int(body["vid"])
    if "sid" in body:
        m.socket_id = bytes(body["sid"])
    if "w" in body:
        m.want = unpack_want(body["w"])
    if "c" in body:
        m.created = float(body["c"])
    if "q" in body and y != "r" and isinstance(body["q"], dict):
        m.query = Query.unpack(body["q"])
    if "n4" in body:
        m.nodes4 = unpack_nodes(bytes(body["n4"]), AF_INET)
    if "n6" in body:
        m.nodes6 = unpack_nodes(bytes(body["n6"]), AF_INET6)
    if "values" in body:
        for vo in body["values"]:
            try:
                m.values.append(Value.unpack(vo))
            except Exception:
                continue
    if "psize" in body:
        m.value_parts_total = int(body["psize"])
    if "fields" in body:
        f = body["fields"]
        m.fields = [int(x) for x in f.get("f", [])]
        flat = f.get("v", [])
        k = len(m.fields)
        if k:
            m.field_values = [flat[i:i + k] for i in range(0, len(flat), k)]

    if y == "r":
        m.type = MessageType.Reply
    else:
        m.type = o.get("q", "")
    return m


# The ``w`` array carries the reference build platform's OS constants
# (Linux AF_INET=2 / AF_INET6=10, ref src/network_engine.cpp:705-709) —
# NOT our internal SockAddr family tags.
WIRE_AF_INET = 2
WIRE_AF_INET6 = 10


def pack_want(want: int) -> list:
    out = []
    if want & WANT4:
        out.append(WIRE_AF_INET)
    if want & WANT6:
        out.append(WIRE_AF_INET6)
    return out


def unpack_want(obj) -> int:
    if isinstance(obj, int):  # tolerate the bitmask form
        return obj
    w = 0
    for af in obj or []:
        if af == WIRE_AF_INET:
            w |= WANT4
        elif af == WIRE_AF_INET6:
            w |= WANT6
    return w


class MessageBuilder:
    """Builds outbound messages (the serialization half of the engine).

    Key order inside every map mirrors the reference packers exactly
    (src/network_engine.cpp:634-1250) so messages are byte-identical —
    pinned by tests/test_wire_golden.py.
    """

    def __init__(self, myid: InfoHash, network: int = 0):
        self.myid = myid
        self.network = network

    def _envelope(self, tid: bytes, y: str, payload_key: str, payload) -> bytes:
        env = {payload_key: payload}
        if y == "q":
            env["q"] = payload.pop("_q")
        env["t"] = tid
        env["y"] = y
        env["v"] = AGENT
        if self.network:
            env["n"] = self.network
        return msgpack.packb(env)

    def _query(self, tid: bytes, method: str, args: dict) -> bytes:
        # "id" is always the first argument key (every reference packer
        # packs it before anything else).
        full = {"id": bytes(self.myid)}
        full.update(args)
        full["_q"] = method
        return self._envelope(tid, "q", "a", full)

    def _reply(self, tid: bytes, dest: Optional[SockAddr],
               pre: Optional[dict] = None,
               post: Optional[dict] = None) -> bytes:
        """Reply body: id, then ``pre`` fields, then the echoed source
        address, then ``post`` fields — the reference's insertAddr call
        position varies per reply type."""
        r = {"id": bytes(self.myid)}
        if pre:
            r.update(pre)
        if dest:
            # IP only, no port (insertAddr src/network_engine.cpp:604-613)
            r["sa"] = dest.pack_ip()[:-2]
        if post:
            r.update(post)
        return self._envelope(tid, "r", "r", r)

    # -- queries -----------------------------------------------------------
    def ping(self, tid: bytes) -> bytes:
        return self._query(tid, "ping", {})

    def find_node(self, tid: bytes, target: InfoHash, want: int) -> bytes:
        args = {"target": bytes(target)}
        if want > 0:
            args["w"] = pack_want(want)
        return self._query(tid, "find", args)

    def get_values(self, tid: bytes, info_hash: InfoHash, query: Optional[Query],
                   want: int) -> bytes:
        args = {"h": bytes(info_hash)}
        if query:
            args["q"] = query.pack()
        if want > 0:
            args["w"] = pack_want(want)
        return self._query(tid, "get", args)

    def listen(self, tid: bytes, info_hash: InfoHash, token: bytes,
               socket_id: bytes, query: Optional[Query]) -> bytes:
        args = {"h": bytes(info_hash), "token": token, "sid": socket_id}
        if query:
            args["q"] = query.pack()
        return self._query(tid, "listen", args)

    def announce_value(self, tid: bytes, info_hash: InfoHash, value: Value,
                       created: Optional[float], token: bytes) -> bytes:
        """``created`` is absolute seconds (the reference packs
        ``to_time_t(created)``, clamped to now by the receiver)."""
        args = {"h": bytes(info_hash), "values": [value.pack()]}
        if created is not None:
            args["c"] = int(created)
        args["token"] = token
        return self._query(tid, "put", args)

    def refresh_value(self, tid: bytes, info_hash: InfoHash, vid: int,
                      token: bytes) -> bytes:
        args = {"h": bytes(info_hash), "vid": vid, "token": token}
        return self._query(tid, "refresh", args)

    # -- replies -----------------------------------------------------------
    def pong(self, tid: bytes, dest: SockAddr) -> bytes:
        return self._reply(tid, dest)

    def nodes_values(self, tid: bytes, dest: SockAddr, nodes4: bytes,
                     nodes6: bytes, values: Optional[list] = None,
                     fields: Optional[dict] = None, token: bytes = b"",
                     values_size: int = 0) -> bytes:
        r = {}
        if nodes4:
            r["n4"] = nodes4
        if nodes6:
            r["n6"] = nodes6
        if token:
            r["token"] = token
        if values:
            r["values"] = values
        if values_size:
            r["psize"] = values_size
        if fields:
            r["fields"] = fields
        return self._reply(tid, dest, post=r)

    def listen_confirm(self, tid: bytes, dest: SockAddr) -> bytes:
        return self._reply(tid, dest)

    def value_announced(self, tid: bytes, dest: SockAddr, vid: int) -> bytes:
        # r = {id, vid, sa} (sendValueAnnounced :1198-1218)
        return self._reply(tid, dest, pre={"vid": vid})

    def value_part(self, tid: bytes, offset: int, chunk: bytes,
                   index: int = 0) -> bytes:
        """Fragment envelope: [n,] y, t, p{index: {o, d}}
        (sendValueParts :853-882 — network id first, no agent tag)."""
        env = {}
        if self.network:
            env["n"] = self.network
        env["y"] = "v"
        env["t"] = tid
        env["p"] = {index: {"o": offset, "d": chunk}}
        return msgpack.packb(env)

    def error(self, tid: bytes, code: int, message: str,
              include_id: bool = False) -> bytes:
        env = {"e": [code, message]}
        if include_id:
            env["r"] = {"id": bytes(self.myid)}
        env["t"] = tid
        env["y"] = "e"
        env["v"] = AGENT
        if self.network:
            env["n"] = self.network
        return msgpack.packb(env)
