"""opendht_tpu — a TPU-native distributed hash table framework.

A ground-up re-design of the OpenDHT capability set (Kademlia DHT with
values, listen/pub-sub, public-key crypto layer, PHT secondary index,
runner/threading runtime, CLI tools, and a test/benchmark harness) built
TPU-first:

* the event-driven host runtime (``core``, ``net``, ``crypto``,
  ``indexation``) mirrors the reference's layer seams so a deterministic
  in-memory transport slots in where UDP does;
* the device path (``ops``, ``parallel``, ``models``) implements the
  160-bit XOR metric, k-bucket routing construction, and massively
  batched iterative Kademlia lookups as JAX/Pallas kernels over packed
  ``[N, 5] uint32`` id matrices, sharded over a ``jax.sharding.Mesh``.

Reference: sim590/opendht (C++11), see SURVEY.md.
"""

__version__ = "0.1.0"

# Binding-parity surface (ref: python/opendht.pyx exports: InfoHash,
# Node, NodeSet, Value, PublicKey, Certificate, Identity, DhtConfig,
# DhtRunner, Pht).
from .utils.infohash import InfoHash  # noqa: F401
from .utils.sockaddr import SockAddr  # noqa: F401
from .core.value import Value, ValueType, Query, Select, Where  # noqa: F401
from .core.node import Node  # noqa: F401
from .core.dht import Dht, DhtConfig  # noqa: F401
# The crypto layer (and the runner built on it) needs the optional
# ``cryptography``/``argon2-cffi`` wheels.  Containers without them
# must still import the package — the host core, the harness and the
# whole device engine are crypto-free — so these imports are GATED:
# missing deps degrade to a loud, attribute-level ImportError instead
# of poisoning ``import opendht_tpu`` for every consumer.
_CRYPTO_IMPORT_ERROR: ImportError | None = None
try:
    from .crypto.identity import (  # noqa: F401
        Certificate,
        Identity,
        PrivateKey,
        PublicKey,
        generate_identity,
    )
    from .crypto.securedht import SecureDht, SecureDhtConfig  # noqa: F401
    from .runtime.dhtrunner import DhtRunner, DhtRunnerConfig  # noqa: F401
except ImportError as _e:  # pragma: no cover — dep-less containers
    _CRYPTO_IMPORT_ERROR = _e

_CRYPTO_NAMES = frozenset({
    "Certificate", "Identity", "PrivateKey", "PublicKey",
    "generate_identity", "SecureDht", "SecureDhtConfig",
    "DhtRunner", "DhtRunnerConfig",
})


def __getattr__(name: str):
    if name in _CRYPTO_NAMES and _CRYPTO_IMPORT_ERROR is not None:
        raise ImportError(
            f"opendht_tpu.{name} requires the optional crypto "
            f"dependencies (cryptography, argon2-cffi): "
            f"{_CRYPTO_IMPORT_ERROR}")
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


from .runtime.nodeset import NodeSet  # noqa: F401,E402
from .indexation.pht import Pht  # noqa: F401,E402
from .harness.network import DhtNetwork  # noqa: F401,E402

# The TPU swarm engine (jax-heavy) is intentionally NOT imported here;
# use ``from opendht_tpu.models import SwarmConfig, build_swarm, lookup``
# or ``from opendht_tpu.parallel import sharded_lookup``.
