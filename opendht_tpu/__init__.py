"""opendht_tpu — a TPU-native distributed hash table framework.

A ground-up re-design of the OpenDHT capability set (Kademlia DHT with
values, listen/pub-sub, public-key crypto layer, PHT secondary index,
runner/threading runtime, CLI tools, and a test/benchmark harness) built
TPU-first:

* the event-driven host runtime (``core``, ``net``, ``crypto``,
  ``indexation``) mirrors the reference's layer seams so a deterministic
  in-memory transport slots in where UDP does;
* the device path (``ops``, ``parallel``, ``models``) implements the
  160-bit XOR metric, k-bucket routing construction, and massively
  batched iterative Kademlia lookups as JAX/Pallas kernels over packed
  ``[N, 5] uint32`` id matrices, sharded over a ``jax.sharding.Mesh``.

Reference: sim590/opendht (C++11), see SURVEY.md.
"""

__version__ = "0.1.0"

from .utils.infohash import InfoHash  # noqa: F401
from .utils.sockaddr import SockAddr  # noqa: F401
from .core.value import Value, ValueType, Query, Select, Where  # noqa: F401
