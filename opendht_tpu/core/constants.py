"""Scale-defining constants of the DHT (the system's "model dimensions").

Mirrors the reference's tuning constants so behavior/convergence match:

- TARGET_NODES (k=8): ref include/opendht/routing_table.h:26
- SEARCH_NODES (14): ref include/opendht/dht.h:314
- MAX_REQUESTED_SEARCH_NODES (alpha=4): ref include/opendht/dht.h:327
- request timeout 1 s x 3 attempts: ref include/opendht/node.h:97,
  include/opendht/request.h:113
- rate limits: ref include/opendht/network_engine.h:462,596-600
- storage limits: ref include/opendht/callbacks.h:72, dht.h:333-339
- liveness timings: ref include/opendht/node.h:91-94, dht.h:341-351
"""

# --- Kademlia dimensions ---------------------------------------------------
TARGET_NODES = 8              # k: bucket size / replication factor
SEARCH_NODES = 14             # nodes tracked per search
MAX_REQUESTED_SEARCH_NODES = 4  # alpha: in-flight requests per search
SEARCH_MAX_BAD_NODES = 25     # consecutive expired nodes => connectivity loss

# --- network engine --------------------------------------------------------
MAX_RESPONSE_TIME = 1.0       # seconds per request attempt
MAX_ATTEMPT_COUNT = 3         # retransmits before EXPIRED
BLACKLIST_EXPIRE_TIME = 10 * 60  # misbehaving peers sit out 10 min
# The blacklist is a bounded set of misbehaving peers (SURVEY §4: "LRU
# of misbehaving peers") — a cap keeps an attacker cycling source
# addresses from growing it without bound; soonest-to-expire entries
# are evicted first when full.
MAX_BLACKLIST_SIZE = 1024
MAX_REQUESTS_PER_SEC = 1600   # global inbound rate limit
MAX_REQUESTS_PER_SEC_PER_IP = 200
MAX_PACKET_VALUE_SIZE = 8 * 1024   # larger values are fragmented
MTU = 1280                    # bytes per value part packet
MAX_VALUE_SIZE = 64 * 1024
RX_MAX_PACKET_TIME = 10.0     # total reassembly window
RX_TIMEOUT = 3.0              # inter-part reassembly timeout
MAX_MESSAGE_VALUE_COUNT = 50  # more values than this => header + parts
AGENT = "RNG1"                # wire agent tag, packed as msgpack str (ref src/network_engine.cpp:43)

# --- storage ---------------------------------------------------------------
MAX_STORAGE_SIZE = 64 * 1024 * 1024
MAX_HASHES = 16384
MAX_VALUES = 1024
MAX_SEARCHES = 2048

# --- liveness & maintenance (seconds) --------------------------------------
NODE_GOOD_TIME = 120 * 60     # replied within => good
NODE_EXPIRE_TIME = 10 * 60    # not heard within => dubious
SEARCH_EXPIRE_TIME = 62 * 60
LISTEN_EXPIRE_TIME = 30.0     # remote listener refresh period
REANNOUNCE_MARGIN = 10.0
SEARCH_GET_TIMEOUT = 3.0
SEARCH_RETRY_MIN_INTERVAL = 10.0
MAX_STORAGE_MAINTENANCE_EXPIRE_TIME = 10 * 60
TOKEN_EXPIRE_TIME = 15 * 60   # secret rotation 15-45 min
BOOTSTRAP_RETRY_PERIOD = 10.0
NODE_MAX_AUTH_ERRORS = 3
