"""The stored datum: ``Value``, ``ValueType`` + store/edit policies, and the
Select/Where/Query remote-filtering algebra.

Re-design of the reference's value layer (ref: include/opendht/value.h:55-955,
src/value.cpp).  Wire layout (msgpack field names ``id``/``dat``/``body``/
``sig``/``seq``/``owner``/``to``/``type``/``data``/``utype``) follows the
reference's canonical forms so signatures stay byte-compatible:

* to-sign form:    value.h:424-441 (map of seq/owner/[to]/type/data/[utype])
* to-encrypt form: value.h:443-457 (cypher bin, or map body/[sig])
* wire form:       value.h:459-465 (map id/dat)

The query algebra (Field, FieldValue, Select, Where, Query) mirrors
value.h:556-882: selection (projection of fields) and where-filtering are
executed *remotely* to cut transfer — the moral equivalent of pushing a
gather mask to the device.
"""

from __future__ import annotations

import enum
import random
from typing import Callable, Dict, List, Optional, Sequence

import msgpack

from .constants import MAX_VALUE_SIZE

ValueId = int
INVALID_ID = 0


# ---------------------------------------------------------------------------
# ValueType & policies (ref: value.h:55-106, src/value.cpp:65-69)
# ---------------------------------------------------------------------------

# StorePolicy(key, value, remote_id, from_addr) -> bool
# (key = the InfoHash being stored at — ref value.h:55)
StorePolicy = Callable[[object, "Value", bytes, object], bool]
# EditPolicy(key, old_value, new_value, remote_id, from_addr) -> bool
EditPolicy = Callable[[object, "Value", "Value", bytes, object], bool]


def default_store_policy(key, value: "Value", remote_id, from_addr) -> bool:
    """Accept any value within the size cap (ref: src/value.cpp:65-69).

    Signature mirrors the reference ``StorePolicy(InfoHash key, value,
    remote node id, from addr)`` (value.h:55) — some policies (e.g. the
    certificate type) depend on the storage key.
    """
    return value.size() <= MAX_VALUE_SIZE


def default_edit_policy(key, old_value: "Value", new_value: "Value",
                        remote_id, from_addr) -> bool:
    """Refuse edits by default (ref: value.h:71-73)."""
    return False


class ValueType:
    __slots__ = ("id", "name", "expiration", "store_policy", "edit_policy")

    def __init__(self, type_id: int, name: str, expiration: float,
                 store_policy: StorePolicy = default_store_policy,
                 edit_policy: EditPolicy = default_edit_policy):
        self.id = type_id
        self.name = name
        self.expiration = float(expiration)
        self.store_policy = store_policy
        self.edit_policy = edit_policy

    def __eq__(self, other):
        return isinstance(other, ValueType) and self.id == other.id

    def __hash__(self):
        return hash(self.id)


USER_DATA = ValueType(0, "User Data", 10 * 60)


# ---------------------------------------------------------------------------
# Value (ref: value.h:117-553)
# ---------------------------------------------------------------------------

class Value:
    __slots__ = ("id", "owner", "recipient", "type", "data", "user_type",
                 "seq", "signature", "cypher", "priority")

    def __init__(self, data: bytes = b"", type_id: int = USER_DATA.id,
                 value_id: ValueId = INVALID_ID, user_type: str = ""):
        self.id = value_id
        self.owner = None          # crypto.PublicKey of the signer
        self.recipient = None      # InfoHash or None
        self.type = type_id
        self.data = bytes(data)
        self.user_type = user_type
        self.seq = 0
        self.signature = b""
        self.cypher = b""
        self.priority = 0

    # -- state predicates --------------------------------------------------
    def is_encrypted(self) -> bool:
        return len(self.cypher) > 0

    def is_signed(self) -> bool:
        return self.owner is not None and len(self.signature) > 0

    def size(self) -> int:
        return (len(self.data) + len(self.cypher) + len(self.signature)
                + len(self.user_type) + 16)

    @staticmethod
    def random_id(rng: Optional[random.Random] = None) -> ValueId:
        r = rng.getrandbits(64) if rng else random.getrandbits(64)
        return r or 1

    # -- canonical msgpack forms ------------------------------------------
    def _pack_to_sign(self) -> dict:
        """Map packed for signing — field order matters for byte-compat
        (ref: value.h:424-441)."""
        m: Dict[str, object] = {}
        has_owner = self.owner is not None
        if has_owner:
            m["seq"] = self.seq
            m["owner"] = self.owner.packed()
            if self.recipient:
                m["to"] = bytes(self.recipient)
        m["type"] = self.type
        m["data"] = self.data
        if self.user_type:
            m["utype"] = self.user_type
        return m

    def get_to_sign(self) -> bytes:
        return msgpack.packb(self._pack_to_sign())

    def _pack_to_encrypt(self):
        if self.is_encrypted():
            return self.cypher
        m: Dict[str, object] = {"body": self._pack_to_sign()}
        if self.is_signed():
            m["sig"] = self.signature
        return m

    def get_to_encrypt(self) -> bytes:
        return msgpack.packb(self._pack_to_encrypt())

    def pack(self) -> dict:
        """Full wire form (ref: value.h:459-465)."""
        return {"id": self.id, "dat": self._pack_to_encrypt()}

    def packed(self) -> bytes:
        return msgpack.packb(self.pack())

    # -- unpack ------------------------------------------------------------
    @classmethod
    def unpack(cls, obj) -> "Value":
        """Parse the wire form (ref: src/value.cpp:109-160)."""
        v = cls()
        if not isinstance(obj, dict):
            raise ValueError("bad value wire form")
        v.id = int(obj.get("id", INVALID_ID))
        dat = obj.get("dat", b"")
        v._unpack_body(dat)
        return v

    def _unpack_body(self, dat) -> None:
        if isinstance(dat, (bytes, bytearray)):
            self.cypher = bytes(dat)
            return
        if not isinstance(dat, dict):
            raise ValueError("bad value body")
        body = dat.get("body", {})
        if "sig" in dat:
            self.signature = bytes(dat["sig"])
        if "seq" in body:
            self.seq = int(body["seq"])
        if "owner" in body:
            from ..crypto.identity import PublicKey
            self.owner = PublicKey.from_packed(bytes(body["owner"]))
        if "to" in body:
            from ..utils.infohash import InfoHash
            self.recipient = InfoHash(bytes(body["to"]))
        self.type = int(body.get("type", USER_DATA.id))
        self.data = bytes(body.get("data", b""))
        self.user_type = str(body.get("utype", ""))

    @classmethod
    def from_packed(cls, blob: bytes) -> "Value":
        return cls.unpack(msgpack.unpackb(blob, raw=False, strict_map_key=False))

    # -- partial (fields-only) form (ref: value.h:468-493) ----------------
    def pack_fields(self, fields: Sequence["Field"]) -> list:
        out = []
        for f in sorted(fields, key=lambda x: x.value):
            if f == Field.Id:
                out.append(self.id)
            elif f == Field.ValueType:
                out.append(self.type)
            elif f == Field.OwnerPk:
                out.append(self.owner.packed() if self.owner else b"")
            elif f == Field.SeqNum:
                out.append(self.seq)
            elif f == Field.UserType:
                out.append(self.user_type)
        return out

    def __eq__(self, other):
        if not isinstance(other, Value):
            return False
        if self.id != other.id:
            return False
        if self.is_encrypted() or other.is_encrypted():
            return self.cypher == other.cypher
        owner_eq = (self.owner is None) == (other.owner is None) and (
            self.owner is None or self.owner.get_id() == other.owner.get_id())
        return (owner_eq and self.type == other.type and self.data == other.data
                and self.user_type == other.user_type
                and self.signature == other.signature)

    def __hash__(self):
        return hash((self.id, self.type, self.data, self.user_type))

    def __repr__(self):
        kind = "enc" if self.is_encrypted() else ("sig" if self.is_signed() else "raw")
        return f"Value[id:{self.id:016x} {kind} t:{self.type} {len(self.data)}B]"


# ---------------------------------------------------------------------------
# Filters (ref: value.h:133-173)
# ---------------------------------------------------------------------------

Filter = Callable[[Value], bool]


def f_true(_v: Value) -> bool:
    return True


def f_chain_and(a: Optional[Filter], b: Optional[Filter]) -> Filter:
    if not a:
        return b or f_true
    if not b:
        return a
    return lambda v: a(v) and b(v)


def f_value_type(tid: int) -> Filter:
    return lambda v: v.type == tid


def f_owner(owner_id) -> Filter:
    return lambda v: v.owner is not None and v.owner.get_id() == owner_id


def f_recipient(rcpt) -> Filter:
    return lambda v: v.recipient == rcpt


def f_user_type(ut: str) -> Filter:
    return lambda v: v.user_type == ut


def f_id(vid: ValueId) -> Filter:
    return lambda v: v.id == vid


def f_seq(seq: int) -> Filter:
    return lambda v: v.seq == seq


# ---------------------------------------------------------------------------
# Query algebra (ref: value.h:556-882)
# ---------------------------------------------------------------------------

class Field(enum.IntEnum):
    Nothing = 0
    Id = 1
    ValueType = 2
    OwnerPk = 3
    SeqNum = 4
    UserType = 5


class FieldValue:
    """A (field, value) equality constraint (ref: value.h:556-639)."""

    __slots__ = ("field", "int_value", "hash_value", "blob_value")

    def __init__(self, field: Field = Field.Nothing, value=None):
        self.field = Field(field)
        self.int_value = 0
        self.hash_value = None
        self.blob_value = b""
        if field in (Field.Id, Field.ValueType, Field.SeqNum):
            self.int_value = int(value)
        elif field == Field.OwnerPk:
            self.hash_value = value
        elif field == Field.UserType:
            self.blob_value = value.encode() if isinstance(value, str) else bytes(value)

    def get_local_filter(self) -> Filter:
        """ref: src/value.cpp:184-200"""
        if self.field == Field.Id:
            return f_id(self.int_value)
        if self.field == Field.ValueType:
            return f_value_type(self.int_value)
        if self.field == Field.SeqNum:
            return f_seq(self.int_value)
        if self.field == Field.OwnerPk:
            return f_owner(self.hash_value)
        if self.field == Field.UserType:
            return f_user_type(self.blob_value.decode())
        return f_true

    def pack(self):
        """Wire form {"f": field, "v": value}
        (ref FieldValue::msgpack_pack value.h:572-590)."""
        if self.field in (Field.Id, Field.ValueType, Field.SeqNum):
            return {"f": int(self.field), "v": self.int_value}
        if self.field == Field.OwnerPk:
            return {"f": int(self.field), "v": bytes(self.hash_value)}
        if self.field == Field.UserType:
            return {"f": int(self.field), "v": self.blob_value}
        return {"f": int(self.field), "v": None}

    @classmethod
    def unpack(cls, obj) -> "FieldValue":
        if isinstance(obj, dict):
            field, raw = Field(obj["f"]), obj.get("v")
        else:  # legacy [field, value] pair
            field, raw = Field(obj[0]), obj[1]
        if field == Field.OwnerPk:
            from ..utils.infohash import InfoHash
            return cls(field, InfoHash(bytes(raw)))
        if field == Field.UserType:
            return cls(field, bytes(raw))
        if field == Field.Nothing:
            return cls()
        return cls(field, int(raw))

    def __eq__(self, other):
        return (isinstance(other, FieldValue) and self.field == other.field
                and self.int_value == other.int_value
                and self.hash_value == other.hash_value
                and self.blob_value == other.blob_value)


class Select:
    """Projection: which fields to return (ref: value.h:664-712)."""

    def __init__(self, fields: Sequence[Field] = ()):
        self.fields: List[Field] = sorted(set(Field(f) for f in fields))

    def field(self, f: Field) -> "Select":
        if f not in self.fields:
            self.fields.append(f)
            self.fields.sort()
        return self

    def is_satisfied_by(self, other: "Select") -> bool:
        """True if a reply to ``other`` contains every field we select
        (ref: Select::isSatisfiedBy src/value.cpp:411-417): our selection
        must be a subset of theirs; empty = select-all can only be
        satisfied by another select-all."""
        if not self.fields and other.fields:
            return False
        return set(self.fields) <= set(other.fields) or not self.fields

    def pack(self):
        return [int(f) for f in self.fields]

    @classmethod
    def unpack(cls, obj) -> "Select":
        return cls([Field(x) for x in (obj or [])])

    def __bool__(self):
        return bool(self.fields)

    def __eq__(self, other):
        return isinstance(other, Select) and self.fields == other.fields


class Where:
    """Conjunction of equality constraints (ref: value.h:715-816)."""

    def __init__(self, filters: Sequence[FieldValue] = ()):
        self.filters: List[FieldValue] = list(filters)

    def id(self, vid: ValueId) -> "Where":
        self.filters.append(FieldValue(Field.Id, vid))
        return self

    def value_type(self, tid: int) -> "Where":
        self.filters.append(FieldValue(Field.ValueType, tid))
        return self

    def owner(self, owner_id) -> "Where":
        self.filters.append(FieldValue(Field.OwnerPk, owner_id))
        return self

    def seq(self, s: int) -> "Where":
        self.filters.append(FieldValue(Field.SeqNum, s))
        return self

    def user_type(self, ut: str) -> "Where":
        self.filters.append(FieldValue(Field.UserType, ut))
        return self

    def get_filter(self) -> Filter:
        f: Optional[Filter] = None
        for fv in self.filters:
            f = f_chain_and(f, fv.get_local_filter())
        return f or f_true

    def is_satisfied_by(self, other: "Where") -> bool:
        """True if ``other``'s constraints are a subset of ours — i.e. a
        reply filtered by ``other`` includes everything matching us
        (ref: Where::isSatisfiedBy src/value.cpp:419-421)."""
        ours = [fv.pack() for fv in self.filters]
        theirs = [fv.pack() for fv in other.filters]
        return all(c in ours for c in theirs)

    def pack(self):
        return [fv.pack() for fv in self.filters]

    @classmethod
    def unpack(cls, obj) -> "Where":
        return cls([FieldValue.unpack(x) for x in (obj or [])])

    def __bool__(self):
        return bool(self.filters)

    def __eq__(self, other):
        return isinstance(other, Where) and self.filters == other.filters


class FieldValueIndex:
    """A partial value: projection of selected fields
    (ref: value.h:883-900, src/value.cpp FieldValueIndex)."""

    __slots__ = ("index",)

    def __init__(self, value: Optional[Value] = None,
                 select: Optional["Select"] = None):
        self.index: Dict[Field, object] = {}
        if value is None:
            return
        fields = (select.fields if select and select.fields
                  else [Field.Id, Field.ValueType, Field.OwnerPk,
                        Field.SeqNum, Field.UserType])
        for f in fields:
            if f == Field.Id:
                self.index[f] = value.id
            elif f == Field.ValueType:
                self.index[f] = value.type
            elif f == Field.OwnerPk:
                self.index[f] = (value.owner.get_id() if value.owner else None)
            elif f == Field.SeqNum:
                self.index[f] = value.seq
            elif f == Field.UserType:
                self.index[f] = value.user_type

    @classmethod
    def from_fields(cls, fields: Sequence[Field], row: Sequence
                    ) -> "FieldValueIndex":
        fvi = cls()
        for f, v in zip(fields, row):
            fvi.index[Field(f)] = v
        return fvi

    def contained_in(self, other: "FieldValueIndex") -> bool:
        """True if every (field, value) here also appears in ``other``."""
        return all(other.index.get(f) == v for f, v in self.index.items())

    def __eq__(self, other):
        return isinstance(other, FieldValueIndex) and self.index == other.index

    def __repr__(self):
        return f"FieldValueIndex({self.index})"


class Query:
    """SELECT <fields> WHERE <constraints> (ref: value.h:819-880)."""

    __slots__ = ("select", "where", "none")

    def __init__(self, select: Optional[Select] = None,
                 where: Optional[Where] = None, q: str = ""):
        self.select = select or Select()
        self.where = where or Where()
        self.none = False
        if q:
            self._parse(q)

    def _parse(self, q: str) -> None:
        """Minimal SQL-ish parser (ref: value.h:838-849 ctor)."""
        toks = q.replace(",", " ").split()
        mode = None
        for tok in toks:
            up = tok.upper()
            if up == "SELECT":
                mode = "select"
            elif up == "WHERE":
                mode = "where"
            elif mode == "select":
                if up == "*":
                    continue
                name = {"ID": Field.Id, "VALUE_TYPE": Field.ValueType,
                        "OWNER_PK": Field.OwnerPk, "SEQ": Field.SeqNum,
                        "USER_TYPE": Field.UserType}.get(up)
                if name:
                    self.select.field(name)
            elif mode == "where" and "=" in tok:
                k, _, val = tok.partition("=")
                ku = k.upper()
                if ku == "ID":
                    self.where.id(int(val, 0))
                elif ku == "VALUE_TYPE":
                    self.where.value_type(int(val, 0))
                elif ku == "SEQ":
                    self.where.seq(int(val, 0))
                elif ku == "USER_TYPE":
                    self.where.user_type(val.strip("'\""))

    def is_satisfied_by(self, other: "Query") -> bool:
        """Would ``other``'s reply satisfy us?
        (ref: Query::isSatisfiedBy src/value.cpp:423-425)"""
        return self.none or (self.where.is_satisfied_by(other.where)
                             and self.select.is_satisfied_by(other.select))

    def pack(self):
        return {"s": self.select.pack(), "w": self.where.pack()}

    @classmethod
    def unpack(cls, obj) -> "Query":
        if not obj:
            return cls()
        return cls(Select.unpack(obj.get("s")), Where.unpack(obj.get("w")))

    def __bool__(self):
        return bool(self.select) or bool(self.where)

    def __eq__(self, other):
        return (isinstance(other, Query) and self.select == other.select
                and self.where == other.where)
