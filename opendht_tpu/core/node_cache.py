"""Global node identity cache.

Re-design of the reference ``NodeCache`` (ref: include/opendht/node_cache.h:
29-51, src/node_cache.cpp): one weakly-referenced ``Node`` object per
(id, address family), deduplicating node identity across routing-table
buckets and searches so liveness state is shared.  ``get_cached_nodes`` is
an XOR-closest walk outward from the target id over the sorted key space
(src/node_cache.cpp:36-66); ``clear_bad_nodes`` resets expiry flags on a
connectivity change (src/node_cache.cpp:68-77).
"""

from __future__ import annotations

import bisect
import weakref
from typing import List, Optional

from ..utils.infohash import InfoHash
from ..utils.sockaddr import AF_INET, AF_INET6, SockAddr
from .node import Node


class _FamilyCache:
    def __init__(self):
        self._map: "weakref.WeakValueDictionary[bytes, Node]" = \
            weakref.WeakValueDictionary()
        self._keys: List[bytes] = []   # sorted id bytes (lazily pruned)

    def get(self, nid: InfoHash) -> Optional[Node]:
        return self._map.get(bytes(nid))

    def get_node(self, nid: InfoHash, addr: SockAddr) -> Node:
        key = bytes(nid)
        n = self._map.get(key)
        if n is None:
            n = Node(nid, addr)
            self._map[key] = n
            i = bisect.bisect_left(self._keys, key)
            if i >= len(self._keys) or self._keys[i] != key:
                self._keys.insert(i, key)
        return n

    def closest(self, nid: InfoHash, count: int) -> List[Node]:
        self._keys = [k for k in self._keys if k in self._map]
        if not self._keys:
            return []
        start = bisect.bisect_left(self._keys, bytes(nid))
        lo, hi = start - 1, start
        out: List[Node] = []
        while len(out) < count and (lo >= 0 or hi < len(self._keys)):
            n_hi = self._map.get(self._keys[hi]) if hi < len(self._keys) else None
            n_lo = self._map.get(self._keys[lo]) if lo >= 0 else None
            if n_hi is not None and (
                    n_lo is None
                    or InfoHash.xor_cmp(n_hi.id, n_lo.id, nid) <= 0):
                pick, hi = n_hi, hi + 1
            elif n_lo is not None:
                pick, lo = n_lo, lo - 1
            else:
                # dead weakrefs on both sides: advance past them
                if hi < len(self._keys):
                    hi += 1
                if lo >= 0:
                    lo -= 1
                continue
            if not pick.is_expired():
                out.append(pick)
        return out

    def clear_bad(self) -> None:
        for n in list(self._map.values()):
            n.reset_expired()


class NodeCache:
    def __init__(self):
        self._c4 = _FamilyCache()
        self._c6 = _FamilyCache()

    def _fam(self, af: int) -> _FamilyCache:
        return self._c4 if af == AF_INET else self._c6

    def get_node(self, nid: InfoHash, addr: SockAddr) -> Node:
        """Find-or-create the canonical Node for (id, af)."""
        return self._fam(addr.family).get_node(nid, addr)

    def find(self, nid: InfoHash, af: int) -> Optional[Node]:
        return self._fam(af).get(nid)

    def get_cached_nodes(self, nid: InfoHash, af: int, count: int) -> List[Node]:
        return self._fam(af).closest(nid, count)

    def clear_bad_nodes(self, af: int = 0) -> None:
        if af in (0, AF_INET):
            self._c4.clear_bad()
        if af in (0, AF_INET6):
            self._c6.clear_bad()
