"""Time-ordered job scheduler — the single-threaded event loop heart.

Re-design of the reference scheduler (ref: include/opendht/scheduler.h:38-123):
a time-ordered queue of closures; ``run()`` executes everything due and
returns the next wakeup time.  The reference uses a ``multimap``; we use a
lazy-deletion binary heap (cancelled/edited jobs are skipped on pop), which
keeps ``edit`` O(log n) instead of O(n).

The scheduler is clock-agnostic (see :mod:`opendht_tpu.utils.clock`) so the
same core logic runs under real time, virtual test time, and the quantized
lock-step time of the TPU swarm simulator.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, Optional

from ..utils.clock import Clock, SteadyClock, TIME_MAX


class Job:
    __slots__ = ("fn", "time", "_cancelled")

    def __init__(self, fn: Optional[Callable[[], None]], t: float):
        self.fn = fn
        self.time = t
        self._cancelled = fn is None

    def cancel(self) -> None:
        self._cancelled = True
        self.fn = None

    @property
    def active(self) -> bool:
        return not self._cancelled


class Scheduler:
    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or SteadyClock()
        self._heap: list = []
        self._seq = itertools.count()
        self._now = self.clock.now()

    # -- time --------------------------------------------------------------
    def time(self) -> float:
        """Scheduler time: frozen during a run() pass (ref: scheduler.h:82)."""
        return self._now

    def sync_time(self) -> float:
        self._now = self.clock.now()
        return self._now

    # -- jobs --------------------------------------------------------------
    def add(self, t: float, fn: Callable[[], None]) -> Job:
        job = Job(fn, t)
        heapq.heappush(self._heap, (t, next(self._seq), job))
        return job

    def run_soon(self, fn: Callable[[], None]) -> Job:
        return self.add(self._now, fn)

    def edit(self, job: Optional[Job], t: float) -> Optional[Job]:
        """Move a job to a new time (ref: scheduler.h:63-80).

        The old heap entry is abandoned (lazy deletion); the returned Job is
        the live handle.
        """
        if job is None or not job.active:
            return job
        fn = job.fn
        job.cancel()
        return self.add(t, fn)

    # -- loop --------------------------------------------------------------
    def run(self) -> float:
        """Run all due jobs; return the next wakeup time (ref: scheduler.h:87-106)."""
        self.sync_time()
        while self._heap:
            t, _, job = self._heap[0]
            if not job.active:
                heapq.heappop(self._heap)
                continue
            if t > self._now:
                break
            heapq.heappop(self._heap)
            fn = job.fn
            job.cancel()
            if fn is not None:
                fn()
        return self.next_wakeup()

    def next_wakeup(self) -> float:
        while self._heap and not self._heap[0][2].active:
            heapq.heappop(self._heap)
        return self._heap[0][0] if self._heap else TIME_MAX

    def pending(self) -> int:
        return sum(1 for _, _, j in self._heap if j.active)
