"""Built-in value types 0-8.

Re-design of the reference default types (ref:
include/opendht/default_types.h, src/default_types.cpp:86-106 type table):

* 0 USER_DATA      — plain user bytes, 10 min TTL
* 1 DhtMessage     — service messages, 5 min TTL
* 2 IpServiceAnnouncement — service endpoint; store policy rewrites the
  stored address to the sender's observed address (src/default_types.cpp:70-84)
* 3 ImMessage      — instant messages (used by dhtchat)
* 4 TrustRequest
* 5 IceCandidates
* 8 CERTIFICATE    — 7-day TTL, only storable at its own key id
  (ref: include/opendht/securedht.h:166-183)
"""

from __future__ import annotations

import msgpack

from ..utils.sockaddr import SockAddr
from .value import USER_DATA, Value, ValueType, default_store_policy


class DhtMessage:
    TYPE = ValueType(1, "DHT message", 5 * 60)

    def __init__(self, service: str = "", data: bytes = b""):
        self.service = service
        self.data = data

    def pack(self) -> bytes:
        return msgpack.packb({"s": self.service, "d": self.data})

    @classmethod
    def unpack(cls, blob: bytes) -> "DhtMessage":
        o = msgpack.unpackb(blob, raw=False)
        return cls(o.get("s", ""), bytes(o.get("d", b"")))


def _ip_service_store_policy(key, value: Value, remote_id, from_addr) -> bool:
    """Rewrite announced address to the sender's observed address
    (ref: src/default_types.cpp:70-84)."""
    if not default_store_policy(key, value, remote_id, from_addr):
        return False
    try:
        ann = IpServiceAnnouncement.unpack(value.data)
    except Exception:
        return False
    if not ann.addr.host and isinstance(from_addr, SockAddr):
        ann.addr = SockAddr(from_addr.host, ann.addr.port or from_addr.port)
        value.data = ann.pack()
    return True


class IpServiceAnnouncement:
    TYPE = ValueType(2, "Internet Service Announcement", 15 * 60,
                     store_policy=_ip_service_store_policy)

    def __init__(self, addr: SockAddr = None):
        self.addr = addr or SockAddr()

    def pack(self) -> bytes:
        return msgpack.packb({"h": self.addr.host, "p": self.addr.port})

    @classmethod
    def unpack(cls, blob: bytes) -> "IpServiceAnnouncement":
        o = msgpack.unpackb(blob, raw=False)
        return cls(SockAddr(o.get("h", ""), o.get("p", 0)))


class ImMessage:
    TYPE = ValueType(3, "IM message", 100 * 24 * 3600)

    def __init__(self, msg_id: int = 0, message: str = "", date: int = 0):
        self.id = msg_id
        self.message = message
        self.date = date

    def pack(self) -> bytes:
        return msgpack.packb({"id": self.id, "im": self.message,
                              "d": self.date})

    @classmethod
    def unpack(cls, blob: bytes) -> "ImMessage":
        o = msgpack.unpackb(blob, raw=False, strict_map_key=False)
        return cls(o.get("id", 0), o.get("im", ""), o.get("d", 0))


TRUST_REQUEST = ValueType(4, "Certificate trust request", 100 * 24 * 3600)
ICE_CANDIDATES = ValueType(5, "ICE candidates", 10 * 60)
CERTIFICATE_TYPE_ID = 8


class TrustRequest:
    """Connectivity/trust handshake payload
    (ref: include/opendht/default_types.h:105-140)."""

    TYPE = TRUST_REQUEST

    def __init__(self, service: str = "", payload: bytes = b"",
                 confirm: bool = False):
        self.service = service
        self.payload = bytes(payload)
        self.confirm = confirm

    def pack(self) -> bytes:
        return msgpack.packb({"s": self.service, "d": self.payload,
                              "c": self.confirm})

    @classmethod
    def unpack(cls, blob: bytes) -> "TrustRequest":
        o = msgpack.unpackb(blob, raw=False, strict_map_key=False)
        return cls(o.get("s", ""), bytes(o.get("d", b"")),
                   bool(o.get("c", False)))


class IceCandidates:
    """ICE negotiation blob (ref: default_types.h:142-180)."""

    TYPE = ICE_CANDIDATES

    def __init__(self, msg_id: int = 0, ice_data: bytes = b""):
        self.id = msg_id
        self.ice_data = bytes(ice_data)

    def pack(self) -> bytes:
        return msgpack.packb({"id": self.id, "ice": self.ice_data})

    @classmethod
    def unpack(cls, blob: bytes) -> "IceCandidates":
        o = msgpack.unpackb(blob, raw=False, strict_map_key=False)
        return cls(o.get("id", 0), bytes(o.get("ice", b"")))

DEFAULT_TYPES = [
    USER_DATA,
    DhtMessage.TYPE,
    IpServiceAnnouncement.TYPE,
    ImMessage.TYPE,
    TRUST_REQUEST,
    ICE_CANDIDATES,
]
