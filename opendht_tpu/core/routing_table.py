"""Kademlia routing table: ordered list of k-buckets.

Re-design of the reference routing table (ref:
include/opendht/routing_table.h:26-79, src/routing_table.cpp).  Buckets are
kept sorted by their ``first`` prefix id; a bucket covers the id range
[first, next.first).  Each holds up to ``TARGET_NODES`` (k=8) nodes plus one
cached replacement candidate.  ``find_closest_nodes`` walks outward from the
home bucket, XOR-merge-sorting good nodes (src/routing_table.cpp:67-111).

This is the host-side, event-driven implementation; the device-resident
batched equivalent lives in :mod:`opendht_tpu.parallel.routing_build` (the
same k-bucket semantics built as one vectorized pass over sorted ids).
"""

from __future__ import annotations

import random
from typing import List, Optional

from ..utils.clock import TIME_INVALID
from ..utils.infohash import HASH_BITS, HASH_LEN, InfoHash
from .constants import TARGET_NODES
from .node import Node


class Bucket:
    __slots__ = ("af", "first", "time", "nodes", "cached")

    def __init__(self, af: int, first: InfoHash, time: float = TIME_INVALID):
        self.af = af
        self.first = first
        self.time = time            # last time bucket was confirmed active
        self.nodes: List[Node] = []
        self.cached: Optional[Node] = None  # replacement candidate

    def contains(self, nid: InfoHash) -> bool:
        return any(n.id == nid for n in self.nodes)

    def find(self, nid: InfoHash) -> Optional[Node]:
        for n in self.nodes:
            if n.id == nid:
                return n
        return None

    def random_node(self, rng: Optional[random.Random] = None) -> Optional[Node]:
        if not self.nodes:
            return None
        return (rng or random).choice(self.nodes)


class RoutingTable:
    def __init__(self, af: int):
        self.af = af
        self.buckets: List[Bucket] = [Bucket(af, InfoHash.zero())]
        self.grow_time = TIME_INVALID

    def __len__(self):
        return len(self.buckets)

    def __iter__(self):
        return iter(self.buckets)

    def is_empty(self) -> bool:
        return len(self.buckets) == 1 and not self.buckets[0].nodes

    # -- bucket lookup (ref: src/routing_table.cpp:113-127) ----------------
    def find_bucket_index(self, nid: InfoHash) -> int:
        lo, hi = 0, len(self.buckets) - 1
        # binary search: last bucket with first <= id
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if InfoHash.cmp(nid, self.buckets[mid].first) < 0:
                hi = mid - 1
            else:
                lo = mid
        return lo

    def find_bucket(self, nid: InfoHash) -> Bucket:
        return self.buckets[self.find_bucket_index(nid)]

    # -- geometry (ref: src/routing_table.cpp:27-66) -----------------------
    def depth(self, idx: int) -> int:
        b = self.buckets[idx]
        bit1 = b.first.lowbit()
        bit2 = (self.buckets[idx + 1].first.lowbit()
                if idx + 1 < len(self.buckets) else -1)
        return max(bit1, bit2) + 1

    def middle(self, idx: int) -> InfoHash:
        bit = self.depth(idx)
        if bit >= HASH_BITS:
            raise IndexError("bucket not splittable")
        return self.buckets[idx].first.set_bit(bit, True)

    def random_id(self, idx: int, rng: Optional[random.Random] = None) -> InfoHash:
        """Random id inside the bucket's range (ref: routing_table.cpp:27-45)."""
        r = rng or random
        b = self.buckets[idx]
        bit = self.depth(idx)
        if bit >= HASH_BITS:
            return b.first
        byte_i = bit // 8
        out = bytearray(bytes(b.first))
        rb = r.getrandbits(8)
        out[byte_i] = (out[byte_i] & (0xFF00 >> (bit % 8)) & 0xFF) | (rb >> (bit % 8))
        for i in range(byte_i + 1, HASH_LEN):
            out[i] = r.getrandbits(8)
        return InfoHash(bytes(out))

    # -- split (ref: src/routing_table.cpp:139-163) ------------------------
    def split(self, idx: int) -> bool:
        try:
            new_first = self.middle(idx)
        except IndexError:
            return False
        b = self.buckets[idx]
        nb = Bucket(self.af, new_first, b.time)
        self.buckets.insert(idx + 1, nb)
        nodes = b.nodes
        b.nodes = []
        for n in nodes:
            self.find_bucket(n.id).nodes.insert(0, n)
        return True

    # -- closest nodes (ref: src/routing_table.cpp:67-111) -----------------
    def find_closest_nodes(self, nid: InfoHash, now: float,
                           count: int = TARGET_NODES) -> List[Node]:
        out: List[Node] = []

        def insert_bucket(b: Bucket) -> None:
            for n in b.nodes:
                if not n.is_good(now):
                    continue
                i = 0
                while i < len(out) and InfoHash.xor_cmp(out[i].id, n.id, nid) < 0:
                    i += 1
                out.insert(i, n)

        home = self.find_bucket_index(nid)
        lo, hi = home - 1, home
        while len(out) < count and (hi < len(self.buckets) or lo >= 0):
            if hi < len(self.buckets):
                insert_bucket(self.buckets[hi])
                hi += 1
            if lo >= 0:
                insert_bucket(self.buckets[lo])
                lo -= 1
        return out[:count]

    # -- stats -------------------------------------------------------------
    def all_nodes(self) -> List[Node]:
        return [n for b in self.buckets for n in b.nodes]

    def node_count(self) -> int:
        return sum(len(b.nodes) for b in self.buckets)
