"""Per-peer liveness state.

Re-design of the reference ``Node`` (ref: include/opendht/node.h:35-112,
src/node.cpp): tracks when a peer was last heard from / last replied,
pending requests, and auth errors.  Liveness policy (src/node.cpp:34-40):
good = replied within 120 min AND heard within 10 min; 3 unanswered request
attempts or 3 auth errors expire the node.
"""

from __future__ import annotations

import weakref
from typing import Optional

from ..utils.clock import TIME_INVALID
from ..utils.infohash import InfoHash
from ..utils.sockaddr import SockAddr
from .constants import NODE_EXPIRE_TIME, NODE_GOOD_TIME, NODE_MAX_AUTH_ERRORS


class Node:
    __slots__ = ("id", "addr", "time", "reply_time", "_expired",
                 "auth_errors", "_requests", "__weakref__")

    def __init__(self, node_id: InfoHash, addr: SockAddr):
        self.id = node_id
        self.addr = addr
        self.time = TIME_INVALID        # last time heard from (any packet)
        self.reply_time = TIME_INVALID  # last time we got a reply
        self._expired = False
        self.auth_errors = 0
        # tid -> weak Request; pending request bookkeeping (node.h:74-97)
        self._requests: dict = {}

    @property
    def family(self) -> int:
        return self.addr.family

    # -- liveness (ref: src/node.cpp:34-50) --------------------------------
    def is_expired(self) -> bool:
        return self._expired

    def is_good(self, now: float) -> bool:
        return (not self._expired
                and self.reply_time >= now - NODE_GOOD_TIME
                and self.time >= now - NODE_EXPIRE_TIME)

    def is_pending_message(self) -> bool:
        return any(r is not None and r.pending() for r in self._iter_requests())

    def is_message_pending(self) -> bool:
        return self.is_pending_message()

    # -- events ------------------------------------------------------------
    def update(self, new_addr: SockAddr) -> None:
        self.addr = new_addr

    def received(self, now: float, req=None) -> None:
        """Packet received from this node (ref: src/node.cpp:52-72)."""
        self.time = now
        self._expired = False
        if req is not None:
            self.reply_time = now
            self._requests.pop(req.tid, None)

    def requested(self, req) -> None:
        self._requests[req.tid] = weakref.ref(req)

    def request_expired(self, req) -> None:
        self._requests.pop(req.tid, None)

    def set_expired(self) -> None:
        """Mark expired and cancel pending requests (ref: src/node.cpp:99-109)."""
        self._expired = True
        for r in list(self._iter_requests()):
            if r is not None:
                r.set_expired()
        self._requests.clear()

    def reset_expired(self) -> None:
        """Clear the expired flag after a connectivity change
        (ref: NodeCache::clearBadNodes src/node_cache.cpp:68-77)."""
        self._expired = False
        self.auth_errors = 0

    def auth_error(self) -> None:
        self.auth_errors += 1
        if self.auth_errors >= NODE_MAX_AUTH_ERRORS:
            self.set_expired()

    def auth_success(self) -> None:
        self.auth_errors = 0

    def _iter_requests(self):
        for ref in list(self._requests.values()):
            yield ref()

    def get_request(self, tid: int):
        ref = self._requests.get(tid)
        return ref() if ref is not None else None

    def __repr__(self):
        return f"Node[{str(self.id)[:8]} {self.addr}]"
