"""Per-hash value storage with local and remote listeners.

Re-design of the reference storage layer (ref: src/dht.cpp:110-209 structs,
2227-2380 store/expire): each tracked hash owns a list of stored values
(with creation times), the set of remote listeners (per node, per listen
socket id) to notify on change, and local listener callbacks.  Global
accounting (64 MB / 16384 hashes / 1024 values) lives in the Dht.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..core.value import Filter, Query, Value
from ..utils.clock import TIME_INVALID


class ValueStorage:
    __slots__ = ("value", "created")

    def __init__(self, value: Value, created: float):
        self.value = value
        self.created = created


class RemoteListener:
    """A remote node listening on this hash via a socket id
    (ref: Listener src/dht.cpp:152-163)."""

    __slots__ = ("socket_id", "time", "query")

    def __init__(self, socket_id: bytes, time: float, query: Query):
        self.socket_id = socket_id
        self.time = time
        self.query = query

    def refresh(self, socket_id: bytes, time: float, query: Query) -> None:
        self.socket_id = socket_id
        self.time = time
        self.query = query


class LocalListener:
    """A local callback listening on this hash
    (ref: LocalListener src/dht.cpp:165-169)."""

    __slots__ = ("query", "filter", "get_cb")

    def __init__(self, query: Optional[Query], filter: Optional[Filter],
                 get_cb: Callable):
        self.query = query
        self.filter = filter
        self.get_cb = get_cb


class Storage:
    """Values stored at one hash (ref: struct Storage src/dht.cpp:171-242)."""

    __slots__ = ("values", "listeners", "local_listeners", "listener_token",
                 "maintenance_time", "total_size")

    def __init__(self, now: float):
        self.values: List[ValueStorage] = []
        # node -> {socket_id: RemoteListener}
        self.listeners: Dict[object, Dict[bytes, RemoteListener]] = {}
        self.local_listeners: Dict[int, LocalListener] = {}
        self.listener_token = 0
        self.maintenance_time = now
        self.total_size = 0

    def is_empty(self) -> bool:
        return not self.values

    def value_count(self) -> int:
        return len(self.values)

    def get(self, f: Optional[Filter] = None) -> List[Value]:
        if f is None:
            return [vs.value for vs in self.values]
        return [vs.value for vs in self.values if f(vs.value)]

    def get_by_id(self, vid: int) -> Optional[Value]:
        for vs in self.values:
            if vs.value.id == vid:
                return vs.value
        return None

    def store(self, value: Value, created: float, size_left: int
              ) -> Tuple[Optional[ValueStorage], int, int]:
        """Insert or replace; returns (stored, size_diff, count_diff)
        (ref: Storage::store src/dht.cpp:2260-2287)."""
        from .constants import MAX_VALUES
        for vs in self.values:
            if vs.value is value or vs.value.id == value.id:
                vs.created = created
                size_diff = value.size() - vs.value.size()
                if size_diff <= size_left and vs.value is not value:
                    vs.value = value
                    self.total_size += size_diff
                    return vs, size_diff, 0
                return (vs if vs.value is value else None), 0, 0
        size = value.size()
        if size <= size_left and len(self.values) < MAX_VALUES:
            vs = ValueStorage(value, created)
            self.values.append(vs)
            self.total_size += size
            return vs, size, 1
        return None, 0, 0

    def refresh(self, now: float, vid: int) -> bool:
        """Reset a value's creation time (ref: Storage::refresh)."""
        for vs in self.values:
            if vs.value.id == vid:
                vs.created = now
                return True
        return False

    def expire(self, get_type, now: float) -> Tuple[int, int, List[Value]]:
        """Drop expired values; returns (size_diff, count_diff, expired)
        (ref: Storage::expire src/dht.cpp:2361-2381)."""
        keep, dropped = [], []
        for vs in self.values:
            t = get_type(vs.value.type)
            if vs.created + t.expiration < now:
                dropped.append(vs.value)
            else:
                keep.append(vs)
        size_diff = -sum(v.size() for v in dropped)
        self.values = keep
        self.total_size += size_diff
        return size_diff, -len(dropped), dropped

    def clear(self) -> Tuple[int, int]:
        n, sz = len(self.values), self.total_size
        self.values = []
        self.total_size = 0
        return -sz, -n
