"""The DHT core: searches, storage, listeners, maintenance.

Re-design of the reference ``class Dht`` (ref: src/dht.cpp, 3436 LoC;
include/opendht/dht.h:55-302).  The behavioral spec is preserved —
iterative Kademlia lookups with α=4 solicitation over a 14-node search set,
8-node sync quorum, announce-with-probe, listen refresh, write tokens,
bucket/neighbourhood maintenance, connectivity-loss detection — while the
structure is an explicit state machine over plain data, so the same spec is
shared with the lock-step TPU swarm engine
(:mod:`opendht_tpu.parallel.swarm`), which vectorizes this per-search state
over millions of concurrent searches.

Key behavior pointers into the reference:

* SearchNode status logic: src/dht.cpp:244-461
* Search container + sync/done predicates: :467-713, 1466-1645
* insertNode sorted-merge with bad-node trimming: :961-1047
* searchStep: :1343-1464
* searchSendGetValues / searchSendAnnounceValue: :1170-1341
* storage + change notification + tokens: :2186-2467
* bucket maintenance / confirmNodes / expire: :2791-3030
* RPC handlers: :3180-3434
"""

from __future__ import annotations

import hashlib
import os
import random
from typing import Callable, Dict, List, Optional, Tuple

import msgpack

from ..net.network_engine import (DhtProtocolException, NetworkEngine,
                                  RequestAnswer)
from ..net.request import Request
from ..net.wire import WANT4, WANT6
from ..utils.clock import TIME_INVALID, TIME_MAX
from ..utils.infohash import HASH_LEN, InfoHash
from ..utils.logger import NONE, Logger
from ..utils.metrics import MetricsRegistry
from ..utils.sockaddr import AF_INET, AF_INET6, SockAddr
from .constants import (LISTEN_EXPIRE_TIME, MAX_HASHES, MAX_SEARCHES,
                        MAX_STORAGE_MAINTENANCE_EXPIRE_TIME, MAX_STORAGE_SIZE,
                        MAX_REQUESTED_SEARCH_NODES, NODE_EXPIRE_TIME,
                        REANNOUNCE_MARGIN, SEARCH_EXPIRE_TIME,
                        SEARCH_MAX_BAD_NODES, SEARCH_NODES, TARGET_NODES)
from .node import Node
from .node_cache import NodeCache
from .routing_table import Bucket, RoutingTable
from .scheduler import Scheduler
from .storage import LocalListener, RemoteListener, Storage
from .value import (Field, FieldValueIndex, Filter, Query, Select, Value, Where,
                    ValueType, USER_DATA, f_chain_and)

LISTEN_NODES = 4  # ref: include/opendht/dht.h:330
TOKEN_SIZE = 64

# callback signatures
GetCallback = Callable[[List[Value]], bool]
QueryCallback = Callable[[List[FieldValueIndex]], bool]
DoneCallback = Callable[[bool, List[Node]], None]


def qkey(query: Optional[Query]) -> bytes:
    """Canonical dict key for a query (reference keys status maps by
    shared_ptr identity + isSatisfiedBy scans; we key by canonical bytes)."""
    if query is None:
        return b"\x00find"
    return msgpack.packb(query.pack())


PROBE_QUERY = Query(Select([Field.Id, Field.SeqNum]))
PROBE_QKEY = qkey(PROBE_QUERY)


class DhtConfig:
    __slots__ = ("node_id", "network", "is_bootstrap", "maintain_storage")

    def __init__(self, node_id: Optional[InfoHash] = None, network: int = 0,
                 is_bootstrap: bool = False, maintain_storage: bool = False):
        self.node_id = node_id
        self.network = network
        self.is_bootstrap = is_bootstrap
        self.maintain_storage = maintain_storage


class NodeStatus:
    Disconnected = "disconnected"
    Connecting = "connecting"
    Connected = "connected"


class NodeStats:
    """Snapshot of one address family's node health + this node's
    search/storage load — the reference's ``NodeStats`` struct
    (returned by ``getNodesStats``, ref src/dht.cpp:2469-2495) grown
    with the search and storage counters the reference reports through
    separate log dumps.
    """

    __slots__ = ("good_nodes", "dubious_nodes", "cached_nodes",
                 "incoming_nodes", "searches", "storage_keys",
                 "storage_values", "storage_bytes")

    def __init__(self, good_nodes: int = 0, dubious_nodes: int = 0,
                 cached_nodes: int = 0, incoming_nodes: int = 0,
                 searches: int = 0, storage_keys: int = 0,
                 storage_values: int = 0, storage_bytes: int = 0):
        self.good_nodes = good_nodes
        self.dubious_nodes = dubious_nodes
        self.cached_nodes = cached_nodes
        self.incoming_nodes = incoming_nodes
        self.searches = searches
        self.storage_keys = storage_keys
        self.storage_values = storage_values
        self.storage_bytes = storage_bytes

    @property
    def total_nodes(self) -> int:
        return self.good_nodes + self.dubious_nodes

    def to_dict(self) -> Dict[str, int]:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self) -> str:
        return (f"NodeStats(good={self.good_nodes}, "
                f"dubious={self.dubious_nodes}, "
                f"cached={self.cached_nodes}, "
                f"incoming={self.incoming_nodes}, "
                f"searches={self.searches}, "
                f"storage={self.storage_values} values/"
                f"{self.storage_bytes} B in {self.storage_keys} keys)")


class Get:
    __slots__ = ("start", "filter", "query", "query_cb", "get_cb", "done_cb")

    def __init__(self, start: float, f: Optional[Filter],
                 query: Optional[Query], query_cb: Optional[QueryCallback],
                 get_cb: Optional[GetCallback],
                 done_cb: Optional[DoneCallback]):
        self.start = start
        self.filter = f
        self.query = query or Query()
        self.query_cb = query_cb
        self.get_cb = get_cb
        self.done_cb = done_cb


class Announce:
    __slots__ = ("permanent", "value", "created", "callback")

    def __init__(self, permanent: bool, value: Value, created: float,
                 callback: Optional[DoneCallback]):
        self.permanent = permanent
        self.value = value
        self.created = created
        self.callback = callback


class SearchListener:
    __slots__ = ("query", "filter", "get_cb")

    def __init__(self, query: Optional[Query], f: Optional[Filter],
                 get_cb: GetCallback):
        self.query = query
        self.filter = f
        self.get_cb = get_cb


class _ListenEntry:
    __slots__ = ("query", "req", "socket")

    def __init__(self, query, req, socket):
        self.query = query
        self.req = req
        self.socket = socket


class SearchNode:
    """Per-node state inside a search (ref: src/dht.cpp:244-461)."""

    __slots__ = ("node", "token", "last_get_reply", "candidate",
                 "get_status", "listen_status", "acked", "probe_query",
                 "pagination_queries")

    def __init__(self, node: Node):
        self.node = node
        self.token = b""
        self.last_get_reply = TIME_INVALID
        self.candidate = False
        # qkey -> (query, Request)
        self.get_status: Dict[bytes, Tuple[Optional[Query], Request]] = {}
        # qkey -> _ListenEntry
        self.listen_status: Dict[bytes, _ListenEntry] = {}
        # vid -> (Request | None, refresh_time)
        self.acked: Dict[int, Tuple[Optional[Request], float]] = {}
        self.probe_query: Optional[Query] = None
        # qkey(original get query) -> [qkey of pagination sub-queries]
        # (ref: SearchNode::pagination_queries src/dht.cpp:258)
        self.pagination_queries: Dict[bytes, List[bytes]] = {}

    def has_started_pagination(self, qk: bytes) -> bool:
        """ref: SearchNode::hasStartedPagination src/dht.cpp:333-342."""
        pqs = self.pagination_queries.get(qk)
        if not pqs:
            return False
        return any(sq in self.get_status for sq in pqs)

    def is_synced(self, now: float) -> bool:
        return (not self.node.is_expired() and bool(self.token)
                and self.last_get_reply >= now - NODE_EXPIRE_TIME)

    def is_bad(self) -> bool:
        return self.node is None or self.node.is_expired() or self.candidate

    def pending_get(self) -> bool:
        return any(r.pending() for _, r in self.get_status.values())

    def can_get(self, now: float, update: float,
                query: Optional[Query] = None) -> bool:
        """ref: SearchNode::canGet src/dht.cpp:302-331

        ``query=None`` stands for the reference's find-node sentinel query
        (Query with none=true, satisfied by/satisfying everything)."""
        if self.node.is_expired():
            return False
        pending = False
        pending_sq = completed_sq = False
        for _, (q, r) in self.get_status.items():
            if r.pending():
                pending = True
            satisfied = (query is None or q is None
                         or query.is_satisfied_by(q))
            if satisfied:
                if r.pending():
                    pending_sq = True
                if r.completed() and not (update > r.reply_time):
                    completed_sq = True
        return ((not pending
                 and now > self.last_get_reply + NODE_EXPIRE_TIME)
                or not (self.has_started_pagination(qkey(query))
                        or completed_sq or pending_sq))

    def is_done(self, get: Get) -> bool:
        """ref: SearchNode::isDone src/dht.cpp:356-369 — a paginated
        get is done when none of its sub-requests are pending."""
        qk = qkey(get.query)
        if self.has_started_pagination(qk):
            return not any(
                self.get_status[sq][1].pending()
                for sq in self.pagination_queries.get(qk, ())
                if sq in self.get_status)
        entry = self.get_status.get(qk)
        return entry is not None and not entry[1].pending()

    def is_announced(self, vid: int, now: float) -> bool:
        ack = self.acked.get(vid)
        return ack is not None and ack[0] is not None and ack[1] > now

    def is_listening(self, now: float) -> bool:
        return any(e.req is not None
                   and e.req.reply_time + LISTEN_EXPIRE_TIME > now
                   for e in self.listen_status.values())

    def get_announce_time(self, vid: int) -> float:
        """ref: SearchNode::getAnnounceTime src/dht.cpp:431-441"""
        ack = self.acked.get(vid)
        probe = (self.get_status.get(qkey(self.probe_query))
                 if self.probe_query is not None else None)
        probe_pending = probe is not None and probe[1].pending()
        if (ack is None or ack[0] is None) and not probe_pending:
            return TIME_INVALID
        if probe_pending or ack is None or ack[0] is None or ack[0].pending():
            return TIME_MAX
        return ack[1] - REANNOUNCE_MARGIN

    def get_listen_time(self, query: Optional[Query]) -> float:
        """ref: SearchNode::getListenTime src/dht.cpp:447-453"""
        e = self.listen_status.get(qkey(query))
        if e is None or e.req is None:
            return TIME_INVALID
        if e.req.pending():
            return TIME_MAX
        return e.req.reply_time + LISTEN_EXPIRE_TIME - REANNOUNCE_MARGIN


class Search:
    """One iterative lookup + its pending operations
    (ref: Dht::Search src/dht.cpp:467-713)."""

    __slots__ = ("id", "af", "tid", "refill_time", "step_time", "step_job",
                 "expired", "done", "nodes", "announce", "callbacks",
                 "listeners", "listener_token")

    def __init__(self, target: InfoHash, af: int, tid: int):
        self.id = target
        self.af = af
        self.tid = tid
        self.refill_time = TIME_INVALID
        self.step_time = TIME_INVALID
        self.step_job = None
        self.expired = False
        self.done = False
        self.nodes: List[SearchNode] = []
        self.announce: List[Announce] = []
        self.callbacks: List[Get] = []
        self.listeners: Dict[int, SearchListener] = {}
        self.listener_token = 0

    # -- membership --------------------------------------------------------
    def get_node(self, node: Node) -> Optional[SearchNode]:
        for sn in self.nodes:
            if sn.node is node:
                return sn
        return None

    def insert_node(self, node: Node, now: float, token: bytes = b"") -> bool:
        """Sorted insert with bad-node-aware trimming
        (ref: Search::insertNode src/dht.cpp:961-1047)."""
        if node.family != self.af:
            return False
        target = self.id
        found = None
        pos = len(self.nodes)
        for i in range(len(self.nodes) - 1, -1, -1):
            sn = self.nodes[i]
            # Same object, or same id (the cache normally guarantees one
            # object per id; equal-id match is defense in depth — two
            # SearchNodes for one id would each count toward the sync
            # quorum while only one can ever reply).
            if sn.node is node or sn.node.id == node.id:
                found = sn
                break
            if InfoHash.xor_cmp(node.id, sn.node.id, target) > 0:
                pos = i + 1
                break
            pos = i

        new_node = False
        if found is None:
            bad = self.bad_node_count()
            if self.expired:
                full = len(self.nodes) >= SEARCH_NODES
                if full:
                    del self.nodes[SEARCH_NODES:]
            else:
                full = len(self.nodes) - bad >= SEARCH_NODES
                if full:
                    # trim so non-bad count stays at SEARCH_NODES
                    t = len(self.nodes)
                    b = bad
                    while t - b > SEARCH_NODES and t > 0:
                        t -= 1
                        if self.nodes[t].is_bad():
                            b -= 1
                    del self.nodes[t:]
            if full and pos >= len(self.nodes):
                return False
            if not self.nodes:
                self.step_time = TIME_INVALID
            found = SearchNode(node)
            self.nodes.insert(min(pos, len(self.nodes)), found)
            node.time = max(node.time, now)
            new_node = True
            if not node.is_expired() and self.expired:
                self.expired = False

        if token:
            found.candidate = False
            found.last_get_reply = now
            if len(token) <= TOKEN_SIZE:
                found.token = token
            self.expired = False
        if new_node:
            self.remove_expired_node(now)
        return new_node

    def remove_expired_node(self, now: float) -> bool:
        for i in range(len(self.nodes) - 1, -1, -1):
            n = self.nodes[i].node
            if n.is_expired() and n.time + NODE_EXPIRE_TIME < now:
                del self.nodes[i]
                return True
        return False

    # -- predicates --------------------------------------------------------
    def bad_node_count(self) -> int:
        return sum(1 for sn in self.nodes if sn.is_bad())

    def consecutive_bad_nodes(self) -> int:
        count = 0
        for sn in self.nodes:
            if not sn.is_bad():
                break
            count += 1
        return count

    def solicited_node_count(self) -> int:
        return sum(1 for sn in self.nodes
                   if not sn.is_bad() and sn.pending_get())

    def is_synced(self, now: float) -> bool:
        """First TARGET_NODES live nodes all synced
        (ref: Search::isSynced src/dht.cpp:1466-1479)."""
        i = 0
        for sn in self.nodes:
            if sn.is_bad():
                continue
            if not sn.is_synced(now):
                return False
            i += 1
            if i == TARGET_NODES:
                break
        return i > 0

    def get_last_get_time(self, query: Optional[Query] = None) -> float:
        last = TIME_INVALID
        for g in self.callbacks:
            if query is None or query.is_satisfied_by(g.query):
                last = max(last, g.start)
        return last

    def is_done(self, get: Get) -> bool:
        i = 0
        for sn in self.nodes:
            if sn.is_bad():
                continue
            if not sn.is_done(get):
                return False
            i += 1
            if i == TARGET_NODES:
                break
        return True

    def is_announced(self, vid: int, now: float) -> bool:
        if not self.nodes:
            return False
        i = 0
        for sn in self.nodes:
            if sn.is_bad():
                continue
            if not sn.is_announced(vid, now):
                return False
            i += 1
            if i == TARGET_NODES:
                break
        return i > 0

    def is_listening(self, now: float) -> bool:
        if not self.nodes or not self.listeners:
            return False
        i = 0
        for sn in self.nodes:
            if sn.is_bad():
                continue
            if not sn.is_listening(now):
                return False
            i += 1
            if i == LISTEN_NODES:
                break
        return i > 0

    # -- event times -------------------------------------------------------
    def get_update_time(self, now: float) -> float:
        """Next time a 'get' step is needed
        (ref: Search::getUpdateTime src/dht.cpp:1505-1533)."""
        ut = TIME_MAX
        last_get = self.get_last_get_time()
        i = t = d = 0
        solicited = self.solicited_node_count()
        for sn in self.nodes:
            if sn.node.is_expired() or (sn.candidate and t >= TARGET_NODES):
                continue
            pending = sn.pending_get()
            if sn.last_get_reply < max(now - NODE_EXPIRE_TIME, last_get) \
                    or pending:
                if not pending and solicited < MAX_REQUESTED_SEARCH_NODES:
                    ut = min(ut, now)
                if not sn.candidate:
                    d += 1
            else:
                ut = min(ut, sn.last_get_reply + NODE_EXPIRE_TIME)
            t += 1
            if not sn.candidate:
                i += 1
                if i == TARGET_NODES:
                    break
        if self.callbacks and d == 0:
            return now
        return ut

    def get_announce_time(self, now: float) -> float:
        if not self.nodes or not self.announce:
            return TIME_MAX
        ret = TIME_MAX
        for a in self.announce:
            if a.value is None:
                continue
            i = t = 0
            for sn in self.nodes:
                if not sn.is_synced(now) or (sn.candidate and t >= TARGET_NODES):
                    continue
                ret = min(ret, sn.get_announce_time(a.value.id))
                t += 1
                if not sn.candidate:
                    i += 1
                    if i == TARGET_NODES:
                        break
        return ret

    def get_listen_time(self, now: float) -> float:
        if not self.listeners:
            return TIME_MAX
        lt = TIME_MAX
        i = t = 0
        for sn in self.nodes:
            if not sn.is_synced(now) or (sn.candidate and t >= LISTEN_NODES):
                continue
            for l in self.listeners.values():
                lt = min(lt, sn.get_listen_time(l.query))
            t += 1
            if not sn.candidate:
                i += 1
                if i == LISTEN_NODES:
                    break
        return lt

    def get_next_step_time(self, now: float) -> float:
        if self.expired or self.done:
            return TIME_MAX
        nxt = self.get_update_time(now)
        if self.is_synced(now):
            nxt = min(nxt, self.get_announce_time(now))
            nxt = min(nxt, self.get_listen_time(now))
        return nxt

    # -- completion / teardown --------------------------------------------
    def get_nodes(self) -> List[Node]:
        return [sn.node for sn in self.nodes]

    def set_get_done(self, get: Get) -> None:
        k = qkey(get.query)
        for sn in self.nodes:
            sn.get_status.pop(k, None)
        if get.done_cb:
            get.done_cb(True, self.get_nodes())

    def set_done(self) -> None:
        for sn in self.nodes:
            sn.get_status.clear()
            sn.listen_status.clear()
            sn.acked.clear()
        self.done = True

    def check_announced(self, now: float, vid: Optional[int] = None) -> None:
        """ref: Search::checkAnnounced src/dht.cpp:687-702"""
        keep = []
        for a in self.announce:
            if vid is not None and (a.value is None or a.value.id != vid):
                keep.append(a)
                continue
            if self.is_announced(a.value.id, now):
                if a.callback:
                    a.callback(True, self.get_nodes())
                    a.callback = None
                if a.permanent:
                    keep.append(a)
            else:
                keep.append(a)
        self.announce = keep

    def expire_search(self) -> None:
        """ref: Search::expire src/dht.cpp:645-680"""
        self.expired = True
        self.nodes = []
        if not self.announce and not self.listeners:
            self.set_done()
        gets, self.callbacks = self.callbacks, []
        for g in gets:
            if g.done_cb:
                g.done_cb(False, [])
        keep = []
        cbs = []
        for a in self.announce:
            if a.callback:
                cbs.append(a.callback)
                a.callback = None
            if a.permanent:
                keep.append(a)
        self.announce = keep
        for cb in cbs:
            cb(False, [])


class Dht:
    """The DHT node core.  Single-threaded; driven by a scheduler.

    Acts as the handler object for :class:`NetworkEngine` (the nine-callback
    seam, ref src/dht.cpp:2746-2755).
    """

    def __init__(self, transport4=None, transport6=None,
                 config: Optional[DhtConfig] = None,
                 scheduler: Optional[Scheduler] = None,
                 logger: Logger = NONE,
                 rng: Optional[random.Random] = None):
        config = config or DhtConfig()
        self.myid = config.node_id or InfoHash.get_random()
        self.config = config
        self.log = logger
        self.rng = rng or random.Random()
        self.scheduler = scheduler or Scheduler()

        self.cache = NodeCache()
        # One registry shared with the engine: wire counters and core
        # gauges expose through a single /metrics surface.
        self.metrics = MetricsRegistry()
        self.engine = NetworkEngine(self.myid, config.network, transport4,
                                    transport6, self.scheduler, self,
                                    self.cache, logger, self.rng,
                                    metrics=self.metrics)
        self.running4 = transport4 is not None
        self.running6 = transport6 is not None

        self.buckets4 = RoutingTable(AF_INET)
        self.buckets6 = RoutingTable(AF_INET6)
        self.searches4: Dict[InfoHash, Search] = {}
        self.searches6: Dict[InfoHash, Search] = {}
        self.store: Dict[InfoHash, Storage] = {}
        self.total_store_size = 0
        self.total_values = 0
        self.max_store_size = MAX_STORAGE_SIZE

        self.types: Dict[int, ValueType] = {}
        for t in _default_types():
            self.register_type(t)

        self._search_id = 1
        self._listener_token = 0
        # api token -> (local_token, token4, token6, hash)
        self.listeners: Dict[int, Tuple[int, int, int, InfoHash]] = {}

        self.mybucket_grow_time = TIME_INVALID
        self.mybucket6_grow_time = TIME_INVALID
        self.reported_addr: List[List] = []   # [count, SockAddr]

        self.secret = os.urandom(16)
        self.oldsecret = self.secret
        self._rotate_secrets()

        now = self.scheduler.time()
        self._confirm_job = self.scheduler.add(
            now + self.rng.uniform(3, 5), self._confirm_nodes)
        self.scheduler.add(now + self.rng.uniform(120, 360), self._expire)

        self.on_status_changed: Optional[Callable] = None
        self._last_status = (self.get_status(AF_INET),
                             self.get_status(AF_INET6))

    # ------------------------------------------------------------------ #
    # basic accessors                                                    #
    # ------------------------------------------------------------------ #

    def buckets(self, af: int) -> RoutingTable:
        return self.buckets4 if af == AF_INET else self.buckets6

    def searches(self, af: int) -> Dict[InfoHash, Search]:
        return self.searches4 if af == AF_INET else self.searches6

    def is_running(self, af: int) -> bool:
        return self.running4 if af == AF_INET else self.running6

    def register_type(self, t: ValueType) -> None:
        self.types[t.id] = t

    def get_type(self, type_id: int) -> ValueType:
        t = self.types.get(type_id)
        if t is not None:
            return t
        return ValueType(type_id, "Unknown", USER_DATA.expiration)

    def get_status(self, af: int) -> str:
        good, dubious, _, incoming = self.get_nodes_stats(af)
        if good:
            return NodeStatus.Connected
        if dubious or self._has_pending_searches(af):
            return NodeStatus.Connecting
        return NodeStatus.Disconnected

    def _has_pending_searches(self, af: int) -> bool:
        return any(not s.done and not s.expired
                   for s in self.searches(af).values())

    def get_nodes_stats(self, af: int) -> Tuple[int, int, int, int]:
        """(good, dubious, cached, incoming) (ref: src/dht.cpp:2469-2495)."""
        now = self.scheduler.time()
        good = dubious = cached = incoming = 0
        for b in self.buckets(af):
            for n in b.nodes:
                if n.is_good(now):
                    good += 1
                    if n.time > n.reply_time:
                        incoming += 1
                elif not n.is_expired():
                    dubious += 1
            if b.cached is not None:
                cached += 1
        return good, dubious, cached, incoming

    def node_stats(self, af: int = AF_INET) -> NodeStats:
        """Full :class:`NodeStats` snapshot for one address family —
        the reference ``getNodesStats`` struct plus this node's search
        and storage load (storage totals are node-global: the store is
        not per-af)."""
        good, dubious, cached, incoming = self.get_nodes_stats(af)
        return NodeStats(
            good_nodes=good, dubious_nodes=dubious, cached_nodes=cached,
            incoming_nodes=incoming, searches=len(self.searches(af)),
            storage_keys=len(self.store),
            storage_values=self.total_values,
            storage_bytes=self.total_store_size)

    def update_metrics(self) -> None:
        """Refresh the registry's gauges from live core state.  Called
        by periodic maintenance (:meth:`_confirm_nodes`/:meth:`_expire`)
        and by exposition surfaces at scrape time — the gauges are
        derived state, so recomputing is always safe.

        Scrape-time calls arrive from gateway HTTP threads while the
        DHT loop thread mutates core state (same diagnostics-read
        contract as ``DhtRunner.get_nodes_stats``): the dict iterations
        below work on ``list()`` snapshots, and the gateway converts
        the residual snapshot race (a dict resized mid-copy raises
        RuntimeError) into a 503 — gauges then refresh on the next
        scrape or maintenance tick instead of crashing the handler."""
        nodes_g = self.metrics.gauge(
            "dht_nodes", "Routing-table nodes by state", ("af", "state"))
        searches_g = self.metrics.gauge(
            "dht_searches", "Live searches", ("af",))
        for af, name in ((AF_INET, "ipv4"), (AF_INET6, "ipv6")):
            good, dubious, cached, incoming = self.get_nodes_stats(af)
            nodes_g.set(good, af=name, state="good")
            nodes_g.set(dubious, af=name, state="dubious")
            nodes_g.set(cached, af=name, state="cached")
            nodes_g.set(incoming, af=name, state="incoming")
            searches_g.set(len(self.searches(af)), af=name)
        self.metrics.gauge(
            "dht_storage_keys", "Distinct stored info-hashes"
        ).set(len(self.store))
        self.metrics.gauge(
            "dht_storage_values", "Stored values"
        ).set(self.total_values)
        self.metrics.gauge(
            "dht_storage_bytes", "Stored value bytes"
        ).set(self.total_store_size)
        listeners = sum(
            sum(len(socks) for socks in list(st.listeners.values()))
            + len(st.local_listeners) for st in list(self.store.values()))
        self.metrics.gauge(
            "dht_storage_listeners", "Registered storage listeners"
        ).set(listeners)

    # ------------------------------------------------------------------ #
    # tokens (ref: src/dht.cpp:2404-2467)                                #
    # ------------------------------------------------------------------ #

    def _make_token(self, addr: SockAddr, old: bool) -> bytes:
        secret = self.oldsecret if old else self.secret
        try:
            ip = addr.pack_ip()
        except ValueError:
            ip = addr.host.encode()
        return hashlib.sha512(secret + ip).digest()[:TOKEN_SIZE]

    def _token_match(self, token: bytes, addr: SockAddr) -> bool:
        if len(token) != TOKEN_SIZE:
            return False
        from ..native import token_eq
        return (token_eq(token, self._make_token(addr, False))
                or token_eq(token, self._make_token(addr, True)))

    def _rotate_secrets(self) -> None:
        self.oldsecret = self.secret
        self.secret = os.urandom(16)
        self.scheduler.add(
            self.scheduler.time() + self.rng.uniform(15 * 60, 45 * 60),
            self._rotate_secrets)

    # ------------------------------------------------------------------ #
    # engine handler callbacks (the nine-callback seam)                  #
    # ------------------------------------------------------------------ #

    def on_error(self, req: Request, code: int) -> None:
        """ref: Dht::onError src/dht.cpp:3152-3176"""
        if code == DhtProtocolException.UNAUTHORIZED:
            node = req.node
            node.auth_error()
            self.engine.cancel_request(req)
            for sr in self.searches(node.family).values():
                for sn in sr.nodes:
                    if sn.node is node:
                        sn.token = b""
                        sn.last_get_reply = TIME_INVALID
                        self._search_send_get_values(sr)
                        break
        elif code == DhtProtocolException.NOT_FOUND:
            self.engine.cancel_request(req)

    def on_reported_addr(self, nid: InfoHash, addr: SockAddr) -> None:
        b = self.buckets(addr.family).find_bucket(nid)
        b.time = self.scheduler.time()
        # The ``sa`` echo carries no port (insertAddr packs the bare
        # ip); the reference records it anyway (onReportedAddr checks
        # socklen, not port — src/dht.cpp:3174-3180).
        if addr.host:
            for entry in self.reported_addr:
                if entry[1] == addr:
                    entry[0] += 1
                    return
            if len(self.reported_addr) < 32:
                self.reported_addr.append([1, addr])

    def get_public_address(self, af: int = 0) -> List[SockAddr]:
        """ref: Dht::getPublicAddress src/dht.cpp:803-814"""
        out = sorted(self.reported_addr, key=lambda e: -e[0])
        return [a for c, a in out if af == 0 or a.family == af]

    def on_new_node(self, node: Node, confirm: int) -> None:
        """Bucket insertion policy (ref: Dht::onNewNode src/dht.cpp:864-936)."""
        table = self.buckets(node.family)
        idx = table.find_bucket_index(node.id)
        b = table.buckets[idx]

        if any(n is node for n in b.nodes):
            if confirm:
                self._try_search_insert(node)
            return

        self._try_search_insert(node)

        now = self.scheduler.time()
        mybucket = idx == table.find_bucket_index(self.myid)
        if mybucket:
            if node.family == AF_INET:
                self.mybucket_grow_time = now
            else:
                self.mybucket6_grow_time = now

        # replace an expired node
        for i, n in enumerate(b.nodes):
            if n.is_expired():
                b.nodes[i] = node
                return

        if len(b.nodes) >= TARGET_NODES:
            dubious = False
            for n in b.nodes:
                if not n.is_good(now):
                    dubious = True
                    if not n.is_pending_message():
                        self.engine.send_ping(n)
                        break
            if (mybucket or (self.config.is_bootstrap
                             and table.depth(idx) < 6)) \
                    and (not dubious or len(table.buckets) == 1):
                self._send_cached_ping(b)
                table.split(idx)
                self.on_new_node(node, 0)
                return
            if confirm or b.cached is None:
                b.cached = node
        else:
            b.nodes.insert(0, node)

    def _send_cached_ping(self, b: Bucket) -> None:
        if b.cached is not None:
            self.engine.send_ping(b.cached)
            b.cached = None

    def _try_search_insert(self, node: Node) -> bool:
        """ref: Dht::trySearchInsert src/dht.cpp:818-849"""
        now = self.scheduler.time()
        inserted = False
        for sr in self.searches(node.family).values():
            if sr.insert_node(node, now):
                inserted = True
                self._schedule_step(sr, sr.get_next_step_time(now))
        return inserted

    # -- RPC request handlers (ref: src/dht.cpp:3183-3421) -----------------
    def on_ping(self, node: Node) -> RequestAnswer:
        return RequestAnswer()

    def on_find(self, node: Node, target: Optional[InfoHash],
                want: int) -> RequestAnswer:
        now = self.scheduler.time()
        ans = RequestAnswer()
        ans.ntoken = self._make_token(node.addr, False)
        if target is None:
            return ans
        if want <= 0:
            want = WANT4 if node.family == AF_INET else WANT6
        if want & WANT4:
            ans.nodes4 = self.buckets4.find_closest_nodes(target, now,
                                                          TARGET_NODES)
        if want & WANT6:
            ans.nodes6 = self.buckets6.find_closest_nodes(target, now,
                                                          TARGET_NODES)
        return ans

    def on_get_values(self, node: Node, info_hash: Optional[InfoHash],
                      want: int, query: Optional[Query]) -> RequestAnswer:
        if not info_hash:
            raise DhtProtocolException(203, "Get_values with no info_hash")
        now = self.scheduler.time()
        ans = RequestAnswer()
        ans.ntoken = self._make_token(node.addr, False)
        ans.nodes4 = self.buckets4.find_closest_nodes(info_hash, now,
                                                      TARGET_NODES)
        ans.nodes6 = self.buckets6.find_closest_nodes(info_hash, now,
                                                      TARGET_NODES)
        st = self.store.get(info_hash)
        if st is not None and not st.is_empty():
            f = query.where.get_filter() if query else None
            ans.values = st.get(f)
            if query is not None and query.select:
                # project to selected fields only
                ans.fields = [FieldValueIndex(v, query.select)
                              for v in ans.values]
        return ans

    def on_listen(self, node: Node, info_hash: Optional[InfoHash],
                  token: bytes, socket_id: bytes,
                  query: Optional[Query]) -> RequestAnswer:
        if not info_hash:
            raise DhtProtocolException(203, "Listen with no info_hash")
        if not self._token_match(token, node.addr):
            raise DhtProtocolException(DhtProtocolException.UNAUTHORIZED,
                                       "Listen with wrong token")
        self._storage_add_listener(info_hash, node, socket_id,
                                   query or Query())
        return RequestAnswer()

    def on_announce(self, node: Node, info_hash: Optional[InfoHash],
                    values: List[Value], created: Optional[float],
                    token: bytes) -> RequestAnswer:
        if not info_hash:
            raise DhtProtocolException(203, "Put with no info_hash")
        if not self._token_match(token, node.addr):
            raise DhtProtocolException(DhtProtocolException.UNAUTHORIZED,
                                       "Put with wrong token")
        now = self.scheduler.time()
        # proximity check (ref: :3351-3358)
        closest = self.buckets(node.family).find_closest_nodes(
            info_hash, now, SEARCH_NODES)
        if len(closest) >= TARGET_NODES and \
                InfoHash.xor_cmp(closest[-1].id, self.myid, info_hash) < 0:
            return RequestAnswer()

        created = min(created if created is not None else now, now)
        ans = RequestAnswer()
        for v in values:
            if v.id == 0:
                raise DhtProtocolException(203, "Put with invalid value id")
            lv = self.get_local_by_id(info_hash, v.id)
            if lv is not None:
                if not (lv == v):
                    t = self.get_type(lv.type)
                    if t.edit_policy(info_hash, lv, v, node.id, node.addr):
                        self._storage_store(info_hash, v, created)
            else:
                t = self.get_type(v.type)
                if t.store_policy(info_hash, v, node.id, node.addr):
                    self._storage_store(info_hash, v, created)
            ans.vid = v.id
        return ans

    def on_refresh(self, node: Node, info_hash: Optional[InfoHash],
                   vid: int, token: bytes) -> RequestAnswer:
        if not self._token_match(token, node.addr):
            raise DhtProtocolException(DhtProtocolException.UNAUTHORIZED,
                                       "Refresh with wrong token")
        now = self.scheduler.time()
        st = self.store.get(info_hash)
        if st is None or not st.refresh(now, vid):
            raise DhtProtocolException(DhtProtocolException.NOT_FOUND,
                                       "Storage not found")
        ans = RequestAnswer()
        ans.vid = vid
        return ans

    # ------------------------------------------------------------------ #
    # storage internals                                                  #
    # ------------------------------------------------------------------ #

    def _storage_store(self, info_hash: InfoHash, value: Value,
                       created: float) -> bool:
        """ref: Dht::storageStore src/dht.cpp:2227-2258"""
        now = self.scheduler.time()
        if created + self.get_type(value.type).expiration < now:
            return False
        st = self.store.get(info_hash)
        if st is None:
            if len(self.store) >= MAX_HASHES:
                return False
            st = self.store[info_hash] = Storage(now)
            if self.config.maintain_storage:
                st.maintenance_time = now + MAX_STORAGE_MAINTENANCE_EXPIRE_TIME
                self.scheduler.add(st.maintenance_time,
                                   lambda: self._data_persistence(info_hash))
        stored, size_diff, count_diff = st.store(
            value, created, self.max_store_size - self.total_store_size)
        if stored is not None:
            self.total_store_size += size_diff
            self.total_values += count_diff
            self._storage_changed(info_hash, st, stored.value)
        return stored is not None

    def _storage_changed(self, info_hash: InfoHash, st: Storage,
                         value: Value) -> None:
        """Notify local + remote listeners (ref: src/dht.cpp:2186-2225)."""
        for l in list(st.local_listeners.values()):
            if l.filter is None or l.filter(value):
                l.get_cb([value])
        for node, sockets in list(st.listeners.items()):
            for lst in list(sockets.values()):
                f = lst.query.where.get_filter() if lst.query else None
                if f is not None and not f(value):
                    continue
                ntoken = self._make_token(node.addr, False)
                self.engine.tell_listener(node, lst.socket_id, info_hash,
                                          [value], ntoken)

    def _storage_add_listener(self, info_hash: InfoHash, node: Node,
                              socket_id: bytes, query: Query) -> None:
        """ref: Dht::storageAddListener src/dht.cpp:2299-2322"""
        now = self.scheduler.time()
        st = self.store.get(info_hash)
        if st is None:
            if len(self.store) >= MAX_HASHES:
                return
            st = self.store[info_hash] = Storage(now)
        sockets = st.listeners.setdefault(node, {})
        entry = sockets.get(socket_id)
        if entry is None:
            vals = st.get(query.where.get_filter() if query else None)
            if vals:
                self.engine.tell_listener(
                    node, socket_id, info_hash, vals,
                    self._make_token(node.addr, False))
            sockets[socket_id] = RemoteListener(socket_id, now, query)
        else:
            entry.refresh(socket_id, now, query)

    def get_local(self, info_hash: InfoHash,
                  f: Optional[Filter] = None) -> List[Value]:
        st = self.store.get(info_hash)
        return st.get(f) if st is not None else []

    def get_local_by_id(self, info_hash: InfoHash, vid: int
                        ) -> Optional[Value]:
        st = self.store.get(info_hash)
        return st.get_by_id(vid) if st is not None else None

    # ------------------------------------------------------------------ #
    # searches                                                           #
    # ------------------------------------------------------------------ #

    def search(self, target: InfoHash, af: int,
               get_cb: Optional[GetCallback] = None,
               query_cb: Optional[QueryCallback] = None,
               done_cb: Optional[DoneCallback] = None,
               f: Optional[Filter] = None,
               query: Optional[Query] = None) -> Optional[Search]:
        """Create or reuse a search (ref: Dht::search src/dht.cpp:1672-1735)."""
        if not self.is_running(af):
            if done_cb:
                done_cb(False, [])
            return None
        srs = self.searches(af)
        sr = srs.get(target)
        if sr is not None:
            sr.done = False
            sr.expired = False
        else:
            if len(self.searches4) + len(self.searches6) >= MAX_SEARCHES:
                # reuse a finished search slot (LRU-ish)
                victim = None
                for key, s in srs.items():
                    if (s.done or s.expired) and not s.announce \
                            and not s.listeners:
                        victim = key
                        break
                if victim is None:
                    if done_cb:
                        done_cb(False, [])
                    return None
                old = srs.pop(victim)
                if old.step_job:
                    old.step_job.cancel()
            sr = Search(target, af, self._search_id)
            self._search_id += 1
            srs[target] = sr

        if get_cb or query_cb:
            sr.callbacks.append(Get(self.scheduler.time(), f, query,
                                    query_cb, get_cb, done_cb))
        self._refill(sr)
        now = self.scheduler.time()
        if sr.step_job is not None and sr.step_job.active:
            self._schedule_step(sr, sr.get_next_step_time(now))
        else:
            self._schedule_step(sr, now)
        return sr

    def _schedule_step(self, sr: Search, t: float) -> None:
        """(Re)schedule a search's step job.  Unlike the reference's
        Scheduler::edit (which re-schedules the stored closure,
        scheduler.h:63-80), our jobs are one-shot — so re-create the job
        when the handle is spent (e.g. while the step is executing)."""
        if t >= TIME_MAX:
            return
        if sr.step_job is not None and sr.step_job.active:
            sr.step_job = self.scheduler.edit(sr.step_job, t)
        else:
            sr.step_job = self.scheduler.add(
                t, lambda: self._search_step(sr))

    def _refill(self, sr: Search) -> int:
        """ref: Dht::refill src/dht.cpp:1647-1668"""
        now = self.scheduler.time()
        cached = self.cache.get_cached_nodes(sr.id, sr.af, SEARCH_NODES)
        inserted = 0
        for n in cached:
            if sr.insert_node(n, now):
                inserted += 1
        sr.refill_time = now
        return inserted

    def _search_step(self, sr: Search) -> None:
        """The search driver (ref: Dht::searchStep src/dht.cpp:1343-1464)."""
        if sr is None or sr.expired or sr.done:
            return
        now = self.scheduler.time()
        sr.step_time = now

        if sr.refill_time + NODE_EXPIRE_TIME < now and \
                len(sr.nodes) - sr.bad_node_count() < SEARCH_NODES:
            self._refill(sr)

        if sr.is_synced(now):
            # complete finished gets
            for g in list(sr.callbacks):
                if sr.is_done(g):
                    sr.set_get_done(g)
                    sr.callbacks.remove(g)
            sr.check_announced(now)
            if not sr.callbacks and not sr.announce and not sr.listeners:
                sr.set_done()

            # listen dispatch
            if sr.listeners:
                i = 0
                for sn in sr.nodes:
                    if not sn.is_synced(now):
                        continue
                    for l in sr.listeners.values():
                        if sn.get_listen_time(l.query) <= now:
                            self._send_listen(sr, sn, l.query)
                    if not sn.candidate:
                        i += 1
                        if i == LISTEN_NODES:
                            break

            # announce dispatch
            self._search_send_announce_value(sr)

            if not sr.callbacks and not sr.announce and not sr.listeners:
                sr.set_done()

        # keep alpha get/find requests in flight (bounded: candidates may
        # be solicited without counting toward alpha, ref :1438-1449)
        sends = 0
        while sr.solicited_node_count() < MAX_REQUESTED_SEARCH_NODES \
                and sends < 2 * SEARCH_NODES:
            if self._search_send_get_values(sr) is None:
                break
            sends += 1

        # connectivity-loss detection (ref: :1451-1457)
        if sr.consecutive_bad_nodes() >= min(len(sr.nodes),
                                             SEARCH_MAX_BAD_NODES):
            sr.expire_search()
            self._connectivity_changed(sr.af)

        if not sr.done:
            self._schedule_step(sr, sr.get_next_step_time(now))

    def _search_send_get_values(self, sr: Search,
                                pn: Optional[SearchNode] = None,
                                update: bool = True) -> Optional[SearchNode]:
        """ref: Dht::searchSendGetValues src/dht.cpp:1170-1235"""
        if sr.done or sr.solicited_node_count() >= MAX_REQUESTED_SEARCH_NODES:
            return None
        now = self.scheduler.time()

        gets = sr.callbacks or [None]
        for g in gets:
            query = g.query if g is not None else None
            up = sr.get_last_get_time(query) if (g is not None and update) \
                else TIME_INVALID
            n = None
            if pn is not None and pn.can_get(now, up, query):
                n = pn
            else:
                for sn in sr.nodes:
                    if sn.can_get(now, up, query):
                        n = sn
                        break
            if g is None:
                if n is None:
                    return None
                k = qkey(None)
                n.get_status[k] = (None, self.engine.send_find_node(
                    n.node, sr.id, self._want(),
                    on_done=lambda req, ans, q=None: self._search_node_get_done(
                        req, ans, sr, q),
                    on_expired=lambda req, over, q=None:
                        self._search_node_get_expired(req, over, sr, q)))
                return n
            else:
                if n is None:
                    continue
                # A get without an explicit selection is paginated:
                # SELECT id first, then one sub-get per value id
                # (ref: Dht::paginate src/dht.cpp:1117-1168, dispatch
                # :1216-1227).
                if query is None or not query.select.fields:
                    self._paginate(sr, query, n)
                    return n
                k = qkey(query)
                n.get_status[k] = (query, self.engine.send_get_values(
                    n.node, sr.id, query if (query and query) else None,
                    self._want(),
                    on_done=lambda req, ans, q=query:
                        self._search_node_get_done(req, ans, sr, q),
                    on_expired=lambda req, over, q=query:
                        self._search_node_get_expired(req, over, sr, q)))
                return n
        return None

    def _want(self) -> int:
        w = 0
        if self.running4:
            w |= WANT4
        if self.running6:
            w |= WANT6
        return w

    def _paginate(self, sr: Search, query: Optional[Query],
                  sn: SearchNode) -> None:
        """Split a select-less get per value id: a ``SELECT id`` probe,
        then one ``GET WHERE id=<vid>`` per discovered id — so huge
        storages stream incrementally (ref: Dht::paginate
        src/dht.cpp:1117-1168)."""
        select_q = Query(Select().field(Field.Id),
                         query.where if query is not None else None)
        qk = qkey(query)

        def on_select_done(req: Request, answer: RequestAnswer) -> None:
            ssr = sr
            nn = ssr.get_node(req.node)
            if nn is None:
                return
            if not answer.fields:
                # Node answered without field projection: fall back to
                # treating this as the whole get's answer.
                self._search_node_get_done(req, answer, ssr, query)
                return
            for fvi in answer.fields:
                vid = fvi.index.get(Field.Id)
                if not vid:
                    continue
                q_vid = Query(Select(), Where().id(int(vid)))
                kq = qkey(q_vid)
                nn.pagination_queries.setdefault(qk, []).append(kq)
                nn.get_status[kq] = (q_vid, self.engine.send_get_values(
                    req.node, ssr.id, q_vid, 0,
                    on_done=lambda r, a, q=query:
                        self._search_node_get_done(r, a, ssr, q),
                    on_expired=lambda r, over, q=q_vid:
                        self._search_node_get_expired(r, over, ssr, q)))

        sn.pagination_queries.setdefault(qk, []).append(qkey(select_q))
        sn.get_status[qkey(select_q)] = (select_q, self.engine.send_get_values(
            sn.node, sr.id, select_q, 0,
            on_done=on_select_done,
            on_expired=lambda r, over, q=select_q:
                self._search_node_get_expired(r, over, sr, q)))

    def _search_node_get_done(self, req: Request, answer: RequestAnswer,
                              sr: Search, query: Optional[Query]) -> None:
        """ref: Dht::searchNodeGetDone src/dht.cpp:1076-1099"""
        now = self.scheduler.time()
        sn = sr.get_node(req.node)
        if sn is not None and query is not None:
            # satisfy other pending gets covered by this answer
            for g in sr.callbacks:
                if g.query is not query and g.query.is_satisfied_by(query):
                    dummy = Request(b"", req.node, b"")
                    dummy.set_done(now)
                    sn.get_status[qkey(g.query)] = (g.query, dummy)
        sr.insert_node(req.node, now, answer.ntoken)
        self._on_get_values_done(req.node, answer, sr, query)

    def _search_node_get_expired(self, req: Request, over: bool, sr: Search,
                                 query: Optional[Query]) -> None:
        """ref: Dht::searchNodeGetExpired src/dht.cpp:1102-1115"""
        if over:
            sn = sr.get_node(req.node)
            if sn is not None:
                sn.get_status.pop(qkey(query), None)
        self._schedule_step(sr, self.scheduler.time())

    def _on_get_values_done(self, node: Node, a: RequestAnswer, sr: Search,
                            orig_query: Optional[Query]) -> None:
        """ref: Dht::onGetValuesDone src/dht.cpp:3227-3297"""
        if sr is None:
            return
        if a.ntoken:
            if a.values or a.fields:
                for g in sr.callbacks:
                    if not (g.get_cb or g.query_cb):
                        continue
                    if orig_query is not None and g.query and \
                            not g.query.is_satisfied_by(orig_query):
                        continue
                    if g.query_cb:
                        if a.fields:
                            g.query_cb(a.fields)
                        elif a.values:
                            g.query_cb([FieldValueIndex(
                                v, orig_query.select if orig_query else None)
                                for v in a.values])
                    elif g.get_cb:
                        vals = [v for v in a.values
                                if g.filter is None or g.filter(v)]
                        if vals:
                            g.get_cb(vals)
                for l in list(sr.listeners.values()):
                    if not l.get_cb:
                        continue
                    if orig_query is not None and l.query and \
                            not l.query.is_satisfied_by(orig_query):
                        continue
                    vals = [v for v in a.values
                            if l.filter is None or l.filter(v)]
                    if vals:
                        l.get_cb(vals)
        else:
            self.engine.blacklist_node(node)

        if not sr.done:
            self._search_send_get_values(sr)
            self._schedule_step(sr, self.scheduler.time())

    def _send_listen(self, sr: Search, sn: SearchNode,
                     query: Optional[Query]) -> None:
        """ref: listen dispatch in searchStep src/dht.cpp:1397-1429"""
        k = qkey(query)
        prev = sn.listen_status.get(k)
        prev_socket = prev.socket if prev is not None else None

        def on_done(req, answer):
            if not sr.done:
                self._search_send_get_values(sr)
                self._schedule_step(sr, self.scheduler.time())

        def on_expired(req, over):
            self._schedule_step(sr, self.scheduler.time())
            if over:
                s = sr.get_node(req.node)
                if s is not None:
                    s.listen_status.pop(k, None)

        def on_values(node, msg):
            ans = self.engine._answer_from(msg)
            if msg.values or msg.fields:
                self._on_get_values_done(node, ans, sr, query)
                self._schedule_step(sr, self.scheduler.time())

        req, socket = self.engine.send_listen(
            sn.node, sr.id, sn.token, query, prev_socket,
            on_done=on_done, on_expired=on_expired, socket_cb=on_values)
        sn.listen_status[k] = _ListenEntry(query, req, socket)

    def _search_send_announce_value(self, sr: Search) -> None:
        """Announce with probe (ref: Dht::searchSendAnnounceValue
        src/dht.cpp:1237-1341): per synced node, first a SELECT id,seq
        probe, then put / refresh / ack-skip depending on what it holds."""
        if not sr.announce:
            return
        now = self.scheduler.time()
        i = 0
        for sn in sr.nodes:
            if not any(sn.is_synced(now)
                       and sn.get_announce_time(a.value.id) <= now
                       for a in sr.announce):
                continue
            sn.probe_query = PROBE_QUERY
            sn.get_status[PROBE_QKEY] = (PROBE_QUERY, self.engine.send_get_values(
                sn.node, sr.id, PROBE_QUERY, self._want(),
                on_done=lambda req, ans: self._on_probe_done(req, ans, sr),
                on_expired=lambda req, over:
                    self._search_node_get_expired(req, over, sr, PROBE_QUERY)))
            if not sn.candidate:
                i += 1
                if i == TARGET_NODES:
                    break

    def _on_probe_done(self, req: Request, answer: RequestAnswer,
                       sr: Search) -> None:
        now = self.scheduler.time()
        sn = sr.get_node(req.node)
        if sn is None:
            return
        sr.insert_node(req.node, now, answer.ntoken)

        def on_done(r, ans):
            self._on_announce_done(r.node, ans, sr)
            self._search_step(sr)

        def on_expired(r, over):
            if over:
                self._schedule_step(sr, self.scheduler.time())

        for a in sr.announce:
            if not (sn.is_synced(now)
                    and sn.get_announce_time(a.value.id) <= now):
                self._schedule_step(sr, sr.get_next_step_time(now))
                continue
            has_value = False
            seq_no = 0
            for fvi in answer.fields:
                if fvi.index.get(Field.Id) == a.value.id:
                    has_value = True
                    seq_no = int(fvi.index.get(Field.SeqNum, 0) or 0)
                    break
            next_refresh = now + self.get_type(a.value.type).expiration
            if not has_value or seq_no < a.value.seq:
                r = self.engine.send_announce_value(
                    sn.node, sr.id, a.value,
                    None if a.permanent else a.created, sn.token,
                    on_done=on_done, on_expired=on_expired)
                sn.acked[a.value.id] = (r, next_refresh)
            elif has_value and a.permanent:
                r = self.engine.send_refresh_value(
                    sn.node, sr.id, a.value.id, sn.token,
                    on_done=on_done, on_expired=on_expired)
                sn.acked[a.value.id] = (r, next_refresh)
            else:
                ack = Request(b"", sn.node, b"")
                ack.set_done(now)
                sn.acked[a.value.id] = (ack, next_refresh)
                self._schedule_step(sr, next_refresh)

    def _on_announce_done(self, node: Node, answer: RequestAnswer,
                          sr: Search) -> None:
        now = self.scheduler.time()
        self._search_send_get_values(sr)
        sr.check_announced(now, answer.vid or None)

    def _connectivity_changed(self, af: int) -> None:
        """ref: Dht::connectivityChanged src/dht.cpp:2383-2402"""
        now = self.scheduler.time()
        if self._confirm_job is not None and self._confirm_job.active:
            self._confirm_job = self.scheduler.edit(self._confirm_job, now)
        else:
            self._confirm_job = self.scheduler.add(now, self._confirm_nodes)
        if af == AF_INET:
            self.mybucket_grow_time = now
        else:
            self.mybucket6_grow_time = now
        for b in self.buckets(af):
            b.time = TIME_INVALID
        self.cache.clear_bad_nodes(af)
        for sr in self.searches(af).values():
            for sn in sr.nodes:
                for e in sn.listen_status.values():
                    self.engine.cancel_request(e.req)
                    self.engine.close_socket(e.socket)
                sn.listen_status.clear()
        self.reported_addr = [e for e in self.reported_addr
                              if e[1].family != af]

    # ------------------------------------------------------------------ #
    # public API                                                         #
    # ------------------------------------------------------------------ #

    def put(self, info_hash: InfoHash, value: Value,
            done_cb: Optional[DoneCallback] = None,
            created: Optional[float] = None, permanent: bool = False) -> None:
        """Dual-stack announce (ref: Dht::put src/dht.cpp:1931-1967)."""
        if value.id == 0:
            value.id = Value.random_id(self.rng)
        now = self.scheduler.time()
        created = min(created if created is not None else now, now)
        state = {"done": False, "ok": False, "done4": False, "done6": False}

        def donecb(nodes):
            if done_cb and not state["done"] and state["done4"] \
                    and state["done6"]:
                state["done"] = True
                done_cb(state["ok"], nodes)

        def done4(ok, nodes):
            state["done4"] = True
            state["ok"] |= ok
            donecb(nodes)

        def done6(ok, nodes):
            state["done6"] = True
            state["ok"] |= ok
            donecb(nodes)

        self._announce(info_hash, AF_INET, value, done4, created, permanent)
        self._announce(info_hash, AF_INET6, value, done6, created, permanent)

    def _announce(self, info_hash: InfoHash, af: int, value: Value,
                  callback: Optional[DoneCallback], created: float,
                  permanent: bool) -> None:
        """ref: Dht::announce src/dht.cpp:1738-1796"""
        now = self.scheduler.time()
        if not self.is_running(af):
            if callback:
                callback(False, [])
            return
        self._storage_store(info_hash, value, created)
        sr = self.searches(af).get(info_hash) or self.search(info_hash, af)
        if sr is None:
            if callback:
                callback(False, [])
            return
        sr.done = False
        sr.expired = False
        existing = next((a for a in sr.announce
                         if a.value.id == value.id), None)
        if existing is None:
            sr.announce.append(Announce(permanent, value, created, callback))
            for sn in sr.nodes:
                sn.probe_query = None
                if value.id in sn.acked:
                    sn.acked[value.id] = (None, sn.acked[value.id][1])
        else:
            if existing.value is not value:
                existing.value = value
                for sn in sr.nodes:
                    if value.id in sn.acked:
                        sn.acked[value.id] = (None, sn.acked[value.id][1])
                    sn.probe_query = None
            if sr.is_announced(value.id, now):
                if existing.callback:
                    existing.callback(True, [])
                    existing.callback = None
                if callback:
                    callback(True, [])
                return
            else:
                if existing.callback:
                    existing.callback(False, [])
                existing.callback = callback
        self._schedule_step(sr, now)

    def get(self, info_hash: InfoHash, get_cb: Optional[GetCallback],
            done_cb: Optional[DoneCallback] = None,
            f: Optional[Filter] = None,
            where: Optional["Where"] = None) -> None:
        """Dual-stack iterative get (ref: Dht::get src/dht.cpp:2013-2052)."""
        from .value import Where as _Where
        q = Query(None, where if where is not None else _Where())
        op = {"done": False, "ok": False, "done4": False, "done6": False,
              "ok4": False, "ok6": False, "values": [], "nodes": []}
        ff = f_chain_and(f, q.where.get_filter())

        def add_values(values):
            newvals = []
            for v in values:
                if any(sv is v or sv == v for sv in op["values"]):
                    continue
                if ff is None or ff(v):
                    newvals.append(v)
            return newvals

        def gcb(values):
            if op["done"]:
                return False
            newvals = add_values(values)
            if newvals:
                if get_cb:
                    op["ok"] = not get_cb(newvals)
                op["values"].extend(newvals)
            done_wrapper([])
            return not op["ok"]

        def done_wrapper(nodes):
            if op["done"]:
                return
            op["nodes"].extend(nodes)
            if op["ok"] or (op["done4"] and op["done6"]):
                # ok = cancelled-satisfied OR either search completed —
                # NOT "values found": a completed search over a missing
                # key reports success with no values
                # (ref: doneCallbackWrapper src/dht.cpp:1983-1993).
                ok = op["ok"] or op["ok4"] or op["ok6"]
                op["done"] = True
                if done_cb:
                    done_cb(ok, op["nodes"])

        def done4(ok, nodes):
            op["done4"] = True
            op["ok4"] = ok
            done_wrapper(nodes)

        def done6(ok, nodes):
            op["done6"] = True
            op["ok6"] = ok
            done_wrapper(nodes)

        # answer locally first
        local = self.get_local(info_hash, ff)
        if local:
            gcb(local)

        self.search(info_hash, AF_INET, gcb, None, done4, ff, q)
        self.search(info_hash, AF_INET6, gcb, None, done6, ff, q)

    def query(self, info_hash: InfoHash, query_cb: QueryCallback,
              done_cb: Optional[DoneCallback] = None,
              q: Optional[Query] = None) -> None:
        """Remote-filtered field query (ref: Dht::query src/dht.cpp:2055-2103)."""
        q = q or Query()
        op = {"done": False, "ok": False, "done4": False, "done6": False,
              "ok4": False, "ok6": False, "values": [], "nodes": []}
        f = q.where.get_filter()

        def add_fields(fields):
            newvals = []
            for fv in fields:
                if any(fv is sf or fv.contained_in(sf) for sf in op["values"]):
                    continue
                op["values"] = [sf for sf in op["values"]
                                if not sf.contained_in(fv)]
                newvals.append(fv)
            return newvals

        def qcb(fields):
            if op["done"]:
                return False
            newvals = add_fields(fields)
            if newvals:
                op["ok"] = not query_cb(newvals)
                op["values"].extend(newvals)
            done_wrapper([])
            return not op["ok"]

        def done_wrapper(nodes):
            if op["done"]:
                return
            op["nodes"].extend(nodes)
            if op["ok"] or (op["done4"] and op["done6"]):
                # ok = cancelled-satisfied OR either search completed —
                # NOT "values found": a completed search over a missing
                # key reports success with no values
                # (ref: doneCallbackWrapper src/dht.cpp:1983-1993).
                ok = op["ok"] or op["ok4"] or op["ok6"]
                op["done"] = True
                if done_cb:
                    done_cb(ok, op["nodes"])

        def done4(ok, nodes):
            op["done4"] = True
            op["ok4"] = ok
            done_wrapper(nodes)

        def done6(ok, nodes):
            op["done6"] = True
            op["ok6"] = ok
            done_wrapper(nodes)

        local = self.get_local(info_hash, f)
        if local:
            qcb([FieldValueIndex(v, q.select) for v in local])

        self.search(info_hash, AF_INET, None, qcb, done4, f, q)
        self.search(info_hash, AF_INET6, None, qcb, done6, f, q)

    def listen(self, info_hash: InfoHash, cb: GetCallback,
               f: Optional[Filter] = None,
               where: Optional["Where"] = None) -> int:
        """Subscribe to value updates (ref: Dht::listen src/dht.cpp:1825-1874)."""
        from .value import Where as _Where
        q = Query(None, where if where is not None else _Where())
        query = q
        ff = f_chain_and(f, q.where.get_filter())
        self._listener_token += 1
        token = self._listener_token
        vals: Dict[int, Value] = {}

        def gcb(values):
            newvals = [v for v in values
                       if v.id not in vals or not (vals[v.id] == v)]
            if newvals:
                if not cb(newvals):
                    self.cancel_listen(info_hash, token)
                    return False
                for v in newvals:
                    vals[v.id] = v
            return True

        token_local = 0
        st = self.store.get(info_hash)
        if st is None and len(self.store) < MAX_HASHES:
            st = self.store[info_hash] = Storage(self.scheduler.time())
        if st is not None:
            existing = st.get(ff)
            if existing and not gcb(existing):
                return 0
            st.listener_token += 1
            token_local = st.listener_token
            st.local_listeners[token_local] = LocalListener(query, ff, gcb)

        token4 = self._listen_to(info_hash, AF_INET, gcb, ff, query)
        token6 = self._listen_to(info_hash, AF_INET6, gcb, ff, query)
        self.listeners[token] = (token_local, token4, token6, info_hash)
        return token

    def _listen_to(self, info_hash: InfoHash, af: int, cb: GetCallback,
                   f: Optional[Filter], query: Query) -> int:
        """ref: Dht::listenTo src/dht.cpp:1799-1822"""
        if not self.is_running(af):
            return 0
        sr = self.searches(af).get(info_hash) or self.search(info_hash, af)
        if sr is None:
            return 0
        sr.done = False
        sr.listener_token += 1
        t = sr.listener_token
        sr.listeners[t] = SearchListener(query, f, cb)
        self._schedule_step(sr, sr.get_next_step_time(self.scheduler.time()))
        return t

    def cancel_listen(self, info_hash: InfoHash, token: int) -> bool:
        """ref: Dht::cancelListen src/dht.cpp:1877-1927"""
        entry = self.listeners.pop(token, None)
        if entry is None:
            return False
        token_local, token4, token6, _ = entry
        st = self.store.get(info_hash)
        if st is not None and token_local:
            st.local_listeners.pop(token_local, None)
        for af, af_token in ((AF_INET, token4), (AF_INET6, token6)):
            if not af_token:
                continue
            sr = self.searches(af).get(info_hash)
            if sr is None:
                continue
            ll = sr.listeners.pop(af_token, None)
            for sn in sr.nodes:
                if not sr.listeners:
                    for e in sn.listen_status.values():
                        self.engine.cancel_request(e.req)
                        self.engine.close_socket(e.socket)
                    sn.listen_status.clear()
                elif ll is not None:
                    e = sn.listen_status.pop(qkey(ll.query), None)
                    if e is not None:
                        self.engine.cancel_request(e.req)
                        self.engine.close_socket(e.socket)
        return True

    def cancel_put(self, info_hash: InfoHash, vid: int) -> bool:
        """ref: Dht::cancelPut src/dht.cpp:2158-2180"""
        cancelled = False
        for srs in (self.searches4, self.searches6):
            sr = srs.get(info_hash)
            if sr is None:
                continue
            before = len(sr.announce)
            sr.announce = [a for a in sr.announce if a.value.id != vid]
            cancelled |= len(sr.announce) < before
        return cancelled

    def get_put(self, info_hash: InfoHash,
                vid: Optional[int] = None):
        out = []
        for srs in (self.searches4, self.searches6):
            sr = srs.get(info_hash)
            if sr is None:
                continue
            for a in sr.announce:
                if vid is None:
                    out.append(a.value)
                elif a.value.id == vid:
                    return a.value
        return out if vid is None else None

    def insert_node(self, nid: InfoHash, addr: SockAddr) -> None:
        """Direct node insertion without ping (bootstrap import)
        (ref: Dht::insertNode src/dht.cpp:3124-3131)."""
        if addr.family not in (AF_INET, AF_INET6):
            return
        node = self.cache.get_node(nid, addr)
        node.time = max(node.time, self.scheduler.time())
        self.on_new_node(node, 0)

    def ping_node(self, addr: SockAddr,
                  done_cb: Optional[Callable[[bool], None]] = None) -> None:
        """ref: Dht::pingNode src/dht.cpp:3134-3149"""
        node = Node(InfoHash.zero(), addr)

        def on_done(req, ans):
            if done_cb:
                done_cb(True)

        def on_expired(req, over):
            if over and done_cb:
                done_cb(False)

        self.engine.send_ping(node, on_done=on_done, on_expired=on_expired)

    # ------------------------------------------------------------------ #
    # maintenance jobs                                                   #
    # ------------------------------------------------------------------ #

    def periodic(self, data: Optional[bytes],
                 from_addr: Optional[SockAddr]) -> float:
        """Process one packet + run due jobs; returns next wakeup
        (ref: Dht::periodic src/dht.cpp:2970-2976)."""
        self.scheduler.sync_time()
        if data:
            self.engine.process_message(data, from_addr)
        return self.scheduler.run()

    def _confirm_nodes(self) -> None:
        """ref: Dht::confirmNodes src/dht.cpp:2991-3027"""
        now = self.scheduler.time()
        soon = False
        if self.running4 and not self.searches4 and \
                self.get_status(AF_INET) == NodeStatus.Connected:
            self.search(self.myid, AF_INET)
        if self.running6 and not self.searches6 and \
                self.get_status(AF_INET6) == NodeStatus.Connected:
            self.search(self.myid, AF_INET6)

        soon |= self._bucket_maintenance(self.buckets4)
        soon |= self._bucket_maintenance(self.buckets6)
        if not soon:
            if self.mybucket_grow_time >= now - 150:
                soon |= self._neighbourhood_maintenance(self.buckets4)
            if self.mybucket6_grow_time >= now - 150:
                soon |= self._neighbourhood_maintenance(self.buckets6)

        delay = self.rng.uniform(5, 25) if soon else self.rng.uniform(60, 180)
        self._confirm_job = self.scheduler.add(now + delay,
                                               self._confirm_nodes)
        self.metrics.counter(
            "dht_maintenance_total", "Periodic maintenance runs",
            ("op",)).inc(op="confirm_nodes")
        self.update_metrics()
        self._check_status_change()

    def _check_status_change(self) -> None:
        st = (self.get_status(AF_INET), self.get_status(AF_INET6))
        if st != self._last_status:
            self._last_status = st
            if self.on_status_changed:
                self.on_status_changed(*st)

    def _neighbourhood_maintenance(self, table: RoutingTable) -> bool:
        """Find nodes near own id (ref: src/dht.cpp:2791-2822)."""
        idx = table.find_bucket_index(self.myid)
        target = InfoHash(bytes(self.myid)[:-1]
                          + bytes([self.rng.getrandbits(8)]))
        q = idx
        if idx + 1 < len(table.buckets) and (
                not table.buckets[q].nodes or self.rng.random() < 1 / 8):
            q = idx + 1
        if idx > 0 and (not table.buckets[q].nodes
                        or self.rng.random() < 1 / 8):
            if table.buckets[idx - 1].nodes:
                q = idx - 1
        n = table.buckets[q].random_node(self.rng)
        if n is not None:
            self.engine.send_find_node(n, target, self._want())
            return True
        return False

    def _bucket_maintenance(self, table: RoutingTable) -> bool:
        """Random find in stale buckets (ref: src/dht.cpp:2824-2885)."""
        now = self.scheduler.time()
        for idx, b in enumerate(table.buckets):
            if b.time < now - 600 or not b.nodes:
                target = table.random_id(idx, self.rng)
                q = idx
                if idx + 1 < len(table.buckets) and (
                        not table.buckets[q].nodes
                        or self.rng.random() < 1 / 8):
                    q = idx + 1
                if idx > 0 and (not table.buckets[q].nodes
                                or self.rng.random() < 1 / 8):
                    if table.buckets[idx - 1].nodes:
                        q = idx - 1
                n = table.buckets[q].random_node(self.rng)
                if n is not None:
                    want = self._want() if self.rng.random() < 1 / 38 else 0
                    self.engine.send_find_node(n, target, want)
                    return True
        return False

    def _expire(self) -> None:
        """ref: Dht::expire src/dht.cpp:2978-2989"""
        now = self.scheduler.time()
        for table in (self.buckets4, self.buckets6):
            for b in table:
                before = len(b.nodes)
                b.nodes = [n for n in b.nodes if not n.is_expired()]
                if len(b.nodes) != before:
                    self._send_cached_ping(b)
        self._expire_storage()
        self._expire_searches()
        self.scheduler.add(now + self.rng.uniform(120, 360), self._expire)
        self.metrics.counter(
            "dht_maintenance_total", "Periodic maintenance runs",
            ("op",)).inc(op="expire")
        self.update_metrics()
        self._check_status_change()

    def _expire_storage(self) -> None:
        now = self.scheduler.time()
        for h in list(self.store.keys()):
            st = self.store[h]
            for node in list(st.listeners.keys()):
                socks = st.listeners[node]
                for sid in list(socks.keys()):
                    if socks[sid].time + NODE_EXPIRE_TIME < now:
                        del socks[sid]
                if not socks:
                    del st.listeners[node]
            size_diff, count_diff, _ = st.expire(self.get_type, now)
            self.total_store_size += size_diff
            self.total_values += count_diff
            if st.is_empty() and not st.listeners and not st.local_listeners:
                del self.store[h]

    def _expire_searches(self) -> None:
        t = self.scheduler.time() - SEARCH_EXPIRE_TIME
        for srs in (self.searches4, self.searches6):
            for key in list(srs.keys()):
                sr = srs[key]
                if not sr.callbacks and not sr.announce and \
                        not sr.listeners and sr.step_time < t:
                    if sr.step_job:
                        sr.step_job.cancel()
                    del srs[key]

    def _data_persistence(self, info_hash: InfoHash) -> None:
        """Republish when no longer among the 8 closest
        (ref: Dht::dataPersistence/maintainStorage src/dht.cpp:2887-2947)."""
        now = self.scheduler.time()
        st = self.store.get(info_hash)
        if st is None or now < st.maintenance_time:
            return
        self._maintain_storage(info_hash, st)
        st.maintenance_time = now + MAX_STORAGE_MAINTENANCE_EXPIRE_TIME
        self.scheduler.add(st.maintenance_time,
                           lambda: self._data_persistence(info_hash))

    def _maintain_storage(self, info_hash: InfoHash, st: Storage,
                          force: bool = False) -> int:
        now = self.scheduler.time()
        announced = 0
        want4 = want6 = True
        for af, table in ((AF_INET, self.buckets4), (AF_INET6, self.buckets6)):
            nodes = table.find_closest_nodes(info_hash, now, TARGET_NODES)
            if nodes and (force or InfoHash.xor_cmp(
                    nodes[-1].id, self.myid, info_hash) < 0):
                for vs in st.values:
                    vt = self.get_type(vs.value.type)
                    if force or vs.created + vt.expiration > \
                            now + MAX_STORAGE_MAINTENANCE_EXPIRE_TIME:
                        self._announce(info_hash, af, vs.value, None,
                                       vs.created, False)
                        announced += 1
                if af == AF_INET:
                    want4 = False
                else:
                    want6 = False
        if not want4 and not want6:
            size_diff, count_diff = st.clear()
            self.total_store_size += size_diff
            self.total_values += count_diff
        return announced

    # ------------------------------------------------------------------ #
    # log dumps (ref: dumpBucket/dumpSearch/getStorageLog                #
    # src/dht.cpp:2497-2730)                                             #
    # ------------------------------------------------------------------ #

    def get_routing_table_log(self, af: int) -> str:
        now = self.scheduler.time()
        out = []
        for b in self.buckets(af).buckets:
            line = f"Bucket {b.first.hex()[:8]}.. "
            if b.cached is not None:
                line += "(cached) "
            out.append(line)
            for n in b.nodes:
                age = now - n.time if n.time > TIME_INVALID else -1
                state = ("good" if n.is_good(now)
                         else "expired" if n.is_expired() else "dubious")
                out.append(f"    Node {n.id} {n.addr.host}:{n.addr.port}"
                           f" [{state}] heard {age:.0f}s ago")
        return "\n".join(out)

    def get_searches_log(self, af: int = 0) -> str:
        now = self.scheduler.time()
        out = []
        for a, srs in ((AF_INET, self.searches4), (AF_INET6,
                                                   self.searches6)):
            if af and a != af:
                continue
            for sr in srs.values():
                out.append(
                    f"Search IPv{a} {sr.id} "
                    f"{'done' if sr.done else 'expired' if sr.expired else 'active'}"
                    f" synced={sr.is_synced(now)}"
                    f" gets={len(sr.callbacks)}"
                    f" announces={len(sr.announce)}"
                    f" listeners={len(sr.listeners)}")
                for sn in sr.nodes:
                    flags = ""
                    flags += "s" if sn.is_synced(now) else "-"
                    flags += "b" if sn.is_bad() else "-"
                    flags += "c" if sn.candidate else "-"
                    out.append(f"    {sn.node.id} [{flags}]")
        return "\n".join(out)

    def get_storage_log(self) -> str:
        now = self.scheduler.time()
        out = [f"Storage: {len(self.store)} keys, "
               f"{self.total_store_size} bytes, "
               f"{self.total_values} values"]
        for h, st in self.store.items():
            listeners = sum(len(m) for m in st.listeners.values())
            out.append(f"  {h}: {len(st.values)} values, "
                       f"{st.total_size} B, {listeners} remote / "
                       f"{len(st.local_listeners)} local listeners")
            for vs in st.values:
                t = self.get_type(vs.value.type)
                exp = vs.created + t.expiration - now
                out.append(f"      id {vs.value.id:016x} type {t.name} "
                           f"{vs.value.size()} B, expires in {exp:.0f}s")
        return "\n".join(out)

    # ------------------------------------------------------------------ #
    # import / export (checkpoint-resume, ref: src/dht.cpp:3029-3121)    #
    # ------------------------------------------------------------------ #

    def export_nodes(self) -> List[Tuple[InfoHash, SockAddr]]:
        now = self.scheduler.time()
        out = []
        for table in (self.buckets4, self.buckets6):
            own = table.find_bucket_index(self.myid)
            order = [own] + [i for i in range(len(table.buckets)) if i != own]
            for i in order:
                for n in table.buckets[i].nodes:
                    if n.is_good(now):
                        out.append((n.id, n.addr))
        return out

    def export_values(self) -> List[Tuple[bytes, bytes]]:
        now = self.scheduler.time()
        out = []
        for h, st in self.store.items():
            vals = [{"v": vs.value.pack(), "a": max(0.0, now - vs.created)}
                    for vs in st.values]
            out.append((bytes(h), msgpack.packb(vals)))
        return out

    def import_values(self, data: List[Tuple[bytes, bytes]]) -> None:
        now = self.scheduler.time()
        for hbytes, blob in data:
            h = InfoHash(bytes(hbytes))
            for entry in msgpack.unpackb(blob, raw=False,
                                         strict_map_key=False):
                try:
                    v = Value.unpack(entry["v"])
                except Exception:
                    continue
                created = now - float(entry.get("a", 0.0))
                self._storage_store(h, v, created)

    def shutdown(self, done_cb: Optional[Callable[[], None]] = None) -> None:
        """Hand off storage then stop (ref: Dht::shutdown src/dht.cpp:736-761)."""
        remaining = [0]

        def on_done(ok, nodes):
            remaining[0] -= 1
            if remaining[0] <= 0 and done_cb:
                done_cb()

        count = 0
        for h, st in list(self.store.items()):
            count += self._maintain_storage(h, st, force=True)
        if count == 0 and done_cb:
            done_cb()
        remaining[0] = count


def _default_types():
    from .default_types import DEFAULT_TYPES
    return DEFAULT_TYPES
