"""Public-key identity layer: RSA keys, X.509 certificates, hybrid encryption.

Re-design of the reference crypto wrappers (ref: include/opendht/crypto.h,
src/crypto.cpp) on top of the ``cryptography`` package instead of
GnuTLS/nettle.  Scheme parity:

* sign/verify: RSA PKCS#1 v1.5 with SHA-512 (ref: src/crypto.cpp:299-313,
  440-449)
* encrypt: plain RSA PKCS#1 v1.5 if payload <= keylen/8 - 11, else an
  RSA-encrypted random AES key followed by AES-GCM(iv | ct | tag)
  (ref: src/crypto.cpp:465-508; GCM layout 120-181)
* key id: SHA-1 of the DER SubjectPublicKeyInfo
  (ref: PublicKey::getId src/crypto.cpp:511-518)
* password KDF: the reference uses argon2i (src/crypto.cpp:194-206); we use
  scrypt (argon2 is not available in-image) — flagged in the API.
* identities: X.509 chains, ``generate_identity`` building CA + leaf
  (ref: src/crypto.cpp:520-1105)
"""

from __future__ import annotations

import datetime
import hashlib
import os
from typing import List, Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import padding, rsa
from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.hazmat.primitives.kdf.scrypt import Scrypt
from cryptography.x509.oid import NameOID

from ..utils.infohash import InfoHash

GCM_IV_SIZE = 12
GCM_DIGEST_SIZE = 16
PASSWORD_SALT_LENGTH = 16


class CryptoException(Exception):
    pass


class DecryptError(CryptoException):
    pass


def aes_key_size(max_block: int) -> int:
    """Largest AES key size fitting the RSA block (ref: src/crypto.cpp:88-95)."""
    for sz in (32, 24, 16):
        if max_block >= sz:
            return sz
    return 0


def aes_encrypt(data: bytes, key: bytes) -> bytes:
    """AES-GCM, output = iv | ciphertext | tag (ref: src/crypto.cpp:120-138)."""
    iv = os.urandom(GCM_IV_SIZE)
    return iv + AESGCM(key).encrypt(iv, data, None)


def aes_decrypt(data: bytes, key: bytes) -> bytes:
    if len(data) <= GCM_IV_SIZE + GCM_DIGEST_SIZE:
        raise DecryptError("Wrong data size")
    try:
        return AESGCM(key).decrypt(data[:GCM_IV_SIZE], data[GCM_IV_SIZE:], None)
    except Exception as e:
        raise DecryptError("Can't decrypt data") from e


def stretch_key(password: str, salt: Optional[bytes], key_length: int = 32
                ) -> Tuple[bytes, bytes]:
    """Password KDF (scrypt here; argon2i in the reference
    src/crypto.cpp:194-206)."""
    if not salt:
        salt = os.urandom(PASSWORD_SALT_LENGTH)
    key = Scrypt(salt=salt, length=key_length, n=2**15, r=8, p=1).derive(
        password.encode("utf-8"))
    return key, salt


def password_encrypt(data: bytes, password: str) -> bytes:
    key, salt = stretch_key(password, None)
    return salt + aes_encrypt(data, key)


def password_decrypt(data: bytes, password: str) -> bytes:
    if len(data) <= PASSWORD_SALT_LENGTH:
        raise DecryptError("Wrong data size")
    key, _ = stretch_key(password, data[:PASSWORD_SALT_LENGTH])
    return aes_decrypt(data[PASSWORD_SALT_LENGTH:], key)


class PublicKey:
    __slots__ = ("_pk", "_der", "_id")

    def __init__(self, pk):
        self._pk = pk
        self._der = pk.public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo)
        self._id = None

    @classmethod
    def from_packed(cls, der: bytes) -> "PublicKey":
        return cls(serialization.load_der_public_key(der))

    def packed(self) -> bytes:
        return self._der

    def get_id(self) -> InfoHash:
        if self._id is None:
            self._id = InfoHash(hashlib.sha1(self._der).digest())
        return self._id

    def get_long_id(self) -> bytes:
        return hashlib.sha256(self._der).digest()

    def check_signature(self, data: bytes, signature: bytes) -> bool:
        try:
            self._pk.verify(signature, data, padding.PKCS1v15(),
                            hashes.SHA512())
            return True
        except Exception:
            return False

    def encrypt(self, data: bytes) -> bytes:
        """Hybrid encryption (ref: src/crypto.cpp:465-508)."""
        key_len = self._pk.key_size // 8
        max_block = key_len - 11
        if len(data) <= max_block:
            return self._pk.encrypt(data, padding.PKCS1v15())
        aks = aes_key_size(max_block)
        if aks == 0:
            raise CryptoException("Key is not long enough for AES128")
        key = os.urandom(aks)
        return self._pk.encrypt(key, padding.PKCS1v15()) + aes_encrypt(data, key)

    def __eq__(self, other):
        return isinstance(other, PublicKey) and self._der == other._der

    def __hash__(self):
        return hash(self._der)

    def __repr__(self):
        return f"PublicKey[{self.get_id()}]"


class PrivateKey:
    __slots__ = ("_sk", "_pub")

    def __init__(self, sk):
        self._sk = sk
        self._pub = PublicKey(sk.public_key())

    @classmethod
    def generate(cls, key_length: int = 4096) -> "PrivateKey":
        return cls(rsa.generate_private_key(public_exponent=65537,
                                            key_size=key_length))

    @classmethod
    def from_der(cls, der: bytes, password: Optional[str] = None) -> "PrivateKey":
        pw = password.encode() if password else None
        return cls(serialization.load_der_private_key(der, pw))

    def serialize(self, password: Optional[str] = None) -> bytes:
        enc = (serialization.BestAvailableEncryption(password.encode())
               if password else serialization.NoEncryption())
        return self._sk.private_bytes(serialization.Encoding.DER,
                                      serialization.PrivateFormat.PKCS8, enc)

    def get_public_key(self) -> PublicKey:
        return self._pub

    def sign(self, data: bytes) -> bytes:
        return self._sk.sign(data, padding.PKCS1v15(), hashes.SHA512())

    def decrypt(self, cipher: bytes) -> bytes:
        """Inverse of PublicKey.encrypt (ref: src/crypto.cpp:328-348)."""
        block = self._sk.key_size // 8
        if len(cipher) < block:
            raise DecryptError("Unexpected cipher length")
        try:
            head = self._sk.decrypt(cipher[:block], padding.PKCS1v15())
        except Exception as e:
            raise DecryptError("RSA decrypt failed") from e
        if len(cipher) == block:
            return head
        return aes_decrypt(cipher[block:], head)


class Certificate:
    """X.509 certificate (chain link) (ref: include/opendht/crypto.h:234-340)."""

    __slots__ = ("_cert", "issuer")

    def __init__(self, cert, issuer: Optional["Certificate"] = None):
        self._cert = cert
        self.issuer = issuer

    @classmethod
    def from_der(cls, der: bytes) -> "Certificate":
        return cls(x509.load_der_x509_certificate(der))

    def packed(self) -> bytes:
        """Full chain DER, leaf first (ref: crypto.h:187-193 packs chain)."""
        out = self._cert.public_bytes(serialization.Encoding.DER)
        if self.issuer is not None:
            out += self.issuer.packed()
        return out

    def get_public_key(self) -> PublicKey:
        return PublicKey(self._cert.public_key())

    def get_id(self) -> InfoHash:
        return self.get_public_key().get_id()

    def get_name(self) -> str:
        attrs = self._cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
        return attrs[0].value if attrs else ""

    def is_ca(self) -> bool:
        try:
            bc = self._cert.extensions.get_extension_for_class(x509.BasicConstraints)
            return bool(bc.value.ca)
        except x509.ExtensionNotFound:
            return False

    def __eq__(self, other):
        return isinstance(other, Certificate) and self.packed() == other.packed()


class Identity:
    """(private key, certificate) pair (ref: crypto.h:63)."""

    __slots__ = ("key", "certificate")

    def __init__(self, key: Optional[PrivateKey] = None,
                 certificate: Optional[Certificate] = None):
        self.key = key
        self.certificate = certificate

    def __bool__(self):
        return self.key is not None and self.certificate is not None


def _build_cert(name: str, pubkey, signer_key, issuer_name: str,
                is_ca: bool) -> x509.Certificate:
    subject = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, name)])
    issuer = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, issuer_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    builder = (x509.CertificateBuilder()
               .subject_name(subject)
               .issuer_name(issuer)
               .public_key(pubkey)
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - datetime.timedelta(days=1))
               .not_valid_after(now + datetime.timedelta(days=365 * 10))
               .add_extension(x509.BasicConstraints(ca=is_ca, path_length=None),
                              critical=True))
    return builder.sign(signer_key, hashes.SHA512())


def generate_identity(name: str = "dhtnode", ca: Optional[Identity] = None,
                      key_length: int = 4096) -> Identity:
    """CA (if none given) + leaf certificate
    (ref: generateIdentity src/crypto.cpp:898-940)."""
    key = PrivateKey.generate(key_length)
    if ca and ca.key:
        cert = _build_cert(name, key._sk.public_key(), ca.key._sk,
                           ca.certificate.get_name(), is_ca=False)
        return Identity(key, Certificate(cert, issuer=ca.certificate))
    cert = _build_cert(name, key._sk.public_key(), key._sk, name, is_ca=True)
    return Identity(key, Certificate(cert))
