"""Public-key identity layer: RSA keys, X.509 certificates, hybrid encryption.

Re-design of the reference crypto wrappers (ref: include/opendht/crypto.h,
src/crypto.cpp) on top of the ``cryptography`` package instead of
GnuTLS/nettle.  Scheme parity:

* sign/verify: RSA PKCS#1 v1.5 with SHA-512 (ref: src/crypto.cpp:299-313,
  440-449)
* encrypt: plain RSA PKCS#1 v1.5 if payload <= keylen/8 - 11, else an
  RSA-encrypted random AES key followed by AES-GCM(iv | ct | tag)
  (ref: src/crypto.cpp:465-508; GCM layout 120-181)
* key id: SHA-1 of the DER SubjectPublicKeyInfo
  (ref: PublicKey::getId src/crypto.cpp:511-518)
* password KDF: argon2i(t=16, m=64 MiB, p=1) + multi-size hash truncate,
  matching the reference byte-for-byte (src/crypto.cpp:194-206; vendored
  argon2 in src/argon2/)
* identities: X.509 chains, ``generate_identity`` building CA + leaf,
  ``RevocationList`` X.509 CRLs (ref: src/crypto.cpp:520-1105,
  include/opendht/crypto.h:165-231)
"""

from __future__ import annotations

import datetime
import hashlib
import os
from typing import List, Optional, Tuple

from argon2 import low_level as argon2_low_level
from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import padding, rsa
from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.x509.oid import ExtensionOID, NameOID

from ..utils.infohash import InfoHash

GCM_IV_SIZE = 12
GCM_DIGEST_SIZE = 16
PASSWORD_SALT_LENGTH = 16


class CryptoException(Exception):
    pass


class DecryptError(CryptoException):
    pass


def aes_key_size(max_block: int) -> int:
    """Largest AES key size fitting the RSA block (ref: src/crypto.cpp:88-95)."""
    for sz in (32, 24, 16):
        if max_block >= sz:
            return sz
    return 0


def aes_encrypt(data: bytes, key: bytes) -> bytes:
    """AES-GCM, output = iv | ciphertext | tag (ref: src/crypto.cpp:120-138)."""
    iv = os.urandom(GCM_IV_SIZE)
    return iv + AESGCM(key).encrypt(iv, data, None)


def aes_decrypt(data: bytes, key: bytes) -> bytes:
    if len(data) <= GCM_IV_SIZE + GCM_DIGEST_SIZE:
        raise DecryptError("Wrong data size")
    try:
        return AESGCM(key).decrypt(data[:GCM_IV_SIZE], data[GCM_IV_SIZE:], None)
    except Exception as e:
        raise DecryptError("Can't decrypt data") from e


def hash_data(data: bytes, hash_len: int) -> bytes:
    """Multi-size hash: SHA-512 above 32 B, SHA-256 above 16 B, else
    SHA-1, truncated to ``hash_len``
    (ref: hash/gnutlsHashAlgo src/crypto.cpp:86-97,209-221)."""
    if hash_len > 32:
        h = hashlib.sha512(data).digest()
    elif hash_len > 16:
        h = hashlib.sha256(data).digest()
    else:
        h = hashlib.sha1(data).digest()
    return h[:hash_len]


def stretch_key(password: str, salt: Optional[bytes], key_length: int = 32
                ) -> Tuple[bytes, bytes]:
    """Password KDF — argon2i(t=16, m=64 MiB, p=1, 32 B raw) then the
    multi-size hash down to ``key_length``, byte-identical to the
    reference's ``stretchKey`` (src/crypto.cpp:194-206)."""
    if not salt:
        salt = os.urandom(PASSWORD_SALT_LENGTH)
    raw = argon2_low_level.hash_secret_raw(
        secret=password.encode("utf-8"), salt=salt, time_cost=16,
        memory_cost=64 * 1024, parallelism=1, hash_len=32,
        type=argon2_low_level.Type.I)
    return hash_data(raw, key_length), salt


def password_encrypt(data: bytes, password: str) -> bytes:
    key, salt = stretch_key(password, None)
    return salt + aes_encrypt(data, key)


def password_decrypt(data: bytes, password: str) -> bytes:
    if len(data) <= PASSWORD_SALT_LENGTH:
        raise DecryptError("Wrong data size")
    key, _ = stretch_key(password, data[:PASSWORD_SALT_LENGTH])
    return aes_decrypt(data[PASSWORD_SALT_LENGTH:], key)


class PublicKey:
    __slots__ = ("_pk", "_der", "_id")

    def __init__(self, pk):
        self._pk = pk
        self._der = pk.public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo)
        self._id = None

    @classmethod
    def from_packed(cls, der: bytes) -> "PublicKey":
        return cls(serialization.load_der_public_key(der))

    def packed(self) -> bytes:
        return self._der

    def get_id(self) -> InfoHash:
        if self._id is None:
            self._id = InfoHash(hashlib.sha1(self._der).digest())
        return self._id

    def get_long_id(self) -> bytes:
        return hashlib.sha256(self._der).digest()

    def check_signature(self, data: bytes, signature: bytes) -> bool:
        try:
            self._pk.verify(signature, data, padding.PKCS1v15(),
                            hashes.SHA512())
            return True
        except Exception:
            return False

    def encrypt(self, data: bytes) -> bytes:
        """Hybrid encryption (ref: src/crypto.cpp:465-508)."""
        key_len = self._pk.key_size // 8
        max_block = key_len - 11
        if len(data) <= max_block:
            return self._pk.encrypt(data, padding.PKCS1v15())
        aks = aes_key_size(max_block)
        if aks == 0:
            raise CryptoException("Key is not long enough for AES128")
        key = os.urandom(aks)
        return self._pk.encrypt(key, padding.PKCS1v15()) + aes_encrypt(data, key)

    def __eq__(self, other):
        return isinstance(other, PublicKey) and self._der == other._der

    def __hash__(self):
        return hash(self._der)

    def __repr__(self):
        return f"PublicKey[{self.get_id()}]"


class PrivateKey:
    __slots__ = ("_sk", "_pub")

    def __init__(self, sk):
        self._sk = sk
        self._pub = PublicKey(sk.public_key())

    @classmethod
    def generate(cls, key_length: int = 4096) -> "PrivateKey":
        return cls(rsa.generate_private_key(public_exponent=65537,
                                            key_size=key_length))

    @classmethod
    def from_der(cls, der: bytes, password: Optional[str] = None) -> "PrivateKey":
        pw = password.encode() if password else None
        return cls(serialization.load_der_private_key(der, pw))

    def serialize(self, password: Optional[str] = None) -> bytes:
        enc = (serialization.BestAvailableEncryption(password.encode())
               if password else serialization.NoEncryption())
        return self._sk.private_bytes(serialization.Encoding.DER,
                                      serialization.PrivateFormat.PKCS8, enc)

    def get_public_key(self) -> PublicKey:
        return self._pub

    def sign(self, data: bytes) -> bytes:
        return self._sk.sign(data, padding.PKCS1v15(), hashes.SHA512())

    def decrypt(self, cipher: bytes) -> bytes:
        """Inverse of PublicKey.encrypt (ref: src/crypto.cpp:328-348)."""
        block = self._sk.key_size // 8
        if len(cipher) < block:
            raise DecryptError("Unexpected cipher length")
        try:
            head = self._sk.decrypt(cipher[:block], padding.PKCS1v15())
        except Exception as e:
            raise DecryptError("RSA decrypt failed") from e
        if len(cipher) == block:
            return head
        return aes_decrypt(cipher[block:], head)


def _der_object_len(data: bytes) -> int:
    """Total length (header + body) of the DER object at data[0]."""
    if len(data) < 2 or data[0] != 0x30:
        raise CryptoException("bad DER sequence")
    first = data[1]
    if first < 0x80:
        return 2 + first
    nlen = first & 0x7F
    if len(data) < 2 + nlen:
        raise CryptoException("truncated DER length")
    return 2 + nlen + int.from_bytes(data[2:2 + nlen], "big")


class RevocationList:
    """X.509 certificate revocation list
    (ref: include/opendht/crypto.h:165-231, src/crypto.cpp:520-680).

    Accumulates revoked certificates, then :meth:`sign` produces the
    DER CRL; ``unpack``/``pack`` round-trip the DER form (the msgpack
    form is a bin of the DER, crypto.h:186-192).
    """

    def __init__(self, packed: Optional[bytes] = None):
        self._crl: Optional[x509.CertificateRevocationList] = None
        self._pending: List[Tuple[int, datetime.datetime]] = []
        if packed:
            self.unpack(packed)

    # -- serialization -----------------------------------------------------
    def unpack(self, data: bytes) -> None:
        self._crl = x509.load_der_x509_crl(data)

    def get_packed(self) -> bytes:
        if self._crl is None:
            raise CryptoException("Revocation list is not signed")
        return self._crl.public_bytes(serialization.Encoding.DER)

    # -- edition -----------------------------------------------------------
    def revoke(self, crt: "Certificate",
               when: Optional[datetime.datetime] = None) -> None:
        """Mark ``crt`` revoked (effective at ``when``, default now) —
        takes effect in the next :meth:`sign` (ref: crypto.h:196)."""
        when = when or datetime.datetime.now(datetime.timezone.utc)
        self._pending.append((crt._cert.serial_number, when))

    def sign(self, key: "PrivateKey", crt: "Certificate",
             validity_period: Optional[datetime.timedelta] = None) -> None:
        """Sign with the issuer's key; ``validity_period`` sets the
        next-update time (ref: RevocationList::sign crypto.h:200-205)."""
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (x509.CertificateRevocationListBuilder()
                   .issuer_name(crt._cert.subject)
                   .last_update(now)
                   .next_update(now + (validity_period
                                       or datetime.timedelta(days=365 * 10)))
                   .add_extension(
                       x509.CRLNumber(int(now.timestamp())), critical=False))
        if self._crl is not None:
            for r in self._crl:
                builder = builder.add_revoked_certificate(r)
        for serial, when in self._pending:
            builder = builder.add_revoked_certificate(
                x509.RevokedCertificateBuilder()
                .serial_number(serial).revocation_date(when).build())
        self._pending.clear()
        self._crl = builder.sign(key._sk, hashes.SHA512())

    # -- queries -----------------------------------------------------------
    def is_revoked(self, crt: "Certificate") -> bool:
        serial = crt._cert.serial_number
        if any(s == serial for s, _ in self._pending):
            return True
        if self._crl is None:
            return False
        return self._crl.get_revoked_certificate_by_serial_number(
            serial) is not None

    def is_signed_by(self, issuer: "Certificate") -> bool:
        if self._crl is None:
            return False
        try:
            return bool(self._crl.is_signature_valid(
                issuer._cert.public_key()))
        except Exception:
            return False

    def get_number(self) -> int:
        """CRL number extension (ref: crypto.h:211-214)."""
        if self._crl is None:
            return 0
        try:
            ext = self._crl.extensions.get_extension_for_oid(
                ExtensionOID.CRL_NUMBER)
            return int(ext.value.crl_number)
        except x509.ExtensionNotFound:
            return 0

    def get_issuer_name(self) -> str:
        if self._crl is None:
            return ""
        attrs = self._crl.issuer.get_attributes_for_oid(NameOID.COMMON_NAME)
        return attrs[0].value if attrs else ""

    def get_update_time(self) -> Optional[datetime.datetime]:
        return self._crl.last_update_utc if self._crl is not None else None

    def get_next_update_time(self) -> Optional[datetime.datetime]:
        return self._crl.next_update_utc if self._crl is not None else None


class Certificate:
    """X.509 certificate (chain link) (ref: include/opendht/crypto.h:234-340)."""

    __slots__ = ("_cert", "issuer", "revocation_lists")

    def __init__(self, cert, issuer: Optional["Certificate"] = None):
        self._cert = cert
        self.issuer = issuer
        self.revocation_lists: List[RevocationList] = []

    @classmethod
    def from_der(cls, der: bytes) -> "Certificate":
        """Parse a certificate or a leaf-first chain (the reference's
        Certificate(Blob) iterates every DER cert in the blob and links
        issuers, ref src/crypto.cpp:560-600)."""
        certs = []
        rest = der
        while rest:
            clen = _der_object_len(rest)
            certs.append(x509.load_der_x509_certificate(rest[:clen]))
            rest = rest[clen:]
        if not certs:
            raise CryptoException("empty certificate blob")
        chain = None
        for c in reversed(certs):  # build from root down
            chain = cls(c, issuer=chain)
        return chain

    def packed(self) -> bytes:
        """Full chain DER, leaf first (ref: crypto.h:187-193 packs chain)."""
        out = self._cert.public_bytes(serialization.Encoding.DER)
        if self.issuer is not None:
            out += self.issuer.packed()
        return out

    def get_public_key(self) -> PublicKey:
        return PublicKey(self._cert.public_key())

    def get_id(self) -> InfoHash:
        return self.get_public_key().get_id()

    def get_name(self) -> str:
        attrs = self._cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
        return attrs[0].value if attrs else ""

    def is_ca(self) -> bool:
        try:
            bc = self._cert.extensions.get_extension_for_class(x509.BasicConstraints)
            return bool(bc.value.ca)
        except x509.ExtensionNotFound:
            return False

    # -- revocation (ref: crypto.h:386-389) -------------------------------
    def add_revocation_list(self, crl: RevocationList) -> None:
        """Attach a CRL issued by this (CA) certificate; rejected unless
        actually signed by us (ref: Certificate::addRevocationList
        src/crypto.cpp — gnutls verifies the CRL signature)."""
        if not crl.is_signed_by(self):
            raise CryptoException("CRL is not signed by this certificate")
        self.revocation_lists.append(crl)

    def get_revocation_lists(self) -> List[RevocationList]:
        return list(self.revocation_lists)

    def is_revoked(self, crt: "Certificate") -> bool:
        """True if any CRL attached to this issuer revokes ``crt``."""
        return any(crl.is_revoked(crt) for crl in self.revocation_lists)

    def __eq__(self, other):
        return isinstance(other, Certificate) and self.packed() == other.packed()


class Identity:
    """(private key, certificate) pair (ref: crypto.h:63)."""

    __slots__ = ("key", "certificate")

    def __init__(self, key: Optional[PrivateKey] = None,
                 certificate: Optional[Certificate] = None):
        self.key = key
        self.certificate = certificate

    def __bool__(self):
        return self.key is not None and self.certificate is not None


def _build_cert(name: str, pubkey, signer_key, issuer_name: str,
                is_ca: bool) -> x509.Certificate:
    subject = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, name)])
    issuer = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, issuer_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    builder = (x509.CertificateBuilder()
               .subject_name(subject)
               .issuer_name(issuer)
               .public_key(pubkey)
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - datetime.timedelta(days=1))
               .not_valid_after(now + datetime.timedelta(days=365 * 10))
               .add_extension(x509.BasicConstraints(ca=is_ca, path_length=None),
                              critical=True))
    return builder.sign(signer_key, hashes.SHA512())


def generate_identity(name: str = "dhtnode", ca: Optional[Identity] = None,
                      key_length: int = 4096) -> Identity:
    """CA (if none given) + leaf certificate
    (ref: generateIdentity src/crypto.cpp:898-940)."""
    key = PrivateKey.generate(key_length)
    if ca and ca.key:
        cert = _build_cert(name, key._sk.public_key(), ca.key._sk,
                           ca.certificate.get_name(), is_ca=False)
        return Identity(key, Certificate(cert, issuer=ca.certificate))
    cert = _build_cert(name, key._sk.public_key(), key._sk, name, is_ca=True)
    return Identity(key, Certificate(cert))
