"""SecureDht: the public-key crypto overlay on the DHT core.

Re-design of the reference ``class SecureDht : public Dht``
(ref: src/securedht.cpp, include/opendht/securedht.h:43-183):

* node id derived from the identity certificate
  (``InfoHash::get("node:" + certId)``, src/securedht.cpp:35-45);
* the node's certificate is announced as a permanent put at the cert's
  own key id (src/securedht.cpp:61-74);
* ``secure_type`` wraps registered value types: the store policy
  verifies signatures of signed values, the edit policy enforces
  same-owner and monotonically increasing ``seq``
  (src/securedht.cpp:80-118);
* ``get``/``listen`` run every value through a filter that verifies
  signed values, decrypts values encrypted for us, and passes plain
  values through (``getCallbackFilter``, src/securedht.cpp:237-279);
* ``put_signed`` bumps ``seq`` above any locally-known or on-DHT value
  with the same id, then signs (src/securedht.cpp:293-328);
* ``put_encrypted`` resolves the recipient's public key over the DHT,
  then signs-and-encrypts (src/securedht.cpp:330-348);
* certificate / public-key caches with a pluggable local cert store
  (include/opendht/securedht.h:153-161).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import msgpack

from ..core.dht import Dht, DhtConfig, DoneCallback, GetCallback
from ..core.default_types import CERTIFICATE_TYPE_ID
from ..core.value import Filter, Value, ValueType, Where, f_id
from ..utils.infohash import InfoHash
from ..utils.logger import NONE, Logger
from .identity import (
    Certificate,
    CryptoException,
    DecryptError,
    Identity,
    PrivateKey,
    PublicKey,
)

CertificateCallback = Callable[[Optional[Certificate]], None]
PublicKeyCallback = Callable[[Optional[PublicKey]], None]


# ---------------------------------------------------------------------------
# Value crypto operations (ref: include/opendht/value.h:300-340)
# ---------------------------------------------------------------------------

def sign_value(key: PrivateKey, v: Value) -> None:
    """Sign ``v`` in place (ref: Value::sign value.h:305-310)."""
    if v.is_encrypted():
        raise CryptoException("Can't sign encrypted data.")
    v.owner = key.get_public_key()
    v.signature = key.sign(v.get_to_sign())


def check_value_signature(v: Value) -> bool:
    """ref: Value::checkSignature value.h:316-318."""
    return (v.is_signed()
            and v.owner.check_signature(v.get_to_sign(), v.signature))


def verify_values_batch(values: List[Value]) -> List[bool]:
    """Batched signature verify — the host half of the device
    integrity plane (:mod:`opendht_tpu.models.integrity`).

    The reference verifies one value per ``getCallbackFilter``
    callback (src/securedht.cpp:237-279); the device engines harvest
    values in batches, so the verify is batch-shaped too: one call per
    harvested batch, serialization amortized, and — driven from a
    :class:`~opendht_tpu.models.integrity.SignatureStage` worker —
    the per-value OpenSSL verifies release the GIL, overlapping the
    next device lookup burst.  A malformed value verifies False, it
    never aborts the batch (one poisoned harvest row must not take
    down the stage)."""
    out = []
    for v in values:
        try:
            out.append(check_value_signature(v))
        except Exception:
            out.append(False)
    return out


def encrypt_value(v: Value, from_key: PrivateKey, to: PublicKey) -> Value:
    """Sign ``v`` with ``from_key`` and return the version encrypted for
    ``to`` (ref: Value::encrypt value.h:327-335)."""
    if v.is_encrypted():
        raise CryptoException("Data is already encrypted.")
    v.recipient = to.get_id()
    sign_value(from_key, v)
    nv = Value(value_id=v.id)
    nv.cypher = to.encrypt(v.get_to_encrypt())
    return nv


def make_certificate_type() -> ValueType:
    """Type 8: a certificate is only storable at its public key's id
    (ref: include/opendht/securedht.h:166-183)."""
    def store(key, value: Value, remote_id, from_addr) -> bool:
        try:
            crt = Certificate.from_der(value.data)
            return crt.get_id() == key
        except Exception:
            return False

    def edit(key, old: Value, new: Value, remote_id, from_addr) -> bool:
        try:
            return (Certificate.from_der(old.data).get_id()
                    == Certificate.from_der(new.data).get_id())
        except Exception:
            return False

    return ValueType(CERTIFICATE_TYPE_ID, "Certificate", 7 * 24 * 3600,
                     store_policy=store, edit_policy=edit)


class SecureDhtConfig:
    """ref: SecureDht::Config include/opendht/securedht.h:48-52."""

    def __init__(self, node_config: Optional[DhtConfig] = None,
                 identity: Optional[Identity] = None):
        self.node_config = node_config or DhtConfig()
        self.identity = identity or Identity()


def _node_config(conf: SecureDhtConfig) -> DhtConfig:
    c = conf.node_config
    if c.node_id is None or not c.node_id:
        ident = conf.identity
        if ident and ident.certificate is not None:
            cert_id = ident.certificate.get_id()
            c.node_id = InfoHash.get("node:" + str(cert_id))
        else:
            c.node_id = InfoHash.get_random()
    return c


class SecureDht(Dht):
    """Dht subclass adding transparent signing/encryption."""

    def __init__(self, transport4=None, transport6=None,
                 config: Optional[SecureDhtConfig] = None,
                 scheduler=None, logger: Logger = NONE, rng=None):
        config = config or SecureDhtConfig()
        super().__init__(transport4, transport6, _node_config(config),
                         scheduler, logger, rng)
        self.key: Optional[PrivateKey] = config.identity.key
        self.certificate: Optional[Certificate] = config.identity.certificate

        self.nodes_certificates: Dict[InfoHash, Certificate] = {}
        self.trusted_certificates: List[Certificate] = []
        self.nodes_pubkeys: Dict[InfoHash, PublicKey] = {}
        # Pluggable local certificate store
        # (ref: setLocalCertificateStore securedht.h:153-156)
        self.local_query_method: Optional[
            Callable[[InfoHash], List[Certificate]]] = None

        # Secure the default types already registered by Dht — all but
        # IpServiceAnnouncement (the single DEFAULT_INSECURE_TYPE,
        # src/default_types.cpp:103-106) — and add the certificate type
        # (insecure: its own store policy rules).
        for t in list(self.types.values()):
            if t.id != 2:
                super().register_type(self.secure_type(t))
        super().register_type(make_certificate_type())

        if self.certificate is not None:
            cert_id = self.certificate.get_id()
            if (self.key is not None
                    and cert_id != self.key.get_public_key().get_id()):
                raise CryptoException(
                    "SecureDht: certificate doesn't match private key.")
            v = Value(self.certificate.packed(), CERTIFICATE_TYPE_ID,
                      value_id=1)
            super().put(cert_id, v, None, None, True)

    def get_id(self) -> Optional[InfoHash]:
        """Id of our public key (not the node id)
        (ref: SecureDht::getId securedht.h:62-64)."""
        return self.key.get_public_key().get_id() if self.key else None

    # ------------------------------------------------------------------ #
    # type wrapping                                                      #
    # ------------------------------------------------------------------ #

    def register_type(self, t: ValueType) -> None:
        super().register_type(self.secure_type(t))

    def register_insecure_type(self, t: ValueType) -> None:
        super().register_type(t)

    def secure_type(self, t: ValueType) -> ValueType:
        """ref: SecureDht::secureType src/securedht.cpp:80-118."""
        base_store, base_edit = t.store_policy, t.edit_policy

        def store(key, value: Value, remote_id, from_addr) -> bool:
            if value.is_signed() and not check_value_signature(value):
                self.log.w("Signature verification failed")
                return False
            return base_store(key, value, remote_id, from_addr)

        def edit(key, old: Value, new: Value, remote_id, from_addr) -> bool:
            if not old.is_signed():
                return base_edit(key, old, new, remote_id, from_addr)
            if not (new.owner is not None and old.owner == new.owner):
                self.log.w("Edition forbidden: owner changed.")
                return False
            if not old.owner.check_signature(new.get_to_sign(),
                                             new.signature):
                self.log.w("Edition forbidden: signature failed.")
                return False
            if old.seq == new.seq:
                # Identical data may be reannounced, possibly by others.
                return old.get_to_sign() == new.get_to_sign()
            return new.seq > old.seq

        return ValueType(t.id, t.name, t.expiration, store_policy=store,
                         edit_policy=edit)

    # ------------------------------------------------------------------ #
    # certificate discovery                                              #
    # ------------------------------------------------------------------ #

    def add_trusted_certificate(self, cert: Certificate) -> None:
        """Register a trust-anchor (CA) certificate whose CRLs are
        consulted when importing certificates — the local trust-list
        role gnutls plays in the reference (crypto.h:386-389).
        Already-cached certificates the new anchor revokes are
        evicted, so revocation applies retroactively."""
        self.trusted_certificates.append(cert)
        self.nodes_certificates = {
            cid: crt for cid, crt in self.nodes_certificates.items()
            if not cert.is_revoked(crt)}

    def is_certificate_revoked(self, crt: Certificate) -> bool:
        """True if any CRL attached to the cert's issuer chain, to our
        own trust chain, or to a registered trust anchor revokes it
        (the gnutls chain verification with CRLs the reference performs
        on import, ref src/crypto.cpp:520-680, crypto.h:386-389)."""
        anchors = list(self.trusted_certificates)
        c = crt.issuer
        while c is not None:
            anchors.append(c)
            c = c.issuer
        own = self.certificate
        while own is not None:
            anchors.append(own)
            own = own.issuer
        return any(a.is_revoked(crt) for a in anchors)

    def register_certificate(self, cert: Certificate) -> InfoHash:
        if self.is_certificate_revoked(cert):
            raise CryptoException("certificate is revoked")
        cid = cert.get_id()
        self.nodes_certificates[cid] = cert
        return cid

    def get_certificate(self, h: InfoHash) -> Optional[Certificate]:
        if self.certificate is not None and self.certificate.get_id() == h:
            return self.certificate
        return self.nodes_certificates.get(h)

    def get_public_key(self, h: InfoHash) -> Optional[PublicKey]:
        if self.key is not None and self.get_id() == h:
            return self.key.get_public_key()
        pk = self.nodes_pubkeys.get(h)
        if pk is None:
            crt = self.get_certificate(h)
            if crt is not None:
                pk = crt.get_public_key()
        return pk

    def find_certificate(self, h: InfoHash,
                         cb: CertificateCallback) -> None:
        """ref: SecureDht::findCertificate src/securedht.cpp:134-180."""
        crt = self.get_certificate(h)
        if crt is not None:
            cb(crt)
            return
        if self.local_query_method is not None:
            res = self.local_query_method(h)
            if res:
                try:
                    # Same import gate as the network path — the local
                    # store may hold since-revoked certificates.
                    self.register_certificate(res[0])
                except CryptoException:
                    cb(None)
                    return
                cb(res[0])
                return

        state = {"found": None}

        def on_values(values: List[Value]) -> bool:
            for v in values:
                if v.type != CERTIFICATE_TYPE_ID:
                    continue
                try:
                    crt = Certificate.from_der(v.data)
                except Exception:
                    continue
                if crt.get_id() == h:
                    try:
                        self.register_certificate(crt)
                    except Exception:
                        continue  # revoked: keep looking
                    state["found"] = crt
                    return False  # stop the get
            return True

        def on_done(ok: bool, nodes) -> None:
            cb(state["found"])

        super().get(h, on_values, on_done,
                    f=lambda v: v.type == CERTIFICATE_TYPE_ID)

    def find_public_key(self, h: InfoHash, cb: PublicKeyCallback) -> None:
        """ref: SecureDht::findPublicKey src/securedht.cpp:182-200."""
        pk = self.get_public_key(h)
        if pk is not None:
            cb(pk)
            return

        def on_cert(crt: Optional[Certificate]) -> None:
            if crt is None:
                cb(None)
                return
            pk = crt.get_public_key()
            self.nodes_pubkeys[pk.get_id()] = pk
            cb(pk)

        self.find_certificate(h, on_cert)

    # ------------------------------------------------------------------ #
    # secure operations                                                  #
    # ------------------------------------------------------------------ #

    def _callback_filter(self, cb: Optional[GetCallback],
                         f: Optional[Filter]) -> GetCallback:
        """ref: getCallbackFilter src/securedht.cpp:237-279."""
        def wrapped(values: List[Value]) -> bool:
            out = []
            for v in values:
                if v.is_encrypted():
                    if self.key is None:
                        continue
                    try:
                        dv = self.decrypt(v)
                    except Exception as e:
                        self.log.w("Could not decrypt value: %s", e)
                        continue
                    if dv.recipient == self.get_id():
                        self.nodes_pubkeys[dv.owner.get_id()] = dv.owner
                        if f is None or f(dv):
                            out.append(dv)
                elif v.is_signed():
                    if check_value_signature(v):
                        self.nodes_pubkeys[v.owner.get_id()] = v.owner
                        if f is None or f(v):
                            out.append(v)
                    else:
                        self.log.w("Signature verification failed")
                else:
                    if f is None or f(v):
                        out.append(v)
            if cb is not None and out:
                return cb(out)
            return True
        return wrapped

    def get(self, info_hash: InfoHash, get_cb: Optional[GetCallback],
            done_cb: Optional[DoneCallback] = None,
            f: Optional[Filter] = None,
            where: Optional[Where] = None) -> None:
        super().get(info_hash, self._callback_filter(get_cb, f), done_cb,
                    None, where)

    def listen(self, info_hash: InfoHash, cb: GetCallback,
               f: Optional[Filter] = None,
               where: Optional[Where] = None) -> int:
        return super().listen(info_hash, self._callback_filter(cb, f),
                              None, where)

    def put_signed(self, info_hash: InfoHash, value: Value,
                   done_cb: Optional[DoneCallback] = None,
                   permanent: bool = False) -> None:
        """ref: SecureDht::putSigned src/securedht.cpp:293-328."""
        if self.key is None:
            raise CryptoException("putSigned needs a private key")
        if value.id == 0:
            value.id = Value.random_id(self.rng)

        # Already announcing this value?  Bump above its seq.
        p = self.get_put(info_hash, value.id)
        if p is not None and value.seq <= p.seq:
            value.seq = p.seq + 1

        my_id = self.get_id()

        def on_values(vals: List[Value]) -> bool:
            for v in vals:
                if not v.is_signed():
                    self.log.e("Existing non-signed value at this key.")
                elif v.owner is None or v.owner.get_id() != my_id:
                    self.log.e("Existing signed value owned by another.")
                elif value.seq <= v.seq:
                    value.seq = v.seq + 1
            return True

        def on_done(ok: bool, nodes) -> None:
            sign_value(self.key, value)
            super(SecureDht, self).put(info_hash, value, done_cb, None,
                                       permanent)

        self.get(info_hash, on_values, on_done, f=f_id(value.id))

    def put_encrypted(self, info_hash: InfoHash, to: InfoHash,
                      value: Value,
                      done_cb: Optional[DoneCallback] = None,
                      permanent: bool = False) -> None:
        """ref: SecureDht::putEncrypted src/securedht.cpp:330-348."""
        if self.key is None:
            raise CryptoException("putEncrypted needs a private key")
        if value.id == 0:
            value.id = Value.random_id(self.rng)

        def on_pk(pk: Optional[PublicKey]) -> None:
            if pk is None:
                if done_cb:
                    done_cb(False, [])
                return
            try:
                ev = encrypt_value(value, self.key, pk)
            except Exception as e:
                self.log.e("Error encrypting data: %s", e)
                if done_cb:
                    done_cb(False, [])
                return
            super(SecureDht, self).put(info_hash, ev, done_cb, None,
                                       permanent)

        self.find_public_key(to, on_pk)

    def decrypt(self, v: Value) -> Value:
        """ref: SecureDht::decrypt src/securedht.cpp:362-380."""
        if not v.is_encrypted():
            raise CryptoException("Data is not encrypted.")
        plain = self.key.decrypt(v.cypher)
        ret = Value(value_id=v.id)
        obj = msgpack.unpackb(plain, raw=False, strict_map_key=False)
        ret._unpack_body(obj)
        if ret.recipient != self.get_id():
            raise DecryptError("Recipient mismatch")
        if not check_value_signature(ret):
            raise DecryptError("Signature mismatch")
        return ret
