"""Logging with per-InfoHash filtering.

Re-design of the reference logger (ref: include/opendht/log_enable.h:43-173,
src/log.cpp:29-84): three levels (debug/warn/error), optional filter that
restricts output to messages mentioning one InfoHash — invaluable when
debugging a single key's traffic in a large swarm.
"""

from __future__ import annotations

import sys
import time
from typing import Optional


class Logger:
    __slots__ = ("name", "level", "_filter", "stream", "enabled")

    DEBUG, WARN, ERROR, OFF = 0, 1, 2, 3

    def __init__(self, name: str = "dht", level: int = OFF, stream=None):
        self.name = name
        self.level = level
        self._filter = None
        self.stream = stream or sys.stderr
        self.enabled = level < Logger.OFF

    def set_filter(self, h: Optional[object],
                   prefix_len: int = 8) -> None:
        """Only emit messages that mention hash ``h``
        (ref: log_enable.h:126-173).

        Matching is by the hash's first ``prefix_len`` hex chars: log
        call sites abbreviate hashes differently (full 40-hex, 8-hex
        short ids, ...), so the filter compares a configurable prefix —
        longer prefixes cut false positives in big swarms, shorter ones
        catch heavily-truncated log forms.  ``prefix_len <= 0`` or a
        prefix longer than the hash string falls back to the full
        string.
        """
        if h:
            s = str(h)
            self._filter = s[:prefix_len] if prefix_len > 0 else s
        else:
            self._filter = None

    def _log(self, lvl_name: str, fmt: str, *args) -> None:
        msg = (fmt % args) if args else fmt
        if self._filter is not None and self._filter not in msg:
            return
        t = time.time()
        ts = time.strftime("%H:%M:%S", time.localtime(t))
        us = int((t % 1) * 1e6)
        print(f"[{ts}.{us:06d}] [{self.name}] {lvl_name}: {msg}",
              file=self.stream)

    def d(self, fmt: str, *args) -> None:
        if self.level <= Logger.DEBUG and self.enabled:
            self._log("DBG", fmt, *args)

    def w(self, fmt: str, *args) -> None:
        if self.level <= Logger.WARN and self.enabled:
            self._log("WRN", fmt, *args)

    def e(self, fmt: str, *args) -> None:
        if self.level <= Logger.ERROR and self.enabled:
            self._log("ERR", fmt, *args)


NONE = Logger(level=Logger.OFF)
