"""Host-plane metrics registry: counters, gauges, histograms with
labels, plus Prometheus text exposition and a JSON view.

The reference exposes per-message-type counters
(ref: include/opendht/network_engine.h:509-516 ``messages_received``/
``messages_sent`` et al.), ``getNodesStats`` and the ``dumpTables``
logs, but no uniform surface to read them from; operators scrape logs.
This module is that missing surface: one registry object shared by the
network engine and the DHT core, rendered by the HTTP gateway's
``/metrics`` (Prometheus text exposition format 0.0.4) and
``/stats.json`` endpoints and by the ``dhtnode`` REPL's ``stats``
command.

Deliberately dependency-free (no prometheus_client — the container
pins its dependency set) and threadsafe: the DHT loop thread writes
while gateway HTTP threads read.  Metric names follow Prometheus
conventions (``_total`` suffix on counters, base units in names).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]

#: Content type of the text exposition every scrape surface serves
#: (the gateway's /metrics route and standalone ``serve_metrics``).
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4"


def _label_key(label_names: Sequence[str], labels: Dict[str, str]
               ) -> LabelKey:
    if set(labels) != set(label_names):
        raise ValueError(
            f"labels {sorted(labels)} != declared {sorted(label_names)}")
    return tuple((k, str(labels[k])) for k in label_names)


def _fmt_labels(key: LabelKey) -> str:
    if not key:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + body + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class Metric:
    """Base: a named family of (label-set → value) series."""

    kind = "untyped"

    def __init__(self, name: str, help: str,
                 label_names: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._series: Dict[LabelKey, float] = {}

    def _key(self, labels: Dict[str, str]) -> LabelKey:
        return _label_key(self.label_names, labels)

    def get(self, **labels) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0.0)

    def series(self) -> List[Tuple[LabelKey, float]]:
        with self._lock:
            return sorted(self._series.items())

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.kind}"]
        series = self.series() or ([((), 0.0)] if not self.label_names
                                   else [])
        for key, val in series:
            out.append(f"{self.name}{_fmt_labels(key)} {_fmt_value(val)}")
        return out

    def to_json(self):
        series = self.series()
        if not self.label_names:
            return series[0][1] if series else 0.0
        return [{**dict(k), "value": v} for k, v in series]


class Counter(Metric):
    """Monotone counter.  ``inc`` only — a decrease is a bug."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counter increments must be >= 0")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount


class Gauge(Metric):
    """Point-in-time value; set/add freely."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def add(self, amount: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount


class Histogram(Metric):
    """Cumulative-bucket histogram (Prometheus semantics: each ``le``
    bucket counts observations ≤ its bound; ``+Inf`` counts all)."""

    kind = "histogram"

    DEFAULT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)

    # Latency-shaped bounds (seconds) for wall-clock distributions —
    # the ledger's per-invocation kernel walls and the serve-mode SLO
    # gauges (ROADMAP #2).  The hop-shaped DEFAULT_BUCKETS above stay
    # the default for count-like observations; pass
    # ``buckets=Histogram.LATENCY_BUCKETS_S`` for time series.
    LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                         0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                         60.0)

    def __init__(self, name: str, help: str,
                 label_names: Sequence[str] = (),
                 buckets: Optional[Iterable[float]] = None):
        super().__init__(name, help, label_names)
        bs = tuple(sorted(buckets if buckets is not None
                          else self.DEFAULT_BUCKETS))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs
        # per label-set: ([counts per bound] + [inf], sum, count)
        self._h: Dict[LabelKey, Tuple[List[int], float, int]] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            counts, total, n = self._h.get(
                key, ([0] * (len(self.buckets) + 1), 0.0, 0))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            counts[-1] += 1
            self._h[key] = (counts, total + value, n + 1)

    def observe_bulk(self, bucket_counts: Sequence[int], total: float,
                     **labels) -> None:
        """Merge pre-aggregated per-bucket counts (NON-cumulative, one
        per bound, overflow last) — how device-side hop histograms are
        folded in without observing L scalars one by one."""
        if len(bucket_counts) != len(self.buckets) + 1:
            raise ValueError(
                f"expected {len(self.buckets) + 1} bucket counts "
                f"(one per bound + overflow), got {len(bucket_counts)}")
        key = self._key(labels)
        with self._lock:
            counts, tot, n = self._h.get(
                key, ([0] * (len(self.buckets) + 1), 0.0, 0))
            cum = 0
            for i in range(len(self.buckets)):
                cum += int(bucket_counts[i])
                counts[i] += cum
            counts[-1] += cum + int(bucket_counts[-1])
            self._h[key] = (counts, tot + total,
                            n + cum + int(bucket_counts[-1]))

    def snapshot(self) -> List[Tuple[LabelKey, Tuple[List[int], float, int]]]:
        with self._lock:
            return sorted((k, (list(c), s, n))
                          for k, (c, s, n) in self._h.items())

    def quantile(self, q: float, **labels) -> float:
        """Bucket-based quantile estimate (Prometheus
        ``histogram_quantile`` semantics): find the bucket the q-th
        observation falls in and interpolate LINEARLY inside it, with
        the first bucket's lower bound taken as 0.  Returns ``nan``
        with no observations; quantiles landing in the ``+Inf``
        overflow bucket clamp to the largest finite bound (past it
        there is nothing to interpolate against)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        key = self._key(labels)
        with self._lock:
            counts, _total, n = self._h.get(
                key, ([0] * (len(self.buckets) + 1), 0.0, 0))
            counts = list(counts)
        if n == 0:
            return float("nan")
        target = q * n
        for i, b in enumerate(self.buckets):
            if counts[i] >= target:
                lo = self.buckets[i - 1] if i else 0.0
                prev = counts[i - 1] if i else 0
                width = counts[i] - prev
                if width <= 0:
                    return float(b)
                return float(lo + (b - lo) * (target - prev) / width)
        return float(self.buckets[-1])

    def bucket_bounds_of_quantile(self, q: float, **labels
                                  ) -> Tuple[float, float]:
        """``(lo, hi]`` bounds of the bucket holding the q-th
        observation (``hi = inf`` for the overflow bucket) — what a
        checker needs to prove a reported quantile is consistent with
        the recorded distribution."""
        key = self._key(labels)
        with self._lock:
            counts, _total, n = self._h.get(
                key, ([0] * (len(self.buckets) + 1), 0.0, 0))
            counts = list(counts)
        if n == 0:
            return (float("nan"), float("nan"))
        target = q * n
        for i, b in enumerate(self.buckets):
            if counts[i] >= target:
                return (self.buckets[i - 1] if i else 0.0, float(b))
        return (float(self.buckets[-1]), float("inf"))

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        snaps = self.snapshot() or (
            [((), ([0] * (len(self.buckets) + 1), 0.0, 0))]
            if not self.label_names else [])
        for key, (counts, total, n) in snaps:
            for i, b in enumerate(self.buckets):
                lk = key + (("le", _fmt_value(b)),)
                out.append(f"{self.name}_bucket{_fmt_labels(lk)} "
                           f"{counts[i]}")
            lk = key + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lk)} {counts[-1]}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} "
                       f"{_fmt_value(total)}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {n}")
        return out

    def to_json(self):
        out = []
        for key, (counts, total, n) in self.snapshot():
            out.append({**dict(key),
                        "buckets": {**{_fmt_value(b): counts[i]
                                       for i, b in enumerate(self.buckets)},
                                    "+Inf": counts[-1]},
                        "sum": total, "count": n})
        if not self.label_names:
            return out[0] if out else {"buckets": {}, "sum": 0.0,
                                       "count": 0}
        return out


class MetricsRegistry:
    """Named metric families; idempotent getters (the second
    ``counter(name)`` call returns the first's object — the engine and
    core share one registry without coordinating construction order)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def _get_or_make(self, cls, name: str, help: str, label_names,
                     **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or \
                        m.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"type or label set")
                # A histogram's bucket bounds are part of its contract:
                # a second registrant asking for DIFFERENT bounds would
                # silently observe into the first's buckets and export
                # a distribution neither asked for.
                want = kw.get("buckets")
                if want is not None and isinstance(m, Histogram) \
                        and tuple(sorted(want)) != m.buckets:
                    raise ValueError(
                        f"histogram {name!r} re-registered with "
                        f"different buckets {tuple(sorted(want))} != "
                        f"{m.buckets}")
                return m
            m = cls(name, help, label_names, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                label_names: Sequence[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help, label_names)

    def gauge(self, name: str, help: str = "",
              label_names: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help, label_names)

    def histogram(self, name: str, help: str = "",
                  label_names: Sequence[str] = (),
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        return self._get_or_make(Histogram, name, help, label_names,
                                 buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def render_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4), newline-terminated."""
        lines: List[str] = []
        for m in self.collect():
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def to_dict(self) -> Dict[str, object]:
        return {m.name: m.to_json() for m in self.collect()}


def serve_metrics(registry: MetricsRegistry, port: int,
                  host: str = "127.0.0.1"):
    """Serve ``registry`` as Prometheus text exposition 0.0.4 on
    ``http://host:port/metrics`` from a daemon thread.

    Standalone scrape surface for tools without their own HTTP server
    (dhtscanner; anything long-running enough to scrape). The HTTP
    gateway instead mounts /metrics as a route on its main server so
    one port covers both the REST API and the scrape (it needs a
    node-state refresh hook at scrape time).

    Returns the server; ``shutdown()`` also closes the listening
    socket. ``port=0`` binds an ephemeral port (read it back from
    ``server_address[1]``).
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.rstrip("/") not in ("/metrics", ""):
                self.send_error(404)
                return
            body = registry.render_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet: progress lives in metrics
            pass

    class Server(ThreadingHTTPServer):
        daemon_threads = True

        def shutdown(self):
            super().shutdown()
            self.server_close()

    srv = Server((host, port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv
