"""Explicit host→device scalar uploads for the host-driven loops.

The burst/serve loops hand their jits a fresh round coordinate every
iteration.  Spelled ``jnp.int32(r)`` that is an IMPLICIT host-to-device
transfer per round — invisible in review, invisible in the profile
(it hides inside dispatch), and exactly the class of hot-path leak
PR 7 caught only because it cost 4.4× on p50.  ``graftlint``'s
strict-mode leg replays the engines under
``jax_transfer_guard=disallow``, which forbids every implicit
transfer; these helpers are the sanctioned spelling — an EXPLICIT
``jax.device_put`` with a small LRU so steady-state loops reuse the
uploaded scalar instead of re-transferring it.  The result is left
UNCOMMITTED (no device argument) on purpose: the round coordinate
must be free to follow the consuming computation's placement — a
scalar pinned to one chip would force a cross-device copy per round
on the sharded mesh.  What the guard checks is that the transfer is
explicit, and after the first call per value there is no transfer at
all.

The cache is bounded (serve round counters grow without bound on a
long-running service) and keyed by value; a miss is just one explicit
upload.  Dtypes match the ``jnp.int32``/``jnp.uint32`` spellings they
replace (strong-typed scalars), so every jit cache key — and therefore
every compiled program — is unchanged.

NEVER pass these at a DONATED jit position: the buffer is shared by
every later cache hit for the same value, and donating it leaves a
dead array in the LRU — the next ``dev_i32(r)`` for that value
returns a deleted buffer and the engine crashes far from the
offending call.  graftlint's ``donated-reuse`` rule flags a
``dev_i32``/``dev_u32`` call placed at a donated argnum.
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = ["dev_i32", "dev_u32"]


@functools.lru_cache(maxsize=4096)
def _dev_i32_cached(v: int):
    import jax
    return jax.device_put(np.int32(v))


@functools.lru_cache(maxsize=4096)
def _dev_u32_cached(v: int):
    import jax
    return jax.device_put(np.uint32(v))


def dev_i32(v):
    """int32 device scalar for ``v`` — explicit (uncommitted) upload,
    cached so steady-state loops re-use it.  A value already on device
    (``jax.Array``, including tracers) passes through with only a
    dtype cast, preserving the input domain of the ``jnp.int32(v)``
    spelling this replaces — and keeping unhashable device arrays out
    of the LRU key."""
    import jax
    if isinstance(v, jax.Array):
        return v.astype(np.int32)
    return _dev_i32_cached(int(v))


def dev_u32(v):
    """uint32 device scalar for ``v`` — explicit (uncommitted) upload,
    cached so steady-state loops re-use it.  Device values pass
    through with only a dtype cast (see ``dev_i32``)."""
    import jax
    if isinstance(v, jax.Array):
        return v.astype(np.uint32)
    return _dev_u32_cached(int(v))
