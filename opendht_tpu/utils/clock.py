"""Time sources for the runtime.

The reference binds everything to ``std::chrono::steady_clock``
(ref: include/opendht/utils.h:37-60).  We instead inject a ``Clock`` so the
whole core can run against a *virtual* clock — this is what makes the DHT
core deterministically unit-testable and lets the lock-step TPU simulator
and the event-driven runtime share one code path.

Times are float seconds.  ``TIME_INVALID`` (= -inf) sorts before every real
time, mirroring the reference's ``time_point::min()`` conventions.
"""

from __future__ import annotations

import time as _time

TIME_INVALID = float("-inf")
TIME_MAX = float("inf")


class Clock:
    def now(self) -> float:  # pragma: no cover - interface
        raise NotImplementedError


class SteadyClock(Clock):
    """Monotonic wall clock for the real (threaded / UDP) runtime."""

    def now(self) -> float:
        return _time.monotonic()


class VirtualClock(Clock):
    """Manually advanced clock for deterministic tests and simulation."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def now(self) -> float:
        return self._t

    def advance(self, dt: float) -> float:
        assert dt >= 0
        self._t += dt
        return self._t

    def set(self, t: float) -> None:
        assert t >= self._t
        self._t = t
