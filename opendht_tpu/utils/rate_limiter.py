"""Sliding-window rate limiting (ref: include/opendht/rate_limiter.h:26-48).

Quota per 1-second sliding window, implemented as a deque of timestamps.
Used by the network engine both globally (1600 req/s) and per source IP
(200 req/s, IPv6 grouped by /64 — ref: network_engine.h:462,572-599).
"""

from __future__ import annotations

from collections import deque


class RateLimiter:
    __slots__ = ("quota", "_hist")

    def __init__(self, quota: int):
        self.quota = quota
        self._hist: deque = deque()

    def limit(self, now: float) -> bool:
        """Record a hit at ``now``; return True if within quota."""
        while self._hist and self._hist[0] < now - 1.0:
            self._hist.popleft()
        if len(self._hist) >= self.quota:
            return False
        self._hist.append(now)
        return True

    def maintain(self, now: float) -> int:
        while self._hist and self._hist[0] < now - 1.0:
            self._hist.popleft()
        return len(self._hist)


def make_rate_limiter(quota: int):
    """Prefer the native (C++) sliding-window limiter when available —
    this sits on the per-packet inbound path (ref:
    network_engine.h:462)."""
    try:
        from ..native import NativeRateLimiter, available
        if available():
            return NativeRateLimiter(quota)
    except Exception:
        pass
    return RateLimiter(quota)
