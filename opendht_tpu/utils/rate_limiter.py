"""Inbound rate limiting (ref: include/opendht/rate_limiter.h:26-48).

Two interchangeable limiters behind one ``limit(now) -> bool`` API:

* :class:`RateLimiter` — the reference's sliding window: quota per
  trailing 1-second window, implemented as a deque of timestamps.
  Exact, but ``limit`` is O(window) deque churn per packet and the
  deque holds up to ``quota`` floats PER SOURCE — the per-IP map pays
  that for every distinct sender.
* :class:`TokenBucket` — the classic token bucket: ``quota`` tokens/s
  accrue up to a ``burst`` ceiling (default ``quota``), one token per
  admitted hit.  O(1) time and O(1) state per source.  At any steady
  arrival rate its long-run admit rate equals the sliding window's
  (``min(arrival, quota)`` per second — property-tested in
  tests/test_rate_limiter.py); the transient difference is burst
  shape only: the window forgets a burst exactly 1 s later, the
  bucket refills it continuously.

Used by the network engine both globally (1600 req/s) and per source
IP (200 req/s, IPv6 grouped by /64 — ref: network_engine.h:462,
572-599).  The per-IP map uses the token bucket (O(1) state per
sender — a flood of distinct sources must not also buy a deque each);
the global limiter keeps the reference's exact sliding window.
"""

from __future__ import annotations

from collections import deque


class RateLimiter:
    __slots__ = ("quota", "_hist")

    def __init__(self, quota: int):
        self.quota = quota
        self._hist: deque = deque()

    def limit(self, now: float) -> bool:
        """Record a hit at ``now``; return True if within quota."""
        while self._hist and self._hist[0] < now - 1.0:
            self._hist.popleft()
        if len(self._hist) >= self.quota:
            return False
        self._hist.append(now)
        return True

    def maintain(self, now: float) -> int:
        while self._hist and self._hist[0] < now - 1.0:
            self._hist.popleft()
        return len(self._hist)


class TokenBucket:
    """O(1) token-bucket limiter: ``quota`` tokens per second accrue
    up to ``burst`` (default ``quota``); each admitted hit spends one.

    Same ``limit(now)`` / ``maintain(now)`` surface as
    :class:`RateLimiter` so the two are drop-in interchangeable.
    ``maintain`` returns the current spent-capacity estimate
    (``burst - tokens``, rounded) — the bucket's analogue of the
    window's in-flight count.  A ``now`` that goes backwards accrues
    nothing (monotone clocks only owe monotone behavior).
    """

    __slots__ = ("quota", "burst", "_tokens", "_last")

    def __init__(self, quota: float, burst: float | None = None):
        if quota <= 0:
            raise ValueError(f"token-bucket quota must be > 0, got "
                             f"{quota}")
        self.quota = float(quota)
        self.burst = float(burst) if burst is not None else float(quota)
        if self.burst < 1.0:
            raise ValueError(f"token-bucket burst must be >= 1, got "
                             f"{self.burst}")
        self._tokens = self.burst
        self._last: float | None = None

    def _refill(self, now: float) -> None:
        # ``_last`` only ever moves FORWARD: a backwards ``now`` must
        # not rewind it, or the next forward sample would re-credit
        # wall time that was already banked (observed-at-review
        # failure mode: t=10, t=0, t=10 again would accrue 10 s of
        # tokens although no time passed since the first sample).
        if self._last is None:
            self._last = now
            return
        dt = now - self._last
        if dt > 0:
            self._tokens = min(self.burst,
                               self._tokens + dt * self.quota)
            self._last = now

    def limit(self, now: float) -> bool:
        """Record a hit at ``now``; return True if a token was
        available (and spend it)."""
        self._refill(now)
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False

    def peek(self, now: float) -> bool:
        """True if a token is available at ``now`` WITHOUT spending
        it.  Lets a caller holding several buckets (the admission
        control's key + class pair) check them all before committing
        any token — a refused composite admission must not drain the
        buckets that said yes.  ``peek`` then ``limit`` at the same
        ``now`` is atomic: the second refill sees dt == 0."""
        self._refill(now)
        return self._tokens >= 1.0

    def maintain(self, now: float) -> int:
        self._refill(now)
        return int(round(self.burst - self._tokens))

    @property
    def tokens(self) -> float:
        return self._tokens


def make_rate_limiter(quota: int, kind: str = "sliding"):
    """Build a limiter for the inbound path.

    ``kind="sliding"`` prefers the native (C++) sliding-window limiter
    when available — this sits on the per-packet inbound path (ref:
    network_engine.h:462).  ``kind="token-bucket"`` returns the O(1)
    :class:`TokenBucket` — what the per-IP limiter map uses, so state
    per distinct sender is one float pair instead of a deque.
    """
    if kind == "token-bucket":
        return TokenBucket(quota)
    if kind != "sliding":
        raise ValueError(f"unknown rate-limiter kind {kind!r}")
    try:
        from ..native import NativeRateLimiter, available
        if available():
            return NativeRateLimiter(quota)
    except Exception:
        pass
    return RateLimiter(quota)
