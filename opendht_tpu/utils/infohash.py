"""160-bit DHT identifiers (InfoHash) and the XOR metric.

TPU-native re-design of the reference's ``InfoHash`` type
(ref: include/opendht/infohash.h:58-215, src/infohash.cpp:46-63).

Two representations coexist:

* :class:`InfoHash` — an immutable host-side wrapper around 20 bytes,
  used by the event-driven C++-style runtime path (protocol, storage,
  routing tables).  Mirrors the reference semantics: ``lowbit``
  (infohash.h:84), three-way ``cmp`` (infohash.h:101), ``common_bits``
  (infohash.h:106), ``xor_cmp`` (infohash.h:131), bit get/set
  (infohash.h:148-162), SHA-1 ``get`` (src/infohash.cpp:46-61) and
  ``get_random`` (src/infohash.cpp:63).

* packed ``uint32[5]`` limbs (big-endian limb order: limb 0 holds bytes
  0-3) — the device-resident form consumed by the batched XOR kernels in
  :mod:`opendht_tpu.ops.xor_topk`.  Lexicographic comparison over limbs
  equals big-integer comparison of the 160-bit id.
"""

from __future__ import annotations

import hashlib
import os
from typing import Iterable, Union

import numpy as np

HASH_LEN = 20  # bytes
HASH_BITS = HASH_LEN * 8
N_LIMBS = 5  # 5 x uint32


class InfoHash:
    """An immutable 160-bit identifier with XOR-metric helpers."""

    __slots__ = ("_b",)

    def __init__(self, data: Union[bytes, bytearray, str, "InfoHash", None] = None):
        if data is None:
            b = bytes(HASH_LEN)
        elif isinstance(data, InfoHash):
            b = data._b
        elif isinstance(data, str):
            # hex string; short/invalid strings yield the zero hash like the
            # reference's fromString (infohash.h:176-189)
            try:
                b = bytes.fromhex(data)
            except ValueError:
                b = b""
            b = b[:HASH_LEN] if len(b) >= HASH_LEN else bytes(HASH_LEN)
        else:
            b = bytes(data)
            if len(b) != HASH_LEN:
                raise ValueError(f"InfoHash needs {HASH_LEN} bytes, got {len(b)}")
        object.__setattr__(self, "_b", b)

    # -- construction ------------------------------------------------------
    @classmethod
    def get(cls, data: Union[bytes, str]) -> "InfoHash":
        """SHA-1 of arbitrary key material (ref: src/infohash.cpp:46-61)."""
        if isinstance(data, str):
            data = data.encode("utf-8")
        return cls(hashlib.sha1(data).digest())

    @classmethod
    def get_random(cls, rng=None) -> "InfoHash":
        if rng is not None:
            return cls(bytes(rng.bytes(HASH_LEN)))
        return cls(os.urandom(HASH_LEN))

    @classmethod
    def zero(cls) -> "InfoHash":
        return cls()

    # -- bytes access ------------------------------------------------------
    def __bytes__(self) -> bytes:
        return self._b

    @property
    def data(self) -> bytes:
        return self._b

    def hex(self) -> str:
        return self._b.hex()

    # -- predicates & metric ----------------------------------------------
    def __bool__(self) -> bool:
        return self._b != bytes(HASH_LEN)

    def xor(self, other: "InfoHash") -> "InfoHash":
        return InfoHash(bytes(a ^ b for a, b in zip(self._b, other._b)))

    def lowbit(self) -> int:
        """Index of the lowest set bit, -1 if zero (ref: infohash.h:84-97)."""
        for i in range(HASH_LEN - 1, -1, -1):
            v = self._b[i]
            if v:
                j = 0
                while not (v & (1 << j)):
                    j += 1
                return 8 * i + (7 - j)
        return -1

    def common_bits(self, other: "InfoHash") -> int:
        """Length of the common binary prefix (ref: infohash.h:106-126)."""
        for i in range(HASH_LEN):
            x = self._b[i] ^ other._b[i]
            if x:
                j = 0
                while not (x & 0x80):
                    x = (x << 1) & 0xFF
                    j += 1
                return 8 * i + j
        return HASH_BITS

    @staticmethod
    def cmp(a: "InfoHash", b: "InfoHash") -> int:
        if a._b < b._b:
            return -1
        if a._b > b._b:
            return 1
        return 0

    @staticmethod
    def xor_cmp(a: "InfoHash", b: "InfoHash", target: "InfoHash") -> int:
        """-1 if ``a`` is XOR-closer to ``target``, 1 if ``b`` is
        (ref: infohash.h:131-146)."""
        for i in range(HASH_LEN):
            xa = a._b[i] ^ target._b[i]
            xb = b._b[i] ^ target._b[i]
            if xa != xb:
                return -1 if xa < xb else 1
        return 0

    def get_bit(self, bit: int) -> bool:
        return bool(self._b[bit // 8] & (0x80 >> (bit % 8)))

    def set_bit(self, bit: int, value: bool) -> "InfoHash":
        b = bytearray(self._b)
        if value:
            b[bit // 8] |= 0x80 >> (bit % 8)
        else:
            b[bit // 8] &= ~(0x80 >> (bit % 8)) & 0xFF
        return InfoHash(bytes(b))

    # -- packed limb form (device path) -----------------------------------
    def to_u32(self) -> np.ndarray:
        """Big-endian uint32 limbs; lexicographic limb order == id order."""
        return np.frombuffer(self._b, dtype=">u4").astype(np.uint32)

    @classmethod
    def from_u32(cls, limbs) -> "InfoHash":
        arr = np.asarray(limbs, dtype=np.uint32)
        return cls(arr.astype(">u4").tobytes())

    # -- dunder ------------------------------------------------------------
    def __eq__(self, other) -> bool:
        return isinstance(other, InfoHash) and self._b == other._b

    def __lt__(self, other: "InfoHash") -> bool:
        return self._b < other._b

    def __le__(self, other: "InfoHash") -> bool:
        return self._b <= other._b

    def __hash__(self) -> int:
        return hash(self._b)

    def __repr__(self) -> str:
        return self.hex()

    def __str__(self) -> str:
        return self.hex()


def pack_ids(ids: Iterable[Union[InfoHash, bytes]]) -> np.ndarray:
    """Pack N 160-bit ids into an ``[N, 5] uint32`` matrix (device layout)."""
    rows = []
    for h in ids:
        b = bytes(h) if isinstance(h, InfoHash) else h
        rows.append(np.frombuffer(b, dtype=">u4"))
    if not rows:
        return np.zeros((0, N_LIMBS), dtype=np.uint32)
    return np.stack(rows).astype(np.uint32)


def unpack_ids(mat: np.ndarray) -> list:
    """Inverse of :func:`pack_ids`."""
    mat = np.asarray(mat, dtype=np.uint32)
    return [InfoHash(row.astype(">u4").tobytes()) for row in mat]


def random_ids(n: int, rng: np.random.Generator) -> np.ndarray:
    """N random ids directly in packed ``[N, 5] uint32`` form."""
    return rng.integers(0, 2**32, size=(n, N_LIMBS), dtype=np.uint32)
