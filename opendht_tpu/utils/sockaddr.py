"""Network endpoint addresses.

Replaces the reference's ``sockaddr_storage`` wrapper
(ref: include/opendht/sockaddr.h:38-71, print_addr src/utils.cpp:26-48) with
a small value type usable both for real UDP endpoints and for virtual
in-memory transport endpoints.
"""

from __future__ import annotations

import ipaddress
from typing import Tuple

AF_INET = 4
AF_INET6 = 6


class SockAddr:
    __slots__ = ("host", "port", "family")

    def __init__(self, host: str = "", port: int = 0, family: int = 0):
        self.host = host
        self.port = int(port)
        if family:
            self.family = family
        elif ":" in host:
            self.family = AF_INET6
        elif host:
            self.family = AF_INET
        else:
            self.family = 0

    @classmethod
    def from_tuple(cls, t: Tuple[str, int]) -> "SockAddr":
        return cls(t[0], t[1])

    def to_tuple(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def is_loopback(self) -> bool:
        try:
            return ipaddress.ip_address(self.host).is_loopback
        except ValueError:
            return False

    def is_private(self) -> bool:
        try:
            return ipaddress.ip_address(self.host).is_private
        except ValueError:
            return False

    def __bool__(self):
        return bool(self.host) and self.port != 0

    def __eq__(self, other):
        return (isinstance(other, SockAddr) and self.host == other.host
                and self.port == other.port and self.family == other.family)

    def __lt__(self, other):
        return (self.family, self.host, self.port) < (
            other.family, other.host, other.port)

    def __hash__(self):
        return hash((self.host, self.port, self.family))

    def __repr__(self):
        if self.family == AF_INET6:
            return f"[{self.host}]:{self.port}"
        return f"{self.host}:{self.port}"

    # -- wire form: packed binary as in compact node info -------------------
    def pack_ip(self) -> bytes:
        """4 or 16 address bytes + 2 port bytes, network order
        (ref node buffers: src/network_engine.cpp:943-992)."""
        ip = ipaddress.ip_address(self.host)
        return ip.packed + self.port.to_bytes(2, "big")

    @classmethod
    def unpack_ip(cls, data: bytes) -> "SockAddr":
        """6/18 bytes = ip+port (node buffers); 4/16 bytes = bare ip,
        port 0 (the ``sa`` echo carries no port — insertAddr,
        ref src/network_engine.cpp:604-613)."""
        if len(data) == 6:
            return cls(str(ipaddress.IPv4Address(data[:4])),
                       int.from_bytes(data[4:6], "big"), AF_INET)
        if len(data) == 18:
            return cls(str(ipaddress.IPv6Address(data[:16])),
                       int.from_bytes(data[16:18], "big"), AF_INET6)
        if len(data) == 4:
            return cls(str(ipaddress.IPv4Address(data)), 0, AF_INET)
        if len(data) == 16:
            return cls(str(ipaddress.IPv6Address(data)), 0, AF_INET6)
        raise ValueError(f"bad packed addr length {len(data)}")
