"""Runtime facade: threaded DhtRunner over real or virtual transports."""

from .dhtrunner import DhtRunner, DhtRunnerConfig  # noqa: F401
