"""Runtime facade: threaded DhtRunner over real or virtual transports."""

# DhtRunner sits on the crypto layer (SecureDht); in containers without
# the optional ``cryptography`` wheel the import is gated so the rest
# of the runtime package (NodeSet) stays usable — same policy as the
# top-level ``opendht_tpu`` facade.
try:
    from .dhtrunner import DhtRunner, DhtRunnerConfig  # noqa: F401
except ImportError as _e:  # pragma: no cover — dep-less containers
    _RUNNER_IMPORT_ERROR = _e

    def __getattr__(name: str):
        if name in ("DhtRunner", "DhtRunnerConfig"):
            raise ImportError(
                f"opendht_tpu.runtime.{name} requires the optional "
                f"crypto dependencies: {_RUNNER_IMPORT_ERROR}")
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
