"""DhtRunner: the thread-safe runtime facade over SecureDht.

Re-design of the reference ``class DhtRunner`` (ref: src/dhtrunner.cpp,
include/opendht/dhtrunner.h:52-415):

* every public operation becomes a closure pushed onto one of two
  queues — ``pending_ops_prio`` (always drained) or ``pending_ops``
  (drained once Connected, or once bootstrap has given up) — executed
  on the loop thread (dhtrunner.cpp:306-322, dhtrunner.h:403-404);
* the loop thread drains ops, feeds received packets to
  ``Dht::periodic``, runs the scheduler, and sleeps until the next
  scheduled wakeup or a condition-variable notification
  (``loop_`` dhtrunner.cpp:306-361);
* packet receive happens on the transport's own thread and is handed
  over through a queue (dhtrunner.cpp:404-454);
* continuous bootstrap: while Disconnected, retry the saved bootstrap
  list every 10 s, most recently added first
  (``tryBootstrapCoutinuously`` dhtrunner.cpp:620-677);
* ``shutdown`` flushes storage announcements then stops; ``join``
  stops threads (dhtrunner.cpp:119-154).

Differences from the reference: transports are injectable (UDP for
real networking, virtual for tests), so the runner is testable without
sockets; futures are ``concurrent.futures.Future``.
"""

from __future__ import annotations

import socket as _socket
import threading
import time as _time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Optional, Tuple

from ..core.dht import DhtConfig, DoneCallback, GetCallback, NodeStatus
from ..core.scheduler import Scheduler
from ..core.value import Filter, Value, Where
from ..crypto.identity import Identity
from ..crypto.securedht import SecureDht, SecureDhtConfig
from ..net.transport import UdpTransport
from ..utils.clock import SteadyClock
from ..utils.infohash import InfoHash
from ..utils.logger import NONE, Logger
from ..utils.sockaddr import AF_INET, AF_INET6, SockAddr

BOOTSTRAP_PERIOD = 10.0  # s, ref: dhtrunner.h:365
# After this many fruitless retry rounds the runner "gives up": the
# normal-op gate opens (ref semantics "Connected-or-gave-up",
# dhtrunner.cpp:316-317) so queued ops run against the empty table and
# their done-callbacks fire with ok=False instead of hanging forever.
BOOTSTRAP_MAX_TRIES = 6


class DhtRunnerConfig:
    """ref: DhtRunner::Config dhtrunner.h:296-299."""

    def __init__(self, dht_config: Optional[SecureDhtConfig] = None,
                 threaded: bool = True):
        self.dht_config = dht_config or SecureDhtConfig()
        self.threaded = threaded


class DhtRunner:
    def __init__(self, logger: Logger = NONE):
        self.log = logger
        self.dht: Optional[SecureDht] = None
        self.scheduler: Optional[Scheduler] = None
        self._t4: Optional[UdpTransport] = None
        self._t6: Optional[UdpTransport] = None

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._ops: deque = deque()
        self._ops_prio: deque = deque()
        self._rcv: deque = deque()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._threaded = True

        self._bootstrap_nodes: List[Tuple[str, int]] = []
        self._bootstrapping = False
        self._bootstrap_job = None
        self._bootstrap_tries = 0
        self._bootstrap_gen = 0

        self.on_status_changed: Optional[Callable[[str, str], None]] = None
        self._status4 = NodeStatus.Disconnected
        self._status6 = NodeStatus.Disconnected

    # ------------------------------------------------------------------ #
    # lifecycle                                                          #
    # ------------------------------------------------------------------ #

    def run(self, port: int = 4222,
            config: Optional[DhtRunnerConfig] = None,
            identity: Optional[Identity] = None,
            bind4: str = "0.0.0.0", bind6: Optional[str] = None,
            transport4=None, transport6=None,
            scheduler: Optional[Scheduler] = None) -> None:
        """Start the node (ref: DhtRunner::run dhtrunner.cpp:59-117).

        Binds UDP sockets unless explicit transports are given.

        The running check-and-claim is ATOMIC (two threads racing
        ``run()`` used to both pass the unlocked ``if self._running``
        guard and double-build the node — the check-then-act shape
        graftlint's lock plane flags); shared state publishes under
        the lock BEFORE the transports start delivering packets, and
        nothing slow (socket bind, transport start, thread start) runs
        while it is held.
        """
        with self._lock:
            if self._running:
                return
            self._running = True            # claimed; we build it
        try:
            config = config or DhtRunnerConfig()
            if identity is not None:
                config.dht_config.identity = identity
            sched = scheduler or Scheduler(SteadyClock())
            if transport4 is None and transport6 is None:
                transport4 = UdpTransport(bind4, port, AF_INET)
                if bind6 is not None:
                    transport6 = UdpTransport(bind6, port, AF_INET6)
            dht = SecureDht(transport4, transport6, config.dht_config,
                            scheduler=sched, logger=self.log)
            dht.on_status_changed = self._on_dht_status
            with self._lock:
                self._threaded = config.threaded
                self.scheduler = sched
                self._t4, self._t6 = transport4, transport6
                self.dht = dht

            for t in (transport4, transport6):
                if t is None:
                    continue
                t.set_receive_callback(self._on_packet)
                start = getattr(t, "start", None)
                if start is not None:
                    start()
        except BaseException:
            # A failed build (port in use, bad bind, ...) must release
            # the claim, or every later run() would return silently at
            # the guard with the node permanently bricked.
            with self._lock:
                self._running = False
            for t in (transport4, transport6):
                if t is not None:
                    try:
                        t.close()
                    except Exception:
                        pass
            raise

        thread = None
        if config.threaded:
            thread = threading.Thread(
                target=self._loop_forever, name="dht-loop", daemon=True)
        with self._lock:
            alive = self._running
            if alive and thread is not None:
                self._thread = thread
        if not alive:
            # A concurrent join() stopped the node mid-build: it saw
            # no thread and no transports, so unwind what we just
            # started instead of leaving bound sockets with no loop.
            for t in (transport4, transport6):
                if t is not None:
                    t.close()
            return
        if thread is not None:
            thread.start()

    def shutdown(self, done_cb: Optional[Callable[[], None]] = None,
                 stop: bool = False) -> None:
        """Flush storage announces (ref: dhtrunner.cpp:119-137)."""
        def op():
            with self._lock:
                dht = self.dht
            if dht is not None:
                dht.shutdown(done_cb)
        self._post(op, prio=True)
        if stop:
            self.join()

    def join(self) -> None:
        """Stop the loop thread and close transports
        (ref: DhtRunner::join dhtrunner.cpp:139-154).

        Pending priority ops (e.g. the shutdown storage flush) are
        drained before the loop stops so ``shutdown(); join()`` cannot
        silently drop the flush."""
        with self._lock:
            thread = self._thread
        if thread is not None and thread.is_alive():
            end = _time.monotonic() + 5
            while _time.monotonic() < end:
                with self._lock:
                    if not self._ops_prio and not self._rcv:
                        break
                _time.sleep(0.01)
        with self._cv:
            self._running = False
            self._cv.notify_all()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)
        for t in (self._t4, self._t6):
            if t is not None:
                t.close()

    def is_running(self) -> bool:
        return self._running

    def get_id(self) -> Optional[InfoHash]:
        return self.dht.get_id() if self.dht else None

    def get_node_id(self) -> Optional[InfoHash]:
        return self.dht.myid if self.dht else None

    def get_bound_port(self) -> int:
        t = self._t4 or self._t6
        return t.local_addr().port if t is not None else 0

    # ------------------------------------------------------------------ #
    # loop                                                               #
    # ------------------------------------------------------------------ #

    def _on_packet(self, data: bytes, from_addr: SockAddr) -> None:
        with self._cv:
            self._rcv.append((data, from_addr))
            self._cv.notify_all()

    def _post(self, op: Callable[[], None], prio: bool = False) -> None:
        with self._cv:
            (self._ops_prio if prio else self._ops).append(op)
            self._cv.notify_all()

    def loop(self) -> float:
        """One manual iteration (non-threaded mode); returns next wakeup
        delay in seconds (ref: DhtRunner::loop dhtrunner.cpp:306-361)."""
        with self._lock:
            prio = list(self._ops_prio)
            self._ops_prio.clear()
            # Normal ops wait for Connected (or bootstrap gave up),
            # ref: dhtrunner.cpp:316-317.
            ready = (self._status4 == NodeStatus.Connected
                     or self._status6 == NodeStatus.Connected
                     or not self._bootstrap_nodes
                     or not self._bootstrapping)
            ops = list(self._ops) if ready else []
            if ready:
                self._ops.clear()
            pkts = list(self._rcv)
            self._rcv.clear()
        for op in prio:
            op()
        for op in ops:
            op()
        wakeup = self.scheduler.clock.now() + 0.25
        for data, addr in pkts:
            wakeup = self.dht.periodic(data, addr)
        wakeup = self.dht.periodic(None, None)
        return max(0.0, wakeup - self.scheduler.clock.now())

    def _loop_forever(self) -> None:
        while True:
            with self._cv:
                if not self._running:
                    break
            delay = self.loop()
            with self._cv:
                if not self._running:
                    break
                if not (self._ops_prio or self._rcv or self._ops):
                    self._cv.wait(timeout=min(delay, 0.25))

    # ------------------------------------------------------------------ #
    # status / bootstrap                                                 #
    # ------------------------------------------------------------------ #

    def _on_dht_status(self, s4: str, s6: str) -> None:
        # Status lands from the loop thread; get_status()/loop() read
        # it from API threads — same lock on both sides.
        with self._lock:
            self._status4, self._status6 = s4, s6
        status = self.get_status()
        if status == NodeStatus.Disconnected and self._bootstrap_nodes:
            self._try_bootstrap_continuously()
        elif status == NodeStatus.Connected:
            with self._lock:
                self._bootstrapping = False
        if self.on_status_changed:
            self.on_status_changed(s4, s6)

    def get_status(self) -> str:
        with self._lock:
            s4, s6 = self._status4, self._status6
        if NodeStatus.Connected in (s4, s6):
            return NodeStatus.Connected
        if NodeStatus.Connecting in (s4, s6):
            return NodeStatus.Connecting
        return NodeStatus.Disconnected

    def bootstrap(self, host: str, port: int = 4222,
                  done_cb: Optional[Callable[[bool], None]] = None) -> None:
        """Add a bootstrap node and ping it
        (ref: DhtRunner::bootstrap dhtrunner.cpp:704-737)."""
        self._bootstrap_nodes.append((host, port))

        def op():
            for addr in self._resolve(host, port):
                self.dht.ping_node(
                    addr, (lambda ok: done_cb(ok)) if done_cb else None)
        self._post(op, prio=True)
        # Arm the 10 s retry chain right away: the initial state is
        # Disconnected and _on_dht_status only fires on *changes*, so a
        # dropped first ping would otherwise strand the node forever.
        if self.get_status() == NodeStatus.Disconnected:
            self._try_bootstrap_continuously()

    def bootstrap_nodes(self,
                        nodes: List[Tuple[InfoHash, SockAddr]]) -> None:
        """Re-insert exported nodes without pinging
        (ref: dhtrunner.cpp:739-749)."""
        def op():
            for nid, addr in nodes:
                self.dht.insert_node(nid, addr)
        self._post(op, prio=True)

    def _try_bootstrap_continuously(self) -> None:
        """ref: tryBootstrapCoutinuously dhtrunner.cpp:620-677.

        Unlike the reference (which retries forever), after
        ``BOOTSTRAP_MAX_TRIES`` fruitless rounds the runner gives up:
        ``_bootstrapping`` clears, which opens the normal-op gate in
        :meth:`loop`, so queued ops (and their futures) complete with
        failure instead of hanging on an unreachable bootstrap.

        Armed from BOTH the loop thread (status change) and API
        threads (:meth:`bootstrap`), so the check-and-arm is atomic
        under the runner lock — the unlocked ``if self._bootstrapping``
        guard used to let two racing callers double-arm the chain and
        double-count tries (the check-then-act shape graftlint's lock
        plane flags).  The lock is scoped to the flag edits only:
        ``_post``/``cancel``/``scheduler.add`` run outside it
        (``_post`` takes the same non-reentrant lock via ``_cv``)."""
        with self._lock:
            if self._bootstrapping or not self._bootstrap_nodes:
                return
            self._bootstrapping = True
            self._bootstrap_tries = 0
            # Generation token: a connect→disconnect cycle can leave
            # the old chain's scheduled job pending; without this it
            # would keep running alongside the new chain, double-
            # counting tries.
            self._bootstrap_gen += 1
            gen = self._bootstrap_gen
            job, self._bootstrap_job = self._bootstrap_job, None
        if job is not None:
            job.cancel()

        def retry():
            with self._lock:
                if (gen != self._bootstrap_gen
                        or not self._bootstrapping
                        or not self._running):
                    return
            if self.get_status() == NodeStatus.Connected:
                with self._lock:
                    self._bootstrapping = False
                return
            with self._lock:
                self._bootstrap_tries += 1
                tries = self._bootstrap_tries
            if tries > BOOTSTRAP_MAX_TRIES:
                # Give up: release the gate and wake the loop so gated
                # ops run now (they will fail fast on the empty table).
                # The give-up is permanent for this chain (deliberate
                # divergence from the reference's retry-forever), so
                # make it VISIBLE: log + fire the status callback so
                # callers know to re-bootstrap() if the network heals.
                with self._lock:
                    self._bootstrapping = False
                    s4, s6 = self._status4, self._status6
                self.log.w("bootstrap gave up after %d fruitless "
                           "rounds; call bootstrap() to retry",
                           BOOTSTRAP_MAX_TRIES)
                if self.on_status_changed:
                    self.on_status_changed(s4, s6)
                with self._cv:
                    self._cv.notify_all()
                return
            # most recently added first
            for host, port in reversed(self._bootstrap_nodes):
                for addr in self._resolve(host, port):
                    self.dht.ping_node(addr, None)
            job2 = self.scheduler.add(
                self.scheduler.time() + BOOTSTRAP_PERIOD, retry)
            with self._lock:
                self._bootstrap_job = job2

        self._post(retry, prio=True)

    @staticmethod
    def _resolve(host: str, port: int) -> List[SockAddr]:
        """DNS resolution (ref: getAddrInfo dhtrunner.cpp:679-702)."""
        try:
            infos = _socket.getaddrinfo(host, port, type=_socket.SOCK_DGRAM)
        except OSError:
            return []
        out, seen = [], set()
        for family, _, _, _, sa in infos:
            if family == _socket.AF_INET:
                a = SockAddr(sa[0], sa[1], AF_INET)
            elif family == _socket.AF_INET6:
                a = SockAddr(sa[0], sa[1], AF_INET6)
            else:
                continue
            k = (a.host, a.port, a.family)
            if k not in seen:
                seen.add(k)
                out.append(a)
        return out

    # ------------------------------------------------------------------ #
    # operations (all enqueue to the loop thread)                        #
    # ------------------------------------------------------------------ #

    def get(self, info_hash: InfoHash, get_cb: Optional[GetCallback],
            done_cb: Optional[DoneCallback] = None,
            f: Optional[Filter] = None,
            where: Optional[Where] = None) -> None:
        self._post(lambda: self.dht.get(info_hash, get_cb, done_cb, f,
                                        where))

    def get_future(self, info_hash: InfoHash,
                   f: Optional[Filter] = None) -> "Future[List[Value]]":
        fut: Future = Future()
        vals: List[Value] = []

        def gcb(vs):
            vals.extend(vs)
            return True

        def dcb(ok, nodes):
            if not fut.done():
                fut.set_result(vals)
        self.get(info_hash, gcb, dcb, f)
        return fut

    def put(self, info_hash: InfoHash, value: Value,
            done_cb: Optional[DoneCallback] = None,
            permanent: bool = False) -> None:
        self._post(lambda: self.dht.put(info_hash, value, done_cb, None,
                                        permanent))

    def put_future(self, info_hash: InfoHash, value: Value,
                   permanent: bool = False) -> "Future[bool]":
        fut: Future = Future()
        self.put(info_hash, value,
                 lambda ok, nodes: fut.done() or fut.set_result(ok),
                 permanent)
        return fut

    def put_signed(self, info_hash: InfoHash, value: Value,
                   done_cb: Optional[DoneCallback] = None,
                   permanent: bool = False) -> None:
        self._post(lambda: self.dht.put_signed(info_hash, value, done_cb,
                                               permanent))

    def put_encrypted(self, info_hash: InfoHash, to: InfoHash,
                      value: Value,
                      done_cb: Optional[DoneCallback] = None,
                      permanent: bool = False) -> None:
        self._post(lambda: self.dht.put_encrypted(info_hash, to, value,
                                                  done_cb, permanent))

    def listen(self, info_hash: InfoHash, cb: GetCallback,
               f: Optional[Filter] = None,
               where: Optional[Where] = None) -> "Future[int]":
        fut: Future = Future()
        self._post(lambda: fut.set_result(
            self.dht.listen(info_hash, cb, f, where)))
        return fut

    def cancel_listen(self, info_hash: InfoHash, token) -> None:
        def op():
            t = token.result() if isinstance(token, Future) else token
            self.dht.cancel_listen(info_hash, t)
        self._post(op)

    def cancel_put(self, info_hash: InfoHash, vid: int) -> None:
        self._post(lambda: self.dht.cancel_put(info_hash, vid))

    def find_certificate(self, h: InfoHash, cb) -> None:
        self._post(lambda: self.dht.find_certificate(h, cb))

    def find_public_key(self, h: InfoHash, cb) -> None:
        self._post(lambda: self.dht.find_public_key(h, cb))

    # ------------------------------------------------------------------ #
    # introspection (loop-thread reads; fine for diagnostics)            #
    # ------------------------------------------------------------------ #

    def get_nodes_stats(self, af: int = AF_INET):
        return self.dht.get_nodes_stats(af)

    def get_node_stats(self, af: int = AF_INET):
        """Full ``NodeStats`` snapshot (good/dubious/cached/incoming
        node counts, live searches, storage keys/values/bytes) — the
        runner-level mirror of the reference ``DhtRunner::getNodesStats``
        returning the ``NodeStats`` struct."""
        return self.dht.node_stats(af)

    def get_stats(self):
        """``(stats_in, stats_out)`` canonical per-message-type wire
        counters (see net.network_engine.CANONICAL_TYPES)."""
        return self.dht.engine.get_stats()

    @property
    def metrics(self):
        """The node's MetricsRegistry (None before :meth:`run`)."""
        return self.dht.metrics if self.dht is not None else None

    def get_public_address(self, af: int = 0):
        return self.dht.get_public_address(af)

    def export_nodes(self):
        return self.dht.export_nodes()

    def export_values(self):
        return self.dht.export_values()

    # ------------------------------------------------------------------ #
    # state persistence (checkpoint/resume; the reference leaves blob    #
    # storage to callers — ref: exportNodes/importValues                 #
    # src/dht.cpp:3029-3121)                                             #
    # ------------------------------------------------------------------ #

    def save_state(self, path: str) -> None:
        """Persist good nodes + stored values to a file."""
        import msgpack

        from .nodeset import NodeSet
        ns = NodeSet(self.dht.export_nodes())
        blob = msgpack.packb({
            "nodes": ns.serialize(),
            "values": self.dht.export_values(),
        })
        with open(path, "wb") as f:
            f.write(blob)

    def load_state(self, path: str) -> int:
        """Re-insert persisted nodes (no pings) and import values.
        Returns the number of bootstrap nodes restored."""
        import msgpack

        from .nodeset import NodeSet
        with open(path, "rb") as f:
            obj = msgpack.unpackb(f.read(), raw=False)
        ns = NodeSet.deserialize(obj["nodes"])
        self.bootstrap_nodes(list(ns))
        vals = [tuple(v) for v in obj.get("values", [])]
        self._post(lambda: self.dht.import_values(vals), prio=True)
        return len(ns)
