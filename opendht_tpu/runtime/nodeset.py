"""NodeSet: a serializable set of node export records.

Parity with the Python binding's ``NodeSet`` (ref: python/opendht.pyx
NodeSet) — the checkpoint/resume container for
``export_nodes()``/``bootstrap_nodes()`` round trips: insertion-ordered,
deduplicated, msgpack-serializable.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Tuple

import msgpack

from ..utils.infohash import InfoHash
from ..utils.sockaddr import SockAddr

NodeExport = Tuple[InfoHash, SockAddr]


class NodeSet:
    def __init__(self, nodes: Iterable[NodeExport] = ()):
        self._nodes: List[NodeExport] = []
        self._seen = set()
        self.extend(nodes)

    def insert(self, nid: InfoHash, addr: SockAddr) -> bool:
        key = (bytes(nid), addr.host, addr.port)
        if key in self._seen:
            return False
        self._seen.add(key)
        self._nodes.append((nid, addr))
        return True

    def extend(self, nodes: Iterable[NodeExport]) -> None:
        for nid, addr in nodes:
            self.insert(nid, addr)

    def first(self) -> NodeExport:
        return self._nodes[0]

    def last(self) -> NodeExport:
        return self._nodes[-1]

    def __len__(self) -> int:
        return len(self._nodes)

    def __iter__(self) -> Iterator[NodeExport]:
        return iter(self._nodes)

    def __contains__(self, item: NodeExport) -> bool:
        nid, addr = item
        return (bytes(nid), addr.host, addr.port) in self._seen

    def serialize(self) -> bytes:
        return msgpack.packb([
            {"id": bytes(nid), "h": addr.host, "p": addr.port,
             "f": addr.family}
            for nid, addr in self._nodes])

    @classmethod
    def deserialize(cls, blob: bytes) -> "NodeSet":
        out = cls()
        for o in msgpack.unpackb(blob, raw=False):
            out.insert(InfoHash(bytes(o["id"])),
                       SockAddr(o["h"], o["p"], o.get("f", 0)))
        return out

    def __repr__(self) -> str:
        return f"NodeSet({len(self._nodes)} nodes)"
