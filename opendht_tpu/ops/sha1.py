"""Batched single-block SHA-1 — device twin of ``InfoHash.get``.

The PHT secondary index locates a trie node at ``SHA-1(prefix content
‖ size byte)`` (``Prefix.hash``, indexation/pht.py — ref pht.h:103-107).
The device index (:mod:`opendht_tpu.models.index`) must derive the SAME
160-bit store keys for a ``[B]`` batch of prefixes, or the host and
device views of one index stop being interchangeable — so the hash is
not approximated or replaced with a cheaper mix: it is SHA-1 itself,
vectorized.

A trie-node message is at most ``prefix_bytes + 1 ≤ 33`` bytes, which
always fits ONE padded 64-byte SHA-1 block (≤ 55 bytes of payload), so
the kernel only implements the single-block compression: 80 rounds of
uint32 rotate/xor/add over ``[B]``-shaped lanes — embarrassingly
batch-parallel, no per-row control flow.  Equality with ``hashlib``
(and hence ``InfoHash.get``) is pinned in ``tests/test_index.py``.

The digest comes back as ``[B, 5] uint32`` big-endian words — exactly
the packed-limb form of an :class:`~opendht_tpu.utils.infohash.InfoHash`
(limb 0 = digest bytes 0-3), so the result IS the storage key the
batched announce/get kernels consume.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_U32 = jnp.uint32
_MASK32 = 0xFFFFFFFF


def _rotl(x: jax.Array, n: int) -> jax.Array:
    return (x << _U32(n)) | (x >> _U32(32 - n))


@jax.jit
def sha1_one_block(msg: jax.Array) -> jax.Array:
    """SHA-1 of one already-padded 64-byte block per row.

    ``msg [..., 16] uint32``: the block as big-endian words — the
    caller has already appended the 0x80 terminator and the 64-bit bit
    length (:func:`sha1_pad_le55` builds it from raw bytes).  Returns
    ``[..., 5] uint32`` big-endian digest words (= InfoHash limbs).

    The 80-round schedule is a static Python unroll of uint32
    elementwise ops (adds wrap mod 2³² natively in uint32): every op is
    ``[B]``-wide, so XLA fuses the whole compression into one pass per
    batch with no gather/scatter at all.
    """
    w = [msg[..., i] for i in range(16)]
    for i in range(16, 80):
        w.append(_rotl(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1))

    shape = msg.shape[:-1]
    a = jnp.full(shape, 0x67452301, _U32)
    b = jnp.full(shape, 0xEFCDAB89, _U32)
    c = jnp.full(shape, 0x98BADCFE, _U32)
    d = jnp.full(shape, 0x10325476, _U32)
    e = jnp.full(shape, 0xC3D2E1F0, _U32)
    h0, h1, h2, h3, h4 = a, b, c, d, e

    for i in range(80):
        if i < 20:
            f = (b & c) | (~b & d)
            k = _U32(0x5A827999)
        elif i < 40:
            f = b ^ c ^ d
            k = _U32(0x6ED9EBA1)
        elif i < 60:
            f = (b & c) | (b & d) | (c & d)
            k = _U32(0x8F1BBCDC)
        else:
            f = b ^ c ^ d
            k = _U32(0xCA62C1D6)
        tmp = _rotl(a, 5) + f + e + k + w[i]
        e, d, c, b, a = d, c, _rotl(b, 30), a, tmp

    return jnp.stack([h0 + a, h1 + b, h2 + c, h3 + d, h4 + e], axis=-1)


def sha1_pad_le55(content: jax.Array, n_bytes: jax.Array) -> jax.Array:
    """Pad per-row variable-length messages (≤ 55 bytes) into one SHA-1
    block.

    ``content [..., C] uint32`` holds the message BYTES packed
    big-endian into words (byte ``k`` of row r is bits
    ``[8·(3-k%4), 8·(4-k%4))`` of ``content[r, k//4]``; bytes at or
    past ``n_bytes`` must already be zero); ``n_bytes [...]`` is the
    per-row message byte length, which must satisfy ``n_bytes ≤ 55``
    (single-block padding) and ``n_bytes ≤ 4·C``.  Returns the padded
    ``[..., 16] uint32`` block for :func:`sha1_one_block`.

    The 0x80 terminator lands at byte ``n_bytes`` and the 64-bit bit
    length in the last two words — all as masked elementwise selects
    over the 14 payload words, so rows with different lengths share one
    compiled program.
    """
    c_words = content.shape[-1]
    nb = n_bytes.astype(jnp.int32)
    words = []
    for wi in range(14):
        if wi < c_words:
            wv = content[..., wi]
        else:
            wv = jnp.zeros(nb.shape, _U32)
        # 0x80 terminator: byte index nb sits in word nb//4 at byte
        # lane nb%4.
        in_word = (nb // 4) == wi
        lane = jnp.clip(nb - 4 * wi, 0, 3)
        term = jnp.where(in_word,
                         _U32(0x80) << (_U32(8) * (3 - lane).astype(_U32)),
                         _U32(0))
        words.append(wv | term)
    bitlen = (nb.astype(_U32) * _U32(8))
    words.append(jnp.zeros(nb.shape, _U32))          # length high word
    words.append(bitlen)                             # length low word
    return jnp.stack(words, axis=-1)
