"""Batched SHA-1 — device twin of ``InfoHash.get``.

The PHT secondary index locates a trie node at ``SHA-1(prefix content
‖ size byte)`` (``Prefix.hash``, indexation/pht.py — ref pht.h:103-107),
and the integrity plane (:mod:`opendht_tpu.models.integrity`) addresses
values by ``id = SHA-1(payload bytes)``.  The device engines must
derive the SAME 160-bit digests as the host, or the host and device
views stop being interchangeable — so the hash is not approximated or
replaced with a cheaper mix: it is SHA-1 itself, vectorized.

Two entry shapes:

* **single block** (:func:`sha1_one_block` over :func:`sha1_pad_le55`)
  — the PHT trie-node message is ≤ 33 bytes and always fits one padded
  64-byte block (≤ 55 bytes of payload);
* **multi block** (:func:`sha1_blocks` over :func:`sha1_pad_blocks`,
  or :func:`sha1_words` for statically fixed-width messages) — the
  integrity plane hashes whole value payloads (``4·W`` bytes, W up to
  the chunk width), so the compression STREAMS over padded
  ``[B, blocks, 16]`` word rows: per static block index one 80-round
  compression pass runs over all ``[B]`` lanes, and rows whose message
  ended earlier carry their finished state through unchanged (a masked
  select per block — no per-row control flow).  Bit-identity with
  ``hashlib`` for arbitrary payload lengths, including the 55/56/64-
  byte padding boundaries, is pinned in ``tests/test_integrity.py``.

Every pass is a static Python unroll of uint32 elementwise ops (adds
wrap mod 2³² natively in uint32): all work is ``[B]``-wide VPU-shaped
lanes, so XLA fuses each compression into one pass per batch with no
gather/scatter at all.

The digest comes back as ``[B, 5] uint32`` big-endian words — exactly
the packed-limb form of an :class:`~opendht_tpu.utils.infohash.InfoHash`
(limb 0 = digest bytes 0-3), so the result IS the storage key the
batched announce/get kernels consume.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_U32 = jnp.uint32
_MASK32 = 0xFFFFFFFF

# SHA-1 initialization vector (FIPS 180-4), shared by every entry.
_IV = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0)


def _rotl(x: jax.Array, n: int) -> jax.Array:
    return (x << _U32(n)) | (x >> _U32(32 - n))


def sha1_compress(state, block: jax.Array):
    """One SHA-1 compression: fold a 64-byte ``block [..., 16]`` into
    ``state`` (a 5-tuple of ``[...]`` uint32 lanes — kept unstacked so
    chained compressions never round-trip through a stack/unstack
    pair).  Returns the new 5-tuple.
    """
    w = [block[..., i] for i in range(16)]
    for i in range(16, 80):
        w.append(_rotl(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1))

    h0, h1, h2, h3, h4 = state
    a, b, c, d, e = h0, h1, h2, h3, h4
    for i in range(80):
        if i < 20:
            f = (b & c) | (~b & d)
            k = _U32(0x5A827999)
        elif i < 40:
            f = b ^ c ^ d
            k = _U32(0x6ED9EBA1)
        elif i < 60:
            f = (b & c) | (b & d) | (c & d)
            k = _U32(0x8F1BBCDC)
        else:
            f = b ^ c ^ d
            k = _U32(0xCA62C1D6)
        tmp = _rotl(a, 5) + f + e + k + w[i]
        e, d, c, b, a = d, c, _rotl(b, 30), a, tmp

    return (h0 + a, h1 + b, h2 + c, h3 + d, h4 + e)


def _iv(shape) -> tuple:
    return tuple(jnp.full(shape, v, _U32) for v in _IV)


@jax.jit
def sha1_one_block(msg: jax.Array) -> jax.Array:
    """SHA-1 of one already-padded 64-byte block per row.

    ``msg [..., 16] uint32``: the block as big-endian words — the
    caller has already appended the 0x80 terminator and the 64-bit bit
    length (:func:`sha1_pad_le55` builds it from raw bytes).  Returns
    ``[..., 5] uint32`` big-endian digest words (= InfoHash limbs).
    """
    return jnp.stack(sha1_compress(_iv(msg.shape[:-1]), msg), axis=-1)


def n_blocks_for(n_bytes: int) -> int:
    """Padded SHA-1 block count for an ``n_bytes`` message: the 0x80
    terminator plus the 8-byte bit length must fit, so
    ``⌊(n_bytes + 8) / 64⌋ + 1`` — 55 B → 1 block, 56 B → 2,
    119 B → 2, 120 B → 3 (the boundaries the property tests pin)."""
    return (n_bytes + 8) // 64 + 1


@jax.jit
def sha1_blocks(blocks: jax.Array, n_blocks: jax.Array) -> jax.Array:
    """Streaming SHA-1 over padded multi-block rows.

    ``blocks [..., NB, 16] uint32``: each row's padded message as NB
    64-byte blocks (:func:`sha1_pad_blocks` builds them; blocks at or
    past a row's ``n_blocks`` are ignored); ``n_blocks [...]`` is the
    per-row ACTIVE block count (≥ 1).  The compression runs NB static
    passes over all rows; a row whose message already ended carries its
    finished state through a masked select — shorter rows cost the same
    wall as the longest, which is the lock-step batch contract every
    engine here uses.  Returns ``[..., 5] uint32`` digest words.
    """
    nb = blocks.shape[-2]
    state = _iv(blocks.shape[:-2])
    n_act = n_blocks.astype(jnp.int32)
    for bi in range(nb):
        new = sha1_compress(state, blocks[..., bi, :])
        if bi == 0:
            state = new          # every message has ≥ 1 block
        else:
            live = bi < n_act
            state = tuple(jnp.where(live, n, s)
                          for n, s in zip(new, state))
    return jnp.stack(state, axis=-1)


def sha1_pad_blocks(content: jax.Array, n_bytes: jax.Array):
    """Pad per-row variable-length messages into SHA-1 blocks.

    ``content [..., C] uint32`` holds the message BYTES packed
    big-endian into words (byte ``k`` of a row is bits
    ``[8·(3-k%4), 8·(4-k%4))`` of ``content[..., k//4]``; bytes at or
    past that row's ``n_bytes`` must already be zero); ``n_bytes
    [...]`` is the per-row byte length, ``n_bytes ≤ 4·C``.  Returns
    ``(blocks [..., NB, 16], n_blocks [...])`` for
    :func:`sha1_blocks`, with ``NB = n_blocks_for(4·C)`` static.

    The 0x80 terminator lands at byte ``n_bytes`` and the 64-bit bit
    length in the last two words of each row's LAST ACTIVE block — all
    as masked elementwise selects over the flat word index, so rows
    with different lengths share one compiled program.  (The length
    words can never collide with content: ``n_bytes + 9 ≤ 64·n_blocks``
    by construction, so the final 8 bytes of the last active block are
    always past the message.)
    """
    c_words = content.shape[-1]
    nb_static = n_blocks_for(4 * c_words)
    nb = n_bytes.astype(jnp.int32)
    n_blocks = (nb + 8) // 64 + 1
    # Flat word index gw ∈ [0, 16·NB): word gw covers message bytes
    # [4·gw, 4·gw+4).
    words = []
    for gw in range(16 * nb_static):
        if gw < c_words:
            wv = content[..., gw]
        else:
            wv = jnp.zeros(nb.shape, _U32)
        in_word = (nb // 4) == gw
        lane = jnp.clip(nb - 4 * gw, 0, 3)
        term = jnp.where(in_word,
                         _U32(0x80) << (_U32(8) * (3 - lane).astype(_U32)),
                         _U32(0))
        # 64-bit message length: high word always 0 for any 4·C < 2²⁹
        # bytes (the int32 geometry cap), low word = 8·n_bytes at the
        # last word of the row's last active block.
        is_len = (16 * n_blocks - 1) == gw
        ln = jnp.where(is_len, nb.astype(_U32) * _U32(8), _U32(0))
        words.append(wv | term | ln)
    blocks = jnp.stack(words, axis=-1)
    return blocks.reshape(blocks.shape[:-1] + (nb_static, 16)), n_blocks


def sha1_bytes(content: jax.Array, n_bytes: jax.Array) -> jax.Array:
    """SHA-1 of per-row variable-length messages: pad
    (:func:`sha1_pad_blocks`) + stream (:func:`sha1_blocks`).
    ``content [..., C] uint32`` big-endian packed bytes, ``n_bytes
    [...]`` per-row lengths ≤ 4·C.  Returns ``[..., 5]`` digests."""
    blocks, n_blocks = sha1_pad_blocks(content, n_bytes)
    return sha1_blocks(blocks, n_blocks)


def sha1_words(content: jax.Array) -> jax.Array:
    """SHA-1 of FIXED-width word rows: every row is exactly
    ``content.shape[-1]`` uint32 words = ``4·W`` big-endian bytes (the
    integrity plane's payload shape).  With the length static, the
    padding folds into program constants and the per-block liveness
    selects of :func:`sha1_blocks` vanish — this is the form the
    verified insert/get programs inline (like ``_payload_digest``,
    it is a plain traced function, not its own jit).
    """
    w = content.shape[-1]
    n_bytes = 4 * w
    nb = n_blocks_for(n_bytes)
    shape = content.shape[:-1]
    state = _iv(shape)
    zero = jnp.zeros(shape, _U32)
    for bi in range(nb):
        words = []
        for wi in range(16):
            gw = bi * 16 + wi
            if gw < w:
                wv = content[..., gw]
            elif gw == w:        # terminator at byte 4·W, lane 0
                wv = jnp.full(shape, 0x80000000, _U32)
            elif gw == nb * 16 - 1:
                wv = jnp.full(shape, 8 * n_bytes, _U32)
            else:
                wv = zero
            words.append(wv)
        state = sha1_compress(state, jnp.stack(words, axis=-1))
    return jnp.stack(state, axis=-1)


def sha1_pad_le55(content: jax.Array, n_bytes: jax.Array) -> jax.Array:
    """Pad per-row variable-length messages (≤ 55 bytes) into one SHA-1
    block.

    ``content [..., C] uint32`` holds the message BYTES packed
    big-endian into words (byte ``k`` of row r is bits
    ``[8·(3-k%4), 8·(4-k%4))`` of ``content[r, k//4]``; bytes at or
    past ``n_bytes`` must already be zero); ``n_bytes [...]`` is the
    per-row message byte length, which must satisfy ``n_bytes ≤ 55``
    (single-block padding) and ``n_bytes ≤ 4·C``.  Returns the padded
    ``[..., 16] uint32`` block for :func:`sha1_one_block`.

    The 0x80 terminator lands at byte ``n_bytes`` and the 64-bit bit
    length in the last two words — all as masked elementwise selects
    over the 14 payload words, so rows with different lengths share one
    compiled program.
    """
    c_words = content.shape[-1]
    nb = n_bytes.astype(jnp.int32)
    words = []
    for wi in range(14):
        if wi < c_words:
            wv = content[..., wi]
        else:
            wv = jnp.zeros(nb.shape, _U32)
        # 0x80 terminator: byte index nb sits in word nb//4 at byte
        # lane nb%4.
        in_word = (nb // 4) == wi
        lane = jnp.clip(nb - 4 * wi, 0, 3)
        term = jnp.where(in_word,
                         _U32(0x80) << (_U32(8) * (3 - lane).astype(_U32)),
                         _U32(0))
        words.append(wv | term)
    bitlen = (nb.astype(_U32) * _U32(8))
    words.append(jnp.zeros(nb.shape, _U32))          # length high word
    words.append(bitlen)                             # length low word
    return jnp.stack(words, axis=-1)
