"""Device-side kernels: batched 160-bit XOR metric, top-k, Pallas hot ops."""

from .xor_metric import (  # noqa: F401
    common_bits,
    common_bits32,
    closest_nodes,
    closest_nodes_batched,
    merge_shortlists,
    merge_shortlists_d0,
    prefix_len32,
    rank_merge_round_d0,
    sort_by_distance,
    xor_ids,
    xor_less,
)
from .pallas_kernels import (  # noqa: F401
    merge_round_pallas,
    nearest_ids,
    nearest_k_ids,
)
