"""Pallas TPU kernels for the 160-bit XOR metric hot path.

The single hottest dense op in the swarm engine is "which k stored
nodes are XOR-nearest to this target" over a node matrix far too large
to materialise a ``[L, N]`` distance plane in HBM (the north star —
L=1M lookups over N=10M nodes — would need a 40 TB plane).  This
module implements it as a tiled streaming Pallas kernel (ref
semantics: the XOR-sorted scan of ``RoutingTable::findClosestNodes``,
src/routing_table.cpp:67-111, and ``InfoHash::xorCmp``,
include/opendht/infohash.h:131-146):

* grid = (L tiles, N tiles); the N axis is the minor, sequentially
  executed dimension — the node matrix streams through VMEM once per
  L tile, so HBM traffic is O(L·5 + N·5) per tile pair, never O(L·N);
* a per-target running best-``k+margin`` list (64-bit surrogate
  distance + global index) lives in VMEM scratch, laid out
  ``[tile_l, kb]`` so every per-candidate op is a lane-sliced 2D op
  (Mosaic rejects 1-D vector shuffles);
* per N tile, ``kb`` rounds of masked lexicographic argmin extract the
  tile's best candidates, each shift-inserted into the sorted running
  list with an unrolled compare/select chain;
* exactness beyond the 64-bit surrogate is restored by a final 160-bit
  5-limb ``lax.sort`` over the ``kb``-wide shortlist (margin ≥ 8), so
  the result is the true top-k unless > ``margin`` candidates tie with
  the k-th best on their first 64 distance bits (P ≈ (N/2^64)·margin
  for the swarm's uniform ids).

On non-TPU backends the same kernel runs under ``interpret=True`` so
tests exercise identical code.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N_LIMBS = 5
_PAD_LIMBS = 8  # sublane tile for uint32
_MAX = 0xFFFFFFFF  # kept as a Python int: a captured jnp scalar would be a kernel constant


def _lex_lt2(a0, a1, b0, b1):
    """64-bit lexicographic (a0,a1) < (b0,b1) on uint32 arrays."""
    return (a0 < b0) | ((a0 == b0) & (a1 < b1))


def _nearest_k_kernel(t_ref, n_ref, v_ref, o_ref, bd0, bd1, bi, *,
                      tn: int, kb: int, n_real: int):
    """Streaming k-best by 64-bit surrogate distance.

    ``t_ref [TL, 8]`` targets (limbs minor), ``n_ref [8, TN]`` nodes
    (limbs major), ``v_ref [1, TN]`` validity, ``o_ref [TL, kb]`` out.
    Running best in ``bd0/bd1/bi [TL, kb]`` kept ascending per row.

    Distances are carried in sign-flipped int32 (``x ^ 0x80000000``
    bitcast), which preserves unsigned order — Mosaic has no unsigned
    min-reduction.
    """
    ln = pl.program_id(1)
    imax = jnp.int32(0x7FFFFFFF)  # sign-flipped image of uint32 MAX

    @pl.when(ln == 0)
    def _init():
        bd0[...] = jnp.full_like(bd0, imax)
        bd1[...] = jnp.full_like(bd1, imax)
        bi[...] = jnp.full_like(bi, -1)

    def signed(x):
        return jax.lax.bitcast_convert_type(
            x ^ jnp.uint32(0x80000000), jnp.int32)

    tl = t_ref.shape[0]
    d0 = signed(jnp.bitwise_xor(t_ref[:, 0:1], n_ref[0:1, :]))  # [TL,TN]
    d1 = signed(jnp.bitwise_xor(t_ref[:, 1:2], n_ref[1:2, :]))

    iota = jax.lax.broadcasted_iota(jnp.int32, (tl, tn), 1)
    # Valid = inside the real node matrix (not tile padding) and not
    # masked out (dead) by the caller.
    mask = ((ln * tn + iota) < n_real) & (v_ref[0:1, :] != 0)

    # Tile skip gate: if no row's masked tile minimum can beat (or tie)
    # that row's current kb-th best on limb 0, the tile cannot change
    # the running list.  Conservative — ties proceed to the full
    # extraction, where limb 1 decides.  After the list warms up this
    # skips the vast majority of tiles (P(hit) ≈ TN·kb / nodes_seen).
    d0_gate = jnp.where(mask, d0, imax)
    m0_gate = jnp.min(d0_gate, axis=1, keepdims=True)       # [TL, 1]
    improve = jnp.any(m0_gate <= bd0[:, kb - 1:kb])

    @pl.when(improve)
    def _extract():
        _extract_rounds(d0, d1, mask, iota, ln, tn, kb, imax,
                        bd0, bd1, bi)

    @pl.when(ln == pl.num_programs(1) - 1)
    def _flush():
        o_ref[...] = bi[...]


def _extract_rounds(d0, d1, mask, iota, ln, tn, kb, imax, bd0, bd1, bi):
    # Running best as lists of [TL, 1] columns (read once, write once).
    B0 = [bd0[:, j:j + 1] for j in range(kb)]
    B1 = [bd1[:, j:j + 1] for j in range(kb)]
    BI = [bi[:, j:j + 1] for j in range(kb)]

    for _ in range(kb):
        d0m = jnp.where(mask, d0, imax)
        m0 = jnp.min(d0m, axis=1, keepdims=True)          # [TL, 1]
        c0mask = mask & (d0m == m0)
        d1m = jnp.where(c0mask, d1, imax)
        m1 = jnp.min(d1m, axis=1, keepdims=True)
        cand = c0mask & (d1m == m1)
        win = jnp.min(jnp.where(cand, iota, jnp.int32(tn)), axis=1,
                      keepdims=True)                      # [TL, 1]
        mask = mask & (iota != win)
        empty = win == tn
        c0 = jnp.where(empty, imax, m0)
        c1 = jnp.where(empty, imax, m1)
        ci = jnp.where(empty, -1, ln * tn + win)
        # Shift-insert into the ascending running list.
        lt = [_lex_lt2(c0, c1, B0[j], B1[j]) for j in range(kb)]
        nB0, nB1, nBI = [], [], []
        for j in range(kb):
            if j == 0:
                nB0.append(jnp.where(lt[0], c0, B0[0]))
                nB1.append(jnp.where(lt[0], c1, B1[0]))
                nBI.append(jnp.where(lt[0], ci, BI[0]))
            else:
                here = lt[j] & ~lt[j - 1]
                nB0.append(jnp.where(~lt[j], B0[j],
                                     jnp.where(here, c0, B0[j - 1])))
                nB1.append(jnp.where(~lt[j], B1[j],
                                     jnp.where(here, c1, B1[j - 1])))
                nBI.append(jnp.where(~lt[j], BI[j],
                                     jnp.where(here, ci, BI[j - 1])))
        B0, B1, BI = nB0, nB1, nBI

    bd0[...] = jnp.concatenate(B0, axis=1)
    bd1[...] = jnp.concatenate(B1, axis=1)
    bi[...] = jnp.concatenate(BI, axis=1)


def _pad_to(x: jax.Array, mult: int, axis: int, fill) -> jax.Array:
    n = x.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad, constant_values=jnp.asarray(fill, x.dtype))


@partial(jax.jit,
         static_argnames=("k", "margin", "tile_l", "tile_n", "interpret"))
def nearest_k_ids(ids: jax.Array, targets: jax.Array, k: int = 8, *,
                  valid: jax.Array | None = None, margin: int = 8,
                  tile_l: int = 64, tile_n: int = 8192,
                  interpret: bool | None = None) -> jax.Array:
    """Exact k XOR-closest rows of ``ids [N,5]`` per target, streamed.

    ``targets [L,5]`` → ``[L,k]`` int32, closest first (-1 where fewer
    than k valid nodes exist).  ``valid``: optional ``[N]`` bool.
    See module docstring for the algorithm and exactness bound.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, l = ids.shape[0], targets.shape[0]
    kb = -(-max(k + margin, 8) // 8) * 8  # sublane-aligned shortlist
    # Scoped-VMEM budget: the kernel's live set is dominated by a
    # handful of [tile_l, tile_n] i32 streaming temporaries whose live
    # ranges grow with the kb unrolled extraction rounds (measured on
    # v5e: kb=16 @ 64x8192 fits the 16 MB scoped limit, kb=32 @ 64x8192
    # allocates 21.2 MB and fails to compile).  Shrink tile_n as kb
    # grows past 16 so tile_l*tile_n*kb stays at or below the known-good
    # product; lane-align to 512.
    if kb > 16:
        tile_n = max(512, (tile_n * 16 // kb) // 512 * 512)

    # Nodes limb-major [8, N]; targets limb-minor [L, 8].  Padded node
    # entries are masked inside the kernel by global index (>= n_real),
    # so the pad value is inert.
    ids_t = _pad_to(ids.T.astype(jnp.uint32), _PAD_LIMBS, 0, 0)
    ids_t = _pad_to(ids_t, tile_n, 1, _MAX)
    tg = _pad_to(targets.astype(jnp.uint32), _PAD_LIMBS, 1, 0)
    tg = _pad_to(tg, tile_l, 0, 0)
    n_pad, l_pad = ids_t.shape[1], tg.shape[0]
    if valid is None:
        vrow = jnp.ones((1, n_pad), jnp.uint32)
    else:
        vrow = _pad_to(valid.astype(jnp.uint32)[None, :], tile_n, 1, 0)

    grid = (l_pad // tile_l, n_pad // tile_n)
    out = pl.pallas_call(
        partial(_nearest_k_kernel, tn=tile_n, kb=kb, n_real=n),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_l, _PAD_LIMBS), lambda li, ni: (li, 0)),
            pl.BlockSpec((_PAD_LIMBS, tile_n), lambda li, ni: (0, ni)),
            pl.BlockSpec((1, tile_n), lambda li, ni: (0, ni)),
        ],
        out_specs=pl.BlockSpec((tile_l, kb), lambda li, ni: (li, 0)),
        out_shape=jax.ShapeDtypeStruct((l_pad, kb), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((tile_l, kb), jnp.int32),
            pltpu.VMEM((tile_l, kb), jnp.int32),
            pltpu.VMEM((tile_l, kb), jnp.int32),
        ],
        interpret=interpret,
    )(tg, ids_t, vrow)

    short = out[:l]                                        # [L,kb]
    # Exact 160-bit refine over the shortlist.  Empty slots sort last
    # via explicit all-ones *distance* (an all-ones sentinel id would
    # not be far from targets with leading 1-bits).
    cand = ids[jnp.clip(short, 0, n - 1)]                  # [L,kb,5]
    d = jnp.bitwise_xor(cand, targets[:, None, :])
    d = jnp.where((short < 0)[..., None], jnp.uint32(_MAX), d)
    keys = tuple(d[..., i] for i in range(N_LIMBS))
    sorted_ = jax.lax.sort(keys + (short,), dimension=1, num_keys=N_LIMBS)
    return sorted_[N_LIMBS][:, :k]


# ---------------------------------------------------------------------------
# fused lookup-round merge kernel
# ---------------------------------------------------------------------------

def _merge_round_kernel(fi_ref, fd_ref, fq_ref, ri_ref, rd_ref,
                        oi_ref, od_ref, oq_ref, dn_ref, *,
                        s: int, c: int, keep: int, quorum: int):
    """One fused lookup round tail: dedup + rank-merge + quorum check,
    frontier resident in VMEM throughout.

    Inputs per tile: frontier ``fi/fd/fq [TL, S]`` (idx i32 / d0 u32 /
    queried i32), responses ``ri/rd [TL, C]``.  Outputs: merged
    ``oi/od/oq [TL, keep]`` plus the fused done contribution
    ``dn [TL, 1]`` (sync-quorum OR exhaustion).

    Semantics are EXACTLY the sort-free rank merge
    (:func:`opendht_tpu.ops.xor_metric.rank_merge_round_d0` — see its
    contract): every entry's output slot is its rank under the total
    order ``(effective d0, idx_u, input ordinal)`` with duplicates'
    and empties' d0 forced to all-ones, computed here by direct
    counting — all loops below are static unrolls over the tiny
    S/C/keep widths, every op an [TL, W]-shaped VPU op, no sort
    network anywhere.
    """
    maxu = jnp.uint32(0xFFFFFFFF)
    fi = fi_ref[...]
    fd = fd_ref[...]
    fq = fq_ref[...]
    ri = ri_ref[...]
    rd = rd_ref[...]
    tl = fi.shape[0]
    w = s + c

    idx = jnp.concatenate([fi, ri], axis=1)                  # [TL, W]
    d0 = jnp.concatenate([fd, rd], axis=1)
    q = jnp.concatenate([fq, jnp.zeros_like(ri)], axis=1)
    idxu = jax.lax.bitcast_convert_type(idx, jnp.uint32)
    invalid = idx < 0
    d0 = jnp.where(invalid, maxu, d0)

    # Dedup: a response duplicates any EARLIER entry with its index
    # (the frontier run, or an earlier response slot — first copy
    # wins).  Frontier entries are duplicate-free by contract.
    dcols = [jnp.zeros((tl, 1), dtype=jnp.bool_) for _ in range(s)]
    for j in range(s, w):
        eq = (idxu[:, :j] == idxu[:, j:j + 1]) & ~invalid[:, :j]
        dcols.append(jnp.any(eq, axis=1, keepdims=True))
    dup = jnp.concatenate(dcols, axis=1) | invalid           # [TL, W]
    eff = jnp.where(dup, maxu, d0)

    # Rank = count of entries strictly before under
    # (eff_d0, idx_u, ordinal) — the merge-path position.
    iota_w = jax.lax.broadcasted_iota(jnp.int32, (tl, w), 1)
    pcols = []
    for j in range(w):
        kd = eff[:, j:j + 1]
        ki = idxu[:, j:j + 1]
        lt = (eff < kd) | ((eff == kd)
                           & ((idxu < ki)
                              | ((idxu == ki) & (iota_w < j))))
        pcols.append(jnp.sum(lt.astype(jnp.int32), axis=1,
                             keepdims=True))
    pos = jnp.concatenate(pcols, axis=1)                     # [TL, W]

    # One-hot placement of the surviving entries; dropped/duplicate
    # slots keep the fill (idx -1, d0 all-ones, unqueried), exactly
    # like the scatter in the XLA rank merge.
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (tl, keep), 1)
    oi = jnp.full((tl, keep), -1, jnp.int32)
    od = jnp.full((tl, keep), maxu, jnp.uint32)
    oq = jnp.zeros((tl, keep), jnp.int32)
    for j in range(w):
        hit = (iota_k == pos[:, j:j + 1]) & ~dup[:, j:j + 1]
        oi = jnp.where(hit, idx[:, j:j + 1], oi)
        od = jnp.where(hit, d0[:, j:j + 1], od)
        oq = jnp.where(hit, q[:, j:j + 1], oq)

    # Fused quorum/exhaustion check (models.swarm._sync_done + the
    # nothing-left-unqueried exit), while the merged head is in VMEM.
    hv = oi[:, :quorum] >= 0
    synced = jnp.all(jnp.where(hv, oq[:, :quorum] != 0, True), axis=1,
                     keepdims=True) & jnp.any(hv, axis=1, keepdims=True)
    exhausted = ~jnp.any((oi >= 0) & (oq == 0), axis=1, keepdims=True)
    oi_ref[...] = oi
    od_ref[...] = od
    oq_ref[...] = oq
    dn_ref[...] = (synced | exhausted).astype(jnp.int32)


@partial(jax.jit,
         static_argnames=("quorum", "keep", "tile_l", "interpret"))
def merge_round_pallas(fr_idx: jax.Array, fr_d0: jax.Array,
                       fr_q: jax.Array, resp_idx: jax.Array,
                       resp_d0: jax.Array, *, quorum: int, keep: int,
                       tile_l: int = 256,
                       interpret: bool | None = None):
    """Fused lookup-round merge: dedup + merge + quorum check in one
    Pallas kernel, grid over lookup-row tiles.

    ``fr_idx/fr_d0/fr_q [L,S]``: the frontier (post queried/evict
    updates — rank_merge_round_d0's input contract); ``resp_idx/
    resp_d0 [L,C]``: the α·2K response block.  Returns ``(idx, d0,
    queried, done)`` with the first three ``[L, min(keep, S+C)]`` and
    ``done [L] bool`` the fused sync-quorum/exhaustion contribution.

    Bit-identical to the XLA rank merge (and hence to the two-pass
    sorted reference) on the round's input domain — asserted under
    ``interpret=True`` in ``tests/test_merge_equivalence.py``.  Off-TPU
    backends run the interpreter, which is for those tests ONLY: the
    hot-path dispatch (``models.swarm.resolve_merge_impl``) never
    selects this kernel off-TPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    l, s = fr_idx.shape
    c = resp_idx.shape[1]
    out_w = min(keep, s + c)
    fi = _pad_to(fr_idx, tile_l, 0, -1)
    fd = _pad_to(fr_d0.astype(jnp.uint32), tile_l, 0, _MAX)
    fq = _pad_to(fr_q.astype(jnp.int32), tile_l, 0, 0)
    ri = _pad_to(resp_idx, tile_l, 0, -1)
    rd = _pad_to(resp_d0.astype(jnp.uint32), tile_l, 0, _MAX)
    lp = fi.shape[0]
    grid = (lp // tile_l,)
    row = lambda width: pl.BlockSpec((tile_l, width), lambda i: (i, 0))
    oi, od, oq, dn = pl.pallas_call(
        partial(_merge_round_kernel, s=s, c=c, keep=out_w,
                quorum=quorum),
        grid=grid,
        in_specs=[row(s), row(s), row(s), row(c), row(c)],
        out_specs=(row(out_w), row(out_w), row(out_w), row(1)),
        out_shape=(jax.ShapeDtypeStruct((lp, out_w), jnp.int32),
                   jax.ShapeDtypeStruct((lp, out_w), jnp.uint32),
                   jax.ShapeDtypeStruct((lp, out_w), jnp.int32),
                   jax.ShapeDtypeStruct((lp, 1), jnp.int32)),
        interpret=interpret,
    )(fi, fd, fq, ri, rd)
    return oi[:l], od[:l], oq[:l] != 0, dn[:l, 0] != 0


@partial(jax.jit, static_argnames=("tile_l", "tile_n", "interpret"))
def nearest_ids(ids: jax.Array, targets: jax.Array, *, tile_l: int = 256,
                tile_n: int = 1024, interpret: bool | None = None
                ) -> jax.Array:
    """Index of the exact XOR-nearest row of ``ids [N,5]`` per target.

    ``targets``: ``[L,5]`` → ``[L]`` int32.  Thin wrapper over the
    streaming k-best kernel with k=1.
    """
    res = nearest_k_ids(ids, targets, 1, margin=7, tile_l=tile_l,
                        tile_n=tile_n, interpret=interpret)
    n = ids.shape[0]
    return jnp.clip(res[:, 0], 0, n - 1)
