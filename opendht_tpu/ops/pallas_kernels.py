"""Pallas TPU kernels for the 160-bit XOR metric hot path.

The single hottest dense op in the swarm engine is "which stored node is
XOR-nearest to this target" over a node matrix far too large to
materialise a ``[L, N]`` distance plane in HBM.  This module implements
it as a tiled Pallas kernel (ref semantics: the XOR-sorted scan of
``RoutingTable::findClosestNodes``, src/routing_table.cpp:67-111, and
``InfoHash::xorCmp``, include/opendht/infohash.h:131-146):

* node ids and targets live limb-transposed ``[8, N] uint32`` (5 live
  limb rows padded to the sublane tile of 8) so the lane dimension is
  the large one;
* grid = (L tiles, N tiles); the N axis is the minor, sequentially
  executed dimension, accumulating a per-target running best
  (distance limbs + index) in VMEM scratch — a streaming argmin, so
  HBM traffic is O(L + N) per tile pair, not O(L·N);
* the in-tile lexicographic argmin is a 5-round masked tournament
  (exact 160-bit compare, no surrogate).

On non-TPU backends the same kernel runs under ``interpret=True`` so
tests exercise identical code.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N_LIMBS = 5
_PAD_LIMBS = 8  # sublane tile for uint32
_MAX = 0xFFFFFFFF  # kept as a Python int: a captured jnp scalar would be a kernel constant


def _nearest_kernel(t_ref, n_ref, o_ref, best_d, best_i, *, tn: int):
    ln = pl.program_id(1)

    @pl.when(ln == 0)
    def _init():
        best_d[...] = jnp.full_like(best_d, jnp.uint32(_MAX))
        best_i[...] = jnp.full_like(best_i, -1)

    t = t_ref[...]  # [8, TL]
    nd = n_ref[...]  # [8, TN]

    tl = t.shape[1]
    # Distance planes d_i = target_limb_i ^ node_limb_i, [TL, TN].
    d = [jnp.bitwise_xor(t[i, :, None], nd[i, None, :])
         for i in range(N_LIMBS)]

    # Masked tournament: after round i, mask keeps only candidates
    # minimal on limbs 0..i; mins[i] is the winner's limb i value.
    mask = jnp.ones((tl, tn), dtype=jnp.bool_)
    mins = []
    for i in range(N_LIMBS):
        di = jnp.where(mask, d[i], jnp.uint32(_MAX))
        mi = jnp.min(di, axis=1, keepdims=True)
        mask = mask & (di == mi)
        mins.append(mi[:, 0])

    iota = jax.lax.broadcasted_iota(jnp.int32, (tl, tn), 1)
    win_local = jnp.min(jnp.where(mask, iota, jnp.int32(tn)), axis=1)
    win_idx = ln * tn + win_local

    # Lexicographic compare of tile winner vs running best.
    lt = jnp.zeros((tl,), dtype=jnp.bool_)
    eq = jnp.ones((tl,), dtype=jnp.bool_)
    for i in range(N_LIMBS):
        bi = best_d[i, :]
        lt = lt | (eq & (mins[i] < bi))
        eq = eq & (mins[i] == bi)

    for i in range(N_LIMBS):
        best_d[i, :] = jnp.where(lt, mins[i], best_d[i, :])
    best_i[0, :] = jnp.where(lt, win_idx, best_i[0, :])

    @pl.when(ln == pl.num_programs(1) - 1)
    def _flush():
        o_ref[...] = best_i[...][:1]


def _pad_to(x: jax.Array, mult: int, axis: int, fill) -> jax.Array:
    n = x.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad, constant_values=jnp.asarray(fill, x.dtype))


@partial(jax.jit, static_argnames=("tile_l", "tile_n", "interpret"))
def nearest_ids(ids: jax.Array, targets: jax.Array, *, tile_l: int = 256,
                tile_n: int = 1024, interpret: bool | None = None
                ) -> jax.Array:
    """Index of the exact XOR-nearest row of ``ids [N,5]`` per target.

    ``targets``: ``[L,5]`` → ``[L]`` int32.  Streams the node matrix in
    ``tile_n`` chunks per ``tile_l`` targets; never materialises the
    full distance plane.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, l = ids.shape[0], targets.shape[0]

    # Limb-transpose + pad.  Padded node rows are all-ones: farthest
    # from any target whose top bit differs, but to be exact we pad with
    # the target-independent sentinel and rely on padded entries losing
    # every tournament against a real node — guaranteed because a real
    # swarm never contains the all-ones id; still, clamp at the end.
    ids_t = _pad_to(ids.T.astype(jnp.uint32), _PAD_LIMBS, 0, 0)
    ids_t = _pad_to(ids_t, tile_n, 1, _MAX)
    tg_t = _pad_to(targets.T.astype(jnp.uint32), _PAD_LIMBS, 0, 0)
    tg_t = _pad_to(tg_t, tile_l, 1, 0)
    n_pad, l_pad = ids_t.shape[1], tg_t.shape[1]

    grid = (l_pad // tile_l, n_pad // tile_n)
    out = pl.pallas_call(
        partial(_nearest_kernel, tn=tile_n),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_PAD_LIMBS, tile_l), lambda li, ni: (0, li)),
            pl.BlockSpec((_PAD_LIMBS, tile_n), lambda li, ni: (0, ni)),
        ],
        out_specs=pl.BlockSpec((1, tile_l), lambda li, ni: (0, li)),
        out_shape=jax.ShapeDtypeStruct((1, l_pad), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((_PAD_LIMBS, tile_l), jnp.uint32),
            pltpu.VMEM((1, tile_l), jnp.int32),
        ],
        interpret=interpret,
    )(tg_t, ids_t)
    res = out[0, :l]
    return jnp.clip(res, 0, n - 1)
