"""Pallas TPU kernels for the 160-bit XOR metric hot path.

The single hottest dense op in the swarm engine is "which k stored
nodes are XOR-nearest to this target" over a node matrix far too large
to materialise a ``[L, N]`` distance plane in HBM (the north star —
L=1M lookups over N=10M nodes — would need a 40 TB plane).  This
module implements it as a tiled streaming Pallas kernel (ref
semantics: the XOR-sorted scan of ``RoutingTable::findClosestNodes``,
src/routing_table.cpp:67-111, and ``InfoHash::xorCmp``,
include/opendht/infohash.h:131-146):

* grid = (L tiles, N tiles); the N axis is the minor, sequentially
  executed dimension — the node matrix streams through VMEM once per
  L tile, so HBM traffic is O(L·5 + N·5) per tile pair, never O(L·N);
* a per-target running best-``k+margin`` list (64-bit surrogate
  distance + global index) lives in VMEM scratch, laid out
  ``[tile_l, kb]`` so every per-candidate op is a lane-sliced 2D op
  (Mosaic rejects 1-D vector shuffles);
* per N tile, ``kb`` rounds of masked lexicographic argmin extract the
  tile's best candidates, each shift-inserted into the sorted running
  list with an unrolled compare/select chain;
* exactness beyond the 64-bit surrogate is restored by a final 160-bit
  5-limb ``lax.sort`` over the ``kb``-wide shortlist (margin ≥ 8), so
  the result is the true top-k unless > ``margin`` candidates tie with
  the k-th best on their first 64 distance bits (P ≈ (N/2^64)·margin
  for the swarm's uniform ids).

On non-TPU backends the same kernel runs under ``interpret=True`` so
tests exercise identical code.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N_LIMBS = 5
_PAD_LIMBS = 8  # sublane tile for uint32
_MAX = 0xFFFFFFFF  # kept as a Python int: a captured jnp scalar would be a kernel constant


def _lex_lt2(a0, a1, b0, b1):
    """64-bit lexicographic (a0,a1) < (b0,b1) on uint32 arrays."""
    return (a0 < b0) | ((a0 == b0) & (a1 < b1))


def _nearest_k_kernel(t_ref, n_ref, v_ref, o_ref, bd0, bd1, bi, *,
                      tn: int, kb: int, n_real: int):
    """Streaming k-best by 64-bit surrogate distance.

    ``t_ref [TL, 8]`` targets (limbs minor), ``n_ref [8, TN]`` nodes
    (limbs major), ``v_ref [1, TN]`` validity, ``o_ref [TL, kb]`` out.
    Running best in ``bd0/bd1/bi [TL, kb]`` kept ascending per row.

    Distances are carried in sign-flipped int32 (``x ^ 0x80000000``
    bitcast), which preserves unsigned order — Mosaic has no unsigned
    min-reduction.
    """
    ln = pl.program_id(1)
    imax = jnp.int32(0x7FFFFFFF)  # sign-flipped image of uint32 MAX

    @pl.when(ln == 0)
    def _init():
        bd0[...] = jnp.full_like(bd0, imax)
        bd1[...] = jnp.full_like(bd1, imax)
        bi[...] = jnp.full_like(bi, -1)

    def signed(x):
        return jax.lax.bitcast_convert_type(
            x ^ jnp.uint32(0x80000000), jnp.int32)

    tl = t_ref.shape[0]
    d0 = signed(jnp.bitwise_xor(t_ref[:, 0:1], n_ref[0:1, :]))  # [TL,TN]
    d1 = signed(jnp.bitwise_xor(t_ref[:, 1:2], n_ref[1:2, :]))

    iota = jax.lax.broadcasted_iota(jnp.int32, (tl, tn), 1)
    # Valid = inside the real node matrix (not tile padding) and not
    # masked out (dead) by the caller.
    mask = ((ln * tn + iota) < n_real) & (v_ref[0:1, :] != 0)

    # Tile skip gate: if no row's masked tile minimum can beat (or tie)
    # that row's current kb-th best on limb 0, the tile cannot change
    # the running list.  Conservative — ties proceed to the full
    # extraction, where limb 1 decides.  After the list warms up this
    # skips the vast majority of tiles (P(hit) ≈ TN·kb / nodes_seen).
    d0_gate = jnp.where(mask, d0, imax)
    m0_gate = jnp.min(d0_gate, axis=1, keepdims=True)       # [TL, 1]
    improve = jnp.any(m0_gate <= bd0[:, kb - 1:kb])

    @pl.when(improve)
    def _extract():
        _extract_rounds(d0, d1, mask, iota, ln, tn, kb, imax,
                        bd0, bd1, bi)

    @pl.when(ln == pl.num_programs(1) - 1)
    def _flush():
        o_ref[...] = bi[...]


def _extract_rounds(d0, d1, mask, iota, ln, tn, kb, imax, bd0, bd1, bi):
    # Running best as lists of [TL, 1] columns (read once, write once).
    B0 = [bd0[:, j:j + 1] for j in range(kb)]
    B1 = [bd1[:, j:j + 1] for j in range(kb)]
    BI = [bi[:, j:j + 1] for j in range(kb)]

    for _ in range(kb):
        d0m = jnp.where(mask, d0, imax)
        m0 = jnp.min(d0m, axis=1, keepdims=True)          # [TL, 1]
        c0mask = mask & (d0m == m0)
        d1m = jnp.where(c0mask, d1, imax)
        m1 = jnp.min(d1m, axis=1, keepdims=True)
        cand = c0mask & (d1m == m1)
        win = jnp.min(jnp.where(cand, iota, jnp.int32(tn)), axis=1,
                      keepdims=True)                      # [TL, 1]
        mask = mask & (iota != win)
        empty = win == tn
        c0 = jnp.where(empty, imax, m0)
        c1 = jnp.where(empty, imax, m1)
        ci = jnp.where(empty, -1, ln * tn + win)
        # Shift-insert into the ascending running list.
        lt = [_lex_lt2(c0, c1, B0[j], B1[j]) for j in range(kb)]
        nB0, nB1, nBI = [], [], []
        for j in range(kb):
            if j == 0:
                nB0.append(jnp.where(lt[0], c0, B0[0]))
                nB1.append(jnp.where(lt[0], c1, B1[0]))
                nBI.append(jnp.where(lt[0], ci, BI[0]))
            else:
                here = lt[j] & ~lt[j - 1]
                nB0.append(jnp.where(~lt[j], B0[j],
                                     jnp.where(here, c0, B0[j - 1])))
                nB1.append(jnp.where(~lt[j], B1[j],
                                     jnp.where(here, c1, B1[j - 1])))
                nBI.append(jnp.where(~lt[j], BI[j],
                                     jnp.where(here, ci, BI[j - 1])))
        B0, B1, BI = nB0, nB1, nBI

    bd0[...] = jnp.concatenate(B0, axis=1)
    bd1[...] = jnp.concatenate(B1, axis=1)
    bi[...] = jnp.concatenate(BI, axis=1)


def _pad_to(x: jax.Array, mult: int, axis: int, fill) -> jax.Array:
    n = x.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad, constant_values=jnp.asarray(fill, x.dtype))


@partial(jax.jit,
         static_argnames=("k", "margin", "tile_l", "tile_n", "interpret"))
def nearest_k_ids(ids: jax.Array, targets: jax.Array, k: int = 8, *,
                  valid: jax.Array | None = None, margin: int = 8,
                  tile_l: int = 64, tile_n: int = 8192,
                  interpret: bool | None = None) -> jax.Array:
    """Exact k XOR-closest rows of ``ids [N,5]`` per target, streamed.

    ``targets [L,5]`` → ``[L,k]`` int32, closest first (-1 where fewer
    than k valid nodes exist).  ``valid``: optional ``[N]`` bool.
    See module docstring for the algorithm and exactness bound.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, l = ids.shape[0], targets.shape[0]
    kb = -(-max(k + margin, 8) // 8) * 8  # sublane-aligned shortlist
    # Scoped-VMEM budget: the kernel's live set is dominated by a
    # handful of [tile_l, tile_n] i32 streaming temporaries whose live
    # ranges grow with the kb unrolled extraction rounds (measured on
    # v5e: kb=16 @ 64x8192 fits the 16 MB scoped limit, kb=32 @ 64x8192
    # allocates 21.2 MB and fails to compile).  Shrink tile_n as kb
    # grows past 16 so tile_l*tile_n*kb stays at or below the known-good
    # product; lane-align to 512.
    if kb > 16:
        tile_n = max(512, (tile_n * 16 // kb) // 512 * 512)

    # Nodes limb-major [8, N]; targets limb-minor [L, 8].  Padded node
    # entries are masked inside the kernel by global index (>= n_real),
    # so the pad value is inert.
    ids_t = _pad_to(ids.T.astype(jnp.uint32), _PAD_LIMBS, 0, 0)
    ids_t = _pad_to(ids_t, tile_n, 1, _MAX)
    tg = _pad_to(targets.astype(jnp.uint32), _PAD_LIMBS, 1, 0)
    tg = _pad_to(tg, tile_l, 0, 0)
    n_pad, l_pad = ids_t.shape[1], tg.shape[0]
    if valid is None:
        vrow = jnp.ones((1, n_pad), jnp.uint32)
    else:
        vrow = _pad_to(valid.astype(jnp.uint32)[None, :], tile_n, 1, 0)

    grid = (l_pad // tile_l, n_pad // tile_n)
    out = pl.pallas_call(
        partial(_nearest_k_kernel, tn=tile_n, kb=kb, n_real=n),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_l, _PAD_LIMBS), lambda li, ni: (li, 0)),
            pl.BlockSpec((_PAD_LIMBS, tile_n), lambda li, ni: (0, ni)),
            pl.BlockSpec((1, tile_n), lambda li, ni: (0, ni)),
        ],
        out_specs=pl.BlockSpec((tile_l, kb), lambda li, ni: (li, 0)),
        out_shape=jax.ShapeDtypeStruct((l_pad, kb), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((tile_l, kb), jnp.int32),
            pltpu.VMEM((tile_l, kb), jnp.int32),
            pltpu.VMEM((tile_l, kb), jnp.int32),
        ],
        interpret=interpret,
    )(tg, ids_t, vrow)

    short = out[:l]                                        # [L,kb]
    # Exact 160-bit refine over the shortlist.  Empty slots sort last
    # via explicit all-ones *distance* (an all-ones sentinel id would
    # not be far from targets with leading 1-bits).
    cand = ids[jnp.clip(short, 0, n - 1)]                  # [L,kb,5]
    d = jnp.bitwise_xor(cand, targets[:, None, :])
    d = jnp.where((short < 0)[..., None], jnp.uint32(_MAX), d)
    keys = tuple(d[..., i] for i in range(N_LIMBS))
    sorted_ = jax.lax.sort(keys + (short,), dimension=1, num_keys=N_LIMBS)
    return sorted_[N_LIMBS][:, :k]


# ---------------------------------------------------------------------------
# fused lookup-round merge kernel
# ---------------------------------------------------------------------------

def _merge_core(fi, fd, fq, ri, rd, *, s: int, c: int, keep: int,
                quorum: int):
    """Shared in-kernel round tail: dedup + rank-merge + quorum check
    on VMEM-resident values.  Called by both the merge-only kernel
    (:func:`merge_round_pallas`) and the whole-round fused kernel
    (:func:`fused_round_pallas`), so the two cannot drift.

    Semantics are EXACTLY the sort-free rank merge
    (:func:`opendht_tpu.ops.xor_metric.rank_merge_round_d0` — see its
    contract): every entry's output slot is its rank under the total
    order ``(effective d0, idx_u, input ordinal)`` with duplicates'
    and empties' d0 forced to all-ones, computed here by direct
    counting — all loops below are static unrolls over the tiny
    S/C/keep widths, every op an [TL, W]-shaped VPU op, no sort
    network anywhere.  Returns ``(oi, od, oq, dn)`` values.
    """
    maxu = jnp.uint32(0xFFFFFFFF)
    tl = fi.shape[0]
    w = s + c

    idx = jnp.concatenate([fi, ri], axis=1)                  # [TL, W]
    d0 = jnp.concatenate([fd, rd], axis=1)
    q = jnp.concatenate([fq, jnp.zeros_like(ri)], axis=1)
    idxu = jax.lax.bitcast_convert_type(idx, jnp.uint32)
    invalid = idx < 0
    d0 = jnp.where(invalid, maxu, d0)

    # Dedup: a response duplicates any EARLIER entry with its index
    # (the frontier run, or an earlier response slot — first copy
    # wins).  Frontier entries are duplicate-free by contract.
    dcols = [jnp.zeros((tl, 1), dtype=jnp.bool_) for _ in range(s)]
    for j in range(s, w):
        eq = (idxu[:, :j] == idxu[:, j:j + 1]) & ~invalid[:, :j]
        dcols.append(jnp.any(eq, axis=1, keepdims=True))
    dup = jnp.concatenate(dcols, axis=1) | invalid           # [TL, W]
    eff = jnp.where(dup, maxu, d0)

    # Rank = count of entries strictly before under
    # (eff_d0, idx_u, ordinal) — the merge-path position.
    iota_w = jax.lax.broadcasted_iota(jnp.int32, (tl, w), 1)
    pcols = []
    for j in range(w):
        kd = eff[:, j:j + 1]
        ki = idxu[:, j:j + 1]
        lt = (eff < kd) | ((eff == kd)
                           & ((idxu < ki)
                              | ((idxu == ki) & (iota_w < j))))
        pcols.append(jnp.sum(lt.astype(jnp.int32), axis=1,
                             keepdims=True))
    pos = jnp.concatenate(pcols, axis=1)                     # [TL, W]

    # One-hot placement of the surviving entries; dropped/duplicate
    # slots keep the fill (idx -1, d0 all-ones, unqueried), exactly
    # like the scatter in the XLA rank merge.
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (tl, keep), 1)
    oi = jnp.full((tl, keep), -1, jnp.int32)
    od = jnp.full((tl, keep), maxu, jnp.uint32)
    oq = jnp.zeros((tl, keep), jnp.int32)
    for j in range(w):
        hit = (iota_k == pos[:, j:j + 1]) & ~dup[:, j:j + 1]
        oi = jnp.where(hit, idx[:, j:j + 1], oi)
        od = jnp.where(hit, d0[:, j:j + 1], od)
        oq = jnp.where(hit, q[:, j:j + 1], oq)

    # Fused quorum/exhaustion check (models.swarm._sync_done + the
    # nothing-left-unqueried exit), while the merged head is in VMEM.
    hv = oi[:, :quorum] >= 0
    synced = jnp.all(jnp.where(hv, oq[:, :quorum] != 0, True), axis=1,
                     keepdims=True) & jnp.any(hv, axis=1, keepdims=True)
    exhausted = ~jnp.any((oi >= 0) & (oq == 0), axis=1, keepdims=True)
    return oi, od, oq, (synced | exhausted).astype(jnp.int32)


def _merge_round_kernel(fi_ref, fd_ref, fq_ref, ri_ref, rd_ref,
                        oi_ref, od_ref, oq_ref, dn_ref, *,
                        s: int, c: int, keep: int, quorum: int):
    """Merge-only kernel: read the frontier + response tiles, run the
    shared round tail (:func:`_merge_core`), write the merged state and
    fused done contribution."""
    oi, od, oq, dn = _merge_core(
        fi_ref[...], fd_ref[...], fq_ref[...], ri_ref[...], rd_ref[...],
        s=s, c=c, keep=keep, quorum=quorum)
    oi_ref[...] = oi
    od_ref[...] = od
    oq_ref[...] = oq
    dn_ref[...] = dn


@partial(jax.jit,
         static_argnames=("quorum", "keep", "tile_l", "interpret"))
def merge_round_pallas(fr_idx: jax.Array, fr_d0: jax.Array,
                       fr_q: jax.Array, resp_idx: jax.Array,
                       resp_d0: jax.Array, *, quorum: int, keep: int,
                       tile_l: int = 256,
                       interpret: bool | None = None):
    """Fused lookup-round merge: dedup + merge + quorum check in one
    Pallas kernel, grid over lookup-row tiles.

    ``fr_idx/fr_d0/fr_q [L,S]``: the frontier (post queried/evict
    updates — rank_merge_round_d0's input contract); ``resp_idx/
    resp_d0 [L,C]``: the α·2K response block.  Returns ``(idx, d0,
    queried, done)`` with the first three ``[L, min(keep, S+C)]`` and
    ``done [L] bool`` the fused sync-quorum/exhaustion contribution.

    Bit-identical to the XLA rank merge (and hence to the two-pass
    sorted reference) on the round's input domain — asserted under
    ``interpret=True`` in ``tests/test_merge_equivalence.py``.  Off-TPU
    backends run the interpreter, which is for those tests ONLY: the
    hot-path dispatch (``models.swarm.resolve_merge_impl``) never
    selects this kernel off-TPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    l, s = fr_idx.shape
    c = resp_idx.shape[1]
    out_w = min(keep, s + c)
    fi = _pad_to(fr_idx, tile_l, 0, -1)
    fd = _pad_to(fr_d0.astype(jnp.uint32), tile_l, 0, _MAX)
    fq = _pad_to(fr_q.astype(jnp.int32), tile_l, 0, 0)
    ri = _pad_to(resp_idx, tile_l, 0, -1)
    rd = _pad_to(resp_d0.astype(jnp.uint32), tile_l, 0, _MAX)
    lp = fi.shape[0]
    grid = (lp // tile_l,)
    row = lambda width: pl.BlockSpec((tile_l, width), lambda i: (i, 0))
    oi, od, oq, dn = pl.pallas_call(
        partial(_merge_round_kernel, s=s, c=c, keep=out_w,
                quorum=quorum),
        grid=grid,
        in_specs=[row(s), row(s), row(s), row(c), row(c)],
        out_specs=(row(out_w), row(out_w), row(out_w), row(1)),
        out_shape=(jax.ShapeDtypeStruct((lp, out_w), jnp.int32),
                   jax.ShapeDtypeStruct((lp, out_w), jnp.uint32),
                   jax.ShapeDtypeStruct((lp, out_w), jnp.int32),
                   jax.ShapeDtypeStruct((lp, 1), jnp.int32)),
        interpret=interpret,
    )(fi, fd, fq, ri, rd)
    return oi[:l], od[:l], oq[:l] != 0, dn[:l, 0] != 0


# ---------------------------------------------------------------------------
# whole-round fused kernel: gather + window decode + merge, VMEM-resident
# ---------------------------------------------------------------------------

_DMA_SEMS = 8  # in-flight row DMAs per wave (bounded hw semaphores)


def _pl_window_d0(s16, wr, tg, nid_d0):
    """In-kernel twin of ``models.swarm._window_d0`` on [TL, 1]
    columns: reconstruct the first-limb distance from a 16-bit member
    window (bits shared with the solicited node come from its own
    distance ``nid_d0``; sub-window bits read as zero)."""
    maxu = jnp.uint32(0xFFFFFFFF)
    wu = jnp.clip(wr, 0, 31).astype(jnp.uint32)
    t16 = (tg << wu) >> jnp.uint32(16)
    d16 = s16 ^ t16
    lsh = jnp.clip(16 - wr, 0, 16).astype(jnp.uint32)
    rsh = jnp.clip(wr - 16, 0, 16).astype(jnp.uint32)
    placed = jnp.where(wr <= 16, d16 << lsh, d16 >> rsh)
    hm = jnp.where(
        wr > 0,
        maxu << jnp.clip(32 - wr, 0, 31).astype(jnp.uint32),
        jnp.uint32(0))
    return (nid_d0 & hm) | placed


def _fused_round_kernel(sel_ref, tables_ref, tg_ref, fi_ref, fd_ref,
                        fq_ref, d0_ref, pos_ref, w0_ref, qh_ref,
                        eh_ref, oi_ref, od_ref, oq_ref, dn_ref,
                        rowbuf, sem, *, s: int, a: int, k: int,
                        b_total: int, row_w: int, keep: int,
                        quorum: int):
    """One ENTIRE lookup round per [TL] tile, frontier VMEM-resident
    throughout: in-kernel whole-row table gather (async DMAs from the
    HBM-resident table, ``_DMA_SEMS``-deep waves), bucket-pair window
    select + per-member decode (the aug-table layout of
    ``models.swarm._respond``), the queried/evict position update, and
    the shared rank merge + fused quorum check (:func:`_merge_core`).

    The α-select SCALARS arrive precomputed (``sel_ref [TL, A]`` in
    SMEM — DMA control must read scalar row indices, and SMEM is the
    scalar-readable space; the [TL,*] vector halves of the selection —
    ``d0/pos/w0/qh/eh`` — ride VMEM).  Between the solicitation and
    the merged output, nothing round-trips to HBM: the round-5 kernel
    kept only the MERGE resident, paying an HBM round-trip for the
    gathered rows and decoded responses; this kernel swallows both.
    """
    tl = fi_ref.shape[0]
    maxu = jnp.uint32(0xFFFFFFFF)
    q_total = tl * a
    assert q_total % _DMA_SEMS == 0, "tile_l*alpha must cover DMA waves"

    def dma_for(q):
        t = q // a
        j = q % a
        return pltpu.make_async_copy(
            tables_ref.at[sel_ref[t, j]],
            rowbuf.at[t, pl.ds(j * row_w, row_w)],
            sem.at[q % _DMA_SEMS])

    def wave(i, _):
        base = i * _DMA_SEMS
        for j in range(_DMA_SEMS):
            dma_for(base + j).start()
        for j in range(_DMA_SEMS):
            dma_for(base + j).wait()
        return 0

    jax.lax.fori_loop(0, q_total // _DMA_SEMS, wave, 0)

    # --- window select + member decode, per solicitation slot.  All
    # ops are [TL, X] 2-D vector ops on the DMA'd rows; the bucket-pair
    # window is extracted with the same static-select chain as the XLA
    # respond (B-2 selects over the fetched row).
    tg = tg_ref[...]                                     # [TL, 1] u32
    w3 = 3 * k
    ri_cols, rd_cols = [], []
    for ai in range(a):
        rowa = rowbuf[:, ai * row_w:(ai + 1) * row_w]    # [TL, row_w]
        w0a = w0_ref[:, ai:ai + 1]                       # [TL, 1] i32
        oka = qh_ref[:, ai:ai + 1] != 0
        d0a = d0_ref[:, ai:ai + 1]
        win = rowa[:, 0:2 * w3]
        for b in range(1, b_total - 1):
            win = jnp.where(w0a == b, rowa[:, b * w3:b * w3 + 2 * w3],
                            win)
        for r_ in (0, 1):
            base = r_ * w3
            wr = w0a + r_
            for m in range(k):
                lo = win[:, base + m:base + m + 1].astype(jnp.uint32)
                hi = win[:, base + k + m:base + k + m + 1].astype(
                    jnp.uint32)
                s16 = win[:, base + 2 * k + m:base + 2 * k + m + 1
                          ].astype(jnp.uint32)
                idx_j = jax.lax.bitcast_convert_type(
                    lo | (hi << jnp.uint32(16)), jnp.int32)
                valid = oka & (idx_j >= 0)
                d0_j = _pl_window_d0(s16, wr, tg, d0a)
                ri_cols.append(jnp.where(valid, idx_j, -1))
                rd_cols.append(jnp.where(valid, d0_j, maxu))
    ri = jnp.concatenate(ri_cols, axis=1)              # [TL, A*2K]
    rd = jnp.concatenate(rd_cols, axis=1)

    # --- queried/evict position update (models.swarm._merge_round's
    # two scatters, as one-hot selects on the resident frontier).
    fi = fi_ref[...]
    fd = fd_ref[...]
    fq = fq_ref[...] != 0
    iota_s = jax.lax.broadcasted_iota(jnp.int32, (tl, s), 1)
    evict = jnp.zeros((tl, s), dtype=jnp.bool_)
    for ai in range(a):
        hit = iota_s == pos_ref[:, ai:ai + 1]
        fq = fq | (hit & (qh_ref[:, ai:ai + 1] != 0))
        evict = evict | (hit & (eh_ref[:, ai:ai + 1] != 0))
    fi = jnp.where(evict, -1, fi)
    fd = jnp.where(evict, maxu, fd)

    oi, od, oq, dn = _merge_core(fi, fd, fq.astype(jnp.int32), ri, rd,
                                 s=s, c=2 * k * a, keep=keep,
                                 quorum=quorum)
    oi_ref[...] = oi
    od_ref[...] = od
    oq_ref[...] = oq
    dn_ref[...] = dn


@partial(jax.jit, static_argnames=("bucket_k", "quorum", "keep",
                                   "tile_l", "interpret"))
def fused_round_pallas(tables: jax.Array, targets0: jax.Array,
                       fr_idx: jax.Array, fr_d0: jax.Array,
                       fr_q: jax.Array, safe_sel: jax.Array,
                       sel_d0: jax.Array, sel_pos: jax.Array,
                       w0: jax.Array, q_hit: jax.Array,
                       e_hit: jax.Array, *, bucket_k: int, quorum: int,
                       keep: int, tile_l: int = 128,
                       interpret: bool | None = None):
    """Whole-round fused Pallas kernel: table gather + window decode +
    queried/evict update + rank merge + quorum check, frontier
    VMEM-resident across the round (``merge_impl="pallas-round"``).

    ``tables [N, row_w] u16`` stays in HBM (``ANY`` memory space) and
    is row-gathered by in-kernel async DMAs; everything else is [L]-
    leading and tiles over lookup rows.  ``safe_sel [L,A]`` are the
    solicited rows CLIPPED to valid indices (invalid solicitations DMA
    row 0 harmlessly and are masked by ``q_hit``); ``w0`` is the
    clipped bucket-pair start; ``q_hit``/``e_hit`` are the
    queried/evict masks the round tail would scatter.  Returns
    ``(idx, d0, queried, done)`` exactly like
    :func:`merge_round_pallas`, for the full α·2K response semantics
    of the local augmented respond — asserted bit-identical to
    ``step_impl`` in ``tests/test_merge_equivalence.py`` (interpret
    mode; the hot-path dispatch never runs the interpreter).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    l, s = fr_idx.shape
    a = safe_sel.shape[1]
    k = bucket_k
    row_w = tables.shape[1]
    c = 2 * k * a
    out_w = min(keep, s + c)
    # Bucket count from the padded row width is ambiguous; recover it
    # from the window-start clip domain: w0 ≤ B-2 by construction, and
    # the select chain only needs the row's real positions — derive
    # B from the unpadded row: the largest b with (b+1)*3K ≤ row_w
    # bounds the chain; harmless to over-cover into the pad (0xFFFF
    # slots decode to -1).
    b_total = row_w // (3 * k)
    fi = _pad_to(fr_idx, tile_l, 0, -1)
    fd = _pad_to(fr_d0.astype(jnp.uint32), tile_l, 0, _MAX)
    fq = _pad_to(fr_q.astype(jnp.int32), tile_l, 0, 0)
    tg = _pad_to(targets0.astype(jnp.uint32)[:, None], tile_l, 0, 0)
    sel = _pad_to(safe_sel, tile_l, 0, 0)
    d0s = _pad_to(sel_d0.astype(jnp.uint32), tile_l, 0, _MAX)
    pos = _pad_to(sel_pos, tile_l, 0, -1)
    w0p = _pad_to(w0, tile_l, 0, 0)
    qh = _pad_to(q_hit.astype(jnp.int32), tile_l, 0, 0)
    eh = _pad_to(e_hit.astype(jnp.int32), tile_l, 0, 0)
    lp = fi.shape[0]
    grid = (lp // tile_l,)
    row = lambda width: pl.BlockSpec((tile_l, width), lambda i: (i, 0))
    smem_row = pl.BlockSpec((tile_l, a), lambda i: (i, 0),
                            memory_space=pltpu.SMEM)
    oi, od, oq, dn = pl.pallas_call(
        partial(_fused_round_kernel, s=s, a=a, k=k, b_total=b_total,
                row_w=row_w, keep=out_w, quorum=quorum),
        grid=grid,
        in_specs=[
            smem_row,                                   # sel (scalars)
            pl.BlockSpec(memory_space=pltpu.ANY),       # tables (HBM)
            row(1),                                     # targets0
            row(s), row(s), row(s),                     # frontier
            row(a), row(a), row(a), row(a), row(a),     # d0/pos/w0/q/e
        ],
        out_specs=(row(out_w), row(out_w), row(out_w), row(1)),
        out_shape=(jax.ShapeDtypeStruct((lp, out_w), jnp.int32),
                   jax.ShapeDtypeStruct((lp, out_w), jnp.uint32),
                   jax.ShapeDtypeStruct((lp, out_w), jnp.int32),
                   jax.ShapeDtypeStruct((lp, 1), jnp.int32)),
        scratch_shapes=[
            pltpu.VMEM((tile_l, a * row_w), jnp.uint16),
            pltpu.SemaphoreType.DMA((_DMA_SEMS,)),
        ],
        interpret=interpret,
    )(sel, tables, tg, fi, fd, fq, d0s, pos, w0p, qh, eh)
    return oi[:l], od[:l], oq[:l] != 0, dn[:l, 0] != 0


@partial(jax.jit, static_argnames=("tile_l", "tile_n", "interpret"))
def nearest_ids(ids: jax.Array, targets: jax.Array, *, tile_l: int = 256,
                tile_n: int = 1024, interpret: bool | None = None
                ) -> jax.Array:
    """Index of the exact XOR-nearest row of ``ids [N,5]`` per target.

    ``targets``: ``[L,5]`` → ``[L]`` int32.  Thin wrapper over the
    streaming k-best kernel with k=1.
    """
    res = nearest_k_ids(ids, targets, 1, margin=7, tile_l=tile_l,
                        tile_n=tile_n, interpret=interpret)
    n = ids.shape[0]
    return jnp.clip(res[:, 0], 0, n - 1)
