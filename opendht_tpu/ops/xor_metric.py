"""Batched 160-bit XOR metric ops over packed id matrices (JAX).

The reference computes XOR distance one pair at a time with byte loops
(``InfoHash::xorCmp`` include/opendht/infohash.h:131-146, ``commonBits``
:106-117, ``RoutingTable::findClosestNodes``'s XOR-sorted merge
src/routing_table.cpp:67-111).  Here the same metric is a set of
vectorized kernels over device-resident ``uint32[..., 5]`` limb arrays
(big-endian limb order: limb 0 = id bytes 0-3), designed so XLA tiles
them onto the VPU:

* XOR distance compares are 5-limb lexicographic — implemented with
  ``jax.lax.sort`` multi-operand (lexicographic) sorts, never Python
  loops over bits;
* leading-zero count (= matching prefix length) uses ``lax.clz`` on the
  first differing limb;
* top-k closest over big node matrices uses a two-stage scheme: a cheap
  64-bit surrogate ``lax.top_k`` prefilter, then an exact 160-bit sort
  over the shortlist (exact whenever fewer than ``prefilter`` candidates
  tie on their first 64 distance bits — overwhelmingly the case for
  random ids).

All functions are shape-polymorphic over leading batch dims and safe
under ``jit``/``vmap``/``shard_map``.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

N_LIMBS = 5
HASH_BITS = 160

# Sentinel id: all-ones is "as far as possible" from any realistic
# target once XORed (and equal-distance dedup never confuses it with a
# real node because invalid entries also carry index -1).
SENTINEL_LIMB = jnp.uint32(0xFFFFFFFF)


def xor_ids(a: jax.Array, b: jax.Array) -> jax.Array:
    """Element-wise XOR of packed ids, broadcasting like jnp."""
    return jnp.bitwise_xor(a, b)


def prefix_len32(d0: jax.Array) -> jax.Array:
    """Leading-zero count of a first-limb XOR distance, 32 where zero.

    ``d0 = limb0(a ^ b)`` ⇒ this is the common-prefix length of a and
    b capped at 32 — exact whenever the true common prefix is < 32
    bits, always the case for distinct uniform ids below ~2^32 nodes.
    The lookup hot path uses it to derive bucket indices from
    distances it already holds, with no id gather.
    """
    return jnp.where(d0 == 0, jnp.int32(32),
                     jax.lax.clz(d0).astype(jnp.int32))


def common_bits32(a0: jax.Array, b0: jax.Array) -> jax.Array:
    """Common-prefix length from the *first limbs only*, capped at 32.

    Callers that clip the result to a bucket count ≤ 32
    (``SwarmConfig.n_buckets``) get the same answer as
    :func:`common_bits` from 1/5 of the gather traffic.
    """
    return prefix_len32(jnp.bitwise_xor(a0, b0))


def common_bits(a: jax.Array, b: jax.Array) -> jax.Array:
    """Length of the common bit-prefix of two packed ids.

    Mirrors ``InfoHash::commonBits`` (include/opendht/infohash.h:106-117);
    returns 160 for equal ids.  Batched over leading dims.
    """
    x = jnp.bitwise_xor(a, b)
    nz = x != 0
    first = jnp.argmax(nz, axis=-1)
    any_nz = jnp.any(nz, axis=-1)
    limb = jnp.take_along_axis(x, first[..., None], axis=-1)[..., 0]
    clz = jax.lax.clz(limb)
    return jnp.where(any_nz, first * 32 + clz.astype(jnp.int32),
                     HASH_BITS).astype(jnp.int32)


def xor_less(da: jax.Array, db: jax.Array) -> jax.Array:
    """Lexicographic ``da < db`` over distance limb arrays ``[..., 5]``.

    The 5-limb big-endian lexicographic order equals 160-bit integer
    order, i.e. the reference's ``xorCmp`` result
    (include/opendht/infohash.h:131-146).
    """
    lt = jnp.zeros(da.shape[:-1], dtype=bool)
    eq = jnp.ones(da.shape[:-1], dtype=bool)
    for i in range(N_LIMBS):
        ai, bi = da[..., i], db[..., i]
        lt = lt | (eq & (ai < bi))
        eq = eq & (ai == bi)
    return lt


def _dist_keys(ids: jax.Array, target: jax.Array) -> Tuple[jax.Array, ...]:
    """XOR-distance limbs of ``ids`` to ``target`` as a tuple of 5 sort keys."""
    d = jnp.bitwise_xor(ids, target[..., None, :])
    return tuple(d[..., i] for i in range(N_LIMBS))


def sort_by_distance(ids: jax.Array, target: jax.Array,
                     *payloads: jax.Array) -> Tuple[jax.Array, ...]:
    """Sort candidate ids by exact 160-bit XOR distance to target.

    ``ids``: ``[..., C, 5]``; ``target``: ``[..., 5]``; each payload
    ``[..., C]``.  Returns ``(sorted_ids, *sorted_payloads)``.
    """
    keys = _dist_keys(ids, target)
    limbs = tuple(ids[..., i] for i in range(N_LIMBS))
    out = jax.lax.sort(keys + limbs + payloads, dimension=ids.ndim - 2,
                       num_keys=N_LIMBS, is_stable=True)
    sorted_ids = jnp.stack(out[N_LIMBS:2 * N_LIMBS], axis=-1)
    return (sorted_ids,) + tuple(out[2 * N_LIMBS:])


@partial(jax.jit, static_argnames=("k",))
def closest_nodes(ids: jax.Array, target: jax.Array, k: int) -> jax.Array:
    """Exact k XOR-closest rows of ``ids [N,5]`` to ``target [5]``.

    Full lexicographic sort — O(N log N); use for ground truth and
    moderate N.  Returns ``[k]`` int32 indices, closest first.
    Equivalent of ``RoutingTable::findClosestNodes``
    (src/routing_table.cpp:67-111) run over a flat node matrix.
    """
    n = ids.shape[0]
    keys = _dist_keys(ids, target)
    (_, _, _, _, _, idx) = jax.lax.sort(
        keys + (jnp.arange(n, dtype=jnp.int32),), num_keys=N_LIMBS)
    return idx[:k]


def closest_nodes_batched(ids: jax.Array, targets: jax.Array, k: int,
                          prefilter: int = 32,
                          valid: jax.Array | None = None) -> jax.Array:
    """k XOR-closest node indices for a batch of targets.

    ``ids``: ``[N,5]``, ``targets``: ``[L,5]`` → ``[L,k]`` indices.
    ``valid``: optional ``[N]`` bool — excluded rows never appear in
    the result.

    On TPU this dispatches to the Pallas streaming k-best kernel
    (:func:`opendht_tpu.ops.pallas_kernels.nearest_k_ids`) — HBM
    traffic O(L·5 + N·5) per tile pair, no ``[L,N]`` plane — so it
    scales to the north-star sizes (L=1M targets over N=10M nodes
    would need a 40 TB plane).  Elsewhere it falls back to the plane
    implementation below (Pallas interpret mode is far slower than
    XLA:CPU's fused top_k).
    """
    if jax.default_backend() == "tpu":
        from .pallas_kernels import nearest_k_ids
        return nearest_k_ids(ids, targets, k, valid=valid,
                             margin=max(8, prefilter - k))
    return closest_nodes_batched_plane(ids, targets, k, prefilter,
                                       valid=valid)


@partial(jax.jit, static_argnames=("k", "prefilter"))
def closest_nodes_batched_plane(ids: jax.Array, targets: jax.Array,
                                k: int, prefilter: int = 32,
                                valid: jax.Array | None = None
                                ) -> jax.Array:
    """Plane-based k-closest (reference implementation / CPU path).

    Two-stage: ``lax.top_k`` on the negated first-32-bit surrogate
    distance over an explicit ``[L,N]`` plane, then an exact 5-limb
    sort over the ``prefilter`` shortlist.  Exact unless more than
    ``prefilter`` candidates tie on their first 32 distance bits
    (probability ≈ (N/2^32)·prefilter for random ids).
    """
    # Surrogate: bit-inverted first distance limb: top_k on limb0;
    # ties broken within the shortlist's exact sort.
    d0 = jnp.bitwise_xor(ids[None, :, 0], targets[:, 0:1])      # [L,N]
    # top_k wants "largest"; invert so nearer = larger.  int32 view keeps
    # order if we flip the sign bit.
    surro = (jnp.bitwise_xor(d0, jnp.uint32(0xFFFFFFFF))
             ^ jnp.uint32(0x80000000)).astype(jnp.int32)
    if valid is not None:
        surro = jnp.where(valid[None, :], surro, jnp.int32(-2**31))
    _, short = jax.lax.top_k(surro, prefilter)                   # [L,P]
    cand = ids[short]                                            # [L,P,5]
    if valid is not None:
        # Push excluded shortlist rows to the back of the exact sort
        # and mark them -1.
        inval = ~valid[short]
        cand = jnp.where(inval[..., None], SENTINEL_LIMB, cand)
        short = jnp.where(inval, -1, short)
    _, sidx = sort_by_distance(cand, targets, short)
    return sidx[:, :k]


def lex_less(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lexicographic ``a < b`` over packed id arrays ``[..., 5]``.

    Same comparator as :func:`xor_less` (5-limb big-endian order equals
    160-bit integer order); named separately because it compares ids,
    not distances — the reference's ``InfoHash::cmp``
    (include/opendht/infohash.h:101-104).
    """
    return xor_less(a, b)


def lex_searchsorted(sorted_ids: jax.Array, queries: jax.Array,
                     side: str = "left") -> jax.Array:
    """Vectorized binary search over lexicographically sorted packed ids.

    ``sorted_ids``: ``[N,5]`` ascending; ``queries``: ``[...,5]``.
    Returns insertion positions (int32), like ``np.searchsorted`` but
    with the 160-bit 5-limb comparator.  O(log N) gather steps under
    ``jit`` — the device equivalent of walking the reference's ordered
    bucket list (``RoutingTable::findBucket``,
    src/routing_table.cpp:113-127).
    """
    n = sorted_ids.shape[0]
    steps = max(1, (n - 1).bit_length() + 1) if n > 1 else 1
    batch = queries.shape[:-1]
    lo = jnp.zeros(batch, jnp.int32)
    hi = jnp.full(batch, n, jnp.int32)

    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) // 2
        mid_ids = sorted_ids[jnp.clip(mid, 0, n - 1)]
        if side == "left":
            go_right = lex_less(mid_ids, queries)
        else:
            go_right = ~lex_less(queries, mid_ids)
        go_right = go_right & (lo < hi)
        new_lo = jnp.where(go_right, mid + 1, lo)
        new_hi = jnp.where(go_right, hi, jnp.where(lo < hi, mid, hi))
        return new_lo, new_hi

    lo, hi = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo


def _group_queried_first(group_keys: Tuple[jax.Array, ...],
                         queried: jax.Array,
                         payloads: Tuple[jax.Array, ...]):
    """Shared pass 1 of the two-pass merge family: stable lexicographic
    sort by ``(group_keys..., ~queried)`` so same-id copies become
    adjacent with QUERIED COPIES FIRST, then adjacent-equality duplicate
    marking over ALL group keys.

    This is the single home of the dedup tie-break rules both
    :func:`merge_shortlists` (exact 5-limb group keys) and
    :func:`merge_shortlists_d0` (node-index group key) used to restate
    independently — the queried-copy-first rule and the first-copy-wins
    rule live here once, so the two merges cannot silently drift.  The
    sort-free round core (:func:`rank_merge_round_d0`,
    ``ops.pallas_kernels.merge_round_pallas``) implements the same
    contract by rank arithmetic; ``tests/test_merge_equivalence.py``
    pins all of them to this reference bit-for-bit.

    Returns ``(sorted_group_keys, sorted_queried, sorted_payloads,
    dup)`` — ``dup`` marks every non-first member of an id group
    (callers fold their own invalid-slot mask in afterwards).
    """
    inv_q = (~queried).astype(jnp.uint32)
    ops = tuple(group_keys) + (inv_q,) + tuple(payloads) + (queried,)
    out = jax.lax.sort(ops, dimension=1, num_keys=len(group_keys) + 1,
                       is_stable=True)
    g = out[:len(group_keys)]
    s_pay = out[len(group_keys) + 1:-1]
    s_q = out[-1]
    dup = jnp.ones(g[0].shape, bool)
    for k in g:
        dup = dup & (k == jnp.roll(k, 1, axis=1))
    dup = dup.at[:, 0].set(False)
    return g, s_q, s_pay, dup


def _dedup_pushback_sort(mask_keys: Tuple[jax.Array, ...],
                         dup: jax.Array,
                         extra_keys: Tuple[jax.Array, ...],
                         payloads: Tuple[jax.Array, ...],
                         num_keys: int):
    """Shared pass 2: force duplicate rows' order keys to the all-ones
    sentinel and stable-sort, so survivors keep their pass-1 relative
    order and duplicates/empties land at the back.  Operand order is
    ``mask_keys + extra_keys + payloads``; ``num_keys`` counts from the
    front as usual."""
    masked = tuple(jnp.where(dup, SENTINEL_LIMB, k) for k in mask_keys)
    return jax.lax.sort(masked + tuple(extra_keys) + tuple(payloads),
                        dimension=1, num_keys=num_keys, is_stable=True)


def merge_shortlists_d0(cand_d0: jax.Array, cand_idx: jax.Array,
                        cand_queried: jax.Array, keep: int
                        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Surrogate-distance merge + dedup for the lookup hot loop.

    Candidates carry only (an approximation of) the first 32
    XOR-distance bits (``d0 = limb0(id ^ target)``).  Two passes, both
    fixed-width ``lax.sort``:

    1. group by node index, queried copies first within a group —
       duplicates become adjacent *regardless of their d0 values*.
       The same node legitimately arrives with different d0s when d0
       is a 16-bit window surrogate reconstructed at different bucket
       depths (``models.swarm._window_d0``), so dedup must never rely
       on equal keys the way an id-sorted merge could;
    2. order the survivors by d0, duplicates and empties pushed back.

    Order error vs the exact 160-bit merge (``Search::insertNode``,
    src/dht.cpp:961-1047): two *distinct* candidates tie on d0 with
    probability ≈ 2⁻³³ per pair for exact d0, ≈ 2⁻¹⁷ per pair for
    window surrogates (≥16 significant bits past the leading one);
    either way the final result is re-sorted exactly once per lookup
    (``models.swarm._finalize``).  Sentinel note: an empty slot's key
    is all-ones, so a *live* candidate whose exact d0 is 0xFFFFFFFF
    (probability 2⁻³² per candidate) sorts among the invalid entries —
    it can at worst trigger a premature exhaustion-done on that one
    lookup; window-surrogate d0s can never take the sentinel value
    (their sub-window bits read as zero while their leading bits can
    only be all-ones when the window starts at bit 0).

    The payoff vs the former 5-limb merge: no ``[..., 5]``-minor arrays
    (which tile onto TPU lanes at 5/128 utilisation) and 2 sorts of 3-4
    operands instead of 8.  Invalid slots (idx < 0) must carry all-ones
    ``d0``.

    Returns ``(idx [L,keep], d0 [L,keep], queried [L,keep])``.
    """
    maxu = jnp.uint32(0xFFFFFFFF)
    d0 = jnp.where(cand_idx < 0, maxu, cand_d0)
    # -1 becomes 0xFFFFFFFF and groups/sorts last; bitcast back below
    # recovers the int32 index for free.
    idx_u = cand_idx.astype(jnp.uint32)
    (s_idx_u,), s_q, (s_d0,), dup = _group_queried_first(
        (idx_u,), cand_queried, (d0,))
    s_idx = s_idx_u.astype(jnp.int32)
    dup = dup | (s_idx < 0)
    f_d0, f_idx_u, f_q = _dedup_pushback_sort(
        (s_d0,), dup, (), (jnp.where(dup, maxu, s_idx_u), s_q),
        num_keys=1)
    f_idx = f_idx_u.astype(jnp.int32)
    f_q = f_q & (f_idx >= 0)
    return f_idx[:, :keep], f_d0[:, :keep], f_q[:, :keep]


def merge_shortlists(target: jax.Array, cand_ids: jax.Array,
                     cand_idx: jax.Array, cand_queried: jax.Array,
                     keep: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Merge + dedup lookup shortlists, XOR-sorted, fixed width.

    The device-side equivalent of ``Search::insertNode``'s sorted
    insert/trim (src/dht.cpp:961-1047): concatenated candidates
    (current shortlist + RPC responses) are sorted by exact XOR
    distance, duplicates collapsed (keeping the queried flag if any
    copy is queried), and the best ``keep`` survive.

    Args (leading batch dim L throughout):
      target:       ``[L,5]``
      cand_ids:     ``[L,C,5]``
      cand_idx:     ``[L,C]`` int32 node indices, -1 = empty slot
      cand_queried: ``[L,C]`` bool
    Returns ``(idx [L,keep], ids [L,keep,5], queried [L,keep])``.
    """
    invalid = cand_idx < 0
    ids_m = jnp.where(invalid[..., None], SENTINEL_LIMB, cand_ids)
    keys = _dist_keys(ids_m, target)
    # Among equal distances (same id), queried copies sort first so the
    # dedup pass keeps the queried flag — the shared pass-1 helper.
    limbs = tuple(ids_m[..., i] for i in range(N_LIMBS))
    s_keys_t, s_q, s_pay, dup = _group_queried_first(
        keys, cand_queried, limbs + (cand_idx,))
    s_ids = jnp.stack(s_pay[:N_LIMBS], axis=-1)
    s_idx = s_pay[N_LIMBS]
    # Duplicate = same distance as previous row (same id, since XOR with
    # a fixed target is a bijection).  Push dups to the back via resort.
    dup = dup | (s_idx < 0)
    s_idx = jnp.where(dup, -1, s_idx)
    dup_key = dup.astype(jnp.uint32)
    limbs2 = tuple(jnp.where(dup, SENTINEL_LIMB, s_ids[..., i])
                   for i in range(N_LIMBS))
    out2 = _dedup_pushback_sort(
        s_keys_t, dup, (dup_key,), limbs2 + (s_idx, s_q),
        num_keys=N_LIMBS + 1)
    f_ids = jnp.stack(out2[N_LIMBS + 1:2 * N_LIMBS + 1], axis=-1)
    f_idx, f_q = out2[2 * N_LIMBS + 1], out2[2 * N_LIMBS + 2]
    f_q = f_q & (f_idx >= 0)
    return f_idx[:, :keep], f_ids[:, :keep], f_q[:, :keep]


# ---------------------------------------------------------------------------
# sort-free round merge: rank arithmetic over the standing frontier order
# ---------------------------------------------------------------------------

def rank_merge_round_d0(fr_idx: jax.Array, fr_d0: jax.Array,
                        fr_q: jax.Array, resp_idx: jax.Array,
                        resp_d0: jax.Array, keep: int
                        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sort-free round merge: bit-equal to
    ``merge_shortlists_d0(concat([fr_d0, resp_d0]), concat([fr_idx,
    resp_idx]), concat([fr_q, False]), keep)`` on the lookup round's
    input domain, without ever sorting the combined width.

    CONTRACT (the standing invariant of ``models.swarm._merge_round``,
    asserted adversarially in ``tests/test_merge_equivalence.py``):

    * the frontier ``[L, S]`` is the output prefix of a previous merge
      with row-local edits only — its VALID entries (idx ≥ 0) are
      sorted ascending by ``(d0, idx_u)`` and duplicate-free; invalid
      slots (empty or evicted: idx = -1, d0 = all-ones, queried flag
      arbitrary) may sit anywhere;
    * responses ``[L, C]`` are arbitrary (duplicates of the frontier
      and of each other, possibly with DIFFERENT d0s per copy — the
      window-surrogate case; invalid slots idx < 0) and always arrive
      UNQUERIED.

    Under that domain the two-pass sorted merge's tie-break rules
    (:func:`_group_queried_first` / :func:`_dedup_pushback_sort`)
    collapse to one total order — ``(effective d0, idx_u, input
    ordinal)`` with duplicates' and empties' d0 forced to all-ones —
    and every survivor's output slot is computable by RANK ARITHMETIC,
    with no sort anywhere:

    1. a frontier entry's within-run rank is a running valid-count
       (the valid prefix of a previous merge output is already
       sorted), an O(S) cumsum;
    2. responses dedup against the frontier by a membership plane and
       against each other by an earlier-slot equality plane (first
       copy wins — the queried-copy-first rule never binds, responses
       arrive unqueried);
    3. a response's within-run rank is a strictly-before count under
       the total order;
    4. merge-path placement: final slot = within-run rank + cross-run
       rank (one ``[L,S,C]`` comparison plane, read in both
       directions: strict ``<`` counts for frontier entries,
       ``S − count`` for responses — equal keys resolve
       frontier-first, the input-ordinal rule), then ONE scatter per
       run.

    All counts are branch-free broadcast-compare-reduce planes that
    XLA fuses into the reductions — measured 2.1× faster than the
    two-pass sorted merge on XLA:CPU at the gate geometry (a
    searchsorted/binary-search formulation was also measured, and
    loses: its ``take_along_axis`` chains serialize on gathers).

    Duplicates and empties participate in the ranking with their
    original ``idx_u`` (exactly like the reference's pass-2 stable
    sort, where a dup keeps its pass-1 position) but are never
    scattered — their payload equals the fill (idx -1, d0 all-ones,
    unqueried), which also reproduces the documented sentinel corner:
    a LIVE candidate whose d0 is exactly 0xFFFFFFFF ranks by its real
    idx_u among the all-ones group, bit-identically to the sorted
    reference.

    NARROWED PLANES (round 18): the counting planes accumulate in the
    narrowest unsigned dtype that provably fits every rank — u8 while
    ``S + C ≤ 255`` (the engine's S≤14, C = α·2K domain with wide
    margin), u16 to 65535, i32 beyond; every cross-run plane reduces
    over its MINOR axis (the ``pos_b`` count is computed transposed as
    ``#(A ≤ KB_j)`` directly instead of ``S − #(B < A)``); and
    placement is a one-hot min/max CONTRACTION over the ``out_w``-wide
    head instead of the former two row scatters — measured 2.3× on
    XLA:CPU at the gate geometry (the scatters alone were ~48 % of the
    merge wall; see BASELINE.md round 18).  Overflow safety of the
    narrow accumulators is MACHINE-PROVEN (round 19): graftlint's
    jaxpr interval prover (``tools/graftlint_ranges.py``, rule
    ``narrow-overflow``) abstract-interprets every registered entry
    point's traced program with integer intervals and proves each
    u8/u16 accumulate in range at the registered widths — a
    mis-widened plane (width 256 on u8) fails ``make lint``, not just
    the boundary tests pinned in ``tests/test_merge_equivalence.py``.
    (The exclusive-rank ``cumsum − 1`` below wraps only in lanes the
    consuming ``where`` discards; ``sub`` is deliberately outside the
    checked set, and the prover widens its result to the full domain
    so nothing downstream can inherit a false proof.)

    Returns ``(idx, d0, queried)``, each ``[L, min(keep, S+C)]``.
    """
    l, s = fr_idx.shape
    c = resp_idx.shape[1]
    out_w = min(keep, s + c)
    maxu = jnp.uint32(0xFFFFFFFF)
    # Narrow rank accumulators: ranks/positions are bounded by S+C.
    w = s + c
    acc = jnp.uint8 if w <= 255 else (
        jnp.uint16 if w <= 65535 else jnp.int32)

    # --- run A: the frontier in place.  Valid entries are sorted and
    # duplicate-free by contract, so their within-run rank is the
    # running valid-count; invalid slots carry the (all-ones, all-ones)
    # key and never precede a valid entry.
    fv = fr_idx >= 0
    a_idxu = fr_idx.astype(jnp.uint32)
    a_d0 = jnp.where(fv, fr_d0, maxu)
    rank_a = jnp.cumsum(fv.astype(acc), axis=1) - acc(1)

    # --- run B: responses.  Dedup by membership plane (vs the valid
    # frontier) and by earlier-slot equality (vs other responses).
    rv = resp_idx >= 0
    r_idxu = resp_idx.astype(jnp.uint32)
    r_d0 = jnp.where(rv, resp_d0, maxu)
    in_frontier = jnp.any(
        (r_idxu[:, :, None] == a_idxu[:, None, :]) & fv[:, None, :],
        axis=2)
    earlier = (jnp.arange(c)[None, :] < jnp.arange(c)[:, None])[None]
    dup_within = jnp.any(
        (r_idxu[:, :, None] == r_idxu[:, None, :]) & earlier
        & rv[:, None, :], axis=2)
    dup = in_frontier | dup_within | ~rv
    b_d0 = jnp.where(dup, maxu, r_d0)
    # Within-run rank under (eff_d0, idx_u, slot); placeholders keep
    # their ORIGINAL idx_u as rank key (the reference's pass-2 stable
    # sort leaves a dup at its pass-1 position) but emit no payload.
    bj_d0, bk_d0 = b_d0[:, :, None], b_d0[:, None, :]
    bj_ix, bk_ix = r_idxu[:, :, None], r_idxu[:, None, :]
    ltb = (bk_d0 < bj_d0) | ((bk_d0 == bj_d0)
                             & ((bk_ix < bj_ix)
                                | ((bk_ix == bj_ix) & earlier)))
    rank_b = jnp.sum(ltb.astype(acc), axis=2)

    # --- cross-run ranks from two planes, EACH reduced over its minor
    # axis.  Frontier entry i gains the strict count #(KB_j < KA_i)
    # (equal B keys place AFTER it) from a [L,S,C] plane; response j
    # gains #(A ≤ KB_j) (equal A keys place BEFORE it — the
    # frontier-first input-ordinal rule) from the TRANSPOSED [L,C,S]
    # plane, so neither reduction strides and neither plane needs
    # materializing for a second reduction direction.
    lt_a = (b_d0[:, None, :] < a_d0[:, :, None]) | (
        (b_d0[:, None, :] == a_d0[:, :, None])
        & (r_idxu[:, None, :] < a_idxu[:, :, None]))
    pos_a = jnp.where(fv, rank_a + jnp.sum(lt_a.astype(acc), axis=2),
                      acc(out_w))
    ge_b = ~((b_d0[:, :, None] < a_d0[:, None, :]) | (
        (b_d0[:, :, None] == a_d0[:, None, :])
        & (r_idxu[:, :, None] < a_idxu[:, None, :])))
    pos_b = jnp.where(dup, acc(out_w),
                      rank_b + jnp.sum(ge_b.astype(acc), axis=2))

    # --- placement: one-hot min/max contraction over the kept head.
    # Positions are unique among survivors (a total order), so each
    # output slot matches at most one entry per run; duplicates,
    # empties and ranks past the kept width hold the fill.  Replaces
    # the former two `.at[rows, pos].set` scatters, which ran on the
    # scalar scatter path and dominated the merge wall on CPU.
    iota_k = jnp.arange(out_w, dtype=acc)[None, None, :]
    ha = pos_a[:, :, None] == iota_k                     # [L,S,out_w]
    hb = pos_b[:, :, None] == iota_k                     # [L,C,out_w]
    o_idx = jnp.maximum(
        jnp.max(jnp.where(ha, fr_idx[:, :, None], -1), axis=1),
        jnp.max(jnp.where(hb, resp_idx[:, :, None], -1), axis=1))
    o_d0 = jnp.minimum(
        jnp.min(jnp.where(ha, a_d0[:, :, None], maxu), axis=1),
        jnp.min(jnp.where(hb, b_d0[:, :, None], maxu), axis=1))
    o_q = jnp.any(ha & fr_q[:, :, None], axis=1)
    return o_idx, o_d0, o_q


def merge_ladder_widths(c: int, block: int) -> list[int]:
    """Ascending power-of-two response-width ladder for a ``[*, c]``
    response plane whose live slots arrive in ``block``-wide runs (one
    solicited node's 2K candidates).

    Rungs are ``block · 2^j`` capped at (and always including) ``c`` —
    the candidate-width twin of the row ladder's ``L → 2^k`` prefix
    shapes: at most ``log2(c/block) + 1`` step specializations, widths
    chosen per burst from the live-slot watermark the done-check
    readback already pays for."""
    if c <= 0 or block <= 0:
        return [max(c, 0)]
    widths = set()
    w = min(block, c)
    while w < c:
        widths.add(w)
        w *= 2
    widths.add(c)
    return sorted(widths)


def pick_merge_width(wneed: int, c: int, block: int) -> int | None:
    """Smallest ladder rung covering ``wneed`` live response columns.

    Returns ``None`` for the full width so callers keep dispatching the
    exact pre-ladder program (byte-identical jit cache key) when the
    ladder cannot help."""
    for w in merge_ladder_widths(c, block):
        if w >= wneed:
            return None if w >= c else w
    return None


def rank_merge_round_d0_w(fr_idx: jax.Array, fr_d0: jax.Array,
                          fr_q: jax.Array, resp_idx: jax.Array,
                          resp_d0: jax.Array, keep: int,
                          merge_w: int | None
                          ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Width-laddered :func:`rank_merge_round_d0`: rank planes priced
    at ``merge_w ≤ C`` response columns, GUARDED in-jit so any width
    choice is sound.

    The response block arrives at full width ``C = α·2K`` every round,
    but its live columns are bounded by the round's live-slot watermark
    (``2K ×`` the widest row's live solicitation count) — in tail
    rounds most of the block is empty and the O(C²) rank planes price
    dead columns.  The caller (the burst loops) picks ``merge_w`` from
    the watermark the previous done-check readback returned; because
    the watermark is NOT monotone (a merged round can add unqueried
    candidates), the choice is protected by an in-jit guard: columns
    ``≥ merge_w`` are checked live-free, and ``lax.cond`` falls back
    to the full-width planes when the guard fails — bit-identical
    output either way, the narrow path merely cheaper.  (An in-jit
    ``switch`` picking the width per ROUND was measured 2.5× slower at
    full width on XLA:CPU: ops inside a data-dependent conditional
    lose the parallel task assignment, so the full-width rung must
    stay OUTSIDE any conditional — the guard only wraps dispatches the
    caller already narrowed.)

    Dropping all-invalid trailing columns is exact: an invalid entry's
    key is (all-ones d0, all-ones idx_u), which never precedes any
    other entry under the total order and never emits a payload, so
    removing it changes no rank and no output (the documented
    live-sentinel corner keeps its REAL idx_u and is untouched —
    sliced columns are invalid everywhere, not sentinel-valued).
    """
    l, s = fr_idx.shape
    c = resp_idx.shape[1]
    if merge_w is None or merge_w >= c:
        return rank_merge_round_d0(fr_idx, fr_d0, fr_q, resp_idx,
                                   resp_d0, keep)
    out_w = min(keep, s + c)

    def pad_out(out):
        o_idx, o_d0, o_q = out
        padw = out_w - o_idx.shape[1]
        if padw <= 0:
            return out
        return (jnp.concatenate(
            [o_idx, jnp.full((l, padw), -1, jnp.int32)], axis=1),
            jnp.concatenate(
                [o_d0, jnp.full((l, padw), jnp.uint32(0xFFFFFFFF))],
                axis=1),
            jnp.concatenate([o_q, jnp.zeros((l, padw), bool)], axis=1))

    def narrow(fi, fd, fq, ri, rd):
        return pad_out(rank_merge_round_d0(
            fi, fd, fq, ri[:, :merge_w], rd[:, :merge_w], keep))

    def full(fi, fd, fq, ri, rd):
        return rank_merge_round_d0(fi, fd, fq, ri, rd, keep)

    overflow = jnp.any(resp_idx[:, merge_w:] >= 0)
    return jax.lax.cond(overflow, full, narrow, fr_idx, fr_d0, fr_q,
                        resp_idx, resp_d0)
