"""Benchmark CLI over the scenario suite + the TPU swarm engine.

Parity with the reference's ``python/tools/benchmark.py`` (WorkBench
:37-143, CLI :145-240):

    python -m opendht_tpu.harness.benchmark --performance -t gets
    python -m opendht_tpu.harness.benchmark --persistence -t delete
    python -m opendht_tpu.harness.benchmark --swarm -n 100000 -l 10000

The ``--swarm`` mode runs the device-resident lock-step engine
(opendht_tpu.models.swarm) instead of the event-driven cluster — the
configuration the reference could never reach.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .scenarios import SCENARIOS


def run_scenario(name: str, args) -> dict:
    fn = SCENARIOS[name]
    t0 = time.monotonic()
    kw = {}
    if args.node_num is not None:
        kw["n_nodes"] = args.node_num
    if args.seed is not None:
        kw["seed"] = args.seed
    out = fn(**kw)
    out["scenario"] = name
    out["wall_s"] = round(time.monotonic() - t0, 2)
    return out


def run_swarm(args) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models.swarm import SwarmConfig, build_swarm, lookup

    cfg = SwarmConfig.for_nodes(args.node_num
                                if args.node_num is not None else 100_000)
    swarm = build_swarm(jax.random.PRNGKey(args.seed
                                        if args.seed is not None else 0), cfg)
    targets = jax.random.bits(jax.random.PRNGKey(1),
                              (args.lookups, 5), jnp.uint32)
    res = lookup(swarm, cfg, targets, jax.random.PRNGKey(2))
    jax.block_until_ready(res.found)
    t0 = time.monotonic()
    res = lookup(swarm, cfg, targets, jax.random.PRNGKey(3))
    jax.block_until_ready(res.found)
    dt = time.monotonic() - t0
    return {
        "scenario": "swarm",
        "n_nodes": cfg.n_nodes,
        "n_lookups": args.lookups,
        "lookups_per_sec": round(args.lookups / dt, 1),
        "median_hops": float(np.median(np.asarray(res.hops))),
        "done_frac": float(np.asarray(res.done).mean()),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="benchmark", description=__doc__)
    ap.add_argument("--performance", action="store_true")
    ap.add_argument("--persistence", action="store_true")
    ap.add_argument("--swarm", action="store_true")
    ap.add_argument("-t", "--test", default="gets",
                    choices=sorted(SCENARIOS))
    ap.add_argument("-n", "--node-num", type=int, default=None)
    ap.add_argument("-l", "--lookups", type=int, default=10_000)
    ap.add_argument("--seed", type=int, default=None)
    args = ap.parse_args(argv)

    if args.swarm:
        out = run_swarm(args)
    else:
        out = run_scenario(args.test, args)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
