"""Subprocess DHT node driven by a msgpack-over-stdio control protocol.

A miniature of the reference harness's ``DhtNetworkSubProcess``
(ref: python/tools/dht/network.py:42-280,447-595): the parent spawns
``python -m opendht_tpu.harness.proc_node``, writes msgpack request
maps to its stdin and reads msgpack reply maps from its stdout, while
the node itself talks real UDP on localhost.  This is what puts an OS
process boundary (separate interpreter, separate GIL, real sockets)
under the runtime tests — the reference gets the same from netns
subprocesses.

Requests (maps with ``op``; each gets one reply map with ``ok``):

=============  ============================  ==========================
op             request fields                reply fields
=============  ============================  ==========================
run            port (0 = ephemeral)          port (bound), id (hex)
bootstrap      host, port                    —
put            key (20 B), value (bytes)     stored (bool)
get            key (20 B)                    values (list of bytes)
listen         key (20 B)                    token (int)
poll_listen    token (int)                   values (list of bytes)
stats          —                             good, dubious
shutdown       —                             — (process exits after)
=============  ============================  ==========================
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List

import msgpack

from ..core.value import Value
from ..runtime.dhtrunner import DhtRunner
from ..utils.infohash import InfoHash


def serve(stdin=None, stdout=None) -> None:  # pragma: no cover (subproc)
    import os

    stdin = stdin or sys.stdin.buffer
    stdout = stdout or sys.stdout.buffer
    # Feed the unpacker from incremental reads: wrapping the pipe in
    # Unpacker(stream) would block in a full buffered read() until EOF.
    unpacker = msgpack.Unpacker(raw=False)
    fd = stdin.fileno()
    runner = DhtRunner()
    listens: Dict[int, List[bytes]] = {}
    next_token = [1]

    rid_box = [None]

    def reply(**kw):
        # Echo the request id so a parent that timed out on one request
        # can discard its late reply instead of mis-pairing the stream.
        kw["rid"] = rid_box[0]
        stdout.write(msgpack.packb(kw, use_bin_type=True))
        stdout.flush()

    def requests():
        while True:
            chunk = os.read(fd, 65536)
            if not chunk:
                return
            unpacker.feed(chunk)
            yield from unpacker

    for req in requests():
        op = req.get("op")
        rid_box[0] = req.get("rid")
        try:
            if op == "run":
                runner.run(port=int(req.get("port", 0)),
                           bind4="127.0.0.1")
                reply(ok=True, port=runner.get_bound_port(),
                      id=str(runner.get_id()))
            elif op == "bootstrap":
                runner.bootstrap(req["host"], int(req["port"]))
                reply(ok=True)
            elif op == "put":
                h = InfoHash(req["key"])
                fut = runner.put_future(h, Value(req["value"]))
                reply(ok=True, stored=bool(fut.result(timeout=20)))
            elif op == "get":
                h = InfoHash(req["key"])
                vals = runner.get_future(h).result(timeout=20)
                reply(ok=True, values=[v.data for v in vals])
            elif op == "listen":
                h = InfoHash(req["key"])
                token = next_token[0]
                next_token[0] += 1
                box: List[bytes] = []
                listens[token] = box

                def on_values(vs, box=box):
                    box.extend(v.data for v in vs)
                    return True
                runner.listen(h, on_values)
                reply(ok=True, token=token)
            elif op == "poll_listen":
                box = listens.get(int(req["token"]), [])
                vals, box[:] = list(box), []
                reply(ok=True, values=vals)
            elif op == "stats":
                st = runner.get_nodes_stats()
                reply(ok=True, good=int(st[0]), dubious=int(st[1]))
            elif op == "shutdown":
                runner.shutdown()
                runner.join()
                reply(ok=True)
                return
            else:
                reply(ok=False, error=f"unknown op {op!r}")
        except Exception as e:  # noqa: BLE001 — report to the parent
            reply(ok=False, error=f"{type(e).__name__}: {e}")


class ProcNode:
    """Parent-side handle: spawn, drive, and stop a subprocess node."""

    def __init__(self):
        import subprocess

        self.proc = subprocess.Popen(
            [sys.executable, "-m", "opendht_tpu.harness.proc_node"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        self._unpacker = msgpack.Unpacker(raw=False)
        self._next_rid = 0

    def request(self, timeout: float = 30.0, **req) -> dict:
        """Send one request, return its reply.

        Requests carry a monotonically increasing ``rid`` echoed in the
        reply; a reply arriving late for an earlier (timed-out) request
        is discarded rather than mis-paired with the current one.
        """
        self._next_rid += 1
        rid = self._next_rid
        req["rid"] = rid
        self.proc.stdin.write(msgpack.packb(req, use_bin_type=True))
        self.proc.stdin.flush()
        end = time.monotonic() + timeout
        import os
        import select
        fd = self.proc.stdout.fileno()
        while time.monotonic() < end:
            r, _, _ = select.select([fd], [], [], 0.1)
            if r:
                chunk = os.read(fd, 65536)
                if not chunk:
                    break
                self._unpacker.feed(chunk)
                for msg in self._unpacker:
                    if msg.get("rid") == rid:
                        return msg
                    # stale reply to a timed-out request: drop it
        raise TimeoutError(f"no reply to {req.get('op')!r}")

    def close(self) -> None:
        try:
            if self.proc.poll() is None:
                self.request(op="shutdown", timeout=10)
        except Exception:
            pass
        finally:
            try:
                self.proc.stdin.close()
            except Exception:
                pass
            try:
                self.proc.wait(timeout=10)
            except Exception:
                self.proc.kill()
                self.proc.wait(timeout=5)


class ProcCluster:
    """A cluster of subprocess nodes — the multi-process half of the
    harness (ref ``DhtNetwork`` managing ``DhtNetworkSubProcess``
    clusters, python/tools/dht/network.py:283-445).

    Each node is its own OS process with real UDP sockets on
    localhost, star-bootstrapped to node 0.
    """

    def __init__(self, n: int):
        # Build incrementally so a spawn failure partway still closes
        # the processes already started.
        self.nodes: List[ProcNode] = []
        self.ports: List[int] = []
        try:
            for _ in range(n):
                self.nodes.append(ProcNode())
            for node in self.nodes:
                r = node.request(op="run", port=0)
                if not r.get("ok"):
                    raise RuntimeError(f"run failed: {r}")
                self.ports.append(r["port"])
            for i, node in enumerate(self.nodes):
                peer = self.ports[0] if i else self.ports[-1]
                r = node.request(op="bootstrap", host="127.0.0.1",
                                 port=peer)
                if not r.get("ok"):
                    raise RuntimeError(f"bootstrap failed: {r}")
        except Exception:
            self.close()
            raise

    def wait_connected(self, min_good: int = 1,
                       timeout: float = 60.0) -> bool:
        """Every node sees ≥ min_good good peers.

        A child process that died (or stopped answering) counts as
        not-connected rather than raising an opaque TimeoutError out
        of the poll loop — the caller sees a clean False.
        """
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            stats = []
            for n in self.nodes:
                if n.proc.poll() is not None:
                    stats.append({"good": -1, "dead": True})
                    continue
                try:
                    stats.append(n.request(op="stats", timeout=5))
                except (TimeoutError, OSError):
                    stats.append({"good": -1})
            if all(s.get("good", 0) >= min_good for s in stats):
                return True
            time.sleep(0.2)
        return False

    def put(self, i: int, key: bytes, value: bytes) -> bool:
        r = self.nodes[i].request(op="put", key=key, value=value)
        return bool(r.get("ok") and r.get("stored"))

    def get(self, i: int, key: bytes) -> List[bytes]:
        r = self.nodes[i].request(op="get", key=key)
        return list(r.get("values", []))

    def stats(self) -> List[dict]:
        """Per-node stats; a dead/unresponsive child reports an error
        entry instead of blowing up the whole sweep."""
        out = []
        for n in self.nodes:
            if n.proc.poll() is not None:
                out.append({"error": "process exited",
                            "returncode": n.proc.returncode})
                continue
            try:
                out.append(n.request(op="stats", timeout=5))
            except (TimeoutError, OSError) as e:
                out.append({"error": f"{type(e).__name__}: {e}"})
        return out

    def close(self) -> None:
        for n in self.nodes:
            n.close()


if __name__ == "__main__":
    serve()
