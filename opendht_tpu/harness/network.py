"""In-process deterministic DHT cluster manager.

The framework's equivalent of the reference's netns test cluster stack
(ref: python/tools/dht/network.py ``DhtNetwork``/``DhtNetworkSubProcess``
and python/tools/dht/virtual_network_builder.py): N Dht cores share one
virtual clock / scheduler / packet network, so whole-swarm scenarios
(put/get/listen, churn, persistence) run deterministically, with
simulated seconds passing in real milliseconds.

Differences from the reference: no subprocess/netns split is needed —
the virtual transport gives loss/latency injection in-process (the
``netem`` equivalent, ref virtual_network_builder.py:61-116), and the
cluster scales to thousands of in-process nodes.
"""

from __future__ import annotations

import random
from typing import Callable, List, Optional

from ..core.dht import Dht, DhtConfig
from ..core.scheduler import Scheduler
from ..net.transport import VirtualNetwork
from ..utils.clock import VirtualClock
from ..utils.infohash import InfoHash
from ..utils.sockaddr import SockAddr


class DhtNetwork:
    """A cluster of in-process Dht nodes on one virtual network."""

    def __init__(self, n: int, seed: int = 1, delay: float = 0.01,
                 loss: float = 0.0, **dht_kwargs):
        self.clock = VirtualClock()
        self.scheduler = Scheduler(self.clock)
        self.net = VirtualNetwork(self.scheduler, delay=delay, loss=loss,
                                  seed=seed)
        self.nodes: List[Dht] = []
        self.seed = seed
        self._spawned = 0
        for _ in range(n):
            self.add_node(**dht_kwargs)

    # -- membership -----------------------------------------------------
    def _host(self, i: int) -> str:
        return f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}"

    def _node_wiring(self, i: Optional[int]):
        """Shared per-node wiring: (index, socket, node id, rng)."""
        if i is None:
            i = self._spawned
        self._spawned = max(self._spawned, i + 1)
        sock = self.net.socket(self._host(i), 4222)
        node_id = InfoHash.get(f"node-{self.seed}-{i}")
        rng = random.Random(self.seed * 10007 + i)
        return i, sock, node_id, rng

    def _host6(self, i: int) -> str:
        return f"2001:db9::{i + 1:x}"

    def add_node(self, i: Optional[int] = None, family: str = "ipv4",
                 **dht_kwargs) -> Dht:
        """Add a node; ``family``: "ipv4", "ipv6", or "dual" — the
        netns harness's v4/v6 address assignment
        (ref python/tools/dht/virtual_network_builder.py:61-116).
        Dual-stack nodes fork every op into per-family searches with a
        merged done callback (ref src/dht.cpp:1969-2011)."""
        i, sock, node_id, rng = self._node_wiring(i)
        sock4 = sock if family in ("ipv4", "dual") else None
        sock6 = None
        if family in ("ipv6", "dual"):
            sock6 = self.net.socket(self._host6(i), 4222)
        if sock4 is None:
            self.net.unregister(sock.local_addr())
        dht = Dht(sock4, sock6, DhtConfig(node_id=node_id),
                  scheduler=self.scheduler, rng=rng, **dht_kwargs)
        self.nodes.append(dht)
        return dht

    def add_secure_node(self, identity=None, i: Optional[int] = None):
        """Add a SecureDht node (crypto overlay) to the same network."""
        from ..crypto.securedht import SecureDht, SecureDhtConfig
        i, sock, node_id, rng = self._node_wiring(i)
        cfg = SecureDhtConfig(DhtConfig(node_id=node_id), identity)
        dht = SecureDht(sock, None, cfg, scheduler=self.scheduler, rng=rng)
        self.nodes.append(dht)
        return dht

    def addr_of(self, dht: Dht) -> SockAddr:
        t = dht.engine.t4 or dht.engine.t6
        return t.local_addr()

    def addr6_of(self, dht: Dht) -> SockAddr:
        return dht.engine.t6.local_addr()

    def bootstrap_all(self, to: int = 0) -> None:
        """Everyone learns about node ``to``."""
        target = self.nodes[to]
        addr = self.addr_of(target)
        for d in self.nodes:
            if d is not target:
                d.insert_node(target.myid, addr)

    def interconnect(self) -> None:
        """Full mesh knowledge — for tests that skip discovery."""
        for a in self.nodes:
            for b in self.nodes:
                if a is not b:
                    a.insert_node(b.myid, self.addr_of(b))

    # -- fault injection (netem / node-kill equivalents) ----------------
    def _hosts_of(self, dht: Dht) -> List[str]:
        return [t.local_addr().host
                for t in (dht.engine.t4, dht.engine.t6) if t is not None]

    def kill(self, dht: Dht) -> None:
        """Partition a node away on every family (the node-kill knob,
        ref: DhtNetworkSubProcess shutdown_node network.py:50-64)."""
        for h in self._hosts_of(dht):
            self.net.partition(h, True)

    def revive(self, dht: Dht) -> None:
        for h in self._hosts_of(dht):
            self.net.partition(h, False)

    def remove_node(self, dht: Dht) -> None:
        """Kill and forget a node (graceful-removal equivalent).

        Shuts the core down and unregisters its sockets so removed
        nodes stop scheduling maintenance against the shared
        scheduler."""
        addrs = [t.local_addr()
                 for t in (dht.engine.t4, dht.engine.t6) if t is not None]
        self.kill(dht)
        dht.shutdown()
        for a in addrs:
            self.net.unregister(a)
        self.nodes.remove(dht)

    def replace_cluster(self, count: Optional[int] = None,
                        bootstrap: int = 0) -> List[Dht]:
        """Kill ``count`` random nodes and spawn fresh replacements —
        the reference's cluster replacement (ref: WorkBench
        python/tools/benchmark.py:100-120, tests.py:869-875)."""
        rng = random.Random(self.seed + len(self.nodes))
        count = count if count is not None else max(1, len(self.nodes) // 4)
        victims = rng.sample([n for i, n in enumerate(self.nodes)
                              if i != bootstrap],
                             min(count, len(self.nodes) - 1))
        for v in victims:
            self.remove_node(v)
        fresh = []
        boot_addr = self.addr_of(self.nodes[bootstrap])
        boot_id = self.nodes[bootstrap].myid
        for _ in range(len(victims)):
            d = self.add_node()
            d.insert_node(boot_id, boot_addr)
            fresh.append(d)
        return fresh

    def resize(self, n: int, bootstrap: int = 0) -> None:
        """Grow/shrink the cluster (ref: DhtNetwork.resize
        python/tools/dht/network.py:420-445)."""
        while len(self.nodes) > n:
            self.remove_node(self.nodes[-1])
        boot_addr = self.addr_of(self.nodes[bootstrap])
        boot_id = self.nodes[bootstrap].myid
        while len(self.nodes) < n:
            d = self.add_node()
            d.insert_node(boot_id, boot_addr)

    def warmup(self, min_good: int = 4, timeout: float = 120.0) -> bool:
        """Run virtual time until the mesh has converged (most nodes
        know several good peers).  Goodness needs request/reply cycles
        from maintenance, so a fresh bootstrap-star takes ~30-60
        simulated seconds to become a usable mesh."""
        from ..utils.sockaddr import AF_INET

        def ready():
            goods = [d.get_nodes_stats(AF_INET)[0] for d in self.nodes]
            return sorted(goods)[len(goods) // 4] >= min_good

        return self.run_until(ready, timeout, step=5.0)

    # -- virtual time ---------------------------------------------------
    def run(self, duration: float, max_step: float = 0.25) -> None:
        """Advance virtual time, running all due jobs."""
        end = self.clock.now() + duration
        while self.clock.now() < end:
            nxt = self.scheduler.run()
            if nxt >= end:
                self.clock.set(end)
                break
            self.clock.set(min(end, max(nxt, self.clock.now() + 1e-6)))
        self.scheduler.run()

    def run_until(self, pred: Callable[[], bool], timeout: float = 30.0,
                  step: float = 0.05) -> bool:
        end = self.clock.now() + timeout
        while self.clock.now() < end:
            if pred():
                return True
            self.run(step)
        return pred()
