"""Scenario tests over the virtual cluster — the reference's
FeatureTest/PersistenceTest/PerformanceTest suite re-done in-process
(ref: python/tools/dht/tests.py:181-994).

Each scenario returns a metrics dict; the benchmark CLI prints them.
Virtual time makes minutes-long churn scenarios run in wall-clock
milliseconds.
"""

from __future__ import annotations

import random
import statistics
from typing import Dict, List

from ..core.value import Value
from ..utils.infohash import InfoHash
from .network import DhtNetwork


def _put(net: DhtNetwork, node, h: InfoHash, data: bytes,
         timeout: float = 30.0) -> bool:
    done = {}
    node.put(h, Value(data), lambda ok, nodes: done.update(ok=ok))
    net.run_until(lambda: "ok" in done, timeout)
    return done.get("ok", False)


def _get(net: DhtNetwork, node, h: InfoHash, timeout: float = 30.0):
    got: List[Value] = []
    done = {}
    node.get(h, lambda vals: got.extend(vals) or True,
             lambda ok, nodes: done.update(ok=ok))
    net.run_until(lambda: "ok" in done, timeout)
    return got, done.get("ok", False)


def performance_gets(n_nodes: int = 32, rounds: int = 10,
                     gets_per_round: int = 50, seed: int = 1,
                     delay: float = 0.01, loss: float = 0.0
                     ) -> Dict[str, float]:
    """Random-key get latency distribution over a churning cluster
    (ref: PerformanceTest 'gets' tests.py:865-950)."""
    net = DhtNetwork(n_nodes, seed=seed, delay=delay, loss=loss)
    net.bootstrap_all()
    net.warmup()
    rng = random.Random(seed)
    times: List[float] = []
    for r in range(rounds):
        net.replace_cluster(max(1, n_nodes // 8))
        net.run(2.0)
        for _ in range(gets_per_round):
            node = rng.choice(net.nodes)
            h = InfoHash.get_random()
            t0 = net.clock.now()
            _get(net, node, h)
            times.append(net.clock.now() - t0)
    return {
        "gets": len(times),
        "sum_s": round(sum(times), 3),
        "mean_s": round(statistics.mean(times), 4),
        "stdev_s": round(statistics.pstdev(times), 4),
        "min_s": round(min(times), 4),
        "max_s": round(max(times), 4),
    }


def persistence_delete(n_nodes: int = 24, n_values: int = 8,
                       seed: int = 2) -> Dict[str, float]:
    """Put values, kill every node currently storing them, verify the
    values are re-found on fresh nodes (ref: PersistenceTest 'delete'
    tests.py:439-550)."""
    net = DhtNetwork(n_nodes, seed=seed)
    net.bootstrap_all()
    net.warmup()
    writer = net.nodes[1]
    keys = [InfoHash.get(f"persist-{i}") for i in range(n_values)]
    stored = 0
    for i, h in enumerate(keys):
        if _put(net, writer, h, f"value-{i}".encode()):
            stored += 1
    net.run(5.0)

    # Kill every storing node (the writer keeps its local replica alive
    # and must republish — ref maintain_storage / dataPersistence).
    killed = 0
    for d in list(net.nodes):
        if d is writer:
            continue
        if any(d.get_local(h) for h in keys):
            net.remove_node(d)
            killed += 1
    # Fresh nodes join; give maintenance time to republish.
    for _ in range(killed):
        d = net.add_node()
        d.insert_node(net.nodes[0].myid, net.addr_of(net.nodes[0]))
    net.run(120.0)

    refound = 0
    reader = net.nodes[-1]
    for h in keys:
        got, _ = _get(net, reader, h)
        if got:
            refound += 1
    return {"stored": stored, "killed_hosts": killed,
            "refound": refound, "total": n_values}


def persistence_replace(n_nodes: int = 24, seed: int = 3
                        ) -> Dict[str, float]:
    """Replace whole sub-clusters repeatedly and verify a value
    survives (ref: PersistenceTest 'replace' tests.py:560-640)."""
    net = DhtNetwork(n_nodes, seed=seed)
    net.bootstrap_all()
    net.warmup()
    h = InfoHash.get("survivor")
    assert _put(net, net.nodes[1], h, b"still-here")
    survived = 0
    rounds = 4
    for r in range(rounds):
        net.replace_cluster(n_nodes // 4)
        net.run(60.0)
        got, _ = _get(net, net.nodes[-1], h)
        if any(v.data == b"still-here" for v in got):
            survived += 1
    return {"rounds": rounds, "survived": survived}


def listen_churn(n_nodes: int = 16, seed: int = 4) -> Dict[str, float]:
    """Listeners keep receiving across storing-node churn
    (ref: pingpong.py + PersistenceTest mult_time)."""
    net = DhtNetwork(n_nodes, seed=seed)
    net.bootstrap_all()
    net.warmup()
    h = InfoHash.get("feed")
    seen: List[bytes] = []
    net.nodes[2].listen(h, lambda vals: seen.extend(
        v.data for v in vals) or True)
    net.run(2.0)
    sent = 0
    for i in range(5):
        if _put(net, net.nodes[3], h, f"msg-{i}".encode()):
            sent += 1
        if i == 2:
            net.replace_cluster(n_nodes // 4)
            net.run(30.0)
        net.run(5.0)
    return {"sent": sent, "received": len(set(seen))}


def local_putget(n_keys: int = 1000, seed: int = 5) -> Dict[str, float]:
    """Single-node 1k-key put/get loop — BASELINE.json config 1 (the
    CPU floor: pure core + storage path, no network)."""
    import time as _t
    net = DhtNetwork(1, seed=seed)
    node = net.nodes[0]
    keys = [InfoHash.get(f"k{i}") for i in range(n_keys)]
    t0 = _t.monotonic()
    for i, h in enumerate(keys):
        done = {}
        node.put(h, Value(f"v{i}".encode()),
                 lambda ok, nodes: done.update(ok=True))
        net.run(0.01)
    put_dt = _t.monotonic() - t0
    t0 = _t.monotonic()
    hits = 0
    for i, h in enumerate(keys):
        vals = node.get_local(h)
        if vals and vals[0].data == f"v{i}".encode():
            hits += 1
    get_dt = _t.monotonic() - t0
    return {
        "keys": n_keys, "hit_rate": hits / n_keys,
        "puts_per_sec": round(n_keys / put_dt, 1),
        "local_gets_per_sec": round(n_keys / get_dt, 1),
    }


SCENARIOS = {
    "gets": performance_gets,
    "delete": persistence_delete,
    "replace": persistence_replace,
    "listen": listen_churn,
    "local": local_putget,
}
