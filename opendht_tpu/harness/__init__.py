"""Test/benchmark harness: in-process cluster manager + scenario suite."""

from .network import DhtNetwork  # noqa: F401
from .scenarios import SCENARIOS  # noqa: F401
